"""PyTorch → Flax weight conversion for the BERT encoder.

The reference fine-tunes from HF PyTorch checkpoints (bert-base-uncased
or the further-pretrained ``out_wwm/`` dir, custom_PTM_embedder.py:95-99).
This module maps an HF ``BertModel`` state_dict onto the in-repo encoder's
parameter tree so those checkpoints are usable for F1-parity runs — the
single highest-risk item called out in SURVEY.md §7.

Layout notes: torch ``nn.Linear`` stores [out, in] (transposed vs Flax);
the attention projections reshape to per-head [in, H, Dh] for
``nn.DenseGeneral``; with ``scan_layers`` the per-layer trees stack into
leading-[L] arrays.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import numpy as np

from .bert import BertConfig


def _t(w: np.ndarray) -> np.ndarray:
    return np.ascontiguousarray(w.T)


def _layer_params(sd: Dict[str, np.ndarray], i: int, c: BertConfig) -> Dict:
    h, heads = c.hidden_size, c.num_heads
    dh = h // heads
    p = f"encoder.layer.{i}."

    def qkv(name: str) -> Dict:
        kernel = _t(sd[p + f"attention.self.{name}.weight"]).reshape(h, heads, dh)
        bias = sd[p + f"attention.self.{name}.bias"].reshape(heads, dh)
        return {"kernel": kernel, "bias": bias}

    attn_out_kernel = _t(sd[p + "attention.output.dense.weight"]).reshape(
        heads, dh, h
    )
    return {
        "attention": {
            "query": qkv("query"),
            "key": qkv("key"),
            "value": qkv("value"),
            "output": {
                "kernel": attn_out_kernel,
                "bias": sd[p + "attention.output.dense.bias"],
            },
            "output_LayerNorm": {
                "scale": sd[p + "attention.output.LayerNorm.weight"],
                "bias": sd[p + "attention.output.LayerNorm.bias"],
            },
        },
        "intermediate": {
            "kernel": _t(sd[p + "intermediate.dense.weight"]),
            "bias": sd[p + "intermediate.dense.bias"],
        },
        "output": {
            "kernel": _t(sd[p + "output.dense.weight"]),
            "bias": sd[p + "output.dense.bias"],
        },
        "output_LayerNorm": {
            "scale": sd[p + "output.LayerNorm.weight"],
            "bias": sd[p + "output.LayerNorm.bias"],
        },
    }


def convert_bert_state_dict(
    state_dict: Dict[str, np.ndarray], config: BertConfig
) -> Tuple[Dict, Optional[Dict]]:
    """HF BertModel state_dict → (encoder subtree for ``params/bert``,
    pooler subtree for ``params/pooler`` or None).

    Accepts keys with or without a leading ``bert.`` prefix; tensors may be
    torch tensors or numpy arrays.
    """
    sd = {}
    for k, v in state_dict.items():
        if k.startswith("bert."):
            k = k[len("bert."):]
        sd[k] = np.asarray(
            v.detach().cpu().numpy() if hasattr(v, "detach") else v
        )

    embeddings = {
        "word_embeddings": {"embedding": sd["embeddings.word_embeddings.weight"]},
        "position_embeddings": {
            "embedding": sd["embeddings.position_embeddings.weight"]
        },
        "token_type_embeddings": {
            "embedding": sd["embeddings.token_type_embeddings.weight"]
        },
        "LayerNorm": {
            "scale": sd["embeddings.LayerNorm.weight"],
            "bias": sd["embeddings.LayerNorm.bias"],
        },
    }
    checkpoint_layers = {
        int(k.split(".")[2]) for k in sd if k.startswith("encoder.layer.")
    }
    if checkpoint_layers and max(checkpoint_layers) + 1 != config.num_layers:
        raise ValueError(
            f"checkpoint has {max(checkpoint_layers) + 1} encoder layers but "
            f"config.num_layers={config.num_layers} — depth mismatch would "
            "silently truncate the converted model"
        )
    layers = [_layer_params(sd, i, config) for i in range(config.num_layers)]
    if config.scan_layers:
        import jax

        stacked = jax.tree_util.tree_map(lambda *xs: np.stack(xs, 0), *layers)
        encoder = {"layers": {"layer": stacked}}
    else:
        encoder = {f"layer_{i}": layers[i] for i in range(config.num_layers)}

    bert_subtree = {"embeddings": embeddings, "encoder": encoder}
    pooler = None
    if "pooler.dense.weight" in sd:
        pooler = {
            "dense": {
                "kernel": _t(sd["pooler.dense.weight"]),
                "bias": sd["pooler.dense.bias"],
            }
        }
    return bert_subtree, pooler


def _unstack_layers(encoder: Dict, config: BertConfig) -> list:
    """Per-layer param trees, whether scan-stacked or expanded."""
    import jax

    if config.scan_layers:
        stacked = encoder["layers"]["layer"]
        return [
            jax.tree_util.tree_map(lambda x: np.asarray(x)[i], stacked)
            for i in range(config.num_layers)
        ]
    return [encoder[f"layer_{i}"] for i in range(config.num_layers)]


def export_bert_state_dict(
    bert_subtree: Dict, pooler: Optional[Dict], config: BertConfig
) -> Dict[str, np.ndarray]:
    """The inverse of :func:`convert_bert_state_dict`: Flax encoder (+
    optional pooler) → an HF ``BertModel``-keyed state dict.

    Completes bidirectional interop with the reference stack: models
    further-pretrained or fine-tuned here export to the checkpoint layout
    the reference's ``AutoModel.from_pretrained`` consumes
    (custom_PTM_embedder.py:95-99).  Round-trip identity with the import
    direction is pinned by tests/test_convert_parity.py."""
    h, heads = config.hidden_size, config.num_heads
    emb = bert_subtree["embeddings"]
    sd: Dict[str, np.ndarray] = {
        "embeddings.word_embeddings.weight": emb["word_embeddings"]["embedding"],
        "embeddings.position_embeddings.weight": emb["position_embeddings"][
            "embedding"
        ],
        "embeddings.token_type_embeddings.weight": emb["token_type_embeddings"][
            "embedding"
        ],
        "embeddings.LayerNorm.weight": emb["LayerNorm"]["scale"],
        "embeddings.LayerNorm.bias": emb["LayerNorm"]["bias"],
    }
    for i, layer in enumerate(_unstack_layers(bert_subtree["encoder"], config)):
        p = f"encoder.layer.{i}."
        attn = layer["attention"]
        for name in ("query", "key", "value"):
            sd[p + f"attention.self.{name}.weight"] = _t(
                np.asarray(attn[name]["kernel"]).reshape(h, h)
            )
            sd[p + f"attention.self.{name}.bias"] = np.asarray(
                attn[name]["bias"]
            ).reshape(h)
        sd[p + "attention.output.dense.weight"] = _t(
            np.asarray(attn["output"]["kernel"]).reshape(h, h)
        )
        sd[p + "attention.output.dense.bias"] = np.asarray(attn["output"]["bias"])
        sd[p + "attention.output.LayerNorm.weight"] = np.asarray(
            attn["output_LayerNorm"]["scale"]
        )
        sd[p + "attention.output.LayerNorm.bias"] = np.asarray(
            attn["output_LayerNorm"]["bias"]
        )
        sd[p + "intermediate.dense.weight"] = _t(np.asarray(layer["intermediate"]["kernel"]))
        sd[p + "intermediate.dense.bias"] = np.asarray(layer["intermediate"]["bias"])
        sd[p + "output.dense.weight"] = _t(np.asarray(layer["output"]["kernel"]))
        sd[p + "output.dense.bias"] = np.asarray(layer["output"]["bias"])
        sd[p + "output.LayerNorm.weight"] = np.asarray(
            layer["output_LayerNorm"]["scale"]
        )
        sd[p + "output.LayerNorm.bias"] = np.asarray(layer["output_LayerNorm"]["bias"])
    if pooler is not None:
        sd["pooler.dense.weight"] = _t(np.asarray(pooler["dense"]["kernel"]))
        sd["pooler.dense.bias"] = np.asarray(pooler["dense"]["bias"])
    return {k: np.asarray(v, np.float32) for k, v in sd.items()}


def load_into_classifier(classifier_params, state_dict, config: BertConfig):
    """Return classifier params with the encoder (and pooler, if present)
    replaced by converted torch weights."""
    import copy

    bert_subtree, pooler = convert_bert_state_dict(state_dict, config)
    out = copy.deepcopy(
        {"params": dict(classifier_params["params"])}
    )
    _check_shapes(out["params"]["bert"], bert_subtree, "bert")
    out["params"]["bert"] = bert_subtree
    if pooler is not None and "pooler" in out["params"]:
        _check_shapes(out["params"]["pooler"], pooler, "pooler")
        out["params"]["pooler"] = pooler
    return out


def _check_shapes(ours, theirs, name: str) -> None:
    import jax

    ours_leaves = jax.tree_util.tree_leaves_with_path(ours)
    theirs_flat = dict(jax.tree_util.tree_leaves_with_path(theirs))
    for path, leaf in ours_leaves:
        if path not in theirs_flat:
            raise KeyError(f"{name}: missing converted param at {path}")
        if tuple(theirs_flat[path].shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"{name}: shape mismatch at {path}: "
                f"{theirs_flat[path].shape} vs {np.shape(leaf)}"
            )
