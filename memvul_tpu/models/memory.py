"""The Siamese memory-network matcher (the flagship model).

Reference semantics (MemVul/model_memory.py):

* encode a text with BERT, take tanh-pooled CLS, optionally pass a
  ReLU projection header 768→512 (reference: model_memory.py:64-71);
* training: encode both pair members, classify ``[u, v, |u-v|]`` with a
  bias-free linear layer into {same, diff}, cross-entropy on
  ``logits / temperature`` (reference: model_memory.py:150-158);
* inference: encode the report once and match it against the whole
  anchor bank.

TPU-first redesign of the inference step: the reference loops/expands
per anchor (reference: model_memory.py:134-147); here the bias-free
linear over the concatenation decomposes into three matmuls —

    logits[b,a] = u[b]·W_u + v[a]·W_v + |u[b]-v[a]|·W_d

so the whole bank match is two tiny matmuls plus one batched abs-diff
contraction, fused by XLA into a single device program against a
device-resident anchor bank.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp
from flax import linen as nn

from .bert import BertConfig, BertEncoder, BertPooler
from .losses import masked_cross_entropy


class ProjectionHeader(nn.Module):
    """FeedForward(hidden→header_dim, ReLU, dropout) — reference's
    ``_projector_single`` (model_memory.py:70)."""

    config: BertConfig
    header_dim: int = 512

    @nn.compact
    def __call__(self, x, deterministic: bool = True):
        x = nn.Dense(self.header_dim, dtype=self.config.dtype, name="dense")(x)
        x = nn.relu(x)
        return nn.Dropout(self.config.hidden_dropout)(x, deterministic=deterministic)


class MemoryModel(nn.Module):
    config: BertConfig
    use_header: bool = True
    header_dim: int = 512
    temperature: float = 0.1
    num_classes: int = 2

    def setup(self):
        self.encoder = BertEncoder(self.config, name="bert")
        self.pooler = BertPooler(self.config, name="pooler")
        if self.use_header:
            self.header = ProjectionHeader(self.config, self.header_dim, name="header")
        # bias-free pair classifier over [u, v, |u-v|]
        # (reference: model_memory.py:73); owned directly so the training
        # and anchor-match paths share one parameter
        out_dim = self.header_dim if self.use_header else self.config.hidden_size
        self.pair_kernel = self.param(
            "pair_kernel",
            nn.initializers.normal(stddev=self.config.initializer_range),
            (3 * out_dim, self.num_classes),
        )

    def encode(self, sample, deterministic: bool = True) -> jax.Array:
        """Token batch {input_ids, attention_mask[, token_type_ids]} → [B, D].

        The named scopes here (with the per-op ones inside the encoder)
        are what make a ``trace_context`` profile attributable — xprof
        shows "bert_encode"/"pooler"/"header" rows instead of one fused
        blob (docs/observability.md, named-scope map)."""
        with jax.named_scope("bert_encode"):
            hidden = self.encoder(
                sample["input_ids"],
                sample["attention_mask"],
                sample.get("token_type_ids"),
                deterministic=deterministic,
            )
        with jax.named_scope("pooler"):
            pooled = self.pooler(hidden, deterministic=deterministic)
        if self.use_header:
            with jax.named_scope("header"):
                pooled = self.header(pooled, deterministic=deterministic)
        return pooled

    def encode_ragged(self, sample, deterministic: bool = True) -> jax.Array:
        """Packed flat batch → per-request embeddings [max_rows, D].

        ``sample`` is one :func:`~memvul_tpu.data.batching.collate_ragged`
        pack: a ``[1, token_budget]`` token row whose ``segment_ids``
        block attention on request boundaries and whose ``position_ids``
        restart per request, plus the ``row_starts`` table.  The encoder
        runs ONCE over the flat row; segment-aware pooling then gathers
        each request's CLS position out of it and feeds the gathered
        rows through the same pooler/header parameters the padded path
        uses — so a request's embedding matches its padded-batch
        embedding up to attention reduction order (docs/ragged_serving.md).
        Rows past the pack's real count gather position 0 and are sliced
        off host-side."""
        with jax.named_scope("bert_encode_ragged"):
            hidden = self.encoder(
                sample["input_ids"],
                sample["attention_mask"],
                sample.get("token_type_ids"),
                deterministic=deterministic,
                position_ids=sample["position_ids"],
                segment_ids=sample["segment_ids"],
            )
        with jax.named_scope("ragged_row_gather"):
            # [1, budget, H] → [max_rows, 1, H]: each row's CLS token,
            # shaped so the pooler's hidden[:, 0] sees one CLS per row
            cls = jnp.take(hidden[0], sample["row_starts"], axis=0)[:, None, :]
        with jax.named_scope("pooler"):
            pooled = self.pooler(cls, deterministic=deterministic)
        if self.use_header:
            with jax.named_scope("header"):
                pooled = self.header(pooled, deterministic=deterministic)
        return pooled

    def score_ragged(
        self,
        sample,
        anchors: jax.Array,
        deterministic: bool = True,
        anchor_impl: Optional[str] = None,
    ) -> jax.Array:
        """Packed flat batch × bank [A, D] → anchor logits
        [max_rows, A, 2] — the ragged twin of ``__call__(sample1,
        anchors=...)`` (invoked via ``model.apply(...,
        method=model.score_ragged)`` by the predictor's ragged score
        program)."""
        u = self.encode_ragged(sample, deterministic=deterministic)
        return self.match_anchors(u, anchors, impl=anchor_impl)

    def pair_logits(self, u: jax.Array, v: jax.Array) -> jax.Array:
        """[B, D] × [B, D] → [B, 2] (training path)."""
        with jax.named_scope("pair_logits"):
            features = jnp.concatenate([u, v, jnp.abs(u - v)], axis=-1)
            return features @ self.pair_kernel.astype(features.dtype)

    def match_anchors(
        self, u: jax.Array, anchors: jax.Array, impl: Optional[str] = None
    ) -> jax.Array:
        """[B, D] × [A, D] → logits [B, A, 2] against the full bank.

        Decomposes the concat-linear so no [B, A, 3D] tensor is built;
        the backend for the remaining |u-v| contraction comes from
        ``config.anchor_match_impl`` (or the per-call ``impl`` override):
        on TPU the fused Pallas kernel streams the [B, A, D] intermediate
        through VMEM so it never touches HBM; elsewhere (and for a
        model-sharded bank) the jnp decomposition runs
        (ops/pallas/anchor_match.py).

        Degradation: a Pallas/Mosaic build failure in the fused path
        falls back to the jnp decomposition with one warning instead of
        aborting (the two are parity-pinned ≤1e-5) — the dispatch in
        ``ops.pallas.anchor_match`` handles trace-time failures, and
        ``SiamesePredictor`` rebuilds its score program on "xla" for
        failures that only surface at jit-compile time.
        """
        from ..ops.pallas.anchor_match import anchor_match

        kernel = self.pair_kernel.astype(u.dtype)
        return anchor_match(
            u, anchors, kernel, impl=impl or self.config.anchor_match_impl
        )

    def __call__(
        self,
        sample1,
        sample2=None,
        anchors: Optional[jax.Array] = None,
        deterministic: bool = True,
        anchor_impl: Optional[str] = None,
        sample2_index: Optional[jax.Array] = None,
    ):
        """Training: (sample1, sample2) → pair logits [B, 2].
        Inference: (sample1, anchors=[A, D]) → anchor logits [B, A, 2].
        ``anchor_impl`` overrides ``config.anchor_match_impl`` per call
        (the predictor forces "xla" when the bank is model-sharded).

        ``sample2_index`` enables in-batch anchor deduplication: sample2
        then holds only the batch's UNIQUE second-side rows [U, L] and
        the [B] index gathers each pair's embedding back to its position
        — tower-2 runs U ≤ B rows instead of B, and gradients scatter-add
        through the gather automatically.  The gather is exact (bitwise:
        duplicate pairs share one embedding row), so pair losses match
        the undeduped batch up to the batch-size sensitivity of the
        encoder itself (parity pinned in tests/test_train_throughput.py).
        """
        u = self.encode(sample1, deterministic=deterministic)
        if anchors is not None:
            return self.match_anchors(u, anchors, impl=anchor_impl)
        if sample2 is None:
            return u
        v = self.encode(sample2, deterministic=deterministic)
        if sample2_index is not None:
            with jax.named_scope("anchor_dedup_gather"):
                v = jnp.take(v, sample2_index, axis=0)
        return self.pair_logits(u, v)

    def loss(self, logits, labels, weights) -> jax.Array:
        """Pair loss at this model's configured temperature."""
        return pair_loss(logits, labels, weights, self.temperature)


def pair_loss(
    logits: jax.Array,
    labels: jax.Array,
    weights: jax.Array,
    temperature: float,
) -> jax.Array:
    """Mean CE over real rows of ``logits/temperature``
    (reference: model_memory.py:158)."""
    return masked_cross_entropy(
        logits.astype(jnp.float32) / temperature, labels, weights
    )


def anchor_probs(anchor_logits: jax.Array, same_index: int = 0) -> jax.Array:
    """[B, A, 2] logits → per-anchor P(same) [B, A]."""
    probs = jax.nn.softmax(anchor_logits.astype(jnp.float32), axis=-1)
    return probs[..., same_index]


def best_anchor_score(anchor_logits: jax.Array, same_index: int = 0):
    """Reference decision rule (model_memory.py:144-147, predict_memory.py
    :168-177): the report's positive-class probability is its *best* anchor
    match.  Returns (max P(same) [B], argmax anchor index [B])."""
    p_same = anchor_probs(anchor_logits, same_index)
    return p_same.max(axis=-1), p_same.argmax(axis=-1)
