"""The mandatory parity gate: tuning may change speed, never results.

Every candidate the tuner would persist is classified and checked
against the untuned baseline BEFORE it is eligible to win:

* **serving layout knobs** (micro-batch cap, coalescing window,
  ``token_budget``, ``max_rows_per_pack`` — anything that only changes
  HOW rows are packed into programs) must reproduce the fixed probe
  set's scores **bitwise** (``np.array_equal``).  The serving paths pin
  this property in their own test suites (a row's bucket depends only
  on its own length), so a mismatch here is a real score change, not
  noise — refusal code ``parity_score_mismatch``.
* **training collation knobs** (bucket grid, dedup, prefetch depth)
  must reproduce the per-step loss trajectory of a short deterministic
  epoch within the pinned step-parity tolerance
  (tests/test_train_throughput.py holds padding invariance and dedup
  parity at ~1e-5 per step; the gate allows ``LOSS_TOL`` to absorb one
  epoch of accumulation) — refusal code ``parity_loss_divergence``.
  Trajectory *length* must match exactly (same stream, same step
  count) — refusal code ``parity_step_count``.
* **anything score-adjacent** (the cascade band) does NOT come through
  here — it goes through ``bankops.evaluate_cascade`` →
  ``evaluate_gate`` (tuning/cascade.py), the same machinery bank
  promotions answer to.

Verdicts reuse the ``PromotionDecision`` reason idiom
(``{code, observed, limit}``) so tune reports and promotion audit
trails read the same way.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from .knobs import Candidate

# one short epoch of fp32 accumulation over the pinned 1e-5 per-step
# parity property; measured headroom, not an invitation
LOSS_TOL = 1e-4


@dataclasses.dataclass
class ParityVerdict:
    candidate: Candidate
    passed: bool
    reasons: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    max_abs_delta: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        return {
            "candidate": self.candidate.to_json(),
            "passed": self.passed,
            "reasons": list(self.reasons),
            "max_abs_delta": self.max_abs_delta,
        }


def check_serve_parity(
    candidate: Candidate,
    baseline_scores,
    candidate_scores,
) -> ParityVerdict:
    """Bitwise score equality on the fixed probe set for a layout-only
    serving candidate."""
    import numpy as np

    base = np.asarray(baseline_scores)
    cand = np.asarray(candidate_scores)
    if base.shape != cand.shape:
        return ParityVerdict(
            candidate=candidate, passed=False,
            reasons=[{
                "code": "parity_score_mismatch",
                "observed": f"shape {cand.shape} vs {base.shape}",
                "limit": "identical shapes",
            }],
        )
    if np.array_equal(base, cand):
        return ParityVerdict(candidate=candidate, passed=True,
                             max_abs_delta=0.0)
    delta = float(np.max(np.abs(base.astype(np.float64)
                                - cand.astype(np.float64))))
    return ParityVerdict(
        candidate=candidate, passed=False, max_abs_delta=delta,
        reasons=[{
            "code": "parity_score_mismatch",
            "observed": delta,
            "limit": 0.0,
        }],
    )


def check_train_parity(
    candidate: Candidate,
    baseline_losses: Sequence[float],
    candidate_losses: Sequence[float],
    *,
    tol: float = LOSS_TOL,
) -> ParityVerdict:
    """Loss-trajectory equality (within ``tol``) for a training
    collation candidate over the identical seeded epoch stream."""
    base = list(baseline_losses)
    cand = list(candidate_losses)
    if len(base) != len(cand):
        return ParityVerdict(
            candidate=candidate, passed=False,
            reasons=[{
                "code": "parity_step_count",
                "observed": len(cand),
                "limit": len(base),
            }],
        )
    if not base:
        return ParityVerdict(
            candidate=candidate, passed=False,
            reasons=[{
                "code": "parity_no_evidence",
                "observed": 0,
                "limit": ">=1 probe step",
            }],
        )
    delta = max(abs(float(b) - float(c)) for b, c in zip(base, cand))
    if delta <= tol:
        return ParityVerdict(candidate=candidate, passed=True,
                             max_abs_delta=delta)
    return ParityVerdict(
        candidate=candidate, passed=False, max_abs_delta=delta,
        reasons=[{
            "code": "parity_loss_divergence",
            "observed": delta,
            "limit": tol,
        }],
    )
