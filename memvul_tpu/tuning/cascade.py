"""Cascade rescue-band autotuner (ROADMAP cascade follow-up d).

The two-tier cascade serves int8 scores everywhere and rescores only
rows whose int8 score lands inside ``[cascade_low, cascade_high]``
through the fp32 program.  The band has been hand-set (0.3/0.7) since
the cascade shipped; the right band is a property of the *score
distribution on this model + golden set* — wide enough to catch every
row the int8 tier might flip across the decision threshold, narrow
enough that the fp32 rescue bill stays at the target rescore rate.

:func:`choose_band` derives it from measurement: score the golden set
on the pure int8 tier, take the ``target_rescore_rate`` fraction of
rows NEAREST the decision threshold (those are the flippable ones), and
set the band to exactly cover their scores.  The chosen band is then
**gated, not trusted**: the predictor's band is set to the candidate
and ``bankops.evaluate_cascade`` runs the full fp32-vs-cascade
promotion gate (AUC/F1 drop, flip rate) over the same golden set — a
band that lets uncertain rows short-circuit on int8 refuses with the
standard machine-readable reasons and the hand-set default stays.
"""

from __future__ import annotations

import math
from typing import Any, Dict, Iterable, Optional


def choose_band(
    predictor,
    eval_instances: Iterable[Dict],
    *,
    target_rescore_rate: float = 0.1,
    threshold: float = 0.5,
    thresholds=None,
) -> Dict[str, Any]:
    """Pick ``[cascade_low, cascade_high]`` from the golden set's int8
    score distribution and gate it through ``evaluate_cascade``.

    Returns a JSON-ready record: the chosen band, the predicted rescore
    rate it implies on this golden set, and the gate's
    ``PromotionDecision``.  ``approved=False`` means the caller must
    keep the shipped default band.

    ``thresholds`` defaults to the standard :class:`GateThresholds`
    with ``min_shadow_samples`` relaxed to the golden-set size when the
    set is smaller than 100 — the offline flip summary IS the whole
    golden set here, there is no larger sample to insist on.
    """
    import numpy as np

    from ..bankops.promote import GateThresholds, evaluate_cascade

    instances = list(eval_instances)
    if not instances:
        raise ValueError("choose_band needs a non-empty golden set")
    if not 0.0 < target_rescore_rate <= 1.0:
        raise ValueError(
            f"target_rescore_rate must be in (0, 1], got {target_rescore_rate}"
        )
    texts = [inst["text1"] for inst in instances]
    int8 = predictor.score_texts(texts, impl="int8")
    best = np.asarray(int8).max(axis=-1)

    # the flippable rows are the ones nearest the decision threshold;
    # cover exactly the target fraction of them
    k = max(1, math.ceil(target_rescore_rate * len(best)))
    nearest = np.argsort(np.abs(best - threshold), kind="stable")[:k]
    low = float(best[nearest].min())
    high = float(best[nearest].max())
    # a one-sided cluster (every near-threshold score below it) still
    # must cover the threshold itself, or a row AT the decision
    # boundary would short-circuit on int8
    low = min(low, threshold)
    high = max(high, threshold)
    predicted = float(((best >= low) & (best <= high)).mean())

    if thresholds is None:
        thresholds = GateThresholds(
            min_shadow_samples=min(100, len(instances))
        )
    prior_band = tuple(predictor.cascade_band)
    predictor.cascade_band = (low, high)
    try:
        decision = evaluate_cascade(
            predictor, instances, thresholds=thresholds, threshold=threshold
        )
    finally:
        # the tuner only measures; installing the band is the profile
        # loader's job, after the gate approves
        predictor.cascade_band = prior_band
    return {
        "cascade_low": round(low, 6),
        "cascade_high": round(high, 6),
        "target_rescore_rate": target_rescore_rate,
        "predicted_rescore_rate": round(predicted, 6),
        "golden_set_size": len(instances),
        "decision_threshold": threshold,
        "gate": decision.to_json(),
        "approved": bool(decision.approved),
    }
