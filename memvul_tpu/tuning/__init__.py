"""Roofline-driven offline autotuner (docs/tuning.md).

The performance envelope of the stack — training bucket grids, the
dedup capacity ladder, prefetch depth, serving ``token_budget`` /
``max_rows_per_pack`` / micro-batch caps, the cascade rescue band — has
been governed by hand-set config, while the compiled-program registry
(telemetry/programs.py) measures FLOPs, bytes, HBM footprint and
achieved MFU for every executable.  This package closes that loop
offline:

* :mod:`knobs` — the per-device-class candidate space (training and
  serving knob grids);
* :mod:`prune` — analytic feasibility through ``ProgramRegistry``
  cost/memory analysis + the peak-spec table (HBM overflow,
  compiled-program-count blowups) BEFORE a candidate costs a run;
* :mod:`microbench` — short seeded in-process microbench runs (the
  same primitives as ``BENCH_MICRO=train_step`` / ``serve``) scoring
  the survivors;
* :mod:`parity` — the mandatory gate: layout-only candidates must
  reproduce a fixed probe set's scores bitwise (and loss trajectories
  within the pinned step-parity tolerance); anything score-adjacent
  goes through the ``bankops.evaluate_gate`` machinery.  Tuning can
  change speed, never results;
* :mod:`cascade` — the ``[cascade_low, cascade_high]`` band autotuner
  (golden-set score distributions → target rescore rate), gated by
  ``bankops.evaluate_cascade``;
* :mod:`profile` — the versioned, sha256-manifested tuned profile per
  device class that ``build.train_from_config`` /
  ``build.serve_from_archive`` load by default (explicit config always
  wins; unknown device class falls back to the shipped defaults);
* :mod:`report` — the measured roofline table renderer
  (docs/roofline_train.md's generated section);
* :mod:`autotune` — the orchestration the ``python -m memvul_tpu
  tune`` CLI drives.
"""

from .knobs import Candidate, serve_space, train_space  # noqa: F401
from .profile import (  # noqa: F401
    PROFILE_SCHEMA,
    load_profile,
    resolve_device_class,
    save_profile,
)
