"""Measured roofline report: the registry's numbers, rendered.

docs/roofline_train.md originally carried a hand-derived FLOP budget
and a hand-computed ~25% MFU estimate.  PR 11's ``ProgramRegistry``
measures all of it — XLA-analyzed FLOPs/bytes per program, HBM
footprint, invocation counts, device time — so the table should be
*generated*, not maintained.  ``python -m memvul_tpu tune --report``
renders this module's markdown from the live registry (or a persisted
``programs.json``), and the generated section in the doc is fenced by
the marker comments below so regeneration is a splice, not an edit.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Union

BEGIN_MARK = "<!-- BEGIN GENERATED: tune --report -->"
END_MARK = "<!-- END GENERATED: tune --report -->"


def _fmt_count(x: Optional[float], unit: str = "") -> str:
    if x is None:
        return "—"
    x = float(x)
    for factor, suffix in ((1e12, "T"), (1e9, "G"), (1e6, "M"), (1e3, "k")):
        if abs(x) >= factor:
            return f"{x / factor:.2f} {suffix}{unit}".rstrip()
    return f"{x:.6g} {unit}".rstrip()


def _fmt_pct(x: Optional[float]) -> str:
    return "—" if x is None else f"{100.0 * float(x):.1f}%"


def roofline_markdown(
    snapshot: Sequence[Dict[str, Any]],
    roofline: Dict[str, Any],
) -> str:
    """The generated roofline section: per-program measured table +
    aggregate achieved-vs-peak summary.  Pure formatting — callable on
    a live registry's ``snapshot()``/``roofline()`` or on a persisted
    ``programs.json``, no jax anywhere."""
    lines: List[str] = [BEGIN_MARK, ""]
    kind = roofline.get("device_kind", "unknown")
    if roofline.get("interpret_only"):
        lines += [
            f"Measured on `{kind}` — **interpret-only** (no peak spec: "
            "analyzed FLOPs/bytes below are real XLA cost-analysis "
            "output, the MFU/bandwidth columns stay null rather than "
            "divide by a made-up peak).",
            "",
        ]
    else:
        lines += [
            f"Measured on `{kind}` — peak "
            f"{_fmt_count(roofline.get('peak_flops_per_s'), 'FLOP/s')}, "
            f"{_fmt_count(roofline.get('peak_bytes_per_s'), 'B/s')} HBM.",
            "",
        ]
    lines += [
        "| program | invocations | FLOPs/inv | bytes/inv | HBM bytes "
        "| device s | MFU |",
        "|---|---|---|---|---|---|---|",
    ]
    for row in snapshot:
        lines.append(
            "| `{key}` | {inv} | {flops} | {bytes} | {hbm} | {dev} | {mfu} |"
            .format(
                key=row.get("key", "?"),
                inv=row.get("invocations", 0),
                flops=_fmt_count(row.get("flops")),
                bytes=_fmt_count(row.get("bytes_accessed")),
                hbm=_fmt_count(row.get("hbm_bytes")),
                dev=f"{row.get('device_time_s', 0.0):.4f}",
                mfu=_fmt_pct(row.get("mfu")),
            )
        )
    lines += [
        "",
        "Aggregate: {n} programs, {flops} total FLOPs, {bytes} total "
        "bytes, {dev:.4f} s device time — achieved {af}, {ab}, "
        "MFU {mfu}, HBM bandwidth {bw}.".format(
            n=roofline.get("programs", len(snapshot)),
            flops=_fmt_count(roofline.get("flops_total")),
            bytes=_fmt_count(roofline.get("bytes_total")),
            dev=float(roofline.get("device_time_s") or 0.0),
            af=_fmt_count(roofline.get("achieved_flops_per_s"), "FLOP/s"),
            ab=_fmt_count(roofline.get("achieved_bytes_per_s"), "B/s"),
            mfu=_fmt_pct(roofline.get("mfu")),
            bw=_fmt_pct(roofline.get("membw_util")),
        ),
        "",
        END_MARK,
    ]
    return "\n".join(lines)


def report_from_registry(registry=None) -> str:
    """Render from the live process registry (default: the
    process-wide one)."""
    from ..telemetry.programs import get_program_registry

    reg = registry if registry is not None else get_program_registry()
    return roofline_markdown(reg.snapshot(), reg.roofline())


def report_from_programs_json(path: Union[str, Path]) -> str:
    """Render from a run dir's persisted ``programs.json``
    (``telemetry.programs.write_programs`` output)."""
    payload = json.loads(Path(path).read_text())
    return roofline_markdown(
        payload.get("programs") or [], payload.get("roofline") or {}
    )


def splice_generated_section(doc_text: str, generated: str) -> str:
    """Replace the fenced generated section of a doc with a fresh
    render (or append one when the doc has no fence yet)."""
    begin = doc_text.find(BEGIN_MARK)
    end = doc_text.find(END_MARK)
    if begin == -1 or end == -1 or end < begin:
        sep = "" if doc_text.endswith("\n") else "\n"
        return f"{doc_text}{sep}\n{generated}\n"
    return (
        doc_text[:begin] + generated + doc_text[end + len(END_MARK):]
    )
