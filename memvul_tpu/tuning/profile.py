"""Tuned-profile store: versioned, sha256-manifested, per device class.

Layout (``<root>/<device_class>/``)::

    profile-0001.json     # immutable profile documents, one per tune run
    profile-0002.json
    MANIFEST.json         # {"schema", "device_class", "version",
                          #  "active": "profile-0002.json",
                          #  "sha256": <of the active document>}

Writes follow the BankStore/checkpoint idiom — every file lands through
``atomic_write_text`` and the manifest commit is the atomic pointer
advance, so a kill mid-write leaves the previous profile intact.  Loads
verify the manifest checksum and the document schema; ANY failure
(missing file, torn JSON, checksum mismatch, stale schema) degrades to
"no profile" with one warning per path — the build entry points then
run on today's shipped defaults, exactly as if no tuner had ever run.

The device class is the normalized ``device_kind`` of the default
backend (``tpu_v5_lite``, ``cpu``, …).  A class with no peak-spec row
still *loads* a profile fine (the profile was measured, not derived
from a roofline) — the refusal to TUNE against a made-up roofline lives
in :mod:`memvul_tpu.tuning.autotune`.
"""

from __future__ import annotations

import hashlib
import json
import logging
import os
import re
import time
from pathlib import Path
from typing import Any, Dict, Optional, Set, Tuple, Union

logger = logging.getLogger(__name__)

PROFILE_SCHEMA = 1
MANIFEST_NAME = "MANIFEST.json"

# env override for the profile root; the tuning.profile_dir config key
# wins over it, explicit config always wins over any loaded profile
PROFILE_DIR_ENV = "MEMVUL_TUNED_PROFILES"

# knobs a profile may carry per section; anything else is dropped at
# apply time (a stale profile from a newer schema cannot smuggle an
# unknown key into a TrainerConfig/ServiceConfig constructor)
TRAIN_PROFILE_KEYS = ("train_buckets", "dedup_anchors", "prefetch_depth")
SERVING_PROFILE_KEYS = (
    "score_impl", "max_batch", "max_wait_ms", "token_budget",
    "max_rows_per_pack", "cascade_low", "cascade_high",
)

# one warning per offending path per process — a serving fleet that
# builds N replicas through the same corrupt profile logs once, not N
# times
_warned_paths: Set[str] = set()


def _warn_once(path: Path, message: str) -> None:
    key = str(path)
    if key in _warned_paths:
        return
    _warned_paths.add(key)
    logger.warning("tuned profile %s: %s — falling back to defaults",
                   path, message)


def normalize_device_class(kind: str) -> str:
    """``"TPU v5 lite"`` → ``"tpu_v5_lite"`` — filesystem- and
    metric-suffix-safe."""
    return re.sub(r"[^a-z0-9]+", "_", str(kind).lower()).strip("_") or "unknown"


def resolve_device_class(
    override: Optional[str] = None,
) -> Tuple[str, Optional[Dict[str, float]]]:
    """(device_class, peak_spec_or_None) for the default backend, or for
    an explicit override (cross-class tuning / tests)."""
    from ..telemetry.programs import device_info, peak_spec

    if override:
        return normalize_device_class(override), peak_spec(str(override))
    _platform, kind = device_info()
    return normalize_device_class(kind), peak_spec(kind)


def profile_root(configured: Optional[Union[str, Path]] = None) -> Optional[Path]:
    """The tuned-profile root directory: the ``tuning.profile_dir``
    config value when set, else ``$MEMVUL_TUNED_PROFILES``, else None
    (no profile loading at all — the zero-config default)."""
    if configured:
        return Path(configured)
    env = os.environ.get(PROFILE_DIR_ENV)
    return Path(env) if env else None


def _sha256(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def save_profile(
    root: Union[str, Path],
    device_class: str,
    profile: Dict[str, Any],
) -> Path:
    """Persist one tune run's output as the next profile version and
    advance the manifest pointer.  Returns the written document path."""
    from ..resilience.io import atomic_write_text

    class_dir = Path(root) / normalize_device_class(device_class)
    class_dir.mkdir(parents=True, exist_ok=True)
    manifest_path = class_dir / MANIFEST_NAME
    version = 0
    if manifest_path.exists():
        try:
            version = int(json.loads(manifest_path.read_text()).get("version", 0))
        except (ValueError, json.JSONDecodeError):
            # a torn manifest must not wedge the writer; restart at the
            # highest on-disk document version
            versions = [
                int(m.group(1))
                for p in class_dir.glob("profile-*.json")
                if (m := re.match(r"profile-(\d+)\.json$", p.name))
            ]
            version = max(versions, default=0)
    version += 1
    document = dict(profile)
    document["schema"] = PROFILE_SCHEMA
    document["device_class"] = normalize_device_class(device_class)
    document["version"] = version
    document.setdefault("created_wall", time.time())
    text = json.dumps(document, indent=2, sort_keys=True, default=float)
    doc_name = f"profile-{version:04d}.json"
    atomic_write_text(class_dir / doc_name, text)
    atomic_write_text(manifest_path, json.dumps({
        "schema": PROFILE_SCHEMA,
        "device_class": document["device_class"],
        "version": version,
        "active": doc_name,
        "sha256": _sha256(text),
    }, indent=2))
    return class_dir / doc_name


def load_profile(
    root: Optional[Union[str, Path]],
    device_class: str,
) -> Optional[Dict[str, Any]]:
    """The active tuned profile for a device class, checksum-verified,
    or None (no root configured / no profile for this class / any
    corruption — each failure warns once and degrades to defaults)."""
    if root is None:
        return None
    class_dir = Path(root) / normalize_device_class(device_class)
    manifest_path = class_dir / MANIFEST_NAME
    if not manifest_path.exists():
        return None  # untuned device class: silent defaults, not a warning
    try:
        manifest = json.loads(manifest_path.read_text())
    except (OSError, json.JSONDecodeError) as e:
        _warn_once(manifest_path, f"unreadable manifest ({e})")
        return None
    doc_path = class_dir / str(manifest.get("active") or "")
    if not doc_path.is_file():
        _warn_once(manifest_path,
                   f"active document {manifest.get('active')!r} missing")
        return None
    try:
        text = doc_path.read_text()
    except OSError as e:
        _warn_once(doc_path, f"unreadable ({e})")
        return None
    if _sha256(text) != manifest.get("sha256"):
        _warn_once(doc_path, "sha256 mismatch vs MANIFEST.json")
        return None
    try:
        document = json.loads(text)
    except json.JSONDecodeError as e:
        _warn_once(doc_path, f"torn JSON ({e})")
        return None
    if document.get("schema") != PROFILE_SCHEMA:
        _warn_once(
            doc_path,
            f"stale schema {document.get('schema')!r} "
            f"(this build reads {PROFILE_SCHEMA})",
        )
        return None
    return document


def _emit_device_class_gauge(device_class: str, applied: bool) -> None:
    """``tune.device_class.<class>`` — 1.0 when a tuned profile was
    applied for this class, 0.0 when the build fell back to defaults
    (untuned class, disabled loading, or a corrupt store)."""
    from ..telemetry import get_registry

    get_registry().gauge(f"tune.device_class.{device_class}").set(
        1.0 if applied else 0.0
    )


def _load_for_build(config) -> Tuple[Optional[Dict[str, Any]], str]:
    """Shared by the two apply helpers: resolve (profile_or_None,
    device_class) from a run config's ``tuning`` section."""
    from ..config import tuning_config

    tcfg = tuning_config(config)
    device_class, _peak = resolve_device_class(tcfg.get("device_class"))
    if not bool(tcfg["enabled"]):
        return None, device_class
    root = profile_root(tcfg.get("profile_dir"))
    return load_profile(root, device_class), device_class


def apply_tuned_trainer(
    trainer_cfg: Dict[str, Any], config: Optional[Dict[str, Any]]
) -> Dict[str, Any]:
    """Overlay the tuned profile's training knobs UNDER the config's
    explicit ``trainer`` section: a knob the user wrote wins untouched;
    only absent knobs take the tuned value.  No profile → the dict
    comes back unchanged (byte-identical pre-tuner behavior)."""
    profile, device_class = _load_for_build(config)
    tuned = dict((profile or {}).get("train") or {})
    applied = {}
    for key in TRAIN_PROFILE_KEYS:
        if key in tuned and key not in trainer_cfg:
            trainer_cfg[key] = tuned[key]
            applied[key] = tuned[key]
    _emit_device_class_gauge(device_class, bool(applied))
    if applied:
        logger.info(
            "tuned profile %s v%s: applied trainer knobs %s",
            device_class, (profile or {}).get("version"), applied,
        )
    return trainer_cfg


def apply_tuned_serving(
    serve_cfg: Dict[str, Any],
    explicit_section: Optional[Dict[str, Any]],
    config: Optional[Dict[str, Any]],
) -> Dict[str, Any]:
    """Overlay the tuned profile's serving knobs under the archive
    config's explicit ``serving`` section.  ``serve_cfg`` is the
    defaults-merged view (``config.serving_config``), so explicitness is
    judged against the RAW section: a key the archive/overrides wrote
    (non-null) always wins; knobs the profile tuned fill the rest."""
    profile, device_class = _load_for_build(config)
    tuned = dict((profile or {}).get("serving") or {})
    explicit = {
        k for k, v in (explicit_section or {}).items() if v is not None
    }
    applied = {}
    for key in SERVING_PROFILE_KEYS:
        if key in tuned and key not in explicit:
            serve_cfg[key] = tuned[key]
            applied[key] = tuned[key]
    _emit_device_class_gauge(device_class, bool(applied))
    if applied:
        logger.info(
            "tuned profile %s v%s: applied serving knobs %s",
            device_class, (profile or {}).get("version"), applied,
        )
    return serve_cfg
