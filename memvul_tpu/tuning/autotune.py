"""Tune-run orchestration: the engine behind ``python -m memvul_tpu tune``.

One :func:`run_tune` call is one offline tuning pass for ONE device
class:

1. resolve the device class (``--device-class`` override or the default
   backend) and its ``PEAK_SPECS`` row — a class with no peak spec is a
   machine-readable ``unknown_device_class`` refusal unless the caller
   explicitly opts into measurement-only mode
   (``allow_unknown_device``: the analytic HBM pruner then skips with a
   note instead of pruning against a made-up roofline; this is how the
   CPU harness record is produced);
2. enumerate the knob space (tuning/knobs.py), prune analytically
   (tuning/prune.py), and microbench every survivor with the seeded
   in-process harness (tuning/microbench.py);
3. run the mandatory parity gate per survivor (tuning/parity.py):
   layout-only candidates must match the untuned baseline bitwise
   (serving probe scores) / within the pinned step tolerance (training
   loss trajectory).  A candidate that fails parity CANNOT win,
   whatever its throughput;
4. optionally tune the cascade band (tuning/cascade.py) — the one
   score-adjacent knob, gated through ``bankops.evaluate_cascade``;
5. pick winners (train: real-token throughput; serve: requests/sec),
   and persist the versioned profile (tuning/profile.py) when an output
   root is given.

The returned record is the whole audit trail: every candidate's prune
decision, parity verdict, and measurement, plus the winners and the
tuned-vs-default deltas.  ``tune.*`` counters
(candidates/pruned/parity_refused) and the ``tune.device_class.<class>``
gauge make a tune run observable like every other subsystem.
"""

from __future__ import annotations

import logging
from typing import Any, Dict, List, Optional

from .knobs import serve_space, train_space
from .microbench import TuneBench
from .parity import check_serve_parity, check_train_parity
from .prune import prune_candidates
from .profile import resolve_device_class, save_profile

logger = logging.getLogger(__name__)

# the hand-set defaults the tuner must beat — and the parity baselines
# every candidate is compared against
DEFAULT_TRAIN_KNOBS: Dict[str, Any] = {
    "train_buckets": "pow2", "dedup_anchors": True, "prefetch_depth": 8,
}
DEFAULT_SERVE_KNOBS: Dict[str, Any] = {
    "score_impl": "bucketed", "max_batch": 16, "max_wait_ms": 5.0,
}


def unknown_device_refusal(device_class: str) -> Dict[str, Any]:
    """The machine-readable refusal contract: tuning against a device
    with no peak-spec row would prune against a made-up roofline."""
    from ..telemetry.programs import PEAK_SPECS

    return {
        "error": "unknown_device_class",
        "device_class": device_class,
        "known_markers": sorted(PEAK_SPECS),
        "hint": (
            "pass --allow-unknown-device to tune in measurement-only "
            "mode (analytic HBM pruning skipped), or --device-class "
            "with a known marker to tune for a target class"
        ),
    }


def _tel():
    from ..telemetry import get_registry

    return get_registry()


def _tune_train(
    bench: TuneBench,
    peak: Optional[Dict[str, float]],
    *,
    max_programs: int,
    hbm_fraction: float,
    space_kwargs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    kwargs = dict(
        max_length=bench.seq_len, batch_size=bench.batch_size,
    )
    kwargs.update(space_kwargs or {})
    candidates = train_space(**kwargs)
    decisions = prune_candidates(
        candidates, batch_size=bench.batch_size, max_length=bench.seq_len,
        max_batch=bench.max_batch, max_programs=max_programs,
        hbm_fraction=hbm_fraction, peak=peak,
    )
    _tel().counter("tune.candidates").inc(len(candidates))
    pruned = [d for d in decisions if not d.feasible]
    if pruned:
        _tel().counter("tune.pruned").inc(len(pruned))
    baseline = bench.bench_train(DEFAULT_TRAIN_KNOBS, with_losses=True)
    baseline_losses = baseline.pop("losses")
    results: List[Dict[str, Any]] = []
    best: Optional[Dict[str, Any]] = None
    for d in decisions:
        row: Dict[str, Any] = {"prune": d.to_json()}
        if d.feasible:
            measured = bench.bench_train(d.candidate.knobs, with_losses=True)
            losses = measured.pop("losses")
            verdict = check_train_parity(d.candidate, baseline_losses, losses)
            row["parity"] = verdict.to_json()
            if verdict.passed:
                row["bench"] = measured
                if (
                    best is None
                    or measured["real_tokens_per_s"]
                    > best["bench"]["real_tokens_per_s"]
                ):
                    best = row
            else:
                _tel().counter("tune.parity_refused").inc()
        results.append(row)
    return {
        "default_knobs": dict(DEFAULT_TRAIN_KNOBS),
        "default_bench": baseline,
        "candidates": results,
        "winner": best,
        "speedup_real_tokens": (
            round(
                best["bench"]["real_tokens_per_s"]
                / max(baseline["real_tokens_per_s"], 1e-9),
                3,
            )
            if best else None
        ),
    }


def _gate_impl_change(
    bench: TuneBench,
    base_knobs: Dict[str, Any],
    cand_knobs: Dict[str, Any],
    *,
    threshold: float = 0.5,
):
    """Cross-impl winner check: changing the dispatch impl itself
    (bucketed → ragged/continuous) is score-adjacent (the packed
    kernels pin ≤1e-6, not bitwise), so it answers to the same
    ``evaluate_gate`` machinery as a bank promotion — measured AUC/F1
    on the golden set plus a synthesized flip summary."""
    import numpy as np

    from ..bankops.promote import GateThresholds, evaluate_gate
    from ..training.metrics import SiameseMeasure

    instances = bench.golden_instances
    texts = [inst["text1"] for inst in instances]
    metas = [inst.get("meta") or {} for inst in instances]
    base = np.asarray(
        bench.build_predictor(base_knobs).score_texts(texts)
    )
    cand = np.asarray(
        bench.build_predictor(cand_knobs).score_texts(texts)
    )

    def _measured(probs) -> Dict[str, float]:
        measure = SiameseMeasure()
        measure.update(probs.max(axis=-1), metas)
        out = measure.compute(reset=True)
        out["n_eval"] = float(len(instances))
        return out

    best_base = base.max(axis=-1)
    best_cand = cand.max(axis=-1)
    flips = int(((best_base >= threshold) != (best_cand >= threshold)).sum())
    deltas = np.abs(best_cand - best_base)
    shadow_summary = {
        "sampled": len(instances),
        "flips": flips,
        "flip_rate": flips / max(len(instances), 1),
        "anchor_changes": int(
            (base.argmax(axis=-1) != cand.argmax(axis=-1)).sum()
        ),
        "mean_abs_delta": float(deltas.mean()) if len(deltas) else 0.0,
        "max_abs_delta": float(deltas.max()) if len(deltas) else 0.0,
    }
    return evaluate_gate(
        _measured(base),
        _measured(cand),
        shadow_summary,
        thresholds=GateThresholds(
            min_shadow_samples=min(100, len(instances))
        ),
        candidate=cand_knobs.get("score_impl", "?"),
        parent=base_knobs.get("score_impl", "?"),
    )


def _tune_serve(
    bench: TuneBench,
    peak: Optional[Dict[str, float]],
    *,
    max_programs: int,
    hbm_fraction: float,
    space_kwargs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    kwargs = dict(max_length=bench.seq_len, max_batch=bench.max_batch)
    kwargs.update(space_kwargs or {})
    candidates = serve_space(**kwargs)
    decisions = prune_candidates(
        candidates, batch_size=bench.batch_size, max_length=bench.seq_len,
        max_batch=bench.max_batch, max_programs=max_programs,
        hbm_fraction=hbm_fraction, peak=peak,
    )
    _tel().counter("tune.candidates").inc(len(candidates))
    pruned = [d for d in decisions if not d.feasible]
    if pruned:
        _tel().counter("tune.pruned").inc(len(pruned))
    default_knobs = dict(DEFAULT_SERVE_KNOBS, max_batch=bench.max_batch)
    baseline = bench.bench_serve(default_knobs)
    # per-impl parity baselines: layout knobs within an impl must be
    # bitwise against THAT impl's default layout; the impl change
    # itself is gated separately (evaluate_gate) on the winner
    probe_baselines: Dict[str, Any] = {}

    def _impl_baseline(impl: str):
        if impl not in probe_baselines:
            knobs = dict(default_knobs)
            if impl in ("ragged", "continuous"):
                knobs = {
                    "score_impl": impl,
                    "max_batch": bench.max_batch,
                    "token_budget": 4 * bench.seq_len,
                    "max_rows_per_pack": bench.max_batch,
                }
            probe_baselines[impl] = bench.probe_scores(knobs)
        return probe_baselines[impl]

    results: List[Dict[str, Any]] = []
    best: Optional[Dict[str, Any]] = None
    for d in decisions:
        row: Dict[str, Any] = {"prune": d.to_json()}
        if d.feasible:
            impl = d.candidate.knobs.get("score_impl", "bucketed")
            verdict = check_serve_parity(
                d.candidate,
                _impl_baseline(impl),
                bench.probe_scores(d.candidate.knobs),
            )
            row["parity"] = verdict.to_json()
            if verdict.passed:
                row["bench"] = bench.bench_serve(d.candidate.knobs)
                if (
                    best is None
                    or row["bench"]["requests_per_sec"]
                    > best["bench"]["requests_per_sec"]
                ):
                    best = row
            else:
                _tel().counter("tune.parity_refused").inc()
        results.append(row)
    impl_gate = None
    if best is not None:
        winner_knobs = best["prune"]["candidate"]["knobs"]
        if winner_knobs.get("score_impl", "bucketed") != "bucketed":
            decision = _gate_impl_change(bench, default_knobs, winner_knobs)
            impl_gate = decision.to_json()
            if not decision.approved:
                # fall back to the best same-impl candidate
                bucketed = [
                    r for r in results
                    if r.get("bench")
                    and r["prune"]["candidate"]["knobs"].get(
                        "score_impl", "bucketed") == "bucketed"
                ]
                best = max(
                    bucketed,
                    key=lambda r: r["bench"]["requests_per_sec"],
                    default=None,
                )
    return {
        "default_knobs": default_knobs,
        "default_bench": baseline,
        "candidates": results,
        "winner": best,
        "impl_gate": impl_gate,
        "speedup_rps": (
            round(
                best["bench"]["requests_per_sec"]
                / max(baseline["requests_per_sec"], 1e-9),
                3,
            )
            if best else None
        ),
    }


def run_tune(
    mode: str = "all",
    *,
    device_class: Optional[str] = None,
    allow_unknown_device: bool = False,
    out_dir: Optional[str] = None,
    cascade: bool = False,
    target_rescore_rate: float = 0.1,
    max_programs: int = 64,
    hbm_fraction: float = 0.9,
    bench_kwargs: Optional[Dict[str, Any]] = None,
    train_space_kwargs: Optional[Dict[str, Any]] = None,
    serve_space_kwargs: Optional[Dict[str, Any]] = None,
) -> Dict[str, Any]:
    """One offline tune pass.  Returns the full audit record; when the
    device class has no ``PEAK_SPECS`` row and ``allow_unknown_device``
    is False, returns the ``unknown_device_class`` refusal instead of
    tuning against a made-up roofline."""
    if mode not in ("train", "serve", "all"):
        raise ValueError(f"mode must be train|serve|all, got {mode!r}")
    cls, peak = resolve_device_class(device_class)
    _tel().gauge(f"tune.device_class.{cls}").set(1.0 if peak else 0.0)
    if peak is None and not allow_unknown_device:
        return unknown_device_refusal(cls)

    bench = TuneBench(**(bench_kwargs or {}))
    record: Dict[str, Any] = {
        "device_class": cls,
        "peak_spec": dict(peak) if peak else None,
        "mode": mode,
        "bench": {
            "model_size": bench.model_size, "seq_len": bench.seq_len,
            "batch_size": bench.batch_size,
            "steps_per_epoch": bench.steps_per_epoch,
            "n_requests": bench.n_requests, "n_clients": bench.n_clients,
            "max_batch": bench.max_batch, "seed": bench.seed,
        },
    }
    profile: Dict[str, Any] = {}
    if mode in ("train", "all"):
        record["train"] = _tune_train(
            bench, peak, max_programs=max_programs,
            hbm_fraction=hbm_fraction, space_kwargs=train_space_kwargs,
        )
        if record["train"]["winner"]:
            profile["train"] = dict(
                record["train"]["winner"]["prune"]["candidate"]["knobs"]
            )
    if mode in ("serve", "all"):
        record["serve"] = _tune_serve(
            bench, peak, max_programs=max_programs,
            hbm_fraction=hbm_fraction, space_kwargs=serve_space_kwargs,
        )
        if record["serve"]["winner"]:
            profile["serving"] = dict(
                record["serve"]["winner"]["prune"]["candidate"]["knobs"]
            )
    if cascade:
        from .cascade import choose_band

        predictor = bench.build_predictor({"score_impl": "cascade"})
        band = choose_band(
            predictor, bench.golden_instances,
            target_rescore_rate=target_rescore_rate,
        )
        record["cascade"] = band
        if band["approved"]:
            profile.setdefault("serving", {}).update(
                cascade_low=band["cascade_low"],
                cascade_high=band["cascade_high"],
            )
    record["profile"] = profile or None
    if out_dir and profile:
        evidence = {
            "train": {
                k: record.get("train", {}).get(k)
                for k in ("default_bench", "speedup_real_tokens")
            },
            "serve": {
                k: record.get("serve", {}).get(k)
                for k in ("default_bench", "speedup_rps")
            },
            "cascade": {
                k: record.get("cascade", {}).get(k)
                for k in ("predicted_rescore_rate", "approved")
            } if cascade else None,
        }
        path = save_profile(
            out_dir, cls, dict(profile, evidence=evidence)
        )
        record["profile_path"] = str(path)
        logger.info("tuned profile written: %s", path)
    return record
