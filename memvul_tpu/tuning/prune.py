"""Analytic candidate pruning (docs/tuning.md, "Pruning rule").

A microbench run is the expensive part of tuning — each survivor pays a
compile + a timed run.  This module rejects candidates the registry's
cost/memory analysis already proves infeasible, BEFORE they cost
anything:

* **compiled-program-count blowup** — the training bucket grid compiles
  one step program per occupied ``(anchor_bucket, report_bucket)`` cell,
  times the dedup capacity ladder (``data.batching.dedup_capacities``)
  when dedup is on.  A grid whose worst-case program count exceeds
  ``tuning.max_programs`` is pruned: on real devices each program is
  tens of seconds of XLA compile and its own HBM-resident executable.
* **HBM overflow** — scale the registry's measured per-program HBM
  footprint (argument+output+temp bytes from ``memory_analysis()``, the
  same figure the ``xla.hbm_bytes`` gauge reports) by the candidate's
  padded-token ratio against the measured baseline shape, and prune
  when the projection exceeds ``hbm_fraction`` of the device class's
  ``PEAK_SPECS["hbm_bytes"]`` capacity.

Both checks are *honest*: on an interpret-only host (CPU — no peak
spec, no ``memory_analysis``) or before any program has been measured,
the corresponding check is skipped and recorded as a note instead of
pruning against numbers that do not exist.  Every decision is a
JSON-serializable record carried into the tune report.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence

from .knobs import Candidate


@dataclasses.dataclass
class PruneDecision:
    """One candidate's analytic verdict.  ``feasible=False`` carries
    the refusal in ``reasons`` as ``{code, observed, limit}`` rows
    (the ``PromotionDecision`` reason idiom); skipped checks land in
    ``notes``."""

    candidate: Candidate
    feasible: bool = True
    reasons: List[Dict[str, Any]] = dataclasses.field(default_factory=list)
    notes: List[str] = dataclasses.field(default_factory=list)
    estimated_programs: Optional[int] = None
    estimated_hbm_bytes: Optional[float] = None

    def refuse(self, code: str, observed: float, limit: float) -> None:
        self.feasible = False
        self.reasons.append(
            {"code": code, "observed": observed, "limit": limit}
        )

    def to_json(self) -> Dict[str, Any]:
        return {
            "candidate": self.candidate.to_json(),
            "feasible": self.feasible,
            "reasons": list(self.reasons),
            "notes": list(self.notes),
            "estimated_programs": self.estimated_programs,
            "estimated_hbm_bytes": self.estimated_hbm_bytes,
        }


def _resolve_buckets(train_buckets, max_length: int) -> Optional[List[int]]:
    """The concrete bucket boundary list a knob value produces, via the
    same resolver the trainer uses (None → pad-to-max)."""
    if train_buckets is None:
        return None
    from ..data.batching import resolve_train_buckets

    return list(resolve_train_buckets(train_buckets, max_length))


def estimate_train_programs(
    train_buckets,
    dedup_anchors: bool,
    batch_size: int,
    max_length: int,
) -> int:
    """Worst-case compiled train-step program count for one collation
    candidate: every occupied ``(b_anchor, b_report)`` grid cell is a
    distinct step signature, and dedup multiplies each cell by its
    anchor-capacity ladder (``dedup_capacities``)."""
    buckets = _resolve_buckets(train_buckets, max_length)
    cells = 1 if buckets is None else len(buckets) ** 2
    if not dedup_anchors or buckets is None:
        return cells
    from ..data.batching import dedup_capacities

    ladder = len(dedup_capacities(batch_size))
    return cells * ladder


def measured_hbm_baseline(registry=None) -> Optional[Dict[str, float]]:
    """(max per-program HBM bytes, its padded token count proxy) from
    the live ``ProgramRegistry`` — None when nothing has been measured
    (fresh process, or a backend without ``memory_analysis``)."""
    from ..telemetry.programs import get_program_registry

    reg = registry if registry is not None else get_program_registry()
    rows = [r for r in reg.snapshot() if r.get("hbm_bytes")]
    if not rows:
        return None
    worst = max(rows, key=lambda r: r["hbm_bytes"])
    return {"hbm_bytes": float(worst["hbm_bytes"]), "key": worst["key"]}


def _padded_token_ratio(candidate: Candidate, max_length: int,
                        batch_size: int, max_batch: int) -> float:
    """How the candidate's worst-case padded footprint scales against
    the baseline shape the registry measured (pad-to-max at the default
    batch).  Deliberately coarse — an upper bound, not a model: a
    bucket grid's worst cell is the full-length bucket, a serving
    token_budget IS the padded token count of one pack."""
    knobs = candidate.knobs
    if candidate.kind == "train":
        # worst-case cell is always (max bucket)^2 == pad-to-max, so
        # collation knobs never grow the footprint; prefetch_depth holds
        # `depth` host-side batches but no extra device residency
        return 1.0
    impl = knobs.get("score_impl", "bucketed")
    if impl == "bucketed":
        return float(knobs.get("max_batch", max_batch)) / float(max_batch)
    budget = float(knobs.get("token_budget") or 4 * max_length)
    baseline_tokens = float(max_batch * max_length)
    return budget / baseline_tokens if baseline_tokens else 1.0


def prune_candidates(
    candidates: Sequence[Candidate],
    *,
    batch_size: int = 32,
    max_length: int = 512,
    max_batch: int = 16,
    max_programs: int = 64,
    hbm_fraction: float = 0.9,
    peak: Optional[Dict[str, float]] = None,
    registry=None,
) -> List[PruneDecision]:
    """Run both analytic checks over a candidate list.  ``peak`` is the
    device class's ``PEAK_SPECS`` row (None on interpret-only hosts —
    the HBM check is then skipped with a note, never guessed)."""
    baseline = measured_hbm_baseline(registry)
    hbm_capacity = (peak or {}).get("hbm_bytes")
    out: List[PruneDecision] = []
    for cand in candidates:
        d = PruneDecision(candidate=cand)
        if cand.kind == "train":
            programs = estimate_train_programs(
                cand.knobs.get("train_buckets"),
                bool(cand.knobs.get("dedup_anchors")),
                batch_size,
                max_length,
            )
            d.estimated_programs = programs
            if programs > max_programs:
                d.refuse("program_count_blowup", programs, max_programs)
        if hbm_capacity is None:
            d.notes.append("hbm_check_skipped:no_peak_spec")
        elif baseline is None:
            d.notes.append("hbm_check_skipped:no_measured_footprint")
        else:
            ratio = _padded_token_ratio(cand, max_length, batch_size, max_batch)
            projected = baseline["hbm_bytes"] * ratio
            d.estimated_hbm_bytes = projected
            limit = hbm_fraction * float(hbm_capacity)
            if projected > limit:
                d.refuse("hbm_overflow", projected, limit)
        out.append(d)
    return out


def survivors(decisions: Sequence[PruneDecision]) -> List[Candidate]:
    return [d.candidate for d in decisions if d.feasible]
