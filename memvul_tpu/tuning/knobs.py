"""The autotuner's candidate space (docs/tuning.md, "Knob space").

A candidate is one named, JSON-serializable knob assignment for either
the training collation path or the serving dispatch path.  The grids
here are deliberately small — the tuner's cost model is "prune
analytically, then PAY for a microbench per survivor", so every axis
earns its place:

* training: ``train_buckets`` (pad-to-max / pow2 grid / an explicit
  coarse grid), ``dedup_anchors``, ``prefetch_depth`` — the three
  collation knobs PR 5 measured as the train-step envelope;
* serving: per dispatch impl — micro-batch cap (``max_batch``) and
  coalescing window for the bucketed path, ``token_budget`` +
  ``max_rows_per_pack`` for the packed (ragged/continuous) paths.

The optimal point shifts per device generation (arXiv 2104.08335,
2605.25645), which is why candidates are swept per device class rather
than hand-set once.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence


@dataclasses.dataclass(frozen=True)
class Candidate:
    """One knob assignment.  ``name`` is the stable label every prune /
    bench / parity record carries; ``knobs`` maps directly onto
    ``TrainerConfig`` fields (kind="train") or the serving section /
    ``SiamesePredictor`` arguments (kind="serve")."""

    kind: str  # "train" | "serve"
    name: str
    knobs: Dict[str, Any]

    def to_json(self) -> Dict[str, Any]:
        return {"kind": self.kind, "name": self.name, "knobs": dict(self.knobs)}


def _bucket_label(buckets) -> str:
    if buckets is None:
        return "none"
    if isinstance(buckets, str):
        return buckets
    return "x".join(str(b) for b in buckets)


def train_space(
    max_length: int = 512,
    batch_size: int = 32,
    bucket_grids: Optional[Sequence[Any]] = None,
    dedup_options: Sequence[bool] = (True, False),
    prefetch_depths: Sequence[int] = (2, 8, 16),
) -> List[Candidate]:
    """The training-collation candidate grid.

    The default bucket axis is pad-to-max (``None`` — the pre-PR-5
    baseline, kept so the tuner can *prove* the grid earns its compile
    bill on this device class), the shipped ``"pow2"`` derivation, and
    one coarse explicit grid (quartile boundaries).  ``dedup_anchors``
    only changes behavior under a bucketed collation, so the pad-to-max
    row is emitted once.
    """
    if bucket_grids is None:
        quartiles = sorted({
            max(8, max_length // 4), max(8, max_length // 2), max_length
        })
        bucket_grids = [None, "pow2", list(quartiles)]
    out: List[Candidate] = []
    seen = set()
    for buckets in bucket_grids:
        for dedup in dedup_options:
            if buckets is None and not dedup:
                continue  # dedup is a no-op under pad-to-max; one row suffices
            for depth in prefetch_depths:
                dedup_eff = bool(dedup) and buckets is not None
                key = (_bucket_label(buckets), dedup_eff, int(depth))
                if key in seen:
                    continue
                seen.add(key)
                out.append(Candidate(
                    kind="train",
                    name=(
                        f"train:buckets={_bucket_label(buckets)},"
                        f"dedup={int(dedup_eff)},prefetch={int(depth)}"
                    ),
                    knobs={
                        "train_buckets": buckets,
                        "dedup_anchors": dedup_eff,
                        "prefetch_depth": int(depth),
                    },
                ))
    return out


def serve_space(
    max_length: int = 512,
    max_batch: int = 16,
    impls: Sequence[str] = ("bucketed", "ragged", "continuous"),
    batch_caps: Optional[Sequence[int]] = None,
    wait_ms_options: Sequence[float] = (2.0, 5.0),
    budget_factors: Sequence[int] = (2, 4, 8),
    rows_factors: Sequence[int] = (1, 2),
) -> List[Candidate]:
    """The serving-dispatch candidate grid, one sub-grid per impl.

    Bucketed dispatch sweeps the micro-batch cap (its batch shape set —
    every cap is a new program family, which is why the analytic pruner
    sees these first) and the coalescing window; the packed impls sweep
    ``token_budget`` (multiples of ``max_length``) and the rows cap.
    The cascade band is NOT swept here — it is score-adjacent and owned
    by :mod:`memvul_tpu.tuning.cascade` behind ``evaluate_cascade``.
    """
    if batch_caps is None:
        batch_caps = sorted({max(1, max_batch // 2), max_batch, 2 * max_batch})
    out: List[Candidate] = []
    for impl in impls:
        if impl == "bucketed":
            for cap in batch_caps:
                for wait in wait_ms_options:
                    out.append(Candidate(
                        kind="serve",
                        name=f"serve:{impl},max_batch={cap},wait_ms={wait:g}",
                        knobs={
                            "score_impl": impl,
                            "max_batch": int(cap),
                            "max_wait_ms": float(wait),
                        },
                    ))
        elif impl in ("ragged", "continuous"):
            for factor in budget_factors:
                for rf in rows_factors:
                    rows = int(max_batch * rf)
                    out.append(Candidate(
                        kind="serve",
                        name=(
                            f"serve:{impl},budget={factor}xL,"
                            f"rows={rows}"
                        ),
                        knobs={
                            "score_impl": impl,
                            "max_batch": int(max_batch),
                            "token_budget": int(factor * max_length),
                            "max_rows_per_pack": rows,
                        },
                    ))
        else:
            raise ValueError(
                f"serve_space: unknown impl {impl!r} "
                "(known: bucketed, ragged, continuous)"
            )
    return out
