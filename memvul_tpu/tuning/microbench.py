"""Seeded in-process microbenches for tune candidates.

One :class:`TuneBench` builds the expensive shared state ONCE — the
synthetic workspace, the model, the initialized parameters, the probe
texts and the anchor set — and then scores candidates with the same
primitives the standalone ``BENCH_MICRO=train_step`` / ``serve``
harnesses use (bench.py), just smaller and callable in-process:

* :meth:`bench_train` — one warmup epoch (compiles) + one timed epoch
  over the identical seeded pair stream per candidate, returning the
  trainer's own epoch metrics (real/padded token throughput);
* :meth:`bench_serve` — a closed-loop client pool over a fixed text
  schedule through a :class:`ScoringService` built with the candidate's
  dispatch knobs, returning rps + latency percentiles + the padding
  ledger from the leg's private telemetry registry;
* :meth:`probe_scores` / :meth:`train_losses` — the parity gate's
  evidence: scores on a fixed probe set, and the per-step loss
  trajectory (``step_loss_log``) for one short deterministic epoch.

Everything is seeded (workspace seed, reader seed, PRNGKey(0)); two
calls with the same knobs produce the same stream, which is what lets
the parity gate demand bitwise equality for layout-only candidates.
"""

from __future__ import annotations

import json
import logging
import os
import queue as _queue
import tempfile
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

logger = logging.getLogger(__name__)

# the fixed probe set size for parity evidence; small because every
# serving candidate pays one probe pass through BOTH layouts
DEFAULT_PROBE = 32


class TuneBench:
    """Shared microbench state + per-candidate runners.

    ``model_size`` follows the bench harness contract: ``"tiny"``
    exercises every code path off-TPU in seconds (the CPU harness
    record), ``"base"`` is the geometry that means something on
    hardware.
    """

    def __init__(
        self,
        *,
        seed: int = 0,
        model_size: str = "tiny",
        seq_len: int = 128,
        batch_size: int = 8,
        grad_accum: int = 1,
        steps_per_epoch: int = 4,
        reports_per_project: int = 48,
        n_requests: int = 96,
        n_clients: int = 4,
        max_batch: int = 8,
        probe_size: int = DEFAULT_PROBE,
        workdir: Optional[str] = None,
    ) -> None:
        import numpy as np
        import jax
        import jax.numpy as jnp

        from ..data.readers import MemoryReader
        from ..data.synthetic import build_workspace
        from ..models import BertConfig, MemoryModel

        self.seed = int(seed)
        self.model_size = model_size
        self.batch_size = int(batch_size)
        self.grad_accum = int(grad_accum)
        self.steps_per_epoch = int(steps_per_epoch)
        self.n_requests = int(n_requests)
        self.n_clients = int(n_clients)
        self.max_batch = int(max_batch)
        self._workdir = workdir or tempfile.mkdtemp(prefix="memvul-tune-")
        self.workspace = build_workspace(
            self._workdir, seed=self.seed, num_projects=8,
            reports_per_project=int(reports_per_project),
            realistic_lengths=True,
        )
        if model_size == "tiny":
            cfg = BertConfig.tiny(
                vocab_size=self.workspace["tokenizer"].vocab_size
            )
            seq_len = min(int(seq_len), cfg.max_position_embeddings)
        else:
            cfg = BertConfig.base(
                vocab_size=max(30522, self.workspace["tokenizer"].vocab_size),
                dtype=jnp.bfloat16,
            )
            if int(seq_len) > cfg.max_position_embeddings:
                cfg = cfg.replace(max_position_embeddings=int(seq_len))
        self.seq_len = int(seq_len)
        self.buckets = tuple(
            b for b in (64, 128, 256, 512) if b <= self.seq_len
        ) or (self.seq_len,)
        self.model = MemoryModel(cfg)
        dummy = {
            "input_ids": np.zeros((2, 8), np.int32),
            "attention_mask": np.ones((2, 8), np.int32),
        }
        self.params = self.model.init(jax.random.PRNGKey(0), dummy, dummy)

        reader = MemoryReader(
            cve_path=self.workspace["paths"]["cve"],
            anchor_path=self.workspace["paths"]["anchors"],
        )
        instances = list(
            reader.read(self.workspace["paths"]["test"], split="test")
        )
        # the labeled golden set: the cascade band chooser and any
        # cross-impl evaluate_gate check score these, metas included
        self.golden_instances: List[Dict[str, Any]] = instances
        texts = [inst["text1"] for inst in instances]
        while len(texts) < max(self.n_requests, probe_size):
            texts = texts + texts
        self.texts: List[str] = texts[: self.n_requests]
        self.probe_texts: List[str] = texts[: int(probe_size)]
        base_anchors = list(self.workspace["anchors"].items())
        self.anchor_instances = [
            {
                "text1": base_anchors[i % len(base_anchors)][1],
                "meta": {
                    "label": f"{base_anchors[i % len(base_anchors)][0]}#{i}",
                    "type": "golden",
                },
            }
            for i in range(33)
        ]

    # -- training ---------------------------------------------------------------

    def _make_trainer(self, knobs: Dict[str, Any],
                      step_loss_log: Optional[str] = None):
        import jax
        import jax.numpy as jnp

        from ..data.readers import MemoryReader
        from ..training.trainer import MemoryTrainer, TrainerConfig

        reader = MemoryReader(
            cve_path=self.workspace["paths"]["cve"],
            anchor_path=self.workspace["paths"]["anchors"],
            sample_neg=0.5, seed=2021,
        )
        cfg_kw = {
            k: knobs[k]
            for k in ("train_buckets", "dedup_anchors", "prefetch_depth")
            if k in knobs
        }
        return MemoryTrainer(
            self.model,
            # fresh buffers per candidate: the jitted step DONATES
            # params/opt-state, so reusing one pytree across candidates
            # would hand the next run already-deleted arrays
            jax.tree_util.tree_map(jnp.array, self.params),
            self.workspace["tokenizer"], reader,
            train_path=self.workspace["paths"]["train"],
            config=TrainerConfig(
                batch_size=self.batch_size, grad_accum=self.grad_accum,
                max_length=self.seq_len,
                steps_per_epoch=self.steps_per_epoch, num_epochs=1,
                warmup_steps=1, serialization_dir=None,
                step_loss_log=step_loss_log,
                **cfg_kw,
            ),
        )

    def bench_train(self, knobs: Dict[str, Any],
                    with_losses: bool = False) -> Dict[str, Any]:
        """Warmup epoch (compiles every stack shape) + one timed epoch
        over the identical epoch-0 stream, per the train_step harness
        contract.  ``with_losses=True`` also returns the WARMUP epoch's
        per-step loss trajectory (the parity gate's training evidence —
        epoch 0 from fresh params, the same stream every candidate
        sees) without paying a third epoch."""
        log_path = self._loss_log_path(knobs) if with_losses else None
        trainer = self._make_trainer(knobs, step_loss_log=log_path)
        trainer.train_epoch()  # warmup: compiles
        m = trainer.train_epoch()  # timed: same epoch-0 stream
        out = {
            "epoch_s": round(m["epoch_seconds"], 4),
            "steps": m["num_steps"],
            "padded_tokens": m["padded_tokens"],
            "real_tokens": m["real_tokens"],
            "padded_tokens_per_s": round(m["tokens_per_sec"], 1),
            "real_tokens_per_s": round(m["real_tokens_per_sec"], 1),
            "compiled_step_shapes": trainer.train_trace_count,
        }
        if log_path is not None:
            # the log holds both epochs; epoch 0 (fresh params, the
            # parity trajectory) is the first num_steps entries
            out["losses"] = self._read_losses(log_path)[: m["num_steps"]]
        return out

    def _loss_log_path(self, knobs: Dict[str, Any]) -> str:
        import hashlib

        digest = hashlib.sha256(
            json.dumps(knobs, sort_keys=True, default=str).encode()
        ).hexdigest()[:12]
        log_path = os.path.join(self._workdir, f"losses-{digest}.jsonl")
        if os.path.exists(log_path):
            os.unlink(log_path)
        return log_path

    @staticmethod
    def _read_losses(log_path: str) -> List[float]:
        with open(log_path) as fh:
            return [json.loads(line)["loss"] for line in fh if line.strip()]

    def train_losses(self, knobs: Dict[str, Any]) -> List[float]:
        """The per-step loss trajectory of ONE deterministic epoch —
        the training side of the parity gate's probe evidence, when the
        caller wants it without a full bench."""
        log_path = self._loss_log_path(knobs)
        trainer = self._make_trainer(knobs, step_loss_log=log_path)
        trainer.train_epoch()
        return self._read_losses(log_path)

    # -- serving ----------------------------------------------------------------

    def build_predictor(self, knobs: Dict[str, Any], *,
                        encoder_precision: str = "fp32"):
        """A :class:`SiamesePredictor` wired for one serve candidate,
        anchors encoded (so it is immediately scoreable)."""
        from ..evaluate.predict_memory import SiamesePredictor

        impl = knobs.get("score_impl", "bucketed")
        kwargs: Dict[str, Any] = {}
        if impl in ("ragged", "continuous"):
            kwargs = dict(
                score_impl=impl,
                token_budget=int(
                    knobs.get("token_budget") or 4 * self.seq_len
                ),
                max_rows_per_pack=int(
                    knobs.get("max_rows_per_pack")
                    or knobs.get("max_batch", self.max_batch)
                ),
            )
        elif impl == "cascade":
            kwargs = dict(
                score_impl="cascade", encoder_precision="int8",
                cascade_low=float(knobs.get("cascade_low", 0.3)),
                cascade_high=float(knobs.get("cascade_high", 0.7)),
            )
        if encoder_precision != "fp32" and "encoder_precision" not in kwargs:
            kwargs["encoder_precision"] = encoder_precision
        predictor = SiamesePredictor(
            self.model, self.params, self.workspace["tokenizer"],
            batch_size=int(knobs.get("max_batch", self.max_batch)),
            max_length=self.seq_len, buckets=self.buckets,
            **kwargs,
        )
        predictor.encode_anchors(self.anchor_instances)
        return predictor

    def bench_serve(self, knobs: Dict[str, Any]) -> Dict[str, Any]:
        """One closed-loop leg (the serve harness's ``_drive_leg``
        shape): ``n_clients`` threads drain a shared queue of the fixed
        text schedule through an :class:`InprocessClient`, deadlines
        off.  Returns rps, latency percentiles, and the padding
        ledger from the leg's own registry."""
        import numpy as np

        from ..serving import InprocessClient, ScoringService, ServiceConfig
        from ..telemetry.registry import TelemetryRegistry

        registry = TelemetryRegistry(enabled=True)
        predictor = self.build_predictor(knobs)
        max_batch = int(knobs.get("max_batch", self.max_batch))
        service = ScoringService(
            predictor,
            config=ServiceConfig(
                max_batch=max_batch,
                max_wait_ms=float(knobs.get("max_wait_ms", 5.0)),
                max_queue=max(256, 2 * self.n_clients * max_batch),
                default_deadline_ms=0.0,
            ),
            registry=registry,
        )
        client = InprocessClient(service)
        work: "_queue.SimpleQueue" = _queue.SimpleQueue()
        for text in self.texts:
            work.put(text)
        latencies: List[float] = []
        lat_lock = threading.Lock()
        errors = [0]

        def _client_loop() -> None:
            own: List[float] = []
            while True:
                try:
                    text = work.get_nowait()
                except _queue.Empty:
                    break
                t0 = time.perf_counter()
                resp = client.score(text, deadline_ms=0)
                own.append(time.perf_counter() - t0)
                if resp["status"] != "ok":
                    errors[0] += 1
            with lat_lock:
                latencies.extend(own)

        client.score(self.texts[0], deadline_ms=0)  # warmup trickle
        threads = [
            threading.Thread(target=_client_loop, daemon=True)
            for _ in range(self.n_clients)
        ]
        start = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        elapsed = time.perf_counter() - start
        service.drain()
        counters = registry.snapshot()["counters"]
        lat_ms = np.sort(np.asarray(latencies)) * 1e3
        pct = (
            lambda q: round(float(np.percentile(lat_ms, q)), 3)
            if len(lat_ms) else None
        )
        return {
            "requests_per_sec": round(len(self.texts) / elapsed, 1),
            "latency_ms": {"p50": pct(50), "p95": pct(95), "p99": pct(99)},
            "errors": errors[0],
            "real_tokens": int(counters.get("serve.tokens_real", 0)),
            "padded_tokens": int(counters.get("serve.tokens_padded", 0)),
        }

    def probe_scores(self, knobs: Dict[str, Any], *,
                     impl: Optional[str] = None):
        """Scores of the fixed probe set through one candidate's
        predictor — the serving side of the parity gate's evidence.
        ``impl`` passes through to ``score_texts`` (``"int8"`` is the
        cascade band chooser's distribution input)."""
        predictor = self.build_predictor(
            knobs,
            encoder_precision="int8" if impl in ("int8", "cascade") else "fp32",
        )
        return predictor.score_texts(self.probe_texts, impl=impl)
