"""Multi-host (multi-process) initialization over DCN.

The reference's distributed backend is torch.distributed/NCCL with
explicit all_reduce/barrier calls (reference: custom_trainer.py:254-259,
379-396) — coded but never enabled by any shipped config.  The TPU
equivalent needs no hand-written collectives at all: after
``jax.distributed.initialize``, ``jax.devices()`` spans every host's
chips, a mesh built over them shards arrays across ICI within a slice
and DCN across slices, and XLA inserts all communication.

Typical multi-host launch (same program on every host)::

    from memvul_tpu.parallel import multihost, create_mesh
    multihost.initialize()                 # env-driven on TPU pods
    mesh = create_mesh({"data": -1})       # all global devices
    ...
    if multihost.is_primary():             # one writer for checkpoints/logs
        save(...)
"""

from __future__ import annotations

import logging
import os
from typing import Optional

import jax

logger = logging.getLogger(__name__)

_initialized = False

# env markers that signal a multi-process launch; checked WITHOUT touching
# jax (any jax.devices()/process_count() call would initialize the XLA
# backend, after which jax.distributed.initialize refuses to run)
_ENV_MARKERS = (
    "MEMVUL_MULTIHOST",
    "JAX_COORDINATOR_ADDRESS",
    "COORDINATOR_ADDRESS",
    "MEGASCALE_COORDINATOR_ADDRESS",
)


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
    require: bool = False,
) -> bool:
    """Join the multi-process runtime.  MUST run before any jax
    computation (backend initialization closes the window).

    The decision to join is made from explicit arguments or environment
    markers only — never by probing jax, which would itself initialize
    the backend.  On TPU pods, set ``MEMVUL_MULTIHOST=1`` (or pass
    ``require=True``) and the TPU runtime supplies coordinator/process
    details; elsewhere pass them explicitly.  Returns False when nothing
    signals a multi-process launch.
    """
    global _initialized
    if _initialized:
        return True
    explicit = (
        require
        or coordinator_address is not None
        or num_processes is not None
    )
    env_opt_in = any(os.environ.get(k) for k in _ENV_MARKERS)
    if not (explicit or env_opt_in):
        logger.debug("no multi-process markers — skipping distributed init")
        return False
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    logger.info(
        "multihost: process %d/%d, %d local + %d global devices",
        jax.process_index(), jax.process_count(),
        jax.local_device_count(), jax.device_count(),
    )
    return True


def is_primary() -> bool:
    """True on the process that should write checkpoints/metrics."""
    return jax.process_index() == 0


def process_count() -> int:
    return jax.process_count()


def local_batch_slice(global_batch: int) -> slice:
    """This host's contiguous slice of a globally sharded batch — for
    host-side input pipelines that shard by process (each host feeds its
    own chips; the mesh handles the rest)."""
    n = jax.process_count()
    if global_batch % n != 0:
        raise ValueError(f"global batch {global_batch} not divisible by {n} hosts")
    per = global_batch // n
    i = jax.process_index()
    return slice(i * per, (i + 1) * per)
