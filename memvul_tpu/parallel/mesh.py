"""Device mesh + sharding helpers — the framework's distributed backbone.

Replaces the reference's torch.distributed/NCCL machinery (DDP wrap,
all_reduce done-flags, barriers — reference: custom_trainer.py:254-259,
379-396) with the SPMD model: one ``jax.sharding.Mesh`` over the
available devices, ``NamedSharding`` annotations, and XLA-inserted
collectives over ICI/DCN.  Under SPMD with fixed-shape sharded batches
the reference's ragged-epoch done-flag dance disappears entirely.

Axes convention:
  ``data``   batch dimension (primary scaling axis; ICI all-reduce of grads)
  ``model``  optional tensor-parallel axis for large encoders
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

DATA_AXIS = "data"
MODEL_AXIS = "model"


def create_mesh(
    axes: Optional[Dict[str, int]] = None,
    devices: Optional[Sequence[jax.Device]] = None,
) -> Mesh:
    """Build a mesh; default is 1-D data parallelism over all devices.

    ``axes`` maps axis name → size; sizes must multiply to the device
    count (a -1 size is inferred).
    """
    devices = list(devices if devices is not None else jax.devices())
    axes = dict(axes or {DATA_AXIS: len(devices)})
    sizes = list(axes.values())
    if -1 in sizes:
        known = int(np.prod([s for s in sizes if s != -1]))
        sizes[sizes.index(-1)] = len(devices) // known
        axes = dict(zip(axes.keys(), sizes))
    total = int(np.prod(sizes))
    if total != len(devices):
        raise ValueError(
            f"mesh axes {axes} need {total} devices, have {len(devices)}"
        )
    device_array = np.asarray(devices).reshape(sizes)
    return Mesh(device_array, tuple(axes.keys()))


def batch_spec(mesh: Mesh) -> P:
    """Shard the leading (batch) dim over the data axis, if present."""
    return P(DATA_AXIS) if DATA_AXIS in mesh.axis_names else P()


def shard_batch(batch, mesh: Mesh, batch_axis: int = 0):
    """Device-put a pytree of arrays with dimension ``batch_axis`` sharded
    over ``data`` when the mesh has that axis; arrays too small for the
    axis, scalars, and non-array leaves (metadata) pass through
    replicated/untouched.  ``batch_axis=1`` shards a [K, B, ...] microbatch
    stack on its B dimension."""
    has_data_axis = DATA_AXIS in mesh.axis_names

    def put(x):
        if isinstance(x, (np.ndarray, jax.Array)):
            if has_data_axis and x.ndim > batch_axis:
                axes = [None] * x.ndim
                axes[batch_axis] = DATA_AXIS
                spec = P(*axes)
            else:
                spec = P()
            return jax.device_put(x, NamedSharding(mesh, spec))
        return x

    return jax.tree_util.tree_map(put, batch)


def replicate(tree, mesh: Mesh):
    """Replicate a pytree (params, anchor bank) across the mesh."""
    sharding = NamedSharding(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sharding), tree)
