"""Tensor-parallel parameter sharding rules for the BERT encoder.

The reference has no tensor parallelism (SURVEY §2.5) — this is the TPU
build's scaling axis for larger encoders: attention heads and the FFN
hidden dim are split over the ``model`` mesh axis (the Megatron layout),
so each device holds a slice of every layer and XLA inserts the
all-reduces after the attention-output and FFN-output matmuls.  Params
not matched by a rule are replicated (embeddings, LayerNorms, poolers,
classification heads — all small).

Rules are path-suffix → trailing-dim partition specs, padded with
``None`` on the left for any extra leading dims, which makes the same
rules correct for both the per-layer layout (``layer_0/...``) and the
scanned layout (stacked leaves with a leading [L] dim).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from .mesh import MODEL_AXIS

# (path substring, spec for the *trailing* dims). Checked in order; first
# match wins — keep more specific patterns first.
DEFAULT_TP_RULES: Tuple[Tuple[str, Tuple[Optional[str], ...]], ...] = (
    # attention projections: DenseGeneral [H, heads, head_dim] — split heads
    ("attention/query/kernel", (None, MODEL_AXIS, None)),
    ("attention/key/kernel", (None, MODEL_AXIS, None)),
    ("attention/value/kernel", (None, MODEL_AXIS, None)),
    ("attention/query/bias", (MODEL_AXIS, None)),
    ("attention/key/bias", (MODEL_AXIS, None)),
    ("attention/value/bias", (MODEL_AXIS, None)),
    # attention output: DenseGeneral [heads, head_dim, H] — split heads
    # (row-parallel: XLA all-reduces the partial sums)
    ("attention/output/kernel", (MODEL_AXIS, None, None)),
    # FFN up-projection [H, I] — split the hidden dim (column-parallel)
    ("intermediate/kernel", (None, MODEL_AXIS)),
    ("intermediate/bias", (MODEL_AXIS,)),
    # FFN down-projection [I, H] — split the hidden dim (row-parallel).
    # attention/output matched above, so this only hits the FFN output.
    ("output/kernel", (MODEL_AXIS, None)),
)


def _path_str(path) -> str:
    return "/".join(str(getattr(k, "key", k)) for k in path)


def tp_spec_for(
    path_str: str,
    ndim: int,
    rules: Sequence[Tuple[str, Tuple[Optional[str], ...]]] = DEFAULT_TP_RULES,
) -> P:
    """Partition spec for one param leaf (replicated when no rule hits)."""
    for needle, trailing in rules:
        if needle in path_str:
            if len(trailing) > ndim:
                # e.g. a bias rule written for the unscanned layout hitting
                # a lower-rank leaf — replicate rather than mis-shard
                return P()
            pad = ndim - len(trailing)
            return P(*((None,) * pad + tuple(trailing)))
    return P()


def param_specs(params, rules=DEFAULT_TP_RULES):
    """PartitionSpec pytree matching ``params``."""
    return jax.tree_util.tree_map_with_path(
        lambda path, leaf: tp_spec_for(_path_str(path), leaf.ndim, rules), params
    )


def shard_params(params, mesh: Mesh, rules=DEFAULT_TP_RULES):
    """Place params on the mesh with tensor-parallel shardings (replicated
    over every axis except ``model``).  Falls back to full replication
    when the mesh has no ``model`` axis."""
    if MODEL_AXIS not in mesh.axis_names:
        from .mesh import replicate

        return replicate(params, mesh)
    specs = param_specs(params, rules)
    return jax.tree_util.tree_map(
        lambda leaf, spec: jax.device_put(leaf, NamedSharding(mesh, spec)),
        params,
        specs,
    )


def validate_divisibility(params, mesh: Mesh, rules=DEFAULT_TP_RULES) -> List[str]:
    """Paths whose sharded dim is not divisible by the model-axis size —
    useful as a pre-flight check before ``shard_params``."""
    if MODEL_AXIS not in mesh.axis_names:
        return []
    size = mesh.shape[MODEL_AXIS]
    bad: List[str] = []

    def check(path, leaf):
        spec = tp_spec_for(_path_str(path), leaf.ndim, rules)
        for dim, axis in enumerate(spec):
            if axis == MODEL_AXIS and leaf.shape[dim] % size != 0:
                bad.append(f"{_path_str(path)}[{dim}]={leaf.shape[dim]} % {size}")
        return leaf

    jax.tree_util.tree_map_with_path(check, params)
    return bad
