from .mesh import create_mesh, shard_batch, replicate  # noqa: F401
