from .mesh import create_mesh, shard_batch, replicate  # noqa: F401
from . import multihost  # noqa: F401
from .ring import (  # noqa: F401
    encode_sequence_parallel,
    make_ring_attention,
    ring_attention,
)
