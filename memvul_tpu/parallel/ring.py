"""Sequence-parallel ring attention over a device mesh axis.

The reference handles long inputs by *folding* (segments encoded
independently, custom_PTM_embedder.py:244-381) — no true long-context
attention exists there.  This module supplies the TPU-native stretch
capability: the sequence axis is sharded across devices, each device
holds a query block plus one key/value block, and the key/value blocks
rotate around the ring via ``lax.ppermute`` while a streaming
(online-softmax) accumulator builds the exact full-sequence attention
output.  Communication rides the ICI ring; compute on the current block
overlaps the permute of the next.

Numerics: block accumulation runs in float32 with the standard
running-max/denominator rescaling, so the result matches single-device
softmax attention to bf16/fp32 tolerance regardless of ring order.

Usage:
* :func:`ring_attention` — the per-shard op, call it inside
  ``shard_map`` with a bound sequence axis name;
* :func:`make_ring_attention` — binds a mesh + axis and returns a
  drop-in ``(q, k, v, mask) -> out`` callable operating on globally
  sharded arrays.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental.shard_map import shard_map
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P


def ring_attention(
    query: jax.Array,
    key: jax.Array,
    value: jax.Array,
    key_mask: Optional[jax.Array] = None,
    key_bias: Optional[jax.Array] = None,
    axis_name: str = "seq",
    axis_size: Optional[int] = None,
) -> jax.Array:
    """Exact attention with the sequence axis sharded over ``axis_name``.

    Per-shard shapes: query/key/value [B, T_local, H, Dh]; ``key_mask``
    [B, T_local] marks real key positions (1) vs padding (0) —
    alternatively pass ``key_bias``, an additive bias broadcastable to
    [B, 1, 1, T_local] (the encoder's ``mask_to_bias`` output, already
    sharded on its key dim).  Returns the local query block's attention
    output [B, T_local, H, Dh] in the dtype of ``query``.  Must run
    inside ``shard_map`` with ``axis_name`` bound.
    """
    if axis_size is None:
        axis_size = jax.lax.psum(1, axis_name)
    depth = query.shape[-1]
    scale = 1.0 / jnp.sqrt(jnp.asarray(depth, jnp.float32))
    neg = jnp.finfo(jnp.float32).min

    b, t_q, h, _ = query.shape
    if key_bias is not None:
        # only key-position biases can ride the ring: a bias with a real
        # query or head dim cannot travel with the rotating key block
        for dim in (-3, -2):  # the head and query dims must broadcast
            if key_bias.ndim >= -dim and key_bias.shape[dim] != 1:
                raise ValueError(
                    "ring attention supports key-only bias (broadcastable "
                    f"to [B, 1, 1, T_k]); got shape {key_bias.shape}"
                )
        key_bias = jnp.broadcast_to(
            key_bias.astype(jnp.float32), (b, 1, 1, key.shape[1])
        )
    else:
        if key_mask is None:
            key_mask = jnp.ones(key.shape[:2], jnp.int32)
        key_bias = jnp.where(key_mask[:, None, None, :] > 0, 0.0, neg).astype(
            jnp.float32
        )  # [B, 1, 1, T_k]

    acc = jnp.zeros((b, t_q, h, depth), jnp.float32)
    row_max = jnp.full((b, h, t_q), neg, jnp.float32)
    denom = jnp.zeros((b, h, t_q), jnp.float32)
    perm = [(i, (i + 1) % axis_size) for i in range(axis_size)]

    def accumulate(acc, row_max, denom, k, v, kb):
        scores = (
            jnp.einsum("bqhd,bkhd->bhqk", query, k).astype(jnp.float32) * scale
            + kb
        )
        block_max = scores.max(axis=-1)
        new_max = jnp.maximum(row_max, block_max)
        correction = jnp.exp(row_max - new_max)
        p = jnp.exp(scores - new_max[..., None])
        denom = denom * correction + p.sum(axis=-1)
        acc = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
            "bhqk,bkhd->bqhd", p, v.astype(jnp.float32)
        )
        return acc, new_max, denom

    def step(carry, _):
        acc, row_max, denom, k, v, kb = carry
        acc, row_max, denom = accumulate(acc, row_max, denom, k, v, kb)
        # rotate the key/value block (and its mask bias) to the next device
        k, v, kb = (
            jax.lax.ppermute(x, axis_name, perm) for x in (k, v, kb)
        )
        return (acc, row_max, denom, k, v, kb), None

    # scan covers axis_size-1 compute+rotate rounds; the final block is
    # consumed without the (otherwise wasted) closing rotation
    (acc, row_max, denom, key, value, key_bias), _ = jax.lax.scan(
        step,
        (acc, row_max, denom, key, value, key_bias),
        None,
        length=axis_size - 1,
    )
    acc, row_max, denom = accumulate(acc, row_max, denom, key, value, key_bias)
    out = acc / jnp.maximum(denom.transpose(0, 2, 1)[..., None], 1e-30)
    # a query row whose keys are masked in EVERY block never escapes the
    # mask floor (row_max stays ~finfo.min); its softmax is a uniform
    # average over padding — return zeros instead of that artifact (real
    # scores are bounded far above neg/2, so the test is exact)
    alive = row_max > neg * 0.5  # [B, H, T_q]
    out = jnp.where(alive.transpose(0, 2, 1)[..., None], out, 0.0)
    return out.astype(query.dtype)


def make_ring_attention(mesh: Mesh, axis_name: str = "seq"):
    """Bind ``ring_attention`` to a mesh: returns ``fn(q, k, v, mask)``
    over *global* arrays with the sequence dim sharded on ``axis_name``
    (batch/heads replicated across that axis)."""
    axis_size = mesh.shape[axis_name]
    spec_qkv = P(None, axis_name, None, None)
    spec_mask = P(None, axis_name)

    inner = functools.partial(
        ring_attention, axis_name=axis_name, axis_size=axis_size
    )
    return shard_map(
        inner,
        mesh=mesh,
        in_specs=(spec_qkv, spec_qkv, spec_qkv, spec_mask),
        out_specs=spec_qkv,
        check_rep=False,
    )


def encode_sequence_parallel(
    model,
    params,
    input_ids: jax.Array,
    attention_mask: jax.Array,
    mesh: Mesh,
    axis_name: str = "seq",
) -> jax.Array:
    """Run a :class:`BertEncoder` built with ``attention_impl="ring"``
    with its *sequence* axis sharded over ``axis_name``.

    Everything except attention is position-wise, so each device encodes
    its sequence slice locally (with correct global position ids) and only
    the attention step communicates — key/value blocks ride the ICI ring.
    Inference path (``deterministic=True``); returns the full [B, T, H]
    hidden states, sequence-sharded on ``axis_name``.
    """
    if model.config.attention_impl != "ring":
        raise ValueError(
            "sequence-parallel encoding needs attention_impl='ring' "
            f"(got {model.config.attention_impl!r})"
        )
    b, t = input_ids.shape
    n = mesh.shape[axis_name]
    if t % n != 0:
        raise ValueError(f"sequence length {t} not divisible by {axis_name}={n}")
    if t > model.config.max_position_embeddings:
        # the encoder's own guard only sees the local shard length inside
        # shard_map; check the global length here or OOB position-embedding
        # gathers would silently clamp
        raise ValueError(
            f"sequence length {t} exceeds max_position_embeddings="
            f"{model.config.max_position_embeddings}"
        )
    position_ids = jnp.broadcast_to(jnp.arange(t)[None, :], (b, t))

    def local(params, ids, mask, pos):
        return model.apply(
            params, ids, mask, position_ids=pos, deterministic=True
        )

    seq2 = P(None, axis_name)
    fn = shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), seq2, seq2, seq2),
        out_specs=P(None, axis_name, None),
        check_rep=False,
    )
    return fn(params, input_ids, attention_mask, position_ids)
