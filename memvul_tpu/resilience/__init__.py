"""Fault-tolerant execution layer.

Long-running jobs here are measured in hours (a 1.2M-report scoring
pass) or days (a training run on a preemptible pod): a SIGTERM, a bad
corpus record, or a transiently wedged backend must cost seconds of
rework, not the whole job.  This package holds the shared machinery the
training and scoring paths build their recovery on:

* :mod:`faults`  — deterministic, env-driven fault injection (named
  points, chosen trigger counts) so chaos tests drive the REAL recovery
  code paths instead of mocks;
* :mod:`retry`   — the one transient-failure classification + backoff
  policy (generalized from the bench supervisor's);
* :mod:`journal` — append-only progress journal + dead-letter
  quarantine for restartable corpus scoring;
* :mod:`io`      — atomic (tmp + ``os.replace``) small-file writes for
  markers, manifests and metadata sidecars.

See docs/fault_tolerance.md for the operator-facing contract.
"""

from . import faults  # noqa: F401
from .io import atomic_write_text  # noqa: F401
from .journal import DeadLetter, ScoreJournal  # noqa: F401
from .retry import RETRYABLE_MARKERS, RetryPolicy, exception_text  # noqa: F401
