"""Shared transient-failure classification + retry/backoff policy.

Generalized from the bench supervisor's private ``_RETRYABLE_MARKERS``
and backoff loop (``memvul_tpu/bench.py:_supervise``) so the bench, the
corpus-scoring path, and any future long-running job agree on what
"transient" means: a backend that answers ``UNAVAILABLE`` to the bench
is the same backend that will throw it mid-stream at batch 900k of a
scoring run, and both must burn a retry rather than the whole job.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Callable, Optional, Sequence, TypeVar

logger = logging.getLogger(__name__)

T = TypeVar("T")

# Substrings marking a transient backend failure worth retrying (the
# round-2 bench capture died with the first one).  A watchdog
# phase-timeout is retryable too: a phase that stops making progress
# mid-run is the silently-wedged-backend signature, same as a hung
# first device op.
RETRYABLE_MARKERS = (
    "UNAVAILABLE",
    "Unable to initialize backend",
    "DEADLINE_EXCEEDED",
    "ABORTED",
    "Socket closed",
    "failed to connect",
    "watchdog: phase",
)


def exception_text(exc: BaseException) -> str:
    """The string the markers are matched against for an in-process
    exception — type name + message, mirroring what a child process
    would have printed to stderr."""
    return f"{type(exc).__name__}: {exc}"


@dataclasses.dataclass
class RetryPolicy:
    """Attempts + backoff + the shared transient classification.

    ``delay(attempt)`` reproduces the bench supervisor's schedule
    (``backoff * attempt`` seconds after the attempt-th failure), so
    moving the supervisor onto this policy is behavior-preserving.
    ``exponential=True`` switches to ``backoff * 2**(attempt-1)`` — the
    shard supervisor's schedule (docs/full_corpus.md), where a flapping
    worker must back off hard instead of hammering a sick host.
    """

    attempts: int = 3
    backoff: float = 2.0
    markers: Sequence[str] = RETRYABLE_MARKERS
    sleep: Callable[[float], None] = time.sleep
    exponential: bool = False

    def is_transient(self, text: str) -> bool:
        return any(m in text for m in self.markers)

    def delay(self, attempt: int) -> float:
        if self.exponential:
            return self.backoff * (2 ** (max(1, attempt) - 1))
        return self.backoff * attempt

    def call(
        self,
        fn: Callable[[], T],
        description: str = "operation",
        on_retry: Optional[Callable[[BaseException, int], None]] = None,
    ) -> T:
        """Run ``fn`` with up to ``attempts`` tries.  Only exceptions
        whose text matches a transient marker are retried; anything else
        (a genuine bug) propagates immediately without burning retries —
        the same fail-fast contract as the bench supervisor."""
        last: Optional[BaseException] = None
        for attempt in range(1, max(1, self.attempts) + 1):
            try:
                return fn()
            except BaseException as e:
                if not self.is_transient(exception_text(e)):
                    raise
                last = e
                if attempt >= self.attempts:
                    break
                # lazy import — resilience counts INTO telemetry, never
                # the other way (see docs/observability.md)
                from ..telemetry import get_registry

                get_registry().counter("resilience.retries").inc()
                if on_retry is not None:
                    on_retry(e, attempt)
                logger.warning(
                    "%s failed transiently (%s); retry %d/%d in %.0fs",
                    description, exception_text(e)[:200],
                    attempt, self.attempts - 1, self.delay(attempt),
                )
                self.sleep(self.delay(attempt))
        assert last is not None
        raise last
