"""Atomic small-file writes.

A bare ``Path.write_text`` killed mid-write leaves a torn file — half a
JSON object where a resume path expects metadata.  Everything that must
survive a kill (metrics sidecars, checksum manifests, preemption
markers) goes through :func:`atomic_write_text`: write a tmp file in
the same directory, then ``os.replace`` it into place.  The rename is
atomic on POSIX, so readers only ever see the old content or the new —
the same commit pattern orbax uses for whole checkpoint directories.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Union

from . import faults


def atomic_write_text(path: Union[str, Path], text: str) -> Path:
    """Write ``text`` to ``path`` via tmp-file + ``os.replace``.

    The ``ckpt.write`` fault point sits in the torn-write window (tmp
    written, not yet renamed) so chaos tests can prove a failure there
    leaves the previous file intact; an injected exception also cleans
    its own tmp file (a hard kill may leave tmp litter, which is inert —
    nothing ever reads ``*.tmp.<pid>`` files)."""
    path = Path(path)
    tmp = path.with_name(f"{path.name}.tmp.{os.getpid()}")
    try:
        # this IS the committed helper: the tmp write precedes the
        # atomic os.replace commit below
        tmp.write_text(text)  # lint: disable=MV103
        faults.fault_point("ckpt.write")
        os.replace(tmp, path)
    finally:
        if tmp.exists():  # a fault/crash between write and replace
            try:
                tmp.unlink()
            except OSError:
                pass
    return path
