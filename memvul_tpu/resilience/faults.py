"""Deterministic fault injection for chaos tests.

Production code is salted with **named injection points** — a call to
:func:`fault_point` at the spot where the real world can hurt it:

=================  ==========================================================
point              fires
=================  ==========================================================
``data.read``      once per raw corpus record, before it is parsed
``ckpt.write``     inside the atomic-write helper, after the tmp file is
                   written but before ``os.replace`` commits it (the torn-
                   write window)
``score.batch``    once per scoring batch, at dispatch
``serve.batch``    once per serving micro-batch, at dispatch (inside the
                   service's RetryPolicy window, serving/service.py)
``replica.kill``   once per request routed to a serving replica, on its
                   submit path (serving/replica.py) — firing it
                   hard-kills that replica with SIGKILL semantics
                   (nothing resolves, the router must sweep + re-route);
                   ``replica.kill.replica-<i>`` targets one member
``bank.shadow``    once per shadow-scored sample batch, inside the shadow
                   worker thread (bankops/shadow.py) — a firing lands in
                   ``bank.shadow_errors`` and must never touch the active
                   serving path (clients cannot observe it)
``step.N``         at the start of optimizer step ``N`` (global step index)
``kernel.lower``   when the fused Pallas anchor-match kernel is selected,
                   before it is traced (simulates a Mosaic lowering failure)
``shard.kill``     once per corpus row a shard worker yields
                   (distributed/worker.py) — arm with ``sigkill`` to die
                   like an OOM-killed host, mid-span, no handler;
                   ``shard.kill.shard-<i>`` targets one shard
``shard.stall``    same site — arm with a ``raise`` action and the worker
                   wedges (alive, no progress) so the coordinator's
                   heartbeat-age stall detector is what must catch it;
                   ``shard.stall.shard-<i>`` targets one shard
``merge.verify``   at merge-phase entry, before the exactly-once
                   verification pass (distributed/coordinator.py)
``host.kill``      once per request routed to a fleet host, on its
                   submit path (serving/fleet.py) — firing it
                   hard-kills that host with SIGKILL semantics
                   (nothing resolves; the balancer must sweep +
                   re-route); ``host.kill.host-<i>`` targets one host
``host.stall``     same site — the host wedges (alive, accepting, no
                   progress: submitted futures park unresolved and the
                   heartbeat freezes) so the balancer's heartbeat-age
                   stall detector is what must catch it;
                   ``host.stall.host-<i>`` targets one host
``scaler.spawn``   once per autoscaler scale-up, before the replica
                   factory runs (serving/autoscaler.py) — a firing is
                   a failed spawn the scaler must retry through its
                   RetryPolicy and then refuse machine-readably
``incident.dump``  inside the flight recorder's worker thread, before a
                   bundle is written (serving/incident.py) — a firing
                   lands in ``incident.dump_errors`` and must never
                   block or delay request resolution (the trigger side
                   is a non-blocking bounded-queue put)
``cache.lookup``   once per admission-cache probe, before the LRU map is
                   read (serving/admission_cache.py) — a firing degrades
                   that lookup to a miss (one ``cache.errors``): a broken
                   cache costs a device call, never a request
``bank.resolve``   once per submitted request, at tenant→bank resolution
                   (serving/service.py) — a firing errors that ONE
                   request (``serve.errors``; the exact-counter
                   invariant keeps summing) and touches no other tenant
=================  ==========================================================

With no configuration every point is a near-zero-cost no-op.  Arming is
via the ``MEMVUL_FAULTS`` environment variable (read once, at the first
``fault_point`` call) or programmatically via :func:`configure`:

    MEMVUL_FAULTS="score.batch@3=raise:RuntimeError:UNAVAILABLE injected"
    MEMVUL_FAULTS="step.4=sigterm;data.read@2=raise:ValueError:bad record"

Grammar: ``;``-separated clauses, each ``point[@n]=action`` —

* ``@n``: the 1-based hit count at which the fault fires (default 1);
* ``raise[:ExcName[:message]]``: raise a builtin exception (default
  ``RuntimeError("injected fault")``);
* ``sigterm`` / ``sigint``: deliver that signal to the current process
  (``os.kill`` — the delivery path is identical to an external kill, so
  the handler under test is the production handler);
* ``sigkill``: SIGKILL the current process — no handler runs, no
  cleanup happens, exactly the OOM-killer / preemption-without-notice
  failure the journal-resume paths must survive.

Each clause fires exactly **once** and then disarms, so a retry loop
that survives its injected failure proceeds normally — the property the
transient-failure tests depend on.
"""

from __future__ import annotations

import builtins
import dataclasses
import os
import signal
import threading
from typing import Dict, List, Optional

_ENV_VAR = "MEMVUL_FAULTS"

# Machine-readable registry of the injection points in the table above.
# The static-analysis engine (docs/static_analysis.md, checker MV401)
# reconciles every ``fault_point(...)`` call site and every point named
# in a test/doc MEMVUL_FAULTS spec against this set — a typo'd chaos
# spec otherwise arms nothing and silently tests nothing.  Dynamic
# families (``step.<n>``, ``replica.kill.<name>``) register their
# prefix in REGISTERED_POINT_PREFIXES.
REGISTERED_POINTS = frozenset({
    "data.read",
    "ckpt.write",
    "score.batch",
    "serve.batch",
    "serve.cascade",
    "replica.kill",
    "bank.shadow",
    "kernel.lower",
    "shard.kill",
    "shard.stall",
    "merge.verify",
    "host.kill",
    "host.stall",
    "scaler.spawn",
    "incident.dump",
    "cache.lookup",
    "bank.resolve",
})
REGISTERED_POINT_PREFIXES = (
    "step.", "replica.kill.", "shard.kill.", "shard.stall.",
    "host.kill.", "host.stall.",
)

_lock = threading.Lock()
_faults: Dict[str, List["_Fault"]] = {}
_armed = False  # fast-path gate: fault_point returns immediately when False
_env_loaded = False


@dataclasses.dataclass
class _Fault:
    point: str
    trigger: int = 1  # fire at the trigger-th hit of the point
    action: str = "raise"  # "raise" | "sigterm" | "sigint" | "sigkill"
    exc_name: str = "RuntimeError"
    message: str = "injected fault"
    hits: int = 0
    fired: bool = False

    def fire(self) -> None:
        self.fired = True
        if self.action == "sigterm":
            os.kill(os.getpid(), signal.SIGTERM)
            return
        if self.action == "sigint":
            os.kill(os.getpid(), signal.SIGINT)
            return
        if self.action == "sigkill":
            # uncatchable by design: the process dies here, mid-write,
            # mid-batch — whatever recovery exists must live on disk
            os.kill(os.getpid(), signal.SIGKILL)
            return
        exc_type = getattr(builtins, self.exc_name, None)
        if not (isinstance(exc_type, type) and issubclass(exc_type, BaseException)):
            exc_type = RuntimeError
        raise exc_type(f"{self.message} [injected at {self.point}]")


def parse_spec(spec: str) -> List[_Fault]:
    """``point[@n]=action`` clauses, ``;``-separated.  Raises ValueError
    on a malformed clause — a typo'd chaos spec must fail the run loudly,
    not silently test nothing."""
    out: List[_Fault] = []
    for clause in spec.split(";"):
        clause = clause.strip()
        if not clause:
            continue
        if "=" not in clause:
            raise ValueError(f"fault clause {clause!r}: expected point[@n]=action")
        target, action = clause.split("=", 1)
        target, action = target.strip(), action.strip()
        trigger = 1
        if "@" in target:
            target, n = target.rsplit("@", 1)
            try:
                trigger = int(n)
            except ValueError:
                raise ValueError(f"fault clause {clause!r}: bad trigger count {n!r}")
            if trigger < 1:
                raise ValueError(f"fault clause {clause!r}: trigger must be >= 1")
        if not target:
            raise ValueError(f"fault clause {clause!r}: empty point name")
        fault = _Fault(point=target, trigger=trigger)
        parts = action.split(":", 2)
        kind = parts[0]
        if kind in ("sigterm", "sigint", "sigkill"):
            if len(parts) > 1:
                raise ValueError(f"fault clause {clause!r}: {kind} takes no arguments")
            fault.action = kind
        elif kind == "raise":
            fault.action = "raise"
            if len(parts) > 1 and parts[1]:
                fault.exc_name = parts[1]
            if len(parts) > 2:
                fault.message = parts[2]
        else:
            raise ValueError(
                f"fault clause {clause!r}: unknown action {kind!r} "
                "(want raise[:Exc[:msg]] | sigterm | sigint | sigkill)"
            )
        out.append(fault)
    return out


def configure(spec: Optional[str]) -> None:
    """Arm the fault set from a spec string (None/"" disarms).  Replaces
    any previous configuration, including one loaded from the env."""
    global _armed, _env_loaded
    with _lock:
        _faults.clear()
        _env_loaded = True  # explicit configure wins over the env var
        for fault in parse_spec(spec) if spec else []:
            _faults.setdefault(fault.point, []).append(fault)
        _armed = bool(_faults)


def reset() -> None:
    """Disarm everything and forget that the env was ever read (tests)."""
    global _armed, _env_loaded
    with _lock:
        _faults.clear()
        _armed = False
        _env_loaded = False


def active() -> bool:
    _ensure_env_loaded()
    return _armed


def describe() -> List[str]:
    """Armed, not-yet-fired clauses (for startup logging)."""
    _ensure_env_loaded()
    with _lock:
        return [
            f"{f.point}@{f.trigger}={f.action}"
            for fs in _faults.values()
            for f in fs
            if not f.fired
        ]


def _ensure_env_loaded() -> None:
    global _env_loaded
    if _env_loaded:
        return
    with _lock:
        if _env_loaded:
            return
        spec = os.environ.get(_ENV_VAR)
    if spec is not None:
        configure(spec)
    else:
        global _armed
        _env_loaded = True
        _armed = False


def fault_point(name: str) -> None:
    """Mark an injection point.  No-op unless a configured fault targets
    ``name`` and this hit reaches its trigger count; then the fault fires
    (raise or signal) exactly once and disarms."""
    if not _env_loaded:
        _ensure_env_loaded()
    if not _armed:
        return
    to_fire = None
    with _lock:
        for fault in _faults.get(name, ()):
            if fault.fired:
                continue
            fault.hits += 1
            if fault.hits >= fault.trigger:
                to_fire = fault
                break
    if to_fire is not None:
        to_fire.fire()  # outside the lock: a handler may hit another point
