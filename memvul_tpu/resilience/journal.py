"""Progress journal + dead-letter quarantine for restartable scoring.

A corpus-scoring pass (``SiamesePredictor.predict_file``) writes one
output line per batch.  The journal is an **append-only JSONL sidecar**
(``<out>.journal``) recording, per committed output line:

    {"line": <0-based output line index>,
     "rows": [[start, end), ...]  # stream indices of the reports scored,
     "n": <row count>,
     "sha256": <hex digest of the output line text, newline excluded>}

On restart, :meth:`ScoreJournal.verified_prefix` replays the journal
against the output file and keeps the longest prefix whose lines hash
clean — a torn final line (killed mid-write) or a journal entry whose
output line never landed simply falls off the end and its rows are
re-scored.  The surviving rows are skipped in the input stream and the
surviving output lines are fed back into the metrics accumulator, so a
resumed run finishes with **identical final metrics** to an
uninterrupted one.

The dead-letter file (``<out>.deadletter``) quarantines records the
stream cannot score — unparseable JSON lines, records that blow up
normalization, over-long texts — one JSON line each with the reason, so
a single corrupt record at report 900k costs one journal line instead
of the run.
"""

from __future__ import annotations

import hashlib
import json
import logging
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Set, Tuple, Union

logger = logging.getLogger(__name__)

# refuse to tokenize texts beyond this many chars: the tokenizer's cost is
# superlinear in pathological inputs and a single 100MB "report" (a dump
# pasted into an issue body) would stall the whole stream
DEFAULT_MAX_TEXT_CHARS = 1_000_000


def line_digest(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def to_spans(indices: Iterable[int]) -> List[List[int]]:
    """Sorted indices → minimal [start, end) spans (journal compression:
    un-bucketed streams are contiguous, bucketed ones near-contiguous)."""
    spans: List[List[int]] = []
    for i in sorted(indices):
        if spans and i == spans[-1][1]:
            spans[-1][1] = i + 1
        else:
            spans.append([i, i + 1])
    return spans


def from_spans(spans: Iterable[Sequence[int]]) -> Set[int]:
    out: Set[int] = set()
    for start, end in spans:
        out.update(range(int(start), int(end)))
    return out


class DeadLetter:
    """Append-only quarantine for malformed/over-long records."""

    def __init__(
        self,
        path: Union[str, Path],
        max_text_chars: int = DEFAULT_MAX_TEXT_CHARS,
    ) -> None:
        self.path = Path(path)
        self.max_text_chars = max_text_chars
        self.count = 0
        self._f = None

    def record(
        self,
        reason: str,
        raw: Optional[str] = None,
        meta: Optional[Dict[str, Any]] = None,
    ) -> None:
        if self._f is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            # the dead-letter trail is itself the committed append-only
            # artifact (flushed per line, torn-tail-tolerant readers)
            self._f = open(self.path, "w", encoding="utf-8")  # lint: disable=MV103
        entry: Dict[str, Any] = {"reason": reason}
        if raw is not None:
            entry["raw"] = raw[:2000]  # enough to identify, never a 100MB dump
        if meta:
            entry["meta"] = meta
        self._f.write(json.dumps(entry, default=str) + "\n")
        self._f.flush()
        self.count += 1
        logger.warning("dead-letter: %s", reason)
        # lazy import: telemetry must stay importable without resilience
        # (the dependency edge points resilience → telemetry only)
        from ..telemetry import get_registry

        get_registry().counter("score.dead_letters").inc()

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None


class ScoreJournal:
    """Append-only progress journal beside a scoring output file."""

    def __init__(self, path: Union[str, Path]) -> None:
        self.path = Path(path)
        self._f = None
        self.entries_written = 0  # verified-resumed + appended this run

    # -- resume side ---------------------------------------------------------

    def read_entries(self) -> List[Dict[str, Any]]:
        """All parseable journal entries, in order.  A torn final line
        (the kill window) is dropped silently; a torn line anywhere else
        ends the trusted prefix there."""
        if not self.path.exists():
            return []
        entries: List[Dict[str, Any]] = []
        lines = self.path.read_text(encoding="utf-8").splitlines()
        for i, line in enumerate(lines):
            try:
                entry = json.loads(line)
            except ValueError:
                if i != len(lines) - 1:
                    logger.warning(
                        "journal %s: unparseable entry at line %d — "
                        "trusting only the %d entries before it",
                        self.path, i, len(entries),
                    )
                break
            if not isinstance(entry, dict) or "sha256" not in entry:
                break
            entries.append(entry)
        return entries

    def verified_prefix(
        self, out_path: Union[str, Path]
    ) -> Tuple[int, Set[int], List[str]]:
        """Check the journal against the output file.

        Returns ``(n_lines, completed_rows, kept_lines)``: the number of
        output lines whose checksums verify against the journal (in
        order, no gaps), the set of input-stream row indices those lines
        cover, and the verified line texts (newline-stripped) for
        replaying into the metrics accumulator.
        """
        entries = self.read_entries()
        out_path = Path(out_path)
        if not entries or not out_path.exists():
            return 0, set(), []
        with open(out_path, encoding="utf-8") as f:
            out_lines = f.read().splitlines()
        kept: List[str] = []
        completed: Set[int] = set()
        for i, entry in enumerate(entries):
            if entry.get("line") != i:
                logger.warning(
                    "journal %s: entry %d indexes line %s — stopping the "
                    "verified prefix here", self.path, i, entry.get("line"),
                )
                break
            if i >= len(out_lines) or line_digest(out_lines[i]) != entry["sha256"]:
                logger.warning(
                    "journal %s: output line %d missing or checksum-"
                    "mismatched (torn write?) — re-scoring from there",
                    self.path, i,
                )
                break
            kept.append(out_lines[i])
            completed |= from_spans(entry.get("rows", ()))
        return len(kept), completed, kept

    def truncate_to(self, n_entries: int, out_path: Union[str, Path]) -> None:
        """Drop everything past the verified prefix: rewrite the journal
        to its first ``n_entries`` entries (atomically) and truncate the
        output file to the matching byte length."""
        from .io import atomic_write_text

        entries = self.read_entries()[:n_entries]
        atomic_write_text(
            self.path, "".join(json.dumps(e) + "\n" for e in entries)
        )
        out_path = Path(out_path)
        if out_path.exists():
            keep_bytes = 0
            with open(out_path, "rb") as f:
                for _ in range(n_entries):
                    line = f.readline()
                    if not line:
                        break
                    keep_bytes += len(line)
            # truncating a torn tail back to the last committed line is
            # the journal's own recovery commit, not a bare write
            with open(out_path, "r+b") as f:  # lint: disable=MV103
                f.truncate(keep_bytes)
        self.entries_written = n_entries

    # -- writer side ---------------------------------------------------------

    def append(self, line_index: int, rows: Iterable[int], line_text: str) -> None:
        """Record one committed output line.  The caller must have
        flushed the output line to its file first — the journal entry is
        the durable claim that the line landed."""
        if self._f is None:
            self.path.parent.mkdir(parents=True, exist_ok=True)
            # the journal IS the committed append-only trail (flushed
            # per entry; restart verifies/truncates any torn tail)
            self._f = open(self.path, "a", encoding="utf-8")  # lint: disable=MV103
        rows = list(rows)
        entry = {
            "line": line_index,
            "rows": to_spans(rows),
            "n": len(rows),
            "sha256": line_digest(line_text),
        }
        self._f.write(json.dumps(entry) + "\n")
        self._f.flush()
        self.entries_written += 1
        # committed-work counters (this process's appends only — a
        # resumed prefix was committed by an earlier process); the
        # HEARTBEAT.json snapshot of these is what lets a supervisor
        # check liveness against the journal itself
        from ..telemetry import get_registry

        tel = get_registry()
        tel.counter("journal.lines_committed").inc()
        tel.counter("journal.rows_committed").inc(len(rows))

    def close(self) -> None:
        if self._f is not None:
            self._f.close()
            self._f = None
