"""Versioned anchor-bank store — the bank as a managed artifact.

MemVul's external CWE memory is the system's no-retrain update lever
(docs/anchor_bank.md): anchors can be added, retired, reweighted or
edited without touching the model.  Exploiting that safely needs the
bank to stop being a loose JSON file and become a *versioned* artifact:

* **immutable versions** — each version is a write-once directory
  ``<root>/v<N>/`` holding the anchor set (``anchors.json``, the exact
  ``data/cwe.py:save_anchors``/``load_anchors`` format, so a bank built
  by ``build-data`` imports verbatim) and a ``bank_manifest.json``
  carrying the sha256 of the anchor bytes.  Reads verify the digest —
  a tampered or torn artifact raises :class:`BankIntegrityError`
  instead of silently serving the wrong memory;
* **lineage** — every derived version records its parent and the exact
  :class:`BankDiff` ops (``add`` / ``retire`` / ``reweight`` /
  ``edit``) that produced it.  :meth:`BankStore.derive` is the only way
  to mint a non-root version, so ``bank log`` can always answer "where
  did the serving bank come from";
* **promotion state** — ``ACTIVE.json`` points at the store version
  operators consider live, and ``promotions.jsonl`` is the append-only
  audit trail the promotion gate (bankops/promote.py) writes.

Every artifact write goes through ``resilience.io.atomic_write_text``
(or the telemetry ``JsonlSink`` for the append-only trail) — enforced
by ``tools/lint_bank_artifact_writes.py``.  A version directory is
committed by its manifest: a crash between the anchor write and the
manifest write leaves a manifest-less directory that every reader
ignores and the next ``create``/``derive`` skips past.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Tuple, Union

from ..resilience.io import atomic_write_text
from ..telemetry.sinks import JsonlSink, read_jsonl

ANCHORS_NAME = "anchors.json"
MANIFEST_NAME = "bank_manifest.json"
ACTIVE_NAME = "ACTIVE.json"
PROMOTIONS_NAME = "promotions.jsonl"

DIFF_OPS = ("add", "retire", "reweight", "edit")

_VERSION_RE = re.compile(r"^v(\d+)$")


class BankStoreError(ValueError):
    """Invalid store operation (bad diff, unknown version, reuse)."""


class BankIntegrityError(RuntimeError):
    """An on-disk artifact does not match its manifest digest."""


def canonical_anchor_text(anchors: Dict[str, str]) -> str:
    """The byte-stable serialization the sha256 manifest covers.  Keys
    are sorted so two builds of the same anchor set hash identically
    regardless of dict insertion order (the reproducibility contract
    ``tests/test_cwe_anchors.py`` pins on the builder side)."""
    return json.dumps(anchors, indent=2, sort_keys=True, ensure_ascii=False)


def anchor_sha256(anchors: Dict[str, str]) -> str:
    return hashlib.sha256(
        canonical_anchor_text(anchors).encode("utf-8")
    ).hexdigest()


@dataclasses.dataclass(frozen=True)
class DiffOp:
    """One lineage operation.  ``add``/``edit`` carry a description,
    ``reweight`` a weight; ``retire`` only names its category."""

    op: str
    category: str
    description: Optional[str] = None
    weight: Optional[float] = None

    def to_json(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {"op": self.op, "category": self.category}
        if self.description is not None:
            out["description"] = self.description
        if self.weight is not None:
            out["weight"] = self.weight
        return out


class BankDiff:
    """An ordered list of :class:`DiffOp` — the ONLY way to derive a new
    bank version (:meth:`BankStore.derive`).  ``apply`` is pure: it
    validates every op against the parent state and returns the new
    ``(anchors, weights)`` without touching disk."""

    def __init__(self, ops: Iterable[DiffOp]) -> None:
        self.ops: List[DiffOp] = list(ops)
        for op in self.ops:
            if op.op not in DIFF_OPS:
                raise BankStoreError(
                    f"unknown diff op {op.op!r} (want one of {DIFF_OPS})"
                )
            if not op.category:
                raise BankStoreError(f"diff op {op.op!r} needs a category")

    @classmethod
    def from_json(cls, data: Iterable[Dict[str, Any]]) -> "BankDiff":
        ops = []
        for item in data:
            if not isinstance(item, dict):
                raise BankStoreError(f"diff op must be an object, got {item!r}")
            unknown = set(item) - {"op", "category", "description", "weight"}
            if unknown:
                raise BankStoreError(
                    f"diff op has unknown key(s) {sorted(unknown)}: {item!r}"
                )
            ops.append(DiffOp(
                op=str(item.get("op", "")),
                category=str(item.get("category", "")),
                description=item.get("description"),
                weight=(
                    float(item["weight"]) if item.get("weight") is not None
                    else None
                ),
            ))
        return cls(ops)

    def to_json(self) -> List[Dict[str, Any]]:
        return [op.to_json() for op in self.ops]

    def summary(self) -> Dict[str, int]:
        out: Dict[str, int] = {}
        for op in self.ops:
            out[op.op] = out.get(op.op, 0) + 1
        return out

    def apply(
        self, anchors: Dict[str, str], weights: Dict[str, float]
    ) -> Tuple[Dict[str, str], Dict[str, float]]:
        anchors = dict(anchors)
        weights = dict(weights)
        for op in self.ops:
            cat = op.category
            if op.op == "add":
                if cat in anchors:
                    raise BankStoreError(
                        f"add {cat!r}: already in the bank (use edit)"
                    )
                if not op.description:
                    raise BankStoreError(f"add {cat!r} needs a description")
                anchors[cat] = op.description
                if op.weight is not None:
                    weights[cat] = op.weight
            elif op.op == "retire":
                if cat not in anchors:
                    raise BankStoreError(f"retire {cat!r}: not in the bank")
                del anchors[cat]
                weights.pop(cat, None)
            elif op.op == "edit":
                if cat not in anchors:
                    raise BankStoreError(
                        f"edit {cat!r}: not in the bank (use add)"
                    )
                if not op.description:
                    raise BankStoreError(f"edit {cat!r} needs a description")
                anchors[cat] = op.description
            elif op.op == "reweight":
                if cat not in anchors:
                    raise BankStoreError(f"reweight {cat!r}: not in the bank")
                if op.weight is None:
                    raise BankStoreError(f"reweight {cat!r} needs a weight")
                weights[cat] = op.weight
        return anchors, weights


class BankStore:
    """The on-disk versioned bank store (layout in the module docstring;
    full semantics in docs/anchor_bank.md)."""

    def __init__(self, root: Union[str, Path]) -> None:
        self.root = Path(root)

    # -- version enumeration ---------------------------------------------------

    def versions(self) -> List[str]:
        """Committed version ids, oldest first.  A directory without a
        manifest is an uncommitted crash remnant and is ignored."""
        if not self.root.is_dir():
            return []
        found: List[Tuple[int, str]] = []
        for child in self.root.iterdir():
            m = _VERSION_RE.match(child.name)
            if m and (child / MANIFEST_NAME).exists():
                found.append((int(m.group(1)), child.name))
        return [name for _, name in sorted(found)]

    def latest(self) -> Optional[str]:
        versions = self.versions()
        return versions[-1] if versions else None

    def _next_id(self) -> str:
        highest = 0
        if self.root.is_dir():
            for child in self.root.iterdir():
                m = _VERSION_RE.match(child.name)
                if m:  # skip past uncommitted remnants too — never reuse
                    highest = max(highest, int(m.group(1)))
        return f"v{highest + 1}"

    def _vdir(self, version: str) -> Path:
        if not _VERSION_RE.match(version):
            raise BankStoreError(f"bad version id {version!r} (want v<N>)")
        return self.root / version

    # -- create / derive -------------------------------------------------------

    def create(
        self,
        anchors: Dict[str, str],
        source: str = "build",
        note: Optional[str] = None,
        weights: Optional[Dict[str, float]] = None,
    ) -> Dict[str, Any]:
        """Commit a ROOT version (no parent, empty diff) from a full
        anchor set — e.g. the ``build-data`` output imported wholesale.
        Returns the committed manifest."""
        if not anchors:
            raise BankStoreError("refusing to commit an empty anchor set")
        return self._commit(
            anchors, dict(weights or {}), parent=None, diff=[],
            source=source, note=note,
        )

    def derive(
        self,
        parent: str,
        diff: BankDiff,
        source: str = "diff",
        note: Optional[str] = None,
    ) -> Dict[str, Any]:
        """Apply ``diff`` to ``parent`` and commit the result as a new
        version — the only path to a non-root version, so lineage is
        complete by construction."""
        if not diff.ops:
            raise BankStoreError("empty diff — nothing to derive")
        parent_manifest = self.manifest(parent)
        anchors = self.anchors(parent)
        weights = dict(parent_manifest.get("weights") or {})
        new_anchors, new_weights = diff.apply(anchors, weights)
        if not new_anchors:
            raise BankStoreError(
                f"diff retires every anchor of {parent} — refusing an "
                "empty bank"
            )
        return self._commit(
            new_anchors, new_weights, parent=parent, diff=diff.to_json(),
            source=source, note=note,
        )

    def _commit(
        self,
        anchors: Dict[str, str],
        weights: Dict[str, float],
        parent: Optional[str],
        diff: List[Dict[str, Any]],
        source: str,
        note: Optional[str],
    ) -> Dict[str, Any]:
        version = self._next_id()
        vdir = self._vdir(version)
        vdir.mkdir(parents=True, exist_ok=False)  # versions are write-once
        text = canonical_anchor_text(anchors)
        atomic_write_text(vdir / ANCHORS_NAME, text)
        manifest = {
            "version": version,
            "parent": parent,
            "source": source,
            "note": note,
            "n_anchors": len(anchors),
            "anchors_sha256": hashlib.sha256(
                text.encode("utf-8")
            ).hexdigest(),
            "weights": weights,
            "diff": diff,
            "created_wall": time.time(),
        }
        # the manifest write IS the commit: readers treat a manifest-less
        # version dir as garbage, so a crash here leaves no torn version
        atomic_write_text(
            vdir / MANIFEST_NAME, json.dumps(manifest, indent=2)
        )
        return manifest

    # -- reads -----------------------------------------------------------------

    def manifest(self, version: str) -> Dict[str, Any]:
        path = self._vdir(version) / MANIFEST_NAME
        try:
            return json.loads(path.read_text(encoding="utf-8"))
        except OSError:
            raise BankStoreError(
                f"unknown bank version {version!r} in {self.root}"
            ) from None

    def anchors(self, version: str, verify: bool = True) -> Dict[str, str]:
        """The version's anchor set, digest-verified against its
        manifest by default."""
        manifest = self.manifest(version)
        text = (self._vdir(version) / ANCHORS_NAME).read_text(
            encoding="utf-8"
        )
        if verify:
            digest = hashlib.sha256(text.encode("utf-8")).hexdigest()
            if digest != manifest.get("anchors_sha256"):
                raise BankIntegrityError(
                    f"bank {version}: anchors.json sha256 {digest[:12]}… "
                    f"does not match manifest "
                    f"{str(manifest.get('anchors_sha256'))[:12]}…"
                )
        return json.loads(text)

    def verify(self, version: str) -> bool:
        """Digest-check one version; raises :class:`BankIntegrityError`
        on mismatch, returns True when intact."""
        self.anchors(version, verify=True)
        return True

    def instances(self, version: str) -> List[Dict[str, Any]]:
        """The version as anchor *instances* — the exact shape
        ``MemoryReader.read_anchors`` yields, so a store version feeds
        ``SiamesePredictor.encode_anchors`` / ``swap_bank`` directly.
        Per-anchor weights ride in ``meta["weight"]`` (recorded and
        surfaced by telemetry; the scoring math itself is unweighted —
        docs/anchor_bank.md)."""
        manifest = self.manifest(version)
        weights = dict(manifest.get("weights") or {})
        return [
            {
                "text1": description,
                "label": "same",
                "meta": {
                    "type": "golden",
                    "label": category,
                    "weight": float(weights.get(category, 1.0)),
                    "bank_version": version,
                },
            }
            for category, description in self.anchors(version).items()
        ]

    def log(self, version: Optional[str] = None) -> List[Dict[str, Any]]:
        """Lineage of ``version`` (default: latest), root first — each
        entry is the committed manifest."""
        version = version or self.latest()
        if version is None:
            return []
        chain: List[Dict[str, Any]] = []
        seen = set()
        current: Optional[str] = version
        while current is not None:
            if current in seen:  # defensive: a hand-edited cycle
                raise BankStoreError(f"lineage cycle at {current!r}")
            seen.add(current)
            manifest = self.manifest(current)
            chain.append(manifest)
            current = manifest.get("parent")
        chain.reverse()
        return chain

    # -- promotion state -------------------------------------------------------

    def set_active(
        self, version: str, source: str = "manual"
    ) -> Dict[str, Any]:
        """Point ``ACTIVE.json`` at a committed version (atomic — an
        operator never reads a torn pointer)."""
        self.manifest(version)  # must exist and be committed
        record = {
            "version": version,
            "source": source,
            "promoted_wall": time.time(),
        }
        atomic_write_text(
            self.root / ACTIVE_NAME, json.dumps(record, indent=2)
        )
        return record

    def active(self) -> Optional[Dict[str, Any]]:
        try:
            obj = json.loads(
                (self.root / ACTIVE_NAME).read_text(encoding="utf-8")
            )
        except (OSError, ValueError):
            return None
        return obj if isinstance(obj, dict) else None

    def record_promotion(self, **fields: Any) -> None:
        """Append one audit record to ``promotions.jsonl`` (gate
        decisions, promotions, demotions — bankops/promote.py)."""
        fields.setdefault("t", round(time.time(), 3))
        sink = JsonlSink(self.root / PROMOTIONS_NAME)
        try:
            sink.emit(fields)
        finally:
            sink.close()

    def promotions(self) -> List[Dict[str, Any]]:
        records, _ = read_jsonl(self.root / PROMOTIONS_NAME)
        return records
