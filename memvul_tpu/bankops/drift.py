"""Per-anchor win attribution and drift vs a pinned baseline.

The serving path attributes every served decision to its winning anchor
(``bank.anchor_wins.<id>`` counters + ``bank.anchor_score.<id>``
match-score reservoir histograms, recorded in
``serving/service.py:_score_chunk``).  This module turns those raw
counters into the operator-facing signal: the *win-share distribution*
— what fraction of served decisions each anchor wins — and its drift
against a **pinned baseline** distribution captured when the bank was
known healthy.  A degrading anchor (its subtree description going
stale, traffic shifting to a weakness class it used to catch) shows up
as its win share bleeding away — visible in the
``telemetry-report`` per-anchor table *before* it costs recall.

Drift metric: total-variation distance between the current and
baseline win-share distributions (``0`` = identical, ``1`` = disjoint),
published as the ``bank.anchor_drift`` gauge.  The baseline is a plain
JSON file (``anchor_baseline.json``), written atomically so a pinned
baseline can never be read torn.
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Dict, Optional, Union

from ..resilience.io import atomic_write_text

WINS_PREFIX = "bank.anchor_wins."
SCORE_PREFIX = "bank.anchor_score."
BASELINE_NAME = "anchor_baseline.json"
DRIFT_GAUGE = "bank.anchor_drift"


def win_counts(counters: Dict[str, int]) -> Dict[str, int]:
    """Per-anchor win counts from a counter mapping (a registry
    snapshot or a ``telemetry.json`` counters dict)."""
    return {
        name[len(WINS_PREFIX):]: int(value)
        for name, value in counters.items()
        if name.startswith(WINS_PREFIX)
    }


def win_shares(counts: Dict[str, int]) -> Dict[str, float]:
    total = sum(counts.values())
    if total <= 0:
        return {}
    return {anchor: count / total for anchor, count in counts.items()}


def total_variation(
    current: Dict[str, float], baseline: Dict[str, float]
) -> float:
    """Total-variation distance between two win-share distributions —
    half the L1 over the union of anchors, so an anchor present in only
    one distribution contributes its full share."""
    keys = set(current) | set(baseline)
    return 0.5 * sum(
        abs(current.get(k, 0.0) - baseline.get(k, 0.0)) for k in keys
    )


def pin_baseline(
    registry, path: Union[str, Path]
) -> Dict[str, float]:
    """Snapshot the registry's current win-share distribution as the
    pinned baseline file.  Returns the pinned distribution."""
    shares = win_shares(win_counts(registry.snapshot()["counters"]))
    atomic_write_text(
        Path(path),
        json.dumps({"win_shares": shares}, indent=2, sort_keys=True),
    )
    return shares


def load_baseline(path: Union[str, Path]) -> Optional[Dict[str, float]]:
    """The pinned win-share distribution, or None when absent or
    unreadable (a report/monitor must degrade, not crash)."""
    try:
        obj = json.loads(Path(path).read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return None
    shares = obj.get("win_shares") if isinstance(obj, dict) else None
    if not isinstance(shares, dict):
        return None
    try:
        return {str(k): float(v) for k, v in shares.items()}
    except (TypeError, ValueError):
        return None


def update_drift_gauge(
    registry, baseline: Dict[str, float]
) -> Optional[float]:
    """Recompute win-share drift vs ``baseline`` and publish it as the
    ``bank.anchor_drift`` gauge.  Returns the drift, or None when no
    wins have been recorded yet."""
    shares = win_shares(win_counts(registry.snapshot()["counters"]))
    if not shares:
        return None
    drift = total_variation(shares, baseline)
    registry.gauge(DRIFT_GAUGE).set(drift)
    return drift


class DriftMonitor:
    """Background drift publisher for a serving process: every
    ``interval_s`` it recomputes the drift gauge from the registry's
    win counters.  Pure control plane — it never touches the request
    path, and a missing/empty distribution is just skipped."""

    def __init__(
        self,
        registry,
        baseline: Dict[str, float],
        interval_s: float = 30.0,
    ) -> None:
        self._registry = registry
        self._baseline = dict(baseline)
        self._interval_s = max(0.05, float(interval_s))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, name="memvul-bank-drift", daemon=True
        )
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self._interval_s):
            try:
                update_drift_gauge(self._registry, self._baseline)
            except Exception:  # pragma: no cover - defensive
                pass

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        self._thread.join(timeout)
