"""Gated promotion — a candidate bank earns its way into serving.

The promotion gate is the contract between "someone derived a new bank
version" and "millions of users are scored by it".  A candidate must
pass BOTH checks before :func:`promote` will install it:

* **golden-set parity** — the active and candidate banks each score a
  pinned labeled golden set through the same warmed predictor
  (``bankops/shadow.py:score_texts``); the candidate's AUC/F1 may not
  drop more than the configured tolerances;
* **shadow evidence** — a shadow summary (online
  :class:`~memvul_tpu.bankops.shadow.ShadowScorer` or offline
  :func:`~memvul_tpu.bankops.shadow.replay_results`) must cover at
  least ``min_shadow_samples`` requests with a decision-flip rate at or
  under ``max_flip_rate``.

Refusals are **machine-readable**: a :class:`PromotionDecision` carries
one ``{"code", "observed", "limit"}`` record per violated gate, so a
rollout controller can branch on ``code`` instead of parsing prose.

:func:`promote` installs an approved candidate through the PR 6 fleet
path — ``rolling_swap`` for a :class:`ReplicaRouter` (every response
carries exactly one bank version throughout), plain ``swap_bank`` for a
single service — stamping provenance (``source="promotion"``, the store
version id) into the serving manifest, then advances the store's
``ACTIVE`` pointer and appends the audit record.  :func:`demote` is the
rollback: re-install the active store version's *parent* the same way.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Any, Dict, Iterable, List, Optional

import numpy as np

from ..telemetry import get_registry
from ..training.metrics import SiameseMeasure
from .shadow import score_texts
from .store import BankStore, BankStoreError

logger = logging.getLogger(__name__)

# machine-readable refusal codes (docs/anchor_bank.md)
REASON_AUC = "auc_regression"
REASON_F1 = "f1_regression"
REASON_FLIP_RATE = "flip_rate_exceeded"
REASON_SHADOW_SAMPLES = "insufficient_shadow_samples"
REASON_SHADOW_MISSING = "shadow_evidence_missing"


@dataclasses.dataclass(frozen=True)
class GateThresholds:
    """Promotion-gate tolerances; defaults mirror
    ``config.BANKOPS_DEFAULTS``."""

    max_auc_drop: float = 0.01
    max_f1_drop: float = 0.01
    max_flip_rate: float = 0.02
    min_shadow_samples: int = 100
    require_shadow: bool = True


@dataclasses.dataclass
class PromotionDecision:
    """The gate's verdict.  ``reasons`` is empty iff ``approved``."""

    approved: bool
    candidate: Optional[str]
    parent: Optional[str]
    reasons: List[Dict[str, Any]]
    metrics: Dict[str, Any]

    def to_json(self) -> Dict[str, Any]:
        return {
            "approved": self.approved,
            "candidate": self.candidate,
            "parent": self.parent,
            "reasons": self.reasons,
            "metrics": self.metrics,
        }


class PromotionRefused(RuntimeError):
    """Raised by :func:`promote` on an unapproved decision; carries the
    machine-readable decision."""

    def __init__(self, decision: PromotionDecision) -> None:
        codes = [r.get("code") for r in decision.reasons]
        super().__init__(f"promotion refused: {codes}")
        self.decision = decision


def golden_metrics(
    predictor,
    bank_instances: Iterable[Dict],
    eval_instances: Iterable[Dict],
) -> Dict[str, float]:
    """Threshold-swept siamese metrics of one bank over a labeled
    golden set, scored through the predictor's warmed program (a
    new-geometry bank is AOT-warmed first — the gate never costs a
    serving process a mid-serve compile)."""
    bank, _labels, n_anchors = predictor.encode_bank(list(bank_instances))
    predictor.warmup_bank_shapes(bank)
    instances = list(eval_instances)
    probs = score_texts(
        predictor, [inst["text1"] for inst in instances], bank, n_anchors
    )
    measure = SiameseMeasure()
    measure.update(
        probs.max(axis=-1) if len(instances) else np.zeros((0,)),
        [inst.get("meta") or {} for inst in instances],
    )
    out = measure.compute(reset=True)
    out["n_eval"] = float(len(instances))
    return out


def evaluate_gate(
    active_metrics: Dict[str, float],
    candidate_metrics: Dict[str, float],
    shadow_summary: Optional[Dict[str, Any]],
    thresholds: Optional[GateThresholds] = None,
    candidate: Optional[str] = None,
    parent: Optional[str] = None,
) -> PromotionDecision:
    """Pure gate logic over already-computed evidence (deterministic,
    directly testable).  ``shadow_summary`` is the dict
    ``ShadowScorer.stop()`` / ``replay_results`` return."""
    thresholds = thresholds or GateThresholds()
    reasons: List[Dict[str, Any]] = []

    auc_drop = float(active_metrics.get("auc", 0.0)) - float(
        candidate_metrics.get("auc", 0.0)
    )
    if auc_drop > thresholds.max_auc_drop:
        reasons.append({
            "code": REASON_AUC,
            "observed": round(auc_drop, 6),
            "limit": thresholds.max_auc_drop,
        })
    f1_drop = float(active_metrics.get("f1", 0.0)) - float(
        candidate_metrics.get("f1", 0.0)
    )
    if f1_drop > thresholds.max_f1_drop:
        reasons.append({
            "code": REASON_F1,
            "observed": round(f1_drop, 6),
            "limit": thresholds.max_f1_drop,
        })

    if shadow_summary is None:
        if thresholds.require_shadow:
            reasons.append({
                "code": REASON_SHADOW_MISSING,
                "observed": None,
                "limit": thresholds.min_shadow_samples,
            })
    else:
        sampled = int(shadow_summary.get("sampled", 0))
        if sampled < thresholds.min_shadow_samples:
            reasons.append({
                "code": REASON_SHADOW_SAMPLES,
                "observed": sampled,
                "limit": thresholds.min_shadow_samples,
            })
        flip_rate = float(shadow_summary.get("flip_rate", 0.0))
        if flip_rate > thresholds.max_flip_rate:
            reasons.append({
                "code": REASON_FLIP_RATE,
                "observed": round(flip_rate, 6),
                "limit": thresholds.max_flip_rate,
            })

    return PromotionDecision(
        approved=not reasons,
        candidate=candidate,
        parent=parent,
        reasons=reasons,
        metrics={
            "active": dict(active_metrics),
            "candidate": dict(candidate_metrics),
            "shadow": dict(shadow_summary) if shadow_summary else None,
        },
    )


def evaluate_cascade(
    predictor,
    eval_instances: Iterable[Dict],
    shadow_summary: Optional[Dict[str, Any]] = None,
    thresholds: Optional[GateThresholds] = None,
    threshold: float = 0.5,
) -> PromotionDecision:
    """Parity gate for the quantized cascade (docs/quantized_serving.md):
    the same golden set scored twice through the SAME warmed predictor
    and bank — the fp32 bucket grid as "active", the offline cascade
    rule (int8 everywhere, in-band rows rescored fp32;
    ``score_texts(impl="cascade")``) as "candidate" — then the standard
    :func:`evaluate_gate` over AUC/F1 drop and decision flip rate.  A
    mis-set band that lets uncertain rows short-circuit on int8 shows
    up as flips and refuses with the machine-readable
    ``{code, observed, limit}`` record.

    ``shadow_summary`` is the live evidence when available: a
    :class:`~memvul_tpu.bankops.shadow.ShadowScorer` attached to a
    cascade service rescores served (cascade) traffic through the fp32
    path, so its summary measures exactly served-vs-fp32 flips.
    Without one, an offline flip summary over the golden set is
    synthesized in the same shape (``flip`` = the ``threshold``
    decision differs between the two scorings)."""
    if getattr(predictor, "int8_params", None) is None:
        raise ValueError(
            "evaluate_cascade needs an encoder_precision='int8' predictor"
        )
    instances = list(eval_instances)
    texts = [inst["text1"] for inst in instances]
    metas = [inst.get("meta") or {} for inst in instances]
    fp32 = predictor.score_texts(texts, impl="bucketed")
    cascade = predictor.score_texts(texts, impl="cascade")

    def _measured(probs) -> Dict[str, float]:
        measure = SiameseMeasure()
        measure.update(
            probs.max(axis=-1) if instances else np.zeros((0,)), metas
        )
        out = measure.compute(reset=True)
        out["n_eval"] = float(len(instances))
        return out

    if shadow_summary is None and instances:
        best_active = fp32.max(axis=-1)
        best_shadow = cascade.max(axis=-1)
        flips = int(
            ((best_active >= threshold) != (best_shadow >= threshold)).sum()
        )
        deltas = np.abs(best_shadow - best_active)
        shadow_summary = {
            "sampled": len(instances),
            "flips": flips,
            "flip_rate": flips / len(instances),
            "anchor_changes": int(
                (fp32.argmax(axis=-1) != cascade.argmax(axis=-1)).sum()
            ),
            "mean_abs_delta": float(deltas.mean()),
            "max_abs_delta": float(deltas.max()),
        }
    return evaluate_gate(
        _measured(fp32),
        _measured(cascade),
        shadow_summary,
        thresholds=thresholds,
        candidate="cascade",
        parent="fp32",
    )


def evaluate_reweight(
    predictor,
    store: BankStore,
    version: str,
    eval_instances: Iterable[Dict],
    shadow_summary: Optional[Dict[str, Any]] = None,
    thresholds: Optional[GateThresholds] = None,
    threshold: float = 0.5,
) -> PromotionDecision:
    """Parity gate for per-anchor reweighting (docs/multitenancy.md):
    the golden set is scored ONCE through a store version's warmed
    bank, then judged twice from the same probability matrix — the
    plain ``argmax`` selection as "active" and the weighted selection
    (``argmax(probs * weights)``, weights from each anchor instance's
    ``meta["weight"]``, default 1.0) as "candidate".  The candidate's
    per-text score is the RAW probability of the weighted winner —
    exactly what the serving path reports (serving/dispatch.py), so the
    gate measures precisely the decision change a tenant would see.

    A bank whose weights are all 1.0 selects identically by
    construction: zero flips, identical metrics, approved — the parity
    anchor the reweight tests pin.  Skewed weights show up as decision
    flips and refuse through the standard machine-readable
    ``{code, observed, limit}`` records of :func:`evaluate_gate`."""
    bank_instances = store.instances(version)
    bank, _labels, n_anchors = predictor.encode_bank(bank_instances)
    predictor.warmup_bank_shapes(bank)
    raw = [
        float((inst.get("meta") or {}).get("weight", 1.0))
        for inst in bank_instances
    ]
    if len(raw) != int(n_anchors):
        raise BankStoreError(
            f"bank {version}: {len(raw)} instances vs {n_anchors} anchors "
            "— cannot align weights to anchor rows"
        )
    weights = np.asarray(raw, dtype=np.float32)
    instances = list(eval_instances)
    texts = [inst["text1"] for inst in instances]
    metas = [inst.get("meta") or {} for inst in instances]
    probs = score_texts(predictor, texts, bank, n_anchors)
    probs = probs[:, :n_anchors] if len(instances) else probs

    if instances:
        best_active = probs.max(axis=-1)
        # raw prob of the weighted winner — the served "score"
        winners = (probs * weights[None, :]).argmax(axis=-1)
        best_candidate = probs[np.arange(len(instances)), winners]
    else:
        best_active = best_candidate = np.zeros((0,))
        winners = np.zeros((0,), dtype=np.int64)

    def _measured(best) -> Dict[str, float]:
        measure = SiameseMeasure()
        measure.update(best, metas)
        out = measure.compute(reset=True)
        out["n_eval"] = float(len(instances))
        return out

    if shadow_summary is None and instances:
        flips = int(
            ((best_active >= threshold) != (best_candidate >= threshold)).sum()
        )
        deltas = np.abs(best_candidate - best_active)
        shadow_summary = {
            "sampled": len(instances),
            "flips": flips,
            "flip_rate": flips / len(instances),
            "anchor_changes": int(
                (probs.argmax(axis=-1) != winners).sum()
            ),
            "mean_abs_delta": float(deltas.mean()),
            "max_abs_delta": float(deltas.max()),
        }
    return evaluate_gate(
        _measured(best_active),
        _measured(best_candidate),
        shadow_summary,
        thresholds=thresholds,
        candidate=f"{version}+reweight",
        parent=version,
    )


def evaluate_candidate(
    predictor,
    store: BankStore,
    candidate: str,
    eval_instances: Iterable[Dict],
    active: Optional[str] = None,
    shadow_summary: Optional[Dict[str, Any]] = None,
    thresholds: Optional[GateThresholds] = None,
) -> PromotionDecision:
    """Run the full gate for a store candidate: golden-set metrics for
    the active version (``ACTIVE`` pointer, else the candidate's
    parent) and the candidate, then :func:`evaluate_gate` with the
    shadow evidence."""
    manifest = store.manifest(candidate)
    if active is None:
        pointer = store.active()
        active = (
            pointer["version"] if pointer else manifest.get("parent")
        )
    if active is None:
        raise BankStoreError(
            f"candidate {candidate} has no parent and no ACTIVE pointer "
            "to gate against"
        )
    eval_instances = list(eval_instances)
    active_metrics = golden_metrics(
        predictor, store.instances(active), eval_instances
    )
    candidate_metrics = golden_metrics(
        predictor, store.instances(candidate), eval_instances
    )
    return evaluate_gate(
        active_metrics,
        candidate_metrics,
        shadow_summary,
        thresholds=thresholds,
        candidate=candidate,
        parent=active,
    )


def _install(
    target,
    instances: List[Dict],
    source: str,
    store_version: str,
    tenant: Optional[str] = None,
) -> int:
    """Install a bank on a single service or roll it across a fleet —
    the PR 6 path, so the no-torn-version invariant holds throughout.
    ``tenant`` scopes the install to one named tenant's bank slot
    (serving/tenancy.py); ``None`` keeps the default-tenant path
    byte-identical to the pre-tenancy behaviour."""
    if hasattr(target, "replicas"):
        from ..serving.router import rolling_swap

        return rolling_swap(
            target, instances, source=source, store_version=store_version,
            tenant=tenant,
        )
    return target.swap_bank(
        instances, source=source, store_version=store_version, tenant=tenant
    )


def promote(
    target,
    store: BankStore,
    decision: PromotionDecision,
    registry=None,
    tenant: Optional[str] = None,
) -> int:
    """Install an approved candidate into serving and advance the
    store's ``ACTIVE`` pointer + audit trail.  Raises
    :class:`PromotionRefused` (carrying the machine-readable decision)
    when the gate did not approve.  Returns the new serving bank
    version number.  ``tenant`` scopes the install (and the audit
    record) to one named tenant's bank slot; other tenants' banks —
    and the default bank — are untouched."""
    tel = registry if registry is not None else get_registry()
    if not decision.approved:
        store.record_promotion(
            kind="promotion_refused", tenant=tenant, **decision.to_json()
        )
        tel.counter("bank.promotions_refused").inc()
        raise PromotionRefused(decision)
    if decision.candidate is None:
        raise BankStoreError("decision names no candidate version")
    serving_version = _install(
        target,
        store.instances(decision.candidate),
        source="promotion",
        store_version=decision.candidate,
        tenant=tenant,
    )
    store.set_active(decision.candidate, source="promotion")
    store.record_promotion(
        kind="promotion",
        candidate=decision.candidate,
        parent=decision.parent,
        serving_version=serving_version,
        reasons=decision.reasons,
        tenant=tenant,
    )
    tel.counter("bank.promotions").inc()
    tel.event(
        "bank_promotion",
        candidate=decision.candidate,
        serving_version=serving_version,
        tenant=tenant,
    )
    logger.info(
        "bank %s promoted to serving v%d", decision.candidate, serving_version
    )
    return serving_version


def demote(
    target, store: BankStore, registry=None, tenant: Optional[str] = None
) -> Dict[str, Any]:
    """Roll serving back to the active store version's parent (the
    demote-to-parent rollback): install the parent bank through the
    same fleet path, repoint ``ACTIVE``, append the audit record.
    Returns ``{"version": parent_id, "serving_version": int}``.
    ``tenant`` scopes the rollback to one named tenant's bank slot."""
    tel = registry if registry is not None else get_registry()
    pointer = store.active()
    if pointer is None:
        raise BankStoreError("no ACTIVE pointer — nothing to demote from")
    current = pointer["version"]
    parent = store.manifest(current).get("parent")
    if parent is None:
        raise BankStoreError(
            f"active bank {current} is a root version — no parent to "
            "demote to"
        )
    serving_version = _install(
        target, store.instances(parent),
        source="demotion", store_version=parent, tenant=tenant,
    )
    store.set_active(parent, source="demotion")
    store.record_promotion(
        kind="demotion",
        demoted=current,
        restored=parent,
        serving_version=serving_version,
        tenant=tenant,
    )
    tel.counter("bank.demotions").inc()
    tel.event("bank_demotion", demoted=current, restored=parent, tenant=tenant)
    logger.info(
        "bank %s demoted — %s restored at serving v%d",
        current, parent, serving_version,
    )
    return {"version": parent, "serving_version": serving_version}
