"""Anchor-bank lifecycle subsystem (docs/anchor_bank.md).

MemVul's external CWE memory is the system's no-retrain update lever;
this package makes the bank a managed, evolvable artifact instead of a
static JSON file:

* **store** — immutable versioned on-disk bank artifacts with sha256
  manifests and full diff lineage (``add``/``retire``/``reweight``/
  ``edit``), an ``ACTIVE`` pointer, and a promotions audit trail;
* **shadow** — score live or journaled traffic against a candidate
  bank off the hot path; per-request deltas stream to
  ``shadow_deltas.jsonl``;
* **promote** — AUC/F1-parity + shadow-flip-rate gate with
  machine-readable refusals, fleet install via ``rolling_swap``,
  demote-to-parent rollback;
* **drift** — per-anchor win-share attribution and total-variation
  drift against a pinned baseline (``bank.anchor_drift``), rendered as
  the ``telemetry-report`` per-anchor table.

CLI: ``python -m memvul_tpu bank {build,diff,log,shadow,promote}``.
"""

from .store import (  # noqa: F401
    ACTIVE_NAME,
    ANCHORS_NAME,
    DIFF_OPS,
    MANIFEST_NAME,
    PROMOTIONS_NAME,
    BankDiff,
    BankIntegrityError,
    BankStore,
    BankStoreError,
    DiffOp,
    anchor_sha256,
    canonical_anchor_text,
)
from .shadow import (  # noqa: F401
    SHADOW_DELTAS_NAME,
    ShadowConfig,
    ShadowScorer,
    replay_results,
    score_texts,
)
from .promote import (  # noqa: F401
    GateThresholds,
    PromotionDecision,
    PromotionRefused,
    demote,
    evaluate_candidate,
    evaluate_cascade,
    evaluate_gate,
    evaluate_reweight,
    golden_metrics,
    promote,
)
from .drift import (  # noqa: F401
    BASELINE_NAME,
    DRIFT_GAUGE,
    DriftMonitor,
    load_baseline,
    pin_baseline,
    total_variation,
    update_drift_gauge,
    win_counts,
    win_shares,
)
