"""Shadow scoring — evaluate a candidate bank against real traffic.

A candidate bank (a :class:`~memvul_tpu.bankops.store.BankStore`
version, or any anchor-instance list) must prove itself against the
traffic the active bank actually serves before promotion
(bankops/promote.py).  Two modes, one delta-row format:

* **online** (:class:`ShadowScorer`) — attach to a live
  :class:`~memvul_tpu.serving.ScoringService` (or a
  :class:`~memvul_tpu.serving.ReplicaRouter`, which fans the tap out to
  every replica).  The service's shadow tap fires on the batcher thread
  but only *enqueues* copies of sampled served requests into a bounded
  queue; this module's own worker thread re-scores them through the
  predictor's already-warmed score program against an immutable
  candidate snapshot.  The hot path is untouched: active responses are
  bitwise-identical with the shadow on or off, ``score_trace_count``
  stays flat (a candidate of new geometry is AOT-warmed at attach
  time, off the request path), and a crashing shadow worker only ever
  increments ``bank.shadow_errors`` — clients cannot observe it
  (chaos-pinned via the ``bank.shadow`` fault point);
* **offline** (:func:`replay_results`) — replay a journaled
  ``predict_file`` output (the PR 2 resumable scoring artifact) against
  the candidate: stream the same corpus, score it with the candidate
  bank, and diff row-by-row against the recorded active scores.

Both stream per-request delta rows to ``shadow_deltas.jsonl`` (one row
per shadow-scored request — the ``bank.shadow_sampled`` counter equals
the row count exactly) and return the same summary dict the promotion
gate consumes: sampled count, decision-flip rate at the serving
threshold, mean/max absolute score delta.
"""

from __future__ import annotations

import collections
import dataclasses
import logging
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple, Union

import numpy as np

from ..resilience import faults
from ..telemetry import get_registry
from ..telemetry.sinks import JsonlSink
from .drift import update_drift_gauge

logger = logging.getLogger(__name__)

SHADOW_DELTAS_NAME = "shadow_deltas.jsonl"


@dataclasses.dataclass(frozen=True)
class ShadowConfig:
    """Shadow sampling knobs; defaults mirror ``config.BANKOPS_DEFAULTS``."""

    sample_stride: int = 1     # shadow-score every Nth served request
    max_queue: int = 512       # bounded sample queue; overflow drops + counts
    threshold: float = 0.5     # serving decision threshold (flip detection)
    drift_every: int = 50      # update the drift gauge every N samples


def score_texts(
    predictor,
    texts: Sequence[str],
    bank_array,
    n_anchors: int,
) -> np.ndarray:
    """Score ``texts`` against an *explicit* bank through the
    predictor's warmed serving impl — bucket routing + ``_pad_block``
    when the active path is bucketed, token-budget packing through the
    single warmed ragged program when it is ragged
    (:meth:`SiamesePredictor.score_texts` owns the routing) — so a
    shadow score of a request is computed exactly the way the candidate
    bank *would have served* it, whichever impl is live.  Shadow deltas
    are therefore impl-invariant by construction (pinned in
    tests/test_ragged_serving.py).  Returns ``[len(texts), n_anchors]``
    probabilities.

    Dispatches only the predictor's warmed shapes; callers warm a
    new-geometry bank via ``warmup_bank_shapes`` first (the shadow/gate
    attach paths do), keeping ``score_trace_count`` flat.
    """
    if not texts:
        return np.zeros((0, n_anchors), np.float32)
    return predictor.score_texts(texts, bank_array, n_anchors)


def _delta_row(
    index: int,
    active_score: float,
    active_anchor: Optional[str],
    active_version: Any,
    shadow_row: np.ndarray,
    labels: Sequence[str],
    candidate_version: Any,
    threshold: float,
) -> Dict[str, Any]:
    best = int(np.argmax(shadow_row))
    shadow_score = float(shadow_row[best])
    return {
        "i": index,
        "active_version": active_version,
        "candidate_version": candidate_version,
        "active_score": float(active_score),
        "shadow_score": shadow_score,
        "delta": shadow_score - float(active_score),
        "active_anchor": active_anchor,
        "shadow_anchor": labels[best],
        "flip": (float(active_score) >= threshold) != (shadow_score >= threshold),
    }


class _DeltaStats:
    """Running aggregate over emitted delta rows (the summary the
    promotion gate reads)."""

    def __init__(self) -> None:
        self.sampled = 0
        self.flips = 0
        self.anchor_changes = 0
        self.abs_delta_sum = 0.0
        self.abs_delta_max = 0.0

    def update(self, row: Dict[str, Any]) -> None:
        self.sampled += 1
        if row["flip"]:
            self.flips += 1
        if row["active_anchor"] != row["shadow_anchor"]:
            self.anchor_changes += 1
        a = abs(row["delta"])
        self.abs_delta_sum += a
        self.abs_delta_max = max(self.abs_delta_max, a)

    def summary(self) -> Dict[str, Any]:
        n = self.sampled
        return {
            "sampled": n,
            "flips": self.flips,
            "flip_rate": self.flips / n if n else 0.0,
            "anchor_changes": self.anchor_changes,
            "mean_abs_delta": self.abs_delta_sum / n if n else 0.0,
            "max_abs_delta": self.abs_delta_max,
        }


class ShadowScorer:
    """Online shadow: re-score sampled served requests against a
    candidate bank, off the hot path (module docstring).

    ``target`` is a :class:`ScoringService` or :class:`ReplicaRouter`;
    the candidate is encoded (and, if its padded geometry differs from
    the active bank's, AOT-warmed) at construction — all before the tap
    is installed, so attaching never costs the request path a compile.
    """

    def __init__(
        self,
        target,
        candidate_instances: Iterable[Dict],
        out_dir: Optional[Union[str, Path]] = None,
        config: Optional[ShadowConfig] = None,
        registry=None,
        candidate_version: Optional[str] = None,
        baseline: Optional[Dict[str, float]] = None,
    ) -> None:
        self.config = config or ShadowConfig()
        if self.config.sample_stride < 1:
            raise ValueError("sample_stride must be >= 1")
        self._tel = registry if registry is not None else get_registry()
        self._target = target
        self._baseline = baseline
        service = (
            target.replicas[0].service
            if hasattr(target, "replicas") else target
        )
        self.predictor = service.predictor
        self.candidate_version = candidate_version
        bank, labels, n_anchors = self.predictor.encode_bank(
            list(candidate_instances)
        )
        active = service.bank_snapshot()
        if tuple(bank.shape) != tuple(active.array.shape):
            # a new-geometry candidate means new XLA programs; compile
            # them here, before the tap exists, so the batcher never
            # traces on our account (score_trace_count stays flat)
            self.predictor.warmup_bank_shapes(bank)
        self._bank = bank
        self._labels: Tuple[str, ...] = tuple(labels)
        self._n_anchors = n_anchors
        self._sink = (
            JsonlSink(Path(out_dir) / SHADOW_DELTAS_NAME)
            if out_dir is not None else None
        )
        self._stats = _DeltaStats()
        self._queue: "collections.deque" = collections.deque()
        self._cond = threading.Condition()
        self._stop = threading.Event()
        self._seen = 0  # tap-side request counter (stride sampling)
        self._thread = threading.Thread(
            target=self._worker, name="memvul-bank-shadow", daemon=True
        )
        self._thread.start()
        target.set_shadow_tap(self._tap)

    # -- tap (batcher thread: enqueue only, never score) -----------------------

    def _tap(self, texts: List[str], probs: np.ndarray, bank) -> None:
        # runs on the (or, behind a router, *a*) batcher thread: enqueue
        # copies only, under the one condition lock — a fleet fans this
        # tap out to N batcher threads, so the sample counter and queue
        # must be guarded together
        stride = self.config.sample_stride
        with self._cond:
            appended = False
            for text, row in zip(texts, probs):
                self._seen += 1
                if (self._seen - 1) % stride:
                    continue
                if len(self._queue) >= self.config.max_queue:
                    self._tel.counter("bank.shadow_dropped").inc()
                    continue
                best = int(np.argmax(row))
                self._queue.append((
                    text, float(row[best]), bank.labels[best], bank.version,
                ))
                appended = True
            if appended:
                self._cond.notify()

    # -- worker (shadow thread: scoring + delta emission) ----------------------

    def _worker(self) -> None:
        while True:
            with self._cond:
                while not self._queue and not self._stop.is_set():
                    self._cond.wait(0.05)
                if not self._queue and self._stop.is_set():
                    return
                batch = []
                while self._queue and len(batch) < 64:
                    batch.append(self._queue.popleft())
            try:
                # chaos hook: a crashing shadow scorer must only ever
                # surface here — counted, never client-visible
                faults.fault_point("bank.shadow")
                rows = score_texts(
                    self.predictor,
                    [text for text, _, _, _ in batch],
                    self._bank,
                    self._n_anchors,
                )
            except Exception as e:
                self._tel.counter("bank.shadow_errors").inc(len(batch))
                logger.warning(
                    "shadow scoring failed for %d sample(s) (active path "
                    "unaffected): %s", len(batch), str(e)[:200],
                )
                continue
            for (text, a_score, a_anchor, a_version), row in zip(batch, rows):
                record = _delta_row(
                    self._stats.sampled, a_score, a_anchor, a_version,
                    row, self._labels, self.candidate_version,
                    self.config.threshold,
                )
                self._stats.update(record)
                self._tel.counter("bank.shadow_sampled").inc()
                if record["flip"]:
                    self._tel.counter("bank.shadow_flips").inc()
                self._tel.histogram("bank.shadow_abs_delta").observe(
                    abs(record["delta"])
                )
                if self._sink is not None:
                    self._sink.emit(record)
            if (
                self._baseline
                and self._stats.sampled
                and self._stats.sampled % max(1, self.config.drift_every) == 0
            ):
                update_drift_gauge(self._tel, self._baseline)

    # -- lifecycle -------------------------------------------------------------

    def summary(self) -> Dict[str, Any]:
        out = self._stats.summary()
        out.update(
            candidate_version=self.candidate_version,
            dropped=self._tel.counter("bank.shadow_dropped").value,
            errors=self._tel.counter("bank.shadow_errors").value,
        )
        return out

    def stop(self, timeout: float = 10.0) -> Dict[str, Any]:
        """Detach the tap, drain the sample queue, stop the worker and
        close the delta sink.  Returns the final summary."""
        self._target.clear_shadow_tap()
        self._stop.set()
        with self._cond:
            self._cond.notify()
        self._thread.join(timeout)
        if self._sink is not None:
            self._sink.close()
        summary = self.summary()
        self._tel.event("shadow_stop", **{
            k: v for k, v in summary.items() if not isinstance(v, dict)
        })
        return summary


def replay_results(
    predictor,
    candidate_instances: Iterable[Dict],
    reader,
    corpus_path: Union[str, Path],
    results_path: Union[str, Path],
    out_dir: Optional[Union[str, Path]] = None,
    split: Optional[str] = None,
    threshold: float = 0.5,
    candidate_version: Optional[str] = None,
    batch: int = 64,
    registry=None,
) -> Dict[str, Any]:
    """Offline shadow: diff a candidate bank against a journaled
    ``predict_file`` run.

    Streams ``corpus_path`` through ``reader``, scores every report
    against the candidate bank, and joins each report with its active
    score recorded in ``results_path`` (the JSON-lines output
    ``predict_file`` wrote and its PR 2 journal verified).  The join is
    by ``Issue_Url`` when every recorded row carries one — a bucketed
    recorded run writes rows in length-bucket order, not stream order —
    with a positional fallback for url-less corpora (repeated urls
    consume their records in recorded order).  Emits the same
    ``shadow_deltas.jsonl`` rows as the online scorer and returns the
    same summary dict.
    """
    import json as _json

    tel = registry if registry is not None else get_registry()
    results_path = Path(results_path)
    recorded: List[Dict[str, Any]] = []
    for line in results_path.read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if line:
            recorded.extend(_json.loads(line))
    by_url: Optional[Dict[Any, List[Dict[str, Any]]]] = None
    if recorded and all(rec.get("Issue_Url") for rec in recorded):
        by_url = {}
        for rec in recorded:
            by_url.setdefault(rec["Issue_Url"], []).append(rec)
    bank, labels, n_anchors = predictor.encode_bank(list(candidate_instances))
    predictor.warmup_bank_shapes(bank)
    sink = (
        JsonlSink(Path(out_dir) / SHADOW_DELTAS_NAME)
        if out_dir is not None else None
    )
    stats = _DeltaStats()
    skew = 0
    try:
        instances = reader.read(str(corpus_path), split=split)
        pending: List[Tuple[int, str, Dict[str, Any]]] = []

        def flush() -> None:
            rows = score_texts(
                predictor, [t for _, t, _ in pending], bank, n_anchors
            )
            for (index, _, rec), row in zip(pending, rows):
                preds = rec.get("predict") or {}
                active_score = max(preds.values()) if preds else 0.0
                active_anchor = (
                    max(preds, key=preds.get) if preds else None
                )
                record = _delta_row(
                    index, active_score, active_anchor, "recorded",
                    row, labels, candidate_version, threshold,
                )
                stats.update(record)
                tel.counter("bank.shadow_sampled").inc()
                if record["flip"]:
                    tel.counter("bank.shadow_flips").inc()
                if sink is not None:
                    sink.emit(record)
            pending.clear()

        for i, inst in enumerate(instances):
            if by_url is not None:
                url = (inst.get("meta") or {}).get("Issue_Url")
                queue = by_url.get(url)
                if not queue:
                    skew += 1
                    continue
                rec = queue.pop(0)
            elif i < len(recorded):
                rec = recorded[i]
            else:
                skew += 1
                continue
            pending.append((i, inst["text1"], rec))
            if len(pending) >= batch:
                flush()
        if pending:
            flush()
    finally:
        if sink is not None:
            sink.close()
    summary = stats.summary()
    summary.update(
        candidate_version=candidate_version,
        recorded_rows=len(recorded),
        corpus_rows_unmatched=skew,
        mode="replay",
    )
    if skew:
        logger.warning(
            "replay: corpus has %d more row(s) than the recorded results "
            "— the run being replayed was truncated or the corpus changed",
            skew,
        )
    return summary
