"""Render a run directory's telemetry into a human summary.

``python -m memvul_tpu telemetry-report <run_dir>`` — the operator's
first stop on any run that died, stalled, or just finished: a phase
table, step-time percentiles, counter totals, and the last-heartbeat
age, all reconstructed from whatever subset of the three sink files
survived (a SIGKILLed run legitimately leaves only a torn
``events.jsonl`` and a stale ``HEARTBEAT.json`` — the report renders
those too, it never requires a clean shutdown).
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Dict, List, Optional, Union

from .sinks import HeartbeatFile, SummaryFile, read_jsonl


def load_run(run_dir: Union[str, Path]) -> Dict[str, Any]:
    """Everything readable from a run dir's telemetry sinks."""
    run_dir = Path(run_dir)
    events, skipped = read_jsonl(run_dir / "events.jsonl")
    return {
        "run_dir": run_dir,
        "events": events,
        "events_skipped": skipped,
        "summary": SummaryFile(run_dir / "telemetry.json").read(),
        "heartbeat": HeartbeatFile(run_dir / "HEARTBEAT.json").read(),
    }


def _span_table(events: List[Dict[str, Any]]) -> Dict[str, Dict[str, float]]:
    """Aggregate ``span`` events by name: count / total / mean / max."""
    table: Dict[str, Dict[str, float]] = {}
    for ev in events:
        if ev.get("kind") != "span":
            continue
        name = str(ev.get("name"))
        try:
            dur = float(ev.get("dur_s", 0.0))
        except (TypeError, ValueError):
            continue
        row = table.setdefault(name, {"count": 0.0, "total_s": 0.0, "max_s": 0.0})
        row["count"] += 1
        row["total_s"] += dur
        row["max_s"] = max(row["max_s"], dur)
    for row in table.values():
        row["mean_s"] = row["total_s"] / row["count"] if row["count"] else 0.0
    return table


def _fmt_s(v: Optional[float]) -> str:
    """Seconds for display; tolerates junk (a hand-edited or corrupted
    sink value must degrade to "-", never crash the report — the report
    is the post-mortem tool, it has no one to crash to)."""
    try:
        return f"{float(v):.3f}s"
    except (TypeError, ValueError):
        return "-"


def _fmt_num(v: Any) -> str:
    if isinstance(v, float):
        return f"{v:.6g}"
    return str(v)


_ROUTER_EVENT_KINDS = frozenset({
    "router_start", "router_drained", "replica_dead", "replica_restart",
    "replica_restart_failed", "rolling_swap_start", "rolling_swap_done",
    "replica_swap_begin", "replica_swap_done",
})


def _replica_rows(
    run_dir: Path, events: List[Dict[str, Any]], now: float
) -> Dict[str, Any]:
    """The data behind the REPLICAS section (and the ``replicas`` block
    of the ``--json`` report): per-replica counter rows from each
    ``replica-<i>/`` subdir's own sinks, plus the router's lifecycle
    event tallies from the main stream."""
    replica_dirs = sorted(
        d for d in run_dir.glob("replica-*") if d.is_dir()
    )
    router_events = [
        ev for ev in events if ev.get("kind") in _ROUTER_EVENT_KINDS
    ]
    restarts: Dict[str, int] = {}
    deaths: Dict[str, int] = {}
    for ev in router_events:
        name = str(ev.get("replica", "?"))
        if ev.get("kind") == "replica_restart":
            restarts[name] = restarts.get(name, 0) + 1
        elif ev.get("kind") == "replica_dead":
            deaths[name] = deaths.get(name, 0) + 1
    rows: List[Dict[str, Any]] = []
    for replica_dir in replica_dirs:
        name = replica_dir.name
        sub = load_run(replica_dir)
        counters = dict((sub["summary"] or {}).get("counters") or {})
        if not counters:
            counters = dict((sub["heartbeat"] or {}).get("counters") or {})
        if not (sub["events"] or sub["summary"] or sub["heartbeat"]):
            rows.append({"name": name, "recorded": False})
            continue
        heartbeat = sub["heartbeat"] or {}
        try:
            age: Optional[float] = now - float(heartbeat.get("written_wall"))
        except (TypeError, ValueError):
            age = None
        rows.append({
            "name": name,
            "recorded": True,
            "heartbeat_age_s": age,
            "served": counters.get("serve.served", 0),
            "shed": counters.get("serve.shed", 0),
            "errors": counters.get("serve.errors", 0),
            "restarts": counters.get(
                "replica.restarts", restarts.get(name, 0)
            ),
        })
    return {
        "router_events": len(router_events),
        "deaths": sum(deaths.values()),
        "restarts": sum(restarts.values()),
        "members": rows,
    }


def _replica_section(
    run_dir: Path, events: List[Dict[str, Any]], now: float
) -> List[str]:
    """Per-replica rows for a scale-out serving run dir (serve
    ``--replicas``): each ``replica-<i>/`` subdir carries that
    replica's own PR 3 sinks, and the main event stream carries the
    router's lifecycle events.  Rendered whenever either is present; a
    replica that never wrote events (killed before its first flush, or
    telemetry disabled) renders as an explicit "(no telemetry
    recorded)" row instead of vanishing — its absence is exactly the
    post-mortem signal."""
    data = _replica_rows(run_dir, events, now)
    if not (data["members"] or data["router_events"]):
        return []
    lines = ["REPLICAS"]
    if data["router_events"]:
        lines.append(
            f"  router events: {data['router_events']}"
            + (f"  deaths: {data['deaths']}" if data["deaths"] else "")
            + (f"  restarts: {data['restarts']}" if data["restarts"] else "")
        )
    for row in data["members"]:
        if not row["recorded"]:
            lines.append(f"  {row['name']}: (no telemetry recorded)")
            continue
        lines.append(
            f"  {row['name']}: heartbeat {_fmt_s(row['heartbeat_age_s'])} ago"
            f"  served={_fmt_num(row['served'])}"
            f"  shed={_fmt_num(row['shed'])}"
            f"  errors={_fmt_num(row['errors'])}"
            f"  restarts={_fmt_num(row['restarts'])}"
        )
    return lines


_SHARD_EVENT_KINDS = frozenset({
    "shard_start", "shard_restart", "shard_dead", "shard_stalled",
    "shard_quarantined", "shard_done", "merge_verified",
})


def _shard_rows(
    run_dir: Path, events: List[Dict[str, Any]], now: float
) -> Dict[str, Any]:
    """The data behind the SHARDS section (and the ``shards`` block of
    the ``--json`` report): per-shard progress rows from each
    ``shard-<i>/`` subdir's own sinks (a sharded ``score-corpus`` run,
    docs/full_corpus.md), plus the coordinator's lifecycle event tallies
    from the main stream — the ``_replica_rows`` pattern applied to the
    offline map-reduce tier."""
    shard_dirs = sorted(d for d in run_dir.glob("shard-*") if d.is_dir())
    shard_events = [
        ev for ev in events if ev.get("kind") in _SHARD_EVENT_KINDS
    ]
    restarts: Dict[str, int] = {}
    quarantined: Dict[str, bool] = {}
    done: Dict[str, bool] = {}
    for ev in shard_events:
        name = str(ev.get("shard", "?"))
        if ev.get("kind") == "shard_restart":
            restarts[name] = restarts.get(name, 0) + 1
        elif ev.get("kind") == "shard_quarantined":
            quarantined[name] = True
        elif ev.get("kind") == "shard_done":
            done[name] = True
    rows: List[Dict[str, Any]] = []
    for shard_dir in shard_dirs:
        name = shard_dir.name
        sub = load_run(shard_dir)
        if not (sub["events"] or sub["summary"] or sub["heartbeat"]):
            rows.append({"name": name, "recorded": False})
            continue
        heartbeat = sub["heartbeat"] or {}
        counters = dict((sub["summary"] or {}).get("counters") or {})
        if not counters:
            counters = dict(heartbeat.get("counters") or {})
        try:
            age: Optional[float] = now - float(heartbeat.get("written_wall"))
        except (TypeError, ValueError):
            age = None
        committed = heartbeat.get("rows_scored")
        if committed is None:
            committed = counters.get("journal.rows_committed", 0)
        rows.append({
            "name": name,
            "recorded": True,
            "heartbeat_age_s": age,
            "rows_committed": committed,
            "retries": counters.get("resilience.retries", 0),
            "restarts": restarts.get(name, 0),
            "quarantined": quarantined.get(name, False),
            "done": done.get(name, False),
        })
    return {
        "coordinator_events": len(shard_events),
        "restarts": sum(restarts.values()),
        "quarantined": sum(quarantined.values()),
        "members": rows,
    }


def _shard_section(
    run_dir: Path, events: List[Dict[str, Any]], now: float
) -> List[str]:
    """Per-shard rows for a sharded corpus-scoring run dir.  Always
    rendered (the PROGRAMS pattern): a pre-existing run dir — or a
    single-process one — says "(no shards recorded)" explicitly rather
    than leaving the operator to wonder whether the section was
    dropped.  A shard that never wrote telemetry (killed before its
    first heartbeat) renders as an explicit row — its silence is the
    post-mortem signal."""
    data = _shard_rows(run_dir, events, now)
    lines = ["SHARDS"]
    if not (data["members"] or data["coordinator_events"]):
        lines.append("  (no shards recorded)")
        return lines
    if data["coordinator_events"]:
        lines.append(
            f"  coordinator events: {data['coordinator_events']}"
            + (f"  restarts: {data['restarts']}" if data["restarts"] else "")
            + (f"  quarantined: {data['quarantined']}"
               if data["quarantined"] else "")
        )
    for row in data["members"]:
        if not row["recorded"]:
            lines.append(f"  {row['name']}: (no telemetry recorded)")
            continue
        status = (
            "quarantined" if row["quarantined"]
            else "done" if row["done"] else "running"
        )
        lines.append(
            f"  {row['name']}: heartbeat {_fmt_s(row['heartbeat_age_s'])} ago"
            f"  rows={_fmt_num(row['rows_committed'])}"
            f"  retries={_fmt_num(row['retries'])}"
            f"  restarts={_fmt_num(row['restarts'])}"
            f"  {status}"
        )
    return lines


def _anchor_bank_section(
    run_dir: Path, counters: Dict[str, Any], summary: Dict[str, Any]
) -> List[str]:
    """Per-anchor win/score/drift table (docs/anchor_bank.md): the
    serving path counts ``bank.anchor_wins.<id>`` and samples
    ``bank.anchor_score.<id>`` per served decision; a pinned
    ``anchor_baseline.json`` beside the sinks turns win shares into a
    drift column, so a degrading anchor is visible before it costs
    recall.  Shadow-scoring counters render as one summary line."""
    wins: Dict[str, float] = {}
    for name, value in counters.items():
        if name.startswith("bank.anchor_wins."):
            try:
                wins[name[len("bank.anchor_wins."):]] = float(value)
            except (TypeError, ValueError):
                continue
    shadow = {
        key: counters.get(f"bank.shadow_{key}", 0)
        for key in ("sampled", "flips", "errors", "dropped")
    }
    has_shadow = any(_as_num(v) for v in shadow.values())
    if not (wins or has_shadow):
        return []
    lines = ["ANCHOR BANK"]
    if wins:
        total = sum(wins.values())
        hists = summary.get("histograms") or {}
        baseline = None
        try:
            from ..bankops.drift import load_baseline

            baseline = load_baseline(run_dir / "anchor_baseline.json")
        except Exception:  # pragma: no cover - report must always render
            baseline = None
        gauges = summary.get("gauges") or {}
        drift_line = f"  decisions: {int(total)}"
        if gauges.get("bank.anchor_drift") is not None:
            drift_line += (
                f"  drift(gauge): {_fmt_num(gauges['bank.anchor_drift'])}"
            )
        if baseline and total > 0:
            shares = {k: v / total for k, v in wins.items()}
            keys = set(shares) | set(baseline)
            tv = 0.5 * sum(
                abs(shares.get(k, 0.0) - baseline.get(k, 0.0)) for k in keys
            )
            drift_line += f"  drift(vs baseline): {tv:.3f}"
        lines.append(drift_line)
        lines.append(
            f"  {'anchor':<24} {'wins':>8} {'share':>7} {'score p50':>10}"
            f" {'score max':>10}" + ("  Δshare" if baseline else "")
        )
        ranked = sorted(wins, key=lambda a: -wins[a])
        for anchor in ranked[:20]:
            count = wins[anchor]
            share = count / total if total else 0.0
            h = hists.get(f"bank.anchor_score.{anchor}") or {}
            row = (
                f"  {anchor:<24} {int(count):>8} {share:>6.1%}"
                f" {_fmt_num(h.get('p50', '-')):>10}"
                f" {_fmt_num(h.get('max', '-')):>10}"
            )
            if baseline:
                row += f"  {share - baseline.get(anchor, 0.0):+.3f}"
            lines.append(row)
        if len(ranked) > 20:
            lines.append(f"  (+{len(ranked) - 20} more anchors)")
    if has_shadow:
        sampled = _as_num(shadow["sampled"])
        flips = _as_num(shadow["flips"])
        lines.append(
            f"  shadow: sampled={int(sampled)} flips={int(flips)}"
            + (f" flip_rate={flips / sampled:.4f}" if sampled else "")
            + f" errors={int(_as_num(shadow['errors']))}"
            + f" dropped={int(_as_num(shadow['dropped']))}"
        )
    return lines


def _as_num(v: Any) -> float:
    try:
        return float(v)
    except (TypeError, ValueError):
        return 0.0


def _load_programs(
    run_dir: Path, events: List[Dict[str, Any]]
) -> Dict[str, Any]:
    """The compiled-program table for the PROGRAMS/ROOFLINE sections.

    Preferred source: ``programs.json`` (telemetry/programs.py
    ``write_programs`` — full records incl. invocation counts and the
    roofline aggregate).  A run killed before that file landed still
    has its per-compile ``program`` events in the stream, so those
    reconstruct a partial table (no invocation/roofline data).  A
    pre-registry run dir has neither and renders "(no programs
    recorded)"."""
    import json

    path = run_dir / "programs.json"
    if path.exists():
        try:
            payload = json.loads(path.read_text())
            programs = payload.get("programs") or []
            if isinstance(programs, list):
                return {
                    "source": "programs.json",
                    "programs": programs,
                    "roofline": payload.get("roofline"),
                }
        except (OSError, ValueError):  # torn write → fall back to events
            pass
    rows: List[Dict[str, Any]] = []
    for ev in events:
        if ev.get("kind") != "program":
            continue
        rows.append({
            "key": ev.get("key"),
            "scope": ev.get("scope"),
            "compile_s": ev.get("compile_s"),
            "flops": ev.get("flops"),
            "bytes_accessed": ev.get("bytes_accessed"),
            "hbm_bytes": ev.get("hbm_bytes"),
            "device_kind": ev.get("device_kind"),
        })
    return {
        "source": "events" if rows else None,
        "programs": rows,
        "roofline": None,
    }


def _programs_section(programs: Dict[str, Any]) -> List[str]:
    """PROGRAMS + ROOFLINE text rendering; always emits the PROGRAMS
    header so an operator sees explicitly when a run predates the
    program registry."""
    lines = ["PROGRAMS (compiled XLA executables)"]
    rows = programs["programs"]
    if not rows:
        lines.append("  (no programs recorded)")
        return lines
    if programs["source"] == "events":
        lines.append(
            "  (reconstructed from program events — programs.json "
            "missing; invocation counts unavailable)"
        )
    lines.append(
        f"  {'key':<40} {'scope':<7} {'compile':>9} {'flops':>12}"
        f" {'hbm_bytes':>12} {'calls':>7}"
    )
    for row in rows[:20]:
        lines.append(
            f"  {str(row.get('key'))[:40]:<40}"
            f" {str(row.get('scope', '-')):<7}"
            f" {_fmt_s(row.get('compile_s')):>9}"
            f" {_fmt_num(row.get('flops', '-')):>12}"
            f" {_fmt_num(row.get('hbm_bytes', '-')):>12}"
            f" {_fmt_num(row.get('invocations', '-')):>7}"
        )
    if len(rows) > 20:
        lines.append(f"  (+{len(rows) - 20} more programs)")
    roof = programs.get("roofline")
    if roof:
        lines.append("")
        lines.append("ROOFLINE")
        lines.append(
            f"  device: {roof.get('device_kind', '?')}"
            + ("  (interpret-only — no peak spec, MFU unavailable)"
               if roof.get("interpret_only") else "")
        )
        lines.append(
            f"  programs: {_fmt_num(roof.get('programs', 0))}"
            f"  flops_total: {_fmt_num(roof.get('flops_total', 0))}"
            f"  device_time: {_fmt_s(roof.get('device_time_s'))}"
        )
        if roof.get("mfu") is not None:
            lines.append(
                f"  mfu: {_as_num(roof.get('mfu')):.4f}"
                f"  membw_util: {_fmt_num(roof.get('membw_util', '-'))}"
                f"  achieved_flops_per_s:"
                f" {_fmt_num(roof.get('achieved_flops_per_s', '-'))}"
            )
    return lines


# the per-request journey stages (serving/service.py tracing): together
# they partition enqueued→resolved, so their totals decompose serve
# latency into WHERE a request spent its time
_LATENCY_STAGES = (
    ("queue_wait", "serve.queue_wait_s"),
    ("pack", "serve.pack_s"),
    ("device", "serve.device_s"),
    ("resolve", "serve.resolve_s"),
)


def _latency_decomposition(
    histograms: Dict[str, Any],
) -> Dict[str, Dict[str, Any]]:
    """Stage rows (count/mean/p50/p95/share) from the serve stage
    histograms; empty when the run never traced (sampling off)."""
    rows: Dict[str, Dict[str, Any]] = {}
    total = 0.0
    for stage, metric in _LATENCY_STAGES:
        h = histograms.get(metric) or {}
        if not _as_num(h.get("count")):
            continue
        rows[stage] = {
            "metric": metric,
            "count": int(_as_num(h.get("count"))),
            "total_s": _as_num(h.get("total")),
            "mean_s": _as_num(h.get("mean")),
            "p50_s": h.get("p50"),
            "p95_s": h.get("p95"),
        }
        total += _as_num(h.get("total"))
    for row in rows.values():
        row["share"] = row["total_s"] / total if total > 0 else 0.0
    return rows


def _latency_section(histograms: Dict[str, Any]) -> List[str]:
    rows = _latency_decomposition(histograms)
    if not rows:
        return []
    lines = ["LATENCY DECOMPOSITION (request-journey stages)"]
    lines.append(
        f"  {'stage':<12} {'count':>7} {'mean':>10} {'p50':>10}"
        f" {'p95':>10} {'share':>7}"
    )
    for stage, _metric in _LATENCY_STAGES:
        row = rows.get(stage)
        if row is None:
            continue
        lines.append(
            f"  {stage:<12} {row['count']:>7}"
            f" {_fmt_s(row['mean_s']):>10}"
            f" {_fmt_s(row['p50_s']):>10}"
            f" {_fmt_s(row['p95_s']):>10}"
            f" {row['share']:>6.1%}"
        )
    return lines


def _derived_metrics(counters: Dict[str, Any]) -> Dict[str, float]:
    """The report-derived ratios (documented as ``derived`` in the
    metric catalog) — shared by the text COUNTERS section and the
    ``--json`` report."""
    out: Dict[str, float] = {}
    hits = _as_num(counters.get("data.encode_cache_hits"))
    misses = _as_num(counters.get("data.encode_cache_misses"))
    if hits + misses > 0:
        out["data.encode_cache_hit_rate"] = hits / (hits + misses)
    real = _as_num(counters.get("serve.tokens_real"))
    padded = _as_num(counters.get("serve.tokens_padded"))
    if padded > 0:
        out["serve.real_token_utilization"] = real / padded
    topups = _as_num(counters.get("serve.pack_topups"))
    served = _as_num(counters.get("serve.served"))
    if topups > 0 and served > 0:
        # continuous admission only: the fraction of served requests
        # that joined a pack while the device was busy with another —
        # how much of the load actually overlapped the round-trip
        out["serve.admission_efficiency"] = topups / served
    rescored = _as_num(counters.get("serve.cascade_rescored"))
    shortcut = _as_num(counters.get("serve.cascade_shortcircuit"))
    if rescored + shortcut > 0:
        # cascade dispatch only: the fraction of served requests whose
        # int8 score landed inside the uncertainty band and paid the
        # fp32 rescore (docs/quantized_serving.md)
        out["serve.cascade_rescore_rate"] = rescored / (rescored + shortcut)
    cache_hits = _as_num(counters.get("cache.hits"))
    cache_misses = _as_num(counters.get("cache.misses"))
    if cache_hits + cache_misses > 0:
        # admission cache only (serving/admission_cache.py): the share
        # of probed requests answered without a device call
        out["cache.hit_rate"] = cache_hits / (cache_hits + cache_misses)
    return out


def _cascade_block(
    counters: Dict[str, Any], programs: List[Dict[str, Any]]
) -> Optional[Dict[str, Any]]:
    """The ``cascade`` block of the ``--json`` report (and the CASCADE
    text section): the tier split the CascadeDispatcher's counters
    record, plus each tier's share of device time read from the program
    registry's scope split (``score_int8:*`` = the int8 tier,
    ``score:*`` = the fp32 tier).  None when the run never dispatched a
    cascade batch."""
    rescored = _as_num(counters.get("serve.cascade_rescored"))
    shortcut = _as_num(counters.get("serve.cascade_shortcircuit"))
    total = rescored + shortcut
    if total <= 0:
        return None
    tiers: Dict[str, Dict[str, float]] = {}
    for row in programs or []:
        scope = row.get("scope")
        tier = {"score_int8": "int8", "score": "fp32"}.get(scope)
        if tier is None:
            continue
        t = tiers.setdefault(
            tier, {"programs": 0.0, "invocations": 0.0, "device_time_s": 0.0}
        )
        t["programs"] += 1
        t["invocations"] += _as_num(row.get("invocations"))
        t["device_time_s"] += _as_num(row.get("device_time_s"))
    device_total = sum(t["device_time_s"] for t in tiers.values())
    for t in tiers.values():
        t["device_time_share"] = (
            t["device_time_s"] / device_total if device_total > 0 else 0.0
        )
    return {
        "rescored": int(rescored),
        "shortcircuit": int(shortcut),
        "rescore_rate": rescored / total,
        "tiers": tiers,
    }


def _fleet_block(
    counters: Dict[str, Any], gauges: Dict[str, Any]
) -> Optional[Dict[str, Any]]:
    """The ``fleet`` block of the ``--json`` report (and the FLEET text
    section): the cross-host balancer's request/supervision counters
    plus the per-host heartbeat-age gauges its monitor republishes
    (serving/fleet.py).  None when the run had no host balancer."""
    fleet = {
        k.split(".", 1)[1]: v for k, v in counters.items()
        if k.startswith("fleet.")
    }
    heartbeat_ages = {
        k.split("fleet.heartbeat_age_s.", 1)[1]: v
        for k, v in gauges.items()
        if k.startswith("fleet.heartbeat_age_s.")
    }
    hosts = gauges.get("fleet.hosts")
    if not fleet and hosts is None:
        return None
    out: Dict[str, Any] = {
        "hosts": hosts,
        "hosts_alive": gauges.get("fleet.hosts_alive"),
        "counters": fleet,
    }
    if heartbeat_ages:
        out["heartbeat_age_s"] = heartbeat_ages
    return out


def _autoscaler_block(
    counters: Dict[str, Any],
    gauges: Dict[str, Any],
    events: Optional[List[Dict[str, Any]]] = None,
) -> Optional[Dict[str, Any]]:
    """The ``autoscaler`` block of the ``--json`` report (and the
    AUTOSCALER text section): scale_hint actuation totals plus the
    persisted ``scaler_decision`` trajectory (one event per control
    tick — the in-memory decision deque dies with the process; these
    survive in ``events.jsonl``).  None when no autoscaler ran."""
    scaler = {
        k.split(".", 1)[1]: v for k, v in counters.items()
        if k.startswith("scaler.")
    }
    replicas = gauges.get("scaler.replicas")
    decisions = [
        ev for ev in (events or []) if ev.get("kind") == "scaler_decision"
    ]
    if not scaler and replicas is None and not decisions:
        return None
    out: Dict[str, Any] = {
        "replicas": replicas,
        "hint": gauges.get("scaler.hint"),
        "counters": scaler,
    }
    if decisions:
        acted = [d for d in decisions if d.get("action")]
        out["decisions"] = {
            "ticks": len(decisions),
            "acted": len(acted),
            "last_actions": [
                {
                    "t_s": d.get("t_s"),
                    "action": d.get("action"),
                    "replicas": d.get("replicas"),
                    "hint": d.get("hint"),
                    "burn_rate_fast": d.get("burn_rate_fast"),
                }
                for d in acted[-8:]
            ],
        }
    return out


_ALERT_EVENT_KINDS = frozenset({"alert_fired", "alert_resolved"})


def _alerts_block(
    events: List[Dict[str, Any]],
) -> Optional[Dict[str, Any]]:
    """The ``alerts`` block of the ``--json`` report (and the ALERTS
    text section): alert-rule transitions replayed from the event
    stream (telemetry/alerts.py emits one ``alert_fired`` /
    ``alert_resolved`` event per edge).  A rule that fired without a
    matching resolve was still firing when the run ended — exactly the
    post-mortem lead.  None when no alert engine ran."""
    transitions = [
        ev for ev in events if ev.get("kind") in _ALERT_EVENT_KINDS
    ]
    if not transitions:
        return None
    fired = resolved = 0
    open_rules: Dict[str, Dict[str, Any]] = {}
    for ev in transitions:
        rule = str(ev.get("rule", "?"))
        if ev.get("kind") == "alert_fired":
            fired += 1
            open_rules[rule] = ev
        else:
            resolved += 1
            open_rules.pop(rule, None)
    return {
        "fired": fired,
        "resolved": resolved,
        "still_firing": sorted(open_rules),
        "transitions": [
            {
                k: v for k, v in ev.items()
                if k not in ("t", "mono", "phase")
            }
            for ev in transitions[-10:]
        ],
    }


def load_incidents(run_dir: Union[str, Path]) -> List[Dict[str, Any]]:
    """Summarize every incident bundle under ``run_dir/incidents/``
    (serving/incident.py writes them; this reader lives in the
    telemetry layer so ``telemetry-report`` never imports the serving
    package).  Torn or missing bundle files degrade per-bundle — the
    report is the post-mortem tool, it has no one to crash to."""
    import json

    incidents_dir = Path(run_dir) / "incidents"
    out: List[Dict[str, Any]] = []
    if not incidents_dir.is_dir():
        return out
    for bundle in sorted(p for p in incidents_dir.iterdir() if p.is_dir()):
        record: Dict[str, Any] = {"bundle": bundle.name}
        try:
            manifest = json.loads((bundle / "manifest.json").read_text())
            record["trigger"] = manifest.get("trigger")
            record["wall"] = manifest.get("wall")
            alerts = manifest.get("alerts")
            if isinstance(alerts, dict):
                record["firing"] = [
                    str(r.get("rule", "?"))
                    for r in alerts.get("firing") or []
                ]
            record["detail"] = manifest.get("detail")
        except (OSError, ValueError) as exc:
            record["error"] = f"{type(exc).__name__}: {exc}"
        try:
            history = json.loads(
                (bundle / "metrics.json").read_text()
            ).get("history") or {}
            record["series"] = len(history)
        except (OSError, ValueError, AttributeError):
            record["series"] = 0
        try:
            traces = json.loads((bundle / "traces.json").read_text())
            record["traces"] = len(traces) if isinstance(traces, list) else 0
        except (OSError, ValueError):
            record["traces"] = 0
        out.append(record)
    return out


def report_json(
    run_dir: Union[str, Path], now: Optional[float] = None
) -> Dict[str, Any]:
    """The machine-readable report (``telemetry-report --json``) — the
    same sinks the text report renders, as one stable-schema dict so
    bench/CI consume run summaries without scraping table text.  Top
    keys are pinned by tests (the ``lint --json`` pattern): ``schema``,
    ``run_dir``, ``events``, ``heartbeat``, ``spans``, ``counters``,
    ``gauges``, ``histograms``, ``derived``, ``latency_decomposition``,
    ``cascade``, ``fleet``, ``autoscaler``, ``alerts``, ``incidents``,
    ``replicas``, ``shards``, ``programs``, ``roofline``."""
    data = load_run(run_dir)
    now = time.time() if now is None else now
    summary = data["summary"]
    heartbeat = data["heartbeat"]
    counters = dict(summary.get("counters") or {})
    if not counters:
        counters = dict((heartbeat or {}).get("counters") or {})
    histograms = dict(summary.get("histograms") or {})
    try:
        heartbeat_age: Optional[float] = now - float(
            heartbeat.get("written_wall")
        )
    except (TypeError, ValueError):
        heartbeat_age = None
    programs = _load_programs(data["run_dir"], data["events"])
    return {
        "schema": 1,
        "run_dir": str(data["run_dir"]),
        "generated_wall": now,
        "events": {
            "parsed": len(data["events"]),
            "skipped": data["events_skipped"],
        },
        "heartbeat": (
            dict(heartbeat, age_s=heartbeat_age) if heartbeat else None
        ),
        "spans": _span_table(data["events"]),
        "counters": counters,
        "gauges": dict(summary.get("gauges") or {}),
        "histograms": histograms,
        "derived": _derived_metrics(counters),
        "latency_decomposition": _latency_decomposition(histograms),
        "cascade": _cascade_block(counters, programs["programs"]),
        "fleet": _fleet_block(counters, dict(summary.get("gauges") or {})),
        "autoscaler": _autoscaler_block(
            counters, dict(summary.get("gauges") or {}), data["events"]
        ),
        "alerts": _alerts_block(data["events"]),
        "incidents": load_incidents(data["run_dir"]),
        "replicas": _replica_rows(data["run_dir"], data["events"], now),
        "shards": _shard_rows(data["run_dir"], data["events"], now),
        "programs": programs["programs"],
        "roofline": programs["roofline"],
    }


def render_report(run_dir: Union[str, Path], now: Optional[float] = None) -> str:
    """The human summary as one string (the CLI prints it verbatim)."""
    data = load_run(run_dir)
    events = data["events"]
    summary = data["summary"]
    heartbeat = data["heartbeat"]
    now = time.time() if now is None else now

    lines: List[str] = []
    lines.append(f"telemetry report: {data['run_dir']}")
    lines.append(
        f"  events: {len(events)} parsed"
        + (f", {data['events_skipped']} torn/unparseable skipped"
           if data["events_skipped"] else "")
    )
    if not (events or summary or heartbeat):
        # distinguish "this dir never had telemetry" from "a run started
        # but recorded nothing" (empty/blank sink files — e.g. a server
        # that was killed before its first event, or telemetry started
        # and immediately torn) — the operator's next step differs
        sink_files = [
            name for name in ("events.jsonl", "telemetry.json", "HEARTBEAT.json")
            if (data["run_dir"] / name).exists()
        ]
        if sink_files:
            lines.append(
                "  no events recorded (empty sink file(s): "
                + ", ".join(sink_files) + ")"
            )
        else:
            lines.append("  (no telemetry sinks found in this directory)")
        # a fleet run dir may carry per-replica sinks even when the
        # router process itself recorded nothing — still render them
        replica_lines = _replica_section(data["run_dir"], events, now)
        if replica_lines:
            lines.append("")
            lines.extend(replica_lines)
        # likewise shard-<i>/ sinks from a coordinator killed before its
        # first event flush
        shard_data = _shard_rows(data["run_dir"], events, now)
        if shard_data["members"] or shard_data["coordinator_events"]:
            lines.append("")
            lines.extend(_shard_section(data["run_dir"], events, now))
        return "\n".join(lines)
    if not events:
        # heartbeat-/summary-only dirs (a SIGKILL before the first event
        # flush, or events disabled) still render the sections below —
        # but say explicitly that the event stream is empty rather than
        # silently omitting the phase table
        lines.append("  no events recorded — phase table unavailable")

    # -- liveness -------------------------------------------------------------
    if heartbeat:
        try:
            age: Optional[float] = now - float(heartbeat.get("written_wall"))
        except (TypeError, ValueError):
            age = None
        lines.append("")
        lines.append("HEARTBEAT")
        lines.append(
            f"  phase: {heartbeat.get('phase', '?')}"
            f"  pid: {heartbeat.get('pid', '?')}"
            f"  uptime: {_fmt_s(heartbeat.get('uptime_s'))}"
        )
        lines.append(
            f"  last written: {_fmt_s(age)} ago"
            + ("  (stale?)" if age is not None and age > 300 else "")
        )
        for key in ("rows_per_sec", "eta_s"):
            if key in heartbeat and heartbeat[key] is not None:
                lines.append(f"  {key}: {_fmt_num(heartbeat[key])}")

    # -- phases ---------------------------------------------------------------
    spans = _span_table(events)
    if spans:
        lines.append("")
        lines.append("PHASES (spans)")
        lines.append(
            f"  {'name':<28} {'count':>6} {'total':>10} {'mean':>10} {'max':>10}"
        )
        for name in sorted(spans, key=lambda n: -spans[n]["total_s"]):
            row = spans[name]
            lines.append(
                f"  {name:<28} {int(row['count']):>6}"
                f" {_fmt_s(row['total_s']):>10}"
                f" {_fmt_s(row['mean_s']):>10}"
                f" {_fmt_s(row['max_s']):>10}"
            )

    # -- timing histograms ----------------------------------------------------
    hists = {
        name: h
        for name, h in (summary.get("histograms") or {}).items()
        if h and not name.startswith("span.")
    }
    if hists:
        lines.append("")
        lines.append("TIMINGS")
        for name in sorted(hists):
            h = hists[name]
            lines.append(
                f"  {name}: count={int(h.get('count', 0))}"
                f" mean={_fmt_num(h.get('mean'))}"
                f" p50={_fmt_num(h.get('p50'))}"
                f" p95={_fmt_num(h.get('p95'))}"
                f" max={_fmt_num(h.get('max'))}"
            )

    # -- serve latency decomposition (request-journey tracing) -----------------
    latency_lines = _latency_section(summary.get("histograms") or {})
    if latency_lines:
        lines.append("")
        lines.extend(latency_lines)

    # -- counters / gauges ----------------------------------------------------
    counters = dict(summary.get("counters") or {})
    if not counters:
        counters = dict(heartbeat.get("counters") or {})
    if counters:
        lines.append("")
        lines.append("COUNTERS")
        for name in sorted(counters):
            lines.append(f"  {name} = {_fmt_num(counters[name])}")
        # derived: host-side tokenization cache effectiveness — the
        # CachedEncoder counters make host tokenization cost attributable
        # (a low hit rate on a pair-training run means the memo is being
        # evicted or the stream has no repeats to exploit)
        try:
            hits = float(counters["data.encode_cache_hits"])
            misses = float(counters["data.encode_cache_misses"])
            total = hits + misses
        except (KeyError, TypeError, ValueError):
            total = 0.0
        if total > 0:
            lines.append(
                f"  data.encode_cache_hit_rate = {hits / total:.3f}"
                f" ({int(hits)}/{int(total)} lookups)"
            )
        # derived: serve-path padding efficiency — real tokens served vs
        # token slots the dispatched shapes paid for (the ragged path's
        # headline number, docs/ragged_serving.md)
        try:
            real = float(counters["serve.tokens_real"])
            padded = float(counters["serve.tokens_padded"])
        except (KeyError, TypeError, ValueError):
            padded = 0.0
        if padded > 0:
            lines.append(
                f"  serve.real_token_utilization = {real / padded:.3f}"
                f" ({int(real)}/{int(padded)} token slots)"
            )
        # derived: continuous-admission overlap — how much of the served
        # load joined a pack while the device was busy with another
        # (continuous dispatcher only; docs/serving.md)
        try:
            topups = float(counters["serve.pack_topups"])
            served = float(counters["serve.served"])
        except (KeyError, TypeError, ValueError):
            topups = served = 0.0
        if topups > 0 and served > 0:
            lines.append(
                f"  serve.admission_efficiency = {topups / served:.3f}"
                f" ({int(topups)}/{int(served)} served admitted mid-flight)"
            )
        # derived: cascade uncertainty-band pressure — served requests
        # whose int8 score needed the fp32 rescore
        # (docs/quantized_serving.md)
        rescored = _as_num(counters.get("serve.cascade_rescored"))
        shortcut = _as_num(counters.get("serve.cascade_shortcircuit"))
        if rescored + shortcut > 0:
            lines.append(
                f"  serve.cascade_rescore_rate ="
                f" {rescored / (rescored + shortcut):.3f}"
                f" ({int(rescored)}/{int(rescored + shortcut)} rescored fp32)"
            )
        # derived: admission-cache yield — probed requests answered from
        # the content-addressed cache without a device call
        # (serving/admission_cache.py, docs/multitenancy.md)
        cache_hits = _as_num(counters.get("cache.hits"))
        cache_misses = _as_num(counters.get("cache.misses"))
        if cache_hits + cache_misses > 0:
            lines.append(
                f"  cache.hit_rate ="
                f" {cache_hits / (cache_hits + cache_misses):.3f}"
                f" ({int(cache_hits)}/{int(cache_hits + cache_misses)}"
                " probes hit)"
            )
    gauges = summary.get("gauges") or {}
    if gauges:
        lines.append("")
        lines.append("GAUGES")
        for name in sorted(gauges):
            lines.append(f"  {name} = {_fmt_num(gauges[name])}")

    # -- anchor bank (per-anchor wins / drift / shadow) ------------------------
    anchor_lines = _anchor_bank_section(data["run_dir"], counters, summary)
    if anchor_lines:
        lines.append("")
        lines.extend(anchor_lines)

    # -- compiled programs / roofline (telemetry/programs.py) ------------------
    programs = _load_programs(data["run_dir"], events)
    lines.append("")
    lines.extend(_programs_section(programs))

    # -- quantized cascade tier split (docs/quantized_serving.md) --------------
    cascade = _cascade_block(counters, programs["programs"])
    if cascade:
        lines.append("")
        lines.append("CASCADE (int8 tier + fp32 rescue band)")
        lines.append(
            f"  shortcircuit(int8): {cascade['shortcircuit']}"
            f"  rescored(fp32): {cascade['rescored']}"
            f"  rescore_rate: {cascade['rescore_rate']:.3f}"
        )
        for tier in ("int8", "fp32"):
            t = cascade["tiers"].get(tier)
            if t is None:
                continue
            lines.append(
                f"  {tier}: programs={int(t['programs'])}"
                f"  invocations={int(t['invocations'])}"
                f"  device_time={_fmt_s(t['device_time_s'])}"
                f"  share={t['device_time_share']:.1%}"
            )

    # -- admission cache (serving/admission_cache.py) --------------------------
    cache_hits = _as_num(counters.get("cache.hits"))
    cache_misses = _as_num(counters.get("cache.misses"))
    if cache_hits + cache_misses > 0:
        lines.append("")
        lines.append("CACHE (content-addressed admission cache)")
        lines.append(
            f"  hits: {int(cache_hits)}  misses: {int(cache_misses)}"
            f"  hit_rate: {cache_hits / (cache_hits + cache_misses):.3f}"
        )
        lines.append(
            f"  evictions: {int(_as_num(counters.get('cache.evictions')))}"
            f"  invalidations:"
            f" {int(_as_num(counters.get('cache.invalidations')))}"
            f"  errors: {int(_as_num(counters.get('cache.errors')))}"
            f"  tokens_saved:"
            f" {int(_as_num(counters.get('cache.tokens_saved')))}"
        )

    # -- cross-host fleet (serving/fleet.py) -----------------------------------
    fleet = _fleet_block(counters, gauges)
    if fleet:
        lines.append("")
        lines.append("FLEET (cross-host balancer)")
        lines.append(
            f"  hosts: {_fmt_num(fleet.get('hosts', '?'))}"
            f"  alive: {_fmt_num(fleet.get('hosts_alive', '?'))}"
        )
        fc = fleet["counters"]
        if fc:
            lines.append(
                f"  requests: {_fmt_num(fc.get('requests', 0))}"
                f"  served: {_fmt_num(fc.get('served', 0))}"
                f"  reroutes: {_fmt_num(fc.get('reroutes', 0))}"
                f"  host_deaths: {_fmt_num(fc.get('host_deaths', 0))}"
                f"  restarts: {_fmt_num(fc.get('host_restarts', 0))}"
                f"  quarantined: {_fmt_num(fc.get('quarantined', 0))}"
            )
        for host, age in sorted(fleet.get("heartbeat_age_s", {}).items()):
            lines.append(f"  {host}: heartbeat_age={_fmt_s(age)}")

    # -- autoscaler (serving/autoscaler.py) ------------------------------------
    scaler = _autoscaler_block(counters, gauges, events)
    if scaler:
        lines.append("")
        lines.append("AUTOSCALER (scale_hint actuation)")
        sc = scaler["counters"]
        lines.append(
            f"  replicas: {_fmt_num(scaler.get('replicas', '?'))}"
            f"  scale_events: {_fmt_num(sc.get('scale_events', 0))}"
            f"  ups: {_fmt_num(sc.get('scale_ups', 0))}"
            f"  downs: {_fmt_num(sc.get('scale_downs', 0))}"
            f"  spawn_failures: {_fmt_num(sc.get('spawn_failures', 0))}"
        )
        decisions = scaler.get("decisions")
        if decisions:
            lines.append(
                f"  decisions: {decisions['ticks']} ticks,"
                f" {decisions['acted']} acted"
            )
            for d in decisions["last_actions"]:
                lines.append(
                    f"    +{_fmt_num(d.get('t_s', '?'))}s"
                    f" {d.get('action')}"
                    f" → {_fmt_num(d.get('replicas', '?'))} replicas"
                    f" (hint={d.get('hint')}"
                    f" burn_fast={_fmt_num(d.get('burn_rate_fast'))})"
                )

    # -- alert-rule transitions (telemetry/alerts.py) --------------------------
    alerts = _alerts_block(events)
    if alerts:
        lines.append("")
        lines.append("ALERTS")
        lines.append(
            f"  fired: {alerts['fired']}  resolved: {alerts['resolved']}"
            + (
                "  STILL FIRING: " + ", ".join(alerts["still_firing"])
                if alerts["still_firing"] else ""
            )
        )
        for ev in alerts["transitions"]:
            if ev.get("kind") == "alert_fired":
                lines.append(
                    f"  fired {ev.get('rule')}:"
                    f" value={_fmt_num(ev.get('value'))}"
                    f" series={ev.get('series')}"
                )
            else:
                lines.append(
                    f"  resolved {ev.get('rule')}:"
                    f" after {_fmt_s(ev.get('duration_s'))}"
                )

    # -- incident bundles (serving/incident.py) --------------------------------
    incidents = load_incidents(data["run_dir"])
    if incidents:
        lines.append("")
        lines.append("INCIDENTS (flight-recorder bundles)")
        for rec in incidents:
            if "error" in rec:
                lines.append(f"  {rec['bundle']}: (torn: {rec['error']})")
                continue
            lines.append(
                f"  {rec['bundle']}: trigger={rec.get('trigger')}"
                f"  series={rec.get('series', 0)}"
                f"  traces={rec.get('traces', 0)}"
                + (
                    "  firing=" + ",".join(rec["firing"])
                    if rec.get("firing") else ""
                )
            )

    # -- replicas (scale-out serving runs) ------------------------------------
    replica_lines = _replica_section(data["run_dir"], events, now)
    if replica_lines:
        lines.append("")
        lines.extend(replica_lines)

    # -- shards (sharded corpus-scoring runs) ---------------------------------
    lines.append("")
    lines.extend(_shard_section(data["run_dir"], events, now))

    # -- last events ----------------------------------------------------------
    if events:
        lines.append("")
        lines.append("LAST EVENTS")
        for ev in events[-5:]:
            kind = ev.get("kind", "?")
            detail = {
                k: v for k, v in ev.items()
                if k not in ("t", "mono", "kind", "phase")
            }
            lines.append(
                f"  +{_fmt_num(ev.get('mono', '?'))}s {kind}"
                + (f" {detail}" if detail else "")
            )
    return "\n".join(lines)
