"""Process-wide run telemetry: counters, gauges, histograms, timed spans.

Every long-running path (train epoch loops, corpus scoring, the bench
phases) reports through one registry instead of each keeping a private
log line, so a supervisor — or ``python -m memvul_tpu telemetry-report``
— sees one coherent picture of a run.  Contract (docs/observability.md):

* **near-zero overhead when disabled** — the accessors hand back shared
  no-op singletons, so instrumented code keeps unconditional ``.inc()``
  / ``.observe()`` calls without per-call branching, and the hot loops
  gate their event emission on ``registry.enabled`` /
  ``registry.step_events`` so a disabled run performs zero additional
  per-step host work;
* **liveness is tracked even when disabled** — :meth:`~TelemetryRegistry
  .progress` updates two in-memory timestamps (monotonic + wall), which
  is what lets the bench watchdog report a heartbeat age in its failure
  record without requiring a run dir;
* **sinks attach only when a run dir is configured** — an append-only
  ``events.jsonl`` stream, a rolled-up ``telemetry.json`` summary, and
  the ``HEARTBEAT.json`` liveness file (see :mod:`.sinks` for the
  torn-write story of each).

The registry is deliberately dependency-light: no jax, no numpy, and no
import of ``resilience`` at load time (resilience modules count *into*
telemetry, so the edge must point one way).
"""

from __future__ import annotations

import contextlib
import os
import random
import threading
import time
from pathlib import Path
from typing import Any, Dict, Iterator, List, Optional, Union

from .sinks import HeartbeatFile, JsonlSink, SummaryFile


class Counter:
    """Monotonic event count (thread-safe — the scoring writer thread
    and the main loop both increment)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value = 0
        self._lock = threading.Lock()

    def inc(self, n: int = 1) -> None:
        with self._lock:
            self._value += n

    @property
    def value(self) -> int:
        return self._value


class Gauge:
    """Last-write-wins scalar (e.g. tokens/sec of the latest epoch)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str) -> None:
        self.name = name
        self._value: Optional[float] = None

    def set(self, value: float) -> None:
        self._value = float(value)

    @property
    def value(self) -> Optional[float]:
        return self._value


class Histogram:
    """Streaming count/sum/min/max plus a bounded reservoir sample for
    percentiles — a 1.2M-batch scoring run must not pin one float per
    observation in host RAM."""

    __slots__ = ("name", "count", "total", "min", "max", "_sample", "_cap", "_rng", "_lock")

    def __init__(self, name: str, cap: int = 4096) -> None:
        self.name = name
        self.count = 0
        self.total = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._sample: List[float] = []
        self._cap = cap
        self._rng = random.Random(0x5EED)
        self._lock = threading.Lock()

    def observe(self, value: float) -> None:
        value = float(value)
        with self._lock:
            self.count += 1
            self.total += value
            if self.min is None or value < self.min:
                self.min = value
            if self.max is None or value > self.max:
                self.max = value
            if len(self._sample) < self._cap:
                self._sample.append(value)
            else:
                # classic reservoir: keep each observation with p=cap/n
                j = self._rng.randrange(self.count)
                if j < self._cap:
                    self._sample[j] = value

    def percentile(self, q: float) -> Optional[float]:
        with self._lock:
            if not self._sample:
                return None
            ordered = sorted(self._sample)
        idx = int(round((len(ordered) - 1) * (q / 100.0)))
        return ordered[max(0, min(idx, len(ordered) - 1))]

    def summary(self) -> Dict[str, float]:
        if not self.count:
            return {}
        out = {
            "count": float(self.count),
            "total": self.total,
            "mean": self.total / self.count,
            "min": self.min,
            "max": self.max,
        }
        for q in (50, 95):
            p = self.percentile(q)
            if p is not None:
                out[f"p{q}"] = p
        return out


class _NullCounter:
    __slots__ = ()
    value = 0

    def inc(self, n: int = 1) -> None:
        pass


class _NullGauge:
    __slots__ = ()
    value = None

    def set(self, value: float) -> None:
        pass


class _NullHistogram:
    __slots__ = ()
    count = 0

    def observe(self, value: float) -> None:
        pass

    def percentile(self, q: float) -> Optional[float]:
        return None

    def summary(self) -> Dict[str, float]:
        return {}


NULL_COUNTER = _NullCounter()
NULL_GAUGE = _NullGauge()
NULL_HISTOGRAM = _NullHistogram()


class TelemetryRegistry:
    """One process-wide bag of named metrics + the liveness clock.

    Use the module-level :func:`get_registry` / :func:`configure` pair;
    constructing a registry directly is for tests.
    """

    def __init__(
        self,
        run_dir: Optional[Union[str, Path]] = None,
        enabled: bool = False,
        events: bool = True,
        heartbeat_every_s: float = 30.0,
        step_events: bool = True,
    ) -> None:
        self.enabled = bool(enabled)
        self.run_dir = Path(run_dir) if run_dir else None
        # per-step event emission (train_step lines in events.jsonl);
        # hot loops read this one attribute as their cadence gate
        self.step_events = bool(step_events) and self.enabled
        self.heartbeat_every_s = float(heartbeat_every_s)
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._histograms: Dict[str, Histogram] = {}
        self._lock = threading.Lock()
        self._phase_stack: List[str] = []
        now_m, now_w = time.monotonic(), time.time()
        self.started_monotonic = now_m
        self.started_wall = now_w
        self.last_progress_monotonic = now_m
        self.last_progress_wall = now_w
        self._last_heartbeat_monotonic = float("-inf")
        self._closed = False
        self._events: Optional[JsonlSink] = None
        self._heartbeat_file: Optional[HeartbeatFile] = None
        self._summary_file: Optional[SummaryFile] = None
        if self.enabled and self.run_dir is not None:
            self.run_dir.mkdir(parents=True, exist_ok=True)
            if events:
                self._events = JsonlSink(self.run_dir / "events.jsonl")
            self._heartbeat_file = HeartbeatFile(self.run_dir / "HEARTBEAT.json")
            self._summary_file = SummaryFile(self.run_dir / "telemetry.json")
            self.event("run_start", pid=os.getpid())

    # -- metric accessors ------------------------------------------------------

    def counter(self, name: str):
        if not self.enabled:
            return NULL_COUNTER
        with self._lock:
            c = self._counters.get(name)
            if c is None:
                c = self._counters[name] = Counter(name)
            return c

    def gauge(self, name: str):
        if not self.enabled:
            return NULL_GAUGE
        with self._lock:
            g = self._gauges.get(name)
            if g is None:
                g = self._gauges[name] = Gauge(name)
            return g

    def histogram(self, name: str):
        if not self.enabled:
            return NULL_HISTOGRAM
        with self._lock:
            h = self._histograms.get(name)
            if h is None:
                h = self._histograms[name] = Histogram(name)
            return h

    # -- liveness --------------------------------------------------------------

    @property
    def phase(self) -> str:
        return self._phase_stack[-1] if self._phase_stack else "idle"

    def progress(self) -> None:
        """Mark forward progress.  Always updates the in-memory clocks —
        even disabled — so a watchdog can compute a heartbeat age; costs
        two clock reads, called at batch/drain granularity only."""
        self.last_progress_monotonic = time.monotonic()
        self.last_progress_wall = time.time()

    def heartbeat_age_s(self) -> float:
        """Seconds since the last recorded progress."""
        return time.monotonic() - self.last_progress_monotonic

    def heartbeat(self, force: bool = False, **extra: Any) -> None:
        """Write ``HEARTBEAT.json`` (rate-limited to ``heartbeat_every_s``
        unless ``force``).  Callers invoke this exactly at progress
        milestones, so it also marks progress."""
        self.progress()
        if self._heartbeat_file is None or self._closed:
            return
        now = time.monotonic()
        if not force and now - self._last_heartbeat_monotonic < self.heartbeat_every_s:
            return
        self._last_heartbeat_monotonic = now
        payload: Dict[str, Any] = {
            "phase": self.phase,
            "pid": os.getpid(),
            "written_wall": time.time(),
            "uptime_s": round(now - self.started_monotonic, 3),
            "last_progress_wall": self.last_progress_wall,
            "last_progress_monotonic": self.last_progress_monotonic,
            "counters": self._counter_values(),
        }
        payload.update(extra)
        self._heartbeat_file.write(payload)

    # -- events / spans --------------------------------------------------------

    def event(self, kind: str, **fields: Any) -> None:
        """Append one record to the JSONL event stream (no-op without a
        configured sink)."""
        if self._events is None or self._closed:
            return
        record: Dict[str, Any] = {
            "t": round(time.time(), 3),
            "mono": round(time.monotonic() - self.started_monotonic, 6),
            "kind": kind,
            "phase": self.phase,
        }
        record.update(fields)
        self._events.emit(record)

    @contextlib.contextmanager
    def span(self, name: str, **fields: Any) -> Iterator[None]:
        """Timed phase scope: sets the liveness phase for its duration,
        feeds ``span.<name>`` timing stats, and emits start/end events."""
        self._phase_stack.append(name)
        self.progress()
        self.event("span_start", name=name, **fields)
        start = time.perf_counter()
        try:
            yield
        finally:
            dur = time.perf_counter() - start
            if self._phase_stack and self._phase_stack[-1] == name:
                self._phase_stack.pop()
            self.histogram(f"span.{name}").observe(dur)
            self.event("span", name=name, dur_s=round(dur, 6), **fields)
            self.heartbeat()

    def set_phase(self, name: str) -> None:
        """Replace the phase stack (for flat, non-nested phase reporting)."""
        self._phase_stack[:] = [name]
        self.progress()
        self.event("phase", name=name)

    # -- rollup ----------------------------------------------------------------

    def _counter_values(self) -> Dict[str, int]:
        with self._lock:
            return {k: c.value for k, c in sorted(self._counters.items())}

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            counters = {k: c.value for k, c in sorted(self._counters.items())}
            gauges = {
                k: g.value for k, g in sorted(self._gauges.items())
                if g.value is not None
            }
            hists = list(sorted(self._histograms.items()))
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {k: h.summary() for k, h in hists},
        }

    def write_summary(self, **extra: Any) -> None:
        """Roll the current state up into ``telemetry.json``."""
        if self._summary_file is None:
            return
        payload: Dict[str, Any] = {
            "run_dir": str(self.run_dir),
            "phase": self.phase,
            "started_wall": self.started_wall,
            "written_wall": time.time(),
            "uptime_s": round(time.monotonic() - self.started_monotonic, 3),
        }
        payload.update(self.snapshot())
        payload.update(extra)
        self._summary_file.write(payload)

    def close(self) -> None:
        """Final rollup: ``run_end`` event, forced heartbeat, summary.
        Idempotent; the registry goes quiet (accessors return the no-op
        singletons) afterwards."""
        if self._closed:
            return
        self.event("run_end")
        self.heartbeat(force=True)
        self.write_summary()
        self._closed = True
        self.enabled = False
        self.step_events = False
        if self._events is not None:
            self._events.close()


# -- process-wide instance -----------------------------------------------------

_default = TelemetryRegistry(enabled=False)
_current: TelemetryRegistry = _default


def get_registry() -> TelemetryRegistry:
    """The process-wide registry (a disabled no-op one until
    :func:`configure` runs)."""
    return _current


def configure(
    run_dir: Optional[Union[str, Path]] = None,
    *,
    enabled: bool = True,
    events: bool = True,
    heartbeat_every_s: float = 30.0,
    step_events: bool = True,
) -> TelemetryRegistry:
    """Install a fresh process-wide registry (closing any previous one)
    and return it.  ``enabled=False`` installs a disabled registry —
    useful to guarantee a clean slate."""
    global _current
    if _current is not _default:
        _current.close()
    _current = TelemetryRegistry(
        run_dir=run_dir,
        enabled=enabled,
        events=events,
        heartbeat_every_s=heartbeat_every_s,
        step_events=step_events,
    )
    return _current


def reset() -> None:
    """Close any configured registry and restore the disabled default
    (tests).  Also clears the process-wide program registry — the two
    describe one run, so tests that reset telemetry state get a clean
    compiled-program slate too."""
    global _current
    if _current is not _default:
        _current.close()
    _current = _default
    from .programs import get_program_registry  # local: import cycle

    get_program_registry().reset()
