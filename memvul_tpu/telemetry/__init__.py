"""Unified run telemetry: counters / gauges / histograms / spans with
JSONL + summary + liveness sinks (docs/observability.md).

The train, score, and bench paths all report through the process-wide
registry here; ``python -m memvul_tpu telemetry-report <run_dir>``
renders what a run left behind.
"""

from .registry import (  # noqa: F401
    NULL_COUNTER,
    NULL_GAUGE,
    NULL_HISTOGRAM,
    Counter,
    Gauge,
    Histogram,
    TelemetryRegistry,
    configure,
    get_registry,
    reset,
)
from .sinks import read_jsonl  # noqa: F401
from .exposition import (  # noqa: F401
    parse_exposition,
    render_exposition,
    render_target,
    sanitize_metric_name,
)
from .programs import (  # noqa: F401
    ProgramRecord,
    ProgramRegistry,
    get_program_registry,
    shape_key,
    write_programs,
)
from .live import start_metrics_server  # noqa: F401
from .timeseries import (  # noqa: F401
    MetricsSampler,
    TimeSeriesStore,
    series_name,
)
from .alerts import (  # noqa: F401
    AlertEngine,
    AlertRule,
    default_rules,
)
