"""Telemetry sinks — the on-disk formats a run directory accumulates.

Three files, three durability stories (docs/observability.md):

* ``events.jsonl`` — the append-only event stream.  One self-contained
  JSON object per line, flushed per write, so a SIGKILL can tear at
  most the final line; every reader (:mod:`.report`, the chaos tests)
  skips an unparseable tail — the same torn-tail contract as
  ``resilience.journal.ScoreJournal``.
* ``telemetry.json`` — the rolled-up summary (counters, gauges,
  histogram percentiles), rewritten whole through
  ``resilience.io.atomic_write_text`` so readers only ever see a
  complete document.
* ``HEARTBEAT.json`` — the liveness file, same atomic-write contract.
  A supervisor polls it to tell a stalled run from a slow one: the
  payload carries the current phase plus monotonic *and* wall
  timestamps of the last progress event (registry.py documents the
  protocol).
"""

from __future__ import annotations

import json
import threading
from pathlib import Path
from typing import Any, Dict, List, Tuple, Union


class JsonlSink:
    """Append-only JSONL event stream (one flushed line per event)."""

    def __init__(self, path: Union[str, Path]) -> None:
        self.path = Path(path)
        self._f = None
        self._lock = threading.Lock()

    def emit(self, record: Dict[str, Any]) -> None:
        line = json.dumps(record, default=str)
        with self._lock:
            if self._f is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                # JsonlSink IS the sanctioned append-only writer (one
                # flushed line per event, torn-tail-tolerant readers)
                self._f = open(self.path, "a", encoding="utf-8")  # lint: disable=MV103
            self._f.write(line + "\n")
            self._f.flush()

    def close(self) -> None:
        with self._lock:
            if self._f is not None:
                self._f.close()
                self._f = None


def read_jsonl(path: Union[str, Path]) -> Tuple[List[Dict[str, Any]], int]:
    """Parse a JSONL stream, tolerating a torn tail.

    Returns ``(records, n_skipped)``.  Unparseable or non-dict lines are
    skipped rather than fatal — a SIGKILL mid-write legitimately leaves
    half a line, and a report over a crashed run must still render.
    """
    path = Path(path)
    if not path.exists():
        return [], 0
    records: List[Dict[str, Any]] = []
    skipped = 0
    for line in path.read_text(encoding="utf-8").splitlines():
        line = line.strip()
        if not line:
            continue
        try:
            obj = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(obj, dict):
            records.append(obj)
        else:
            skipped += 1
    return records, skipped


class AtomicJsonFile:
    """Whole-document JSON snapshot via tmp + ``os.replace``."""

    def __init__(self, path: Union[str, Path]) -> None:
        self.path = Path(path)

    def write(self, payload: Dict[str, Any]) -> None:
        # lazy import: resilience.journal/retry count into telemetry, so
        # the telemetry package must not import resilience at load time
        from ..resilience.io import atomic_write_text

        atomic_write_text(self.path, json.dumps(payload, indent=2, default=str))

    def read(self) -> Dict[str, Any]:
        """The current snapshot, or {} when absent/unreadable (a report
        over a crashed or pre-telemetry run dir must still render)."""
        try:
            obj = json.loads(self.path.read_text(encoding="utf-8"))
        except (OSError, ValueError):
            return {}
        return obj if isinstance(obj, dict) else {}


class HeartbeatFile(AtomicJsonFile):
    """The liveness snapshot (``HEARTBEAT.json``)."""


class SummaryFile(AtomicJsonFile):
    """The rolled-up run summary (``telemetry.json``)."""
