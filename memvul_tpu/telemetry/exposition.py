"""Prometheus text-format exposition of registry snapshots.

The PR 3 telemetry layer is *offline*: per-process JSONL sinks read
post-hoc by ``telemetry-report``.  A live fleet needs a scrape surface
— this module renders any :meth:`TelemetryRegistry.snapshot` dict as
`Prometheus text format
<https://prometheus.io/docs/instrumenting/exposition_formats/>`_, which
is what ``GET /metrics`` (serving/frontend.py) serves.

Mapping (docs/observability.md, "Live exposition"):

* counters → ``# TYPE <name> counter`` samples, gauges → ``gauge``;
* histogram summaries → a Prometheus *summary*: ``<name>{quantile=..}``
  for the reservoir percentiles plus ``<name>_sum`` / ``<name>_count``;
* metric names are sanitized (``serve.queue_depth`` →
  ``serve_queue_depth``; any other non-``[a-zA-Z0-9_:]`` byte becomes
  ``_``) — the mapping is a bijection over the repo's metric catalog,
  so a scrape agrees *exactly* with the snapshot it was rendered from
  (pinned in tests/test_telemetry.py);
* ``labels`` attach to every sample of a part — the router renders one
  part per replica with ``{"replica": "replica-<i>"}``, mirroring
  ``health_summary()``'s fan-out, so per-replica counters stay
  separable at the scrape endpoint exactly as they are on disk.

Rendering only *reads* snapshots: no locks beyond the registry's own
snapshot lock, no device work — safe to call from an HTTP handler
(checker MV102 holds the handlers to snapshot-read-only calls).
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

# quantiles rendered for each histogram summary — the percentiles the
# registry's reservoir already answers (registry.Histogram.summary)
_SUMMARY_QUANTILES = (("0.5", "p50"), ("0.95", "p95"))

_NAME_BAD = re.compile(r"[^a-zA-Z0-9_:]")

# one snapshot part: (labels, snapshot) — a bare service exposes one
# unlabeled part, a router one part per replica plus its own
SnapshotPart = Tuple[Mapping[str, str], Mapping[str, Any]]


def sanitize_metric_name(name: str) -> str:
    """``serve.queue_depth`` → ``serve_queue_depth`` (dots and every
    other byte outside the Prometheus name alphabet become ``_``; a
    leading digit is prefixed)."""
    out = _NAME_BAD.sub("_", name)
    if out and out[0].isdigit():
        out = "_" + out
    return out


def _escape_label_value(value: str) -> str:
    return (
        str(value)
        .replace("\\", r"\\")
        .replace("\n", r"\n")
        .replace('"', r"\"")
    )


def _label_str(labels: Mapping[str, str]) -> str:
    if not labels:
        return ""
    inner = ",".join(
        f'{sanitize_metric_name(k)}="{_escape_label_value(v)}"'
        for k, v in sorted(labels.items())
    )
    return "{" + inner + "}"


def _fmt_value(value: Any) -> str:
    if isinstance(value, bool):  # bool is an int subclass; be explicit
        return "1" if value else "0"
    if isinstance(value, int):
        return str(value)
    try:
        f = float(value)
    except (TypeError, ValueError):
        return "0"
    return repr(f)


def render_exposition(parts: Sequence[SnapshotPart]) -> str:
    """Render snapshot parts as one Prometheus text document.

    All samples of one metric are grouped under a single ``# TYPE``
    line (the format's requirement), so two replicas' ``serve.served``
    land adjacent with their ``replica`` labels telling them apart.
    """
    counters: Dict[str, List[str]] = {}
    gauges: Dict[str, List[str]] = {}
    summaries: Dict[str, List[str]] = {}
    for labels, snapshot in parts:
        label_str = _label_str(labels)
        for name, value in (snapshot.get("counters") or {}).items():
            metric = sanitize_metric_name(name)
            counters.setdefault(metric, []).append(
                f"{metric}{label_str} {_fmt_value(value)}"
            )
        for name, value in (snapshot.get("gauges") or {}).items():
            if value is None:
                continue
            metric = sanitize_metric_name(name)
            gauges.setdefault(metric, []).append(
                f"{metric}{label_str} {_fmt_value(value)}"
            )
        for name, summary in (snapshot.get("histograms") or {}).items():
            if not summary:
                continue
            metric = sanitize_metric_name(name)
            lines = summaries.setdefault(metric, [])
            for quantile, key in _SUMMARY_QUANTILES:
                if summary.get(key) is None:
                    continue
                q_labels = dict(labels)
                q_labels["quantile"] = quantile
                lines.append(
                    f"{metric}{_label_str(q_labels)} "
                    f"{_fmt_value(summary[key])}"
                )
            lines.append(
                f"{metric}_sum{label_str} "
                f"{_fmt_value(summary.get('total', 0.0))}"
            )
            lines.append(
                f"{metric}_count{label_str} "
                f"{_fmt_value(int(summary.get('count', 0)))}"
            )
    out: List[str] = []
    for metric in sorted(counters):
        out.append(f"# TYPE {metric} counter")
        out.extend(counters[metric])
    for metric in sorted(gauges):
        out.append(f"# TYPE {metric} gauge")
        out.extend(gauges[metric])
    for metric in sorted(summaries):
        out.append(f"# TYPE {metric} summary")
        out.extend(summaries[metric])
    return "\n".join(out) + ("\n" if out else "")


def render_target(target) -> str:
    """Render a serving target's live registries.

    ``target`` is anything exposing ``metrics_snapshots()`` — a
    :class:`~memvul_tpu.serving.service.ScoringService` (one unlabeled
    part) or a :class:`~memvul_tpu.serving.router.ReplicaRouter` (its
    own registry plus one ``replica``-labeled part per replica)."""
    return render_exposition(target.metrics_snapshots())


def parse_exposition(text: str) -> Dict[str, Dict[str, float]]:
    """Parse Prometheus text format back into
    ``{metric: {label_str: value}}`` — the test-side half of the
    exact-agreement contract (and a convenient scrape reader for the
    SLO harness).  Raises ``ValueError`` on a malformed sample line, so
    "parses as Prometheus text format" is a real assertion."""
    out: Dict[str, Dict[str, float]] = {}
    for raw in text.splitlines():
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        m = re.match(
            r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(\S+)$", line
        )
        if m is None:
            raise ValueError(f"not a Prometheus sample line: {raw!r}")
        name, labels, value = m.group(1), m.group(2) or "", m.group(3)
        out.setdefault(name, {})[labels] = float(value)
    return out
