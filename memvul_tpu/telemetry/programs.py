"""Compiled-program observability: the XLA program registry.

Every ``jit(...).lower(...).compile()`` in the package goes through ONE
chokepoint — :meth:`ProgramRegistry.compile_and_register` — so the
process always knows *which executables exist*, what each cost to
compile, what XLA's own ``cost_analysis()`` / ``memory_analysis()``
say it does per launch, and how often it has been dispatched.  That is
the data a roofline reading needs (docs/roofline_train.md): analyzed
FLOPs and bytes per invocation against a small per-device peak-spec
table turn raw seconds into ``xla.mfu`` and achieved-bandwidth gauges,
live, instead of the hand-computed figure the chip-window debt item
complains about.  Checker MV405 (analysis/checkers/drift.py) keeps the
chokepoint honest: a raw ``.lower(...).compile(`` anywhere else in the
package is registry-bypass drift.

Design constraints, in order:

* **separate state** — program records and the ``xla.*`` rows they
  derive live in THIS registry, not in the
  :class:`~memvul_tpu.telemetry.registry.TelemetryRegistry` metric
  maps.  The ``xla.*`` metrics materialize only at render time
  (:meth:`metrics_part` is merged as an extra snapshot part by the
  exposition surfaces), so the emitted metric set of every existing
  run/serve path is bit-identical to the pre-registry baseline and the
  serving parity pins hold untouched;
* **dependency-light** — no jax import at module load (device-kind
  detection is lazy and failure-tolerant), mirroring the telemetry
  registry's own rule;
* **events are the diagnosis channel** — each chokepoint compile emits
  a ``program`` event, and any *trace after warmup* (a cache miss that
  is about to cost a mid-run compile) emits an ``rcompile`` event
  naming the offending shape key — turning the bare
  ``score_trace_count`` / ``train_trace_count`` assertions into
  attributable records in ``events.jsonl``.

Scopes: each compile family (``"score"``, ``"probs"``, ``"train"``)
marks itself warm when its AOT warmup / first epoch completes
(:meth:`mark_warm`); :meth:`note_trace` is called from the trace-time
probe wrappers and only escalates to ``rcompile`` once its scope is
warm, so warmup compiles stay quiet.
"""

from __future__ import annotations

import threading
import time
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional, Tuple

from .registry import get_registry

# Peak specs for the roofline denominators (docs/roofline_train.md):
# dense bf16 FLOP/s, HBM bandwidth, and HBM capacity per chip, keyed by
# a lowercase substring of jax's ``device_kind``.  Small on purpose —
# an unknown device (and every CPU) renders as interpret-only rather
# than against a made-up peak.  ``hbm_bytes`` is the capacity ceiling
# the offline autotuner's analytic pruner checks candidate footprints
# against (tuning/prune.py); roofline() itself only reads the two rate
# rows.  Order matters: substring matching means the more specific
# marker must precede its prefix ("v5 lite"/"v5p" before "v5e" is
# irrelevant, but "v2"/"v3"/"v4" must not shadow "v5*" — they cannot,
# dict order is first-match and the v5 rows come first).
PEAK_SPECS: Dict[str, Dict[str, float]] = {
    "v5 lite": {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9,
                "hbm_bytes": 16e9},
    "v5e": {"flops_per_s": 197e12, "hbm_bytes_per_s": 819e9,
            "hbm_bytes": 16e9},
    "v5p": {"flops_per_s": 459e12, "hbm_bytes_per_s": 2765e9,
            "hbm_bytes": 95e9},
    "v6e": {"flops_per_s": 918e12, "hbm_bytes_per_s": 1640e9,
            "hbm_bytes": 32e9},
    "v4": {"flops_per_s": 275e12, "hbm_bytes_per_s": 1228e9,
           "hbm_bytes": 32e9},
    "v3": {"flops_per_s": 123e12, "hbm_bytes_per_s": 900e9,
           "hbm_bytes": 32e9},
    "v2": {"flops_per_s": 45e12, "hbm_bytes_per_s": 700e9,
           "hbm_bytes": 16e9},
}


def device_info() -> Tuple[str, str]:
    """(platform, device_kind) of the default backend — ``("cpu",
    "cpu")`` on hosts, never raises (the registry must work in a
    process whose backend failed to initialize)."""
    try:
        import jax

        dev = jax.devices()[0]
        return str(dev.platform), str(getattr(dev, "device_kind", dev.platform))
    except Exception:  # pragma: no cover - backend init failure
        return "unknown", "unknown"


def peak_spec(device_kind: str) -> Optional[Dict[str, float]]:
    """The peak-spec row for a device kind, or None (interpret-only)."""
    kind = device_kind.lower()
    for marker, spec in PEAK_SPECS.items():
        if marker in kind:
            return spec
    return None


def shape_key(prefix: str, tree: Any) -> str:
    """A compact, deterministic shape signature for a pytree of arrays
    (or tracers — ``.shape`` is all it reads), e.g.
    ``train_step:2x32x128,2x32x256``.  Used as the registry key for
    programs whose compiled shape set is data-dependent (the trainers'
    bucketed stack grid)."""
    import jax

    shapes = sorted({
        "x".join(str(d) for d in leaf.shape)
        for leaf in jax.tree_util.tree_leaves(tree)
        if getattr(leaf, "shape", None)
    })
    return f"{prefix}:{','.join(shapes)}" if shapes else prefix


def _cost_analysis(executable) -> Dict[str, float]:
    """``executable.cost_analysis()`` defensively: the return shape has
    drifted across jax versions (dict vs list-of-dict) and some
    backends raise — the registry records zeros rather than breaking a
    compile that already succeeded."""
    try:
        cost = executable.cost_analysis()
    except Exception:
        return {}
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return {}
    out: Dict[str, float] = {}
    for k, v in cost.items():
        try:
            out[str(k)] = float(v)
        except (TypeError, ValueError):
            continue
    return out


def _memory_analysis(executable) -> Dict[str, int]:
    """argument/output/temp HBM bytes from ``memory_analysis()``;
    empty when the backend does not implement it (CPU)."""
    try:
        mem = executable.memory_analysis()
    except Exception:
        return {}
    out: Dict[str, int] = {}
    for name, attr in (
        ("argument_bytes", "argument_size_in_bytes"),
        ("output_bytes", "output_size_in_bytes"),
        ("temp_bytes", "temp_size_in_bytes"),
    ):
        value = getattr(mem, attr, None)
        if value is None and isinstance(mem, dict):
            value = mem.get(attr)
        try:
            if value is not None:
                out[name] = int(value)
        except (TypeError, ValueError):
            continue
    return out


@dataclass
class ProgramRecord:
    """One registered executable (one compiled shape signature)."""

    key: str
    scope: str
    compile_s: float
    compiled_wall: float
    compiled_monotonic: float
    platform: str
    device_kind: str
    interpret_only: bool
    flops: float = 0.0
    bytes_accessed: float = 0.0
    argument_bytes: int = 0
    output_bytes: int = 0
    temp_bytes: int = 0
    invocations: int = 0
    device_time_s: float = 0.0
    recompiles: int = 0
    compile_times: List[float] = field(default_factory=list)

    @property
    def hbm_bytes(self) -> int:
        return self.argument_bytes + self.output_bytes + self.temp_bytes

    def as_dict(self, peak: Optional[Dict[str, float]]) -> Dict[str, Any]:
        mfu = None
        if (
            peak is not None
            and self.device_time_s > 0
            and self.flops > 0
        ):
            mfu = (self.flops * self.invocations / self.device_time_s) / peak[
                "flops_per_s"
            ]
        return {
            "key": self.key,
            "scope": self.scope,
            "compile_s": round(self.compile_s, 6),
            "compiled_wall": self.compiled_wall,
            "flops": self.flops,
            "bytes_accessed": self.bytes_accessed,
            "argument_bytes": self.argument_bytes,
            "output_bytes": self.output_bytes,
            "temp_bytes": self.temp_bytes,
            "hbm_bytes": self.hbm_bytes,
            "invocations": self.invocations,
            "device_time_s": round(self.device_time_s, 6),
            "recompiles": self.recompiles,
            "platform": self.platform,
            "device_kind": self.device_kind,
            "interpret_only": self.interpret_only,
            "mfu": mfu,
        }


class ProgramRegistry:
    """Thread-safe record of every compiled executable in the process
    (or, behind a replica factory, one replica's executables).

    ``telemetry`` optionally binds the event channel to a specific
    :class:`TelemetryRegistry` (the per-replica registries); unbound,
    events go through the process-wide :func:`get_registry` at emit
    time, so a registry constructed before ``telemetry.configure()``
    still reports into the configured run."""

    def __init__(self, telemetry=None) -> None:
        self._telemetry = telemetry
        self._lock = threading.Lock()
        self._records: Dict[str, ProgramRecord] = {}
        self._order: List[str] = []  # insertion order; newest = last
        self._warm_scopes: Dict[str, bool] = {}
        self._rcompiles = 0
        self._unattributed_invocations = 0

    # -- event channel ---------------------------------------------------------

    def _tel(self, override=None):
        if override is not None:
            return override
        if self._telemetry is not None:
            return self._telemetry
        return get_registry()

    # -- the chokepoint --------------------------------------------------------

    def compile_and_register(
        self,
        key: str,
        lowered,
        *,
        scope: str = "default",
        telemetry=None,
    ):
        """Compile ``lowered`` (a ``jit(...).lower(...)`` result),
        record the executable's analyzed costs under ``key``, and
        return the compiled object.  Compile failures propagate
        unrecorded — callers' retry/degradation paths (the Mosaic
        fallback in predict_memory) keep their exact semantics.

        Re-registering an existing key (a score-program rebuild, a
        second predictor warming the same shared program) updates the
        record in place and bumps its ``recompiles`` count; the record
        moves to the head of the newest-compile-first ordering."""
        t0 = time.perf_counter()
        executable = lowered.compile()
        compile_s = time.perf_counter() - t0
        cost = _cost_analysis(executable)
        mem = _memory_analysis(executable)
        platform, kind = device_info()
        interpret_only = peak_spec(kind) is None
        now_wall, now_mono = time.time(), time.monotonic()
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                rec = ProgramRecord(
                    key=key,
                    scope=scope,
                    compile_s=compile_s,
                    compiled_wall=now_wall,
                    compiled_monotonic=now_mono,
                    platform=platform,
                    device_kind=kind,
                    interpret_only=interpret_only,
                )
                self._records[key] = rec
            else:
                rec.recompiles += 1
                rec.compile_s = compile_s
                rec.compiled_wall = now_wall
                rec.compiled_monotonic = now_mono
                self._order.remove(key)
            rec.compile_times.append(compile_s)
            rec.flops = cost.get("flops", rec.flops)
            rec.bytes_accessed = cost.get("bytes accessed", rec.bytes_accessed)
            rec.argument_bytes = mem.get("argument_bytes", rec.argument_bytes)
            rec.output_bytes = mem.get("output_bytes", rec.output_bytes)
            rec.temp_bytes = mem.get("temp_bytes", rec.temp_bytes)
            self._order.append(key)
        self._tel(telemetry).event(
            "program",
            key=key,
            scope=scope,
            compile_s=round(compile_s, 6),
            flops=rec.flops,
            bytes_accessed=rec.bytes_accessed,
            hbm_bytes=rec.hbm_bytes,
            device_kind=kind,
        )
        return executable

    # -- runtime accounting ----------------------------------------------------

    def record_invocation(self, key: str, seconds: Optional[float] = None) -> None:
        """One dispatch of a registered program; ``seconds`` is the
        host-observed device time of the launch when the call site has
        it (the serving chunk scorer, the trainer step timer) — the
        async streaming paths count invocations only rather than
        reintroduce a per-batch host sync."""
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                self._unattributed_invocations += 1
                return
            rec.invocations += 1
            if seconds is not None and seconds > 0:
                rec.device_time_s += float(seconds)

    def mark_warm(self, scope: str, warm: bool = True) -> None:
        """Warmup-state edge for a compile scope: traces in a warm
        scope escalate to ``rcompile`` events.  AOT warmups and the
        trainers' first epoch call ``mark_warm(scope, False)`` on
        entry (a rebuild/re-warm is intentional recompilation) and
        ``mark_warm(scope)`` when every expected shape is compiled."""
        with self._lock:
            self._warm_scopes[scope] = bool(warm)

    def is_warm(self, scope: str) -> bool:
        with self._lock:
            return self._warm_scopes.get(scope, False)

    def note_trace(self, scope: str, key: str, telemetry=None) -> None:
        """Called at TRACE time from the jit probe wrappers (the
        ``score_trace_count`` / ``train_trace_count`` bodies): a trace
        is a jit cache miss, i.e. a compile is about to happen.  In a
        warm scope that is the diagnosable event this registry exists
        for — emit ``rcompile`` with the offending shape key."""
        with self._lock:
            warm = self._warm_scopes.get(scope, False)
            if warm:
                self._rcompiles += 1
        if warm:
            self._tel(telemetry).event("rcompile", scope=scope, key=key)

    # -- read surfaces ---------------------------------------------------------

    def snapshot(self) -> List[Dict[str, Any]]:
        """Per-program rows, newest compile first (the ``/programz``
        ordering)."""
        with self._lock:
            records = [self._records[k] for k in reversed(self._order)]
            return [r.as_dict(peak_spec(r.device_kind)) for r in records]

    def last_compile(self) -> Optional[Dict[str, Any]]:
        """The most recent registered compile — the bench watchdog's
        wedged-init vs slow-first-step discriminator."""
        with self._lock:
            if not self._order:
                return None
            rec = self._records[self._order[-1]]
            return {
                "key": rec.key,
                "compile_s": round(rec.compile_s, 6),
                "age_s": time.monotonic() - rec.compiled_monotonic,
            }

    def roofline(self) -> Dict[str, Any]:
        """Aggregate achieved-vs-peak figures over every recorded
        program (CPU and unknown devices are interpret-only: analyzed
        costs still report, the MFU denominators stay null)."""
        with self._lock:
            records = list(self._records.values())
        platform, kind = device_info()
        if records:
            platform = records[-1].platform
            kind = records[-1].device_kind
        peak = peak_spec(kind)
        flops_total = sum(r.flops * r.invocations for r in records)
        bytes_total = sum(r.bytes_accessed * r.invocations for r in records)
        device_time = sum(r.device_time_s for r in records)
        achieved_flops = flops_total / device_time if device_time > 0 else None
        achieved_bytes = bytes_total / device_time if device_time > 0 else None
        mfu = None
        membw_util = None
        if peak is not None and achieved_flops is not None:
            mfu = achieved_flops / peak["flops_per_s"]
        if peak is not None and achieved_bytes is not None:
            membw_util = achieved_bytes / peak["hbm_bytes_per_s"]
        return {
            "platform": platform,
            "device_kind": kind,
            "interpret_only": peak is None,
            "peak_flops_per_s": peak["flops_per_s"] if peak else None,
            "peak_bytes_per_s": peak["hbm_bytes_per_s"] if peak else None,
            "programs": len(records),
            "flops_total": flops_total,
            "bytes_total": bytes_total,
            "device_time_s": round(device_time, 6),
            "achieved_flops_per_s": achieved_flops,
            "achieved_bytes_per_s": achieved_bytes,
            "mfu": mfu,
            "membw_util": membw_util,
        }

    def metrics_part(self) -> Dict[str, Any]:
        """The ``xla.*`` rows as one snapshot-shaped dict, for merging
        as an extra part into the Prometheus exposition.  Empty when
        nothing is registered, so a process that never compiles scrapes
        exactly its pre-registry metric set."""
        with self._lock:
            records = list(self._records.values())
            rcompiles = self._rcompiles
            unattributed = self._unattributed_invocations
        if not records:
            return {}
        roof = self.roofline()
        compile_times = sorted(
            t for r in records for t in r.compile_times
        )
        total_compiles = len(compile_times)
        hist = {
            "count": float(total_compiles),
            "total": sum(compile_times),
            "mean": sum(compile_times) / total_compiles,
            "min": compile_times[0],
            "max": compile_times[-1],
            "p50": compile_times[(total_compiles - 1) // 2],
            "p95": compile_times[
                min(total_compiles - 1, int(round((total_compiles - 1) * 0.95)))
            ],
        }
        counters = {
            "xla.programs": len(records),
            "xla.compiles": total_compiles,
            "xla.recompiles": rcompiles,
            "xla.invocations": (
                sum(r.invocations for r in records) + unattributed
            ),
            "xla.flops_total": int(roof["flops_total"]),
            "xla.bytes_total": int(roof["bytes_total"]),
        }
        gauges: Dict[str, float] = {
            "xla.device_time_s": roof["device_time_s"],
            "xla.interpret_only": 1.0 if roof["interpret_only"] else 0.0,
            "xla.hbm_bytes": float(max(r.hbm_bytes for r in records)),
        }
        for gauge_name, value in (
            ("xla.mfu", roof["mfu"]),
            ("xla.achieved_flops_per_s", roof["achieved_flops_per_s"]),
            ("xla.achieved_bytes_per_s", roof["achieved_bytes_per_s"]),
        ):
            if value is not None:
                gauges[gauge_name] = float(value)
        return {
            "counters": counters,
            "gauges": gauges,
            "histograms": {"xla.compile_s": hist},
        }

    def reset(self) -> None:
        with self._lock:
            self._records.clear()
            self._order.clear()
            self._warm_scopes.clear()
            self._rcompiles = 0
            self._unattributed_invocations = 0


# -- process-wide instance -----------------------------------------------------

_programs = ProgramRegistry()


def get_program_registry() -> ProgramRegistry:
    """The process-wide program registry (trainers, the offline
    predictors, and single-service serving all record here; replica
    factories construct their own per-replica instances)."""
    return _programs


def write_programs(run_dir) -> None:
    """Persist the process registry's programs + roofline beside the
    telemetry sinks (``<run_dir>/programs.json``) so telemetry-report
    renders the PROGRAMS table post-hoc.  No-op when nothing was
    registered — pre-registry run dirs and program-free runs stay
    byte-identical."""
    import json
    from pathlib import Path

    # lazy, mirroring sinks.py: telemetry never imports resilience at
    # module load, only the atomic-commit helper at write time
    from ..resilience.io import atomic_write_text

    snapshot = _programs.snapshot()
    if not snapshot:
        return
    payload = {
        "schema": 1,
        "written_wall": time.time(),
        "programs": snapshot,
        "roofline": _programs.roofline(),
    }
    atomic_write_text(
        Path(run_dir) / "programs.json",
        json.dumps(payload, indent=2, default=float),
    )
