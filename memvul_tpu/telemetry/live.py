"""Opt-in live exposition for non-serving runs.

The serving tier has had ``GET /metrics`` since PR 10, but a training
run or the paper's 1.22M-IR corpus pass (``predict_file``) still only
writes files — a multi-hour run is invisible until it finishes or
dies.  ``telemetry.metrics_port`` (config.TELEMETRY_DEFAULTS, default
0 = off) starts THIS server as a daemon thread inside
``train_from_config`` / ``evaluate_from_archive``: the same Prometheus
rendering the serving frontend uses, over the process-wide registries,
so rows/s, heartbeat age, and the compiled-program table are
scrapeable while the run is still going.

Endpoints (all snapshot reads — the MV102 rule for handler threads
holds here exactly as it does for the serving frontend):

* ``GET /metrics``  — the process registry's snapshot plus the
  ``xla.*`` program part, Prometheus text format;
* ``GET /programz`` — the program registry's newest-compile-first rows
  as JSON;
* ``GET /healthz``  — phase + heartbeat age, the liveness probe;
* ``GET /metricsz`` / ``GET /alertz`` — the in-process metric history
  and alert state (telemetry/timeseries.py, telemetry/alerts.py) when
  ``telemetry.tsdb_cadence_s`` > 0; ``{"enabled": false}`` otherwise.

Default-off is load-bearing: with ``metrics_port`` 0 nothing here is
constructed, imported state stays untouched, and the run's emitted
metric/event set is pinned identical to the pre-registry baseline.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import List, Optional

from .exposition import SnapshotPart, render_exposition
from .programs import get_program_registry
from .registry import get_registry

logger = logging.getLogger(__name__)


def live_parts() -> List[SnapshotPart]:
    """The process-wide snapshot parts a live scrape renders: the
    telemetry registry's metrics plus (when any program is registered)
    the derived ``xla.*`` part."""
    parts: List[SnapshotPart] = [({}, get_registry().snapshot())]
    program_part = get_program_registry().metrics_part()
    if program_part:
        parts.append(({}, program_part))
    return parts


class _LiveMetricsHandler(BaseHTTPRequestHandler):
    server_version = "memvul-telemetry/1.0"
    protocol_version = "HTTP/1.1"

    def log_message(self, format: str, *args) -> None:  # noqa: A002
        logger.debug("%s %s", self.address_string(), format % args)

    def _reply(self, status: int, body: bytes, ctype: str) -> None:
        self.send_response(status)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def _reply_json(self, status: int, payload) -> None:
        self._reply(
            status,
            json.dumps(payload, default=float).encode("utf-8"),
            "application/json",
        )

    def do_GET(self) -> None:
        path, _, query = self.path.partition("?")
        if path == "/metrics":
            text = render_exposition(live_parts())
            self._reply(
                200, text.encode("utf-8"), "text/plain; version=0.0.4"
            )
            return
        if path == "/programz":
            programs = get_program_registry().snapshot()
            payload = {
                "count": len(programs),
                "programs": programs,
                "roofline": get_program_registry().roofline(),
            }
            self._reply(
                200,
                json.dumps(payload, default=float).encode("utf-8"),
                "application/json",
            )
            return
        if path == "/healthz":
            tel = get_registry()
            payload = {
                "phase": tel.phase,
                "heartbeat_age_s": round(tel.heartbeat_age_s(), 3),
                "enabled": tel.enabled,
            }
            self._reply(
                200, json.dumps(payload).encode("utf-8"), "application/json"
            )
            return
        if path == "/metricsz":
            # metric history rings (telemetry/timeseries.py) — snapshot
            # copies only, same as the serving frontend's route
            params = urllib.parse.parse_qs(query)
            try:
                window_s = (
                    float(params["window"][0]) if "window" in params else None
                )
            except (TypeError, ValueError):
                self._reply_json(
                    400,
                    {"status": "error", "reason": "window must be a number"},
                )
                return
            metric = params["metric"][0] if "metric" in params else None
            sampler = getattr(self.server, "sampler", None)
            if sampler is None:
                self._reply_json(
                    200, {"enabled": False, "series": 0, "history": {}}
                )
                return
            payload = sampler.status()
            payload["history"] = sampler.history(window_s, metric)
            self._reply_json(200, payload)
            return
        if path == "/alertz":
            engine = getattr(self.server, "engine", None)
            if engine is None:
                self._reply_json(
                    200, {"enabled": False, "firing": [], "rules": []}
                )
                return
            self._reply_json(200, engine.status())
            return
        self._reply(
            404,
            json.dumps({"status": "error", "reason": "unknown path"}).encode(
                "utf-8"
            ),
            "application/json",
        )


class LiveMetricsServer(ThreadingHTTPServer):
    """The daemon-thread exposition server; ``close()`` is idempotent
    and owned by the run entry point's ``finally`` — the same place
    the telemetry registry closes, so a SIGTERM-preempted run (which
    unwinds through that ``finally``) releases the port cleanly."""

    daemon_threads = True

    def __init__(self, address, sampler=None, engine=None) -> None:
        super().__init__(address, _LiveMetricsHandler)
        # the history plane, when the run turned it on (tsdb_cadence_s
        # > 0 in build.train_from_config); None keeps /metricsz and
        # /alertz answering {"enabled": false}
        self.sampler = sampler
        self.engine = engine
        self._thread: threading.Thread = threading.Thread(
            target=self.serve_forever, name="memvul-metrics-http", daemon=True
        )
        self._closed = False

    def start(self) -> "LiveMetricsServer":
        self._thread.start()
        logger.info(
            "live telemetry exposition on http://%s:%d "
            "(GET /metrics, /programz, /healthz, /metricsz, /alertz)",
            *self.server_address[:2],
        )
        return self

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self.shutdown()
        self.server_close()
        # the server owns the sampler/engine threads it was started
        # with (build passes freshly-constructed ones): stop them with
        # the port so a preempted run unwinds cleanly
        for worker in (self.sampler, self.engine):
            if worker is not None:
                worker.stop()


def start_metrics_server(
    port: int,
    host: str = "127.0.0.1",
    sampler=None,
    engine: Optional[object] = None,
) -> LiveMetricsServer:
    """Bind and start the live exposition server (port 0 = ephemeral;
    read the bound port off ``server.server_address``).  ``sampler`` /
    ``engine`` attach the metric-history plane to /metricsz + /alertz;
    ``close()`` stops them with the port."""
    return LiveMetricsServer((host, port), sampler=sampler, engine=engine).start()
