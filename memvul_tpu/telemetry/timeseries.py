"""Bounded in-process metrics history — the "what happened" plane.

Every live surface so far is point-in-time: ``GET /metrics`` is a
snapshot, ``/tracez`` a small ring, and the SLO monitor's burn rates
evaporate the moment they change.  Unless an external Prometheus
happened to be scraping, the 3am question — *why* did the fleet flap,
*when* did HBM start growing — has no answer.  This module keeps a
small sliding window of history inside the process itself:

* :class:`TimeSeriesStore` — per-``(labels, metric)`` rings of
  ``(wall_ts, value)`` points.  Gauges are stored as-is; counters are
  stored as **derived per-second rates** under ``<name>.rate`` (the
  raw monotone totals are already in the snapshot — the interesting
  signal is the slope); histogram summaries contribute their
  ``mean``/``p50``/``p95`` as separate series.  ``resolution_s``
  coalesces points closer together than one bucket, ``retention_s``
  bounds each ring, so memory is ``O(series × retention/resolution)``
  regardless of sampler cadence.
* :class:`MetricsSampler` — a daemon thread that snapshots a target at
  a fixed cadence into one store.  A serving target's
  ``metrics_snapshots()`` is sampled when it has one (so the router's
  per-``replica`` parts and the ``HostBalancer``'s per-``host`` parts
  label their history for free, exactly like a ``/metrics`` scrape);
  a bare :class:`~memvul_tpu.telemetry.registry.TelemetryRegistry` or
  a parts-returning callable (``telemetry.live.live_parts``) works
  too.

Served as ``GET /metricsz?window=&metric=`` by the serving frontend
and the live exposition server, fed to ``telemetry/alerts.py`` rule
evaluation, and dumped into incident bundles (serving/incident.py).

Default-off is load-bearing (the ``metrics_port`` discipline): with
``telemetry.tsdb_cadence_s`` 0 nothing here is constructed and the
run's emitted metric/event set stays byte-identical to the baseline.
When a sampler *is* running it reports its own cost as ``tsdb.samples``
/ ``tsdb.sample_errors`` counters, a ``tsdb.series`` gauge, and a
``tsdb.sample_s`` histogram — the overhead figure the serve microbench
records (bench.py, ``BENCH_SERVE_TSDB_CADENCE``).
"""

from __future__ import annotations

import collections
import logging
import threading
import time
from typing import Any, Dict, List, Mapping, Optional, Sequence, Tuple

from .registry import get_registry

logger = logging.getLogger(__name__)

# (sorted (key, value) label pairs, metric name) — one ring per pair
_SeriesKey = Tuple[Tuple[Tuple[str, str], ...], str]

DEFAULT_RESOLUTION_S = 1.0
DEFAULT_RETENTION_S = 600.0


def series_name(metric: str, label_key: Tuple[Tuple[str, str], ...]) -> str:
    """The flat Prometheus-style name a labeled series renders under in
    ``/metricsz`` JSON, e.g. ``serve.requests.rate{replica="replica-0"}``."""
    if not label_key:
        return metric
    inner = ",".join(f'{k}="{v}"' for k, v in label_key)
    return f"{metric}{{{inner}}}"


class TimeSeriesStore:
    """Thread-safe bounded rings of metric history.

    ``observe(parts)`` ingests one multi-part snapshot (the
    ``SnapshotPart`` shape ``telemetry.exposition`` renders); readers
    (``history``/``window``/``stats``) only copy under the lock — the
    handler snapshot discipline (MV102) holds for every consumer."""

    def __init__(
        self,
        resolution_s: float = DEFAULT_RESOLUTION_S,
        retention_s: float = DEFAULT_RETENTION_S,
    ) -> None:
        resolution_s = float(resolution_s)
        retention_s = float(retention_s)
        if resolution_s <= 0:
            raise ValueError(
                f"tsdb resolution_s must be > 0, got {resolution_s!r}"
            )
        if retention_s < resolution_s:
            raise ValueError(
                "tsdb retention_s must be >= resolution_s, got "
                f"{retention_s!r} < {resolution_s!r}"
            )
        self.resolution_s = resolution_s
        self.retention_s = retention_s
        self._maxlen = max(2, int(round(retention_s / resolution_s)))
        self._lock = threading.Lock()
        self._series: Dict[_SeriesKey, "collections.deque"] = {}
        # last raw counter totals, for the rate derivation
        self._prev_counters: Dict[_SeriesKey, Tuple[float, float]] = {}
        self._samples = 0

    # -- ingest ----------------------------------------------------------------

    def observe(
        self,
        parts: Sequence[Tuple[Mapping[str, str], Mapping[str, Any]]],
        now: Optional[float] = None,
    ) -> None:
        """Ingest one sample: every part's counters (as rates), gauges,
        and histogram summaries, labeled like the exposition would."""
        now = time.time() if now is None else float(now)
        with self._lock:
            self._samples += 1
            for labels, snapshot in parts:
                self._observe_part(dict(labels or {}), snapshot or {}, now)

    def _observe_part(
        self, labels: Dict[str, str], snapshot: Mapping[str, Any], now: float
    ) -> None:
        label_key = tuple(sorted((str(k), str(v)) for k, v in labels.items()))
        for name, value in (snapshot.get("counters") or {}).items():
            try:
                total = float(value)
            except (TypeError, ValueError):
                continue
            key = (label_key, str(name))
            prev = self._prev_counters.get(key)
            self._prev_counters[key] = (now, total)
            if prev is None or now <= prev[0]:
                continue
            rate = max(0.0, total - prev[1]) / (now - prev[0])
            self._append(label_key, f"{name}.rate", now, rate)
        for name, value in (snapshot.get("gauges") or {}).items():
            try:
                self._append(label_key, str(name), now, float(value))
            except (TypeError, ValueError):
                continue
        for name, summary in (snapshot.get("histograms") or {}).items():
            if not isinstance(summary, Mapping):
                continue
            for field in ("mean", "p50", "p95"):
                value = summary.get(field)
                if value is None:
                    continue
                try:
                    self._append(
                        label_key, f"{name}.{field}", now, float(value)
                    )
                except (TypeError, ValueError):
                    continue

    def _append(
        self,
        label_key: Tuple[Tuple[str, str], ...],
        metric: str,
        now: float,
        value: float,
    ) -> None:
        key = (label_key, metric)
        ring = self._series.get(key)
        if ring is None:
            ring = self._series[key] = collections.deque(maxlen=self._maxlen)
        if ring and now - ring[-1][0] < self.resolution_s:
            # within one resolution bucket: keep the newest reading at
            # the bucket's original timestamp (rings stay retention-bounded)
            ring[-1] = (ring[-1][0], value)
        else:
            ring.append((now, value))

    # -- read surfaces ---------------------------------------------------------

    def history(
        self,
        window_s: Optional[float] = None,
        metric: Optional[str] = None,
        now: Optional[float] = None,
    ) -> Dict[str, List[List[float]]]:
        """``{series_name: [[ts, value], ...]}`` — the ``/metricsz``
        body.  ``window_s`` keeps only points newer than ``now -
        window_s``; ``metric`` filters by exact name or prefix (so
        ``?metric=serve.`` selects the whole family)."""
        now = time.time() if now is None else float(now)
        cutoff = None if window_s is None else now - float(window_s)
        out: Dict[str, List[List[float]]] = {}
        with self._lock:
            items = sorted(self._series.items(), key=lambda kv: (kv[0][1], kv[0][0]))
            for (label_key, name), ring in items:
                if metric and not (name == metric or name.startswith(metric)):
                    continue
                points = [
                    [ts, value]
                    for ts, value in ring
                    if cutoff is None or ts >= cutoff
                ]
                if points:
                    out[series_name(name, label_key)] = points
        return out

    def window(
        self,
        metrics: Sequence[str],
        window_s: float,
        now: Optional[float] = None,
    ) -> Dict[str, List[List[float]]]:
        """The justification slice an autoscaler decision carries: the
        named metrics' recent points (all label sets), compact."""
        now = time.time() if now is None else float(now)
        cutoff = now - float(window_s)
        wanted = set(metrics)
        out: Dict[str, List[List[float]]] = {}
        with self._lock:
            for (label_key, name), ring in self._series.items():
                if name not in wanted:
                    continue
                points = [[ts, value] for ts, value in ring if ts >= cutoff]
                if points:
                    out[series_name(name, label_key)] = points
        return out

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "series": len(self._series),
                "samples": self._samples,
                "resolution_s": self.resolution_s,
                "retention_s": self.retention_s,
            }

    @property
    def series_count(self) -> int:
        with self._lock:
            return len(self._series)


class MetricsSampler:
    """Daemon-thread sampler: one target, one store, one cadence.

    ``target`` is sampled via its ``metrics_snapshots()`` when it has
    one (service / router / balancer — per-member labels come free), a
    parts-returning callable (``telemetry.live.live_parts``), or a bare
    registry's ``snapshot()``.  ``start=False`` skips the thread so
    tests drive :meth:`sample` deterministically."""

    def __init__(
        self,
        target: Any,
        store: Optional[TimeSeriesStore] = None,
        cadence_s: float = 1.0,
        registry=None,
        start: bool = True,
    ) -> None:
        cadence_s = float(cadence_s)
        if cadence_s <= 0:
            # cadence 0 means "off", and off means NOT CONSTRUCTED —
            # the wiring sites (build.serve_from_archive,
            # serving.incident.attach_flight_recorder) own that gate
            raise ValueError(
                f"sampler cadence_s must be > 0, got {cadence_s!r}"
            )
        self.target = target
        self.store = store if store is not None else TimeSeriesStore()
        self.cadence_s = cadence_s
        self._tel = registry if registry is not None else get_registry()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="memvul-tsdb-sampler", daemon=True
            )
            self._thread.start()

    # -- one sample ------------------------------------------------------------

    def _parts(self) -> Sequence[Tuple[Mapping[str, str], Mapping[str, Any]]]:
        snapshots = getattr(self.target, "metrics_snapshots", None)
        if snapshots is not None:
            return snapshots()
        if callable(self.target):  # live_parts-style provider
            return self.target()
        return [({}, self.target.snapshot())]

    def sample(self, now: Optional[float] = None) -> None:
        """Take one sample (the loop body; tests call it directly).  A
        failing target read is counted, never raised — a half-dead
        replica mid-sweep must not kill the history of its death."""
        t0 = time.perf_counter()
        try:
            parts = self._parts()
            self.store.observe(parts, now=now)
        except Exception:
            self._tel.counter("tsdb.sample_errors").inc()
            logger.exception("tsdb sample failed")
            return
        self._tel.counter("tsdb.samples").inc()
        self._tel.gauge("tsdb.series").set(self.store.series_count)
        self._tel.histogram("tsdb.sample_s").observe(time.perf_counter() - t0)

    def _loop(self) -> None:
        while not self._stop.wait(self.cadence_s):
            self.sample()

    # -- read surfaces ---------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The ``/metricsz`` envelope (history attached by the handler)."""
        return {
            "enabled": True,
            "cadence_s": self.cadence_s,
            **self.store.stats(),
        }

    def history(
        self,
        window_s: Optional[float] = None,
        metric: Optional[str] = None,
    ) -> Dict[str, List[List[float]]]:
        return self.store.history(window_s=window_s, metric=metric)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
