"""Declarative alert rules over the in-process metrics history.

The TSDB (telemetry/timeseries.py) answers "what happened"; this module
answers "should someone look".  Rules are tiny declarative records
evaluated over :class:`~memvul_tpu.telemetry.timeseries.TimeSeriesStore`
windows — no callbacks in config, no expression language — by an
:class:`AlertEngine` that tracks firing state per rule and emits a
transition record at each edge:

* ``alert_fired`` / ``alert_resolved`` events into ``events.jsonl``
  (the post-mortem trail ``telemetry-report`` renders as the ALERTS
  section), with the rule, the observed value, and the series that
  tripped it;
* ``alert.fired`` / ``alert.resolved`` counters and an
  ``alert.firing`` gauge (how many rules are firing right now);
* registered listeners — the incident flight recorder
  (serving/incident.py) subscribes so an alert edge snapshots a bundle.

Rule kinds (``AlertRule.kind``):

=============  ==============================================================
kind           fires when, over the trailing ``window_s``
=============  ==============================================================
``threshold``  the newest in-window value of any ``metric`` series is
               ``> threshold`` (gauges; e.g. ``slo.burn_rate_fast``)
``rate``       the mean of the in-window ``<metric>.rate`` samples (the
               TSDB's counter→rate derivation) is ``> threshold``
``absence``    the store's newest sample — ANY series — is older than
               ``window_s`` (the sampler, or the whole process, stalled;
               the heartbeat-age rule)
``growth``     the newest value of ``metric`` grew more than
               ``threshold`` (a fraction) over the oldest in-window value
               (the HBM-leak shape: monotone growth, no spike)
``recompile``  any in-window ``<metric>.rate`` sample is positive —
               ``xla.recompiles`` only counts post-warmup traces
               (telemetry/programs.py), so any motion is a mid-serve
               compile
=============  ==============================================================

The default rule set (:func:`default_rules`) covers serve error rate,
dead-letter streaks, sampler/heartbeat stall, HBM growth, recompiles
after warmup, and SLO fast-burn.  Like the TSDB, the engine is only
constructed when ``telemetry.tsdb_cadence_s`` > 0 — disabled runs emit
a byte-identical metric/event set.
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from .registry import get_registry
from .timeseries import TimeSeriesStore

logger = logging.getLogger(__name__)

KIND_THRESHOLD = "threshold"
KIND_RATE = "rate"
KIND_ABSENCE = "absence"
KIND_GROWTH = "growth"
KIND_RECOMPILE = "recompile"
_KINDS = (KIND_THRESHOLD, KIND_RATE, KIND_ABSENCE, KIND_GROWTH, KIND_RECOMPILE)


@dataclasses.dataclass(frozen=True)
class AlertRule:
    """One declarative rule; see the kind table in the module docstring."""

    name: str
    kind: str
    metric: str = ""
    threshold: float = 0.0
    window_s: float = 60.0
    description: str = ""

    def __post_init__(self) -> None:
        if not self.name:
            raise ValueError("alert rule needs a name")
        if self.kind not in _KINDS:
            raise ValueError(
                f"alert rule {self.name!r}: unknown kind {self.kind!r} "
                f"(want one of {_KINDS})"
            )
        if self.kind != KIND_ABSENCE and not self.metric:
            raise ValueError(f"alert rule {self.name!r}: needs a metric")
        if self.window_s <= 0:
            raise ValueError(f"alert rule {self.name!r}: window_s must be > 0")

    def as_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


def default_rules() -> Tuple[AlertRule, ...]:
    """The shipped rule set — the failure shapes PRs 10–17 taught the
    serving tier to survive, now watched instead of grepped for."""
    return (
        AlertRule(
            "serve_error_rate", KIND_RATE, "serve.errors",
            threshold=0.0, window_s=60.0,
            description="dead-lettered batches are resolving client "
                        "requests as errors",
        ),
        AlertRule(
            "dead_letter_streak", KIND_RATE, "serve.dead_letters",
            threshold=0.0, window_s=60.0,
            description="micro-batches are dead-lettering after retries",
        ),
        AlertRule(
            "heartbeat_stalled", KIND_ABSENCE,
            window_s=30.0,
            description="no new metric samples — the sampler (or the "
                        "whole process) has stalled",
        ),
        AlertRule(
            "hbm_growth", KIND_GROWTH, "serve.hbm_in_use_bytes",
            threshold=0.2, window_s=300.0,
            description="live HBM grew >20% over the window (leak shape)",
        ),
        AlertRule(
            "recompile_after_warm", KIND_RECOMPILE, "xla.recompiles",
            threshold=0.0, window_s=300.0,
            description="a warm scope traced — a mid-serve compile "
                        "latency cliff",
        ),
        AlertRule(
            "slo_fast_burn", KIND_THRESHOLD, "slo.burn_rate_fast",
            threshold=1.0, window_s=60.0,
            description="fast-window error-budget burn rate over 1",
        ),
    )


class AlertEngine:
    """Evaluate rules over a store on a fixed interval; track edges.

    Reads snapshots only (the MV102 discipline — ``status()`` is safe
    from any handler thread); all heavy work is dict-building.
    ``start=False`` skips the thread so tests drive :meth:`tick`."""

    def __init__(
        self,
        store: TimeSeriesStore,
        registry=None,
        rules: Optional[Sequence[AlertRule]] = None,
        interval_s: float = 5.0,
        start: bool = True,
    ) -> None:
        self.store = store
        self.rules: Tuple[AlertRule, ...] = tuple(
            rules if rules is not None else default_rules()
        )
        names = [rule.name for rule in self.rules]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate alert rule names in {names}")
        self.interval_s = float(interval_s)
        self._tel = registry if registry is not None else get_registry()
        self._lock = threading.Lock()
        self._firing: Dict[str, Dict[str, Any]] = {}
        self._listeners: List[Callable[[Dict[str, Any]], None]] = []
        # grace anchor: before the first sample lands, "newest sample"
        # for the absence rule is the engine's own birth, not -inf
        self._started_wall = time.time()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if start:
            self._thread = threading.Thread(
                target=self._loop, name="memvul-alert-engine", daemon=True
            )
            self._thread.start()

    # -- listeners -------------------------------------------------------------

    def add_listener(self, fn: Callable[[Dict[str, Any]], None]) -> None:
        """``fn(record)`` runs on the engine thread at each FIRE edge
        (not resolves).  Must be cheap and non-blocking — the incident
        recorder's ``trigger`` is a bounded-queue put.  A raising
        listener is swallowed and logged, never kills the engine."""
        with self._lock:
            self._listeners.append(fn)

    # -- evaluation ------------------------------------------------------------

    def _evaluate(
        self, rule: AlertRule, now: float
    ) -> Tuple[bool, Optional[float], Optional[str]]:
        """(firing, observed value, offending series name)."""
        if rule.kind == KIND_ABSENCE:
            newest = self._started_wall
            history = self.store.history(now=now)
            for points in history.values():
                newest = max(newest, points[-1][0])
            age = now - newest
            return age > rule.window_s, age, None
        metric = (
            f"{rule.metric}.rate"
            if rule.kind in (KIND_RATE, KIND_RECOMPILE)
            else rule.metric
        )
        history = self.store.history(
            window_s=rule.window_s, metric=metric, now=now
        )
        worst: Tuple[bool, Optional[float], Optional[str]] = (False, None, None)
        for name, points in history.items():
            base = name.partition("{")[0]
            if base != metric:
                continue  # prefix match pulled in a sibling series
            if rule.kind == KIND_THRESHOLD:
                value = points[-1][1]
                fired = value > rule.threshold
            elif rule.kind == KIND_RATE:
                value = sum(p[1] for p in points) / len(points)
                fired = value > rule.threshold
            elif rule.kind == KIND_RECOMPILE:
                value = max(p[1] for p in points)
                fired = value > 0.0
            else:  # KIND_GROWTH
                oldest, newest = points[0][1], points[-1][1]
                if oldest <= 0:
                    continue
                value = (newest - oldest) / oldest
                fired = value > rule.threshold
            if worst[1] is None or (value is not None and value > worst[1]):
                worst = (fired, value, name)
            if fired:
                return True, value, name
        return worst

    def tick(self, now: Optional[float] = None) -> Dict[str, Any]:
        """One evaluation pass over every rule; returns :meth:`status`.
        Wall-clock based (the store's timestamps are wall time)."""
        now = time.time() if now is None else float(now)
        fired_records: List[Dict[str, Any]] = []
        with self._lock:
            listeners = list(self._listeners)
            for rule in self.rules:
                try:
                    firing, value, series = self._evaluate(rule, now)
                except Exception:  # pragma: no cover - a bad series must
                    logger.exception(  # not kill the engine
                        "alert rule %s evaluation failed", rule.name
                    )
                    continue
                active = self._firing.get(rule.name)
                if firing and active is None:
                    record = {
                        "rule": rule.name,
                        # "rule_kind", not "kind": the record doubles as
                        # the alert_fired event payload, and "kind" is
                        # the event stream's own discriminator
                        "rule_kind": rule.kind,
                        "metric": rule.metric,
                        "threshold": rule.threshold,
                        "window_s": rule.window_s,
                        "value": value,
                        "series": series,
                        "fired_wall": now,
                        "description": rule.description,
                    }
                    self._firing[rule.name] = record
                    fired_records.append(dict(record))
                elif firing and active is not None:
                    active["value"] = value
                    active["series"] = series
                elif not firing and active is not None:
                    resolved = self._firing.pop(rule.name)
                    self._tel.counter("alert.resolved").inc()
                    self._tel.event(
                        "alert_resolved",
                        rule=rule.name,
                        duration_s=round(now - resolved["fired_wall"], 3),
                        value=value,
                    )
            firing_count = len(self._firing)
        for record in fired_records:
            self._tel.counter("alert.fired").inc()
            self._tel.event("alert_fired", **record)
            logger.warning(
                "ALERT %s fired: value=%s series=%s (%s)",
                record["rule"], record["value"], record["series"],
                record["description"],
            )
            for fn in listeners:
                try:
                    fn(record)
                except Exception:  # pragma: no cover - defensive
                    logger.exception("alert listener failed")
        self._tel.gauge("alert.firing").set(firing_count)
        return self.status()

    # -- read surface ----------------------------------------------------------

    def status(self) -> Dict[str, Any]:
        """The ``GET /alertz`` body: every rule with its firing state,
        plus the currently-firing records — a snapshot read."""
        with self._lock:
            firing = [dict(record) for record in self._firing.values()]
            rules = [
                {**rule.as_dict(), "firing": rule.name in self._firing}
                for rule in self.rules
            ]
        return {
            "enabled": True,
            "interval_s": self.interval_s,
            "firing": firing,
            "rules": rules,
        }

    def _loop(self) -> None:
        while not self._stop.wait(max(0.05, self.interval_s)):
            try:
                self.tick()
            except Exception:  # pragma: no cover - the engine outlives
                logger.exception("alert tick failed")  # one bad pass

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)
