"""One-command real-weights F1-parity runner.

The ±0.5-F1 acceptance (BASELINE.md) needs the genuine bert-base-uncased
checkpoint and, ideally, a reference-trained ``model.tar.gz``
(reference: predict_memory.py:62-67) — artifacts a zero-egress
environment cannot fetch.  This module packages the whole chain so that
anyone with network access runs it as ONE command:

    python -m memvul_tpu parity --hf-dir /path/to/bert-base-uncased \\
        [--archive model.tar.gz --corpus test_project.json \\
         --anchors CWE_anchor_golden_project.json] \\
        [--ref-metrics reference_metric.json] [-o parity_out/]

Stages (each skipped cleanly when its inputs are absent):

(a) **convert parity** — HF torch ``BertModel`` forward vs the in-repo
    Flax encoder through :mod:`memvul_tpu.models.convert`, at the
    checkpoint's own geometry, on random inputs; reports the max
    absolute/relative hidden-state error (the logit-level oracle of
    tests/test_convert_parity.py, at real scale).
(b) **archive scoring** — load the reference archive
    (:mod:`memvul_tpu.evaluate.reference_archive`), tokenize with the
    checkpoint's own ``vocab.txt`` (id-level parity-tested vs HF's
    BertTokenizer), and run the full streaming eval
    (reference: predict_memory.py:49-114) over ``--corpus``, writing the
    reference-format result and metric files.
(c) **metric diff** — compare (b)'s metrics against a metric file the
    reference pipeline produced (``--ref-metrics``), flagging any
    divergence beyond the acceptance band.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Dict, Optional, Union

import numpy as np

from ..models.bert import BertConfig, BertEncoder

# max-over-anchors F1 acceptance band, in absolute F1 points (BASELINE.md)
F1_TOLERANCE = 0.005


def hf_geometry(hf_dir: Union[str, Path]) -> BertConfig:
    """Encoder geometry from an HF checkpoint dir's ``config.json``."""
    cfg = json.loads((Path(hf_dir) / "config.json").read_text())
    return BertConfig(
        vocab_size=cfg["vocab_size"],
        hidden_size=cfg["hidden_size"],
        num_layers=cfg["num_hidden_layers"],
        num_heads=cfg["num_attention_heads"],
        intermediate_size=cfg["intermediate_size"],
        max_position_embeddings=cfg.get("max_position_embeddings", 512),
        type_vocab_size=cfg.get("type_vocab_size", 2),
        layer_norm_eps=cfg.get("layer_norm_eps", 1e-12),
    )


def convert_logit_parity(
    hf_dir: Union[str, Path],
    batch: int = 4,
    seq_len: int = 128,
    seed: int = 0,
    atol: float = 5e-4,
) -> Dict[str, Any]:
    """Stage (a): torch-vs-Flax hidden-state parity at checkpoint geometry.

    Loads the torch weights from ``hf_dir`` (``from_pretrained`` on a
    local directory — no network), converts them, and compares the final
    hidden states on random unmasked-and-masked inputs.  fp32 both sides;
    errors come only from op-order differences, so they stay near machine
    epsilon per layer and accumulate with depth — ``atol`` defaults to a
    band that 12-layer bert-base clears by an order of magnitude.
    """
    import torch
    import transformers

    from ..models.convert import convert_bert_state_dict

    config = hf_geometry(hf_dir)
    model = transformers.BertModel.from_pretrained(
        str(hf_dir), local_files_only=True
    ).eval()

    rng = np.random.default_rng(seed)
    ids = rng.integers(
        1, config.vocab_size, size=(batch, seq_len)
    ).astype(np.int32)
    mask = np.ones_like(ids)
    mask[batch // 2 :, seq_len // 2 :] = 0  # exercise padding handling too

    with torch.no_grad():
        theirs = model(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).last_hidden_state.numpy()

    bert_subtree, _ = convert_bert_state_dict(model.state_dict(), config)
    ours = np.asarray(
        BertEncoder(config).apply({"params": bert_subtree}, ids, mask)
    )

    real = mask.astype(bool)  # masked positions are junk on both sides
    diff = np.abs(ours[real] - theirs[real])
    denom = np.maximum(np.abs(theirs[real]), 1e-6)
    result = {
        "geometry": {
            "hidden_size": config.hidden_size,
            "num_layers": config.num_layers,
            "num_heads": config.num_heads,
            "vocab_size": config.vocab_size,
        },
        "batch": batch,
        "seq_len": seq_len,
        "max_abs_err": float(diff.max()),
        "mean_abs_err": float(diff.mean()),
        "max_rel_err": float((diff / denom).max()),
        "atol": atol,
        "ok": bool(diff.max() <= atol),
    }
    return result


def archive_scoring(
    archive: Union[str, Path],
    hf_dir: Union[str, Path],
    corpus: Union[str, Path],
    anchors: Union[str, Path],
    out_dir: Union[str, Path],
    max_length: int = 512,
    batch_size: int = 512,
    thres: float = 0.5,
) -> Dict[str, Any]:
    """Stage (b): score ``corpus`` with the reference-trained archive.

    Geometry comes from the HF checkpoint dir (the archive's config names
    an HF model rather than carrying dims, reference_archive.py), the
    vocabulary from its ``vocab.txt`` (precedence documented in
    data/tokenizer.py — the genuine file gives reference tokenization
    exactly).  Output files follow the reference's result/metric format
    byte-for-byte key-wise (evaluate/measure.py).
    """
    from ..data.readers import MemoryReader
    from ..data.tokenizer import WordPieceTokenizer
    from .predict_memory import test_siamese
    from .reference_archive import load_reference_archive

    vocab = Path(hf_dir) / "vocab.txt"
    if not vocab.exists():
        raise FileNotFoundError(
            f"{vocab} missing — archive scoring needs the checkpoint's own "
            "vocabulary for reference-exact tokenization"
        )
    out = Path(out_dir)
    out.mkdir(parents=True, exist_ok=True)

    config = hf_geometry(hf_dir)
    model, params, stored = load_reference_archive(archive, config)
    tokenizer = WordPieceTokenizer(vocab_path=vocab)
    metrics = test_siamese(
        model,
        params,
        tokenizer,
        test_file=corpus,
        golden_file=anchors,
        out_results=out / "parity_result.json",
        out_metrics=out / "parity_metric.json",
        reader=MemoryReader(anchor_path=str(anchors)),
        use_mesh=False,
        batch_size=batch_size,
        max_length=max_length,
        thres=thres,
    )
    return {
        "archive_config_model": (stored.get("model") or {}).get("type"),
        "result_file": str(out / "parity_result.json"),
        "metric_file": str(out / "parity_metric.json"),
        "metrics": metrics,
    }


def metric_diff(
    ours: Dict[str, float],
    ref_metrics_path: Union[str, Path],
    f1_tolerance: float = F1_TOLERANCE,
) -> Dict[str, Any]:
    """Stage (c): ours vs a reference-produced metric file.

    Compares every shared numeric key; the accept/reject verdict hangs on
    f1 alone (the BASELINE.md criterion)."""
    theirs = json.loads(Path(ref_metrics_path).read_text())
    deltas = {}
    for key, ref_val in theirs.items():
        if isinstance(ref_val, (int, float)) and key in ours:
            deltas[key] = {
                "ours": float(ours[key]),
                "reference": float(ref_val),
                "delta": float(ours[key]) - float(ref_val),
            }
    f1_delta = deltas.get("f1", {}).get("delta")
    return {
        "deltas": deltas,
        "f1_delta": f1_delta,
        "f1_tolerance": f1_tolerance,
        "ok": f1_delta is not None and abs(f1_delta) <= f1_tolerance,
    }


def run_parity(
    hf_dir: Union[str, Path],
    archive: Optional[Union[str, Path]] = None,
    corpus: Optional[Union[str, Path]] = None,
    anchors: Optional[Union[str, Path]] = None,
    ref_metrics: Optional[Union[str, Path]] = None,
    out_dir: Union[str, Path] = "parity_out",
    max_length: int = 512,
    batch_size: int = 512,
    thres: float = 0.5,
    atol: float = 5e-4,
    seq_len: int = 128,
) -> Dict[str, Any]:
    """Run every stage whose inputs are present.  A stage not run appears
    in the report as ``{"skipped": true, "reason": ...}`` (shape-stable
    for programmatic consumers); PARTIALLY supplied stage inputs are an
    error, not a skip — an acceptance run that quietly dropped its
    scoring stage must never read as a pass."""
    scoring_inputs = {"--archive": archive, "--corpus": corpus,
                      "--anchors": anchors}
    supplied = [k for k, v in scoring_inputs.items() if v]
    missing = [k for k, v in scoring_inputs.items() if not v]
    if supplied and missing:
        raise ValueError(
            f"archive scoring needs {', '.join(missing)} too "
            f"(got only {', '.join(supplied)})"
        )
    if ref_metrics and missing:
        raise ValueError(
            "--ref-metrics diffs the archive-scoring metrics — supply "
            "--archive/--corpus/--anchors as well"
        )

    report: Dict[str, Any] = {
        "convert_parity": convert_logit_parity(
            hf_dir, seq_len=seq_len, atol=atol
        )
    }
    ok = report["convert_parity"]["ok"]

    if not missing:
        report["archive_scoring"] = archive_scoring(
            archive, hf_dir, corpus, anchors, out_dir,
            max_length=max_length, batch_size=batch_size, thres=thres,
        )
        if ref_metrics:
            report["metric_diff"] = metric_diff(
                report["archive_scoring"]["metrics"], ref_metrics
            )
            ok = ok and report["metric_diff"]["ok"]
        else:
            report["metric_diff"] = {
                "skipped": True,
                "reason": "pass --ref-metrics <reference metric.json> to "
                "diff against the reference pipeline's own numbers",
            }
    else:
        report["archive_scoring"] = {
            "skipped": True,
            "reason": "pass --archive model.tar.gz --corpus test.json "
            "--anchors golden.json to score a reference-trained checkpoint",
        }
        report["metric_diff"] = {
            "skipped": True,
            "reason": "needs archive scoring first",
        }
    report["ok"] = ok
    return report
