"""Result-file scoring — the reference's ``cal_metrics`` contract.

``{model}_result.json`` holds one JSON line per batch, each line a list of
``{"Issue_Url", "label", "predict": {anchor: score}}`` records
(reference: predict_memory.py:159-197).  ``cal_metrics`` reduces each
record to its best anchor score, thresholds, and writes
``{model}_metric_all.json`` — byte-compatible with the reference so its
own evaluation arithmetic validates this framework's outputs.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Optional, Union

import numpy as np

from ..training.metrics import model_measure


def read_result_lines(path: Union[str, Path]) -> List[Dict]:
    merged: List[Dict] = []
    with open(path) as f:
        for line in f:
            line = line.strip()
            if line:
                merged.extend(json.loads(line))
    return merged


def cal_metrics(
    result_file: Union[str, Path],
    thres: float = 0.5,
    out_file: Optional[Union[str, Path]] = None,
) -> Dict[str, float]:
    """Max-over-anchors vote, threshold at ``thres`` (validation-chosen),
    then the standard measure (reference: predict_memory.py:159-197)."""
    merged = read_result_lines(result_file)
    if not merged:
        empty = {
            "TP": 0, "FN": 0, "TN": 0, "FP": 0, "pd&recall": 0.0,
            "prec": 0.0, "f1": 0.0, "ap": 0.0, "auc": 0.0, "thres": thres,
        }
        if out_file is not None:
            Path(out_file).write_text(json.dumps(empty, indent=4))
        return empty
    labels, preds, scores = [], [], []
    for sample in merged:
        prediction = sample["predict"]
        vote = float(np.max(list(prediction.values()))) if isinstance(
            prediction, dict
        ) else float(prediction)
        labels.append(0 if sample["label"] == "neg" else 1)
        preds.append(1 if vote >= thres else 0)
        scores.append(vote)
    measured = model_measure(labels, preds, scores)
    measured["thres"] = thres
    if out_file is None:
        stem = Path(result_file)
        name = stem.name.rsplit("_", 1)[0] + "_metric_all.json"
        out_file = stem.with_name(name)
    Path(out_file).write_text(json.dumps(measured, indent=4))
    return measured
