from .measure import cal_metrics  # noqa: F401
from .predict_memory import SiamesePredictor, test_siamese  # noqa: F401
from .predict_single import SinglePredictor, test_single  # noqa: F401
