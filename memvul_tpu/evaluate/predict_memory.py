"""Siamese memory-model inference — the north-star scoring path.

Reference flow (predict_memory.py:49-114): load the archived model,
pre-encode the anchor bank in chunks of ≤128, stream the test set at
batch 512, write per-sample anchor-score dicts, then ``cal_metrics``.

TPU redesign: the anchor bank is encoded by one jitted forward and kept
device-resident; scoring is a single fused program — BERT encode + the
decomposed anchor match + per-anchor softmax — ``pjit``-sharded over the
``data`` axis of a mesh, so the 1.2M-report corpus streams through all
chips with host-side tokenization prefetched off the critical path.
"""

from __future__ import annotations

import contextlib
import json
import logging
import time
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence, Tuple, Union

import jax
import numpy as np

from ..data.batching import (
    LABELS_SIAMESE,
    CachedEncoder,
    _pad_block,
    batches_from_instances,
    bucket_batch_sizes,
    bucketed_batches_from_instances,
    inflight_pipeline,
    prefetch,
    validate_buckets,
)
from ..data.readers import MemoryReader
from ..models.memory import MemoryModel, anchor_probs
from ..parallel.mesh import MODEL_AXIS, create_mesh, replicate, shard_batch
from ..resilience import faults
from ..resilience.journal import DeadLetter, ScoreJournal
from ..resilience.retry import RetryPolicy, exception_text
from ..telemetry import get_registry
from ..telemetry.programs import get_program_registry
from ..training.metrics import SiameseMeasure
from .measure import cal_metrics

logger = logging.getLogger(__name__)


class SiamesePredictor:
    def __init__(
        self,
        model: MemoryModel,
        params,
        tokenizer,
        mesh=None,
        batch_size: int = 512,
        max_length: int = 512,
        buckets: Optional[Sequence[int]] = None,
        tokens_per_batch: Optional[int] = None,
        anchor_chunk: int = 128,
        anchor_match_impl: Optional[str] = None,
        aot_warmup: bool = True,
        score_impl: str = "bucketed",
        token_budget: Optional[int] = None,
        max_rows_per_pack: Optional[int] = None,
        program_registry=None,
        encoder_precision: str = "fp32",
        cascade_low: float = 0.3,
        cascade_high: float = 0.7,
    ) -> None:
        self.model = model
        self.mesh = mesh
        self.batch_size = batch_size
        # every lower().compile() routes through this registry's
        # chokepoint (telemetry/programs.py; checker MV405) — replica
        # factories pass their own instance, everything else shares the
        # process-wide one
        self.programs = (
            program_registry if program_registry is not None
            else get_program_registry()
        )
        # a fresh predictor has warmed nothing yet: re-traces before its
        # warmup completes are expected, not recompile regressions
        self.programs.mark_warm("score", warm=False)
        self.anchor_chunk = anchor_chunk
        self.encoder = CachedEncoder(tokenizer, max_length=max_length)
        self.buckets = validate_buckets(buckets, max_length) if buckets else None
        # ragged serve path (docs/ragged_serving.md): ONE compiled
        # program over a fixed [1, token_budget] packed batch replaces
        # the per-bucket program grid; warmup/scoring/swap all route on
        # this knob, so the bucketed contract is untouched by default
        if score_impl not in ("bucketed", "ragged", "continuous", "cascade"):
            raise ValueError(
                f"score_impl must be 'bucketed', 'ragged', 'continuous' or "
                f"'cascade', got {score_impl!r}"
            )
        if score_impl in ("ragged", "continuous", "cascade") and mesh is not None:
            raise ValueError(
                f"score_impl={score_impl!r} serves a single-device predictor; "
                "scale out with serving replicas, not a mesh"
            )
        if encoder_precision not in ("fp32", "int8"):
            raise ValueError(
                f"encoder_precision must be 'fp32' or 'int8', "
                f"got {encoder_precision!r}"
            )
        if score_impl == "cascade" and encoder_precision != "int8":
            raise ValueError(
                "score_impl='cascade' needs the int8 tier: pass "
                "encoder_precision='int8'"
            )
        if encoder_precision == "int8" and score_impl in ("ragged", "continuous"):
            raise ValueError(
                f"encoder_precision='int8' builds the bucketed program grid; "
                f"score_impl={score_impl!r} is not cascadable"
            )
        if not (0.0 <= cascade_low <= cascade_high <= 1.0):
            raise ValueError(
                f"cascade band must satisfy 0 <= low <= high <= 1, got "
                f"[{cascade_low!r}, {cascade_high!r}]"
            )
        self.score_impl = score_impl
        self.encoder_precision = encoder_precision
        # [low, high] max-anchor-probability band (inclusive): cascade
        # rows landing inside are re-dispatched to the fp32 program,
        # everything outside short-circuits on the int8 tier
        self.cascade_band = (float(cascade_low), float(cascade_high))
        if token_budget is None:
            token_budget = 4 * max_length
        if token_budget < max_length:
            raise ValueError(
                f"token_budget {token_budget} < max_length {max_length}: one "
                "cap-length request must fit a pack"
            )
        self.token_budget = int(token_budget)
        self.max_rows_per_pack = int(
            max_rows_per_pack if max_rows_per_pack is not None else batch_size
        )
        if self.max_rows_per_pack < 1:
            raise ValueError("max_rows_per_pack must be >= 1")
        # constant-token-budget batching: short buckets run bigger batches
        if self.buckets and tokens_per_batch:
            n_data = mesh.shape.get("data", 1) if mesh is not None else 1
            self.bucket_sizes = bucket_batch_sizes(
                self.buckets, tokens_per_batch, multiple_of=8 * n_data
            )
        else:
            self.bucket_sizes = None
        self.params = replicate(params, mesh) if mesh is not None else params
        self.anchor_bank = None  # [A(+pad), D] device array
        self.n_anchors = 0  # real (unpadded) bank size
        self.anchor_labels: List[str] = []
        n_model = mesh.shape.get(MODEL_AXIS, 1) if mesh is not None else 1
        if n_model > 1 and anchor_match_impl not in (None, "xla"):
            # a model-sharded bank needs XLA's SPMD partitioner to split
            # the |u−v| contraction over the mesh; the Pallas kernel has
            # no sharded lowering, so the fused path is forced off
            logger.info(
                "anchor bank is model-sharded (×%d): forcing "
                "anchor_match_impl='xla' (was %r)", n_model, anchor_match_impl,
            )
        self.anchor_match_impl = "xla" if n_model > 1 else anchor_match_impl
        self.aot_warmup = aot_warmup
        # compile-count probe: increments only when jit misses its cache
        # and traces (once per batch shape) — after warmup_compile() it
        # must stay flat for every shape in the bucket set
        self.score_trace_count = 0

        # int8 tier: the SAME params serve a quantized twin of the model
        # (BertConfig.quant="int8") whose per-column weight quant is
        # cached ONCE here, at build time, into the "quant" collection —
        # the jitted int8 forward then reads it as a plain input (no
        # per-call re-quantization, no new checkpoint format)
        self._int8_model = None
        self.int8_params = None
        if encoder_precision == "int8":
            self.programs.mark_warm("score_int8", warm=False)
            self._int8_model = self.model.clone(
                config=self.model.config.replace(quant="int8")
            )
            dummy = {
                "input_ids": np.zeros((1, 8), np.int32),
                "attention_mask": np.ones((1, 8), np.int32),
            }
            _, qvars = self._int8_model.apply(
                self.params, dummy, deterministic=True, mutable=["quant"]
            )
            self.int8_params = {**self.params, "quant": qvars["quant"]}

        self._encode_fn = jax.jit(
            lambda p, b: self.model.apply(p, b, deterministic=True)
        )
        self._build_score_fn()

    def _build_score_fn(self) -> None:
        """(Re)build the jitted score programs.  Reads
        ``self.anchor_match_impl`` at trace time, so a degradation to
        "xla" only needs a fresh jit wrapper (old fused executables die
        with the old wrapper's cache).  The ragged program shares the
        ``score_trace_count`` probe: after a ragged warmup, ANY length
        mix must dispatch without a new trace — the single-warm-program
        contract the serving tests pin."""

        def _score(p, b, bank):
            self.score_trace_count += 1  # host-side, runs at trace only
            self.programs.note_trace(
                "score", self.bucket_program_key(*b["input_ids"].shape)
            )
            return anchor_probs(
                self.model.apply(
                    p, b, anchors=bank, deterministic=True,
                    anchor_impl=self.anchor_match_impl,
                )
            )

        self._score_fn = jax.jit(_score)

        def _score_ragged(p, sample, bank):
            self.score_trace_count += 1  # host-side, runs at trace only
            self.programs.note_trace("score", self.ragged_program_key())
            return anchor_probs(
                self.model.apply(
                    p, sample, bank, deterministic=True,
                    anchor_impl=self.anchor_match_impl,
                    method=type(self.model).score_ragged,
                )
            )

        self._ragged_score_fn = jax.jit(_score_ragged)

        if self._int8_model is not None:
            def _score_int8(p, b, bank):
                self.score_trace_count += 1  # host-side, runs at trace only
                self.programs.note_trace(
                    "score_int8", self.int8_program_key(*b["input_ids"].shape)
                )
                return anchor_probs(
                    self._int8_model.apply(
                        p, b, anchors=bank, deterministic=True,
                        anchor_impl=self.anchor_match_impl,
                    )
                )

            self._int8_score_fn = jax.jit(_score_int8)

    def _maybe_degrade_to_xla(self, error: BaseException) -> bool:
        """Mosaic/Pallas failures that escaped the trace-time fallback in
        ``ops.pallas.anchor_match`` (they surface at the enclosing jit's
        *compile*): rebuild the score program on the jnp decomposition —
        parity-pinned ≤1e-5 vs fused — instead of aborting the run.
        Returns True when the caller should retry the failed operation."""
        if self.anchor_match_impl == "xla":
            return False
        text = f"{type(error).__name__}: {error}".lower()
        if not any(m in text for m in ("mosaic", "pallas", "lowering")):
            return False
        logger.warning(
            "score program failed to build on the fused anchor-match "
            "kernel (%s) — degrading to anchor_match_impl='xla' "
            "(identical scores; see docs/anchor_match_kernel.md)",
            f"{type(error).__name__}: {error}",
        )
        get_registry().counter("score.degradations").inc()
        self.anchor_match_impl = "xla"
        self._build_score_fn()
        return True

    # -- phase 1: anchor bank ------------------------------------------------

    def encode_anchors(self, anchor_instances: Iterable[Dict]) -> None:
        """Encode anchors in fixed-size chunks (reference encodes ≤128 at a
        time, predict_memory.py:81-83) and cache the bank on device."""
        with get_registry().span("anchor_encode"):
            self._encode_anchors(anchor_instances)

    def _encode_anchors(self, anchor_instances: Iterable[Dict]) -> None:
        bank, labels, n_anchors = self.encode_bank(anchor_instances)
        self.anchor_bank = bank
        self.anchor_labels = labels
        self.n_anchors = n_anchors
        n_model = self.mesh.shape.get(MODEL_AXIS, 1) if self.mesh is not None else 1
        logger.info(
            "anchor bank: %d anchors (%d padded), dim %d, model-sharding ×%d",
            n_anchors, bank.shape[0] - n_anchors, bank.shape[1], n_model,
        )
        if self.aot_warmup:
            self.warmup_compile()

    def encode_bank(
        self, anchor_instances: Iterable[Dict]
    ) -> Tuple[jax.Array, List[str], int]:
        """Encode an anchor set into a device-resident bank WITHOUT
        installing it — the serving hot-swap path builds the replacement
        bank here while the old one keeps serving, then installs its own
        versioned snapshot (serving/service.py:swap_bank).  Returns
        ``(bank, labels, n_real)``; the bank includes any model-sharding
        padding rows, ``n_real`` is the unpadded anchor count."""
        instances = list(anchor_instances)
        labels = [inst["meta"]["label"] for inst in instances]
        chunks: List[np.ndarray] = []
        for start in range(0, len(instances), self.anchor_chunk):
            chunk = instances[start : start + self.anchor_chunk]
            texts = [inst["text1"] for inst in chunk]
            seqs = self.encoder.encode_many(texts)
            ids = np.full(
                (self.anchor_chunk, self.encoder.max_length),
                self.encoder.pad_id,
                dtype=np.int32,
            )
            mask = np.zeros_like(ids)
            for i, seq in enumerate(seqs):
                ids[i, : len(seq)] = seq
                mask[i, : len(seq)] = 1
            batch = {"input_ids": ids, "attention_mask": mask}
            if self.mesh is not None:
                batch = replicate(batch, self.mesh)
            embeddings = np.asarray(self._encode_fn(self.params, batch))
            chunks.append(embeddings[: len(chunk)])
        bank = np.concatenate(chunks, axis=0)
        n_anchors = bank.shape[0]
        n_model = self.mesh.shape.get(MODEL_AXIS, 1) if self.mesh is not None else 1
        if n_model > 1:
            # CWE-1000 stretch: shard the anchor axis over "model" so the
            # [B, A, D] |u−v| intermediate of the bank match (the only
            # O(B·A·D) tensor, models/memory.py:match_anchors) splits
            # across both mesh axes; zero-pad rows to divisibility — their
            # scores are sliced off before anything downstream sees them
            from jax.sharding import NamedSharding, PartitionSpec as P

            pad = (-n_anchors) % n_model
            if pad:
                bank = np.concatenate(
                    [bank, np.zeros((pad, bank.shape[1]), bank.dtype)], axis=0
                )
            device_bank = jax.device_put(
                bank, NamedSharding(self.mesh, P(MODEL_AXIS, None))
            )
        elif self.mesh is not None:
            device_bank = replicate(bank, self.mesh)
        else:
            device_bank = jax.device_put(bank)
        return device_bank, labels, n_anchors

    # -- phase 1.5: AOT shape warmup -----------------------------------------

    def stream_shapes(self) -> List[Tuple[int, int]]:
        """The closed (rows, seq_len) shape set streaming can produce.

        With buckets every batch is one of the bucket lengths at its
        fixed row count (tails are dead-row padded to the same shape);
        without buckets everything pads to (batch_size, max_length)."""
        if self.buckets is None:
            return [(self.batch_size, self.encoder.max_length)]
        sizes = self.bucket_sizes or {b: self.batch_size for b in self.buckets}
        return [(sizes[b], b) for b in self.buckets]

    def warmup_compile(self) -> int:
        """AOT-precompile the score program for every stream shape.

        XLA compiles one program per input shape, and at base geometry a
        compile is multi-second; without warmup the first occurrence of
        each bucket shape mid-stream stalls the inflight pipeline behind
        it.  ``jit(...).lower(...).compile()`` populates the same
        executable cache the streaming calls hit, so after this returns
        no shape in the bucket set can trigger a mid-stream compile
        (asserted via the ``score_trace_count`` probe in tests).
        Returns the number of shapes compiled.
        """
        if self.anchor_bank is None:
            raise RuntimeError("call encode_anchors() first")
        return self.warmup_bank_shapes(self.anchor_bank)

    def bucket_program_key(self, rows: int, length: int) -> str:
        """Program-registry key for one bucketed score shape — shared
        between warmup registration, trace attribution, and the serving
        tier's per-dispatch invocation accounting."""
        return f"score:{rows}x{length}"

    def int8_program_key(self, rows: int, length: int) -> str:
        """Program-registry key for one int8-tier score shape — its own
        ``score_int8`` scope, so ``xla.membw_util``/``xla.mfu`` split by
        tier and the memory-bound premise is checkable per device."""
        return f"score_int8:{rows}x{length}"

    def ragged_program_key(self) -> str:
        """Program-registry key for the single ragged score program."""
        return (
            f"score_ragged:budget={self.token_budget}"
            f",rows={self.max_rows_per_pack}"
        )

    def ragged_shape(self) -> Tuple[int, int]:
        """The single (token_budget, max_rows) geometry the ragged score
        program compiles at — every pack dispatches this one shape."""
        return (self.token_budget, self.max_rows_per_pack)

    @property
    def uses_ragged_program(self) -> bool:
        """Whether this predictor scores through the single packed
        ``[1, token_budget]`` program — true for the ragged pull AND
        the continuous-admission serve impl, which shares the warm
        program and differs only in how the serving tier fills packs
        (serving/dispatch.py)."""
        return self.score_impl in ("ragged", "continuous")

    def _ragged_warm_sample(self) -> Dict[str, np.ndarray]:
        """A representative (content-irrelevant) pack at the warm
        geometry — what ``lower().compile()`` keys the executable on."""
        from ..data.batching import collate_ragged

        return collate_ragged(
            [[self.encoder.pad_id]], self.token_budget,
            self.max_rows_per_pack, self.encoder.pad_id,
        )

    def warmup_bank_shapes(self, bank) -> int:
        """:meth:`warmup_compile` against an explicit bank array — the
        serving hot-swap path warms a *replacement* bank's shapes here
        before installing it, so a bank of a new geometry still never
        costs a mid-serve compile (docs/serving.md).

        With ``score_impl="ragged"`` or ``"continuous"`` this warms
        exactly ONE program — the packed ``[1, token_budget]`` score
        program that serves any length mix — instead of the per-bucket
        grid (docs/ragged_serving.md).  The bucketed ``score_instances``
        path on such a predictor still works but compiles lazily."""
        # warmup (or a hot-swap re-warmup) legitimately traces: unlatch
        # the warm flag so those traces don't read as recompiles, then
        # re-latch once every warmed shape is registered
        self.programs.mark_warm("score", warm=False)
        if self.uses_ragged_program:
            start = time.perf_counter()
            tel = get_registry()
            with tel.span("aot_warmup", shapes=1):
                tel.progress()
                try:
                    self.programs.compile_and_register(
                        self.ragged_program_key(),
                        self._ragged_score_fn.lower(
                            self.params, self._ragged_warm_sample(), bank
                        ),
                        scope="score",
                    )
                except Exception as e:
                    if not self._maybe_degrade_to_xla(e):
                        raise
                    return self.warmup_bank_shapes(bank)
            self.programs.mark_warm("score")
            logger.info(
                "AOT warmup: 1 ragged score program (budget=%d, max_rows=%d) "
                "compiled in %.1fs — replaces the bucket grid",
                self.token_budget, self.max_rows_per_pack,
                time.perf_counter() - start,
            )
            return 1
        shapes = self.stream_shapes()
        start = time.perf_counter()
        tel = get_registry()
        with tel.span("aot_warmup", shapes=len(shapes)):
            for rows, length in shapes:
                tel.progress()  # each compile is progress, not a stall
                sample = {
                    "input_ids": np.zeros((rows, length), np.int32),
                    "attention_mask": np.ones((rows, length), np.int32),
                }
                if self.mesh is not None:
                    sample = shard_batch(sample, self.mesh)
                try:
                    self.programs.compile_and_register(
                        self.bucket_program_key(rows, length),
                        self._score_fn.lower(self.params, sample, bank),
                        scope="score",
                    )
                except Exception as e:
                    if not self._maybe_degrade_to_xla(e):
                        raise
                    # the rebuilt program invalidates any shapes already
                    # compiled on the fused one — restart the warmup so
                    # the zero-mid-stream-compile contract still holds
                    return self.warmup_bank_shapes(bank)
        self.programs.mark_warm("score")
        n_compiled = len(shapes)
        if self._int8_model is not None:
            # second warmed program family: the int8 tier compiles the
            # same shape grid over the same (fp32-encoded) bank under its
            # own scope, so a cascade never traces mid-serve on either
            # tier and per-tier roofline gauges stay separable
            self.programs.mark_warm("score_int8", warm=False)
            with tel.span("aot_warmup", shapes=len(shapes)):
                for rows, length in shapes:
                    tel.progress()
                    sample = {
                        "input_ids": np.zeros((rows, length), np.int32),
                        "attention_mask": np.ones((rows, length), np.int32),
                    }
                    try:
                        self.programs.compile_and_register(
                            self.int8_program_key(rows, length),
                            self._int8_score_fn.lower(
                                self.int8_params, sample, bank
                            ),
                            scope="score_int8",
                        )
                    except Exception as e:
                        if not self._maybe_degrade_to_xla(e):
                            raise
                        return self.warmup_bank_shapes(bank)
            self.programs.mark_warm("score_int8")
            n_compiled += len(shapes)
        logger.info(
            "AOT warmup: %d score program(s) %s compiled in %.1fs",
            n_compiled, shapes, time.perf_counter() - start,
        )
        return n_compiled

    # -- phase 2: streaming scoring ------------------------------------------

    def score_instances(
        self,
        instances: Iterable[Dict],
        prefetch_depth: int = 4,
        inflight: int = 2,
        retry_policy: Optional[RetryPolicy] = None,
        with_anchors: bool = False,
    ) -> Iterator[Tuple[np.ndarray, List[Dict]]]:
        """Yields (per-report best anchor probabilities [b, A], metas) per
        batch, padding rows removed.

        ``with_anchors=True`` additionally stamps each meta with the
        winning anchor (``meta["_anchor"]`` id, ``meta["_anchor_index"]``
        bank index) so offline attribution matches what the serving path
        records per response (docs/anchor_bank.md).  Off by default —
        the yielded tuple shape and metas are unchanged otherwise.

        The device dispatch is asynchronous: up to ``inflight`` batches are
        queued on the accelerator before the oldest result is pulled to
        host, so the host-side ``np.asarray`` sync never leaves the chip
        idle between steps (the per-batch host sync was the round-1
        throughput leak).  With buckets set, batches arrive length-binned
        via :func:`bucketed_batches_from_instances`.

        ``retry_policy`` makes a *transient* backend failure on a batch
        (the shared UNAVAILABLE/DEADLINE_EXCEEDED classification,
        resilience/retry.py) cost one re-dispatch instead of the stream:
        failures are caught both at dispatch and at the host-side sync
        where asynchronously-dispatched errors surface.  Non-transient
        errors propagate immediately either way.
        """
        if self.anchor_bank is None:
            raise RuntimeError("call encode_anchors() first")
        if self.buckets is not None:
            batches = bucketed_batches_from_instances(
                instances,
                self.encoder,
                batch_size=self.bucket_sizes or self.batch_size,
                label_map=LABELS_SIAMESE,
                buckets=self.buckets,
            )
        else:
            batches = batches_from_instances(
                instances,
                self.encoder,
                batch_size=self.batch_size,
                label_map=LABELS_SIAMESE,
                pad_to_max=True,
            )
        def dispatch(batch):
            def once():
                # chaos hook: fires per batch, inside the retried window
                faults.fault_point("score.batch")
                sample = batch["sample1"]
                if self.mesh is not None:
                    sample = shard_batch(sample, self.mesh)
                return self._score_fn(self.params, sample, self.anchor_bank)

            try:
                if retry_policy is None:
                    return once()
                return retry_policy.call(once, description="score batch")
            except Exception as e:
                if self._maybe_degrade_to_xla(e):
                    return once()  # re-dispatch through the rebuilt program
                raise

        tel = get_registry()
        latency_hist = tel.histogram("score.batch_latency_s")
        occupancy_hist = tel.histogram("score.bucket_occupancy")
        batches_ctr = tel.counter("score.batches")
        rows_ctr = tel.counter("score.rows")
        last_sync = time.perf_counter()
        for dev, batch in inflight_pipeline(
            prefetch(batches, depth=prefetch_depth), dispatch, inflight=inflight
        ):
            metas = batch["meta"]
            try:
                arr = np.asarray(dev)
            except Exception as e:
                # an asynchronously-dispatched batch failed on device;
                # the error only surfaces here, at the blocking sync
                if retry_policy is None or not retry_policy.is_transient(
                    exception_text(e)
                ):
                    raise
                logger.warning(
                    "batch failed at host sync (%s) — re-dispatching",
                    exception_text(e)[:200],
                )
                get_registry().counter("resilience.retries").inc()
                arr = np.asarray(dispatch(batch))
            # batch telemetry: host-sync-to-host-sync latency (the
            # steady-state inverse throughput under the inflight
            # pipeline), real-row occupancy of the padded batch shape,
            # and a liveness tick the watchdogs age against
            now = time.perf_counter()
            latency_hist.observe(now - last_sync)
            last_sync = now
            occupancy_hist.observe(len(metas) / max(1, arr.shape[0]))
            batches_ctr.inc()
            rows_ctr.inc(len(metas))
            # count-only attribution: dispatch is async, so per-call
            # device time isn't observable here — the sync-to-sync
            # latency histogram above carries the timing story
            self.programs.record_invocation(
                self.bucket_program_key(*batch["sample1"]["input_ids"].shape)
            )
            tel.progress()
            # drop dead rows and any zero-padded anchor columns
            sliced = arr[: len(metas), : self.n_anchors]
            if with_anchors:
                for meta, idx in zip(metas, sliced.argmax(axis=-1)):
                    meta["_anchor_index"] = int(idx)
                    meta["_anchor"] = self.anchor_labels[int(idx)]
            yield sliced, metas

    def score_texts(
        self,
        texts: Sequence[str],
        bank_array=None,
        n_anchors: Optional[int] = None,
        impl: Optional[str] = None,
    ) -> np.ndarray:
        """Score raw texts against a bank through THIS predictor's
        serving impl — bucketed texts route to their warmed bucket
        shapes (the micro-batcher's ``_pad_block`` layout), ragged texts
        pack into the single warmed ``[1, token_budget]`` program.  The
        shadow scorer (bankops/shadow.py) calls this so a shadow score
        is always computed the way the active service would have served
        it, whichever impl is live.  Returns ``[len(texts), n_anchors]``
        probabilities; ``bank_array``/``n_anchors`` default to the
        predictor's own bank.

        ``impl`` overrides the routing on an ``encoder_precision="int8"``
        predictor: ``"bucketed"`` forces the fp32 bucket grid (the
        default here even for ``score_impl="cascade"`` — a shadow tap on
        a cascade service therefore rescores in fp32, which is exactly
        the parity evidence the promotion gate wants), ``"int8"`` scores
        everything on the quantized tier, ``"cascade"`` applies the
        serving cascade rule offline: int8 everywhere, then rows whose
        max-anchor score lands inside ``cascade_band`` (inclusive)
        rescored through the fp32 program."""
        if impl not in (None, "bucketed", "int8", "cascade"):
            raise ValueError(
                f"impl must be None, 'bucketed', 'int8' or 'cascade', "
                f"got {impl!r}"
            )
        if impl in ("int8", "cascade") and self.int8_params is None:
            raise RuntimeError(
                f"impl={impl!r} needs the quantized tier: build the "
                "predictor with encoder_precision='int8'"
            )
        bank = self.anchor_bank if bank_array is None else bank_array
        n = self.n_anchors if n_anchors is None else int(n_anchors)
        if bank is None:
            raise RuntimeError("call encode_anchors() first")
        if not texts:
            return np.zeros((0, n), np.float32)
        seqs = self.encoder.encode_many(list(texts))
        if impl is None and self.uses_ragged_program:
            from ..data.batching import collate_ragged, pack_token_budget

            out = np.zeros((len(texts), n), np.float32)
            budget, max_rows = self.token_budget, self.max_rows_per_pack
            for pack in pack_token_budget(
                [len(s) for s in seqs], budget, max_rows
            ):
                sample = collate_ragged(
                    [seqs[i] for i in pack], budget, max_rows,
                    self.encoder.pad_id,
                )
                probs = np.asarray(
                    self._ragged_score_fn(self.params, sample, bank)
                )[: len(pack), :n]
                for row, i in zip(probs, pack):
                    out[i] = row
            return out
        if impl == "int8":
            return self._score_seqs_bucketed(
                seqs, bank, n, self._int8_score_fn, self.int8_params
            )
        if impl == "cascade":
            out = self._score_seqs_bucketed(
                seqs, bank, n, self._int8_score_fn, self.int8_params
            )
            low, high = self.cascade_band
            best = out.max(axis=1) if n else np.zeros(len(seqs))
            band = [i for i in range(len(seqs)) if low <= best[i] <= high]
            if band:
                rescored = self._score_seqs_bucketed(
                    [seqs[i] for i in band], bank, n,
                    self._score_fn, self.params,
                )
                for row, i in zip(rescored, band):
                    out[i] = row
            return out
        return self._score_seqs_bucketed(
            seqs, bank, n, self._score_fn, self.params
        )

    def _score_seqs_bucketed(
        self, seqs, bank, n: int, score_fn, params
    ) -> np.ndarray:
        """Score encoded sequences through a bucketed program grid —
        grouped by smallest covering warmed length, chunked at the
        bucket's row count, ``_pad_block`` layout (the serving
        micro-batcher's exact geometry)."""
        out = np.zeros((len(seqs), n), np.float32)
        rows_by_length = {
            length: rows for rows, length in self.stream_shapes()
        }
        lengths = sorted(rows_by_length)
        groups: Dict[int, List[int]] = {}
        for i, seq in enumerate(seqs):
            length = next(
                (b for b in lengths if b >= len(seq)), lengths[-1]
            )
            groups.setdefault(length, []).append(i)
        for length in sorted(groups):
            rows = rows_by_length[length]
            indices = groups[length]
            for start in range(0, len(indices), rows):
                chunk = indices[start : start + rows]
                sample = _pad_block(
                    [seqs[i] for i in chunk], rows, self.encoder.pad_id, length
                )
                if self.mesh is not None:
                    sample = shard_batch(sample, self.mesh)
                dev = score_fn(params, sample, bank)
                probs = np.asarray(dev)[: len(chunk), :n]
                for row, i in zip(probs, chunk):
                    out[i] = row
        return out

    def predict_single(self, text: str) -> Dict[str, Union[float, str, int, Dict]]:
        """Score ONE report text and return the full attribution the
        serving path returns per response: the per-anchor probability
        dict, the max score, and the winning anchor's id + bank index.
        Dispatches at the smallest warmed stream shape, so after
        ``warmup_compile`` this never traces (``score_trace_count``
        flat) — the offline twin of one served request."""
        if self.anchor_bank is None:
            raise RuntimeError("call encode_anchors() first")
        from ..data.batching import _pad_block

        seq = self.encoder.encode_many([text])[0]
        # smallest warmed bucket covering the text; over-long texts
        # truncate into the largest (the micro-batcher's _bucket_for rule)
        shapes = sorted(self.stream_shapes(), key=lambda rl: rl[1])
        rows, length = shapes[-1]
        for cand_rows, cand_length in shapes:
            if cand_length >= len(seq):
                rows, length = cand_rows, cand_length
                break
        sample = _pad_block([seq], rows, self.encoder.pad_id, length)
        if self.mesh is not None:
            sample = shard_batch(sample, self.mesh)
        row = np.asarray(
            self._score_fn(self.params, sample, self.anchor_bank)
        )[0, : self.n_anchors]
        best = int(np.argmax(row))
        return {
            "predict": {
                label: float(p)
                for label, p in zip(self.anchor_labels, row)
            },
            "score": float(row[best]),
            "anchor": self.anchor_labels[best],
            "anchor_index": best,
        }

    def predict_file(
        self,
        reader: MemoryReader,
        test_path: Union[str, Path],
        out_path: Union[str, Path],
        split: Optional[str] = None,
        inflight: int = 2,
        resume: bool = False,
        quarantine: Union[bool, str, Path, None] = None,
        heartbeat_batches: int = 0,
        retry_policy: Optional[RetryPolicy] = None,
        expected_reports: Optional[int] = None,
        attribute_anchors: bool = False,
    ) -> Dict[str, float]:
        """Stream a corpus file, write the reference-format result lines,
        return the threshold-swept siamese metrics.

        Serialization (one ~129-float dict per report → JSON) runs on a
        dedicated writer thread: at corpus-scale throughput that is
        hundreds of thousands of float-to-text conversions per second,
        which would otherwise sit on the same thread that syncs device
        results and starve the dispatch pipeline.

        Fault tolerance (docs/fault_tolerance.md):

        * ``resume=True`` keeps an append-only progress journal
          (``<out>.journal``) of committed output lines; a restarted run
          verifies the journal against the output file, skips every
          report the verified prefix covers, and finishes with metrics
          identical to an uninterrupted run.
        * ``quarantine`` (True for ``<out>.deadletter``, or a path)
          dead-letters malformed/over-long records with reasons instead
          of killing the stream.
        * ``heartbeat_batches=N`` logs progress every N batches —
          rows/s, ETA (when ``expected_reports`` is known — the corpus
          streams, so the total is a caller-supplied hint), batches this
          run vs journal total, quarantine count — and writes the run
          dir's ``HEARTBEAT.json`` through the telemetry registry, so a
          stalled corpus run is distinguishable from a slow one.
        * ``retry_policy`` retries transiently-failing batches
          (see :meth:`score_instances`).
        * ``attribute_anchors=True`` adds the winning anchor's id and
          bank index (``"anchor"``/``"anchor_index"``) to every output
          record — flag-gated so the default output stays byte-stable
          with the reference format.
        """
        import queue
        import threading

        out_path = Path(out_path)
        measure = SiameseMeasure()
        n = 0
        n_resumed = 0
        journal: Optional[ScoreJournal] = None
        completed: set = set()
        dead: Optional[DeadLetter] = None
        if quarantine:
            dead_path = (
                Path(quarantine)
                if not isinstance(quarantine, bool)
                else Path(str(out_path) + ".deadletter")
            )
            dead = DeadLetter(dead_path)
        journal_path = Path(str(out_path) + ".journal")
        if resume:
            journal = ScoreJournal(journal_path)
            kept_n, completed, kept_lines = journal.verified_prefix(out_path)
            # drop the unverified tail (torn final line / journal entries
            # whose output never landed) so this run redoes those rows
            journal.truncate_to(kept_n, out_path)
            for line in kept_lines:
                for rec in json.loads(line):
                    preds = rec.get("predict") or {}
                    score = max(preds.values()) if preds else 0.0
                    measure.update([score], [{"label": rec.get("label")}])
                    n += 1
            n_resumed = n
            if kept_n:
                logger.info(
                    "resume: %d journaled output lines verified (%d "
                    "reports) — skipping their spans", kept_n, n_resumed,
                )
        elif journal_path.exists():
            # a fresh (non-resume) run overwrites the output; a stale
            # journal beside it would poison a LATER resume
            journal_path.unlink()

        start = time.perf_counter()
        q: "queue.Queue" = queue.Queue(maxsize=16)
        writer_error: List[BaseException] = []
        failed = threading.Event()

        tel = get_registry()
        commit_lag_hist = tel.histogram("score.journal_commit_lag_s")

        def _writer() -> None:
            try:
                with open(out_path, "a" if resume else "w") as f:
                    while True:
                        item = q.get()
                        if item is None:
                            return
                        probs, metas, enqueued_monotonic = item
                        records = [
                            {
                                "Issue_Url": meta.get("Issue_Url"),
                                "label": meta.get("label"),
                                "predict": {
                                    anchor: float(p)
                                    for anchor, p in zip(self.anchor_labels, row)
                                },
                                **(
                                    {
                                        "anchor": meta.get("_anchor"),
                                        "anchor_index": meta.get("_anchor_index"),
                                    }
                                    if attribute_anchors else {}
                                ),
                            }
                            for row, meta in zip(probs, metas)
                        ]
                        text = json.dumps(records)
                        f.write(text + "\n")
                        if journal is not None:
                            # the journal entry is the durable claim that
                            # the line landed — flush the line first
                            f.flush()
                            journal.append(
                                journal.entries_written,
                                [meta["_row"] for meta in metas],
                                text,
                            )
                            # commit lag: scored-on-host → durable-in-
                            # journal.  A growing lag means the writer
                            # thread (serialization + fsync-side cost)
                            # is falling behind the device
                            commit_lag_hist.observe(
                                time.monotonic() - enqueued_monotonic
                            )
            except BaseException as e:  # propagated to the caller below
                writer_error.append(e)
                failed.set()

        instances = reader.read(str(test_path), split=split, quarantine=dead) \
            if dead is not None else reader.read(str(test_path), split=split)
        if journal is not None:
            instances = _indexed_stream(instances, completed)

        writer = threading.Thread(target=_writer, daemon=True)
        writer.start()
        batches_done = 0
        # rows/sec is sourced from the registry's score.rows counter
        # (delta over this call — the counter is process-cumulative);
        # with telemetry disabled the null counter stays 0 and the local
        # count is the fallback, same number by construction
        rows_ctr_start = tel.counter("score.rows").value
        # an explicit ExitStack (not a nested with) keeps the span's exit
        # inside the finally block without re-indenting the hot loop
        span = contextlib.ExitStack()
        span.enter_context(tel.span("score_stream"))
        try:
            for probs, metas in self.score_instances(
                instances, inflight=inflight, retry_policy=retry_policy,
                with_anchors=attribute_anchors,
            ):
                while not failed.is_set():
                    try:
                        q.put((probs, metas, time.monotonic()), timeout=1.0)
                        break
                    except queue.Full:
                        continue
                if failed.is_set():
                    break
                measure.update(probs.max(axis=-1), metas)
                n += len(metas)
                batches_done += 1
                if heartbeat_batches and batches_done % heartbeat_batches == 0:
                    elapsed = time.perf_counter() - start
                    rows_delta = tel.counter("score.rows").value - rows_ctr_start
                    rows_this_run = rows_delta or (n - n_resumed)
                    rate = rows_this_run / max(elapsed, 1e-9)
                    eta_s = None
                    if expected_reports and rate > 0:
                        eta_s = max(0.0, (expected_reports - n) / rate)
                    logger.info(
                        "scoring heartbeat: %d batches this run (journal "
                        "total %s), %d/%d reports, %.0f rows/s, ETA %s, "
                        "%d quarantined",
                        batches_done,
                        journal.entries_written if journal is not None else "-",
                        rows_this_run, n, rate,
                        f"{eta_s:.0f}s" if eta_s is not None else "unknown",
                        dead.count if dead is not None else 0,
                    )
                    tel.heartbeat(
                        force=True,
                        rows_scored=n,
                        rows_per_sec=round(rate, 1),
                        eta_s=round(eta_s, 1) if eta_s is not None else None,
                    )
        finally:
            # signal end-of-stream with the same failure-aware loop as the
            # data puts: the writer may die (and stop consuming) at any
            # moment, so an unconditional blocking put could deadlock
            while True:
                if failed.is_set():
                    try:
                        while True:
                            q.get_nowait()
                    except queue.Empty:
                        pass
                try:
                    q.put(None, timeout=1.0)
                    break
                except queue.Full:
                    continue
            writer.join()
            if journal is not None:
                journal.close()
            if dead is not None:
                dead.close()
            span.close()
            # final liveness snapshot AFTER the writer drained: its
            # counters (journal.rows_committed et al.) now match what is
            # durably on disk — the invariant the chaos test pins
            tel.heartbeat(force=True, rows_scored=n)
        if writer_error:
            raise writer_error[0]
        elapsed = time.perf_counter() - start
        logger.info(
            "scored %d reports in %.1fs (%.0f reports/s)%s%s",
            n - n_resumed, elapsed, (n - n_resumed) / max(elapsed, 1e-9),
            f", {n_resumed} resumed from journal" if n_resumed else "",
            f", {dead.count} quarantined" if dead is not None and dead.count else "",
        )
        metrics = measure.compute(reset=True)
        metrics["num_samples"] = n
        metrics["elapsed_s"] = elapsed
        if dead is not None:
            metrics["num_quarantined"] = dead.count
        return metrics


def _indexed_stream(instances: Iterable[Dict], completed: set) -> Iterator[Dict]:
    """Stamp each instance's meta with its input-stream index (``_row``,
    what the journal records) and drop the rows a verified resume prefix
    already covers.  Indices number the post-quarantine stream; the
    quarantine's drop decisions are deterministic for a given corpus
    file, so the numbering is stable across a kill/resume boundary."""
    for i, inst in enumerate(instances):
        if i in completed:
            continue
        inst = dict(inst)
        meta = dict(inst.get("meta") or {})
        meta["_row"] = i
        inst["meta"] = meta
        yield inst


def test_siamese(
    model: MemoryModel,
    params,
    tokenizer,
    test_file: Union[str, Path],
    golden_file: Union[str, Path],
    out_results: Union[str, Path],
    out_metrics: Optional[Union[str, Path]] = None,
    reader: Optional[MemoryReader] = None,
    mesh=None,
    use_mesh: bool = True,
    batch_size: int = 512,
    max_length: int = 512,
    buckets: Optional[Sequence[int]] = None,
    tokens_per_batch: Optional[int] = None,
    thres: float = 0.5,
    inflight: int = 2,
    anchor_match_impl: Optional[str] = None,
    aot_warmup: bool = True,
    resume: bool = False,
    quarantine: Union[bool, str, Path, None] = None,
    heartbeat_batches: int = 0,
    score_retries: int = 0,
    expected_reports: Optional[int] = None,
    attribute_anchors: bool = False,
) -> Dict[str, float]:
    """End-to-end evaluation mirroring the reference's ``test_siamese``
    (predict_memory.py:49-114) + ``cal_metrics`` (:159-197).

    ``resume``/``quarantine``/``heartbeat_batches``/``expected_reports``
    are forwarded to :meth:`SiamesePredictor.predict_file`;
    ``score_retries`` > 0 builds the shared transient-failure
    :class:`RetryPolicy` with that attempt budget
    (docs/fault_tolerance.md)."""
    reader = reader or MemoryReader()
    if mesh is None and use_mesh and len(jax.devices()) > 1:
        mesh = create_mesh()
    predictor = SiamesePredictor(
        model,
        params,
        tokenizer,
        mesh=mesh,
        batch_size=batch_size,
        max_length=max_length,
        buckets=buckets,
        tokens_per_batch=tokens_per_batch,
        anchor_match_impl=anchor_match_impl,
        aot_warmup=aot_warmup,
    )
    predictor.encode_anchors(reader.read_anchors(str(golden_file)))
    eval_metrics = predictor.predict_file(
        reader, test_file, out_results, inflight=inflight,
        resume=resume,
        quarantine=quarantine,
        heartbeat_batches=heartbeat_batches,
        retry_policy=RetryPolicy(attempts=score_retries)
        if score_retries > 0 else None,
        expected_reports=expected_reports,
        attribute_anchors=attribute_anchors,
    )
    final = cal_metrics(out_results, thres=thres, out_file=out_metrics)
    final.update({f"s_{k}": v for k, v in eval_metrics.items()})
    return final
