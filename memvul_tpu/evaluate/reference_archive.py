"""Load a reference-format ``model.tar.gz`` (PyTorch/AllenNLP) into the
TPU-native :class:`MemoryModel`.

The reference's training run leaves an archive holding ``config.json``
(the full train config) and ``weights.th`` (the torch state dict of
``model_memory``, reference: predict_memory.py:62-67).  This module maps
that state dict onto our parameter tree so a checkpoint trained by the
reference pipeline can be scored by this framework — the archive-level
half of the F1-parity chain (the tokenizer half lives in
tests/test_tokenizer_hf_parity.py).

State-dict layout consumed (reference: model_memory.py:63-73):

* ``_text_field_embedder.token_embedder_tokens.transformer_model.*`` —
  the HF BertModel (mapped by :mod:`memvul_tpu.models.convert`);
* ``_bert_pooler.pooler.dense.*`` — the fine-tuned tanh pooler (the
  transformer's own frozen pooler copy is ignored, as in the reference
  forward path which only calls ``_bert_pooler``);
* ``_projector_single._linear_layers.0.*`` — the ReLU projection header;
* ``_projector.weight`` — the bias-free [2, 3D] pair classifier.
"""

from __future__ import annotations

import json
import tarfile
import tempfile
from pathlib import Path
from typing import Dict, Tuple, Union

import numpy as np

from ..models.bert import BertConfig
from ..models.convert import _t, convert_bert_state_dict
from ..models.memory import MemoryModel

TRANSFORMER_PREFIX = "_text_field_embedder.token_embedder_tokens.transformer_model."


def _to_numpy(v) -> np.ndarray:
    return np.asarray(v.detach().cpu().numpy() if hasattr(v, "detach") else v)


def convert_memory_state_dict(
    state_dict: Dict, config: BertConfig, use_header: bool = True
) -> Dict:
    """Reference ``model_memory`` state dict → our full params tree."""
    sd = {k: _to_numpy(v) for k, v in state_dict.items()}

    transformer_sd = {
        k[len(TRANSFORMER_PREFIX):]: v
        for k, v in sd.items()
        if k.startswith(TRANSFORMER_PREFIX)
    }
    if not transformer_sd:
        raise KeyError(
            f"no keys under {TRANSFORMER_PREFIX!r} — not a model_memory "
            "state dict?"
        )
    bert_subtree, _ = convert_bert_state_dict(transformer_sd, config)

    params: Dict = {
        "bert": bert_subtree,
        "pooler": {
            "dense": {
                "kernel": _t(sd["_bert_pooler.pooler.dense.weight"]),
                "bias": sd["_bert_pooler.pooler.dense.bias"],
            }
        },
        "pair_kernel": _t(sd["_projector.weight"]),
    }
    if use_header:
        params["header"] = {
            "dense": {
                "kernel": _t(sd["_projector_single._linear_layers.0.weight"]),
                "bias": sd["_projector_single._linear_layers.0.bias"],
            }
        }
    return {"params": params}


def load_reference_archive(
    archive_path: Union[str, Path],
    config: BertConfig,
) -> Tuple[MemoryModel, Dict, Dict]:
    """Reference ``model.tar.gz`` → (model, params, stored_config).

    ``config`` supplies the encoder geometry (the reference config names
    an HF model rather than carrying dims).  Model hyperparameters that
    the archive's config does carry (``use_header``, ``temperature``) are
    honored.
    """
    archive_path = Path(archive_path)
    with tempfile.TemporaryDirectory() as tmp:
        tmp = Path(tmp)
        with tarfile.open(archive_path, "r:gz") as tar:
            tar.extractall(tmp, filter="data")
        stored = json.loads((tmp / "config.json").read_text())
        import torch

        state_dict = torch.load(
            tmp / "weights.th", map_location="cpu", weights_only=True
        )
    model_cfg = stored.get("model") or {}
    use_header = bool(model_cfg.get("use_header", True))
    temperature = float(model_cfg.get("temperature", 0.1))
    header_dim = 512  # reference hardcodes FeedForward(dim, 1, [512], ReLU)
    model = MemoryModel(
        config,
        use_header=use_header,
        header_dim=header_dim,
        temperature=temperature,
    )
    params = convert_memory_state_dict(state_dict, config, use_header=use_header)
    return model, params, stored
