"""Single-model (MemVul-m / TextCNN) inference.

Reference flow (predict_single.py:46-121): stream the test set, record
``{"Issue_Url", "label", "predict", "prob"}`` per report — ``predict`` is
the argmax label, ``prob`` the positive-class probability — then compute
the standard measure without a threshold sweep.
"""

from __future__ import annotations

import json
import logging
import time
import weakref
from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Union

import jax
import numpy as np

from ..data.batching import (
    LABELS_BINARY,
    CachedEncoder,
    batches_from_instances,
    bucket_batch_sizes,
    bucketed_batches_from_instances,
    inflight_pipeline,
    prefetch,
    validate_buckets,
)
from ..data.readers import DatasetReader, SingleReader
from ..parallel.mesh import create_mesh, replicate, shard_batch
from ..telemetry.programs import get_program_registry
from ..training.metrics import model_measure

logger = logging.getLogger(__name__)

POS_INDEX = LABELS_BINARY["pos"]


class _ProbsProgram:
    """One jitted softmax-probs program per model, shared across
    predictor instances.

    Historically every ``SinglePredictor`` jitted a fresh lambda, so
    each ``test_single`` call — and every one-off single-IR score —
    cold-compiled its own executable even for an identical model.  jit
    caches executables *on the function object*; keying the function by
    model (linen modules hash by configuration) makes the second
    predictor over the same model compile-free, the same warmed-program
    contract the scoring service leans on (docs/serving.md).
    ``trace_count`` mirrors ``SiamesePredictor.score_trace_count``: it
    moves only when jit misses its cache and re-traces."""

    def __init__(self, model) -> None:
        self.trace_count = 0
        # program-registry keys already registered through the shared
        # program — a later predictor's warmup skips these outright, so
        # sharing never shows up as a recompile
        self.warmed_keys: set = set()
        get_program_registry().mark_warm("probs", warm=False)

        def _probs(p, b):
            self.trace_count += 1  # host-side, runs at trace only
            get_program_registry().note_trace(
                "probs", "probs:{}x{}".format(*b["input_ids"].shape)
            )
            return jax.nn.softmax(
                model.apply(p, b, deterministic=True).astype(np.float32), axis=-1
            )

        self.fn = jax.jit(_probs)


# weak keys: a program (and its compiled executables) lives exactly as
# long as some caller still holds the model it was traced for
_PROBS_PROGRAMS: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def probs_program(model) -> _ProbsProgram:
    """The shared per-model probs program (see :class:`_ProbsProgram`)."""
    program = _PROBS_PROGRAMS.get(model)
    if program is None:
        program = _PROBS_PROGRAMS[model] = _ProbsProgram(model)
    return program


class SinglePredictor:
    def __init__(
        self,
        model,
        params,
        tokenizer,
        mesh=None,
        batch_size: int = 512,
        max_length: int = 512,
        buckets: Optional[Sequence[int]] = None,
        tokens_per_batch: Optional[int] = None,
        aot_warmup: bool = True,
    ) -> None:
        self.model = model
        self.mesh = mesh
        self.batch_size = batch_size
        self.encoder = CachedEncoder(tokenizer, max_length=max_length)
        self.buckets = validate_buckets(buckets, max_length) if buckets else None
        if self.buckets and tokens_per_batch:
            n_data = mesh.shape.get("data", 1) if mesh is not None else 1
            self.bucket_sizes = bucket_batch_sizes(
                self.buckets, tokens_per_batch, multiple_of=8 * n_data
            )
        else:
            self.bucket_sizes = None
        self.params = replicate(params, mesh) if mesh is not None else params
        self._program = probs_program(model)
        self._probs_fn = self._program.fn
        if aot_warmup:
            self.warmup_compile()

    @property
    def score_trace_count(self) -> int:
        """Traces of the shared probs program (cumulative across every
        predictor over this model — the sharing is the point)."""
        return self._program.trace_count

    def stream_shapes(self) -> List[tuple]:
        """The closed (rows, seq_len) set streaming can produce (the
        same contract as ``SiamesePredictor.stream_shapes``)."""
        if self.buckets is None:
            return [(self.batch_size, self.encoder.max_length)]
        sizes = self.bucket_sizes or {b: self.batch_size for b in self.buckets}
        return [(sizes[b], b) for b in self.buckets]

    def warmup_compile(self) -> int:
        """AOT-precompile the probs program for every stream shape, so a
        one-off score after startup never pays a compile (the shapes are
        in the shared program's jit cache; a later predictor over the
        same model skips even this warmup)."""
        shapes = self.stream_shapes()
        programs = get_program_registry()
        fresh = [
            (rows, length)
            for rows, length in shapes
            if f"probs:{rows}x{length}" not in self._program.warmed_keys
        ]
        if fresh:
            # warming genuinely-new shapes traces; unlatch the warm flag
            # so those traces don't read as recompile regressions
            programs.mark_warm("probs", warm=False)
        for rows, length in fresh:
            sample = {
                "input_ids": np.zeros((rows, length), np.int32),
                "attention_mask": np.ones((rows, length), np.int32),
            }
            if self.mesh is not None:
                sample = shard_batch(sample, self.mesh)
            key = f"probs:{rows}x{length}"
            programs.compile_and_register(
                key, self._probs_fn.lower(self.params, sample), scope="probs"
            )
            self._program.warmed_keys.add(key)
        programs.mark_warm("probs")
        return len(shapes)

    def predict_file(
        self,
        reader: DatasetReader,
        test_path: Union[str, Path],
        out_path: Union[str, Path],
        split: Optional[str] = None,
        inflight: int = 2,
    ) -> Dict[str, float]:
        if self.buckets is not None:
            batches = bucketed_batches_from_instances(
                reader.read(str(test_path), split=split),
                self.encoder,
                batch_size=self.bucket_sizes or self.batch_size,
                label_map=LABELS_BINARY,
                buckets=self.buckets,
            )
        else:
            batches = batches_from_instances(
                reader.read(str(test_path), split=split),
                self.encoder,
                batch_size=self.batch_size,
                label_map=LABELS_BINARY,
                pad_to_max=True,
            )
        labels: List[int] = []
        preds: List[int] = []
        scores: List[float] = []
        n = 0
        start = time.perf_counter()

        def dispatch(batch):
            sample = batch["sample1"]
            if self.mesh is not None:
                sample = shard_batch(sample, self.mesh)
            return self._probs_fn(self.params, sample)

        def _drain(dev_probs, metas, f):
            nonlocal n
            probs = np.asarray(dev_probs)
            records = []
            for row, meta in zip(probs[: len(metas)], metas):
                p_pos = float(row[POS_INDEX])
                predicted = int(np.argmax(row))
                records.append(
                    {
                        "Issue_Url": meta.get("Issue_Url"),
                        "label": meta.get("label"),
                        "predict": "pos" if predicted == POS_INDEX else "neg",
                        "prob": p_pos,
                    }
                )
                labels.append(0 if meta.get("label") == "neg" else 1)
                preds.append(1 if predicted == POS_INDEX else 0)
                scores.append(p_pos)
            n += len(metas)
            f.write(json.dumps(records) + "\n")

        programs = get_program_registry()
        with open(out_path, "w") as f:
            for dev, batch in inflight_pipeline(
                prefetch(batches), dispatch, inflight=inflight
            ):
                # count-only: the dispatch is asynchronous, so per-call
                # device time isn't observable at this drain point
                programs.record_invocation(
                    "probs:{}x{}".format(*batch["sample1"]["input_ids"].shape)
                )
                _drain(dev, batch["meta"], f)
        elapsed = time.perf_counter() - start
        logger.info(
            "scored %d reports in %.1fs (%.0f reports/s)", n, elapsed, n / max(elapsed, 1e-9)
        )
        measured = model_measure(labels, preds, scores)
        measured["num_samples"] = n
        measured["elapsed_s"] = elapsed
        return measured


def test_single(
    model,
    params,
    tokenizer,
    test_file: Union[str, Path],
    out_results: Union[str, Path],
    out_metrics: Optional[Union[str, Path]] = None,
    reader: Optional[DatasetReader] = None,
    mesh=None,
    use_mesh: bool = True,
    batch_size: int = 512,
    max_length: int = 512,
    buckets: Optional[Sequence[int]] = None,
    tokens_per_batch: Optional[int] = None,
    inflight: int = 2,
) -> Dict[str, float]:
    reader = reader or SingleReader()
    if mesh is None and use_mesh and len(jax.devices()) > 1:
        mesh = create_mesh()
    predictor = SinglePredictor(
        model,
        params,
        tokenizer,
        mesh=mesh,
        batch_size=batch_size,
        max_length=max_length,
        buckets=buckets,
        tokens_per_batch=tokens_per_batch,
    )
    measured = predictor.predict_file(
        reader, test_file, out_results, inflight=inflight
    )
    if out_metrics is not None:
        Path(out_metrics).write_text(json.dumps(measured, indent=4))
    return measured
