#!/bin/bash
# Round-4 on-chip agenda, strictly serialized (one JAX client at a time —
# the axon tunnel wedges under concurrent clients; see SMOKE.md header).
#
# Runs, in order of round-3 verdict priority:
#   1. bench.py at the shipped default config     -> the driver-comparable number
#   2. bucket/inflight sweep (verdict #2)         -> pick the shipped default
#   3. flash-vs-xla bench A/B (verdict #3)
#   4. streaming rehearsal 16k vs 100k (verdict #6)
#   5. tpu_proofs: flash(256..4096) flashgrad mlmsmoke trainsmoke trainab bf16drift
#
# Usage: bash tools/round4_onchip.sh [logdir]   (default round4_logs/)
set -u
cd "$(dirname "$0")/.."
LOG=${1:-round4_logs}
mkdir -p "$LOG"

probe() {
  timeout 90 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((128,128)); print('alive', float((x@x).sum()))" >/dev/null 2>&1
}

step() { # step <name> <timeout_s> <cmd...>  (resumable: skips on .done)
  local name=$1 tmo=$2; shift 2
  if [ -f "$LOG/$name.done" ]; then
    echo "=== $name already done — skipping ==="
    return 0
  fi
  # DEADLINE_EPOCH: never let a step outlive the round's tunnel hand-off
  # point (the driver's round-end bench needs exclusive tunnel access —
  # two clients wedge it). Shrink the step timeout to what's left; skip
  # entirely if <120s remain.
  if [ -n "${DEADLINE_EPOCH:-}" ]; then
    local now rem; now=$(date +%s); rem=$(( DEADLINE_EPOCH - now ))
    if [ "$rem" -lt 120 ]; then
      echo "DEADLINE reached before $name — stopping agenda" | tee "$LOG/DEADLINE_STOP"
      exit 4
    fi
    if [ "$rem" -lt "$tmo" ]; then tmo=$rem; fi
  fi
  echo "=== $name ($(date +%H:%M:%S)) ==="
  if ! probe; then
    echo "TUNNEL DEAD before $name — aborting remaining steps" | tee "$LOG/ABORTED"
    exit 3
  fi
  ( "$@" ) > "$LOG/$name.out" 2> "$LOG/$name.err" &
  local pid=$!
  if ! timeout "$tmo" tail --pid=$pid -f /dev/null; then
    echo "$name TIMED OUT after ${tmo}s — killing" | tee -a "$LOG/$name.err"
    kill -9 $pid 2>/dev/null
    sleep 5
  fi
  wait $pid 2>/dev/null
  local rc=$?
  echo "rc=$rc -> $LOG/$name.out"
  tail -1 "$LOG/$name.out"
  if [ $rc -eq 0 ]; then
    date > "$LOG/$name.done"
  fi
}
rm -f "$LOG/ABORTED" "$LOG/DEADLINE_STOP"

# 1. the headline number, default config (matches what the driver runs)
step bench_default 2400 env BENCH_DEVICE_WAIT=60 python bench.py

# 2. bucket sweep (fewer reports to keep sweep cheap; relative rps decides)
step bench_auto6   1800 env BENCH_DEVICE_WAIT=60 BENCH_BUCKETS=auto BENCH_BUCKET_COUNT=6 BENCH_REPORTS=16384 python bench.py
step bench_auto8   1800 env BENCH_DEVICE_WAIT=60 BENCH_BUCKETS=auto BENCH_BUCKET_COUNT=8 BENCH_REPORTS=16384 python bench.py
step bench_hand16k 1800 env BENCH_DEVICE_WAIT=60 BENCH_BUCKETS=64,128,256,512 BENCH_REPORTS=16384 python bench.py
step bench_inflight4 1800 env BENCH_DEVICE_WAIT=60 BENCH_INFLIGHT=4 BENCH_REPORTS=16384 python bench.py
step bench_tokens512k 1800 env BENCH_DEVICE_WAIT=60 BENCH_TOKENS=524288 BENCH_REPORTS=16384 python bench.py

# 3. flash-vs-xla at workload lengths (bench-level A/B; kernel-level in
#    proofs) + the int8 MXU path A/B (numerics bounded by quantdrift)
step bench_flash   1800 env BENCH_DEVICE_WAIT=60 BENCH_ATTENTION=flash BENCH_REPORTS=16384 python bench.py
step bench_int8    1800 env BENCH_DEVICE_WAIT=60 BENCH_QUANT=int8_dynamic BENCH_REPORTS=16384 python bench.py

# 3b. long-context e2e (round-4 verdict stretch #8): full scoring path at
#     seq 4096, pad-to-cap (BENCH_BUCKETS empty) so every report pays the
#     4k cost — converts the flash kernel microbenchmark into a workload
#     claim the reference (folding-only at 512) structurally cannot match
# token budget 32k = batch 8 at 4096: the XLA path materializes
# [B, H, T, T] attention scores (8×12×4096²×2B ≈ 3.2 GB bf16) — batch 64
# would want ~26 GB and OOM a 16 GB chip; flash is O(T·D) but both rows
# use the same budget so the A/B is apples-to-apples
step bench_longctx_xla   2400 env BENCH_DEVICE_WAIT=60 BENCH_SEQ_LEN=4096 BENCH_BUCKETS= BENCH_TOKENS=32768 BENCH_REPORTS=2048 python bench.py
step bench_longctx_flash 2400 env BENCH_DEVICE_WAIT=60 BENCH_SEQ_LEN=4096 BENCH_BUCKETS= BENCH_TOKENS=32768 BENCH_REPORTS=2048 BENCH_ATTENTION=flash python bench.py

# 4. streaming rehearsal: the FULL predict_file path (writer thread and
#    all) at 16k vs 102k — reports/s must stay flat
step streaming     7200 python tools/streaming_rehearsal.py

# 5. hardware proofs (flash now covers 256/512; trainab = MFU levers;
#    bf16drift = score-drift bound)
step proofs_flash     2400 python tools/tpu_proofs.py flash
step proofs_flashgrad 2400 python tools/tpu_proofs.py flashgrad
step proofs_mlmsmoke  1800 python tools/tpu_proofs.py mlmsmoke
step proofs_trainsmoke 1800 python tools/tpu_proofs.py trainsmoke
step proofs_trainab   3600 python tools/tpu_proofs.py trainab
step proofs_bf16drift 1800 python tools/tpu_proofs.py bf16drift
step proofs_quantdrift 1800 python tools/tpu_proofs.py quantdrift

echo "=== all steps done ($(date +%H:%M:%S)) — results in $LOG/ ==="

# durability: the round may end (or the tunnel re-wedge) at any moment —
# commit the proof artifacts and sweep logs as soon as they exist
git add TPU_PROOFS.json SMOKE.md "$LOG" 2>/dev/null
if ! git diff --cached --quiet 2>/dev/null; then
  git commit -q -m "On-chip round-4 results: bench sweep + hardware proofs (auto-committed by round4_onchip.sh)"
  echo "artifacts committed"
fi
