#!/usr/bin/env python
"""Lint: ``bankops/`` may write artifacts only through the committed
helpers — ``resilience.io.atomic_write_text`` (whole-document commits)
or the telemetry ``JsonlSink`` (append-only trails).

A bank version is an *immutable, digest-verified* artifact
(docs/anchor_bank.md): a bare ``open(..., "w")`` or
``Path.write_text`` in the lifecycle subsystem is a torn-write hazard
— a kill mid-write would leave half an anchor set or half a manifest
where a promotion gate expects a committed version.  This AST check
flags, anywhere under the target dir (default
``memvul_tpu/bankops/``):

* ``open(...)`` calls whose mode (2nd positional or ``mode=`` keyword)
  contains any of ``w``/``a``/``x``/``+`` — read-only opens are fine;
* ``.write_text(...)`` / ``.write_bytes(...)`` attribute calls (the
  ``Path`` direct-write API).

Usage: ``python tools/lint_bank_artifact_writes.py [dir]`` — exits 1
listing offenders, 0 when clean, 2 on a bad argument.  Invoked as a
tier-1 test from ``tests/test_bankops.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

WRITE_MODE_CHARS = set("wax+")
FORBIDDEN_ATTRS = {"write_text", "write_bytes"}


def _open_write_mode(node: ast.Call) -> bool:
    """True when this is an ``open(...)`` call with a writing mode."""
    func = node.func
    name = func.id if isinstance(func, ast.Name) else (
        func.attr if isinstance(func, ast.Attribute) else ""
    )
    if name != "open":
        return False
    mode = None
    if len(node.args) >= 2:
        mode = node.args[1]
    for kw in node.keywords:
        if kw.arg == "mode":
            mode = kw.value
    if mode is None:
        return False  # default "r"
    if isinstance(mode, ast.Constant) and isinstance(mode.value, str):
        return bool(set(mode.value) & WRITE_MODE_CHARS)
    return True  # dynamic mode: flag it — artifact writes must be static


def find_bare_writes(root: Path) -> List[str]:
    """``path:line`` offender list for every direct artifact write."""
    offenders: List[str] = []
    for path in sorted(root.rglob("*.py")):
        tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _open_write_mode(node):
                offenders.append(f"{path}:{node.lineno}")
            elif (
                isinstance(node.func, ast.Attribute)
                and node.func.attr in FORBIDDEN_ATTRS
            ):
                offenders.append(f"{path}:{node.lineno}")
    return offenders


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = Path(argv[0]) if argv else (
        Path(__file__).resolve().parent.parent / "memvul_tpu" / "bankops"
    )
    if not root.is_dir():
        print(f"not a directory: {root}", file=sys.stderr)
        return 2
    offenders = find_bare_writes(root)
    for offender in offenders:
        print(offender)
    return 1 if offenders else 0


if __name__ == "__main__":
    sys.exit(main())
