#!/usr/bin/env python
"""Lint: durable subsystems may write artifacts only through the
committed helpers — ``resilience.io.atomic_write_text`` (whole-document
commits) or the telemetry ``JsonlSink`` (append-only trails).

Thin shim over the shared static-analysis engine
(``memvul_tpu/analysis/``, checker **MV103** — docs/static_analysis.md),
which generalizes this check beyond ``bankops/`` to ``serving/``,
``resilience/`` and ``telemetry/`` when run over the whole package.
This entry point preserves the historical CLI contract and the
``find_bare_writes`` helper the tier-1 tests import; its default target
stays ``memvul_tpu/bankops/``.

Flagged (see ``memvul_tpu/analysis/checkers/artifacts.py``):

* ``open(...)`` whose mode contains any of ``w``/``a``/``x``/``+``
  (dynamic modes are flagged too — artifact writes must be static);
* ``.write_text(...)`` / ``.write_bytes(...)`` attribute calls.

Usage: ``python tools/lint_bank_artifact_writes.py [dir]`` — exits 1
listing offenders as 1-based ``path:line``, 0 when clean, 2 on a bad
argument.  Invoked as a tier-1 test from ``tests/test_bankops.py``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))


def find_bare_writes(root: Path) -> List[str]:
    """``path:line`` offender list for every direct artifact write
    under ``root``, via the shared engine's MV103 checker."""
    from memvul_tpu.analysis import run_tool_checkers

    root = Path(root)
    result = run_tool_checkers(["MV001", "MV103"], root)
    out: List[str] = []
    for f in result.active:
        path = root / f.path
        if f.code == "MV001":
            out.append(f"{path}:{f.line}: {f.message}")
        else:
            out.append(f"{path}:{f.line}")
    return out


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    root = Path(argv[0]) if argv else (_REPO / "memvul_tpu" / "bankops")
    if not root.is_dir():
        print(f"not a directory: {root}", file=sys.stderr)
        return 2
    offenders = find_bare_writes(root)
    for offender in offenders:
        print(offender)
    return 1 if offenders else 0


if __name__ == "__main__":
    sys.exit(main())
