"""Summarize a round4_onchip.sh sweep into a decision table.

Reads each ``<logdir>/bench_*.out`` (one bench JSON line per file) plus
the latest proof rows in TPU_PROOFS.json, prints a ranked table, and
states the three decisions the round-3 verdict asks for:

* bucket policy (hand 64/128/256/512 vs auto-6 vs auto-8) + inflight/tokens
* flash vs xla at workload lengths
* int8 vs bf16 (gated on the quantdrift numbers)

Pure reporting — flipping shipped defaults stays a human commit.

    python tools/analyze_sweep.py [round4_logs]
"""

from __future__ import annotations

import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def last_json_line(path: Path):
    if not path.exists():
        return None
    for line in reversed(path.read_text().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            try:
                return json.loads(line)
            except json.JSONDecodeError:
                continue
    return None


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    logdir = REPO / (args[0] if args else "round4_logs")
    if not logdir.exists():
        print(f"no sweep logs at {logdir}")
        return 1

    rows = []
    longctx = []
    for out in sorted(logdir.glob("bench_*.out")):
        rec = last_json_line(out)
        row = (
            (out.stem, rec["value"], rec.get("vs_baseline"))
            if rec and "value" in rec
            else (out.stem, None, None)
        )
        # seq-4096 rows measure a different workload (pad-to-4k e2e);
        # ranking them against the 512-cap sweep would be apples/oranges
        (longctx if out.stem.startswith("bench_longctx") else rows).append(row)
    if rows:
        print(f"{'step':24} {'reports/s':>10} {'vs_baseline':>12}")
        ok = [r for r in rows if r[1] is not None]
        for name, value, vs in sorted(
            rows, key=lambda r: -(r[1] or 0)
        ):
            v = f"{value:.1f}" if value is not None else "FAILED"
            b = f"{vs:.2f}x" if vs is not None else ""
            print(f"{name:24} {v:>10} {b:>12}")
        if ok:
            best = max(ok, key=lambda r: r[1])
            print(f"\nbest: {best[0]} at {best[1]:.1f} reports/s")

    if longctx:
        print("\nlong-context e2e @4096 (pad-to-cap; vs_baseline already "
              "length-scaled):")
        for name, value, vs in longctx:
            v = f"{value:.1f}" if value is not None else "FAILED"
            b = f"{vs:.2f}x" if vs is not None else ""
            print(f"{name:24} {v:>10} {b:>12}")
        done = [r for r in longctx if r[1] is not None]
        flash = next((r for r in done if r[0] == "bench_longctx_flash"), None)
        xla = next((r for r in done if r[0] == "bench_longctx_xla"), None)
        if flash and xla and xla[1]:
            print(f"flash/xla @4096: {flash[1] / xla[1]:.2f}x  → "
                  + ("flash wins the long-context config"
                     if flash[1] > 1.05 * xla[1]
                     else "xla holds at 4096"))

    proofs = REPO / "TPU_PROOFS.json"
    if proofs.exists():
        latest = {}
        for line in proofs.read_text().splitlines():
            if line.strip():
                rec = json.loads(line)
                latest[rec["kind"]] = rec
        flash = latest.get("flash_parity_timing")
        if flash:
            short = [r for r in flash["rows"] if r["seq_len"] in (256, 512)]
            if short:
                wins = [
                    r for r in short
                    if (r.get("speedup_vs_xla") or 0) > 1.05
                ]
                print(
                    "\nflash @256/512: "
                    + ", ".join(
                        f"{r['seq_len']}→{r['speedup_vs_xla']:.2f}x"
                        if r.get("speedup_vs_xla")
                        else f"{r['seq_len']}→below-noise"
                        for r in short
                    )
                    + ("  → FLIP default to flash" if len(wins) == len(short)
                       else "  → keep xla at workload lengths")
                )
        drift = latest.get("int8_score_drift")
        if drift:
            ok_drift = (
                drift["max_abs_dp"] < 0.05 and drift["flip_rate"] < 0.005
            )
            print(
                f"int8 drift: max|dp|={drift['max_abs_dp']:.4f} "
                f"flips={drift['flip_rate']*100:.2f}%"
                + ("  → int8 default is defensible" if ok_drift
                   else "  → keep full precision as default")
            )
        ab = latest.get("train_ab_base_geometry")
        if ab:
            timed = [
                r for r in ab["rows"]
                if "steady_step_mean_s" in r
            ]
            if timed:
                best = min(timed, key=lambda r: r["steady_step_mean_s"])
                print(
                    f"train A/B best: {best['variant']} at "
                    f"{best['steady_step_mean_s']*1e3:.0f} ms/step"
                )
    return 0


if __name__ == "__main__":
    sys.exit(main())
