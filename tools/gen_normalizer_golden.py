"""Generate golden outputs for the text normalizer from the REFERENCE code.

Loads ``replace_tokens_simple`` (reference: MemVul/util.py:39-142) plus the
module-level regex constants it closes over, straight out of the reference
source file via AST extraction, and executes it over an adversarial battery
of documents.  The resulting input/output pairs are committed to
``tests/golden/normalizer_golden.json`` and asserted byte-equal against
``memvul_tpu.data.normalize.normalize_text`` by
``tests/test_normalizer_golden.py``.

This script needs ``/root/reference`` present; the committed JSON does not.
Run:  python tools/gen_normalizer_golden.py [path/to/reference/MemVul/util.py]
"""

from __future__ import annotations

import ast
import json
import re
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
DEFAULT_REF = Path("/root/reference/MemVul/util.py")
OUT = REPO / "tests" / "golden" / "normalizer_golden.json"

# Names the reference function actually uses (module-level regex constants).
_WANTED_ASSIGNS = {
    "ERROR_PATTERN",
    "API_PATTERN",
    "WORD_PATTERN",
    "WORD_PATTERN_1",
    "NUM_PATTERN",
    "PATH_PATTERN",
    "TAG_PATTERN",
    "CODE_PATTERN",
    "DOC_PATTERN_URL",
    "DOC_PATTERN_CODE",
    "ISSUE_PATTERN",
}


def load_reference_normalizer(util_path: Path):
    """Extract + exec only the constants and function we need (the reference
    module itself imports torch/allennlp/matplotlib which may be absent)."""
    tree = ast.parse(util_path.read_text())
    keep: list[ast.stmt] = []
    for node in tree.body:
        if isinstance(node, ast.Assign) and any(
            isinstance(t, ast.Name) and t.id in _WANTED_ASSIGNS for t in node.targets
        ):
            keep.append(node)
        elif isinstance(node, ast.FunctionDef) and node.name == "replace_tokens_simple":
            keep.append(node)
    module = ast.Module(body=keep, type_ignores=[])
    namespace = {"re": re, "print": print}
    exec(compile(module, str(util_path), "exec"), namespace)
    return namespace["replace_tokens_simple"]


def battery() -> list[str]:
    """~200 adversarial documents exercising every normalizer pass."""
    docs: list[str] = []

    # --- triple-backtick code fences -------------------------------------
    docs += [
        "``````",
        "before `````` after",
        "```Exception in thread main```",
        "```a warning occurred```",
        "```error: segfault at 0x0```",
        "```404 not found```",
        "```can't open file```",
        "```can not open file```",
        "```cannot open file```",
        "```could not resolve host```",
        "```couldnot resolve```",
        "```unresolved symbol```",  # un[a-z]{3,}
        "```uncommon words here```",
        "```just some plain prose words```",
        "```yaml\nkey: value\n```",
        "```words, with. punctuation?```",
        "```single_token```",
        "``` spaced_token ```",
        "```x = y + z```",
        "```def f(a, b):\n    return a + b\n```",
        "```" + "x" * 200 + "```",
        "```" + "word " * 40 + "```",
        "```int main() { return 0; } // short code```",
        "```first``` middle ```second```",
        "```same``` and again ```same```",
        "text with ```nested `inline` code``` end",
        "```\nmultiline\ncode block\n```",
        "```multi\nline prose\nwords```",
    ]

    # --- inline backtick spans -------------------------------------------
    docs += [
        "``",
        "empty `` span",
        "an `error` inline",
        "a `warning` inline",
        "some `plain words here` inline",
        "an `identifier` inline",
        "call `foo.bar()` here",
        "a `x=1;y=2` snippet",
        "`" + "z" * 180 + "`",
        "`a` `b` `c`",
        "repeat `tok` then `tok` again",
        "`404`",
        "`yaml stuff`",
        "mix ```fence``` and `inline` here",
    ]

    # --- markdown links / images -----------------------------------------
    docs += [
        "[readme](docs)",
        "[click here](http://example.com)",
        "[file.txt](http://host/path)",
        "[text](archive.zip)",
        "![img](screenshot.png)",
        "![alt text](http://imgur.com/abc)",
        "[a.b](c.d) twice [e](f)",
        "[multi\nline](target)",
        "[x](y) [x](y)",
        "[v1.2.3](release)",
        "[link](http://a/b.c)",
    ]

    # --- html-ish angle brackets -----------------------------------------
    docs += [
        "<div><span>>",
        "a <<>> b",
        "<a href=x>",
        "<!DOCTYPE html>",
        "<br/>",
        "<tag with=attr>",
        "<%= erb %>",
        "<$dollar>",
        "text <semi;colon> text",
        "<plain>",
        "<x><y>",
    ]

    # --- URLs -------------------------------------------------------------
    docs += [
        "see https://cve.mitre.org/cgi-bin/cvename.cgi?name=CVE-2021-1234",
        "see https://cwe.mitre.org/data/definitions/79.html",
        "https://bugzilla.redhat.com/show_bug.cgi?id=123",
        "https://bugs.launchpad.net/bugs/1",
        "http://example.com/file.txt",
        "http://example.com/page",
        "https://github.com/owner/repo/issues/42",
        "two urls http://a.com/x.py and https://b.org/y",
        "http://host/archive.tar.gz trailing",
        "url with anchor https://docs.site/guide#section",
        "percent http://h/%20%41 done",
        "https://example.com.",
    ]

    # --- escapes / emphasis / headers ------------------------------------
    docs += [
        "line one\\r\\nline two",
        "a\\n\\nb",
        "a\\r\\rb",
        "a\\t\\tb",
        'quoted \\" text',
        "quoted \\' text",
        "**bold** and *italic* and ***both***",
        "# h1\n## h2\n### h3",
        "#hashtag",
        "a - b -- c --- d",
        "\\r alone \\n alone \\t alone",
        "real\ttab and\nnewline and\rcarriage",
    ]

    # --- CVE / CWE leak guard --------------------------------------------
    docs += [
        "CVE-2021-44228 is log4shell",
        "multiple CVE-2020-1 CVE-2020-2 CVE-2020-33333",
        "CWE-79 cross-site scripting",
        "CWE-1000 view",
        "cve-2021-1234 lowercase stays",
        "CVE-19-1 short",
        "prefix-CVE-2021-9999-suffix",
        "CWE-89 and CVE-2019-0001 together",
    ]

    # --- emails / mentions ------------------------------------------------
    docs += [
        "mail me at user@example.com please",
        "user_name@host.net done",
        "a@b.cn x",
        "@alice please review",
        "@bob, thanks",
        "@carol. done",
        "cc @dave and @erin here",
        "@under_score fine",
        "@with-dash fine",
        "@trailing",
        "email@toolongdomainpart.com here",
        "two mails a@b.com c@d.net end",
    ]

    # --- error tokens -----------------------------------------------------
    docs += [
        "NullPointerException was thrown",
        "got IOError: bad stuff",
        "java.lang.OutOfMemoryError!",
        "an Error occurred",
        "HTTP 404 page",
        "stacktrace FooError(bar) deep",
        "MyException",
        "errors are fine",
        "Exception",
        "Exception  double space",
        "end with Exception",
    ]

    # --- paths ------------------------------------------------------------
    docs += [
        "open /usr/local/bin/tool now",
        "C:\\Users\\name\\file",
        "relative/path/to/thing",
        "a/b",
        "deep/er/path/here and also /etc/passwd/x",
        "(paren/inside/path)",
        "src/main/java/com/example/App",
        "one/two/",
        "~/dot/config/file",
    ]

    # --- file extensions --------------------------------------------------
    docs += [
        "see config.xml here",
        "see data.csv, then",
        "see archive.zip. done",
        "run script.sh now",
        "logo.png image",
        "notes.md file",
        "app.js code",
        "conf.yml and conf.yaml both",
        "query.sql page.html page.jsp page.php",
        "style.scss module.ts photo.jpg anim.gif pic.bmp",
        "doc.pdf report",
        "weird.PROD file",
        "upper.XML too",
        "file.txt? question",
        "noextension here",
        "a.exe b.jar c.sbt d.ml",
    ]

    # --- long tokens / camelCase / calls / dotted / numbers ---------------
    docs += [
        "x" * 35 + " long token",
        "supercalifragilisticexpialidocious29chars",
        "camelCase identifier",
        "PascalCase identifier",
        "getValue() call",
        "arr[] decl",
        "foo.bar().baz chained",
        "module.function_name here",
        "a.b.c.d.e dotted",
        "version 1.2.3 here",
        "v2.0 release",
        "beta3 build",
        "1.0.0-beta2 tag",
        "42 plain number",
        "2021 year",
        "x86_64 arch",
        "utf-8 encoding",
        "3rd place",
        "top-10 list",
        "UPPERCASE WORD",
        "MiXeD cAsE",
        "ALLCAPS",
        "Words In Title Case",
        "lowercase words only",
    ]

    # --- comments / misc --------------------------------------------------
    docs += [
        "<!--- hidden comment ---> visible",
        "<!--- one ---> mid <!--- two ---> end",
        "",
        " ",
        "   multiple   spaces   ",
        "unicode ✓ check émigré naïve",
        "中文字符 mixed English",
        "tab\tseparated\tvalues",
        "trailing newline\n",
        "\n\nleading newlines",
        "a,b;c.d:e",
        "semicolon; separated",
        "quoted \"double\" and 'single'",
        "parens (like this) and [brackets]",
        "curly {braces} here",
        "percent 50% done",
        "dollar $var here",
        "caret ^top and tilde ~home",
        "pipe | separated | values",
        "plus + minus",
        "equals = sign",
        "question? mark",
        "exclamation! point",
    ]

    # --- compound / interaction cases ------------------------------------
    docs += [
        "Bug in `parser.py` at /usr/lib/python/site.py line 42: "
        "NullPointerException, see CVE-2021-1234 and "
        "https://cve.mitre.org/detail or contact admin@corp.com "
        "or ping @maintainer thanks",
        "# Security Report\n\n**Severity**: high\n\n"
        "```\nTraceback (most recent call last):\n  error at line 1\n```\n\n"
        "Affects versions 1.0-2.3, see [advisory](https://github.com/x/y.md)",
        "```same text``` outside same text ```same text```",
        "`dup` and dup outside",
        "APITAG already present CODETAG too",
        "ERRORTAG pre-existing tag",
        "overlap `code with https://url.com inside`",
        "fence with link ```[text](http://a.b)```",
        "email inside path /home/user@host.com/file/x",
        "CVE-2020-1 inside `CVE-2020-2` code",
        "a#b#c hashes mid-token",
        "star*mid*token",
        "dash-separated-words here",
        "@mention-at-end",
        "trailing at-sign @ alone",
        "http://plain URL then words",
        "\\\" escaped quote then `code`",
        "[ref](http://bugzilla.mozilla.org/1) mixed link",
        "(1) numbered list (2) items",
        "50,000 with comma",
        "3.14159 pi approximation",
        "0x1A hex value",
        "IPv4 192.168.0.1 address",
        "port :8080 number",
        "time 12:34:56 stamp",
        "date 2021-01-02 iso",
        "range 1..10 dots",
        "semver >=1.2.3 constraint",
    ]

    assert len(docs) >= 200, len(docs)
    return docs


def main() -> None:
    ref_path = Path(sys.argv[1]) if len(sys.argv) > 1 else DEFAULT_REF
    fn = load_reference_normalizer(ref_path)
    cases = [{"input": doc, "expected": fn(doc)} for doc in battery()]
    OUT.parent.mkdir(parents=True, exist_ok=True)
    OUT.write_text(json.dumps(cases, indent=1, ensure_ascii=False) + "\n")
    print(f"wrote {len(cases)} golden cases -> {OUT}")


if __name__ == "__main__":
    main()
