"""On-chip proof runs: flash-kernel parity/timing + base-geometry train smoke.

Round-2 verdict items 2 and 3: the Pallas flash kernel had only ever run
in interpret mode on CPU, and the production train-step geometry
(reference: MemVul/config_memory.json:51,101 — batch 32 × grad-accum 2,
length 256) had never executed outside tiny-CPU tests.  This tool runs
both on the real chip and records the numbers:

    python tools/tpu_proofs.py flash       # parity + timing at 256..4096
    python tools/tpu_proofs.py flashgrad   # custom-VJP gradient parity
    python tools/tpu_proofs.py trainsmoke  # bert-base train-step stack
    python tools/tpu_proofs.py mlmsmoke    # MLM step, reference geometry
    python tools/tpu_proofs.py trainab     # remat/microbatch/attention A/B
    python tools/tpu_proofs.py bf16drift   # bf16-vs-f32 score drift
    python tools/tpu_proofs.py all

Results are appended to ``TPU_PROOFS.json`` (one JSON object per run) and
summarized in ``SMOKE.md``.  Run from the repo root on a TPU host.
"""

from __future__ import annotations

import json
import statistics
import sys
import time
from pathlib import Path
from typing import Optional

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

RESULTS = REPO / "TPU_PROOFS.json"
SMOKE = REPO / "SMOKE.md"
# hand-written operational notes (outages, methodology caveats) survive
# regeneration by living in their own file, embedded under the title
NOTES = REPO / "smoke_notes.md"


def _record(kind: str, payload: dict) -> None:
    import jax

    row = {
        "kind": kind,
        "backend": jax.default_backend(),
        "device_kind": getattr(jax.devices()[0], "device_kind", "?"),
        **payload,
    }
    with open(RESULTS, "a") as f:
        f.write(json.dumps(row) + "\n")
    print(json.dumps(row))


def _time_on_device(fn, q, *rest, inner: int = 20, reps: int = 3) -> dict:
    """Per-call device time with tunnel effects cancelled out.

    Two axon-tunnel hazards make naive timing garbage here: (1) a blocking
    sync costs ~70 ms RTT, orders above the kernel; (2) repeated calls
    with byte-identical args return instantly (content-cached), and
    ``block_until_ready`` does not actually wait on this backend.  So:
    chain ``inner`` sequential applications inside ONE jitted fori_loop
    (carrying the query through, so XLA cannot DCE or parallelize), force
    a REAL sync by fetching a scalar reduction of the output, perturb the
    input per rep to defeat the content cache, and difference a long chain
    against a short one to cancel the fixed RTT/launch overhead.
    """
    import functools

    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, static_argnums=0)
    def chain(n, step, q_, *rest_):
        q_ = q_ + step.astype(q_.dtype)
        out = jax.lax.fori_loop(
            0, n, lambda i, acc: fn(acc, *rest_).astype(acc.dtype), q_
        )
        return out.astype(jnp.float32).sum()  # scalar fetch = true sync

    def wall(n):
        float(chain(n, jnp.float32(0.0), q, *rest))  # compile + warm
        times = []
        for r in range(reps):
            step = jnp.float32((r + 1) * 1e-4)
            t0 = time.perf_counter()
            float(chain(n, step, q, *rest))
            times.append(time.perf_counter() - t0)
        return statistics.median(times)

    short, long_ = 2, 2 + inner
    per_iter = (wall(long_) - wall(short)) / inner
    # a non-positive difference means the kernel signal drowned in tunnel
    # RTT noise — report it as an invalid measurement, never as a number
    return {
        "median_s": per_iter if per_iter > 0 else None,
        "inner": inner,
        "reps": reps,
    }


def _hbm_fields(mem: dict) -> dict:
    """Peak/limit HBM as numbers when the backend reports them, else None
    — never a numeric 0.0, which would read as 'measured zero' when
    diffing proofs across backends (the axon PJRT plugin exposes no
    memory_stats)."""
    return {
        "peak_hbm_gb": (
            mem["peak_bytes_in_use"] / 1e9 if "peak_bytes_in_use" in mem else None
        ),
        "hbm_limit_gb": (
            mem["bytes_limit"] / 1e9 if "bytes_limit" in mem else None
        ),
    }


def _flash_fn(q, k, v, bias):
    """Mosaic-lowered kernel (never interpret mode) — shared by the
    forward and backward proofs so both test the same configuration."""
    from memvul_tpu.ops.pallas.flash_kernel import flash_attention

    return flash_attention(q, k, v, bias, interpret=False)


def _xla_fn(q, k, v, bias):
    from memvul_tpu.ops.attention import _xla_attention

    return _xla_attention(q, k, v, bias, None, 0.0, True)


def _attn_case(rng, b, t, h, d, lengths):
    """bf16 q/k/v + -inf key-padding bias + valid-row mask for a ragged
    batch — the shared input scaffolding for the flash proofs."""
    import jax.numpy as jnp
    import numpy as np

    q = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.bfloat16)
    k = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.bfloat16)
    v = jnp.asarray(rng.normal(size=(b, t, h, d)), jnp.bfloat16)
    mask = np.zeros((b, 1, 1, t), np.float32)
    row_ok = np.zeros((b, t, 1, 1), np.float32)
    for i, L in enumerate(lengths):
        mask[i, :, :, L:] = np.finfo(np.float32).min
        row_ok[i, :L] = 1.0
    return q, k, v, jnp.asarray(mask), row_ok


def run_flash() -> dict:
    """Mosaic-lowered flash kernel vs the XLA einsum formulation:
    numerical parity and timing with a ragged padding mask (the
    capability superseding the reference's segment folding,
    custom_PTM_embedder.py:244-381).  Covers the north-star workload
    lengths 256/512 (config_memory.json:45, round-3 verdict #3 — decide
    flash-vs-xla where the bench actually runs) as well as the
    long-context lengths 1k-4k."""
    import jax
    import numpy as np

    from memvul_tpu.utils.platform import is_tpu_backend

    assert is_tpu_backend(), "flash proof must run on TPU hardware"
    B, H, D = 4, 12, 64
    rows = []
    rng = np.random.default_rng(0)
    for T in (256, 512, 1024, 2048, 4096):
        # ragged lengths: rows padded to 1/2, 3/4, full, full
        lengths = [T // 2, 3 * T // 4, T, T]
        q, k, v, bias, _ = _attn_case(rng, B, T, H, D, lengths)

        flash = jax.jit(_flash_fn)
        xla = jax.jit(_xla_fn)
        out_f = np.asarray(flash(q, k, v, bias), np.float32)
        out_x = np.asarray(xla(q, k, v, bias), np.float32)
        # padded query rows are unconstrained — compare valid rows only
        max_err = 0.0
        for i, L in enumerate(lengths):
            max_err = max(
                max_err, float(np.abs(out_f[i, :L] - out_x[i, :L]).max())
            )
        # shorter sequences need longer chains for the differenced timing
        # to rise above tunnel-RTT noise
        inner = max(20, 81920 // T)
        t_flash = _time_on_device(flash, q, k, v, bias, inner=inner)
        t_xla = _time_on_device(xla, q, k, v, bias, inner=inner)
        f_s, x_s = t_flash["median_s"], t_xla["median_s"]
        rows.append(
            {
                "seq_len": T,
                "max_abs_err_valid_rows": max_err,
                "flash_median_s": f_s,
                "xla_median_s": x_s,
                "speedup_vs_xla": (x_s / f_s) if (f_s and x_s) else None,
            }
        )
        assert max_err < 3e-2, f"flash parity broke at T={T}: {max_err}"
    payload = {"shape": [B, "T", H, D], "dtype": "bfloat16", "rows": rows}
    _record("flash_parity_timing", payload)
    return payload


def run_flashgrad() -> dict:
    """Backward parity on real Mosaic: the flash kernel's custom VJP vs
    gradients of the XLA formulation.  The loss projects only valid query
    rows (padded rows are unconstrained in both impls; padded KEY positions
    carry -inf bias so their k/v gradients are zero in both)."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from memvul_tpu.utils.platform import is_tpu_backend

    assert is_tpu_backend(), "flash grad proof must run on TPU hardware"
    B, H, D = 2, 12, 64
    rows = []
    rng = np.random.default_rng(1)
    for T in (1024, 2048):
        lengths = [T // 2, T]
        q, k, v, bias, row_ok = _attn_case(rng, B, T, H, D, lengths)
        proj = jnp.asarray(
            rng.normal(size=(B, T, H, D)) * row_ok, jnp.float32
        )  # fixed cotangent restricted to valid rows

        def loss(attn_fn, q_, k_, v_):
            out = attn_fn(q_, k_, v_, bias).astype(jnp.float32)
            return (out * proj).sum()

        g_f = jax.jit(jax.grad(lambda *a: loss(_flash_fn, *a), argnums=(0, 1, 2)))(
            q, k, v
        )
        g_x = jax.jit(jax.grad(lambda *a: loss(_xla_fn, *a), argnums=(0, 1, 2)))(
            q, k, v
        )
        errs = {}
        for name, gf, gx in zip(("dq", "dk", "dv"), g_f, g_x):
            gf = np.asarray(gf, np.float32)
            gx = np.asarray(gx, np.float32)
            scale = float(np.abs(gx).max()) or 1.0
            errs[name] = float(np.abs(gf - gx).max()) / scale
        rows.append({"seq_len": T, "rel_max_err": errs})
        for name, e in errs.items():
            assert e < 5e-2, f"flash {name} grad parity broke at T={T}: {e}"
    payload = {"shape": [B, "T", H, D], "dtype": "bfloat16", "rows": rows}
    _record("flash_grad_parity", payload)
    return payload


def _time_step_loop(advance, state, n_steps: int):
    """Time a train-step sequence with the tunnel RTT paid ONCE.

    ``advance(state) -> (state, loss_array)`` dispatches one step.  The
    first call is timed alone with a blocking loss fetch (compile + first
    run); the next ``n_steps`` are dispatched back-to-back — they
    serialize on-device through donated params — with a single final
    scalar fetch, so the ~70 ms blocking-sync RTT does not inflate every
    step the way a per-step ``float(loss)`` would (~15% at a ~500 ms
    step).  Shared by the train and MLM smokes so both measure the same
    way.  Returns (state, metrics dict)."""
    import numpy as np

    t0 = time.perf_counter()
    state, loss = advance(state)
    first_loss = float(loss)  # blocks: includes compile + first run
    compile_s = time.perf_counter() - t0
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, loss = advance(state)
    last_loss = float(loss)  # ONE sync for the whole chain
    steady_s = (time.perf_counter() - t0) / n_steps
    assert np.isfinite(first_loss) and np.isfinite(last_loss)
    return state, {
        "first_step_s_incl_compile": compile_s,
        "steady_step_mean_s": steady_s,
        "steps_timed": n_steps,
        "first_loss": first_loss,
        "last_loss": last_loss,
    }


def _train_case(
    K: int = 2,
    B: int = 32,
    L: int = 256,
    remat: bool = True,
    attention_impl: str = "xla",
    n_steps: int = 8,
    preset: str = "base",
) -> dict:
    """Build the full bert-base train-step stack at one geometry/config
    and time it — shared by the baseline smoke and the A/B matrix.
    ``preset='tiny'`` lets CPU tests drive the identical code path."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from memvul_tpu.models import BertConfig, MemoryModel
    from memvul_tpu.training.optim import make_optimizer
    from memvul_tpu.training.trainer import make_train_step

    cfg = getattr(BertConfig, preset)(
        vocab_size=30522,
        dtype=jnp.bfloat16,
        scan_layers=True,
        remat=remat,
        attention_impl=attention_impl,
    )
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    t0 = time.perf_counter()
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    init_s = time.perf_counter() - t0
    # the reference schedule's optimizer (config_memory.json:60-75)
    tx, opt_state = make_optimizer(
        params,
        group_lrs={"embedder": 2e-5, "pooler": 5e-5},
        base_lr=1e-4,
        warmup_steps=10000,
        grad_clip_norm=1.0,
    )
    step = jax.jit(make_train_step(model, tx), donate_argnums=(0, 1, 2))

    data_rng = np.random.default_rng(0)
    stack = {
        "sample1": {
            "input_ids": data_rng.integers(0, 30000, (K, B, L)).astype(np.int32),
            "attention_mask": np.ones((K, B, L), np.int32),
        },
        "sample2": {
            "input_ids": data_rng.integers(0, 30000, (K, B, L)).astype(np.int32),
            "attention_mask": np.ones((K, B, L), np.int32),
        },
        "label": data_rng.integers(0, 2, (K, B)).astype(np.int32),
        "weight": np.ones((K, B), np.float32),
    }

    def advance(state):
        params, opt_state, rng = state
        params, opt_state, rng, stats = step(params, opt_state, rng, stack)
        return (params, opt_state, rng), stats["loss"]

    _, m = _time_step_loop(
        advance, (params, opt_state, jax.random.PRNGKey(0)), n_steps
    )
    return {
        "geometry": {"K": K, "batch": B, "seq_len": L, "model": f"bert-{preset}",
                     "scan_layers": True, "remat": remat,
                     "attention_impl": attention_impl, "dtype": "bfloat16"},
        "init_s": init_s,
        **m,
        "pairs_per_s": (K * B) / m["steady_step_mean_s"],
    }


def run_trainsmoke() -> dict:
    """One real bert-base training step at the production geometry:
    batch 32 × grad-accum 2, length 256, scan+remat, bf16 — compile time,
    steady-state step time, peak HBM."""
    from memvul_tpu.utils.profiling import device_memory_stats

    payload = _train_case()
    payload.update(_hbm_fields(device_memory_stats()))
    _record("train_smoke_base_geometry", payload)
    return payload


def run_trainab() -> dict:
    """Round-3 verdict #4: the 477 ms baseline step ≈ ~20% MFU — A/B the
    plausible levers at base geometry on-chip (total pairs per step held
    at 64 so steady step times compare directly):

    * remat off — stop paying recompute FLOPs if HBM allows
    * microbatch 64×K1 vs 32×K2 — halve the scan/accum overhead
    * flash attention at 256 — does the kernel help at workload length?

    Each variant runs in its own try block: an OOM (the remat-off risk on
    a 16 GB chip) records the failure string instead of killing the run.
    """
    from memvul_tpu.utils.platform import is_tpu_backend

    assert is_tpu_backend(), "train A/B must run on TPU hardware"
    variants = {
        "base_remat_K2x32": dict(),
        "noremat_K2x32": dict(remat=False),
        "remat_K1x64": dict(K=1, B=64),
        "noremat_K1x64": dict(K=1, B=64, remat=False),
        "flash_remat_K2x32": dict(attention_impl="flash"),
    }
    rows = []
    for name, kw in variants.items():
        try:
            case = _train_case(**kw)
            rows.append({"variant": name, **case})
            print(f"trainab {name}: steady {case['steady_step_mean_s']*1e3:.0f} ms")
        except Exception as e:  # noqa: BLE001 — record OOM/lowering failures
            rows.append({"variant": name, "error": f"{type(e).__name__}: {e}"[:300]})
            print(f"trainab {name}: FAILED {type(e).__name__}")
    payload = {"rows": rows}
    _record("train_ab_base_geometry", payload)
    return payload


def _decision_drift(
    variant_cfg,
    A: int,
    N: int,
    B: int,
    L: int,
    preset: str,
) -> dict:
    """Score N synthetic reports against an A-anchor bank with the f32
    reference forward and with ``variant_cfg(base_cfg)``'s forward, both
    driven by ONE f32 param set, and measure how far the best-anchor
    probability (the reference's decision value, predict_memory.py:
    168-177) moves.  Shared by the bf16 and int8 drift proofs."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from memvul_tpu.models import BertConfig, MemoryModel
    from memvul_tpu.models.memory import best_anchor_score

    rng = np.random.default_rng(7)
    rng_ids = rng.integers(1000, 30000, (N, L)).astype(np.int32)
    anchor_ids = rng.integers(1000, 30000, (A, L)).astype(np.int32)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }

    def batches():
        for lo in range(0, N, B):
            ids = rng_ids[lo : lo + min(B, N - lo)]
            yield {"input_ids": ids, "attention_mask": np.ones_like(ids)}

    make_cfg = getattr(BertConfig, preset)
    base_cfg = make_cfg(vocab_size=30522, dtype=jnp.float32, scan_layers=True)
    # ONE f32 param set drives both forwards (flax keeps param_dtype f32;
    # cfg.dtype/quant only change the forward computation)
    params = MemoryModel(base_cfg).init(jax.random.PRNGKey(0), dummy, dummy)
    results = {}
    for name, cfg in (("reference", base_cfg), ("variant", variant_cfg(base_cfg))):
        model = MemoryModel(cfg)
        encode = jax.jit(
            lambda p, s, model=model: model.apply(p, s, method="encode")
        )
        match = jax.jit(
            lambda p, s, anc, model=model: best_anchor_score(
                model.apply(p, s, anchors=anc)
            )
        )
        bank = encode(
            params,
            {"input_ids": anchor_ids, "attention_mask": np.ones_like(anchor_ids)},
        )
        probs, args_ = [], []
        for batch in batches():
            p, a = match(params, batch, bank)
            probs.append(np.asarray(p, np.float32))
            args_.append(np.asarray(a))
        results[name] = (np.concatenate(probs), np.concatenate(args_))
    return results


def _drift_payload(results, N: int, A: int, L: int, preset: str) -> dict:
    import numpy as np

    p32, a32 = results["reference"]
    p16, a16 = results["variant"]
    drift = np.abs(p16 - p32)
    flips = int(((p16 >= 0.5) != (p32 >= 0.5)).sum())
    return {
        "model": f"bert-{preset}",
        "n_reports": N,
        "n_anchors": A,
        "seq_len": L,
        "max_abs_dp": float(drift.max()),
        "p99_abs_dp": float(np.percentile(drift, 99)),
        "mean_abs_dp": float(drift.mean()),
        "flips_at_0.5": flips,
        "flip_rate": flips / N,
        "argmax_anchor_agreement": float((a16 == a32).mean()),
        "note": "random-init params + synthetic tokens: bounds the numerical "
        "chain (encode -> 129-way match -> softmax max), not trained accuracy",
    }


def run_bf16drift(
    A: int = 129,
    N: int = 4096,
    B: int = 256,
    L: int = 256,
    preset: str = "base",
    require_tpu: bool = True,
) -> dict:
    """Round-3 verdict #5: the missing link in the ±0.5-F1 parity
    argument — how much do bf16 activations move the best-anchor
    probability relative to f32, through the full encode → 129-way anchor
    match → softmax-max chain?"""
    import jax.numpy as jnp

    from memvul_tpu.utils.platform import is_tpu_backend

    if require_tpu:
        assert is_tpu_backend(), "bf16 drift proof must run on TPU hardware"
    results = _decision_drift(
        lambda c: c.replace(dtype=jnp.bfloat16), A, N, B, L, preset
    )
    payload = _drift_payload(results, N, A, L, preset)
    _record("bf16_score_drift", payload)
    assert payload["max_abs_dp"] < 0.2, payload
    return payload


def run_quantdrift(
    A: int = 129,
    N: int = 4096,
    B: int = 256,
    L: int = 256,
    preset: str = "base",
    require_tpu: bool = True,
) -> dict:
    """Decision drift of the int8_dynamic inference path (bf16
    activations + int8 dense contractions — the deployment combination
    BENCH_QUANT=int8_dynamic benches) vs the f32 reference forward."""
    import jax.numpy as jnp

    from memvul_tpu.utils.platform import is_tpu_backend

    if require_tpu:
        assert is_tpu_backend(), "quant drift proof must run on TPU hardware"
    results = _decision_drift(
        lambda c: c.replace(dtype=jnp.bfloat16, quant="int8_dynamic"),
        A, N, B, L, preset,
    )
    payload = _drift_payload(results, N, A, L, preset)
    _record("int8_score_drift", payload)
    assert payload["max_abs_dp"] < 0.3, payload
    return payload


def run_mlmsmoke() -> dict:
    """One real MLM further-pretraining step at the reference schedule's
    geometry (further_pretrain.json / run_mlm_wwm.py:145-147: batch 16 ×
    grad-accum 2, length 256, bert-base) — compile time and steady-state
    step time on chip.  Labels are synthesized directly (15% positions
    supervised, rest IGNORE) so the proof times the jitted step, not the
    host-side masking that tests already cover."""
    import tempfile

    import jax
    import jax.numpy as jnp
    import numpy as np

    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.models import BertConfig
    from memvul_tpu.pretrain.mlm import IGNORE, MLMTrainer, MLMTrainerConfig
    from memvul_tpu.utils.platform import is_tpu_backend

    assert is_tpu_backend(), "mlm smoke must run on TPU hardware"
    ws = build_workspace(
        tempfile.mkdtemp(), seed=0, num_projects=2, reports_per_project=8
    )
    tok = ws["tokenizer"]
    cfg = BertConfig.base(
        vocab_size=max(30522, tok.vocab_size),
        dtype=jnp.bfloat16,
        scan_layers=True,
        remat=True,
    )
    t0 = time.perf_counter()
    trainer = MLMTrainer(cfg, tok, MLMTrainerConfig())
    init_s = time.perf_counter() - t0

    K, B, L = trainer.c.grad_accum, trainer.c.batch_size, trainer.c.max_length
    rng_np = np.random.default_rng(0)
    ids = rng_np.integers(5, tok.vocab_size, (K, B, L)).astype(np.int32)
    mask = np.ones((K, B, L), np.int32)
    labels = np.full((K, B, L), IGNORE, np.int32)
    pick = rng_np.random((K, B, L)) < 0.15
    labels[pick] = ids[pick]

    from memvul_tpu.utils.profiling import device_memory_stats

    def advance(state):
        params, opt_state, rng = state
        params, opt_state, rng, loss = trainer._train_step(
            params, opt_state, rng, ids, mask, labels
        )
        return (params, opt_state, rng), loss

    _, m = _time_step_loop(
        advance, (trainer.params, trainer.opt_state, jax.random.PRNGKey(0)), 6
    )
    mem = device_memory_stats()
    payload = {
        "geometry": {"K": K, "batch": B, "seq_len": L, "model": "bert-base",
                     "vocab_size": cfg.vocab_size, "dtype": "bfloat16"},
        "init_s": init_s,
        **m,
        "sequences_per_s": (K * B) / m["steady_step_mean_s"],
        **_hbm_fields(mem),
    }
    _record("mlm_smoke_reference_geometry", payload)
    return payload


def _steady(r: dict) -> float:
    """Steady-state step seconds — new records carry the single-sync mean,
    older committed ones the per-step-sync median."""
    return r.get("steady_step_mean_s", r.get("steady_step_median_s"))


def _hbm_line(r: dict) -> str:
    return (
        f"- peak HBM: **{r['peak_hbm_gb']:.2f} GB** of {r['hbm_limit_gb']:.1f} GB"
        if r.get("peak_hbm_gb")
        else "- peak HBM: not reported by this backend "
        "(axon PJRT plugin exposes no memory_stats)"
    )


def write_smoke_md(
    results_path: Optional[Path] = None, out_path: Optional[Path] = None
) -> None:
    """Regenerate SMOKE.md from the accumulated proof records.  Defaults
    resolve at call time so tests can repoint RESULTS/SMOKE."""
    results_path = results_path or RESULTS
    out_path = out_path or SMOKE
    if not results_path.exists():
        return
    rows = [json.loads(l) for l in results_path.read_text().splitlines() if l.strip()]
    lines = ["# TPU hardware proofs", ""]
    if NOTES.exists():
        lines += [NOTES.read_text().strip(), ""]
    lines += [
        "Recorded by `tools/tpu_proofs.py` on real TPU hardware (backend/"
        "device noted per row). Regenerate: `python tools/tpu_proofs.py all`.",
        "",
    ]
    for r in rows:
        if r["kind"] == "flash_parity_timing":
            lines += [
                f"## Flash kernel (Mosaic) parity + timing — {r['device_kind']}",
                "",
                "| seq len | max abs err (valid rows) | flash median | XLA median | speedup |",
                "|---|---|---|---|---|",
            ]
            def _ms(v):
                return f"{v*1e3:.2f} ms" if v else "below noise"

            for row in r["rows"]:
                speedup = row["speedup_vs_xla"]
                lines.append(
                    f"| {row['seq_len']} | {row['max_abs_err_valid_rows']:.4f} "
                    f"| {_ms(row['flash_median_s'])} | {_ms(row['xla_median_s'])} "
                    f"| {f'{speedup:.2f}×' if speedup else 'n/a'} |"
                )
            lines.append("")
        elif r["kind"] == "flash_grad_parity":
            lines += [
                f"## Flash kernel (Mosaic) gradient parity — {r['device_kind']}",
                "",
                "Custom VJP vs XLA-formulation grads, valid-rows loss, bf16"
                " (relative max err, normalized by the XLA grad's max):",
                "",
                "| seq len | dq | dk | dv |",
                "|---|---|---|---|",
            ]
            for row in r["rows"]:
                e = row["rel_max_err"]
                lines.append(
                    f"| {row['seq_len']} | {e['dq']:.4f} | {e['dk']:.4f} "
                    f"| {e['dv']:.4f} |"
                )
            lines.append("")
        elif r["kind"] == "mlm_smoke_reference_geometry":
            g = r["geometry"]
            lines += [
                f"## MLM further-pretraining step — {r['device_kind']}",
                "",
                f"bert-base MLM head, batch {g['batch']} × accum {g['K']}, "
                f"len {g['seq_len']} (reference schedule: further_pretrain.json,"
                " run_mlm_wwm.py:145-147):",
                "",
                f"- first step (incl. XLA compile): **{r['first_step_s_incl_compile']:.1f} s**",
                f"- steady-state step: **{_steady(r)*1e3:.0f} ms** "
                f"({r['sequences_per_s']:.1f} sequences/s)",
                _hbm_line(r),
                f"- loss finite: {r['first_loss']:.4f} → {r['last_loss']:.4f}",
                "",
            ]
        elif r["kind"] == "train_ab_base_geometry":
            lines += [
                f"## Train-step A/B at base geometry — {r['device_kind']}",
                "",
                "64 pairs/step held constant; remat / microbatch / attention"
                " levers (round-3 verdict #4):",
                "",
                "| variant | steady step | pairs/s | compile |",
                "|---|---|---|---|",
            ]
            for row in r["rows"]:
                if "error" in row:
                    lines.append(f"| {row['variant']} | failed: {row['error'][:60]} | | |")
                else:
                    lines.append(
                        f"| {row['variant']} | {_steady(row)*1e3:.0f} ms "
                        f"| {row['pairs_per_s']:.1f} "
                        f"| {row['first_step_s_incl_compile']:.1f} s |"
                    )
            lines.append("")
        elif r["kind"] in ("bf16_score_drift", "int8_score_drift"):
            what = (
                "bf16 vs f32"
                if r["kind"] == "bf16_score_drift"
                else "int8_dynamic (bf16+int8 MXU) vs f32"
            )
            lines += [
                f"## {what} best-anchor score drift — {r['device_kind']}",
                "",
                f"{r['n_reports']} synthetic reports × {r['n_anchors']}-anchor bank, "
                f"len {r['seq_len']}, shared f32 params (round-3 verdict #5 — the "
                "numerical link in the ±0.5-F1 parity argument):",
                "",
                f"- max |Δp(best anchor)|: **{r['max_abs_dp']:.4f}** "
                f"(p99 {r['p99_abs_dp']:.4f}, mean {r['mean_abs_dp']:.5f})",
                f"- decision flips at thres 0.5: **{r['flips_at_0.5']}/{r['n_reports']}**"
                f" ({100*r['flip_rate']:.2f}%)",
                f"- argmax-anchor agreement: {100*r['argmax_anchor_agreement']:.2f}%",
                f"- caveat: {r['note']}",
                "",
            ]
        elif r["kind"] == "streaming_scale":
            lines += [
                f"## Corpus-scale streaming (predict_file) — {r['device_kind']}",
                "",
                f"{r['model']}, len {r['seq_len']} — full streaming path "
                "(jsonl reader → buckets → async dispatch → writer thread), "
                "round-3 verdict #6:",
                "",
                "| corpus | reports/s | elapsed |",
                "|---|---|---|",
            ]
            for row in r["rows"]:
                lines.append(
                    f"| {row['n_reports']} | {row['reports_per_s']:.1f} "
                    f"| {row['elapsed_s']:.1f} s |"
                )
            lines += [
                "",
                f"large/small throughput ratio: "
                f"**{r['large_over_small_rps']:.3f}** (≥0.9 = no host-side sag)",
                "",
            ]
        elif r["kind"] == "train_smoke_base_geometry":
            g = r["geometry"]
            lines += [
                f"## Base-geometry train step — {r['device_kind']}",
                "",
                f"bert-base, batch {g['batch']} × accum {g['K']}, len {g['seq_len']}, "
                "scan+remat, bf16 (reference shape: config_memory.json:51,101):",
                "",
                f"- first step (incl. XLA compile): **{r['first_step_s_incl_compile']:.1f} s**",
                f"- steady-state step: **{_steady(r)*1e3:.0f} ms** "
                f"({r['pairs_per_s']:.1f} pairs/s)",
                _hbm_line(r),
                f"- loss finite: {r['first_loss']:.4f} → {r['last_loss']:.4f}",
                "",
            ]
    out_path.write_text("\n".join(lines))


_RUNNERS = {
    "flash": run_flash,
    "flashgrad": run_flashgrad,
    "trainsmoke": run_trainsmoke,
    "mlmsmoke": run_mlmsmoke,
    "trainab": run_trainab,
    "bf16drift": run_bf16drift,
    "quantdrift": run_quantdrift,
}


def main(argv=None) -> int:
    from memvul_tpu.utils.platform import enable_compilation_cache

    enable_compilation_cache()
    args = argv if argv is not None else sys.argv[1:]
    wanted = list(args) or ["all"]
    if wanted == ["all"]:
        wanted = list(_RUNNERS)
    unknown = [w for w in wanted if w not in _RUNNERS]
    if unknown:
        print(f"unknown proof(s): {unknown}; choose from {list(_RUNNERS)}")
        return 2
    for what in wanted:
        _RUNNERS[what]()
    write_smoke_md()
    return 0


if __name__ == "__main__":
    sys.exit(main())
