#!/bin/bash
# Probe the axon tunnel every 4 min; while it answers, run the (resumable)
# on-chip agenda in the foreground; if the agenda aborts on a re-wedge, go
# back to probing.  Exits only when the agenda completes or the round's
# tunnel hand-off point passes (the driver's round-end bench must have
# exclusive tunnel access — two clients wedge it).
#
# Round-5 clock: round started ~03:35 UTC, ends ~15:35 UTC. Agenda work
# stops at CUTOFF so the tunnel is free well before the driver bench.
cd /root/repo
LOG=/root/repo/.tpu_probe/probe.log
CUTOFF_EPOCH=$(date -d "14:50" +%s)
export DEADLINE_EPOCH=$CUTOFF_EPOCH
while true; do
  TS=$(date +%H:%M:%S)
  # cutoff check BEFORE probing: past the hand-off point even the 75s
  # probe would be a second concurrent tunnel client against the
  # driver's round-end bench — the exact two-client wedge condition
  if [ "$(date +%s)" -ge "$CUTOFF_EPOCH" ]; then
    echo "$TS past agenda cutoff — standing down without probing" >> "$LOG"
    exit 0
  fi
  OUT=$(timeout 75 python - <<'PY' 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((128,128))
print("SUM", float((x@x).sum()))
PY
)
  RC=$?
  if [ $RC -eq 0 ] && echo "$OUT" | grep -q "SUM"; then
    echo "$TS ALIVE — running round4_onchip.sh" >> "$LOG"
    date > /root/repo/.tpu_probe/ALIVE
    bash tools/round4_onchip.sh round4_logs >> /root/repo/round4_logs_driver.log 2>&1
    AGENDA_RC=$?
    echo "$(date +%H:%M:%S) agenda rc=$AGENDA_RC" >> "$LOG"
    if [ $AGENDA_RC -eq 0 ]; then
      exit 0
    fi
    sleep 120   # re-wedged mid-agenda: back to probing
  else
    echo "$TS dead rc=$RC" >> "$LOG"
  fi
  sleep 240
done
