#!/bin/bash
# Probe the axon tunnel every 4 min; while it answers, run the (resumable)
# round-4 on-chip agenda in the foreground; if the agenda aborts on a
# re-wedge, go back to probing.  Exits only when the agenda completes.
cd /root/repo
LOG=/root/repo/.tpu_probe/probe.log
while true; do
  TS=$(date +%H:%M:%S)
  OUT=$(timeout 75 python - <<'PY' 2>&1
import jax, jax.numpy as jnp
x = jnp.ones((128,128))
print("SUM", float((x@x).sum()))
PY
)
  RC=$?
  # after 01:30 the driver's round-end bench may start at any moment —
  # never hold the tunnel with a long agenda then (two clients wedge it);
  # just record liveness and stand down
  H=$(date +%H) ; M=$(date +%M)
  if [ "$H" -ge 2 ] && [ "$H" -lt 14 ] || { [ "$H" -eq 1 ] && [ "$M" -ge 30 ]; }; then
    if [ $RC -eq 0 ] && echo "$OUT" | grep -q "SUM"; then
      echo "$TS ALIVE but past agenda cutoff — standing down" >> "$LOG"
      date > /root/repo/.tpu_probe/ALIVE
    fi
    exit 0
  fi
  if [ $RC -eq 0 ] && echo "$OUT" | grep -q "SUM"; then
    echo "$TS ALIVE — running round4_onchip.sh" >> "$LOG"
    date > /root/repo/.tpu_probe/ALIVE
    bash tools/round4_onchip.sh round4_logs >> /root/repo/round4_logs_driver.log 2>&1
    AGENDA_RC=$?
    echo "$(date +%H:%M:%S) agenda rc=$AGENDA_RC" >> "$LOG"
    if [ $AGENDA_RC -eq 0 ]; then
      exit 0
    fi
    sleep 120   # re-wedged mid-agenda: back to probing
  else
    echo "$TS dead rc=$RC" >> "$LOG"
  fi
  sleep 240
done
