#!/usr/bin/env python
"""Lint: no bare ``print(`` calls in ``memvul_tpu/`` library code.

Library output must go through ``logging`` (operator-facing messages)
or the telemetry registry (machine-facing run data,
docs/observability.md) — a bare print from deep inside a scoring stream
corrupts the one-JSON-line stdout contract of the bench/CLI entry
points and is invisible to telemetry-report.  The two intentional
stdout writers are exempt: ``bench.py`` (its stdout IS the result
contract) and ``__main__.py`` (the CLI's user-facing output).

The check is AST-based, so ``print`` inside string literals (e.g. the
doctor's subprocess probe source, utils/doctor.py) is not flagged —
those strings execute in a child whose stdout is the parsed protocol.

Usage: ``python tools/lint_no_bare_print.py [package_dir]`` — exits 1
listing offenders, 0 when clean.  Invoked as a tier-1 test from
``tests/test_no_bare_print.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# files whose stdout is an intentional, documented contract
ALLOWED_FILES = {"bench.py", "__main__.py"}


def find_bare_prints(package_dir: Path) -> List[str]:
    """``path:line`` for every ``print(...)`` call expression under
    ``package_dir``, excluding :data:`ALLOWED_FILES`."""
    offenders: List[str] = []
    for path in sorted(package_dir.rglob("*.py")):
        if path.name in ALLOWED_FILES:
            continue
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        except SyntaxError as e:  # a file that doesn't parse is its own bug
            offenders.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
            continue
        for node in ast.walk(tree):
            if (
                isinstance(node, ast.Call)
                and isinstance(node.func, ast.Name)
                and node.func.id == "print"
            ):
                offenders.append(f"{path}:{node.lineno}")
    return offenders


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        package_dir = Path(argv[0])
    else:
        package_dir = Path(__file__).resolve().parent.parent / "memvul_tpu"
    if not package_dir.is_dir():
        print(f"lint_no_bare_print: {package_dir} is not a directory",
              file=sys.stderr)
        return 2
    offenders = find_bare_prints(package_dir)
    for line in offenders:
        print(f"bare print() in library code: {line}")
    if offenders:
        print(
            f"{len(offenders)} bare print call(s) — use logging or the "
            "telemetry registry (docs/observability.md)"
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
