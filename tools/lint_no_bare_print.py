#!/usr/bin/env python
"""Lint: no bare ``print(`` calls in ``memvul_tpu/`` library code.

Thin shim over the shared static-analysis engine
(``memvul_tpu/analysis/``, checker **MV101** — docs/static_analysis.md):
the engine owns the single AST walk; this entry point only preserves
the historical CLI contract and the ``find_bare_prints`` helper the
tier-1 tests import.  Library output must go through ``logging`` or the
telemetry registry (docs/observability.md); ``bench.py`` and
``__main__.py`` are exempt by filename (their stdout IS the contract).

Usage: ``python tools/lint_no_bare_print.py [package_dir]`` — exits 1
listing offenders as 1-based ``path:line``, 0 when clean, 2 on a bad
argument.  Invoked as a tier-1 test from ``tests/test_no_bare_print.py``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))


def find_bare_prints(package_dir: Path) -> List[str]:
    """``path:line`` for every ``print(...)`` call expression under
    ``package_dir`` (plus ``path:line: syntax error: ...`` for files
    that do not parse), via the shared engine's MV101 checker."""
    from memvul_tpu.analysis import run_tool_checkers

    package_dir = Path(package_dir)
    result = run_tool_checkers(["MV001", "MV101"], package_dir)
    out: List[str] = []
    for f in result.active:
        path = package_dir / f.path
        if f.code == "MV001":
            out.append(f"{path}:{f.line}: {f.message}")
        else:
            out.append(f"{path}:{f.line}")
    return out


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        package_dir = Path(argv[0])
    else:
        package_dir = _REPO / "memvul_tpu"
    if not package_dir.is_dir():
        print(f"lint_no_bare_print: {package_dir} is not a directory",
              file=sys.stderr)
        return 2
    offenders = find_bare_prints(package_dir)
    for line in offenders:
        print(f"bare print() in library code: {line}")
    if offenders:
        print(
            f"{len(offenders)} bare print call(s) — use logging or the "
            "telemetry registry (docs/observability.md)"
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
