#!/usr/bin/env python
"""Lint: HTTP handler threads may only enqueue + wait on a future, and
router dispatch classes may only select a replica queue.

The serving front end (memvul_tpu/serving/frontend.py) runs one thread
per connection.  A handler that calls ``time.sleep`` or any scoring/
encoding entry point inline serializes the whole server behind one
connection and — worse — can trigger the mid-serve XLA compiles the
micro-batcher exists to prevent (docs/serving.md).  The allowed surface
is exactly: ``service.submit(...)`` and ``future.result(...)``.

The replica router (memvul_tpu/serving/router.py) lives under the same
discipline one layer down: a *routing decision* reads queue depths and
picks a replica — it may never encode, score, warm, swap, or sleep
inline, because every request in the process is behind it.  Heavy fleet
operations (restart rebuilds, bank installs) belong to Replica methods
invoked from control-plane code (the monitor's worker threads, the
module-level ``rolling_swap``), not to the router class body.

The check is AST-based, over two class families wherever they live
under the package dir:

* classes whose *base* name ends with ``RequestHandler`` (stdlib
  ``BaseHTTPRequestHandler`` or a subclass) — handler threads;
* classes whose own or base name ends with ``Router`` — dispatch
  classes.

Flagged names in either family:

* ``sleep`` (``time.sleep`` or a bare imported ``sleep``);
* anything starting with ``predict`` (``predict_file``, ``predict_one``);
* the scoring/encoding entry points: ``score_instances``,
  ``score_texts``, ``encode_anchors``, ``encode_bank``,
  ``warmup_compile``, ``warmup_bank_shapes``, ``swap_bank``,
  ``install_bank``, and the raw jitted programs ``_score_fn`` /
  ``_ragged_score_fn``;
* the ragged serve path's packing/collation (docs/ragged_serving.md):
  ``pack_token_budget`` and ``collate_ragged`` — packing is batcher-
  thread work; a handler or router that packs inline serializes the
  process exactly like inline scoring would.

Usage: ``python tools/lint_no_blocking_in_handler.py [package_dir]`` —
exits 1 listing offenders, 0 when clean, 2 on a bad argument.  Invoked
as a tier-1 test from ``tests/test_no_blocking_in_handler.py``.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

FORBIDDEN_NAMES = {
    "sleep",
    "score_instances",
    "score_texts",
    "encode_anchors",
    "encode_bank",
    "warmup_compile",
    "warmup_bank_shapes",
    "swap_bank",
    "install_bank",
    "_score_fn",
    "_ragged_score_fn",
    "pack_token_budget",
    "collate_ragged",
}
FORBIDDEN_PREFIXES = ("predict",)


def _called_name(node: ast.Call) -> str:
    """The terminal name of a call: ``time.sleep(...)`` → "sleep",
    ``service.predictor.predict_file(...)`` → "predict_file"."""
    func = node.func
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return ""


def _is_handler_class(node: ast.ClassDef) -> bool:
    for base in node.bases:
        name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else ""
        )
        if name.endswith("RequestHandler"):
            return True
    return False


def _is_router_class(node: ast.ClassDef) -> bool:
    """A router dispatch class: named ``*Router`` or deriving from one
    (the serving tier's ``ReplicaRouter`` and anything that subclasses
    it to customize the routing policy)."""
    if node.name.endswith("Router"):
        return True
    for base in node.bases:
        name = base.attr if isinstance(base, ast.Attribute) else (
            base.id if isinstance(base, ast.Name) else ""
        )
        if name.endswith("Router"):
            return True
    return False


def find_blocking_calls(package_dir: Path) -> List[str]:
    """``path:line: name`` for every forbidden call inside a
    ``*RequestHandler`` subclass or a ``*Router`` dispatch class under
    ``package_dir``."""
    offenders: List[str] = []
    for path in sorted(package_dir.rglob("*.py")):
        try:
            tree = ast.parse(path.read_text(encoding="utf-8"), filename=str(path))
        except SyntaxError as e:  # a file that doesn't parse is its own bug
            offenders.append(f"{path}:{e.lineno}: syntax error: {e.msg}")
            continue
        for node in ast.walk(tree):
            if not (
                isinstance(node, ast.ClassDef)
                and (_is_handler_class(node) or _is_router_class(node))
            ):
                continue
            for call in ast.walk(node):
                if not isinstance(call, ast.Call):
                    continue
                name = _called_name(call)
                if name in FORBIDDEN_NAMES or name.startswith(FORBIDDEN_PREFIXES):
                    offenders.append(f"{path}:{call.lineno}: {name}")
    return offenders


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        package_dir = Path(argv[0])
    else:
        package_dir = Path(__file__).resolve().parent.parent / "memvul_tpu"
    if not package_dir.is_dir():
        print(f"lint_no_blocking_in_handler: {package_dir} is not a directory",
              file=sys.stderr)
        return 2
    offenders = find_blocking_calls(package_dir)
    for line in offenders:
        print(f"blocking call in handler/router class: {line}")
    if offenders:
        print(
            f"{len(offenders)} blocking call(s) in handler/router classes "
            "— a handler may only submit() and wait on the future; a "
            "router may only select a replica queue (docs/serving.md)"
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
