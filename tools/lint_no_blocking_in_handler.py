#!/usr/bin/env python
"""Lint: HTTP handler threads may only enqueue + wait on a future,
router dispatch classes may only select a replica queue, ``*Balancer``
and ``*Autoscaler`` classes may only decide from cached host/hint
state, and ``*Dispatcher`` admission paths may never sleep or
round-trip the device per request.

Thin shim over the shared static-analysis engine
(``memvul_tpu/analysis/``, checker **MV102** — docs/static_analysis.md):
the engine owns the single AST walk and the per-family forbidden-name
sets (the serving tier's scoring/encoding/packing surface plus
``sleep`` for handlers/routers; the narrow stall-shaped set —
``sleep``/``score_texts``/``predict*`` — for dispatcher classes; see
``memvul_tpu/analysis/checkers/handlers.py``); this entry point only
preserves the historical CLI contract and the ``find_blocking_calls``
helper the tier-1 tests import.  Rationale lives in docs/serving.md: a
handler that scores inline serializes the server behind one connection;
a router that does it stalls every request in the process; a dispatcher
that blocks its admission loop re-couples queue wait to device latency.

Usage: ``python tools/lint_no_blocking_in_handler.py [package_dir]`` —
exits 1 listing offenders as 1-based ``path:line: name``, 0 when clean,
2 on a bad argument.  Invoked as a tier-1 test from
``tests/test_no_blocking_in_handler.py``.
"""

from __future__ import annotations

import sys
from pathlib import Path
from typing import List

_REPO = Path(__file__).resolve().parent.parent
if str(_REPO) not in sys.path:
    sys.path.insert(0, str(_REPO))


def find_blocking_calls(package_dir: Path) -> List[str]:
    """``path:line: name`` for every forbidden call inside a
    ``*RequestHandler`` subclass, a ``*Router`` dispatch class, a
    ``*Balancer``/``*Autoscaler`` control class, or a ``*Dispatcher``
    strategy class under ``package_dir``, via the shared engine's
    MV102 checker."""
    from memvul_tpu.analysis import run_tool_checkers

    package_dir = Path(package_dir)
    result = run_tool_checkers(["MV001", "MV102"], package_dir)
    out: List[str] = []
    for f in result.active:
        path = package_dir / f.path
        if f.code == "MV001":
            out.append(f"{path}:{f.line}: {f.message}")
        else:
            out.append(f"{path}:{f.line}: {f.symbol}")
    return out


def main(argv: List[str] | None = None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    if argv:
        package_dir = Path(argv[0])
    else:
        package_dir = _REPO / "memvul_tpu"
    if not package_dir.is_dir():
        print(f"lint_no_blocking_in_handler: {package_dir} is not a directory",
              file=sys.stderr)
        return 2
    offenders = find_blocking_calls(package_dir)
    for line in offenders:
        print(f"blocking call in handler/router class: {line}")
    if offenders:
        print(
            f"{len(offenders)} blocking call(s) in handler/router classes "
            "— a handler may only submit() and wait on the future; a "
            "router may only select a replica queue (docs/serving.md)"
        )
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
