"""Corpus-scale streaming rehearsal (round-3 verdict #6).

Drives the FULL ``predict_file`` path — streaming ``.jsonl`` reader
(data/readers.py::_iter_corpus), normalize + tokenize, bucketed batching,
async device dispatch, the writer thread serializing one ~129-float dict
per report, and the threshold-swept metrics — at two corpus scales, and
asserts the host pipeline sustains device throughput as the corpus grows
(the writer thread and tokenizer had never been exercised above toy sizes
on hardware).  This is the predict-side scale story for the reference's
1.2M-report job (predict_memory.py:92-110).

    python tools/streaming_rehearsal.py                  # base model, 16k vs 102k
    python tools/streaming_rehearsal.py --model tiny --sizes 2048,8192   # CPU

Records one ``streaming_scale`` row in TPU_PROOFS.json and regenerates
SMOKE.md.
"""

from __future__ import annotations

import argparse
import json
import sys
import tempfile
import time
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "tools"))


def run(
    sizes,
    model_preset: str,
    seq_len: int,
    tokens_per_batch: int,
    min_ratio: float = 0.9,
) -> dict:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from memvul_tpu.utils.platform import enable_compilation_cache, honor_platform_env

    honor_platform_env()
    enable_compilation_cache()

    from memvul_tpu.data.readers import MemoryReader
    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.evaluate.predict_memory import SiamesePredictor
    from memvul_tpu.models import BertConfig, MemoryModel

    n_max = max(sizes)
    ws = build_workspace(
        tempfile.mkdtemp(prefix="streaming_"),
        seed=0,
        num_projects=8,
        reports_per_project=max(4, min(n_max, 16384) // 8),
        realistic_lengths=True,
    )
    if model_preset == "tiny":
        cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
        seq_len = min(seq_len, cfg.max_position_embeddings)
    else:
        cfg = BertConfig.base(
            vocab_size=max(30522, ws["tokenizer"].vocab_size), dtype=jnp.bfloat16
        )
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)

    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )

    # materialize .jsonl corpora of exactly the requested sizes by cycling
    # the synthetic test split's RAW records (predict_file re-reads from
    # disk each time — the streaming path under test)
    raw = json.loads(Path(ws["paths"]["test"]).read_text())
    corpus_files = {}
    for n in sizes:
        path = Path(ws["paths"]["test"]).parent / f"test_stream_{n}.jsonl"
        with open(path, "w", encoding="utf-8") as f:
            for i in range(n):
                f.write(json.dumps(raw[i % len(raw)]) + "\n")
        corpus_files[n] = str(path)

    predictor = SiamesePredictor(
        model,
        params,
        ws["tokenizer"],
        batch_size=tokens_per_batch // seq_len,
        max_length=seq_len,
        buckets=tuple(b for b in (64, 128, 256, 512) if b <= seq_len) or (seq_len,),
        tokens_per_batch=tokens_per_batch,
    )
    anchors = [
        {"text1": text, "meta": {"label": f"{cat}#{i}", "type": "golden"}}
        for i, (cat, text) in enumerate(
            (list(ws["anchors"].items()) * 20)[:129]
        )
    ]
    predictor.encode_anchors(anchors)

    rows = []
    for n in sorted(sizes):
        out = Path(tempfile.mkdtemp()) / f"result_{n}.jsonl"
        # warmup pass on the SMALLEST corpus only (compile one program per
        # bucket + prime the tokenizer cache exactly as bench.py does)
        if not rows:
            predictor.predict_file(reader, corpus_files[n], out)
        t0 = time.perf_counter()
        metrics = predictor.predict_file(reader, corpus_files[n], out)
        elapsed = time.perf_counter() - t0
        lines = sum(1 for _ in open(out))
        rows.append(
            {
                "n_reports": n,
                "reports_per_s": metrics["num_samples"] / elapsed,
                "elapsed_s": elapsed,
                "result_lines": lines,
                "num_samples": metrics["num_samples"],
            }
        )
        print(f"streaming {n}: {rows[-1]['reports_per_s']:.1f} reports/s")

    small, large = rows[0], rows[-1]
    ratio = large["reports_per_s"] / small["reports_per_s"]
    payload = {
        "model": f"bert-{model_preset}",
        "seq_len": seq_len,
        "rows": rows,
        "large_over_small_rps": ratio,
        # self-describing artifact: which acceptance bar this run was
        # gated against (0.9 on-chip; CPU plumbing tests pass looser)
        "min_ratio": min_ratio,
    }
    import tpu_proofs

    tpu_proofs._record("streaming_scale", payload)
    tpu_proofs.write_smoke_md()
    # the acceptance: throughput at the large scale within 10% of small
    # (no host-side sag as the corpus grows).  ``min_ratio`` is the
    # on-chip gate; CPU plumbing tests pass a looser bound — wall-clock
    # ratios on a loaded 1-core host are not the claim under test there
    assert ratio > min_ratio, payload
    return payload


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="16384,102400")
    ap.add_argument("--model", default="base", choices=("base", "tiny"))
    ap.add_argument("--seq-len", type=int, default=512)
    ap.add_argument("--tokens", type=int, default=256 * 1024)
    args = ap.parse_args(argv)
    sizes = [int(s) for s in args.sizes.split(",")]
    run(sizes, args.model, args.seq_len, args.tokens)
    return 0


if __name__ == "__main__":
    sys.exit(main())
