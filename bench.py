"""Repo-root benchmark shim — the implementation lives in the package
(``memvul_tpu/bench.py``) so installed copies and the CLI share it."""

from memvul_tpu.bench import main

if __name__ == "__main__":
    import sys

    sys.exit(main())
