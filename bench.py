"""Benchmark: Siamese anchor-bank scoring throughput on TPU.

Measures the north-star workload (SURVEY.md §6): stream issue reports
through the full inference path — BERT-base encode (bf16), anchor-bank
match against 129 anchors, per-anchor softmax + best-anchor reduce —
exactly what `predict_memory` does over the 1.2M-report corpus.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline: the reference repo publishes no throughput number (BASELINE.md).
The GTX-3090 estimate used here: ~71 TFLOP/s dense fp16 tensor peak at
~30% achieved MFU for PyTorch-1.8 BERT-base inference ≈ 21 TFLOP/s
effective; one report at eval length 512 costs ≈ 2·110e6·512 ≈ 1.13e11
FLOP → ≈ 190 reports/s. We use 190.0; vs_baseline = measured / 190.
"""

import json
import os
import sys
import tempfile
import time

BASELINE_RPS_512 = 190.0  # estimated GTX-3090 throughput at seq_len 512 (above)


def main() -> None:
    import numpy as np
    import jax
    import jax.numpy as jnp

    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.data.readers import MemoryReader
    from memvul_tpu.evaluate.predict_memory import SiamesePredictor
    from memvul_tpu.models import BertConfig, MemoryModel

    seq_len = int(os.environ.get("BENCH_SEQ_LEN", "512"))
    # batch 1024 ≈ best single-chip throughput at seq 512 (2048 exceeds
    # HBM: the attention score tensor alone is ~13GB); measured sweep:
    # 256→708, 512→848, 1024→898 reports/s on v5e
    batch_size = int(os.environ.get("BENCH_BATCH", "1024"))
    n_reports = int(os.environ.get("BENCH_REPORTS", "4096"))
    n_anchors = 129  # reference external-memory size (utils.py:347)

    ws = build_workspace(
        tempfile.mkdtemp(),
        seed=0,
        num_projects=8,
        reports_per_project=max(4, n_reports // 8),
    )
    cfg = BertConfig.base(
        vocab_size=max(30522, ws["tokenizer"].vocab_size), dtype=jnp.bfloat16
    )
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)

    predictor = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=batch_size, max_length=seq_len
    )
    # 129-anchor bank from synthetic anchor texts (cycled to reference size)
    base_anchors = list(ws["anchors"].items())
    instances = []
    for i in range(n_anchors):
        cat, text = base_anchors[i % len(base_anchors)]
        instances.append(
            {"text1": text, "meta": {"label": f"{cat}#{i}", "type": "golden"}}
        )
    predictor.encode_anchors(instances)

    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    test_instances = list(reader.read(ws["paths"]["test"], split="test"))
    while len(test_instances) < n_reports:
        test_instances = test_instances + test_instances
    test_instances = test_instances[:n_reports]

    def run_pass():
        total = 0
        start = time.perf_counter()
        for probs, metas in predictor.score_instances(iter(test_instances)):
            total += len(metas)
        return total, time.perf_counter() - start

    run_pass()  # warmup: compile + tokenizer cache fill
    total, elapsed = run_pass()
    rps = total / elapsed

    # the baseline estimate is FLOP-derived, so scale it to the actual
    # sequence length when BENCH_SEQ_LEN overrides the 512 default
    baseline = BASELINE_RPS_512 * (512.0 / seq_len)
    print(
        json.dumps(
            {
                "metric": "siamese_scoring_throughput",
                "value": round(rps, 1),
                "unit": "reports/sec",
                "vs_baseline": round(rps / baseline, 2),
            }
        )
    )


if __name__ == "__main__":
    sys.exit(main())
