"""The post-hoc "what happened" plane (PR 18, docs/observability.md
"Metrics history" / "Alert rules" / "Incident bundles"):
telemetry/timeseries.py + telemetry/alerts.py + serving/incident.py.

The acceptance contract this file pins:

* **TSDB semantics** — counters enter as derived per-second rates
  (clamped at 0 across resets), gauges as-is, histogram summaries as
  `<name>.<field>` series; points coalesce within one resolution
  bucket and every ring is retention-bounded; labeled snapshot parts
  render Prometheus-style series names.
* **sampler under fire** — a live concurrent registry writer never
  breaks a sample (`tsdb.sample_errors` stays 0) and history only
  grows — the live twin of the events.jsonl torn-tail test.
* **alert edges** — rules fire and resolve exactly once per
  transition (`alert.fired`/`alert.resolved` counters, `alert.firing`
  gauge, listener calls), and the `alert.*` gauges round-trip the
  Prometheus exposition like every other metric.
* **incident bundles** — triggers are non-blocking and rate-limited;
  a bundle carries manifest + metric window + traces + programs, all
  atomic; retention prunes oldest-first; the `incident.dump` fault
  point proves a failing or slow dump never delays request
  resolution; a replica SIGKILL under load produces a bundle
  automatically and `telemetry-report` renders it (text and --json).
* **disabled is free** — `attach_flight_recorder` with cadence 0
  constructs nothing and adds no metric names; with the sampler ON,
  served scores are bitwise-unchanged.
"""

import json
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

from memvul_tpu import telemetry
from memvul_tpu.resilience import faults
from memvul_tpu.serving import (
    STATUS_OK,
    InprocessClient,
    Replica,
    ReplicaRouter,
    RouterConfig,
    ScoringService,
    ServiceConfig,
)
from memvul_tpu.serving.frontend import run_http_server
from memvul_tpu.serving.incident import (
    BUNDLE_FILES,
    IncidentRecorder,
    attach_flight_recorder,
)
from memvul_tpu.telemetry.alerts import AlertEngine, AlertRule, default_rules
from memvul_tpu.telemetry.registry import TelemetryRegistry
from memvul_tpu.telemetry.timeseries import (
    MetricsSampler,
    TimeSeriesStore,
    series_name,
)


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.reset()
    telemetry.reset()


def _part(counters=None, gauges=None, histograms=None, labels=None):
    return [(
        labels or {},
        {
            "counters": counters or {},
            "gauges": gauges or {},
            "histograms": histograms or {},
        },
    )]


# -- TimeSeriesStore -----------------------------------------------------------

def test_store_derives_counter_rates_and_keeps_gauges():
    store = TimeSeriesStore(resolution_s=1.0, retention_s=60.0)
    store.observe(_part(counters={"serve.errors": 0},
                        gauges={"serve.queue_depth": 1.0}), now=100.0)
    # first counter sample establishes the baseline — no rate point yet
    assert "serve.errors.rate" not in store.history(now=100.0)
    store.observe(_part(counters={"serve.errors": 5},
                        gauges={"serve.queue_depth": 3.0}), now=101.0)
    store.observe(_part(counters={"serve.errors": 5}), now=102.0)
    # a counter RESET (restart) clamps to 0, never a negative rate
    store.observe(_part(counters={"serve.errors": 2}), now=103.0)
    history = store.history(now=103.0)
    assert history["serve.errors.rate"] == [
        [101.0, 5.0], [102.0, 0.0], [103.0, 0.0]
    ]
    assert history["serve.queue_depth"] == [[100.0, 1.0], [101.0, 3.0]]


def test_store_histogram_summaries_become_field_series():
    store = TimeSeriesStore()
    store.observe(_part(histograms={
        "serve.latency_s": {"count": 4, "mean": 0.2, "p50": 0.15, "p95": 0.4},
    }), now=50.0)
    history = store.history(now=50.0)
    assert history["serve.latency_s.mean"] == [[50.0, 0.2]]
    assert history["serve.latency_s.p50"] == [[50.0, 0.15]]
    assert history["serve.latency_s.p95"] == [[50.0, 0.4]]


def test_store_coalesces_within_resolution_and_bounds_retention():
    store = TimeSeriesStore(resolution_s=1.0, retention_s=5.0)
    # two samples inside one bucket: newest value, the bucket's timestamp
    store.observe(_part(gauges={"g": 1.0}), now=10.0)
    store.observe(_part(gauges={"g": 2.0}), now=10.4)
    assert store.history(now=10.4)["g"] == [[10.0, 2.0]]
    # rings hold at most retention/resolution points regardless of feed
    for i in range(20):
        store.observe(_part(gauges={"g": float(i)}), now=20.0 + i)
    points = store.history(now=40.0)["g"]
    assert len(points) == 5  # maxlen = 5/1
    assert points[-1] == [39.0, 19.0]


def test_store_labels_window_and_prefix_filter():
    store = TimeSeriesStore()
    store.observe(
        _part(gauges={"serve.queue_depth": 2.0}, labels={"replica": "r0"})
        + _part(gauges={"serve.queue_depth": 7.0}, labels={"replica": "r1"})
        + _part(gauges={"slo.burn_rate_fast": 0.5}),
        now=100.0,
    )
    assert series_name("m", (("replica", "r0"),)) == 'm{replica="r0"}'
    history = store.history(metric="serve.", now=100.0)
    assert set(history) == {
        'serve.queue_depth{replica="r0"}', 'serve.queue_depth{replica="r1"}'
    }
    # window(): exact-name justification slice, all label sets
    window = store.window(["serve.queue_depth"], 60.0, now=100.0)
    assert window['serve.queue_depth{replica="r1"}'] == [[100.0, 7.0]]
    assert "slo.burn_rate_fast" not in window
    # and the window is a cutoff, not the whole ring
    store.observe(_part(gauges={"slo.burn_rate_fast": 2.0}), now=500.0)
    assert store.window(["slo.burn_rate_fast"], 10.0, now=500.0) == {
        "slo.burn_rate_fast": [[500.0, 2.0]]
    }


def test_store_and_sampler_validation():
    with pytest.raises(ValueError, match="resolution_s"):
        TimeSeriesStore(resolution_s=0)
    with pytest.raises(ValueError, match="retention_s"):
        TimeSeriesStore(resolution_s=2.0, retention_s=1.0)
    with pytest.raises(ValueError, match="cadence_s"):
        MetricsSampler(TelemetryRegistry(enabled=True), cadence_s=0)


# -- MetricsSampler ------------------------------------------------------------

def test_sampler_reports_own_cost_and_samples_bare_registry():
    target = TelemetryRegistry(enabled=True)
    meter = TelemetryRegistry(enabled=True)
    target.gauge("serve.queue_depth").set(4.0)
    sampler = MetricsSampler(target, cadence_s=1.0, registry=meter, start=False)
    sampler.sample(now=100.0)
    assert sampler.history()["serve.queue_depth"] == [[100.0, 4.0]]
    snap = meter.snapshot()
    assert snap["counters"]["tsdb.samples"] == 1
    assert "tsdb.sample_errors" not in snap["counters"]
    assert snap["gauges"]["tsdb.series"] >= 1
    assert snap["histograms"]["tsdb.sample_s"]["count"] == 1
    status = sampler.status()
    assert status["enabled"] is True and status["samples"] == 1


def test_sampler_survives_live_concurrent_registry_writer():
    """The live twin of the events.jsonl torn-tail test: a writer thread
    hammers the registry while the sampler snapshots it — every sample
    succeeds, rates never go negative, history only grows."""
    target = TelemetryRegistry(enabled=True)
    meter = TelemetryRegistry(enabled=True)
    sampler = MetricsSampler(target, cadence_s=1.0, registry=meter, start=False)
    stop = threading.Event()

    def writer():
        for i in range(400):
            target.counter("load.ticks").inc()
            target.gauge("load.depth").set(float(i))
            target.histogram("load.lat_s").observe(0.001 * (i % 7))
            time.sleep(0.0003)
        stop.set()

    thread = threading.Thread(target=writer)
    thread.start()
    samples = 0
    seen = 0
    try:
        while not stop.is_set():
            sampler.sample()  # must never raise
            samples += 1
            points = sampler.history().get("load.depth", [])
            assert len(points) >= seen, "history went backwards"
            seen = len(points)
    finally:
        thread.join(timeout=10)
    assert samples > 10, "the sampler never actually raced the writer"
    assert "tsdb.sample_errors" not in meter.snapshot()["counters"]
    for point in sampler.history().get("load.ticks.rate", []):
        assert point[1] >= 0.0


# -- AlertEngine ---------------------------------------------------------------

def test_alert_rule_validation_and_default_set():
    rules = default_rules()
    assert {r.name for r in rules} == {
        "serve_error_rate", "dead_letter_streak", "heartbeat_stalled",
        "hbm_growth", "recompile_after_warm", "slo_fast_burn",
    }
    with pytest.raises(ValueError, match="unknown kind"):
        AlertRule("x", "weird", "m")
    with pytest.raises(ValueError, match="needs a metric"):
        AlertRule("x", "rate")
    with pytest.raises(ValueError, match="window_s"):
        AlertRule("x", "threshold", "m", window_s=0)
    store = TimeSeriesStore()
    rule = AlertRule("dup", "threshold", "m")
    with pytest.raises(ValueError, match="duplicate"):
        AlertEngine(store, rules=[rule, rule], start=False)


def test_alert_engine_fires_and_resolves_once_per_edge():
    store = TimeSeriesStore(resolution_s=1.0, retention_s=600.0)
    meter = TelemetryRegistry(enabled=True)
    rule = AlertRule("err_rate", "rate", "serve.errors",
                     threshold=0.0, window_s=60.0)
    engine = AlertEngine(store, registry=meter, rules=[rule], start=False)
    heard = []
    engine.add_listener(heard.append)

    store.observe(_part(counters={"serve.errors": 0}), now=100.0)
    store.observe(_part(counters={"serve.errors": 5}), now=101.0)
    status = engine.tick(now=101.0)
    assert [f["rule"] for f in status["firing"]] == ["err_rate"]
    assert heard and heard[0]["rule"] == "err_rate"
    assert heard[0]["value"] == 5.0
    assert heard[0]["series"] == "serve.errors.rate"
    assert heard[0]["rule_kind"] == "rate"  # never the event's own "kind"
    # still firing: no duplicate edge
    engine.tick(now=102.0)
    snap = meter.snapshot()
    assert snap["counters"]["alert.fired"] == 1
    assert snap["gauges"]["alert.firing"] == 1.0
    assert len(heard) == 1
    # the offending points age out of the window → one resolve edge
    engine.tick(now=300.0)
    snap = meter.snapshot()
    assert snap["counters"]["alert.resolved"] == 1
    assert snap["gauges"]["alert.firing"] == 0.0
    assert not engine.status()["firing"]
    rules = {r["name"]: r for r in engine.status()["rules"]}
    assert rules["err_rate"]["firing"] is False


def test_alert_threshold_absence_and_growth_kinds():
    store = TimeSeriesStore()
    meter = TelemetryRegistry(enabled=True)
    engine = AlertEngine(
        store, registry=meter, start=False,
        rules=[
            AlertRule("burn", "threshold", "slo.burn_rate_fast",
                      threshold=1.0, window_s=60.0),
            AlertRule("stall", "absence", window_s=30.0),
            AlertRule("leak", "growth", "serve.hbm_in_use_bytes",
                      threshold=0.2, window_s=600.0),
        ],
    )
    t0 = engine._started_wall
    # grace: an empty store is not an absence until window_s after birth
    assert not engine.tick(now=t0 + 1.0)["firing"]
    status = engine.tick(now=t0 + 31.0)
    assert [f["rule"] for f in status["firing"]] == ["stall"]
    # samples arrive: absence resolves; burn + leak fire on their shapes
    store.observe(_part(gauges={"slo.burn_rate_fast": 0.4,
                                "serve.hbm_in_use_bytes": 1000.0}),
                  now=t0 + 32.0)
    store.observe(_part(gauges={"slo.burn_rate_fast": 2.5,
                                "serve.hbm_in_use_bytes": 1300.0}),
                  now=t0 + 40.0)
    status = engine.tick(now=t0 + 40.0)
    assert {f["rule"] for f in status["firing"]} == {"burn", "leak"}
    leak = next(f for f in status["firing"] if f["rule"] == "leak")
    assert leak["value"] == pytest.approx(0.3)


def test_alert_gauges_roundtrip_exposition():
    """The new alert.* names ride the same Prometheus exposition as
    every other metric — render and parse agree exactly."""
    from memvul_tpu.telemetry.exposition import (
        parse_exposition, render_exposition,
    )

    registry = TelemetryRegistry(enabled=True)
    registry.counter("alert.fired").inc(3)
    registry.counter("alert.resolved").inc(2)
    registry.gauge("alert.firing").set(1.0)
    registry.gauge("tsdb.series").set(42.0)
    text = render_exposition([({}, registry.snapshot())])
    parsed = parse_exposition(text)
    assert parsed["alert_fired"][""] == 3
    assert parsed["alert_resolved"][""] == 2
    assert parsed["alert_firing"][""] == 1.0
    assert parsed["tsdb_series"][""] == 42.0


# -- IncidentRecorder ----------------------------------------------------------

class _Target:
    """Minimal bundle-snapshot surface."""

    def __init__(self):
        self.hold = None  # optional Event: health_summary blocks on it

    def health_summary(self):
        if self.hold is not None:
            assert self.hold.wait(timeout=30), "test forgot to release hold"
        return {"status": "ok", "queue_depth": 0}

    def recent_traces(self, limit=None):
        return [{"trace_id": "t-1"}]

    def programs_snapshot(self):
        return [{"key": "score:4x8"}]


def _recorder(tmp_path, meter, **kw):
    store = TimeSeriesStore()
    store.observe(_part(gauges={"serve.queue_depth": 1.0}), now=time.time())
    kw.setdefault("start", False)
    return IncidentRecorder(
        _Target(), tmp_path, store=store, registry=meter, **kw
    )


def test_incident_bundle_contents_and_rate_limit(tmp_path):
    meter = TelemetryRegistry(enabled=True)
    recorder = _recorder(tmp_path, meter, min_interval_s=3600.0)
    assert recorder.trigger("replica_dead", {"replica": "r0"}) is True
    assert recorder.drain() == 1
    bundles = list((tmp_path / "incidents").iterdir())
    assert len(bundles) == 1 and bundles[0].name.endswith("-replica_dead")
    assert sorted(p.name for p in bundles[0].iterdir()) == sorted(BUNDLE_FILES)
    manifest = json.loads((bundles[0] / "manifest.json").read_text())
    assert manifest["trigger"] == "replica_dead"
    assert manifest["detail"] == {"replica": "r0"}
    assert manifest["health"]["status"] == "ok"
    metrics = json.loads((bundles[0] / "metrics.json").read_text())
    assert "serve.queue_depth" in metrics["history"]
    assert json.loads((bundles[0] / "traces.json").read_text()) == [
        {"trace_id": "t-1"}
    ]
    assert json.loads((bundles[0] / "programs.json").read_text()) == [
        {"key": "score:4x8"}
    ]
    snap = meter.snapshot()
    assert snap["counters"]["incident.dumps"] == 1
    # a second trigger inside min_interval_s is suppressed, not written
    recorder.trigger("replica_dead", {"replica": "r1"})
    assert recorder.drain() == 1
    assert len(list((tmp_path / "incidents").iterdir())) == 1
    assert meter.snapshot()["counters"]["incident.suppressed"] == 1
    assert recorder.status()["bundles"] == [bundles[0].name]


def test_incident_retention_prunes_and_queue_bounds(tmp_path):
    meter = TelemetryRegistry(enabled=True)
    recorder = _recorder(tmp_path, meter, min_interval_s=0.0, max_bundles=2)
    for i in range(4):
        recorder.trigger(f"t{i}")
    assert recorder.drain() == 4
    names = sorted(p.name for p in (tmp_path / "incidents").iterdir())
    assert len(names) == 2  # oldest pruned
    assert names == recorder.status()["bundles"]
    # bounded queue: overflow is a False return + a counter, never a block
    tight = _recorder(tmp_path / "q", meter, queue_size=1)
    assert tight.trigger("a") is True
    assert tight.trigger("b") is False
    assert meter.snapshot()["counters"]["incident.suppressed"] >= 1


def test_incident_on_alert_listener_adapter(tmp_path):
    meter = TelemetryRegistry(enabled=True)
    recorder = _recorder(tmp_path, meter)
    recorder.on_alert({"rule": "slo_fast_burn", "value": 2.0})
    assert recorder.drain() == 1
    (bundle,) = (tmp_path / "incidents").iterdir()
    assert bundle.name.endswith("-alert-slo_fast_burn")
    manifest = json.loads((bundle / "manifest.json").read_text())
    assert manifest["detail"]["rule"] == "slo_fast_burn"


def test_incident_dump_fault_is_counted_never_raised(tmp_path):
    """The incident.dump fault point (docs/fault_tolerance.md): a
    failing dump books incident.dump_errors and writes nothing — the
    trigger side never sees the failure."""
    meter = TelemetryRegistry(enabled=True)
    recorder = _recorder(tmp_path, meter, min_interval_s=0.0)
    faults.configure("incident.dump=raise:RuntimeError:dump chaos")
    assert recorder.trigger("host_dead") is True
    assert recorder.drain() == 1  # handled, not raised
    snap = meter.snapshot()
    assert snap["counters"]["incident.dump_errors"] == 1
    assert "incident.dumps" not in snap["counters"]
    assert not (tmp_path / "incidents").exists()
    # the disarmed point recovers on the next trigger
    recorder.trigger("host_dead")
    assert recorder.drain() == 1
    assert meter.snapshot()["counters"]["incident.dumps"] == 1


# -- the serving path stays decoupled ------------------------------------------

def _fake_service(registry=None, **overrides):
    # the fake-predictor service from the router suite, at test scale
    from test_serving_router import _FakePredictor

    config = ServiceConfig(
        max_batch=4, max_wait_ms=1.0, max_queue=1000,
        default_deadline_ms=30000.0, **overrides,
    )
    return ScoringService(_FakePredictor(), config=config, registry=registry)


@pytest.mark.chaos
def test_slow_or_failing_dump_never_blocks_request_resolution(tmp_path):
    """The off-path claim, chaos-tested: with the recorder's worker WEDGED
    mid-dump (health_summary blocked) and a failing dump queued behind
    it, client requests keep resolving at full speed."""
    registry = TelemetryRegistry(enabled=True)
    service = _fake_service(registry=registry)
    # wedge: the worker blocks inside _dump reading the target's
    # health_summary — the serving path shares only the trigger side
    wedged = _Target()
    hold = wedged.hold = threading.Event()
    recorder = IncidentRecorder(
        wedged, tmp_path, registry=registry,
        min_interval_s=0.0, start=True,
    )
    service.incident_recorder = recorder
    try:
        assert recorder.trigger("wedge") is True
        time.sleep(0.05)  # let the worker pick it up and block
        client = InprocessClient(service)
        t0 = time.perf_counter()
        responses = [client.score(f"report {i}") for i in range(16)]
        elapsed = time.perf_counter() - t0
        assert all(r["status"] == STATUS_OK for r in responses)
        assert elapsed < 5.0, "scoring stalled behind a wedged dump"
        assert "incident.dumps" not in registry.snapshot()["counters"]
    finally:
        hold.set()
        recorder.stop()
        service.drain()
    # released: the wedged bundle completes after the fact
    assert (tmp_path / "incidents").is_dir()


def test_attach_gate_constructs_nothing_when_disabled(tmp_path):
    """Disabled is free: cadence 0 returns the target untouched — no
    attributes, no threads, and (the byte-identical pin) no new metric
    names in the service's own emitted set."""
    registry = TelemetryRegistry(enabled=True)
    service = _fake_service(registry=registry)
    client = InprocessClient(service)
    try:
        assert attach_flight_recorder(
            service, run_dir=tmp_path, registry=registry, cadence_s=0.0
        ) is service
        for attr in ("metrics_sampler", "alert_engine", "incident_recorder"):
            assert not hasattr(service, attr)
        assert client.score("probe")["status"] == STATUS_OK
    finally:
        service.drain()
    names = set(registry.snapshot()["counters"]) | set(
        registry.snapshot()["gauges"]
    )
    assert not [n for n in names
                if n.startswith(("tsdb.", "alert.", "incident."))], names
    assert not (tmp_path / "incidents").exists()


def test_attach_enabled_wires_plane_and_scores_stay_bitwise(tmp_path):
    """With the sampler ON, responses are bitwise-identical to the
    undisturbed service — the history plane observes, never perturbs."""
    texts = [f"report {i}" for i in range(12)]
    plain = _fake_service()
    baseline = [InprocessClient(plain).score(t) for t in texts]
    plain.drain()

    registry = TelemetryRegistry(enabled=True)
    service = _fake_service(registry=registry)
    attach_flight_recorder(
        service, run_dir=tmp_path, registry=registry,
        cadence_s=0.02, alert_interval_s=3600.0, rules=(),
    )
    try:
        assert service.metrics_sampler.cadence_s == 0.02
        assert service.alert_engine is not None
        assert service.incident_recorder is not None
        responses = [InprocessClient(service).score(t) for t in texts]
        deadline = time.monotonic() + 10
        while (time.monotonic() < deadline
               and not service.metrics_sampler.store.series_count):
            time.sleep(0.01)
        assert service.metrics_sampler.store.series_count > 0
    finally:
        service.metrics_sampler.stop()
        service.alert_engine.stop()
        service.incident_recorder.stop()
        service.drain()
    for base, live in zip(baseline, responses):
        assert base["status"] == live["status"] == STATUS_OK
        assert base["predict"] == live["predict"]  # bitwise via JSON floats
        assert base["anchor"] == live["anchor"]


# -- HTTP surfaces -------------------------------------------------------------

def _get_json(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return resp.status, json.loads(resp.read())


def test_frontend_metricsz_and_alertz(tmp_path):
    registry = TelemetryRegistry(enabled=True)
    service = _fake_service(registry=registry)
    server = run_http_server(service, port=0)
    base = "http://127.0.0.1:%d" % server.server_address[1]
    try:
        # disabled: a probe can tell "off" from "wrong URL"
        status, body = _get_json(base, "/metricsz")
        assert status == 200 and body == {
            "enabled": False, "series": 0, "history": {}
        }
        status, body = _get_json(base, "/alertz")
        assert status == 200 and body == {
            "enabled": False, "firing": [], "rules": []
        }
        # enabled: attach the plane and scrape history + rules
        sampler = MetricsSampler(
            service, cadence_s=1.0, registry=registry, start=False
        )
        sampler.store.observe(
            _part(gauges={"serve.queue_depth": 2.0}), now=time.time()
        )
        sampler.sample()
        service.metrics_sampler = sampler
        service.alert_engine = AlertEngine(
            sampler.store, registry=registry, start=False
        )
        status, body = _get_json(base, "/metricsz?window=600")
        assert status == 200 and body["enabled"] is True
        assert "serve.queue_depth" in body["history"]
        status, body = _get_json(
            base, "/metricsz?metric=serve.queue_depth"
        )
        assert list(body["history"]) == ["serve.queue_depth"]
        status, body = _get_json(base, "/alertz")
        assert status == 200 and body["enabled"] is True
        assert {r["name"] for r in body["rules"]} == {
            r.name for r in default_rules()
        }
        # a non-numeric window is a 400, not a stack trace
        with pytest.raises(urllib.error.HTTPError) as err:
            _get_json(base, "/metricsz?window=soon")
        assert err.value.code == 400
    finally:
        server.shutdown()
        service.drain()


# -- the acceptance drill ------------------------------------------------------

@pytest.mark.chaos
def test_replica_sigkill_under_load_produces_bundle_and_report(
    tmp_path, capsys
):
    """ISSUE 18's acceptance drill: SIGKILL a replica under load with the
    plane on → an incident bundle appears automatically carrying the
    metric history window, the trace ring, active alerts, and fleet
    state — and telemetry-report renders it, text and --json."""
    from test_serving_router import _FakePredictor

    run_dir = tmp_path / "run"
    registry = telemetry.configure(run_dir=run_dir)

    def make_factory(i):
        def factory(reg):
            return ScoringService(
                _FakePredictor(),
                config=ServiceConfig(
                    max_batch=4, max_wait_ms=1.0, max_queue=1000,
                    default_deadline_ms=30000.0, trace_sample_rate=1.0,
                ),
                registry=reg,
            )
        return factory

    replicas = [
        Replica(i, make_factory(i), telemetry_enabled=True) for i in range(2)
    ]
    router = ReplicaRouter(
        replicas,
        config=RouterConfig(monitor_interval_s=0.05, max_reroutes=3),
    )
    attach_flight_recorder(
        router, run_dir=run_dir, registry=registry,
        cadence_s=0.02, alert_interval_s=3600.0, rules=(),
        min_interval_s=0.0,
    )
    try:
        warm = [router.submit(f"warm {i}").result(timeout=10) for i in range(8)]
        assert all(r["status"] == STATUS_OK for r in warm)
        faults.configure("replica.kill.replica-0=raise:RuntimeError:chaos kill")
        responses = [
            router.submit(f"post-kill {i}").result(timeout=15)
            for i in range(24)
        ]
        assert all(r["status"] == STATUS_OK for r in responses)
        deadline = time.monotonic() + 15
        incidents = run_dir / "incidents"
        while time.monotonic() < deadline and not (
            incidents.is_dir() and any(incidents.iterdir())
        ):
            time.sleep(0.02)
        bundles = sorted(incidents.iterdir())
        assert bundles, "no incident bundle after a replica SIGKILL"
        assert bundles[0].name.endswith("-replica_dead")
        manifest = json.loads((bundles[0] / "manifest.json").read_text())
        assert manifest["detail"]["replica"] == "replica-0"
        assert manifest["health"]["replicas"]  # fleet state froze in
        assert "firing" in manifest["alerts"]  # active-alert snapshot
        metrics = json.loads((bundles[0] / "metrics.json").read_text())
        assert metrics["history"], "bundle carries no metric history"
        assert any("replica" in name for name in metrics["history"])
        traces = json.loads((bundles[0] / "traces.json").read_text())
        assert traces, "bundle carries no trace ring"
    finally:
        router.metrics_sampler.stop()
        router.alert_engine.stop()
        router.incident_recorder.stop()
        router.drain()
        telemetry.reset()

    # the flight recorder's output is renderable, text and --json
    from memvul_tpu.__main__ import main

    assert main(["telemetry-report", str(run_dir)]) == 0
    text = capsys.readouterr().out
    assert "INCIDENTS" in text and "replica_dead" in text
    assert main(["telemetry-report", str(run_dir), "--json"]) == 0
    payload = json.loads(capsys.readouterr().out)
    assert payload["incidents"], payload.get("incidents")
    incident = payload["incidents"][0]
    assert incident["trigger"] == "replica_dead"
    assert incident["series"] > 0 and incident["traces"] > 0
