"""Native (C++) normalizer: build, parity vs the Python pass table,
fallback contract, and batch throughput sanity.

The Python implementation is the specification (itself pinned against the
reference MemVul/util.py:39-142 by test_normalize.py); the native library
must agree byte-for-byte or be disabled by its own self-check.
"""

import pytest

from memvul_tpu.data.native import (
    get_native_normalizer,
    native_available,
    normalize_batch,
    _native_one,
)
from memvul_tpu.data.normalize import normalize_text
from memvul_tpu.data.synthetic import corpus_texts, generate_corpus

pytestmark = pytest.mark.skipif(
    not native_available(), reason="native normalizer failed to build/self-check"
)

ADVERSARIAL = [
    "",
    " ",
    "CVE-2021-44228 CWE-79 CVE-1-2",
    "https://cve.mitre.org/data?x=1 http://bugzilla.redhat.com/123",
    "https://example.com/a.zip https://example.com/index",
    "[link](a/b/c.md) ![img](http://x.com/i.png) [t](http://y.com)",
    "``` erro```r``` fine ```",
    "`` `` ` ` ``````",
    "nested `outer ```inner``` outer` end",
    "a@b.com someone_longer@domain.net x@y.cn @mention ",
    "Main.java:42 NullPointerException(foo) IOError: bad",
    "/a/b/c d\\e\\f g/h win\\path\\x.txt",
    "v1.2.3 2021-01-01 1e10 0x1F beta7 1.0.0-beta3",
    "thisIsCamel ALLCAPS lower.dotted.name call() arr[]",
    "<html><body> <<>> <a href=\"x\"> <-> < >",
    "-- --- ---- -",
    "####title *bold* **x** \\n\\n \\r\\n \\t\\t",
    "x" * 29, "y" * 30, "z" * 151,
    "word " * 200,
    "yaml\nfoo: bar\nbaz: qux",
    "Traceback (most recent call last):\n  File \"x.py\", line 1",
    "ünïcode naïve café — em-dash…",
    "tab\there newline\nhere cr\rhere",
]


def test_parity_on_adversarial_battery():
    for doc in ADVERSARIAL:
        lib = get_native_normalizer()
        native = _native_one(lib, doc)
        if native is None:
            continue  # explicit fallback is allowed, silence is not
        assert native == normalize_text(doc), f"divergence on {doc[:60]!r}"


def test_parity_on_synthetic_corpus():
    reports, _ = generate_corpus(seed=13, num_projects=6, reports_per_project=30)
    texts = corpus_texts(reports)
    native_out = normalize_batch(texts)
    python_out = [normalize_text(t) for t in texts]
    assert native_out == python_out


def test_batch_matches_single_calls():
    docs = ADVERSARIAL[:10]
    assert normalize_batch(docs) == [normalize_text(d) for d in docs]


def test_force_python_path():
    docs = ["CVE-2020-1 check"]
    assert normalize_batch(docs, force_python=True) == [normalize_text(docs[0])]


def test_non_ascii_doc_falls_back_natively():
    """Byte-oriented std::regex disagrees with Python's unicode \\s (e.g.
    U+00A0), so the library refuses non-ASCII docs and Python answers."""
    lib = get_native_normalizer()
    doc = "@user\xa0hello there"
    assert _native_one(lib, doc) is None
    assert normalize_batch([doc]) == [normalize_text(doc)]


def test_nul_byte_doc_falls_back():
    doc = "abc\x00hidden error text here"
    lib = get_native_normalizer()
    assert _native_one(lib, doc) is None  # would truncate at the NUL
    assert normalize_batch([doc]) == [normalize_text(doc)]


def test_corrupt_library_disables_native(tmp_path, monkeypatch):
    """A wrong-arch/corrupt .so must disable the native path, not crash."""
    import memvul_tpu.data.native as native_mod

    bad = tmp_path / "libmemvul_native.so"
    bad.write_bytes(b"not a shared object")
    monkeypatch.setattr(native_mod, "_LIB", bad)
    monkeypatch.setattr(native_mod, "_build_library", lambda: True)
    assert native_mod._load() is None


def test_oversized_doc_falls_back():
    lib = get_native_normalizer()
    # the single-doc entry runs on the caller's thread: 16KB cap
    big = "word " * 4_000  # 20KB > 16KB → native returns NULL
    assert _native_one(lib, big) is None
    # the batch API pool threads carry 64MB stacks: 256KB cap — a 20KB log
    # dump stays on the native path there, >256KB falls back to Python;
    # either way the result equals the Python specification
    huge = "word " * 60_000  # 300KB > 256KB batch cap
    out = normalize_batch([big, huge, "small CVE-2021-2 doc"])
    assert out[0] == normalize_text(big)
    assert out[1] == normalize_text(huge)
    assert out[2] == normalize_text("small CVE-2021-2 doc")


@pytest.mark.slow  # ~20 s: the Python-spec normalize of 16KB docs is the
# cost; the over/under fallback behavior stays covered fast by
# test_oversized_doc_falls_back
def test_caller_stack_cap_boundary():
    """Documents at the 16KB single-doc boundary: just-below normalizes
    natively, just-above returns NULL (Python fallback)."""
    lib = get_native_normalizer()
    under = "a" * 20 + " word" * ((16 << 10) // 5 - 10)  # just under 16KB
    assert len(under.encode()) <= 16 << 10
    assert _native_one(lib, under) == normalize_text(under)
    over = "b" * ((16 << 10) + 1)
    assert _native_one(lib, over) is None
    assert normalize_batch([over]) == [normalize_text(over)]


def test_sampled_runtime_parity_disables_on_mismatch(monkeypatch):
    """If a native output ever disagrees with the Python spec, the batch is
    recomputed in Python and the native path is disabled process-wide."""
    import memvul_tpu.data.native as native_mod

    assert native_mod.native_available()
    monkeypatch.setattr(native_mod, "_sampled_parity_ok", lambda *a: False)
    docs = ["CVE-2021-44228 here", "plain words"]
    out = native_mod.normalize_batch(docs)
    assert out == [normalize_text(d) for d in docs]
    assert not native_mod.native_available()  # disabled for the process
    # restore for other tests (module-level state)
    native_mod._state = None
    native_mod._lib = None
    assert native_mod.native_available()


def test_preprocess_uses_batch_path():
    from memvul_tpu.data.corpus import preprocess

    reports, _ = generate_corpus(seed=3, num_projects=2, reports_per_project=10)
    raw_titles = {r["Issue_Url"]: r["Issue_Title"] for r in reports}
    clean = preprocess(reports)
    for rec in clean:
        assert rec["Issue_Title"] == normalize_text(raw_titles[rec["Issue_Url"]])
