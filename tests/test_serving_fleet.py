"""Cross-host fleet supervision (serving/fleet.py, docs/serving.md
"Cross-host fleet").

The acceptance contract this file pins:

* **routing + merged endpoints** — a 2-host balancer spreads load,
  answers every request, and merges ``/healthz`` / ``/metrics`` /
  ``/tracez`` / ``/programz`` with per-host labels;
* **host death** — the ``host.kill`` fault point takes a whole host
  down mid-load: every client still gets an answer (re-routed with its
  ORIGINAL absolute deadline), the monitor restarts the host through
  the shared RetryPolicy, and the cross-host counter invariant
  ``Σ served + shed + errors == Σ requests`` stays exact over every
  replica of every host, live and retired;
* **host stall** — a wedged-alive host (``host.stall``) is caught only
  by the heartbeat-age detector, killed, and its parked requests
  re-routed onto survivors;
* **quarantine** — a host out of restart budget is quarantined and a
  request the fleet cannot place resolves a machine-readable refusal
  naming the quarantined hosts;
* **subprocess chaos** — a fresh interpreter SIGKILLs a host mid-load
  (every replica dead, nothing resolves) and from the outside we assert
  zero client hangs + the exact invariant (``@pytest.mark.slow``).
"""

import json
import subprocess
import sys
import threading
import time
from pathlib import Path

import pytest

from memvul_tpu import telemetry
from memvul_tpu.resilience import faults
from memvul_tpu.resilience.retry import RetryPolicy
from memvul_tpu.serving import (
    STATUS_OK,
    FleetConfig,
    HostBalancer,
    HostDead,
    LocalHost,
    ProcessHost,
    Replica,
    ReplicaRouter,
    RouterConfig,
    ScoringService,
    ServiceConfig,
    enumerate_hosts,
    fleet_snapshot,
)
from memvul_tpu.serving.fleet import (
    HOST_DEAD,
    HOST_HEALTHY,
    HOST_QUARANTINED,
)

from test_serving_router import _FakePredictor


@pytest.fixture(autouse=True)
def _clean_faults():
    yield
    faults.reset()
    telemetry.reset()


def _router_factory(n_replicas=1):
    """A factory building a fresh fake-predictor router — the per-host
    target, re-invoked on restart."""

    def build():
        def make_factory(i):
            def factory(registry):
                return ScoringService(
                    _FakePredictor(),
                    config=ServiceConfig(
                        max_batch=4, max_wait_ms=1.0, max_queue=1000,
                        default_deadline_ms=30000.0,
                    ),
                    registry=registry,
                )
            return factory

        replicas = [
            Replica(i, make_factory(i), telemetry_enabled=True)
            for i in range(n_replicas)
        ]
        return ReplicaRouter(
            replicas,
            config=RouterConfig(monitor_interval_s=3600.0),
        )

    return build


def local_fleet(n_hosts=2, n_replicas=1, registry=None, **config_kw):
    config_kw.setdefault("monitor_interval_s", 0.05)
    config_kw.setdefault("heartbeat_timeout_s", 60.0)
    hosts = [
        LocalHost(i, _router_factory(n_replicas)) for i in range(n_hosts)
    ]
    balancer = HostBalancer(
        hosts,
        config=FleetConfig(**config_kw),
        registry=registry,
        retry_policy=RetryPolicy(attempts=2, backoff=0.01),
    )
    return balancer, hosts


def assert_cross_host_invariant(balancer):
    """The cross-host leak detector: served + shed + errors == requests
    summed over every replica of every host, live and retired."""
    snap = fleet_snapshot(balancer.members())
    assert snap["invariant_ok"], snap
    return snap


# -- enumeration ---------------------------------------------------------------

def test_enumerate_hosts_spec_env_and_urls(monkeypatch):
    assert enumerate_hosts("a,b:9000,http://c:8080/") == [
        "http://a:8341", "http://b:9000", "http://c:8080",
    ]
    assert enumerate_hosts("a", default_port=9) == ["http://a:9"]
    monkeypatch.setenv("MEMVUL_FLEET_HOSTS", "x:1, y:2")
    assert enumerate_hosts() == ["http://x:1", "http://y:2"]
    # an explicit spec beats the env
    assert enumerate_hosts("z:3") == ["http://z:3"]
    monkeypatch.delenv("MEMVUL_FLEET_HOSTS")
    assert enumerate_hosts() == []
    # pod-derived: {i}-template × multihost process count, but ONLY once
    # the multihost runtime has actually been joined
    from memvul_tpu.parallel import multihost

    monkeypatch.setenv("MEMVUL_FLEET_HOST_TEMPLATE", "serve-{i}.svc:8343")
    assert enumerate_hosts() == []  # runtime not initialized -> no probe
    monkeypatch.setattr(multihost, "_initialized", True)
    monkeypatch.setattr(multihost, "process_count", lambda: 3)
    assert enumerate_hosts() == [
        "http://serve-0.svc:8343",
        "http://serve-1.svc:8343",
        "http://serve-2.svc:8343",
    ]
    # the explicit env list still wins over the template
    monkeypatch.setenv("MEMVUL_FLEET_HOSTS", "x:1")
    assert enumerate_hosts() == ["http://x:1"]


# -- routing + merged endpoints ------------------------------------------------

def test_balancer_routes_and_stamps_host():
    balancer, hosts = local_fleet(n_hosts=2)
    try:
        responses = [
            balancer.submit(f"r {i}").result(timeout=15) for i in range(16)
        ]
        assert all(r["status"] == STATUS_OK for r in responses)
        by_host = {r["host"] for r in responses}
        assert by_host == {"host-0", "host-1"}  # the load spread
        snap = assert_cross_host_invariant(balancer)
        assert snap["served_total"] == 16
    finally:
        balancer.drain()


def test_balancer_merged_healthz_metrics_traces_programs():
    registry = telemetry.configure(enabled=True)
    try:
        balancer, hosts = local_fleet(n_hosts=2, registry=registry)
        for i in range(8):
            assert balancer.submit(f"r {i}").result(timeout=15)[
                "status"
            ] == STATUS_OK
        health = balancer.health_summary()
        assert health["status"] == "ok"
        assert health["hosts"]["total"] == 2
        assert health["hosts"]["alive"] == 2
        assert health["hosts"]["quarantined"] == []
        rows = {m["host"]: m for m in health["hosts"]["members"]}
        assert set(rows) == {"host-0", "host-1"}
        assert all("heartbeat_age_s" in m for m in rows.values())
        assert all(m["target"]["status"] == "ok" for m in rows.values())
        # /metrics: the balancer's own part plus host-labeled parts
        parts = balancer.metrics_snapshots()
        labels = [dict(lbl) for lbl, _ in parts]
        assert {} in labels  # the fleet.* part, unlabeled
        assert {"host": "host-0"} in [
            {k: v for k, v in lbl.items() if k == "host"} for lbl in labels
        ]
        own = parts[0][1]["counters"]
        assert own.get("fleet.requests") == 8
        assert own.get("fleet.served") == 8
        # /tracez + /programz merge across hosts without error
        assert isinstance(balancer.recent_traces(limit=4), list)
        programs = balancer.programs_snapshot()
        assert all(row["host"] in {"host-0", "host-1"} for row in programs)
        balancer.drain()
    finally:
        telemetry.reset()


def test_balancer_drain_sheds_and_resolves():
    balancer, _ = local_fleet(n_hosts=2)
    balancer.drain()
    response = balancer.submit("late").result(timeout=5)
    assert response["status"] == "drain"


# -- host death: kill fault, re-route, restart ---------------------------------

@pytest.mark.chaos
def test_host_kill_fault_reroutes_restarts_and_invariant_holds():
    """The host.kill fault point takes host-0 down at submit: the
    client's request re-routes to host-1 (original deadline), the
    monitor restarts host-0 through the RetryPolicy, and the cross-host
    invariant stays exact."""
    registry = telemetry.configure(enabled=True)
    try:
        balancer, hosts = local_fleet(n_hosts=2, registry=registry)
        warm = [
            balancer.submit(f"warm {i}").result(timeout=15) for i in range(8)
        ]
        assert all(r["status"] == STATUS_OK for r in warm)
        faults.configure("host.kill.host-0=raise:RuntimeError:chaos kill")
        responses = [
            balancer.submit(f"post-kill {i}", deadline_ms=20000.0).result(
                timeout=30
            )
            for i in range(24)
        ]
        assert all(r["status"] == STATUS_OK for r in responses), responses
        rerouted = [r for r in responses if r.get("host_reroutes")]
        assert rerouted, "the kill never forced a re-route"
        assert all(r["host"] == "host-1" for r in rerouted)
        # the monitor buys host-0 back
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and hosts[0].restart_count == 0:
            time.sleep(0.02)
        assert hosts[0].restart_count == 1
        assert hosts[0].state == HOST_HEALTHY
        counters = registry.snapshot()["counters"]
        assert counters.get("fleet.host_deaths") == 1
        assert counters.get("fleet.host_restarts") == 1
        assert counters.get("fleet.reroutes", 0) >= len(rerouted)
        # the restarted host serves again
        deadline = time.monotonic() + 10
        served_after = None
        while time.monotonic() < deadline:
            response = balancer.submit("after restart").result(timeout=15)
            assert response["status"] == STATUS_OK
            if response["host"] == "host-0":
                served_after = response
                break
        assert served_after is not None, "restarted host never served"
        balancer.drain()
        assert_cross_host_invariant(balancer)
    finally:
        telemetry.reset()


@pytest.mark.chaos
def test_host_stall_caught_by_heartbeat_age_and_rerouted():
    """A stalled host stays alive and accepting but makes no progress —
    only the heartbeat-age detector can catch it.  Its parked request
    re-routes onto the survivor with the original absolute deadline."""
    registry = telemetry.configure(enabled=True)
    try:
        balancer, hosts = local_fleet(
            n_hosts=2, registry=registry,
            heartbeat_timeout_s=0.2, monitor_interval_s=0.05,
        )
        warm = [
            balancer.submit(f"warm {i}").result(timeout=15) for i in range(8)
        ]
        assert all(r["status"] == STATUS_OK for r in warm)
        faults.configure("host.stall.host-0=raise:RuntimeError:wedge")
        # drive until one submission lands on (and stalls) host-0
        futures = [
            balancer.submit(f"stall {i}", deadline_ms=20000.0)
            for i in range(8)
        ]
        assert hosts[0]._stalled_at is not None
        # every future resolves — the stalled host's parked work is
        # reclaimed and re-routed, nothing hangs
        responses = [f.result(timeout=30) for f in futures]
        assert all(r["status"] == STATUS_OK for r in responses), responses
        assert {r["host"] for r in responses} <= {"host-0", "host-1"}
        rerouted = [r for r in responses if r.get("host_reroutes")]
        assert rerouted, "the stall never forced a re-route"
        counters = registry.snapshot()["counters"]
        assert counters.get("fleet.host_deaths") == 1
        balancer.drain()
        assert_cross_host_invariant(balancer)
    finally:
        telemetry.reset()


def test_quarantine_refusal_is_machine_readable():
    """A host out of restart budget is quarantined; a request the fleet
    cannot place resolves the PR 13-style refusal payload naming it."""
    registry = telemetry.configure(enabled=True)
    try:
        balancer, hosts = local_fleet(
            n_hosts=1, registry=registry,
            auto_restart=False, monitor_interval_s=0.05,
        )
        assert balancer.submit("warm").result(timeout=15)[
            "status"
        ] == STATUS_OK
        hosts[0].kill(reason="test")
        deadline = time.monotonic() + 10
        while (
            time.monotonic() < deadline
            and hosts[0].state != HOST_QUARANTINED
        ):
            time.sleep(0.02)
        assert hosts[0].state == HOST_QUARANTINED
        response = balancer.submit("nobody home").result(timeout=5)
        assert response["status"] == "error"
        refusal = response["refusal"]
        assert refusal["error"] == "fleet_unavailable"
        assert refusal["hosts_alive"] == 0
        assert refusal["hosts_total"] == 1
        assert refusal["quarantined"] == ["host-0"]
        health = balancer.health_summary()
        assert health["status"] == "unavailable"
        assert health["hosts"]["quarantined"] == ["host-0"]
        assert registry.snapshot()["counters"].get("fleet.quarantined") == 1
        balancer.drain()
    finally:
        telemetry.reset()


def test_dead_host_submit_raises_hostdead_directly():
    balancer, hosts = local_fleet(n_hosts=2, auto_restart=False)
    try:
        hosts[0].kill(reason="test")
        with pytest.raises(HostDead):
            hosts[0].submit("direct")
        assert hosts[0].state == HOST_DEAD
        # through the balancer the dead host is simply never picked
        response = balancer.submit("routed").result(timeout=15)
        assert response["status"] == STATUS_OK
        assert response["host"] == "host-1"
    finally:
        balancer.drain()


# -- ProcessHost (fast: no real subprocess) ------------------------------------

def test_process_host_attach_mode_and_unreachable_reroute():
    """A url-attached ProcessHost whose endpoint is unreachable resolves
    host_unreachable — and a balancer over it re-routes onto the live
    LocalHost instead of failing the client."""
    with pytest.raises(ValueError, match="exactly one"):
        ProcessHost(0)
    dead = ProcessHost(0, url="http://127.0.0.1:9/")  # discard port: refused
    assert dead.base_url == "http://127.0.0.1:9"
    response = dead.submit("hello").result(timeout=30)
    assert response["status"] == "error"
    assert response["reason"].startswith("host_unreachable")
    with pytest.raises(HostDead, match="attach-only"):
        dead.restart()
    # balancer: the unreachable host's error re-routes to the survivor
    live = LocalHost(1, _router_factory(1))
    balancer = HostBalancer(
        [ProcessHost(0, url="http://127.0.0.1:9"), live],
        config=FleetConfig(monitor_interval_s=3600.0, max_reroutes=2),
    )
    try:
        responses = [
            balancer.submit(f"r {i}", deadline_ms=20000.0).result(timeout=30)
            for i in range(8)
        ]
        assert all(r["status"] == STATUS_OK for r in responses), responses
        assert all(r["host"] == "host-1" for r in responses)
    finally:
        balancer.drain()


# -- subprocess chaos: whole-host SIGKILL semantics mid-load -------------------

_CHAOS_DRIVER = """
import json, threading, time

import sys
sys.path.insert(0, {test_dir!r})
from test_serving_fleet import local_fleet, assert_cross_host_invariant

from memvul_tpu.resilience import faults
from memvul_tpu.serving import fleet_snapshot

balancer, hosts = local_fleet(n_hosts=2, n_replicas=2, max_reroutes=3)
for i in range(8):
    assert balancer.submit(f"warm {{i}}").result(timeout=30)["status"] == "ok"
faults.configure("host.kill.host-1=raise:RuntimeError:SIGKILL chaos")

DEADLINE_MS = 15000.0
overdue = []
statuses = {{}}
lock = threading.Lock()

def client(k):
    for i in range(k, 96, 8):
        t0 = time.monotonic()
        response = balancer.submit(
            f"report {{i}}", deadline_ms=DEADLINE_MS
        ).result(timeout=DEADLINE_MS / 1000.0 + 30.0)
        waited = time.monotonic() - t0
        with lock:
            statuses[response["status"]] = statuses.get(response["status"], 0) + 1
            if waited > DEADLINE_MS / 1000.0 + 5.0:
                overdue.append(round(waited, 3))

threads = [threading.Thread(target=client, args=(k,)) for k in range(8)]
for t in threads: t.start()
for t in threads: t.join()
deadline = time.monotonic() + 20
while time.monotonic() < deadline and hosts[1].restart_count == 0:
    time.sleep(0.05)
restarts = hosts[1].restart_count
balancer.drain()
snapshot = fleet_snapshot(balancer.members())
print(json.dumps({{
    "statuses": statuses,
    "overdue": overdue,
    "invariant_ok": snapshot["invariant_ok"],
    "restarts": restarts,
    "host1_state": hosts[1].state,
    "replicas": snapshot["replicas"],
}}))
"""


@pytest.mark.slow
@pytest.mark.chaos
def test_subprocess_host_sigkill_mid_load_invariant_and_no_hang(tmp_path):
    """Acceptance gate: a fresh interpreter runs a 2-host fleet, the
    host.kill fault point SIGKILLs host-1 mid-load (every replica dead,
    unresolved work swept to errors), and from the outside we assert
    zero client hangs, re-routes under the ORIGINAL deadlines, and the
    exact cross-host invariant."""
    driver = tmp_path / "fleet_chaos_driver.py"
    driver.write_text(_CHAOS_DRIVER.format(
        test_dir=str(Path(__file__).resolve().parent)
    ))
    proc = subprocess.run(
        [sys.executable, str(driver)],
        capture_output=True, text=True, timeout=300,
        env={
            **__import__("os").environ,
            "JAX_PLATFORMS": "cpu",
            "PYTHONPATH": __import__("os").pathsep.join(sys.path),
        },
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    # every client resolved, none past its deadline window
    assert record["overdue"] == []
    assert sum(record["statuses"].values()) == 96
    assert record["statuses"].get("ok", 0) > 0
    # the host died and came back (or was quarantined if restarts failed)
    assert record["restarts"] >= 1 or record["host1_state"] == "quarantined"
    # the cross-host exact-counter invariant survived whole-host death
    assert record["invariant_ok"], record["replicas"]
    for member in record["replicas"]:
        assert (
            member["served"] + member["shed"] + member["errors"]
            == member["requests"]
        ), member
