"""Autotuner unit coverage: profile lifecycle, knob space, analytic
pruning, the mandatory parity gate, and the report renderer.

Everything here is deliberately jax-light (the profile store, parity
checks, prune math and markdown rendering are pure Python) so the whole
file rides tier-1; the end-to-end sweep is exercised by the
``BENCH_MICRO=tune`` harness leg instead (docs/tuning.md).
"""

import json
import logging

import numpy as np
import pytest

from memvul_tpu.tuning.knobs import Candidate, serve_space, train_space
from memvul_tpu.tuning.parity import (
    LOSS_TOL,
    check_serve_parity,
    check_train_parity,
)
from memvul_tpu.tuning.profile import (
    PROFILE_SCHEMA,
    apply_tuned_serving,
    apply_tuned_trainer,
    load_profile,
    normalize_device_class,
    profile_root,
    resolve_device_class,
    save_profile,
)
from memvul_tpu.tuning.prune import (
    estimate_train_programs,
    measured_hbm_baseline,
    prune_candidates,
    survivors,
)
from memvul_tpu.tuning.report import (
    BEGIN_MARK,
    END_MARK,
    roofline_markdown,
    splice_generated_section,
)

PROFILE_LOGGER = "memvul_tpu.tuning.profile"


# ---------------------------------------------------------------------------
# profile lifecycle
# ---------------------------------------------------------------------------


def test_profile_round_trip_with_sha256_manifest(tmp_path):
    """save → load round-trip; the manifest carries the sha256 of the
    exact document text and load verifies it."""
    import hashlib

    profile = {"train": {"train_buckets": "pow2", "prefetch_depth": 8},
               "serving": {"max_batch": 8}}
    doc_path = save_profile(tmp_path, "TPU v5 lite", profile)
    assert doc_path.name == "profile-0001.json"
    assert doc_path.parent.name == "tpu_v5_lite"

    manifest = json.loads((doc_path.parent / "MANIFEST.json").read_text())
    assert manifest["active"] == "profile-0001.json"
    assert manifest["version"] == 1
    assert manifest["schema"] == PROFILE_SCHEMA
    text = doc_path.read_text()
    assert manifest["sha256"] == hashlib.sha256(
        text.encode("utf-8")).hexdigest()

    loaded = load_profile(tmp_path, "TPU v5 lite")
    assert loaded is not None
    assert loaded["train"] == profile["train"]
    assert loaded["serving"] == profile["serving"]
    assert loaded["schema"] == PROFILE_SCHEMA
    assert loaded["device_class"] == "tpu_v5_lite"
    assert loaded["version"] == 1


def test_profile_versions_advance_and_manifest_points_at_latest(tmp_path):
    save_profile(tmp_path, "cpu", {"train": {"prefetch_depth": 2}})
    p2 = save_profile(tmp_path, "cpu", {"train": {"prefetch_depth": 16}})
    assert p2.name == "profile-0002.json"
    # both documents remain on disk (immutable history), manifest points
    # at the latest
    assert (p2.parent / "profile-0001.json").is_file()
    loaded = load_profile(tmp_path, "cpu")
    assert loaded["version"] == 2
    assert loaded["train"]["prefetch_depth"] == 16


def test_save_recovers_from_torn_manifest(tmp_path):
    """A garbage MANIFEST.json must not wedge the writer: the next save
    restarts numbering above the highest on-disk document."""
    save_profile(tmp_path, "cpu", {"train": {}})
    save_profile(tmp_path, "cpu", {"train": {}})
    (tmp_path / "cpu" / "MANIFEST.json").write_text("{torn")
    p3 = save_profile(tmp_path, "cpu", {"train": {"prefetch_depth": 4}})
    assert p3.name == "profile-0003.json"
    assert load_profile(tmp_path, "cpu")["version"] == 3


def test_corrupted_profile_falls_back_with_one_warning(tmp_path, caplog):
    """Checksum mismatch → defaults (None) with exactly ONE warning for
    the path, no matter how many replicas load through it."""
    doc_path = save_profile(tmp_path, "cpu", {"train": {"prefetch_depth": 2}})
    doc_path.write_text(doc_path.read_text().replace("2", "9"))
    with caplog.at_level(logging.WARNING, logger=PROFILE_LOGGER):
        assert load_profile(tmp_path, "cpu") is None
        assert load_profile(tmp_path, "cpu") is None  # second replica
    warnings = [r for r in caplog.records
                if "sha256 mismatch" in r.getMessage()]
    assert len(warnings) == 1
    assert "falling back to defaults" in warnings[0].getMessage()


def test_stale_schema_profile_falls_back_with_warning(tmp_path, caplog):
    from memvul_tpu.resilience.io import atomic_write_text

    doc_path = save_profile(tmp_path, "v6e", {"train": {}})
    document = json.loads(doc_path.read_text())
    document["schema"] = PROFILE_SCHEMA + 1
    text = json.dumps(document, indent=2, sort_keys=True)
    atomic_write_text(doc_path, text)
    # keep the checksum valid so the failure is attributed to the
    # schema, not the sha
    import hashlib
    manifest_path = doc_path.parent / "MANIFEST.json"
    manifest = json.loads(manifest_path.read_text())
    manifest["sha256"] = hashlib.sha256(text.encode("utf-8")).hexdigest()
    atomic_write_text(manifest_path, json.dumps(manifest))
    with caplog.at_level(logging.WARNING, logger=PROFILE_LOGGER):
        assert load_profile(tmp_path, "v6e") is None
    assert any("stale schema" in r.getMessage() for r in caplog.records)


def test_untuned_class_and_no_root_are_silent(tmp_path, caplog):
    """No manifest for a class (or no root configured at all) is the
    normal zero-config state — None without any warning."""
    with caplog.at_level(logging.WARNING, logger=PROFILE_LOGGER):
        assert load_profile(None, "cpu") is None
        assert load_profile(tmp_path, "never_tuned") is None
    assert not caplog.records


def test_profile_root_precedence(tmp_path, monkeypatch):
    monkeypatch.delenv("MEMVUL_TUNED_PROFILES", raising=False)
    assert profile_root(None) is None
    monkeypatch.setenv("MEMVUL_TUNED_PROFILES", str(tmp_path / "env"))
    assert profile_root(None) == tmp_path / "env"
    # explicit config wins over the env
    assert profile_root(tmp_path / "cfg") == tmp_path / "cfg"


def test_normalize_and_resolve_device_class():
    assert normalize_device_class("TPU v5 lite") == "tpu_v5_lite"
    assert normalize_device_class("TPU v5p") == "tpu_v5p"
    assert normalize_device_class("") == "unknown"
    cls, peak = resolve_device_class("TPU v5 lite")
    assert cls == "tpu_v5_lite"
    assert peak is not None and peak["hbm_bytes"] == 16e9
    cls, peak = resolve_device_class("grace-hopper")
    assert cls == "grace_hopper"
    assert peak is None


# ---------------------------------------------------------------------------
# explicit-config-wins precedence
# ---------------------------------------------------------------------------


def _tuned_config(tmp_path, device_class="cpu"):
    return {"tuning": {"profile_dir": str(tmp_path),
                       "device_class": device_class}}


def test_apply_tuned_trainer_fills_only_absent_keys(tmp_path):
    save_profile(tmp_path, "cpu", {"train": {
        "train_buckets": "pow2", "dedup_anchors": True, "prefetch_depth": 16,
        "not_a_trainer_knob": 1,  # must not be smuggled through
    }})
    config = _tuned_config(tmp_path)
    trainer_cfg = {"train_buckets": None, "batch_size": 4}
    out = apply_tuned_trainer(trainer_cfg, config)
    # the user's explicit pad-to-max survives untouched
    assert out["train_buckets"] is None
    # absent knobs take the tuned values; unknown keys are dropped
    assert out["dedup_anchors"] is True
    assert out["prefetch_depth"] == 16
    assert "not_a_trainer_knob" not in out
    assert out["batch_size"] == 4


def test_apply_tuned_trainer_no_profile_is_identity(tmp_path):
    config = _tuned_config(tmp_path / "empty")
    trainer_cfg = {"batch_size": 4}
    assert apply_tuned_trainer(dict(trainer_cfg), config) == trainer_cfg


def test_apply_tuned_trainer_respects_enabled_false(tmp_path):
    save_profile(tmp_path, "cpu", {"train": {"prefetch_depth": 16}})
    config = _tuned_config(tmp_path)
    config["tuning"]["enabled"] = False
    assert apply_tuned_trainer({}, config) == {}


def test_apply_tuned_serving_explicit_non_null_key_wins(tmp_path):
    save_profile(tmp_path, "cpu", {"serving": {
        "score_impl": "ragged", "max_batch": 4, "token_budget": 2048,
    }})
    config = _tuned_config(tmp_path)
    # serve_cfg is the defaults-merged view; explicitness is judged on
    # the RAW archive section — a null there means "defaulted", not
    # "user chose null"
    explicit_section = {"max_batch": 32, "score_impl": None}
    serve_cfg = {"score_impl": "bucketed", "max_batch": 32,
                 "token_budget": None}
    out = apply_tuned_serving(serve_cfg, explicit_section, config)
    assert out["max_batch"] == 32        # explicitly written → wins
    assert out["score_impl"] == "ragged"  # null in section → tuned fills
    assert out["token_budget"] == 2048


# ---------------------------------------------------------------------------
# mandatory parity gate
# ---------------------------------------------------------------------------

CAND = Candidate(kind="serve", name="serve:test", knobs={})
TRAIN_CAND = Candidate(kind="train", name="train:test", knobs={})


def test_serve_parity_requires_bitwise_equality():
    scores = np.array([[0.25, 0.75], [0.9, 0.1]], dtype=np.float32)
    ok = check_serve_parity(CAND, scores, scores.copy())
    assert ok.passed and ok.max_abs_delta == 0.0

    drifted = scores.copy()
    drifted[0, 0] += np.float32(1e-7)  # "close enough" is not parity
    bad = check_serve_parity(CAND, scores, drifted)
    assert not bad.passed
    assert bad.reasons[0]["code"] == "parity_score_mismatch"
    assert bad.reasons[0]["limit"] == 0.0
    assert bad.max_abs_delta == pytest.approx(1e-7, rel=0.5)


def test_serve_parity_shape_mismatch_refuses():
    v = check_serve_parity(CAND, np.zeros((4, 2)), np.zeros((3, 2)))
    assert not v.passed
    assert v.reasons[0]["code"] == "parity_score_mismatch"


def test_train_parity_tolerance_and_refusals():
    base = [2.0, 1.5, 1.2, 1.0]
    within = [x + LOSS_TOL / 2 for x in base]
    assert check_train_parity(TRAIN_CAND, base, within).passed

    diverged = list(base)
    diverged[-1] += 10 * LOSS_TOL
    v = check_train_parity(TRAIN_CAND, base, diverged)
    assert not v.passed
    assert v.reasons[0]["code"] == "parity_loss_divergence"
    assert v.reasons[0]["limit"] == LOSS_TOL

    v = check_train_parity(TRAIN_CAND, base, base[:-1])
    assert not v.passed and v.reasons[0]["code"] == "parity_step_count"

    v = check_train_parity(TRAIN_CAND, [], [])
    assert not v.passed and v.reasons[0]["code"] == "parity_no_evidence"


def test_parity_verdict_serializes():
    v = check_serve_parity(CAND, np.ones(3), np.zeros(3))
    payload = json.loads(json.dumps(v.to_json()))
    assert payload["candidate"]["name"] == "serve:test"
    assert payload["passed"] is False


# ---------------------------------------------------------------------------
# knob space
# ---------------------------------------------------------------------------


def test_train_space_shape_and_dedup_noop_collapse():
    cands = train_space(max_length=512, batch_size=32)
    names = [c.name for c in cands]
    assert len(names) == len(set(names))
    # pad-to-max (None) emits one row per prefetch depth — dedup is a
    # no-op without buckets, so no dedup=1 variant exists there
    padmax = [c for c in cands if c.knobs["train_buckets"] is None]
    assert len(padmax) == 3
    assert all(c.knobs["dedup_anchors"] is False for c in padmax)
    # 3 grids × (dedup axis collapses for None) × 3 depths
    assert len(cands) == 15


def test_serve_space_shape_and_unknown_impl():
    cands = serve_space(max_length=512, max_batch=16)
    assert len(cands) == 18
    impls = {c.knobs["score_impl"] for c in cands}
    assert impls == {"bucketed", "ragged", "continuous"}
    packed = [c for c in cands if c.knobs["score_impl"] != "bucketed"]
    assert all("token_budget" in c.knobs and "max_rows_per_pack" in c.knobs
               for c in packed)
    # the cascade band is score-adjacent and never swept here
    assert all("cascade_low" not in c.knobs for c in cands)
    with pytest.raises(ValueError, match="unknown impl"):
        serve_space(impls=("bucketed", "flash"))


# ---------------------------------------------------------------------------
# analytic pruning
# ---------------------------------------------------------------------------


class _StubRegistry:
    """Quacks like ProgramRegistry.snapshot() for measured_hbm_baseline."""

    def __init__(self, rows):
        self._rows = rows

    def snapshot(self):
        return list(self._rows)


def test_estimate_train_programs():
    # pad-to-max is a single step signature
    assert estimate_train_programs(None, True, 32, 512) == 1
    # an explicit 4-boundary grid: 16 cells, dedup multiplies by the
    # capacity ladder
    from memvul_tpu.data.batching import dedup_capacities

    grid = [64, 128, 256, 512]
    assert estimate_train_programs(grid, False, 32, 512) == 16
    ladder = len(dedup_capacities(32))
    assert estimate_train_programs(grid, True, 32, 512) == 16 * ladder


def test_prune_refuses_program_count_blowup():
    cands = train_space(max_length=512, batch_size=32)
    decisions = prune_candidates(cands, batch_size=32, max_length=512,
                                 max_programs=4)
    refused = [d for d in decisions if not d.feasible]
    assert refused, "a dedup'd grid must blow a 4-program ceiling"
    for d in refused:
        assert d.reasons[0]["code"] == "program_count_blowup"
        assert d.reasons[0]["observed"] > 4
        assert d.reasons[0]["limit"] == 4
    # pad-to-max (1 program) always survives
    assert any(c.knobs.get("train_buckets") is None
               for c in survivors(decisions))


def test_prune_refuses_hbm_overflow_with_measured_evidence():
    # measured footprint 10 GB at the baseline serve shape
    # (max_batch=16 × 512 tokens); doubling the micro-batch cap (or a
    # 32×L token budget = 2× the baseline padded tokens) projects to
    # 20 GB > 90% of a 16 GB part
    registry = _StubRegistry([
        {"key": "serve_step", "hbm_bytes": 10e9},
        {"key": "tiny", "hbm_bytes": 1e9},
    ])
    assert measured_hbm_baseline(registry)["hbm_bytes"] == 10e9
    cands = serve_space(max_length=512, max_batch=16,
                        budget_factors=(2, 32), rows_factors=(1,))
    decisions = prune_candidates(
        cands, max_length=512, max_batch=16,
        peak={"hbm_bytes": 16e9}, registry=registry, hbm_fraction=0.9,
    )
    by_name = {d.candidate.name: d for d in decisions}
    big = by_name["serve:ragged,budget=32xL,rows=16"]
    assert not big.feasible
    assert big.reasons[0]["code"] == "hbm_overflow"
    assert big.estimated_hbm_bytes == pytest.approx(20e9)
    double_cap = by_name["serve:bucketed,max_batch=32,wait_ms=2"]
    assert not double_cap.feasible
    assert double_cap.reasons[0]["code"] == "hbm_overflow"
    # a 2×L budget is a quarter of the baseline footprint and survives,
    # as does the half-cap bucketed candidate
    assert by_name["serve:ragged,budget=2xL,rows=16"].feasible
    assert by_name["serve:bucketed,max_batch=8,wait_ms=2"].feasible


def test_prune_is_honest_when_it_cannot_measure():
    """No peak spec / no measured footprint → the HBM check records a
    note and skips — it never prunes against numbers that don't exist."""
    cands = serve_space(max_length=512, max_batch=16)
    no_peak = prune_candidates(cands, peak=None,
                               registry=_StubRegistry([]))
    assert all(d.feasible for d in no_peak)
    assert all("hbm_check_skipped:no_peak_spec" in d.notes for d in no_peak)

    no_measured = prune_candidates(cands, peak={"hbm_bytes": 16e9},
                                   registry=_StubRegistry([]))
    assert all(d.feasible for d in no_measured)
    assert all("hbm_check_skipped:no_measured_footprint" in d.notes
               for d in no_measured)
    # decisions serialize for the tune report
    json.dumps([d.to_json() for d in no_peak])


def test_unknown_device_refusal_is_machine_readable():
    from memvul_tpu.telemetry.programs import PEAK_SPECS
    from memvul_tpu.tuning.autotune import unknown_device_refusal

    refusal = unknown_device_refusal("grace_hopper")
    assert refusal["error"] == "unknown_device_class"
    assert refusal["device_class"] == "grace_hopper"
    assert refusal["known_markers"] == sorted(PEAK_SPECS)
    assert "allow-unknown-device" in refusal["hint"]
    json.dumps(refusal)


# ---------------------------------------------------------------------------
# cascade band math (gate-free slice; the gated path runs in the bench leg)
# ---------------------------------------------------------------------------


def test_choose_band_covers_nearest_fraction_and_threshold(monkeypatch):
    """The band must cover exactly the target fraction of rows nearest
    the decision threshold, widened to include the threshold itself."""
    import importlib

    # bankops re-exports a `promote` *function*, which shadows the
    # submodule on attribute import — go through importlib
    promote_mod = importlib.import_module("memvul_tpu.bankops.promote")
    from memvul_tpu.tuning.cascade import choose_band

    scores = np.array([0.05, 0.1, 0.2, 0.45, 0.48, 0.52, 0.8, 0.9, 0.95,
                       0.99])

    class _FakePredictor:
        cascade_band = (0.3, 0.7)

        def score_texts(self, texts, impl=None):
            # one anchor column — choose_band's max(axis=-1) then sees
            # exactly these scores
            assert impl == "int8"
            return scores[:, None]

    class _FakeDecision:
        approved = True

        def to_json(self):
            return {"approved": True}

    instances = [{"text1": f"t{i}", "label": i % 2} for i in range(10)]
    predictor = _FakePredictor()
    calls = {}

    def fake_evaluate(pred, insts, thresholds=None, threshold=0.5):
        calls["band_during_gate"] = tuple(pred.cascade_band)
        return _FakeDecision()

    monkeypatch.setattr(promote_mod, "evaluate_cascade", fake_evaluate)
    record = choose_band(predictor, instances, target_rescore_rate=0.3)

    # 3 nearest-to-0.5 rows are 0.45, 0.48, 0.52 → band [0.45, 0.52]
    assert record["cascade_low"] == pytest.approx(0.45)
    assert record["cascade_high"] == pytest.approx(0.52)
    assert record["predicted_rescore_rate"] == pytest.approx(0.3)
    assert record["approved"] is True
    # the gate saw the candidate band; the tuner restored the prior one
    assert calls["band_during_gate"] == (0.45, 0.52)
    assert predictor.cascade_band == (0.3, 0.7)


def test_choose_band_rejects_bad_inputs():
    from memvul_tpu.tuning.cascade import choose_band

    with pytest.raises(ValueError, match="non-empty"):
        choose_band(object(), [])
    with pytest.raises(ValueError, match="target_rescore_rate"):
        choose_band(object(), [{"text1": "x"}], target_rescore_rate=0.0)


# ---------------------------------------------------------------------------
# report renderer
# ---------------------------------------------------------------------------

SNAPSHOT = [
    {"key": "train_step/b128", "invocations": 10, "flops": 2.5e12,
     "bytes_accessed": 3.2e9, "hbm_bytes": 1.1e9, "device_time_s": 1.25,
     "mfu": 0.31},
    {"key": "encode/b64", "invocations": 4, "flops": 1.0e9,
     "bytes_accessed": 2.0e6, "hbm_bytes": None, "device_time_s": 0.01,
     "mfu": None},
]
ROOFLINE = {
    "device_kind": "TPU v5 lite", "interpret_only": False,
    "peak_flops_per_s": 197e12, "peak_bytes_per_s": 819e9,
    "programs": 2, "flops_total": 2.5e12, "bytes_total": 3.2e9,
    "device_time_s": 1.26, "achieved_flops_per_s": 1.98e12,
    "achieved_bytes_per_s": 2.5e9, "mfu": 0.31, "membw_util": 0.003,
}


def test_roofline_markdown_renders_measured_rows():
    md = roofline_markdown(SNAPSHOT, ROOFLINE)
    assert md.startswith(BEGIN_MARK)
    assert md.rstrip().endswith(END_MARK)
    assert "`train_step/b128`" in md
    assert "2.50 TFLOP/s" not in md  # peaks, not per-program, carry units
    assert "197.00 TFLOP/s" in md
    assert "31.0%" in md
    # unmeasured cells render as em-dash, never as a fake zero
    assert "| — |" in md


def test_roofline_markdown_interpret_only_keeps_mfu_null():
    md = roofline_markdown(
        [{"key": "k", "invocations": 1, "flops": 1e9,
          "bytes_accessed": 1e6, "device_time_s": 0.0, "mfu": None}],
        {"device_kind": "cpu", "interpret_only": True},
    )
    assert "interpret-only" in md
    assert "made-up peak" in md


def test_splice_generated_section_replaces_and_appends():
    generated = roofline_markdown(SNAPSHOT, ROOFLINE)
    doc = f"# Roofline\n\nprose above\n\n{BEGIN_MARK}\nOLD\n{END_MARK}\n\nprose below\n"
    out = splice_generated_section(doc, generated)
    assert "OLD" not in out
    assert "prose above" in out and "prose below" in out
    assert out.count(BEGIN_MARK) == 1 and out.count(END_MARK) == 1

    plain = "# Doc with no fence\n"
    appended = splice_generated_section(plain, generated)
    assert appended.startswith(plain)
    assert BEGIN_MARK in appended
