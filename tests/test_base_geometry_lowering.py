"""Base/large-geometry lowering on the virtual 8-device mesh.

The multichip dryrun proves sharded semantics at tiny geometry only
(64-hidden, 2-layer) — shapes there can hide TP-divisibility and layout
mistakes that bite at real scale (round-4 verdict stretch #7).  These
tests jit-lower AND compile (SPMD partition — no execution, no weight
materialization: params are ``ShapeDtypeStruct``s) the fused dp×tp
train step and the anchor-bank scoring program at bert-base and
bert-large geometry, so e.g. 16 heads / tp=2 at bert-large is checked by
the partitioner itself, not just by ``validate_divisibility`` unit
arithmetic, and the dp/tp collectives are asserted present in the
compiled HLO.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.models.memory import anchor_probs
from memvul_tpu.parallel import create_mesh
from memvul_tpu.parallel.sharding import param_specs, validate_divisibility
from memvul_tpu.training.optim import make_optimizer
from memvul_tpu.training.trainer import make_train_step

pytestmark = pytest.mark.slow

DP, TP = 4, 2
SEQ = 256  # the workload length (reference config_memory.json max_length)


def _geometry(name: str) -> BertConfig:
    make = getattr(BertConfig, name)
    return make(dtype=jnp.bfloat16, scan_layers=True)


def _abstract_params(model):
    dummy = {
        "input_ids": jax.ShapeDtypeStruct((2, 8), np.int32),
        "attention_mask": jax.ShapeDtypeStruct((2, 8), np.int32),
    }
    return jax.eval_shape(model.init, jax.random.PRNGKey(0), dummy, dummy)


def _with_shardings(abstract, mesh, specs):
    return jax.tree_util.tree_map(
        lambda a, s: jax.ShapeDtypeStruct(
            a.shape, a.dtype, sharding=NamedSharding(mesh, s)
        ),
        abstract,
        specs,
    )


def _concrete_skeleton(abstract):
    """Minimal concrete tree with the same paths, for optimizer-group
    label derivation only (shapes are irrelevant to the labels)."""
    return jax.tree_util.tree_map(
        lambda a: np.zeros((1,) * a.ndim, np.float32), abstract
    )


@pytest.mark.parametrize("geometry", ["base", "large"])
def test_dp_tp_train_step_lowers_at_real_geometry(geometry):
    mesh = create_mesh({"data": DP, "model": TP})
    cfg = _geometry(geometry)
    model = MemoryModel(cfg)
    abstract = _abstract_params(model)

    bad = validate_divisibility(abstract, mesh)
    assert not bad, f"indivisible TP dims at bert-{geometry}: {bad}"

    specs = param_specs(abstract)
    params_abs = _with_shardings(abstract, mesh, specs)

    tx, _ = make_optimizer(_concrete_skeleton(abstract), warmup_steps=2)
    opt_abs = jax.eval_shape(tx.init, abstract)

    K, B = 2, 4 * DP
    batch_spec = P(None, "data", None)
    row_spec = P(None, "data")
    stack = {
        "sample1": {
            "input_ids": jax.ShapeDtypeStruct(
                (K, B, SEQ), np.int32, sharding=NamedSharding(mesh, batch_spec)
            ),
            "attention_mask": jax.ShapeDtypeStruct(
                (K, B, SEQ), np.int32, sharding=NamedSharding(mesh, batch_spec)
            ),
        },
        "sample2": {
            "input_ids": jax.ShapeDtypeStruct(
                (K, B, SEQ), np.int32, sharding=NamedSharding(mesh, batch_spec)
            ),
            "attention_mask": jax.ShapeDtypeStruct(
                (K, B, SEQ), np.int32, sharding=NamedSharding(mesh, batch_spec)
            ),
        },
        "label": jax.ShapeDtypeStruct(
            (K, B), np.int32, sharding=NamedSharding(mesh, row_spec)
        ),
        "weight": jax.ShapeDtypeStruct(
            (K, B), np.float32, sharding=NamedSharding(mesh, row_spec)
        ),
    }

    step = make_train_step(model, tx)
    # lower() already validates argument shardings (indivisible dims fail
    # here); compile() runs the SPMD partitioner and inserts collectives
    compiled = jax.jit(step).lower(
        params_abs, opt_abs, jax.random.PRNGKey(0), stack
    ).compile()
    hlo = compiled.as_text()
    # the dp gradient all-reduce and the tp partial-sum all-reduce must
    # both appear in the partitioned program
    assert "all-reduce" in hlo, (
        "no collective in the compiled dp×tp train step"
    )


@pytest.mark.parametrize("geometry", ["base", "large"])
def test_bucketed_scoring_program_lowers_at_real_geometry(geometry):
    """The eval-side program: model-axis-sharded anchor bank (CWE-1000
    path, evaluate/predict_memory.py:113-133) × data-sharded report
    batch, at workload shapes (512-row bucket, seq 256)."""
    mesh = create_mesh({"data": DP, "model": TP})
    cfg = _geometry(geometry)
    model = MemoryModel(cfg)
    abstract = _abstract_params(model)
    params_abs = _with_shardings(abstract, mesh, param_specs(abstract))

    B = 512
    A = 130  # 129 CWE anchors padded to model-axis divisibility
    header_dim = 512
    batch = {
        "input_ids": jax.ShapeDtypeStruct(
            (B, SEQ), np.int32, sharding=NamedSharding(mesh, P("data", None))
        ),
        "attention_mask": jax.ShapeDtypeStruct(
            (B, SEQ), np.int32, sharding=NamedSharding(mesh, P("data", None))
        ),
    }
    bank = jax.ShapeDtypeStruct(
        (A, header_dim),
        jnp.bfloat16,
        sharding=NamedSharding(mesh, P("model", None)),
    )

    def score(p, b, bank):
        return anchor_probs(
            model.apply(p, b, anchors=bank, deterministic=True)
        )

    compiled = jax.jit(score).lower(params_abs, batch, bank).compile()
    out_shape = jax.eval_shape(score, abstract, batch, bank)
    assert out_shape.shape == (B, A)
    hlo = compiled.as_text()
    assert "all-reduce" in hlo or "all-gather" in hlo
