"""Tests for the bench supervision harness (memvul_tpu/bench.py).

The round-2 driver capture died with a transient 'Unable to initialize
backend axon: UNAVAILABLE' at the first device op; the supervisor must
retry that class of failure, kill hung attempts by process group, and
emit exactly one JSON line on unrecoverable failure (never a traceback).
Most children are tiny shell-level scripts with no JAX; the
``_wait_for_device`` / wedged-backend tests spawn jax-importing probe
children (bounded budgets keep them fast either way).
"""

import json
import os
import subprocess
import sys

from memvul_tpu.bench import _extract_result_line, _supervise, _wait_for_device

RESULT = '{"metric": "siamese_scoring_throughput", "value": 1.0, "unit": "reports/sec", "vs_baseline": 1.0}'


def _script_cmd(body: str):
    return [sys.executable, "-c", body]


def test_extract_result_line_picks_last_json_dict():
    text = "warning noise\n{not json\n" + RESULT + "\ntrailing"
    line = _extract_result_line(text)
    assert json.loads(line)["metric"] == "siamese_scoring_throughput"
    assert _extract_result_line("no json here") is None
    # a JSON line without 'metric' is not a result
    assert _extract_result_line('{"foo": 1}') is None


def test_extract_result_line_skips_error_records():
    """The watchdog's phase-timeout record carries 'metric' (so drivers
    parsing the stream still recognize it) but must NOT be mistaken for
    a successful measurement; a real result before it still wins."""
    watchdog = json.dumps(
        {"metric": "siamese_scoring_throughput", "value": 0.0,
         "error": "watchdog: phase 'timed_pass' exceeded 600s",
         "watchdog_timeout": True}
    )
    assert _extract_result_line(watchdog) is None
    assert _extract_result_line(RESULT + "\n" + watchdog) == RESULT


def test_phase_watchdog_emits_record_and_exits_124():
    """A phase that stops making progress: the watchdog thread emits one
    parseable JSON failure record naming the phase and hard-exits 124 —
    even though the 'stuck op' (sleep) never returns.  Run in a child
    because the watchdog's os._exit would take pytest down with it."""
    body = (
        "import time\n"
        "from memvul_tpu.bench import _PhaseWatchdog\n"
        "wd = _PhaseWatchdog(0.3, 'siamese_scoring_throughput')\n"
        "with wd.phase('timed_pass'):\n"
        "    time.sleep(30)\n"
        "print('UNREACHABLE')\n"
    )
    proc = subprocess.run(
        _script_cmd(body), capture_output=True, text=True, timeout=25
    )
    assert proc.returncode == 124
    assert "UNREACHABLE" not in proc.stdout
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert record["watchdog_timeout"] is True
    assert "timed_pass" in record["error"]
    assert "watchdog" in proc.stderr


def test_phase_watchdog_quiet_when_phase_completes():
    wd_body = (
        "from memvul_tpu.bench import _PhaseWatchdog\n"
        "wd = _PhaseWatchdog(30, 'm')\n"
        "with wd.phase('fast'):\n"
        "    pass\n"
        "with wd.phase('disabled'):\n"  # timeout 0 disables entirely
        "    pass\n"
        f"print('{RESULT}')\n"
    )
    proc = subprocess.run(
        _script_cmd(wd_body), capture_output=True, text=True, timeout=60
    )
    assert proc.returncode == 0
    assert _extract_result_line(proc.stdout) == RESULT


def test_supervise_retries_watchdog_timeout():
    """A watchdog-killed attempt is the wedged-backend signature — the
    supervisor must treat it as transient and burn a retry on it, then
    surface the watchdog error once the budget is exhausted."""
    body = (
        "import sys\n"
        'print(\'{"metric": "siamese_scoring_throughput", "value": 0.0, '
        '"error": "watchdog: phase \\\'timed_pass\\\' exceeded 1s", '
        '"watchdog_timeout": true}\')\n'
        "sys.exit(124)\n"
    )
    line, err = _supervise(
        _script_cmd(body), attempts=2, attempt_timeout=30, backoff=0
    )
    assert line is None
    assert "watchdog" in err


def test_supervise_success_first_try():
    cmd = _script_cmd(f"print('{RESULT}')")
    line, err = _supervise(cmd, attempts=1, attempt_timeout=30, backoff=0)
    assert err is None
    assert json.loads(line)["vs_baseline"] == 1.0


def test_supervise_retries_unavailable_then_succeeds(tmp_path):
    flag = tmp_path / "failed_once"
    body = (
        "import os, sys\n"
        f"flag = {str(flag)!r}\n"
        "if not os.path.exists(flag):\n"
        "    open(flag, 'w').close()\n"
        "    sys.stderr.write('RuntimeError: Unable to initialize backend "
        "axon: UNAVAILABLE: TPU backend setup/compile error\\n')\n"
        "    sys.exit(1)\n"
        f"print('{RESULT}')\n"
    )
    line, err = _supervise(
        _script_cmd(body), attempts=3, attempt_timeout=30, backoff=0
    )
    assert err is None
    assert json.loads(line)["value"] == 1.0
    assert flag.exists()


def test_supervise_non_retryable_fails_fast(tmp_path):
    counter = tmp_path / "runs"
    body = (
        "import sys\n"
        f"open({str(counter)!r}, 'a').write('x')\n"
        "sys.stderr.write('ValueError: genuine bug\\n')\n"
        "sys.exit(1)\n"
    )
    line, err = _supervise(
        _script_cmd(body), attempts=3, attempt_timeout=30, backoff=0
    )
    assert line is None
    assert "genuine bug" in err
    # a non-transient failure must not burn the retry budget
    assert counter.read_text() == "x"


def test_supervise_kills_hung_attempt_and_reports_timeout():
    body = "import time\ntime.sleep(60)\n"
    line, err = _supervise(
        _script_cmd(body), attempts=2, attempt_timeout=1, backoff=0
    )
    assert line is None
    assert "timed out" in err


def test_result_printed_before_hang_is_harvested():
    """A child that prints the result and THEN hangs (e.g. a teardown
    hang in the axon tunnel) still counts as a successful measurement."""
    body = (
        f"print('{RESULT}', flush=True)\n"
        "import time\n"
        "time.sleep(120)\n"
    )
    line, err = _supervise(
        _script_cmd(body), attempts=1, attempt_timeout=15, backoff=0
    )
    assert err is None
    assert json.loads(line)["value"] == 1.0


def test_zero_exit_without_result_fails_fast(tmp_path):
    counter = tmp_path / "runs"
    body = f"open({str(counter)!r}, 'a').write('x')\nprint('no result here')\n"
    line, err = _supervise(
        _script_cmd(body), attempts=3, attempt_timeout=30, backoff=0
    )
    assert line is None
    assert "without a result line" in err
    assert counter.read_text() == "x"  # no retries burned


def test_error_extraction_skips_jax_boilerplate(tmp_path):
    """JAX prints a traceback-filtering notice AFTER the exception line;
    the reported error must be the exception, not the notice."""
    body = (
        "import sys\n"
        "sys.stderr.write('Traceback (most recent call last):\\n')\n"
        "sys.stderr.write('jaxlib.xla_extension.XlaRuntimeError: "
        "sequence length 512 exceeds cap\\n')\n"
        "sys.stderr.write('--------------------\\n')\n"
        "sys.stderr.write('For simplicity, JAX has removed its internal "
        "frames from the traceback\\n')\n"
        "sys.exit(1)\n"
    )
    line, err = _supervise(
        _script_cmd(body), attempts=1, attempt_timeout=30, backoff=0
    )
    assert line is None
    assert err.startswith(
        "jaxlib.xla_extension.XlaRuntimeError: sequence length 512"
    ), err


def test_exhausted_retries_report_last_error(tmp_path):
    body = (
        "import sys\n"
        "sys.stderr.write('UNAVAILABLE: still down\\n')\n"
        "sys.exit(1)\n"
    )
    line, err = _supervise(
        _script_cmd(body), attempts=2, attempt_timeout=30, backoff=0
    )
    assert line is None
    assert "UNAVAILABLE" in err


def test_wait_for_device_succeeds_on_live_backend():
    """This probe child DOES import jax (CPU platform); the budget allows
    ~2 probes so a JAX-less env fails in bounded time rather than
    churning."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    assert _wait_for_device(90, probe_timeout=80, interval=0.1, env=env)


def test_wait_for_device_gives_up_on_dead_backend():
    """An unanswerable backend (bogus platform → probe errors, never prints
    DEVICE_OK) must exhaust the budget and return False, not loop forever."""
    env = dict(os.environ, JAX_PLATFORMS="no_such_platform")
    assert not _wait_for_device(1, probe_timeout=60, interval=0.1, env=env)


def test_main_emits_error_json_when_device_never_answers(monkeypatch, capsys):
    """The driver-facing contract under a wedged backend: exactly one JSON
    line with an error field and rc=1 — never a hang or a traceback."""
    from memvul_tpu.bench import main as bench_main

    monkeypatch.setenv("JAX_PLATFORMS", "no_such_platform")
    monkeypatch.setenv("BENCH_DEVICE_WAIT", "1")
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT", "30")
    rc = bench_main()
    out = capsys.readouterr().out.strip().splitlines()
    assert rc == 1
    assert len(out) == 1
    report = json.loads(out[0])
    assert report["value"] == 0.0
    assert "device did not answer" in report["error"]
