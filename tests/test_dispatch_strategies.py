"""Dispatcher strategy contract (memvul_tpu/serving/dispatch.py).

PR 4's dispatch semantics were extracted into a strategy interface so
``bucketed``, ``ragged``, and ``continuous`` inherit them from ONE
implementation.  This file pins that the contract actually holds for
ALL THREE through one shared harness:

* **exact-counter invariant** — under a blocked device, queue overflow,
  and expiring deadlines, every per-status response count equals its
  telemetry sub-counter and ``served + shed + errors == requests``;
* **deadline-at-pull** — a request that expired while queued resolves
  ``"deadline"`` and never reaches the device;
* **SIGTERM drain** — in-flight work finishes, everything still queued
  sheds ``"drain"``, and the counters still sum;
* **``serve.batch`` chaos** — retry exhaustion dead-letters with a
  reason instead of hanging clients, and the service recovers once the
  fault clears;
* **continuous parity** — 200 concurrent mixed-length requests through
  a CONTINUOUS service match the bucketed path ≤1e-6 with
  ``score_trace_count`` flat (one warm program);
* **the headline** — on the seeded closed-loop load harness with a slow
  fake device, the continuous dispatcher's p50 ``serve.queue_wait_s``
  is ≥3× below ragged's: admission decoupled from device latency.
"""

import json
import os
import signal
import threading
import time

import numpy as np
import pytest

import jax

from memvul_tpu import telemetry
from memvul_tpu.data.readers import MemoryReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.evaluate.predict_memory import SiamesePredictor
from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.resilience import faults
from memvul_tpu.resilience.retry import RetryPolicy
from memvul_tpu.serving import (
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_OK,
    STATUS_SHED,
    InprocessClient,
    ScoringService,
    ServiceConfig,
)
from memvul_tpu.serving.loadgen import LoadConfig, LoadGenerator

IMPLS = ["bucketed", "ragged", "continuous", "cascade"]

# response status → the telemetry sub-counter that must match it exactly
STATUS_TO_COUNTER = {
    STATUS_OK: "serve.served",
    STATUS_SHED: "serve.shed_overflow",
    STATUS_DEADLINE: "serve.shed_deadline",
    STATUS_DRAIN: "serve.shed_drain",
    "error": "serve.errors",
}


@pytest.fixture()
def tel(tmp_path):
    registry = telemetry.configure(run_dir=tmp_path / "run")
    yield registry
    telemetry.reset()
    faults.reset()


class _FakeEncoder:
    pad_id = 0

    def __init__(self, max_length=8):
        self.max_length = max_length

    def encode_many(self, texts):
        return [[1] * max(1, min(len(t), self.max_length)) for t in texts]


class _StrategyFake:
    """Minimal predictor surface valid for every dispatch strategy;
    scoring blocks until released (``hold``) and optionally sleeps a
    fixed per-batch device time, so the tests control exactly when and
    for how long the device is busy."""

    def __init__(
        self, impl, n_anchors=3, rows=4, length=8, budget=32, device_s=0.0
    ):
        self.score_impl = impl
        self.encoder = _FakeEncoder(length)
        self.mesh = None
        self.params = None
        self.n_anchors = n_anchors
        self.anchor_labels = [f"A{i}" for i in range(n_anchors)]
        self.anchor_bank = np.zeros((n_anchors, 2), np.float32)
        self.score_trace_count = 0
        self._shapes = [(rows, length)]
        self._rows = rows
        self._budget = budget
        self.device_s = device_s
        self.started = threading.Event()  # set when a batch enters scoring
        self.hold = threading.Event()     # scoring blocks until set
        # cascade surface: the fake's int8 tier IS its score fn (max
        # score 0.9 > high, so every row short-circuits — one device
        # call per chunk, same counter semantics as the other impls)
        self.int8_params = None
        self.cascade_band = (0.3, 0.7)

    def stream_shapes(self):
        return list(self._shapes)

    def ragged_shape(self):
        return (self._budget, self._rows)

    def _score(self, rows):
        self.started.set()
        assert self.hold.wait(timeout=30), "test forgot to release hold"
        if self.device_s:
            time.sleep(self.device_s)
        return np.tile(
            np.linspace(0.1, 0.9, self.n_anchors, dtype=np.float32), (rows, 1)
        )

    def _score_fn(self, params, sample, bank):
        return self._score(sample["input_ids"].shape[0])

    def _ragged_score_fn(self, params, sample, bank):
        return self._score(self._rows)

    def _int8_score_fn(self, params, sample, bank):
        return self._score(sample["input_ids"].shape[0])

    def int8_program_key(self, rows, length):
        return f"score_int8:{rows}x{length}"


def _make_service(impl, fake=None, **overrides):
    fake = fake or _StrategyFake(impl)
    defaults = dict(
        max_batch=4, max_wait_ms=1.0, max_queue=1000,
        default_deadline_ms=0.0,
    )
    defaults.update(overrides)
    return fake, ScoringService(fake, config=ServiceConfig(**defaults))


def _statuses(futures, timeout=30):
    counts = {}
    for future in futures:
        status = future.result(timeout=timeout)["status"]
        counts[status] = counts.get(status, 0) + 1
    return counts


def _assert_counters_agree(statuses, counters):
    """The exact-counter contract every strategy inherits: each
    per-status response count equals its sub-counter, the shed ledger
    sums, and nothing is lost or double-counted."""
    for status, counter in STATUS_TO_COUNTER.items():
        assert counters.get(counter, 0) == statuses.get(status, 0), (
            status, counters,
        )
    assert counters.get("serve.shed", 0) == (
        counters.get("serve.shed_overflow", 0)
        + counters.get("serve.shed_deadline", 0)
        + counters.get("serve.shed_drain", 0)
    )
    assert (
        counters.get("serve.served", 0)
        + counters.get("serve.shed", 0)
        + counters.get("serve.errors", 0)
    ) == counters["serve.requests"]


# -- the shared harness, all three strategies ---------------------------------

@pytest.mark.parametrize("impl", IMPLS)
def test_counter_invariant_overflow_and_deadline_at_pull(impl, tel):
    """Blocked device + saturated pipeline + a deadline burst: overflow
    sheds, queued requests expire AT THE PULL (they never reach the
    device), and every counter matches the response counts exactly."""
    fake, service = _make_service(impl, max_queue=4, max_wait_ms=1.0)
    # occupy the device: the first request blocks in scoring...
    preload = [service.submit(f"warm {i}", deadline_ms=0) for i in range(1)]
    assert fake.started.wait(timeout=10)
    # ...then fill the strategy's internal pipeline in paced waves (for
    # continuous: one pack on device, one sealed in the handoff, one
    # sealing; for the pull strategies the queue itself) so admission is
    # genuinely stalled before the burst lands
    for wave in range(2):
        preload += [
            service.submit(f"fill {wave}-{i}", deadline_ms=0)
            for i in range(4)
        ]
        time.sleep(0.05)  # let the admission side absorb the wave
    # burst past the queue cap with a short deadline: the overflow sheds
    # the oldest immediately, the survivors expire while queued
    burst = [service.submit(f"late {i}", deadline_ms=50.0) for i in range(8)]
    time.sleep(0.1)  # all burst deadlines are now past
    fake.hold.set()
    statuses = _statuses(preload + burst)
    service.drain()
    counters = tel.snapshot()["counters"]
    assert counters["serve.requests"] == 17
    _assert_counters_agree(statuses, counters)
    # the load exercised every admission outcome
    assert statuses.get(STATUS_OK, 0) >= 1
    assert statuses.get(STATUS_SHED, 0) >= 1       # overflow landed
    assert statuses.get(STATUS_DEADLINE, 0) >= 1   # expiry at the pull landed
    assert statuses.get("error", 0) == 0


@pytest.mark.parametrize("impl", IMPLS)
def test_sigterm_drain_finishes_inflight_sheds_queue(impl, tel):
    """SIGTERM mid-load: pulled work finishes ``"ok"``, everything still
    queued sheds ``"drain"``, and the counters still sum exactly."""
    fake, service = _make_service(impl)
    previous = service.install_signal_handlers()
    try:
        futures = [service.submit(f"req {i}", deadline_ms=0) for i in range(40)]
        assert fake.started.wait(timeout=10)
        os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
        fake.hold.set()
        service.drain()
    finally:
        service.restore_signal_handlers(previous)
    statuses = _statuses(futures)
    counters = tel.snapshot()["counters"]
    assert set(statuses) <= {STATUS_OK, STATUS_DRAIN}
    assert statuses.get(STATUS_OK, 0) >= 1     # in-flight work finished
    assert statuses.get(STATUS_DRAIN, 0) >= 1  # the kill landed mid-load
    assert counters["serve.requests"] == 40
    _assert_counters_agree(statuses, counters)


@pytest.mark.chaos
@pytest.mark.parametrize("impl", IMPLS)
def test_serve_batch_fault_dead_letters_then_recovers(impl, tel):
    """Retry exhaustion on the ``serve.batch`` fault point dead-letters
    with the reason — through whichever thread the strategy scores on —
    and the service recovers once the fault set is spent."""
    faults.configure(
        "serve.batch=raise:RuntimeError:UNAVAILABLE a;"
        "serve.batch=raise:RuntimeError:UNAVAILABLE b;"
        "serve.batch=raise:RuntimeError:UNAVAILABLE c"
    )
    fake = _StrategyFake(impl)
    fake.hold.set()
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=4, max_wait_ms=1.0, default_deadline_ms=0.0,
        ),
        retry_policy=RetryPolicy(attempts=3, sleep=lambda s: None),
    )
    client = InprocessClient(service)
    response = client.score("doomed", timeout_s=30)  # must not hang
    assert response["status"] == "error"
    assert "UNAVAILABLE" in response["reason"]
    # the fault set is spent — the service recovers without a restart
    faults.reset()
    assert client.score("fine")["status"] == STATUS_OK
    service.drain()
    counters = tel.snapshot()["counters"]
    assert counters["serve.dead_letters"] == 1
    assert counters["serve.errors"] == 1
    _assert_counters_agree({STATUS_OK: 1, "error": 1}, counters)


@pytest.mark.parametrize("impl", IMPLS)
def test_health_summary_names_the_strategy(impl, tel):
    fake, service = _make_service(impl)
    fake.hold.set()
    try:
        summary = service.health_summary()
        assert summary["score_impl"] == impl
        assert summary["status"] == "ok"
        # liveness ANDs the strategy's own workers into the signal (for
        # continuous: the device worker thread)
        assert service.batcher_alive
    finally:
        service.drain()


def test_unknown_score_impl_rejected(tel):
    fake = _StrategyFake("bucketed")
    fake.score_impl = "warp"
    with pytest.raises(ValueError, match="unknown score_impl"):
        ScoringService(fake, config=ServiceConfig(max_wait_ms=1.0))


# -- continuous parity against the offline path --------------------------------

@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("dispatch"), seed=13)


@pytest.fixture(scope="module")
def setup(ws):
    """One tiny model + a bucketed and a CONTINUOUS predictor sharing
    its params — the parity pair (jit caches persist across tests, the
    warmed-program reuse the service relies on)."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    anchors = list(reader.read_anchors(ws["paths"]["anchors"]))
    bucketed = SiamesePredictor(
        model, params, ws["tokenizer"],
        batch_size=8, max_length=48, buckets=[16, 48],
    )
    bucketed.encode_anchors(anchors)
    continuous = SiamesePredictor(
        model, params, ws["tokenizer"],
        batch_size=8, max_length=48,
        score_impl="continuous", token_budget=96, max_rows_per_pack=8,
    )
    continuous.encode_anchors(anchors)
    texts = [
        inst["text1"]
        for inst in reader.read(ws["paths"]["test"], split="test")
    ]
    return {"bucketed": bucketed, "continuous": continuous, "texts": texts}


def test_continuous_service_concurrent_load_parity_one_warm_program(
    setup, tel
):
    """200 concurrent mixed-length requests through a CONTINUOUS
    service: every response matches the bucketed offline path ≤1e-6,
    zero mid-serve recompiles (the continuous dispatcher shares the
    ragged warm program), and the overlap counters registered load."""
    bucketed, continuous = setup["bucketed"], setup["continuous"]
    n = 200
    picks = [setup["texts"][i % len(setup["texts"])] for i in range(n)]
    expected = bucketed.score_texts(picks)
    traces_before = continuous.score_trace_count

    service = ScoringService(
        continuous,
        config=ServiceConfig(
            max_batch=8, max_wait_ms=3.0, max_queue=1000,
            default_deadline_ms=30000.0,
        ),
    )
    client = InprocessClient(service)
    results = {}
    lock = threading.Lock()

    def worker(indices):
        for i in indices:
            response = client.score(picks[i])
            with lock:
                results[i] = response

    threads = [
        threading.Thread(target=worker, args=(range(k, n, 16),))
        for k in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    service.drain()

    assert len(results) == n
    labels = continuous.anchor_labels
    for i in range(n):
        assert results[i]["status"] == STATUS_OK
        got = np.array(
            [results[i]["predict"][label] for label in labels], np.float32
        )
        np.testing.assert_allclose(got, expected[i], atol=1e-6, rtol=0)
    # one warm program served the whole mixed-length load
    assert continuous.score_trace_count == traces_before
    counters = tel.snapshot()["counters"]
    assert counters["serve.served"] == n
    assert counters["serve.requests"] == n
    # padding ledger: every sealed pack paid exactly token_budget slots
    assert counters["serve.tokens_padded"] % continuous.token_budget == 0
    assert 0 < counters["serve.tokens_real"] <= counters["serve.tokens_padded"]
    # the page table recycled slots across packs under sustained load
    assert counters.get("serve.pack_slots_reused", 0) > 0


def test_report_renders_admission_efficiency(tmp_path):
    """telemetry-report derives serve.admission_efficiency from the
    overlap ledger (pack_topups / served) in both the text COUNTERS
    section and the --json report."""
    from memvul_tpu.telemetry.report import render_report, report_json

    registry = telemetry.configure(run_dir=tmp_path / "run")
    registry.counter("serve.pack_topups").inc(30)
    registry.counter("serve.served").inc(40)
    registry.counter("serve.pack_slots_reused").inc(12)
    registry.close()
    try:
        text = render_report(tmp_path / "run")
        report = report_json(tmp_path / "run")
    finally:
        telemetry.reset()
    assert "serve.admission_efficiency = 0.750" in text
    assert "(30/40 served admitted mid-flight)" in text
    assert "serve.pack_slots_reused = 12" in text
    assert report["derived"]["serve.admission_efficiency"] == 0.75


# -- cascade: int8 tier + fp32 rescue band -------------------------------------

@pytest.fixture(scope="module")
def cascade_setup(ws):
    """One tiny model + params shared by every cascade predictor in this
    section (the band varies per test) — warmed over ONE bucket, so each
    predictor's warm-program set is exactly two: the fp32 bucket program
    and its int8 twin."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    anchors = list(reader.read_anchors(ws["paths"]["anchors"]))

    def make(low, high):
        predictor = SiamesePredictor(
            model, params, ws["tokenizer"],
            batch_size=8, max_length=48, buckets=[48],
            encoder_precision="int8", score_impl="cascade",
            cascade_low=low, cascade_high=high,
        )
        predictor.encode_anchors(anchors)
        return predictor

    texts = [
        inst["text1"]
        for inst in reader.read(ws["paths"]["test"], split="test")
    ]
    return {"make": make, "texts": texts}


def test_cascade_band_routes_int8_out_fp32_in(cascade_setup, tel):
    """Out-of-band rows resolve with int8-tier scores, in-band rows with
    fp32 scores — each bitwise-equal to the offline single-text score
    through the same warmed program (the bucketed strategy's bitwise
    contract, held per tier), with the tier-exit counters matching the
    split exactly."""
    texts = cascade_setup["texts"]
    probe = cascade_setup["make"](0.0, 1.0)
    best = probe.score_texts(texts, impl="int8").max(axis=1)
    # cut the corpus on the int8 scores' midpoint: rows at or below it
    # are "uncertain" (rescored fp32), rows above it short-circuit
    cut = float((best.min() + best.max()) / 2.0)
    predictor = cascade_setup["make"](0.0, cut)
    service = ScoringService(
        predictor,
        config=ServiceConfig(
            max_batch=8, max_wait_ms=1.0, max_queue=100,
            default_deadline_ms=30000.0,
        ),
    )
    client = InprocessClient(service)
    labels = predictor.anchor_labels
    n_in = n_out = 0
    try:
        for text, b in zip(texts, best):
            response = client.score(text)
            assert response["status"] == STATUS_OK
            served = np.array(
                [response["predict"][label] for label in labels], np.float32
            )
            if b <= cut:
                expected = predictor.score_texts([text], impl="bucketed")[0]
                n_in += 1
            else:
                expected = predictor.score_texts([text], impl="int8")[0]
                n_out += 1
            np.testing.assert_array_equal(served, expected)
    finally:
        service.drain()
    assert n_in and n_out, "the midpoint cut must split the corpus"
    counters = tel.snapshot()["counters"]
    assert counters["serve.cascade_rescored"] == n_in
    assert counters["serve.cascade_shortcircuit"] == n_out


def test_cascade_full_band_concurrent_parity_two_warm_programs(
    cascade_setup, tel
):
    """Band [0, 1]: every row pays the fp32 rescore, so 200 concurrent
    requests through a CASCADE service match the offline fp32 path
    ≤1e-6 with ``score_trace_count`` flat — the whole load ran on
    exactly the two warmed programs (one per tier), zero mid-serve
    compiles."""
    predictor = cascade_setup["make"](0.0, 1.0)
    texts = cascade_setup["texts"]
    n = 200
    picks = [texts[i % len(texts)] for i in range(n)]
    expected = predictor.score_texts(picks, impl="bucketed")
    traces_before = predictor.score_trace_count
    programs_before = {p["key"] for p in predictor.programs.snapshot()}
    rows, length = predictor.stream_shapes()[0]
    assert predictor.bucket_program_key(rows, length) in programs_before
    assert predictor.int8_program_key(rows, length) in programs_before

    service = ScoringService(
        predictor,
        config=ServiceConfig(
            max_batch=8, max_wait_ms=3.0, max_queue=1000,
            default_deadline_ms=30000.0,
        ),
    )
    client = InprocessClient(service)
    results = {}
    lock = threading.Lock()

    def worker(indices):
        for i in indices:
            response = client.score(picks[i])
            with lock:
                results[i] = response

    threads = [
        threading.Thread(target=worker, args=(range(k, n, 16),))
        for k in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    service.drain()

    assert len(results) == n
    labels = predictor.anchor_labels
    for i in range(n):
        assert results[i]["status"] == STATUS_OK
        got = np.array(
            [results[i]["predict"][label] for label in labels], np.float32
        )
        np.testing.assert_allclose(got, expected[i], atol=1e-6, rtol=0)
    # zero mid-serve compiles: the load ran entirely on the warmed pair
    assert predictor.score_trace_count == traces_before
    assert {p["key"] for p in predictor.programs.snapshot()} == programs_before
    counters = tel.snapshot()["counters"]
    assert counters["serve.served"] == n
    assert counters["serve.cascade_rescored"] == n
    assert counters.get("serve.cascade_shortcircuit", 0) == 0
    # every cascade batch booked two device round-trips into the ledger
    assert counters["serve.batches"] % 2 == 0


def test_report_renders_cascade_tier_split(tmp_path):
    """telemetry-report derives serve.cascade_rescore_rate from the
    tier-exit counters and renders the CASCADE section — tier split plus
    each tier's device-time share from the program registry's scope
    split — in both the text report and the --json block."""
    from memvul_tpu.telemetry.report import render_report, report_json

    registry = telemetry.configure(run_dir=tmp_path / "run")
    registry.counter("serve.cascade_rescored").inc(10)
    registry.counter("serve.cascade_shortcircuit").inc(30)
    registry.close()
    (tmp_path / "run" / "programs.json").write_text(json.dumps({
        "programs": [
            {"key": "score:8x48", "scope": "score",
             "invocations": 10, "device_time_s": 3.0},
            {"key": "score_int8:8x48", "scope": "score_int8",
             "invocations": 40, "device_time_s": 1.0},
        ],
    }))
    try:
        text = render_report(tmp_path / "run")
        report = report_json(tmp_path / "run")
    finally:
        telemetry.reset()
    assert "serve.cascade_rescore_rate = 0.250" in text
    assert "(10/40 rescored fp32)" in text
    assert "CASCADE (int8 tier + fp32 rescue band)" in text
    assert report["derived"]["serve.cascade_rescore_rate"] == 0.25
    cascade = report["cascade"]
    assert cascade["rescored"] == 10 and cascade["shortcircuit"] == 30
    assert cascade["rescore_rate"] == 0.25
    assert cascade["tiers"]["fp32"]["device_time_share"] == 0.75
    assert cascade["tiers"]["int8"]["device_time_share"] == 0.25
    # a run with no cascade traffic renders neither
    other = telemetry.configure(run_dir=tmp_path / "plain")
    other.counter("serve.served").inc(5)
    other.close()
    try:
        assert "CASCADE" not in render_report(tmp_path / "plain")
        assert report_json(tmp_path / "plain")["cascade"] is None
    finally:
        telemetry.reset()


# -- the headline: admission decoupled from device latency ---------------------

def _queue_wait_leg(impl, texts):
    """One seeded closed-loop leg against a slow fake device; returns
    (p50 queue wait seconds, leg counters)."""
    registry = telemetry.configure(run_dir=None)
    try:
        fake = _StrategyFake(impl, rows=8, length=8, budget=64, device_s=0.05)
        fake.hold.set()
        service = ScoringService(
            fake,
            config=ServiceConfig(
                max_batch=8, max_wait_ms=2.0, max_queue=1000,
                default_deadline_ms=0.0, trace_sample_rate=1.0,
            ),
        )
        report = LoadGenerator(
            service.submit,
            LoadConfig(pattern="closed", requests=64, clients=16, seed=5),
        ).run(texts)
        service.drain()
        assert report["outcomes"]["hang"] == 0
        assert report["outcomes"]["ok"] == 64
        snap = registry.snapshot()
        hist = snap["histograms"]["serve.queue_wait_s"]
        assert hist["count"] == 64
        return hist["p50"], snap["counters"]
    finally:
        telemetry.reset()


def test_continuous_queue_wait_p50_at_least_3x_below_ragged():
    """The acceptance bar: at offered load beyond device throughput
    (16 closed-loop clients vs an 8-row 50 ms device), the ragged
    pull-then-seal loop makes every request wait out device round-trips
    before it is even coalesced, while continuous admission pops it into
    the in-flight pack almost immediately — p50 ``serve.queue_wait_s``
    drops ≥3× on the identical seeded schedule."""
    texts = [f"req {'x' * (i % 11)}" for i in range(16)]
    ragged_p50, _ = _queue_wait_leg("ragged", texts)
    continuous_p50, counters = _queue_wait_leg("continuous", texts)
    # the slow device is the bottleneck in BOTH legs; only admission
    # latency differs — that is the entire point of the strategy
    assert ragged_p50 >= 3.0 * continuous_p50, (ragged_p50, continuous_p50)
    # and the overlap the gain comes from is visible in the counters
    assert counters.get("serve.pack_topups", 0) > 0
    assert counters.get("serve.pack_slots_reused", 0) > 0
