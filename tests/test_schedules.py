"""LR-schedule family + momentum scheduler (reference trainer's
scheduler slots, custom_trainer.py:168-169, stepped at 741-744 — no
shipped reference config uses them; provided for drop-in parity)."""

import numpy as np
import pytest

from memvul_tpu.training.optim import (
    make_momentum_schedule,
    make_optimizer,
    make_schedule,
)


def _eval(schedule, steps):
    return np.asarray([float(schedule(s)) for s in steps])


def test_constant():
    s = make_schedule({"type": "constant"})
    np.testing.assert_allclose(_eval(s, [0, 10, 1000]), 1.0)


def test_linear_with_warmup_spec():
    s = make_schedule(
        {"type": "linear_with_warmup", "warmup_steps": 10, "total_steps": 110}
    )
    vals = _eval(s, [0, 5, 10, 60, 110])
    np.testing.assert_allclose(vals, [0.0, 0.5, 1.0, 0.5, 0.0], atol=1e-6)


def test_slanted_triangular_shape():
    s = make_schedule(
        {"type": "slanted_triangular", "num_steps": 100, "cut_frac": 0.1,
         "ratio": 32}
    )
    vals = _eval(s, [0, 5, 10, 55, 100])
    # climbs to 1.0 at the cut, falls back to the 1/ratio floor
    assert vals[0] == pytest.approx(1 / 32)
    assert vals[1] == pytest.approx((1 + 0.5 * 31) / 32)
    assert vals[2] == pytest.approx(1.0)
    assert vals[2] > vals[3] > vals[4]
    assert vals[4] == pytest.approx(1 / 32)


def test_cosine_with_warmup_shape():
    s = make_schedule(
        {"type": "cosine_with_warmup", "warmup_steps": 10, "total_steps": 110}
    )
    vals = _eval(s, [0, 5, 10, 60, 110, 200])
    np.testing.assert_allclose(
        vals, [0.0, 0.5, 1.0, 0.5, 0.0, 0.0], atol=1e-6
    )


def test_polynomial_decay_power_and_floor():
    s = make_schedule(
        {"type": "polynomial_decay", "warmup_steps": 0, "total_steps": 100,
         "power": 2.0, "end_factor": 0.1}
    )
    vals = _eval(s, [0, 50, 100, 150])
    assert vals[0] == pytest.approx(1.0)
    assert vals[1] == pytest.approx(0.25 * 0.9 + 0.1)
    assert vals[2] == pytest.approx(0.1)
    assert vals[3] == pytest.approx(0.1)  # holds the floor


def test_unknown_types_raise():
    with pytest.raises(ValueError):
        make_schedule({"type": "nope"})
    with pytest.raises(ValueError):
        make_schedule({"type": "slanted_triangular"})  # needs num_steps
    with pytest.raises(ValueError):
        make_momentum_schedule({"type": "nope"})


def test_inverted_triangular_momentum():
    s = make_momentum_schedule(
        {"type": "inverted_triangular", "cooldown_steps": 10,
         "warmup_steps": 10, "low": 0.5},
        base=0.9,
    )
    vals = _eval(s, [0, 5, 10, 15, 20, 100])
    np.testing.assert_allclose(
        vals, [0.9, 0.7, 0.5, 0.7, 0.9, 0.9], atol=1e-6
    )


def _tiny_params():
    import jax.numpy as jnp

    return {"bert": {"w": jnp.ones((3,))}, "head": {"w": jnp.ones((3,))}}


def test_optimizer_with_cosine_schedule_steps():
    import jax
    import jax.numpy as jnp

    params = _tiny_params()
    tx, state = make_optimizer(
        params,
        lr_schedule={"type": "cosine_with_warmup", "warmup_steps": 2,
                     "total_steps": 10},
        warmup_steps=2,
        total_steps=10,
    )
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    updates, state = tx.update(grads, state, params)
    # step 0: warmup scale 0 → zero update everywhere
    assert all(
        float(jnp.abs(u).max()) == 0.0
        for u in jax.tree_util.tree_leaves(updates)
    )
    updates, state = tx.update(grads, state, params)
    assert any(
        float(jnp.abs(u).max()) > 0.0
        for u in jax.tree_util.tree_leaves(updates)
    )


def test_optimizer_momentum_schedule_changes_trajectory():
    """An inverted-triangular b1 must produce a different second-step
    update than constant momentum on a sign-flipping gradient."""
    import jax
    import jax.numpy as jnp

    def run(momentum_schedule):
        params = _tiny_params()
        tx, state = make_optimizer(
            params, momentum_schedule=momentum_schedule, warmup_steps=0
        )
        g1 = jax.tree_util.tree_map(jnp.ones_like, params)
        g2 = jax.tree_util.tree_map(lambda x: -jnp.ones_like(x), params)
        _, state = tx.update(g1, state, params)
        upd, _ = tx.update(g2, state, params)
        return np.asarray(upd["head"]["w"])

    base = run(None)
    scheduled = run(
        {"type": "inverted_triangular", "cooldown_steps": 2,
         "warmup_steps": 2, "low": 0.2}
    )
    assert not np.allclose(base, scheduled)


def test_trainer_config_accepts_scheduler_specs(tmp_path):
    """The dataclass fields exist and flow through (config-drift guard for
    the new slots)."""
    from memvul_tpu.training.single_trainer import ClassifierTrainerConfig
    from memvul_tpu.training.trainer import TrainerConfig

    for cls in (TrainerConfig, ClassifierTrainerConfig):
        cfg = cls(
            learning_rate_scheduler={"type": "cosine_with_warmup",
                                     "total_steps": 100},
            momentum_scheduler={"type": "inverted_triangular"},
        )
        assert cfg.learning_rate_scheduler["type"] == "cosine_with_warmup"


def test_memory_trainer_trains_with_scheduler_slots(tmp_path):
    """End-to-end: a MemoryTrainer configured with a cosine LR schedule +
    inverted-triangular momentum actually steps and moves params."""
    import numpy as np

    from memvul_tpu.build import build_model, build_reader, build_tokenizer, init_params
    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.training.trainer import MemoryTrainer, TrainerConfig

    ws = build_workspace(tmp_path, seed=41)
    tokenizer = build_tokenizer({"tokenizer_path": ws["paths"]["tokenizer"]})
    reader = build_reader({
        "type": "reader_memory", "sample_neg": 1.0,
        "same_diff_ratio": {"same": 2, "diff": 2},
        "cve_path": ws["paths"]["cve"], "anchor_path": ws["paths"]["anchors"],
    })
    model = build_model(
        {"type": "model_memory", "encoder": {"preset": "tiny", "vocab_size": 4096},
         "header_dim": 16}, tokenizer.vocab_size,
    )
    trainer = MemoryTrainer(
        model, init_params(model), tokenizer, reader,
        train_path=ws["paths"]["train"],
        config=TrainerConfig(
            num_epochs=1, batch_size=4, grad_accum=2, max_length=32,
            steps_per_epoch=3, warmup_steps=1,
            learning_rate_scheduler={"type": "cosine_with_warmup",
                                     "warmup_steps": 1, "total_steps": 6},
            momentum_scheduler={"type": "inverted_triangular",
                                "cooldown_steps": 2, "warmup_steps": 2},
        ),
    )
    before = np.asarray(trainer.params["params"]["pair_kernel"]).copy()
    trainer.train_epoch()
    after = np.asarray(trainer.params["params"]["pair_kernel"])
    assert np.abs(after - before).max() > 0
