"""Tier-1 invariant: no bare ``print(`` in memvul_tpu library code
(tools/lint_no_bare_print.py) — library output goes through logging or
the telemetry registry; only bench.py/__main__.py own stdout."""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))

from lint_no_bare_print import find_bare_prints, main  # noqa: E402


def test_package_has_no_bare_prints():
    offenders = find_bare_prints(REPO / "memvul_tpu")
    assert offenders == [], (
        "bare print() in library code (use logging / telemetry, "
        f"docs/observability.md): {offenders}"
    )


def test_lint_flags_a_planted_offender(tmp_path):
    (tmp_path / "bad.py").write_text("def f():\n    print('oops')\n")
    (tmp_path / "ok.py").write_text(
        "SRC = 'print(\"in a string is fine\")'\n"
        "import logging\nlogging.getLogger(__name__).info('fine')\n"
    )
    (tmp_path / "bench.py").write_text("print('exempt')\n")
    offenders = find_bare_prints(tmp_path)
    assert len(offenders) == 1 and offenders[0].endswith("bad.py:2")


def test_lint_cli_exit_codes(tmp_path, capsys):
    (tmp_path / "clean.py").write_text("x = 1\n")
    assert main([str(tmp_path)]) == 0
    (tmp_path / "bad.py").write_text("print(1)\n")
    assert main([str(tmp_path)]) == 1
    out = capsys.readouterr().out
    assert "bad.py:1" in out
    assert main([str(tmp_path / "missing")]) == 2
