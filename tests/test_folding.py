import numpy as np

from memvul_tpu.models.folding import fold_tokens, unfold_embeddings

CLS, SEP, PAD = 2, 3, 0


def frame(tokens, total):
    """[CLS] tokens [SEP] padded to total."""
    ids = np.full(total, PAD, dtype=np.int32)
    seq = [CLS] + list(tokens) + [SEP]
    ids[: len(seq)] = seq
    mask = (ids != PAD).astype(np.int32)
    return ids, mask


def test_short_input_single_segment():
    ids, mask = frame([10, 11, 12], 16)
    folded, fmask, s = fold_tokens(
        ids[None], mask[None], max_length=16, cls_id=CLS, sep_id=SEP, pad_id=PAD
    )
    assert s == 1
    assert folded[0, 0] == CLS
    content = folded[0][fmask[0] > 0]
    assert content.tolist() == [CLS, 10, 11, 12, SEP]


def test_long_input_folds_and_reframes():
    tokens = list(range(10, 30))  # 20 content tokens
    ids, mask = frame(tokens, 32)
    max_length = 10  # inner 8 -> ceil((32-1)/8) segments
    folded, fmask, s = fold_tokens(
        ids[None], mask[None], max_length=max_length,
        cls_id=CLS, sep_id=SEP, pad_id=PAD,
    )
    assert folded.shape == (s, max_length)
    # every non-empty segment is CLS-framed and SEP-terminated
    for i in range(s):
        if fmask[i].sum() == 0:
            continue
        seg = folded[i][fmask[i] > 0]
        assert seg[0] == CLS and seg[-1] == SEP
    # all content tokens survive exactly once, in order
    recovered = [
        t
        for i in range(s)
        for t in folded[i][fmask[i] > 0][1:-1].tolist()
    ]
    assert recovered == tokens


def test_batch_folding_shapes():
    a_ids, a_mask = frame(list(range(10, 40)), 40)
    b_ids, b_mask = frame([50], 40)
    ids = np.stack([a_ids, b_ids])
    mask = np.stack([a_mask, b_mask])
    folded, fmask, s = fold_tokens(ids, mask, 12, CLS, SEP, PAD)
    assert folded.shape[0] == 2 * s


def test_unfold_embeddings_roundtrip_shape():
    bs, length, dim = 6, 10, 4
    emb = np.arange(bs * length * dim, dtype=np.float32).reshape(bs, length, dim)
    out, valid = unfold_embeddings(emb, num_segments=3)
    assert out.shape == (2, 3 * (length - 2), dim)
    assert valid.shape == (2, 3 * (length - 2))
    # the first stitched row of report 0 is segment 0 position 1
    np.testing.assert_array_equal(out[0, 0], emb[0, 1])
    # the first row of the second segment follows the last of the first
    np.testing.assert_array_equal(out[0, length - 2], emb[1, 1])


def test_unfold_mask_excludes_partial_segment_sep_and_padding():
    # one report, two segments; second segment holds 2 tokens + SEP
    ids, mask = frame(list(range(10, 20)), 16)  # 10 content tokens
    folded, fmask, s = fold_tokens(
        ids[None], mask[None], max_length=10, cls_id=CLS, sep_id=SEP, pad_id=PAD
    )
    assert s == 2
    emb = np.zeros((folded.shape[0], folded.shape[1], 3), np.float32)
    stream, valid = unfold_embeddings(emb, s, folded_mask=fmask)
    # exactly the 10 content tokens are valid — no SEP, no padding
    assert int(valid.sum()) == 10
