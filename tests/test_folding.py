import numpy as np

from memvul_tpu.models.folding import fold_tokens, unfold_embeddings

CLS, SEP, PAD = 2, 3, 0


def frame(tokens, total):
    """[CLS] tokens [SEP] padded to total."""
    ids = np.full(total, PAD, dtype=np.int32)
    seq = [CLS] + list(tokens) + [SEP]
    ids[: len(seq)] = seq
    mask = (ids != PAD).astype(np.int32)
    return ids, mask


def test_short_input_single_segment():
    ids, mask = frame([10, 11, 12], 16)
    folded, fmask, s = fold_tokens(
        ids[None], mask[None], max_length=16, cls_id=CLS, sep_id=SEP, pad_id=PAD
    )
    assert s == 1
    assert folded[0, 0] == CLS
    content = folded[0][fmask[0] > 0]
    assert content.tolist() == [CLS, 10, 11, 12, SEP]


def test_long_input_folds_and_reframes():
    tokens = list(range(10, 30))  # 20 content tokens
    ids, mask = frame(tokens, 32)
    max_length = 10  # inner 8 -> ceil((32-1)/8) segments
    folded, fmask, s = fold_tokens(
        ids[None], mask[None], max_length=max_length,
        cls_id=CLS, sep_id=SEP, pad_id=PAD,
    )
    assert folded.shape == (s, max_length)
    # every non-empty segment is CLS-framed and SEP-terminated
    for i in range(s):
        if fmask[i].sum() == 0:
            continue
        seg = folded[i][fmask[i] > 0]
        assert seg[0] == CLS and seg[-1] == SEP
    # all content tokens survive exactly once, in order
    recovered = [
        t
        for i in range(s)
        for t in folded[i][fmask[i] > 0][1:-1].tolist()
    ]
    assert recovered == tokens


def test_batch_folding_shapes():
    a_ids, a_mask = frame(list(range(10, 40)), 40)
    b_ids, b_mask = frame([50], 40)
    ids = np.stack([a_ids, b_ids])
    mask = np.stack([a_mask, b_mask])
    folded, fmask, s = fold_tokens(ids, mask, 12, CLS, SEP, PAD)
    assert folded.shape[0] == 2 * s


def test_unfold_embeddings_roundtrip_shape():
    bs, length, dim = 6, 10, 4
    emb = np.arange(bs * length * dim, dtype=np.float32).reshape(bs, length, dim)
    out, valid = unfold_embeddings(emb, num_segments=3)
    assert out.shape == (2, 3 * (length - 2), dim)
    assert valid.shape == (2, 3 * (length - 2))
    # the first stitched row of report 0 is segment 0 position 1
    np.testing.assert_array_equal(out[0, 0], emb[0, 1])
    # the first row of the second segment follows the last of the first
    np.testing.assert_array_equal(out[0, length - 2], emb[1, 1])


def test_unfold_mask_excludes_partial_segment_sep_and_padding():
    # one report, two segments; second segment holds 2 tokens + SEP
    ids, mask = frame(list(range(10, 20)), 16)  # 10 content tokens
    folded, fmask, s = fold_tokens(
        ids[None], mask[None], max_length=10, cls_id=CLS, sep_id=SEP, pad_id=PAD
    )
    assert s == 2
    emb = np.zeros((folded.shape[0], folded.shape[1], 3), np.float32)
    stream, valid = unfold_embeddings(emb, s, folded_mask=fmask)
    # exactly the 10 content tokens are valid — no SEP, no padding
    assert int(valid.sum()) == 10


def test_fold_segment0_equals_truncation_pooled_output():
    """The documented equivalence claim (folding.py docstring): for a
    CLS-pooled classifier, encoding segment 0 of a folded long input gives
    the SAME pooled vector as encoding the truncated input — segment 0 IS
    the truncation.  Verified at the encoder level on a real model."""
    import jax
    from memvul_tpu.models import BertConfig, SingleModel
    from memvul_tpu.models.bert import BertEncoder

    max_length = 16
    cfg = BertConfig.tiny(vocab_size=64)
    model = SingleModel(cfg)
    encoder = BertEncoder(cfg)

    # a long input: 40 content tokens, CLS/SEP framed
    tokens = [(5 + i) % 60 + 4 for i in range(40)]
    ids, mask = frame(tokens, 48)

    folded, fmask, s = fold_tokens(
        ids[None], mask[None], max_length=max_length,
        cls_id=CLS, sep_id=SEP, pad_id=PAD,
    )
    assert s > 1

    # truncation: [CLS] t[:L-2] [SEP] — the reference reader's eval path
    trunc = np.full((1, max_length), PAD, np.int32)
    trunc[0, : max_length - 1] = ids[: max_length - 1]
    trunc[0, max_length - 1] = SEP
    tmask = (trunc != PAD).astype(np.int32)

    # token-level: segment 0 is exactly the truncated sequence
    np.testing.assert_array_equal(folded[0], trunc[0])
    np.testing.assert_array_equal(fmask[0], tmask[0])

    params = model.init(
        jax.random.PRNGKey(0),
        {"input_ids": trunc, "attention_mask": tmask},
    )

    def pooled(batch_ids, batch_mask):
        hidden = encoder.apply(
            {"params": params["params"]["bert"]},
            batch_ids, batch_mask, deterministic=True,
        )
        return np.asarray(hidden[:, 0, :], np.float32)  # CLS vector

    out_trunc = pooled(trunc, tmask)
    out_fold = pooled(folded, fmask)  # all segments batched
    np.testing.assert_allclose(out_fold[0], out_trunc[0], atol=1e-5, rtol=1e-5)
