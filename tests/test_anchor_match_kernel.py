"""Fused Pallas anchor-match kernel vs its XLA formulations.

Runs the kernel logic in Pallas interpret mode on CPU (the identical
code path compiles on TPU; the ``BENCH_MICRO=anchor_match`` harness
records the on-hardware datapoint).  Three-way parity is required:

* the fused kernel,
* the decomposed einsum (``anchor_match_reference`` — the production
  non-TPU path and the model-sharded-bank path),
* the naive ``[u, v, |u−v|]`` concat-linear (the reference semantics,
  model_memory.py:150-158),

including odd (non-multiple-of-tile) B/A/D shapes that exercise the
kernel's internal zero-padding, bf16 inputs, and dispatch behavior.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from memvul_tpu.ops.pallas.anchor_match import (
    anchor_match,
    anchor_match_reference,
    fused_anchor_match,
)


def _inputs(b, a, d, c=2, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    u = jnp.asarray(rng.normal(size=(b, d)), dtype)
    v = jnp.asarray(rng.normal(size=(a, d)), dtype)
    kernel = jnp.asarray(rng.normal(size=(3 * d, c)) * 0.1, dtype)
    return u, v, kernel


def _naive_concat(u, v, kernel):
    """The reference's per-anchor concat-linear, one anchor at a time."""
    rows = []
    for i in range(v.shape[0]):
        feats = jnp.concatenate(
            [u, jnp.broadcast_to(v[i], u.shape), jnp.abs(u - v[i])], axis=-1
        )
        rows.append(feats @ kernel)
    return jnp.stack(rows, axis=1)  # [B, A, C]


@pytest.mark.parametrize(
    "b,a,d",
    [
        (4, 6, 32),      # everything under one tile
        (9, 13, 40),     # odd everywhere
        (17, 129, 200),  # A just past a lane tile, D non-multiple
        (130, 5, 96),    # B past a block, tiny A
    ],
)
def test_fused_matches_both_formulations(b, a, d):
    u, v, kernel = _inputs(b, a, d, seed=b + a + d)
    fused = fused_anchor_match(u, v, kernel, interpret=True)
    ref = anchor_match_reference(u, v, kernel)
    naive = _naive_concat(u, v, kernel)
    assert fused.shape == (b, a, 2)
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(ref), np.asarray(naive), atol=1e-4, rtol=1e-4)


def test_fused_non_default_class_count():
    u, v, kernel = _inputs(5, 7, 64, c=3, seed=7)
    fused = fused_anchor_match(u, v, kernel, interpret=True)
    np.testing.assert_allclose(
        np.asarray(fused), np.asarray(anchor_match_reference(u, v, kernel)),
        atol=1e-5, rtol=1e-5,
    )


def test_fused_bf16_close_to_fp32_reference():
    u, v, kernel = _inputs(8, 9, 128, seed=3, dtype=jnp.bfloat16)
    fused = fused_anchor_match(u, v, kernel, interpret=True)
    ref = anchor_match_reference(
        u.astype(jnp.float32), v.astype(jnp.float32), kernel.astype(jnp.float32)
    )
    assert fused.dtype == jnp.bfloat16
    np.testing.assert_allclose(
        np.asarray(fused, np.float32), np.asarray(ref), atol=3e-2, rtol=3e-2
    )


def test_fused_rejects_mismatched_shapes():
    u, v, kernel = _inputs(4, 5, 32)
    with pytest.raises(ValueError, match="dimension mismatch"):
        fused_anchor_match(u, v, kernel[:-1], interpret=True)
    with pytest.raises(ValueError, match="expected"):
        fused_anchor_match(u[None], v, kernel, interpret=True)


def test_dispatch_impls():
    u, v, kernel = _inputs(4, 5, 32, seed=11)
    ref = anchor_match_reference(u, v, kernel)
    # auto on CPU routes to the jnp decomposition (bit-identical)
    auto = anchor_match(u, v, kernel, impl="auto")
    np.testing.assert_array_equal(np.asarray(auto), np.asarray(ref))
    # fused off-TPU runs the interpret kernel — numerically equal
    fused = anchor_match(u, v, kernel, impl="fused")
    np.testing.assert_allclose(np.asarray(fused), np.asarray(ref), atol=1e-5, rtol=1e-5)
    with pytest.raises(ValueError, match="unknown anchor_match impl"):
        anchor_match(u, v, kernel, impl="einsum")


def test_model_match_anchors_fused_config_matches_default():
    """MemoryModel wired to the fused kernel produces the same anchor
    logits as the default decomposition (the config flag changes the
    backend, never the scores)."""
    from memvul_tpu.models import BertConfig, MemoryModel

    def logits_for(impl):
        cfg = BertConfig.tiny(vocab_size=256, anchor_match_impl=impl)
        model = MemoryModel(cfg)
        batch = {
            "input_ids": np.arange(48, dtype=np.int32).reshape(4, 12) % 256,
            "attention_mask": np.ones((4, 12), np.int32),
        }
        params = model.init(jax.random.PRNGKey(0), batch, batch)
        anchors = jax.random.normal(jax.random.PRNGKey(1), (7, 512))
        return model.apply(params, batch, anchors=anchors)

    # "fused" runs the interpret kernel on CPU; "xla" the decomposition
    np.testing.assert_allclose(
        np.asarray(logits_for("fused")), np.asarray(logits_for("xla")),
        atol=1e-5, rtol=1e-5,
    )


def test_model_sharded_bank_forces_xla_and_matches(tmp_path):
    """With the anchor bank sharded over the ``model`` mesh axis the
    predictor must force the XLA decomposition (the kernel has no SPMD
    lowering) — and the scores must match the unsharded fused-config
    run exactly (rtol: different reduction orders)."""
    from memvul_tpu.data.readers import MemoryReader
    from memvul_tpu.data.synthetic import build_workspace
    from memvul_tpu.evaluate.predict_memory import SiamesePredictor
    from memvul_tpu.models import BertConfig, MemoryModel
    from memvul_tpu.parallel import create_mesh

    if len(jax.devices()) < 4:
        pytest.skip("needs the virtual 8-device CPU mesh")
    ws = build_workspace(tmp_path, seed=5)
    cfg = BertConfig.tiny(
        vocab_size=ws["tokenizer"].vocab_size, anchor_match_impl="fused"
    )
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    mesh = create_mesh({"data": 2, "model": 2}, devices=jax.devices()[:4])
    pred_sharded = SiamesePredictor(
        model, params, ws["tokenizer"], mesh=mesh, batch_size=8, max_length=64
    )
    # the model-sharded bank overrides the configured fused path
    assert pred_sharded.anchor_match_impl == "xla"
    pred_plain = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=8, max_length=64,
        anchor_match_impl="xla",
    )
    results = {}
    for name, pred in [("sharded", pred_sharded), ("plain", pred_plain)]:
        pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
        rows = {}
        for probs, metas in pred.score_instances(
            reader.read(ws["paths"]["test"], split="test")
        ):
            for row, meta in zip(probs, metas):
                rows[meta["Issue_Url"]] = row
        results[name] = rows
    assert results["sharded"].keys() == results["plain"].keys()
    for url, row in results["plain"].items():
        np.testing.assert_allclose(
            results["sharded"][url], row, rtol=1e-4, atol=1e-5
        )
