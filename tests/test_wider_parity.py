"""TextCNN, MLM pretraining, sklearn baselines."""

import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from memvul_tpu.data.synthetic import build_workspace, corpus_texts, generate_corpus
from memvul_tpu.data.tokenizer import WordTokenizer
from memvul_tpu.models import BertConfig
from memvul_tpu.models.textcnn import TextCNN
from memvul_tpu.pretrain import (
    MLMModel,
    MLMTrainer,
    transplant_encoder,
    whole_word_mask,
)
from memvul_tpu.pretrain.mlm import IGNORE, MLMTrainerConfig, continuation_flags, mlm_loss


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("wider"), seed=1)


# -- word tokenizer / TextCNN -------------------------------------------------


def test_word_tokenizer_roundtrip():
    reports, _ = generate_corpus(seed=0)
    tok = WordTokenizer.train_from_corpus(corpus_texts(reports), max_vocab=500)
    ids = tok.encode("the build fails on windows")
    assert all(isinstance(i, int) for i in ids)
    assert tok.encode("") == [1]  # UNK fallback, never empty
    assert tok.pad_id == 0


def test_word_tokenizer_unknown_words():
    tok = WordTokenizer(vocab={"[PAD]": 0, "[UNK]": 1, "build": 2})
    assert tok.encode("build zzzqqq") == [2, 1]


def test_textcnn_forward_shapes():
    model = TextCNN(vocab_size=100, embed_dim=16, num_filters=8)
    ids = np.array([[5, 6, 7, 8, 9, 10, 0, 0]], np.int32)
    batch = {"input_ids": ids, "attention_mask": (ids != 0).astype(np.int32)}
    params = model.init(jax.random.PRNGKey(0), batch)
    logits = model.apply(params, batch)
    assert logits.shape == (1, 2)
    assert np.isfinite(np.asarray(logits)).all()


def test_textcnn_short_input_padded_to_ngram():
    model = TextCNN(vocab_size=50, embed_dim=8, num_filters=4)
    ids = np.array([[7, 8]], np.int32)  # shorter than largest ngram (5)
    batch = {"input_ids": ids, "attention_mask": np.ones_like(ids)}
    params = model.init(jax.random.PRNGKey(0), batch)
    logits = model.apply(params, batch)
    assert np.isfinite(np.asarray(logits)).all()


def test_textcnn_embedding_override():
    model = TextCNN(vocab_size=10, embed_dim=4, num_filters=2)
    ids = np.array([[1, 2, 3, 4, 5]], np.int32)
    batch = {"input_ids": ids, "attention_mask": np.ones_like(ids)}
    params = model.init(jax.random.PRNGKey(0), batch)
    table = np.arange(40, dtype=np.float32).reshape(10, 4)
    updated = model.load_pretrained_embedding(params, table)
    np.testing.assert_array_equal(
        np.asarray(updated["params"]["embedding"]["embedding"]), table
    )


# -- whole word mask / MLM ----------------------------------------------------


def test_whole_word_mask_masks_continuations(ws):
    tok = ws["tokenizer"]
    flags = continuation_flags(tok)
    assert flags.sum() > 0  # vocabulary has ## pieces
    text = "authentication vulnerability in parser"
    ids = np.asarray([tok.encode(text)], np.int32)
    mask = np.ones_like(ids)
    rng = np.random.default_rng(0)
    masked, labels = whole_word_mask(
        ids, mask, rng, tok.mask_id, tok.vocab_size, flags,
        [tok.pad_id, tok.cls_id, tok.sep_id], mask_prob=0.5,
    )
    chosen = labels[0] != IGNORE
    assert chosen.any()
    # specials never chosen
    assert labels[0][0] == IGNORE and labels[0][-1] == IGNORE
    # a chosen head's continuations are chosen with it
    for i in range(1, ids.shape[1] - 1):
        if chosen[i] and i + 1 < ids.shape[1] - 1 and flags[ids[0, i + 1]]:
            assert chosen[i + 1]


def test_mlm_loss_only_on_masked_positions():
    logits = jnp.zeros((1, 4, 10))
    labels = jnp.asarray([[IGNORE, 3, IGNORE, 5]])
    loss = mlm_loss(logits, labels)
    np.testing.assert_allclose(float(loss), np.log(10), rtol=1e-6)


def test_mlm_decoder_tied_to_embeddings(ws):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MLMModel(cfg)
    ids = np.zeros((2, 8), np.int32)
    params = model.init(jax.random.PRNGKey(0), ids, np.ones_like(ids))
    names = set(params["params"].keys())
    assert "decoder_bias" in names
    # no separate [V, D] decoder kernel — logits come from the embedding table
    assert "decoder" not in names


def test_mlm_training_reduces_loss_and_transplants(ws, tmp_path):
    corpus = tmp_path / "mlm.txt"
    reports, _ = generate_corpus(seed=2)
    corpus.write_text("\n".join(corpus_texts(reports)))
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    trainer = MLMTrainer(
        cfg,
        ws["tokenizer"],
        MLMTrainerConfig(
            batch_size=8, max_length=32, num_epochs=3, steps_per_epoch=8,
            learning_rate=3e-3, warmup_steps=2,
        ),
    )
    # held-out eval before training (reference do_eval, run_mlm_wwm.py:386-397):
    # deterministic for a fixed seed, perplexity == exp(loss)
    import math

    held_out = tmp_path / "mlm_eval.txt"
    eval_reports, _ = generate_corpus(seed=9)
    held_out.write_text("\n".join(corpus_texts(eval_reports)[:24]))
    before = trainer.evaluate(str(held_out), seed=4)
    assert before == trainer.evaluate(str(held_out), seed=4)
    assert before["perplexity"] == pytest.approx(
        math.exp(before["eval_loss"]), rel=1e-6
    )
    assert before["masked_tokens"] > 0

    out = trainer.train(str(corpus))
    assert out["history"][-1] < out["history"][0]
    # training on in-domain text lowers held-out masked-LM loss
    after = trainer.evaluate(str(held_out), seed=4)
    assert after["eval_loss"] < before["eval_loss"]

    # encoder subtree transplants into the classifier
    from memvul_tpu.models import MemoryModel

    clf = MemoryModel(cfg)
    d = {"input_ids": np.zeros((2, 8), np.int32),
         "attention_mask": np.ones((2, 8), np.int32)}
    clf_params = clf.init(jax.random.PRNGKey(0), d, d)
    loaded = transplant_encoder(clf_params, trainer.encoder_params())
    trained_word = trainer.encoder_params()["embeddings"]["word_embeddings"]["embedding"]
    np.testing.assert_array_equal(
        np.asarray(loaded["params"]["bert"]["embeddings"]["word_embeddings"]["embedding"]),
        np.asarray(trained_word),
    )
    # transplanted params run
    logits = clf.apply(loaded, d, d)
    assert np.isfinite(np.asarray(logits)).all()


# -- sklearn baselines --------------------------------------------------------


def test_sklearn_baselines_end_to_end(ws, tmp_path):
    from memvul_tpu.baselines import run_baselines

    results = run_baselines(
        ws["paths"]["train"], ws["paths"]["test"], tmp_path / "baseline_out",
        learners=None, seed=7,
    )
    assert set(results) == {"RF", "NB", "MLP", "LR", "KNN"}
    for name, m in results.items():
        assert {"TP", "FN", "TN", "FP", "f1", "auc", "ap"} <= set(m)
        assert (tmp_path / "baseline_out" / f"{name}_result.json").exists()
        assert (tmp_path / "baseline_out" / f"{name}_metric.json").exists()
    records = json.loads(
        (tmp_path / "baseline_out" / "RF_result.json").read_text()
    )
    test_corpus = json.loads(open(ws["paths"]["test"]).read())
    assert len(records) == len(test_corpus)
