"""Tokenizer parity vs HuggingFace's reference ``BertTokenizer``.

The reference tokenizes with bert-base-uncased wordpieces through AllenNLP's
``PretrainedTransformerTokenizer`` (reference: MemVul/config_memory.json:16-20),
which delegates to HF.  This environment has no network egress, so the real
30,522-entry ``vocab.txt`` cannot be vendored; what CAN be proven offline is
that our ``vocab.txt`` loading path (``tokenizer.py::_bert_tokenizer_from_vocab``)
implements the *identical algorithm*: given the same vocab file, our encoder
produces the same id sequences as ``transformers.BertTokenizer`` — basic
tokenization (lowercase, accent-strip, CJK spacing, punctuation splits),
greedy wordpiece with ``##`` continuations and the 100-char [UNK] cutoff,
[CLS]/[SEP] framing, and truncation.  With algorithm parity proven, pointing
``vocab_path`` at a user-supplied bert-base-uncased ``vocab.txt`` yields
id-level parity with the reference pipeline by construction.
"""

import json
from pathlib import Path

import pytest

transformers = pytest.importorskip("transformers")

from memvul_tpu.data.tokenizer import WordPieceTokenizer

GOLDEN = Path(__file__).parent / "golden" / "normalizer_golden.json"

EDGE_TEXTS = [
    "",
    " ",
    "hello world",
    "The Quick BROWN fox!",
    "émigré naïve café über",
    "中文字符 mixed english",
    "日本語とカタカナ",
    "punctuation,everywhere.even;inside:words",
    "x" * 99,
    "x" * 100,  # wordpiece max_input_chars_per_word boundary
    "x" * 101,
    "APITAG CODETAG ERRORTAG FILETAG URLTAG CVETAG",
    "EMAILTAG MENTIONTAG PATHTAG NUMBERTAG",
    "weird space chars here",
    "control\x00chars\x1fstripped",
    "emoji 🙂 inside",
    "a-b-c hyphens",
    "'quoted' \"double\" (parens) [brackets]",
    "123 456.789 0x1A",
    "mixedCASE and ALLCAPS and lower",
    "\t\n\r whitespace soup \t",
    "ünïcödé àccénts ēvērywhere",
]


@pytest.fixture(scope="module")
def vocab_file(tmp_path_factory):
    """Train a realistic wordpiece vocab from the golden corpus and dump it
    in bert ``vocab.txt`` format (one token per line, line number = id)."""
    corpus = [c["expected"] for c in json.loads(GOLDEN.read_text())]
    corpus += [t for t in EDGE_TEXTS if t.strip()]
    tok = WordPieceTokenizer.train_from_corpus(corpus, vocab_size=2048)
    vocab = tok._tok.get_vocab()
    path = tmp_path_factory.mktemp("vocab") / "vocab.txt"
    ordered = sorted(vocab.items(), key=lambda kv: kv[1])
    assert [i for _, i in ordered] == list(range(len(ordered)))
    path.write_text("\n".join(w for w, _ in ordered) + "\n")
    return str(path)


@pytest.fixture(scope="module")
def pair(vocab_file):
    hf = transformers.BertTokenizer(vocab_file, do_lower_case=True)
    ours = WordPieceTokenizer(vocab_path=vocab_file)
    return hf, ours


def test_save_vocab_txt_matches_bert_format(vocab_file, tmp_path):
    """save_vocab_txt reproduces the bert vocab.txt format byte-for-byte
    (the fixture above hand-rolls the format as the independent spec) —
    this is the file the HF-export dir ships for the reference's
    BertTokenizer."""
    ours = WordPieceTokenizer(vocab_path=vocab_file)
    out = tmp_path / "vocab.txt"
    ours.save_vocab_txt(out)
    assert out.read_text() == Path(vocab_file).read_text()


def test_golden_corpus_id_parity(pair):
    """Every normalized golden document tokenizes to identical ids."""
    hf, ours = pair
    for case in json.loads(GOLDEN.read_text()):
        text = case["expected"]
        assert ours.encode(text) == hf.encode(text), repr(text[:60])


@pytest.mark.parametrize("text", EDGE_TEXTS, ids=lambda t: repr(t[:24]))
def test_edge_case_id_parity(pair, text):
    hf, ours = pair
    assert ours.encode(text) == hf.encode(text)


@pytest.mark.parametrize("max_length", [8, 16, 256, 512])
def test_truncation_parity(pair, max_length):
    """Truncation keeps [CLS] ... [SEP] framing exactly like HF
    (train length 256 / eval length 512; reference:
    MemVul/config_memory.json:19, test_config_memory.json:9)."""
    hf, ours = pair
    for case in json.loads(GOLDEN.read_text())[::7]:
        text = case["expected"]
        expected = hf.encode(text, truncation=True, max_length=max_length)
        assert ours.encode(text, max_length=max_length) == expected


def test_special_token_ids_match(pair):
    hf, ours = pair
    assert ours.cls_id == hf.cls_token_id
    assert ours.sep_id == hf.sep_token_id
    assert ours.pad_id == hf.pad_token_id
    assert ours.mask_id == hf.mask_token_id


def test_batch_shapes_and_mask(pair):
    hf, ours = pair
    texts = ["hello world", "a much longer sentence with many more words here"]
    batch = ours.encode_batch(texts, max_length=32, pad_to=32)
    assert batch["input_ids"].shape == (2, 32)
    assert batch["attention_mask"].shape == (2, 32)
    assert batch["token_type_ids"].shape == (2, 32)
    for row, text in zip(range(2), texts):
        ids = hf.encode(text, truncation=True, max_length=32)
        n = len(ids)
        assert batch["input_ids"][row, :n].tolist() == ids
        assert batch["attention_mask"][row, :n].tolist() == [1] * n
        assert batch["attention_mask"][row, n:].sum() == 0
        assert (batch["input_ids"][row, n:] == ours.pad_id).all()
