"""Multi-tenant serving plane + content-addressed admission cache
(serving/tenancy.py, serving/admission_cache.py, docs/multitenancy.md).

The acceptance contract this file pins:

* **tenant spec** — ``name=store_dir`` parsing with the strict
  telemetry-label charset, duplicate and reserved-``default``
  rejection;
* **isolation** — two tenants on ONE service score against their OWN
  bank snapshots (scores are a function of the bank content, so a
  bleed is observable), and the per-tenant ``serve.<tenant>.*``
  ledgers sum exactly to the global counter invariant;
* **swap isolation** — a rolling swap of tenant A's bank under
  concurrent tenant-B load never changes a single B response, and the
  fleet's *default* active version is untouched;
* **chaos** — a replica hard-killed while a tenant rollout is in
  flight is restarted with BOTH tenants' banks re-installed
  (``_sync_bank`` re-rolls named banks), end state consistent across
  the fleet, no cross-tenant bleed at any point;
* **admission cache** — an exact repeat is served bitwise-identical
  WITHOUT a device call; LRU eviction is bounded, a tenant's swap
  invalidates only that tenant's entries, and the ``cache.lookup``
  fault degrades to a miss (a broken cache costs a device call, never
  a request);
* **reweight** — ``evaluate_reweight`` approves an all-1.0 bank with
  zero flips (the parity anchor: weighted selection IS plain argmax),
  refuses a skewed bank on flip rate, and refuses to misalign weights
  across anchor rows;
* **prefix share** — duplicate texts alias row slots in the continuous
  open pack (zero real tokens, pooling gather reads the shared CLS)
  with scores matching the unshared path ≤1e-6, off by default.
"""

import dataclasses
import hashlib
import threading
import time

import numpy as np
import pytest

from memvul_tpu import telemetry
from memvul_tpu.bankops import (
    BankDiff,
    BankStore,
    BankStoreError,
    GateThresholds,
    PromotionRefused,
    evaluate_gate,
    evaluate_reweight,
)
from memvul_tpu.bankops.promote import REASON_FLIP_RATE
from memvul_tpu.data.batching import PackSlotAllocator
from memvul_tpu.resilience import faults
from memvul_tpu.serving import (
    STATUS_ERROR,
    STATUS_OK,
    LoadConfig,
    Replica,
    ReplicaRouter,
    RouterConfig,
    ScoringService,
    ServiceConfig,
    TenantSpecError,
    configure_tenants,
    demote_tenant,
    parse_tenant_spec,
    promote_tenant,
    request_texts,
    rolling_swap,
    run_slo_harness,
    validate_tenant_name,
)
from memvul_tpu.serving.loadgen import fleet_snapshot


@pytest.fixture()
def tel(tmp_path):
    registry = telemetry.configure(run_dir=tmp_path / "run")
    yield registry
    telemetry.reset()
    faults.reset()


# -- fakes: scores are a function of the BANK CONTENT, so serving a
# -- wrong tenant's bank produces a wrong, observable score ------------------

class _CharEncoder:
    """Tokens derived from the text's characters: identical texts get
    identical token sequences (the cache/prefix-share premise) and
    distinct texts get distinct ones."""

    pad_id = 0

    def __init__(self, max_length=8):
        self.max_length = max_length

    def encode_many(self, texts):
        return [
            [(ord(c) % 53) + 2 for c in t[: self.max_length]] or [2]
            for t in texts
        ]


def _bank_base(labels):
    """A deterministic per-bank score offset derived from the anchor
    labels — each distinct bank scores visibly differently."""
    digest = hashlib.sha256("|".join(labels).encode("utf-8")).hexdigest()
    return 0.1 + (int(digest[:4], 16) % 600) / 1000.0


class _TenantPredictor:
    """Minimal predictor surface whose scores embed the served bank's
    identity: ``encode_bank`` writes a label-derived constant into the
    bank array and ``_score_fn`` reads it back, so every response
    proves which tenant's snapshot scored it."""

    def __init__(self, n_anchors=3, rows=4, length=8):
        self.encoder = _CharEncoder(length)
        self.mesh = None
        self.params = None
        self.n_anchors = n_anchors
        self.anchor_labels = [f"A{i}" for i in range(n_anchors)]
        self.anchor_bank = np.zeros((n_anchors, 2), np.float32)
        self.score_trace_count = 0
        self._shapes = [(rows, length)]
        self.device_calls = 0

    def stream_shapes(self):
        return list(self._shapes)

    def encode_bank(self, instances):
        instances = list(instances)
        labels = [inst["meta"]["label"] for inst in instances]
        bank = np.full((len(labels), 2), _bank_base(labels), np.float32)
        return bank, labels, len(labels)

    def warmup_bank_shapes(self, bank):
        pass

    def _score_fn(self, params, sample, bank):
        self.device_calls += 1
        rows = sample["input_ids"].shape[0]
        base = float(bank[0, 0])
        return np.tile(
            base + np.linspace(0.0, 0.05, bank.shape[0], dtype=np.float32),
            (rows, 1),
        )


ORG_A_BANK = [
    {"text1": f"alpha anchor {i}", "meta": {"label": f"ALPHA-{i}"}}
    for i in range(3)
]
ORG_B_BANK = [
    {"text1": f"beta anchor {i}", "meta": {"label": f"BETA-{i}"}}
    for i in range(3)
]
BASE_A = _bank_base([inst["meta"]["label"] for inst in ORG_A_BANK])
BASE_B = _bank_base([inst["meta"]["label"] for inst in ORG_B_BANK])
# every bank's reported "score" is its base + the linspace max
TOP = 0.05


def _make_service(**overrides):
    defaults = dict(max_batch=4, max_wait_ms=1.0, max_queue=1000)
    defaults.update(overrides)
    predictor = _TenantPredictor()
    return predictor, ScoringService(
        predictor, config=ServiceConfig(**defaults)
    )


def _tenant_fleet(n=2, **router_kw):
    overrides = dict(
        max_batch=4, max_wait_ms=1.0, max_queue=1000,
        default_deadline_ms=30000.0,
    )

    def make_factory(i):
        def factory(registry):
            return ScoringService(
                _TenantPredictor(),
                config=ServiceConfig(**overrides),
                registry=registry,
            )
        return factory

    replicas = [
        Replica(i, make_factory(i), telemetry_enabled=True) for i in range(n)
    ]
    router = ReplicaRouter(
        replicas,
        config=RouterConfig(monitor_interval_s=0.05, **router_kw),
    )
    return router, replicas


def _assert_tenant_ledger_sums(counters, tenants=("default", "orga", "orgb")):
    """Multi-tenant mode labels EVERY request, so the per-tenant
    ledgers partition the global counters exactly."""
    for what in ("requests", "served", "errors"):
        per_tenant = sum(
            counters.get(f"serve.{t}.{what}", 0) for t in tenants
        )
        assert per_tenant == counters.get(f"serve.{what}", 0), (
            what, counters,
        )


# -- tenant spec --------------------------------------------------------------

def test_parse_tenant_spec_and_name_validation():
    spec = parse_tenant_spec("orga=/banks/a, orgb=/banks/b,")
    assert spec == {"orga": "/banks/a", "orgb": "/banks/b"}
    assert validate_tenant_name("org-1_x") == "org-1_x"
    for bad in ("Org", "a b", "-lead", "", "x" * 65):
        with pytest.raises(TenantSpecError):
            validate_tenant_name(bad)
    for bad_spec in (
        "orga",                      # no =
        "orga=",                     # empty path
        "Org=/x",                    # charset (names become labels)
        "default=/x",                # reserved for the archive's bank
        "orga=/x,orga=/y",           # duplicate
        "",                          # names no tenants
        ",,",
    ):
        with pytest.raises(TenantSpecError):
            parse_tenant_spec(bad_spec)


# -- isolation on one service -------------------------------------------------

def test_two_tenant_isolation_and_ledger_on_one_service(tel):
    assert BASE_A != BASE_B  # the observable-bleed premise
    predictor, service = _make_service()
    service.swap_bank(ORG_A_BANK, tenant="orga")
    service.swap_bank(ORG_B_BANK, tenant="orgb")

    expected = {"orga": BASE_A, "orgb": BASE_B, "default": 0.0}
    futures = []
    for i in range(8):
        futures.append(("orga", service.submit(f"report {i}", tenant="orga")))
        futures.append(("orgb", service.submit(f"report {i}", tenant="orgb")))
        futures.append(("default", service.submit(f"report {i}")))
    for tenant, future in futures:
        response = future.result(timeout=10)
        assert response["status"] == STATUS_OK
        assert response["score"] == pytest.approx(
            expected[tenant] + TOP, abs=1e-6
        ), tenant
        if tenant != "default":
            prefix = "ALPHA-" if tenant == "orga" else "BETA-"
            assert response["anchor"].startswith(prefix)

    # an unknown tenant errors THAT request only — nothing queued
    ghost = service.submit("x", tenant="ghost").result(timeout=5)
    assert ghost["status"] == STATUS_ERROR and "ghost" in ghost["reason"]

    service.drain()
    counters = tel.snapshot()["counters"]
    for tenant in ("orga", "orgb", "default"):
        assert counters[f"serve.{tenant}.requests"] == 8
        assert counters[f"serve.{tenant}.served"] == 8
    assert counters["serve.ghost.requests"] == 1
    assert counters["serve.ghost.errors"] == 1
    _assert_tenant_ledger_sums(counters, ("default", "orga", "orgb", "ghost"))
    # named swaps emit the per-tenant bank metrics, not the default's
    assert counters["bank.orga.swaps"] == 1
    assert counters["bank.orgb.swaps"] == 1
    gauges = tel.snapshot()["gauges"]
    assert gauges["bank.orga.version"] == 1
    assert gauges["bank.orgb.version"] == 1

    health = service.health_summary()
    assert set(health["tenants"]) == {"orga", "orgb"}
    assert health["tenants"]["orga"]["weighted"] is False
    assert health["bank_version"] == 1  # default bank untouched


def test_bank_resolve_fault_errors_one_request_only(tel):
    predictor, service = _make_service()
    service.swap_bank(ORG_A_BANK, tenant="orga")
    faults.configure("bank.resolve=raise:RuntimeError:resolver down")
    bad = service.submit("r0", tenant="orga").result(timeout=5)
    assert bad["status"] == STATUS_ERROR
    assert "resolver down" in bad["reason"]
    # the clause fired once and disarmed: the next request serves fine
    ok = service.submit("r1", tenant="orga").result(timeout=10)
    assert ok["status"] == STATUS_OK
    service.drain()
    counters = tel.snapshot()["counters"]
    assert counters["serve.errors"] == 1
    assert counters["serve.orga.errors"] == 1
    _assert_tenant_ledger_sums(counters, ("default", "orga"))


# -- fleet: swap isolation + chaos --------------------------------------------

def test_tenant_swap_never_changes_other_tenant_mid_load(tel):
    router, replicas = _tenant_fleet(n=2)
    try:
        rolling_swap(router, ORG_A_BANK, tenant="orga")
        rolling_swap(router, ORG_B_BANK, tenant="orgb")
        default_version = router._active_version

        stop = threading.Event()
        b_responses = []

        def hammer_b():
            i = 0
            while not stop.is_set():
                b_responses.append(
                    router.submit(f"b load {i}", tenant="orgb")
                    .result(timeout=10)
                )
                i += 1

        thread = threading.Thread(target=hammer_b)
        thread.start()
        time.sleep(0.05)
        new_a = [
            {"text1": f"alpha prime {i}", "meta": {"label": f"ALPHA2-{i}"}}
            for i in range(3)
        ]
        rolling_swap(router, new_a, tenant="orga")
        time.sleep(0.05)
        stop.set()
        thread.join(timeout=15)
        assert b_responses and not thread.is_alive()

        # not one B response moved: same bank version, same scores,
        # through the entire A rollout
        assert all(r["status"] == STATUS_OK for r in b_responses)
        assert {r["bank_version"] for r in b_responses} == {1}
        assert {round(r["score"], 6) for r in b_responses} == {
            round(BASE_B + TOP, 6)
        }
        # the fleet's default version (what untagged requests pin to)
        # never advanced
        assert router._active_version == default_version

        # A serves the new bank at its OWN next version
        base_a2 = _bank_base([i["meta"]["label"] for i in new_a])
        rolled = router.submit("post roll", tenant="orga").result(timeout=10)
        assert rolled["bank_version"] == 2
        assert rolled["score"] == pytest.approx(base_a2 + TOP, abs=1e-6)
    finally:
        router.drain()
    snap = fleet_snapshot(replicas)
    assert snap["invariant_ok"], snap
    # per-replica, the per-tenant ledgers partition the replica's own
    # counters — no request is attributed across the tenant boundary
    for replica in replicas:
        _assert_tenant_ledger_sums(replica.registry.snapshot()["counters"])


@pytest.mark.chaos
def test_replica_kill_mid_tenant_swap_recovers_both_banks(tel):
    """The chaos arm: a replica is hard-killed while tenant A's rolling
    swap is in flight.  The monitor restarts it, ``_sync_bank``
    re-rolls BOTH named banks onto the rebuilt member, and the fleet
    ends consistent: A on its new bank everywhere, B untouched."""
    router, replicas = _tenant_fleet(n=2, max_reroutes=3)
    new_a = [
        {"text1": f"alpha prime {i}", "meta": {"label": f"ALPHA2-{i}"}}
        for i in range(3)
    ]
    base_a2 = _bank_base([i["meta"]["label"] for i in new_a])
    try:
        rolling_swap(router, ORG_A_BANK, tenant="orga")
        rolling_swap(router, ORG_B_BANK, tenant="orgb")
        warm = [
            router.submit(f"warm {i}", tenant="orgb").result(timeout=10)
            for i in range(4)
        ]
        assert all(r["status"] == STATUS_OK for r in warm)

        faults.configure("replica.kill.replica-0=raise:RuntimeError:chaos")
        swapper = threading.Thread(
            target=rolling_swap, args=(router, new_a),
            kwargs={"tenant": "orga"},
        )
        swapper.start()
        mid = []
        while swapper.is_alive():
            for tenant in ("orga", "orgb"):
                mid.append(
                    (tenant, router.submit(f"mid {len(mid)}", tenant=tenant)
                     .result(timeout=15))
                )
        swapper.join(timeout=30)
        assert not swapper.is_alive()
        # the swap may finish before the router routes anything to the
        # doomed member — keep driving load until the armed kill lands
        deadline = time.monotonic() + 15
        while (
            time.monotonic() < deadline
            and replicas[0].registry.counter("replica.kills").value == 0
        ):
            for tenant in ("orga", "orgb"):
                mid.append(
                    (tenant, router.submit(f"mid {len(mid)}", tenant=tenant)
                     .result(timeout=15))
                )
        assert replicas[0].registry.counter("replica.kills").value == 1

        # no hang, and — the bleed check — every OK response carries
        # ITS tenant's score (old or new for A, exactly B's for B)
        for tenant, response in mid:
            assert response["status"] in (STATUS_OK, STATUS_ERROR)
            if response["status"] != STATUS_OK:
                continue
            if tenant == "orgb":
                assert response["score"] == pytest.approx(
                    BASE_B + TOP, abs=1e-6
                )
                assert response["bank_version"] == 1
            else:
                assert response["score"] == pytest.approx(
                    BASE_A + TOP, abs=1e-6
                ) or response["score"] == pytest.approx(
                    base_a2 + TOP, abs=1e-6
                )

        # wait out the restart, then prove the rebuilt member serves
        # BOTH tenants' current banks (the _sync_bank re-roll)
        deadline = time.monotonic() + 15
        while time.monotonic() < deadline and replicas[0].restart_count == 0:
            time.sleep(0.02)
        assert replicas[0].restart_count >= 1
        deadline = time.monotonic() + 10
        while (
            time.monotonic() < deadline
            and replicas[0].state != "healthy"
        ):
            time.sleep(0.02)
        for replica in replicas:
            got_a = replica.service.submit(
                "direct a", tenant="orga"
            ).result(timeout=10)
            got_b = replica.service.submit(
                "direct b", tenant="orgb"
            ).result(timeout=10)
            assert got_a["status"] == STATUS_OK, replica.name
            assert got_a["score"] == pytest.approx(base_a2 + TOP, abs=1e-6)
            assert got_a["bank_version"] == 2
            assert got_b["status"] == STATUS_OK, replica.name
            assert got_b["score"] == pytest.approx(BASE_B + TOP, abs=1e-6)
            assert got_b["bank_version"] == 1
    finally:
        router.drain()
    snap = fleet_snapshot(replicas)
    assert snap["invariant_ok"], snap


# -- startup plane: configure_tenants + promote/demote ------------------------

ANCHORS_V1 = {
    "CWE-79": "cross site scripting description",
    "CWE-89": "sql injection description",
    "CWE-22": "path traversal description",
}


def test_configure_tenants_installs_active_banks(tmp_path, tel):
    store_a = BankStore(tmp_path / "orga")
    store_a.create(ANCHORS_V1, source="build")
    store_b = BankStore(tmp_path / "orgb")
    store_b.create(ANCHORS_V1, source="build")
    store_b.create({"CWE-502": "deserialization of untrusted data"})
    store_b.set_active("v1")  # ACTIVE wins over latest

    predictor, service = _make_service()
    manager = configure_tenants(
        service, f"orga={tmp_path / 'orga'},orgb={tmp_path / 'orgb'}"
    )
    try:
        assert service.tenant_manager is manager
        assert manager.tenants == ("orga", "orgb")
        assert manager.live_version("orga") == "v1"
        assert manager.live_version("orgb") == "v1"
        banks = service.tenant_banks()
        assert set(banks) == {"default", "orga", "orgb"}
        assert banks["orga"].store_version == "v1"
        assert banks["orga"].source == "startup"
        summary = manager.summary()
        assert summary["tenants"] == [
            {"tenant": "orga", "store_version": "v1"},
            {"tenant": "orgb", "store_version": "v1"},
        ]
        assert service.health_summary()["tenancy"] == summary
        with pytest.raises(TenantSpecError):
            manager.store("ghost")
        # an empty store refuses loudly at startup
        with pytest.raises(TenantSpecError):
            configure_tenants(service, f"empty={tmp_path / 'empty'}")
    finally:
        service.drain()


def test_promote_and_demote_tenant_scoped(tmp_path, tel):
    store = BankStore(tmp_path / "orga")
    store.create(ANCHORS_V1, source="build")
    diff = BankDiff.from_json([
        {"op": "add", "category": "CWE-502",
         "description": "deserialization of untrusted data"},
    ])
    store.derive("v1", diff, note="rotate")
    store.set_active("v1")  # serve v1; v2 is the promotion candidate

    predictor, service = _make_service()
    manager = configure_tenants(service, f"orga={tmp_path / 'orga'}")
    try:
        v1_score = service.submit("r", tenant="orga").result(timeout=10)
        shadow = {"sampled": 200, "flips": 0, "flip_rate": 0.0}
        approved = evaluate_gate(
            {"auc": 0.9, "f1": 0.8}, {"auc": 0.9, "f1": 0.8},
            shadow, candidate="v2", parent="v1",
        )
        serving_version = promote_tenant(
            service, manager, "orga", approved, registry=tel
        )
        assert serving_version == 2
        assert manager.live_version("orga") == "v2"
        assert store.active()["version"] == "v2"
        assert store.promotions()[-1]["tenant"] == "orga"
        v2_score = service.submit("r", tenant="orga").result(timeout=10)
        assert v2_score["bank_version"] == 2
        assert v2_score["score"] != v1_score["score"]  # 4-anchor bank
        # the default tenant's bank never moved
        assert service.bank_version == 1

        out = demote_tenant(service, manager, "orga", registry=tel)
        assert out == {"version": "v1", "serving_version": 3}
        assert manager.live_version("orga") == "v1"
        restored = service.submit("r", tenant="orga").result(timeout=10)
        assert restored["score"] == v1_score["score"]

        refused = evaluate_gate(
            {"auc": 0.9, "f1": 0.8}, {"auc": 0.5, "f1": 0.2},
            shadow, candidate="v2", parent="v1",
        )
        with pytest.raises(PromotionRefused):
            promote_tenant(service, manager, "orga", refused, registry=tel)
        assert manager.live_version("orga") == "v1"  # refusal changes nothing
        assert store.promotions()[-1]["kind"] == "promotion_refused"
    finally:
        service.drain()


# -- admission cache ----------------------------------------------------------

def test_cache_hit_is_bitwise_identical_and_skips_device(tel):
    predictor, service = _make_service(cache_capacity=8)
    try:
        cold = service.submit("dup report").result(timeout=10)
        assert cold["status"] == STATUS_OK and "cached" not in cold
        calls = predictor.device_calls
        warm = service.submit("dup report").result(timeout=10)
        assert warm["status"] == STATUS_OK and warm["cached"] is True
        assert predictor.device_calls == calls  # the hit never dispatched
        for field in ("predict", "score", "anchor", "bank_version"):
            assert warm[field] == cold[field], field
        # a DIFFERENT text is a miss, not a false hit
        other = service.submit("dup report!").result(timeout=10)
        assert "cached" not in other
    finally:
        service.drain()
    counters = tel.snapshot()["counters"]
    assert counters["cache.hits"] == 1
    assert counters["cache.misses"] == 2
    assert counters["cache.tokens_saved"] >= 1
    # a hit is SERVED: the exact-counter invariant keeps summing
    assert counters["serve.served"] == 3 == counters["serve.requests"]


def test_cache_lru_eviction_is_bounded(tel):
    predictor, service = _make_service(cache_capacity=1)
    try:
        for text in ("a report", "b report", "a report"):
            assert service.submit(text).result(timeout=10)["status"] == STATUS_OK
        assert len(service.admission_cache) == 1
    finally:
        service.drain()
    counters = tel.snapshot()["counters"]
    assert counters.get("cache.hits", 0) == 0  # "a" was evicted by "b"
    assert counters["cache.misses"] == 3
    assert counters["cache.evictions"] >= 1
    assert tel.snapshot()["gauges"]["cache.size"] == 1


def test_cache_invalidation_is_per_tenant_on_swap(tel):
    predictor, service = _make_service(cache_capacity=8)
    try:
        service.swap_bank(ORG_A_BANK, tenant="orga")
        service.swap_bank(ORG_B_BANK, tenant="orgb")
        for tenant in ("orga", "orgb"):
            first = service.submit("t", tenant=tenant).result(timeout=10)
            assert "cached" not in first
            assert service.submit("t", tenant=tenant).result(timeout=10)[
                "cached"
            ] is True
        # swap ONLY orgb: orga's entry must survive, orgb's must not
        new_b = [
            {"text1": f"beta prime {i}", "meta": {"label": f"BETA2-{i}"}}
            for i in range(3)
        ]
        service.swap_bank(new_b, tenant="orgb")
        still_a = service.submit("t", tenant="orga").result(timeout=10)
        assert still_a["cached"] is True
        fresh_b = service.submit("t", tenant="orgb").result(timeout=10)
        assert "cached" not in fresh_b
        assert fresh_b["bank_version"] == 2
        assert fresh_b["score"] == pytest.approx(
            _bank_base([i["meta"]["label"] for i in new_b]) + TOP, abs=1e-6
        )
    finally:
        service.drain()
    counters = tel.snapshot()["counters"]
    assert counters["cache.invalidations"] >= 1


def test_cache_lookup_fault_degrades_to_miss(tel):
    predictor, service = _make_service(cache_capacity=8)
    try:
        first = service.submit("c report").result(timeout=10)
        faults.configure("cache.lookup=raise:RuntimeError:cache on fire")
        degraded = service.submit("c report").result(timeout=10)
        # a broken cache costs a device call, never the request
        assert degraded["status"] == STATUS_OK
        assert "cached" not in degraded
        assert degraded["score"] == first["score"]
        # the clause disarmed: the next repeat hits again
        assert service.submit("c report").result(timeout=10)["cached"] is True
    finally:
        service.drain()
    counters = tel.snapshot()["counters"]
    assert counters["cache.errors"] == 1
    assert counters["cache.hits"] == 1
    assert counters["serve.served"] == 3 == counters["serve.requests"]


def test_slo_harness_dedup_load_reports_cache_block(tel):
    predictor, service = _make_service(
        cache_capacity=64, default_deadline_ms=30000.0
    )
    try:
        record = run_slo_harness(
            service,
            [f"text {i}" for i in range(16)],
            config=LoadConfig(
                pattern="dedup", requests=64, rps=2000.0,
                dedup_unique=4, seed=3,
            ),
        )
    finally:
        service.drain()
    assert record["load"]["outcomes"]["hang"] == 0
    cache = record["cache"]
    assert cache["hits"] > 0
    assert cache["hits"] + cache["misses"] == 64
    assert cache["hit_rate"] == pytest.approx(cache["hits"] / 64, abs=1e-4)
    assert cache["device_calls_avoided"] == cache["hits"]
    # 4 unique texts: misses are the uniques plus the handful of
    # same-text requests racing the first store of their batch window
    assert cache["hit_rate"] >= 0.5


# -- loadgen dedup pattern ----------------------------------------------------

def test_loadgen_dedup_pattern_is_seeded_and_skewed():
    texts = [f"text {i}" for i in range(50)]
    cfg = LoadConfig(pattern="dedup", requests=200, dedup_unique=8, seed=7)
    first = request_texts(cfg, texts)
    assert first == request_texts(cfg, texts)  # deterministic in the seed
    assert len(first) == 200
    assert set(first) <= set(texts[:8])  # draws only from the head pool
    counts = sorted(
        (first.count(t) for t in set(first)), reverse=True
    )
    assert counts[0] > 200 // 8  # Zipf-ish head skew, repeats guaranteed
    assert request_texts(
        dataclasses.replace(cfg, seed=8), texts
    ) != first
    prefixed = request_texts(
        dataclasses.replace(cfg, template_prefix="TPL: "), texts
    )
    assert all(t.startswith("TPL: ") for t in prefixed)
    # non-dedup patterns keep the pre-existing round-robin schedule
    assert request_texts(
        LoadConfig(pattern="poisson", requests=5), texts
    ) == texts[:5]
    with pytest.raises(ValueError):
        request_texts(cfg, [])


# -- reweight gate ------------------------------------------------------------

class _MatrixPredictor:
    """``evaluate_reweight`` surface: a fixed per-text probability row."""

    def __init__(self, probs):
        self.probs = {t: np.asarray(row, np.float32) for t, row in probs.items()}

    def encode_bank(self, instances):
        labels = [inst["meta"]["label"] for inst in instances]
        return np.zeros((len(labels), 2), np.float32), labels, len(labels)

    def warmup_bank_shapes(self, bank):
        pass

    def score_texts(self, texts, bank, n_anchors):
        return np.stack([self.probs[t] for t in texts])


def _reweight_fixture(tmp_path):
    store = BankStore(tmp_path / "banks")
    store.create(ANCHORS_V1, source="build")
    diff = BankDiff.from_json([
        {"op": "reweight", "category": "CWE-89", "weight": 4.0},
    ])
    store.derive("v1", diff, note="boost sqli")
    labels = [inst["meta"]["label"] for inst in store.instances("v1")]
    strong, boosted = labels.index("CWE-79"), labels.index("CWE-89")

    def row(values):
        out = [0.1] * len(labels)
        for idx, v in values.items():
            out[idx] = v
        return out

    probs, instances = {}, []
    for i in range(4):  # positives: plain winner 0.6, boosted anchor 0.2
        text = f"pos {i}"
        probs[text] = row({strong: 0.6, boosted: 0.2})
        instances.append({"text1": text, "meta": {"label": "CWE-79"}})
    for i in range(4):  # negatives: everything low
        text = f"neg {i}"
        probs[text] = row({strong: 0.2, boosted: 0.05})
        instances.append({"text1": text, "meta": {"label": "neg"}})
    return store, _MatrixPredictor(probs), instances


def test_reweight_all_ones_is_parity_anchor(tmp_path):
    store, predictor, instances = _reweight_fixture(tmp_path)
    decision = evaluate_reweight(
        predictor, store, "v1", instances,
        thresholds=GateThresholds(min_shadow_samples=1),
    )
    assert decision.approved, decision.reasons
    assert decision.candidate == "v1+reweight"
    shadow = decision.metrics["shadow"]
    assert shadow["flips"] == 0
    assert shadow["anchor_changes"] == 0
    assert shadow["max_abs_delta"] == 0.0  # weighted selection == argmax
    assert decision.metrics["active"] == decision.metrics["candidate"]


def test_reweight_skewed_weights_flip_and_refuse(tmp_path):
    store, predictor, instances = _reweight_fixture(tmp_path)
    # v2 boosts CWE-89 4x: every positive's weighted winner moves to the
    # 0.2 anchor, crossing the 0.5 decision threshold — 4 flips / 8
    decision = evaluate_reweight(
        predictor, store, "v2", instances,
        thresholds=GateThresholds(min_shadow_samples=1),
    )
    assert not decision.approved
    assert REASON_FLIP_RATE in [r["code"] for r in decision.reasons]
    shadow = decision.metrics["shadow"]
    assert shadow["flips"] == 4
    assert shadow["anchor_changes"] == 4
    assert shadow["max_abs_delta"] == pytest.approx(0.4, abs=1e-6)


def test_reweight_refuses_misaligned_weights(tmp_path):
    store, predictor, instances = _reweight_fixture(tmp_path)

    class _Misaligned(_MatrixPredictor):
        def encode_bank(self, inner):
            inner = list(inner)
            labels = [inst["meta"]["label"] for inst in inner][:-1]
            return np.zeros((len(labels), 2), np.float32), labels, len(labels)

    with pytest.raises(BankStoreError):
        evaluate_reweight(
            _Misaligned(predictor.probs), store, "v1", instances,
            thresholds=GateThresholds(min_shadow_samples=1),
        )


def test_weighted_bank_serves_weighted_winner_raw_score(tel):
    """End to end: a served response's winner uses the weighted argmax,
    its reported score is the RAW probability of that winner — and a
    weight-1.0 bank is bitwise the unweighted path (weights=None)."""
    predictor, service = _make_service()
    try:
        plain = [
            {"text1": f"a{i}", "meta": {"label": f"W-{i}", "weight": 1.0}}
            for i in range(3)
        ]
        service.swap_bank(plain, tenant="orga")
        assert service.tenant_banks()["orga"].weights is None
        response = service.submit("r", tenant="orga").result(timeout=10)
        # linspace scoring: the last anchor wins unweighted
        assert response["anchor"] == "W-2"

        boosted = [
            {"text1": f"a{i}",
             "meta": {"label": f"W-{i}", "weight": 9.0 if i == 0 else 1.0}}
            for i in range(3)
        ]
        service.swap_bank(boosted, tenant="orga")
        bank = service.tenant_banks()["orga"]
        assert bank.weights is not None
        weighted = service.submit("r", tenant="orga").result(timeout=10)
        assert weighted["anchor"] == "W-0"  # the boosted anchor wins...
        # ...but the reported score is its raw probability, not 9x it
        assert weighted["score"] == pytest.approx(
            weighted["predict"]["W-0"], abs=0
        )
        assert weighted["score"] < weighted["predict"]["W-2"]
        assert service.health_summary()["tenants"]["orga"]["weighted"] is True
    finally:
        service.drain()


# -- prefix share -------------------------------------------------------------

def test_pack_slot_allocator_aliases_exact_duplicates():
    shared = PackSlotAllocator(
        token_budget=16, max_rows=8, pad_id=0, share_prefixes=True
    )
    seq = [5, 6, 7]
    assert (shared.admit(seq), shared.admit(seq), shared.admit([8, 9])) == (
        0, 1, 2,
    )
    assert shared.rows_aliased == 1 and shared.tokens_aliased == 3
    assert shared.real_tokens == 5  # the duplicate wrote NOTHING
    sample = shared.sample()
    # the aliased row's pooling gather reads the original's CLS slot
    assert sample["row_starts"][1] == sample["row_starts"][0]
    assert sample["row_starts"][2] != sample["row_starts"][0]
    # a reset recycles the segment index: the next pack re-writes
    shared.reset()
    assert shared.admit(seq) == 0
    assert shared.real_tokens == 3
    assert shared.rows_aliased == 1  # cumulative counter, no new alias

    # an alias needs only a ROW slot — it is admitted even with the
    # token budget exhausted
    tight = PackSlotAllocator(
        token_budget=4, max_rows=4, pad_id=0, share_prefixes=True
    )
    assert tight.admit([1, 2, 3, 4]) == 0
    assert tight.fits([1, 2, 3, 4]) and tight.admit([1, 2, 3, 4]) == 1
    assert tight.admit([9]) is None  # real tokens no longer fit

    # off by default: every row pays its tokens
    plain = PackSlotAllocator(token_budget=16, max_rows=8, pad_id=0)
    plain.admit(seq)
    plain.admit(seq)
    assert plain.rows_aliased == 0 and plain.real_tokens == 6


class _ContinuousFake:
    """Continuous-dispatch predictor whose score is a function of the
    POOLED token each row's ``row_starts`` points at — an aliasing bug
    (wrong gather offset) changes the score, so the ≤1e-6 parity
    assertion is sensitive to the segment-table bookkeeping."""

    score_impl = "continuous"

    def __init__(self, n_anchors=3, rows=8, budget=64, length=8):
        self.encoder = _CharEncoder(length)
        self.mesh = None
        self.params = None
        self.n_anchors = n_anchors
        self.anchor_labels = [f"A{i}" for i in range(n_anchors)]
        self.anchor_bank = np.zeros((n_anchors, 2), np.float32)
        self.score_trace_count = 0
        self._rows = rows
        self._budget = budget
        self._shapes = [(rows, length)]
        self.started = threading.Event()
        self.hold = threading.Event()

    def stream_shapes(self):
        return list(self._shapes)

    def ragged_shape(self):
        return (self._budget, self._rows)

    def encode_bank(self, instances):
        instances = list(instances)
        labels = [inst["meta"]["label"] for inst in instances]
        bank = np.full((len(labels), 2), _bank_base(labels), np.float32)
        return bank, labels, len(labels)

    def warmup_bank_shapes(self, bank):
        pass

    def _ragged_score_fn(self, params, sample, bank):
        self.started.set()
        assert self.hold.wait(timeout=30), "test forgot to release hold"
        ids = sample["input_ids"][0]
        starts = sample["row_starts"]
        base = float(bank[0, 0])
        out = np.zeros((self._rows, bank.shape[0]), np.float32)
        for r in range(self._rows):
            pooled = float(ids[int(starts[r])]) / 1000.0
            out[r] = base + pooled + np.linspace(
                0.0, 0.05, bank.shape[0], dtype=np.float32
            )
        return out


def _run_continuous(prefix_share, texts):
    fake = _ContinuousFake()
    fake.hold.set()  # warmup request flows straight through
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=8, max_wait_ms=1.0, prefix_share=prefix_share,
        ),
    )
    try:
        # block the device on a warmup pack so the real texts accumulate
        # into ONE open pack (aliasing only applies within a pack)
        fake.hold.clear()
        fake.started.clear()
        warm = service.submit("warmup text")
        assert fake.started.wait(timeout=10)
        futures = [service.submit(t) for t in texts]
        time.sleep(0.1)  # let admission alias/write every row
        fake.hold.set()
        warm.result(timeout=10)
        return [f.result(timeout=10) for f in futures]
    finally:
        service.drain()


def test_prefix_share_parity_and_measured_savings(tel):
    texts = ["template body"] * 4 + ["unique one", "other text"]
    unshared = _run_continuous(False, texts)
    counters = tel.snapshot()["counters"]
    assert "serve.prefix_rows_aliased" not in counters  # off by default
    shared = _run_continuous(True, texts)
    assert all(r["status"] == STATUS_OK for r in unshared + shared)
    for a, b in zip(unshared, shared):
        assert abs(a["score"] - b["score"]) <= 1e-6
        for label in a["predict"]:
            assert abs(a["predict"][label] - b["predict"][label]) <= 1e-6
    # identical texts share one row's tokens — the measured win
    counters = tel.snapshot()["counters"]
    assert counters["serve.prefix_rows_aliased"] >= 3
    assert counters["serve.prefix_tokens_saved"] >= 3
