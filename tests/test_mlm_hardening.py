"""MLM subsystem hardening: real gradient accumulation, checkpoint/resume,
refuse-to-clobber, and the vectorized whole-word-mask collator.

Reference semantics: run_mlm_wwm.py — batch 16 × accum 2 schedule
(further_pretrain.json), output-dir guard (run_mlm_wwm.py:190-196),
DataCollatorForWholeWordMask's 15% word masking with 80/10/10 token
treatment.
"""

import numpy as np
import pytest

from memvul_tpu.data.synthetic import build_workspace, corpus_texts, generate_corpus
from memvul_tpu.models import BertConfig
from memvul_tpu.pretrain.mlm import (
    IGNORE,
    MLMTrainer,
    MLMTrainerConfig,
    continuation_flags,
    whole_word_mask,
)


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("mlmh"), seed=11)


@pytest.fixture(scope="module")
def corpus_file(tmp_path_factory):
    reports, _ = generate_corpus(seed=5)
    path = tmp_path_factory.mktemp("corpus") / "mlm.txt"
    path.write_text("\n".join(corpus_texts(reports)))
    return str(path)


def _tiny_cfg(ws, **kw):
    base = dict(
        batch_size=4, grad_accum=2, max_length=32, num_epochs=2,
        steps_per_epoch=3, learning_rate=3e-3, warmup_steps=2,
    )
    base.update(kw)
    return MLMTrainerConfig(**base)


# -- gradient accumulation -----------------------------------------------------

def test_grad_accum_shapes_microbatch_stacks(ws):
    trainer = MLMTrainer(
        BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size),
        ws["tokenizer"], _tiny_cfg(ws, grad_accum=3, batch_size=4),
    )
    trainer._encode_corpus(["some words here to mask"] * 40)
    ids, mask, labels = next(trainer._batches())
    assert ids.shape == (3, 4, 32)  # [K, B, L]
    assert mask.shape == (3, 4, 32) and labels.shape == (3, 4, 32)


def test_grad_accum_is_actually_applied(ws, corpus_file):
    """grad_accum=2 must consume twice the rows per optimizer step as
    grad_accum=1 — the config field drives real behavior now."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    t1 = MLMTrainer(cfg, ws["tokenizer"], _tiny_cfg(ws, grad_accum=1))
    t2 = MLMTrainer(cfg, ws["tokenizer"], _tiny_cfg(ws, grad_accum=2))
    lines = ["alpha beta gamma delta"] * 64
    t1._encode_corpus(lines)
    t2._encode_corpus(lines)
    s1 = next(t1._batches())[0]
    s2 = next(t2._batches())[0]
    assert s1.shape[0] * s1.shape[1] == 4
    assert s2.shape[0] * s2.shape[1] == 8
    out = t2.train(corpus_file)
    assert np.isfinite(out["final_loss"])


# -- checkpoint / resume -------------------------------------------------------

def test_mlm_resume_continues_from_saved_epoch(ws, corpus_file, tmp_path):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    out_dir = str(tmp_path / "mlm_out")
    t1 = MLMTrainer(
        cfg, ws["tokenizer"], _tiny_cfg(ws, num_epochs=2, output_dir=out_dir)
    )
    r1 = t1.train(corpus_file)
    assert len(r1["history"]) == 2

    # a fresh trainer over the same dir resumes after epoch 1 and runs
    # only the remaining epochs
    t2 = MLMTrainer(
        cfg, ws["tokenizer"], _tiny_cfg(ws, num_epochs=4, output_dir=out_dir)
    )
    r2 = t2.train(corpus_file)
    assert t2.start_epoch == 2  # resumed, not restarted
    assert len(r2["history"]) == 2  # epochs 2 and 3 only
    # optimizer step counter carried over
    assert t2.step > t1.step


def test_mlm_refuses_to_clobber_non_checkpoint_dir(ws, tmp_path):
    out = tmp_path / "occupied"
    out.mkdir()
    (out / "precious.txt").write_text("do not delete")
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    with pytest.raises(ValueError, match="not empty"):
        MLMTrainer(
            cfg, ws["tokenizer"], _tiny_cfg(ws, output_dir=str(out))
        )
    # explicit overwrite goes through
    MLMTrainer(
        cfg, ws["tokenizer"],
        _tiny_cfg(ws, output_dir=str(out), overwrite_output_dir=True),
    )


# -- tokenize-once pipeline ----------------------------------------------------

def test_mlm_tokenizes_corpus_only_once(ws, corpus_file, monkeypatch):
    """The packed token cache means each corpus line is tokenized exactly
    once for the WHOLE run — epochs after the first only shuffle + mask
    (reference tokenizes once via datasets.map, run_mlm_wwm.py:322-333).
    Counts texts through BOTH tokenizer entry points (the corpus pass
    goes through the parallel ``encode_many``)."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    t = MLMTrainer(cfg, ws["tokenizer"], _tiny_cfg(ws, num_epochs=3))
    n_lines = sum(
        1 for l in open(corpus_file, encoding="utf-8") if l.strip()
    )
    calls = {"n": 0}
    real_encode = t.tokenizer.encode
    real_encode_many = t.tokenizer.encode_many

    def counting(text, **kw):
        calls["n"] += 1
        return real_encode(text, **kw)

    def counting_many(texts, **kw):
        calls["n"] += len(texts)
        return real_encode_many(texts, **kw)

    monkeypatch.setattr(t.tokenizer, "encode", counting)
    monkeypatch.setattr(t.tokenizer, "encode_many", counting_many)
    t.train(corpus_file)
    assert calls["n"] == n_lines


def test_mlm_loop_drains_losses_in_windows(ws, corpus_file):
    """sync_every=1 and a large window must yield the same history."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    t1 = MLMTrainer(cfg, ws["tokenizer"], _tiny_cfg(ws, num_epochs=1, sync_every=1))
    t2 = MLMTrainer(cfg, ws["tokenizer"], _tiny_cfg(ws, num_epochs=1, sync_every=64))
    r1 = t1.train(corpus_file)
    r2 = t2.train(corpus_file)
    np.testing.assert_allclose(r1["history"], r2["history"], rtol=1e-6)


# -- vectorized whole-word masking --------------------------------------------

def _mask_setup(ws, n=256, length=48, seed=7):
    tok = ws["tokenizer"]
    rng = np.random.default_rng(seed)
    texts = [
        " ".join(rng.choice(["vulnerability", "overflow", "parser",
                             "authentication", "renderer", "injection"], 8))
        for _ in range(n)
    ]
    ids = np.full((n, length), tok.pad_id, np.int32)
    mask = np.zeros_like(ids)
    for i, t in enumerate(texts):
        seq = tok.encode(t, max_length=length)
        ids[i, : len(seq)] = seq
        mask[i, : len(seq)] = 1
    return tok, ids, mask, rng


def test_wwm_masking_statistics(ws):
    """~15% of words selected; of selected tokens ~80% become [MASK],
    ~10% random, ~10% unchanged (HF collator behavior)."""
    tok, ids, mask, rng = _mask_setup(ws)
    flags = continuation_flags(tok)
    special = [tok.pad_id, tok.cls_id, tok.sep_id]
    masked, labels = whole_word_mask(
        ids, mask, rng, tok.mask_id, tok.vocab_size, flags, special, 0.15
    )
    chosen = labels != IGNORE
    frac_tokens = chosen.sum() / (mask.sum() - 2 * len(ids))  # minus CLS/SEP
    assert 0.10 < frac_tokens < 0.25
    is_masked = (masked == tok.mask_id) & chosen
    unchanged = (masked == ids) & chosen
    assert 0.70 < is_masked.sum() / chosen.sum() < 0.90
    assert 0.04 < unchanged.sum() / chosen.sum() < 0.18
    # specials and padding never masked
    assert not chosen[ids == tok.cls_id].any()
    assert not chosen[ids == tok.sep_id].any()
    assert not chosen[mask == 0].any()
    # untouched positions keep their ids
    np.testing.assert_array_equal(masked[~chosen], ids[~chosen])


def test_wwm_whole_words_move_together(ws):
    """Every ## continuation shares its head's fate (the whole-word
    property the reference collator exists for)."""
    tok, ids, mask, rng = _mask_setup(ws, n=64, seed=9)
    flags = continuation_flags(tok)
    special = [tok.pad_id, tok.cls_id, tok.sep_id]
    _, labels = whole_word_mask(
        ids, mask, rng, tok.mask_id, tok.vocab_size, flags, special, 0.15
    )
    chosen = labels != IGNORE
    B, L = ids.shape
    for b in range(B):
        for i in range(1, L):
            if mask[b, i] and flags[ids[b, i]] and mask[b, i - 1] and not (
                ids[b, i - 1] in special
            ):
                assert chosen[b, i] == chosen[b, i - 1], (b, i)


def test_wwm_every_row_with_words_gets_a_mask(ws):
    tok, ids, mask, rng = _mask_setup(ws, n=32, seed=3)
    flags = continuation_flags(tok)
    special = [tok.pad_id, tok.cls_id, tok.sep_id]
    _, labels = whole_word_mask(
        ids, mask, rng, tok.mask_id, tok.vocab_size, flags, special, 0.15
    )
    assert ((labels != IGNORE).sum(axis=1) >= 1).all()


def test_wwm_empty_and_special_only_rows(ws):
    tok = ws["tokenizer"]
    rng = np.random.default_rng(0)
    flags = continuation_flags(tok)
    ids = np.array([[tok.cls_id, tok.sep_id, tok.pad_id, tok.pad_id]], np.int32)
    mask = np.array([[1, 1, 0, 0]], np.int32)
    masked, labels = whole_word_mask(
        ids, mask, rng, tok.mask_id, tok.vocab_size, flags,
        [tok.pad_id, tok.cls_id, tok.sep_id], 0.15,
    )
    np.testing.assert_array_equal(masked, ids)
    assert (labels == IGNORE).all()


def test_grad_accum_tail_stack_not_diluted(ws):
    """An epoch-tail stack containing empty (all-padding) microbatches must
    average loss/grads over REAL microbatches only — 1 real + 2 empty at
    grad_accum=3 gives the same update magnitude as the real batch alone."""
    import jax

    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    t3 = MLMTrainer(cfg, ws["tokenizer"], _tiny_cfg(ws, grad_accum=3))
    t1 = MLMTrainer(cfg, ws["tokenizer"], _tiny_cfg(ws, grad_accum=1))
    # identical initial params by construction (same seed)
    lines = ["alpha beta gamma delta"] * 4  # one microbatch worth of rows
    t1._encode_corpus(lines)
    ids1, mask1, labels1 = next(t1._batches())
    # tail stack: the single real microbatch plus 2 empty ones
    pad = ws["tokenizer"].pad_id
    ids3 = np.concatenate([ids1, np.full_like(ids1, pad), np.full_like(ids1, pad)])
    mask3 = np.concatenate([mask1, np.zeros_like(mask1), np.zeros_like(mask1)])
    from memvul_tpu.pretrain.mlm import IGNORE as IG
    labels3 = np.concatenate([labels1, np.full_like(labels1, IG), np.full_like(labels1, IG)])
    # fresh keys per call: the jitted step donates its rng argument
    p3, _, _, loss3 = t3._train_step(
        t3.params, t3.opt_state, jax.random.PRNGKey(0), ids3, mask3, labels3
    )
    p1, _, _, loss1 = t1._train_step(
        t1.params, t1.opt_state, jax.random.PRNGKey(0), ids1, mask1, labels1
    )
    # loss not diluted by the empty microbatches
    np.testing.assert_allclose(float(loss3), float(loss1), rtol=1e-5)
