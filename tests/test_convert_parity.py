"""Logit-level parity: HF PyTorch BertModel vs the in-repo Flax encoder
through the weight converter (SURVEY §7 'hard parts' — the F1-parity
oracle)."""

import numpy as np
import pytest

import jax

from memvul_tpu.models import BertConfig, BertEncoder, BertPooler, MemoryModel
from memvul_tpu.models.convert import convert_bert_state_dict, load_into_classifier

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture(scope="module")
def hf_bert():
    hf_cfg = transformers.BertConfig(
        vocab_size=512,
        hidden_size=64,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
        max_position_embeddings=128,
        hidden_dropout_prob=0.0,
        attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(0)
    model = transformers.BertModel(hf_cfg).eval()
    return model


@pytest.fixture(scope="module")
def inputs():
    rng = np.random.default_rng(0)
    ids = rng.integers(5, 500, size=(3, 24)).astype(np.int32)
    mask = np.ones_like(ids)
    mask[:, 20:] = 0
    return ids, mask


CFG = BertConfig(
    vocab_size=512, hidden_size=64, num_layers=2, num_heads=4,
    intermediate_size=128, max_position_embeddings=128,
)


def torch_forward(hf_bert, ids, mask):
    with torch.no_grad():
        out = hf_bert(
            input_ids=torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        )
    return out.last_hidden_state.numpy(), out.pooler_output.numpy()


def test_encoder_logit_parity(hf_bert, inputs):
    ids, mask = inputs
    hf_hidden, _ = torch_forward(hf_bert, ids, mask)

    bert_subtree, _ = convert_bert_state_dict(hf_bert.state_dict(), CFG)
    enc = BertEncoder(CFG)
    ours = enc.apply({"params": bert_subtree}, ids, mask)
    ours = np.asarray(ours)
    # compare only unmasked positions (masked positions are junk both ways)
    real = mask.astype(bool)
    np.testing.assert_allclose(ours[real], hf_hidden[real], rtol=2e-4, atol=2e-5)


def test_scan_layers_parity(hf_bert, inputs):
    ids, mask = inputs
    hf_hidden, _ = torch_forward(hf_bert, ids, mask)
    cfg = CFG.replace(scan_layers=True)
    bert_subtree, _ = convert_bert_state_dict(hf_bert.state_dict(), cfg)
    ours = np.asarray(BertEncoder(cfg).apply({"params": bert_subtree}, ids, mask))
    real = mask.astype(bool)
    np.testing.assert_allclose(ours[real], hf_hidden[real], rtol=2e-4, atol=2e-5)


def test_pooler_parity(hf_bert, inputs):
    ids, mask = inputs
    _, hf_pooled = torch_forward(hf_bert, ids, mask)
    bert_subtree, pooler = convert_bert_state_dict(hf_bert.state_dict(), CFG)
    enc_out = BertEncoder(CFG).apply({"params": bert_subtree}, ids, mask)
    ours = np.asarray(BertPooler(CFG).apply({"params": pooler}, enc_out))
    np.testing.assert_allclose(ours, hf_pooled, rtol=2e-4, atol=2e-5)


def test_load_into_classifier_replaces_encoder(hf_bert):
    model = MemoryModel(CFG)
    d = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), d, d)
    loaded = load_into_classifier(params, hf_bert.state_dict(), CFG)
    word = loaded["params"]["bert"]["embeddings"]["word_embeddings"]["embedding"]
    hf_word = hf_bert.state_dict()["embeddings.word_embeddings.weight"].numpy()
    np.testing.assert_array_equal(np.asarray(word), hf_word)
    # non-encoder params untouched
    np.testing.assert_array_equal(
        np.asarray(loaded["params"]["pair_kernel"]),
        np.asarray(params["params"]["pair_kernel"]),
    )


def test_converter_shape_mismatch_raises(hf_bert):
    small_cfg = CFG.replace(hidden_size=32, num_heads=2, intermediate_size=64)
    model = MemoryModel(small_cfg)
    d = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), d, d)
    with pytest.raises((ValueError, KeyError)):
        load_into_classifier(params, hf_bert.state_dict(), small_cfg)


def _synthetic_bert_state_dict(
    vocab=30522, hidden=768, layers=12, heads=12, intermediate=3072, max_pos=512
):
    """A bert-base-uncased-shaped state dict (HF BertModel key layout) with
    zero weights — shape/name-level only, no forward needed."""
    sd = {
        "embeddings.word_embeddings.weight": np.zeros((vocab, hidden), np.float32),
        "embeddings.position_embeddings.weight": np.zeros((max_pos, hidden), np.float32),
        "embeddings.token_type_embeddings.weight": np.zeros((2, hidden), np.float32),
        "embeddings.LayerNorm.weight": np.zeros(hidden, np.float32),
        "embeddings.LayerNorm.bias": np.zeros(hidden, np.float32),
        "pooler.dense.weight": np.zeros((hidden, hidden), np.float32),
        "pooler.dense.bias": np.zeros(hidden, np.float32),
    }
    for i in range(layers):
        p = f"encoder.layer.{i}."
        for name in ("query", "key", "value"):
            sd[p + f"attention.self.{name}.weight"] = np.zeros((hidden, hidden), np.float32)
            sd[p + f"attention.self.{name}.bias"] = np.zeros(hidden, np.float32)
        sd[p + "attention.output.dense.weight"] = np.zeros((hidden, hidden), np.float32)
        sd[p + "attention.output.dense.bias"] = np.zeros(hidden, np.float32)
        sd[p + "attention.output.LayerNorm.weight"] = np.zeros(hidden, np.float32)
        sd[p + "attention.output.LayerNorm.bias"] = np.zeros(hidden, np.float32)
        sd[p + "intermediate.dense.weight"] = np.zeros((intermediate, hidden), np.float32)
        sd[p + "intermediate.dense.bias"] = np.zeros(intermediate, np.float32)
        sd[p + "output.dense.weight"] = np.zeros((hidden, intermediate), np.float32)
        sd[p + "output.dense.bias"] = np.zeros(hidden, np.float32)
        sd[p + "output.LayerNorm.weight"] = np.zeros(hidden, np.float32)
        sd[p + "output.LayerNorm.bias"] = np.zeros(hidden, np.float32)
    return sd


@pytest.mark.parametrize("scan", [False, True])
def test_export_round_trips_through_import(hf_bert, scan):
    """flax → HF state dict → flax is the identity (both layer layouts) —
    the export direction of the bidirectional interop."""
    from memvul_tpu.models.convert import export_bert_state_dict

    cfg = CFG.replace(scan_layers=scan)
    sd = {k: v.detach().numpy() for k, v in hf_bert.state_dict().items()}
    bert, pooler = convert_bert_state_dict(sd, cfg)
    exported = export_bert_state_dict(bert, pooler, cfg)
    bert2, pooler2 = convert_bert_state_dict(exported, cfg)
    for (pa, a), (pb, b) in zip(
        jax.tree_util.tree_leaves_with_path((bert, pooler)),
        jax.tree_util.tree_leaves_with_path((bert2, pooler2)),
    ):
        assert pa == pb
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_export_loads_into_hf_bert_strict(hf_bert):
    """The exported dict loads into a real transformers BertModel with
    every model parameter matched — exact HF-name/shape compatibility,
    i.e. the reference's AutoModel.from_pretrained consumes it."""
    from memvul_tpu.models.convert import export_bert_state_dict

    sd = {k: v.detach().numpy() for k, v in hf_bert.state_dict().items()}
    bert, pooler = convert_bert_state_dict(sd, CFG)
    exported = {
        k: torch.tensor(v) for k, v in export_bert_state_dict(bert, pooler, CFG).items()
    }
    fresh = transformers.BertModel(hf_bert.config)
    missing, unexpected = fresh.load_state_dict(exported, strict=False)
    assert not unexpected, unexpected
    # only non-parameter buffers (e.g. position_ids) may be absent
    assert all("position_ids" in k for k in missing), missing
    # and the loaded model reproduces the original's forward exactly
    ids = np.arange(12, dtype=np.int64)[None, :] + 5
    with torch.no_grad():
        a = hf_bert(torch.tensor(ids)).last_hidden_state.numpy()
        b = fresh.eval()(torch.tensor(ids)).last_hidden_state.numpy()
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_export_hf_checkpoint_loads_with_from_pretrained(tmp_path, hf_bert):
    """export_hf_checkpoint writes a dir AutoModel.from_pretrained loads
    offline — the reference's embedder consumes encoders pretrained here
    (custom_PTM_embedder.py:80,95-99)."""
    from memvul_tpu.build import export_hf_checkpoint

    sd = {k: v.detach().numpy() for k, v in hf_bert.state_dict().items()}
    bert, _ = convert_bert_state_dict(sd, CFG)
    out = export_hf_checkpoint(bert, CFG, tmp_path / "hf")
    loaded = transformers.AutoModel.from_pretrained(str(out)).eval()
    ids = torch.tensor(np.arange(12, dtype=np.int64)[None, :] + 5)
    with torch.no_grad():
        a = hf_bert(ids).last_hidden_state.numpy()
        b = loaded(ids).last_hidden_state.numpy()
    np.testing.assert_allclose(a, b, atol=1e-6)


def test_base_geometry_conversion_shapes():
    """A bert-base-sized reference state dict must convert into the
    scan-stacked param tree name-for-name and shape-for-shape, with NO
    forward pass (jax.eval_shape gives the expected tree for free) —
    catches weights.th name/shape drift at the real 12-layer geometry
    (reference layout: model_memory.py:63-73)."""
    cfg = BertConfig.base(vocab_size=30522, scan_layers=True)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": jax.ShapeDtypeStruct((2, 8), np.int32),
        "attention_mask": jax.ShapeDtypeStruct((2, 8), np.int32),
    }
    expected = jax.eval_shape(model.init, jax.random.PRNGKey(0), dummy, dummy)
    bert_subtree, pooler = convert_bert_state_dict(
        _synthetic_bert_state_dict(), cfg
    )
    converted_flat = {
        jax.tree_util.keystr(path): leaf.shape
        for path, leaf in jax.tree_util.tree_leaves_with_path(bert_subtree)
    }
    expected_flat = {
        jax.tree_util.keystr(path): leaf.shape
        for path, leaf in jax.tree_util.tree_leaves_with_path(
            expected["params"]["bert"]
        )
    }
    assert converted_flat == expected_flat
    # scan stacking puts the 12-layer axis in front
    q = bert_subtree["encoder"]["layers"]["layer"]["attention"]["query"]["kernel"]
    assert q.shape == (12, 768, 12, 64)
    # pooler converts too
    pooler_flat = {
        jax.tree_util.keystr(path): leaf.shape
        for path, leaf in jax.tree_util.tree_leaves_with_path(pooler)
    }
    expected_pooler = {
        jax.tree_util.keystr(path): leaf.shape
        for path, leaf in jax.tree_util.tree_leaves_with_path(
            expected["params"]["pooler"]
        )
    }
    assert pooler_flat == expected_pooler
