"""Chaos tests: the fault-injection harness driving the REAL recovery
paths end-to-end (docs/fault_tolerance.md).

The acceptance contracts proven here:

* a trainer SIGTERM'd at a fault-injected step (in-process AND as a real
  subprocess kill) saves a mid-epoch step checkpoint and the resumed run
  reproduces the uninterrupted run's per-step loss trajectory ≤1e-6;
* a corpus-scoring run killed mid-stream resumes from its journal, skips
  completed spans, and emits byte-identical final metrics;
* malformed records dead-letter with reasons and the stream completes;
* an injected Mosaic lowering failure degrades to the "xla" bank match
  with identical scores and one warning.

Everything is CPU + tiny geometry; the one subprocess test is the fast
single-kill variant kept in tier 1 (the multi-kill variant is @slow).
"""

import json
import logging
import os
import subprocess
import sys
import textwrap
from pathlib import Path

import numpy as np
import pytest

import jax

from memvul_tpu.data.readers import MemoryReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.evaluate.measure import cal_metrics
from memvul_tpu.evaluate.predict_memory import SiamesePredictor
from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.resilience import faults
from memvul_tpu.resilience.journal import DeadLetter
from memvul_tpu.resilience.retry import RetryPolicy
from memvul_tpu.training.trainer import MemoryTrainer, TrainerConfig

pytestmark = pytest.mark.chaos

WS_SEED = 5
# one shared trainer geometry: 2 epochs x 3 steps of [2, 4, 32] stacks
TRAIN_STEPS = 6


@pytest.fixture(autouse=True)
def _clean_faults():
    faults.reset()
    yield
    faults.reset()


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("chaos"), seed=WS_SEED)


def make_trainer(ws, out_dir, loss_log, **cfg_kw):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"],
        anchor_path=ws["paths"]["anchors"],
        same_diff_ratio={"same": 2, "diff": 2},
        sample_neg=0.5,
        seed=2021,
    )
    defaults = dict(
        num_epochs=2,
        patience=None,
        batch_size=4,
        grad_accum=2,
        max_length=32,
        eval_batch_size=8,
        eval_max_length=32,
        warmup_steps=2,
        base_lr=1e-3,
        steps_per_epoch=3,
        sync_every=1,
        serialization_dir=str(out_dir) if out_dir else None,
        step_loss_log=str(loss_log) if loss_log else None,
    )
    defaults.update(cfg_kw)
    return MemoryTrainer(
        model,
        params,
        ws["tokenizer"],
        reader,
        train_path=ws["paths"]["train"],
        validation_path=ws["paths"]["validation"],
        anchor_path=ws["paths"]["anchors"],
        config=TrainerConfig(**defaults),
    )


def read_loss_log(path):
    return {
        rec["step"]: rec["loss"]
        for rec in (json.loads(l) for l in Path(path).read_text().splitlines())
    }


@pytest.fixture(scope="module")
def baseline_losses(ws, tmp_path_factory):
    """The uninterrupted run's per-step loss trajectory — the oracle
    every kill/resume variant must reproduce."""
    base = tmp_path_factory.mktemp("baseline")
    trainer = make_trainer(ws, base / "out", base / "loss.jsonl")
    result = trainer.train()
    assert "preempted" not in result
    losses = read_loss_log(base / "loss.jsonl")
    assert sorted(losses) == list(range(TRAIN_STEPS))
    return losses


# -- preemption-safe training -------------------------------------------------


def test_kill_resume_parity_in_process(ws, tmp_path, baseline_losses):
    """SIGTERM at a fault-injected mid-epoch step (delivered via os.kill
    — the production handler path), then resume: the combined per-step
    loss trajectory must match the uninterrupted run ≤1e-6."""
    out, log = tmp_path / "out", tmp_path / "loss.jsonl"
    faults.configure("step.4=sigterm")  # epoch 1, stack 1 of 3
    killed = make_trainer(ws, out, log)
    result = killed.train()
    faults.reset()
    assert result["preempted"] is True
    assert result["preempt_signal"] == 15
    marker = json.loads((out / "PREEMPTED.json").read_text())
    assert marker["step"] == 5  # steps 0..4 completed
    assert sorted(read_loss_log(log)) == [0, 1, 2, 3, 4]

    resumed = make_trainer(ws, out, log)
    result2 = resumed.train()
    assert "preempted" not in result2
    assert not (out / "PREEMPTED.json").exists()  # marker cleared on completion
    assert len(result2["history"]) == 2  # both epochs' metrics present
    combined = read_loss_log(log)
    assert sorted(combined) == list(range(TRAIN_STEPS))  # no step lost or doubled
    for step, loss in baseline_losses.items():
        assert abs(combined[step] - loss) <= 1e-6, step


def test_subprocess_sigterm_kill_then_resume(ws, tmp_path, baseline_losses):
    """The fast single-kill subprocess variant kept in tier 1: a REAL
    process exit through the signal handler (fault-injected SIGTERM via
    MEMVUL_FAULTS in the child env), resumed in this process."""
    child_ws = tmp_path / "ws"
    out, log = tmp_path / "out", tmp_path / "loss.jsonl"
    script = tmp_path / "chaos_child.py"
    script.write_text(textwrap.dedent(f"""
        import json, os, sys
        sys.path.insert(0, {str(Path(__file__).resolve().parents[1])!r})
        sys.path.insert(0, {str(Path(__file__).resolve().parent)!r})
        import conftest  # noqa: F401  # forces JAX onto CPU before jax imports
        from test_fault_tolerance import WS_SEED, make_trainer
        from memvul_tpu.data.synthetic import build_workspace

        ws = build_workspace({str(child_ws)!r}, seed=WS_SEED)
        trainer = make_trainer(ws, {str(out)!r}, {str(log)!r})
        result = trainer.train()
        print(json.dumps({{"preempted": result.get("preempted", False),
                           "step": trainer.step}}))
    """))
    env = dict(
        os.environ,
        JAX_PLATFORMS="cpu",
        MEMVUL_FAULTS="step.2=sigterm",  # mid-epoch 0, real os.kill SIGTERM
    )
    # the doctor/bench child discipline: own session so a hung child is
    # killable as a process group (utils/doctor.py:_check_device_and_mesh)
    from memvul_tpu.bench import _kill_process_group

    proc = subprocess.Popen(
        [sys.executable, str(script)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True, env=env,
        cwd=Path(__file__).resolve().parents[1],
        start_new_session=True,
    )
    try:
        stdout, stderr = proc.communicate(timeout=240)
    except subprocess.TimeoutExpired:
        _kill_process_group(proc, grace=10.0)
        raise
    assert proc.returncode == 0, stderr[-2000:]
    report = json.loads(stdout.strip().splitlines()[-1])
    assert report["preempted"] is True
    assert report["step"] == 3
    assert (out / "PREEMPTED.json").exists()
    assert sorted(read_loss_log(log)) == [0, 1, 2]

    # resume in THIS process against the child's serialization dir (the
    # workspace artifacts are deterministic per seed, so the module ws is
    # byte-identical to the child's)
    resumed = make_trainer(ws, out, log)
    result = resumed.train()
    assert "preempted" not in result
    combined = read_loss_log(log)
    assert sorted(combined) == list(range(TRAIN_STEPS))
    for step, loss in baseline_losses.items():
        assert abs(combined[step] - loss) <= 1e-6, step


@pytest.mark.slow
def test_double_kill_resume_parity(ws, tmp_path, baseline_losses):
    """Two successive preemptions (different epochs) before completion —
    the journald trajectory still matches the uninterrupted run."""
    out, log = tmp_path / "out", tmp_path / "loss.jsonl"
    for spec, expect_steps in [("step.1=sigterm", [0, 1]), ("step.4=sigterm", [2, 3, 4])]:
        faults.configure(spec)
        t = make_trainer(ws, out, log)
        assert t.train()["preempted"] is True
        faults.reset()
    final = make_trainer(ws, out, log)
    assert "preempted" not in final.train()
    combined = read_loss_log(log)
    assert sorted(combined) == list(range(TRAIN_STEPS))
    for step, loss in baseline_losses.items():
        assert abs(combined[step] - loss) <= 1e-6, step


def test_save_every_steps_periodic_checkpoint(ws, tmp_path):
    """save_every_steps writes verified step checkpoints mid-epoch, and a
    completed epoch supersedes them on restore (stale-step guard)."""
    out = tmp_path / "out"
    t = make_trainer(ws, out, None, save_every_steps=2, num_epochs=1)
    t.train()
    ck = t.checkpointer
    assert ck.latest_step_checkpoint() == 2  # saved at global step 2
    assert ck.verify_manifest("steps", 2)
    meta = ck.step_metadata(2)
    assert meta["epoch"] == 0 and meta["stacks_done"] == 2
    # epoch 0 completed after the step save: the fresh trainer must resume
    # AFTER it, not inside it
    t2 = make_trainer(ws, out, None, save_every_steps=2, num_epochs=1)
    assert t2.maybe_restore() is True
    assert t2.epoch == 1 and t2._resume_skip_stacks == 0


# -- resumable corpus scoring -------------------------------------------------


@pytest.fixture(scope="module")
def memory_setup(ws):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    return model, params, reader


def make_predictor(ws, memory_setup, **kw):
    model, params, reader = memory_setup
    kw.setdefault("batch_size", 8)
    kw.setdefault("max_length", 64)
    pred = SiamesePredictor(model, params, ws["tokenizer"], **kw)
    pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    return pred


def test_scoring_crash_resume_byte_identical(ws, memory_setup, tmp_path):
    model, params, reader = memory_setup
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    m_a = make_predictor(ws, memory_setup).predict_file(
        reader, ws["paths"]["test"], a, resume=True
    )

    # crash hard (non-transient) at batch 4 of 6
    faults.configure("score.batch@4=raise:RuntimeError:injected hard crash")
    with pytest.raises(RuntimeError, match="injected hard crash"):
        make_predictor(ws, memory_setup).predict_file(
            reader, ws["paths"]["test"], b, resume=True
        )
    faults.reset()
    partial_lines = b.read_text().splitlines()
    journal_entries = len((tmp_path / "b.json.journal").read_text().splitlines())
    assert 0 < journal_entries < 6  # real progress was journaled pre-crash

    m_b = make_predictor(ws, memory_setup).predict_file(
        reader, ws["paths"]["test"], b, resume=True
    )
    # the verified prefix was kept byte-identical, not re-scored
    assert b.read_text().splitlines()[:journal_entries] == \
        partial_lines[:journal_entries]
    for k, v in m_a.items():
        if k == "elapsed_s":
            continue
        assert m_b[k] == v, k
    # byte-identical final metrics artifact
    ma = cal_metrics(a, thres=0.5, out_file=tmp_path / "ma.json")
    mb = cal_metrics(b, thres=0.5, out_file=tmp_path / "mb.json")
    assert (tmp_path / "ma.json").read_bytes() == (tmp_path / "mb.json").read_bytes()
    assert ma == mb
    # same report set scored exactly once
    urls_a = sorted(
        r["Issue_Url"] for l in a.read_text().splitlines() for r in json.loads(l)
    )
    urls_b = sorted(
        r["Issue_Url"] for l in b.read_text().splitlines() for r in json.loads(l)
    )
    assert urls_a == urls_b


def test_scoring_quarantine_stream_completes(ws, memory_setup, tmp_path):
    """A corrupt .jsonl line dead-letters with a reason; every valid
    report still gets scored."""
    model, params, reader = memory_setup
    src = json.loads(Path(ws["paths"]["test"]).read_text())
    corpus = tmp_path / "test.jsonl"
    with open(corpus, "w") as f:
        for i, rec in enumerate(src):
            f.write(json.dumps(rec) + "\n")
            if i == 2:
                f.write("{definitely not json\n")
    out = tmp_path / "q.json"
    metrics = make_predictor(ws, memory_setup).predict_file(
        reader, corpus, out, split="test", quarantine=True
    )
    assert metrics["num_samples"] == len(src)
    assert metrics["num_quarantined"] == 1
    dead = [json.loads(l) for l in (out.parent / "q.json.deadletter").read_text().splitlines()]
    assert len(dead) == 1 and "JSONDecodeError" in dead[0]["reason"]


def test_quarantine_over_long_record_at_data_layer(ws, memory_setup, tmp_path):
    """Over-long texts (a dump pasted into an issue body) dead-letter
    with the length in the reason instead of stalling tokenization."""
    _, _, reader = memory_setup
    src = json.loads(Path(ws["paths"]["test"]).read_text())
    monster = dict(src[0])
    monster["Issue_Url"] = "https://github.com/org0/repo0/issues/999"
    monster["Issue_Body"] = "core dump follows " * 50_000  # ~900k chars
    corpus = tmp_path / "test_with_dump.jsonl"
    with open(corpus, "w") as f:
        for rec in src + [monster]:
            f.write(json.dumps(rec) + "\n")
    dead = DeadLetter(tmp_path / "dl.jsonl", max_text_chars=100_000)
    n_kept = sum(
        1 for _ in reader.read(str(corpus), split="test", quarantine=dead)
    )
    assert dead.count == 1
    assert n_kept == len(src)
    entry = json.loads(dead.path.read_text().splitlines()[0])
    assert "over-long" in entry["reason"]
    assert entry["meta"]["Issue_Url"] == monster["Issue_Url"]
    dead.close()


def test_injected_malformed_record_via_fault_point(ws, memory_setup, tmp_path):
    """The data.read fault fires inside the quarantined window, so the
    injected failure lands in the dead-letter file and the stream
    completes — the acceptance wording, driven end-to-end."""
    model, params, reader = memory_setup
    out = tmp_path / "f.json"
    faults.configure("data.read@3=raise:ValueError:injected malformed record")
    metrics = make_predictor(ws, memory_setup).predict_file(
        reader, ws["paths"]["test"], out, split="test", quarantine=True
    )
    faults.reset()
    n_corpus = len(json.loads(Path(ws["paths"]["test"]).read_text()))
    assert metrics["num_quarantined"] == 1
    assert metrics["num_samples"] == n_corpus - 1
    dead = json.loads((tmp_path / "f.json.deadletter").read_text())
    assert "injected malformed record" in dead["reason"]


def test_scoring_transient_batch_retry(ws, memory_setup, tmp_path):
    """An UNAVAILABLE-class failure on one batch costs a retry, not the
    stream, and leaves the scores untouched."""
    model, params, reader = memory_setup
    a, b = tmp_path / "a.json", tmp_path / "b.json"
    m_a = make_predictor(ws, memory_setup).predict_file(
        reader, ws["paths"]["test"], a
    )
    faults.configure("score.batch@2=raise:RuntimeError:UNAVAILABLE tunnel flake")
    m_b = make_predictor(ws, memory_setup).predict_file(
        reader, ws["paths"]["test"], b,
        retry_policy=RetryPolicy(attempts=3, backoff=0.0),
    )
    faults.reset()
    for k, v in m_a.items():
        if k == "elapsed_s":
            continue
        assert m_b[k] == v, k


def test_scoring_heartbeat_logged(ws, memory_setup, tmp_path, caplog):
    model, params, reader = memory_setup
    with caplog.at_level(logging.INFO, logger="memvul_tpu.evaluate.predict_memory"):
        make_predictor(ws, memory_setup).predict_file(
            reader, ws["paths"]["test"], tmp_path / "h.json",
            heartbeat_batches=2, quarantine=True, resume=True,
        )
    beats = [r for r in caplog.records if "scoring heartbeat" in r.message]
    assert beats, "no heartbeat logged"
    # rows/s + ETA + journal total + quarantine count all present (the
    # rate/ETA sourcing lives in tests/test_telemetry.py)
    assert "rows/s" in beats[0].getMessage()
    assert "ETA" in beats[0].getMessage()
    assert "quarantined" in beats[0].getMessage()


# -- fused kernel degradation -------------------------------------------------


def test_mosaic_lowering_failure_falls_back_to_xla(ws, memory_setup, tmp_path, caplog):
    """Injected lowering failure on the fused bank match: the run
    degrades to anchor_match_impl='xla' with ONE warning and identical
    scores (fused/xla parity is pinned ≤1e-5 in
    tests/test_anchor_match_kernel.py)."""
    import memvul_tpu.ops.pallas.anchor_match as am

    model, params, reader = memory_setup
    ref = tmp_path / "xla.json"
    out = tmp_path / "fused_degraded.json"
    make_predictor(ws, memory_setup, anchor_match_impl="xla").predict_file(
        reader, ws["paths"]["test"], ref
    )
    am._fallback_warned = False
    faults.configure("kernel.lower=raise:RuntimeError:Mosaic lowering failed")
    with caplog.at_level(logging.WARNING, logger="memvul_tpu.ops.pallas.anchor_match"):
        make_predictor(ws, memory_setup, anchor_match_impl="fused").predict_file(
            reader, ws["paths"]["test"], out
        )
    faults.reset()
    warnings = [r for r in caplog.records if "degrading to anchor_match_impl" in r.message]
    assert len(warnings) == 1  # one warning, not one per batch/shape
    by_url = {
        r["Issue_Url"]: r
        for l in ref.read_text().splitlines()
        for r in json.loads(l)
    }
    n = 0
    for line in out.read_text().splitlines():
        for rec in json.loads(line):
            exp = by_url[rec["Issue_Url"]]
            for anchor, p in rec["predict"].items():
                assert abs(p - exp["predict"][anchor]) <= 1e-5
            n += 1
    assert n == len(by_url) > 0


def test_predictor_degrade_rebuilds_score_program(ws, memory_setup):
    """Compile-time Mosaic failures (they surface at the enclosing jit,
    past the trace-time fallback) rebuild the score program on 'xla'."""
    pred = make_predictor(ws, memory_setup, anchor_match_impl="fused")
    old_fn = pred._score_fn
    assert pred._maybe_degrade_to_xla(RuntimeError("Mosaic failed to legalize op")) is True
    assert pred.anchor_match_impl == "xla"
    assert pred._score_fn is not old_fn
    # a genuine non-kernel bug is NOT swallowed
    assert pred._maybe_degrade_to_xla(RuntimeError("Mosaic again")) is False  # already xla
    pred2 = make_predictor(ws, memory_setup, anchor_match_impl="fused")
    assert pred2._maybe_degrade_to_xla(ValueError("shape mismatch")) is False
