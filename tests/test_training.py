import json

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

from memvul_tpu.data.readers import MemoryReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.parallel import create_mesh
from memvul_tpu.training import (
    MemoryTrainer,
    MetricTracker,
    TrainerConfig,
    linear_with_warmup,
    make_optimizer,
)
from memvul_tpu.training.optim import label_params_by_prefix


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("train"), seed=5)


def make_trainer(ws, tmp_path, mesh=None, **cfg_kw):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"],
        anchor_path=ws["paths"]["anchors"],
        same_diff_ratio={"same": 2, "diff": 2},
        sample_neg=0.5,
        seed=2021,
    )
    defaults = dict(
        num_epochs=2,
        patience=None,
        batch_size=4,
        grad_accum=2,
        max_length=32,
        eval_batch_size=8,
        eval_max_length=32,
        warmup_steps=2,
        base_lr=1e-3,
        serialization_dir=str(tmp_path / "out"),
    )
    defaults.update(cfg_kw)
    trainer = MemoryTrainer(
        model,
        params,
        ws["tokenizer"],
        reader,
        train_path=ws["paths"]["train"],
        validation_path=ws["paths"]["validation"],
        anchor_path=ws["paths"]["anchors"],
        config=TrainerConfig(**defaults),
        mesh=mesh,
    )
    return trainer


# -- optimizer ----------------------------------------------------------------


def test_linear_with_warmup_schedule():
    s = linear_with_warmup(10, total_steps=100)
    assert float(s(0)) == 0.0
    assert float(s(5)) == pytest.approx(0.5)
    assert float(s(10)) == pytest.approx(1.0)
    assert float(s(55)) == pytest.approx(0.5)
    assert float(s(100)) == pytest.approx(0.0)
    s2 = linear_with_warmup(10)
    assert float(s2(1000)) == 1.0


def test_param_group_labels():
    params = {
        "params": {
            "bert": {"layer_0": {"kernel": np.zeros(1)}},
            "pooler": {"dense": {"kernel": np.zeros(1)}},
            "pair_kernel": np.zeros(1),
        }
    }
    labels = label_params_by_prefix(
        params, (("bert/", "embedder"), ("pooler/", "pooler"))
    )
    assert labels["params"]["bert"]["layer_0"]["kernel"] == "embedder"
    assert labels["params"]["pooler"]["dense"]["kernel"] == "pooler"
    assert labels["params"]["pair_kernel"] == "default"


def test_group_learning_rates_applied():
    params = {
        "params": {
            "bert": {"kernel": jnp.ones(4)},
            "pooler": {"kernel": jnp.ones(4)},
            "head": {"kernel": jnp.ones(4)},
        }
    }
    tx, state = make_optimizer(
        params,
        group_lrs={"embedder": 1e-5, "pooler": 1e-4},
        base_lr=1e-2,
        warmup_steps=0,
        grad_clip_norm=None,
    )
    grads = jax.tree_util.tree_map(jnp.ones_like, params)
    updates, _ = tx.update(grads, state, params)
    # adam step size == lr for constant unit grads at step 1 (approx)
    assert abs(updates["params"]["bert"]["kernel"][0]) < abs(
        updates["params"]["pooler"]["kernel"][0]
    )
    assert abs(updates["params"]["pooler"]["kernel"][0]) < abs(
        updates["params"]["head"]["kernel"][0]
    )


# -- metric tracker -----------------------------------------------------------


def test_metric_tracker_patience():
    t = MetricTracker("+s_f1-score", patience=2)
    assert t.update({"s_f1-score": 0.5}, 0) is True
    assert t.update({"s_f1-score": 0.4}, 1) is False
    assert not t.should_stop()
    assert t.update({"s_f1-score": 0.3}, 2) is False
    assert t.should_stop()
    assert t.best_epoch == 0


def test_metric_tracker_minimize():
    t = MetricTracker("-loss", patience=None)
    assert t.update({"loss": 1.0}, 0)
    assert t.update({"loss": 0.5}, 1)
    assert not t.update({"loss": 0.7}, 2)


def test_metric_tracker_bad_spec():
    with pytest.raises(ValueError):
        MetricTracker("s_f1-score")
    t = MetricTracker("+x")
    with pytest.raises(KeyError):
        t.update({"y": 1.0}, 0)


# -- trainer end-to-end -------------------------------------------------------


def test_trainer_runs_and_tracks(ws, tmp_path):
    trainer = make_trainer(ws, tmp_path, steps_per_epoch=4)
    result = trainer.train()
    assert len(result["history"]) == 2
    first = result["history"][0]
    assert "training_loss" in first and np.isfinite(first["training_loss"])
    assert "validation_s_f1" in first or "validation_s_f1-score" in str(first)
    # checkpoint + metrics file written
    out = tmp_path / "out"
    assert (out / "metrics_epoch_0.json").exists()
    assert result["best_epoch"] is not None


def test_validation_buckets_match_padded(ws, tmp_path):
    """Length-binned validation (eval_buckets/eval_tokens_per_batch) must
    reproduce the reference pad-to-max collation's metrics exactly — the
    trainer-side twin of the predictor equality test in
    tests/test_inference.py."""
    padded = make_trainer(ws, tmp_path / "a", steps_per_epoch=1)
    binned = make_trainer(
        ws,
        tmp_path / "b",
        steps_per_epoch=1,
        eval_buckets=[8, 16, 32],
        eval_tokens_per_batch=256,
    )
    # identical init (same PRNGKey(0) in make_trainer), no training: the
    # two validation passes score the same params
    m_pad = padded.validate()
    m_bin = binned.validate()
    # pin the wiring: the binned trainer really scored through buckets
    # (otherwise the equality below holds vacuously)
    assert binned._val_predictor.buckets == (8, 16, 32)
    assert binned._val_predictor.bucket_sizes is not None
    assert padded._val_predictor.buckets is None
    assert m_pad.keys() == m_bin.keys() and m_pad
    for k, v in m_pad.items():
        if k.endswith("elapsed_s") or k.endswith("reports_per_s"):
            continue  # wall-clock, legitimately differs
        assert m_bin[k] == pytest.approx(v, abs=1e-6), k


def test_trainer_loss_decreases_on_overfit(ws, tmp_path):
    trainer = make_trainer(
        ws,
        tmp_path,
        num_epochs=5,
        steps_per_epoch=6,
        base_lr=5e-3,
        warmup_steps=1,
        serialization_dir=None,
    )
    result = trainer.train()
    losses = [h["training_loss"] for h in result["history"]]
    assert losses[-1] < losses[0]


def test_trainer_resume(ws, tmp_path):
    t1 = make_trainer(ws, tmp_path, num_epochs=1, steps_per_epoch=2)
    t1.train()
    t2 = make_trainer(ws, tmp_path, num_epochs=2, steps_per_epoch=2)
    assert t2.maybe_restore() is True
    assert t2.epoch == 1
    assert t2.step == t1.step
    # params actually restored (identical leaves)
    l1 = jax.tree_util.tree_leaves(jax.device_get(t1.params))
    l2 = jax.tree_util.tree_leaves(jax.device_get(t2.params))
    for a, b in zip(l1, l2):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_trainer_sharded_step(ws, tmp_path):
    mesh = create_mesh()
    trainer = make_trainer(
        ws, tmp_path, mesh=mesh, batch_size=8, steps_per_epoch=2,
        num_epochs=1, serialization_dir=None,
    )
    result = trainer.train()
    assert np.isfinite(result["history"][0]["training_loss"])


@pytest.mark.slow  # checkify-instrumented compile dominates (~1 min on the
# tier-1 host); the nan-localization test below compiles the same
# instrumented step, keeping debug_checks covered in the fast tier
def test_trainer_debug_checks_clean_run(ws, tmp_path):
    """debug_checks mode trains normally on healthy data."""
    trainer = make_trainer(
        ws, tmp_path, debug_checks=True, num_epochs=1, steps_per_epoch=2,
        serialization_dir=None,
    )
    result = trainer.train()
    assert np.isfinite(result["history"][0]["training_loss"])


@pytest.mark.slow  # the checkify-instrumented BERT step compile is ~47 s
# on the tier-1 host; the fast variant below pins the same jit_step
# mechanism (localization + no-donation) without the instrumented compile
def test_trainer_debug_checks_localizes_nan(ws, tmp_path):
    """Poisoned params must raise at the offending step with checkify's
    localization (the NaN guard in _drain_stats only detects, N steps
    later; this names the op)."""
    from jax.experimental import checkify

    trainer = make_trainer(
        ws, tmp_path, debug_checks=True, num_epochs=1, steps_per_epoch=1,
        serialization_dir=None,
    )
    leaves, treedef = jax.tree_util.tree_flatten(trainer.params)
    leaves = [
        jnp.full_like(l, jnp.nan) if jnp.issubdtype(l.dtype, jnp.floating) else l
        for l in leaves
    ]
    trainer.params = jax.tree_util.tree_unflatten(treedef, leaves)
    with pytest.raises(checkify.JaxRuntimeError, match="nan"):
        trainer.train()
    # debug mode must NOT donate: the pre-step state stays inspectable
    # for post-mortem (a donated buffer would raise 'Array has been
    # deleted' here)
    post = [
        l for l in jax.tree_util.tree_leaves(trainer.params)
        if jnp.issubdtype(l.dtype, jnp.floating)
    ]
    assert post and bool(jnp.isnan(post[0]).all())


def test_jit_step_debug_checks_localize_and_no_donation_fast():
    """Fast tier-1 coverage of the checkify contract: jit_step's debug
    mode raises at the first NaN-producing op and must NOT donate its
    inputs (the pre-step state stays inspectable post-mortem).  jit_step
    is the ONE shared implementation behind MemoryTrainer /
    ClassifierTrainer / MLMTrainer, so pinning it here keeps the
    mechanism in the fast tier while the instrumented-BERT e2e variants
    are @slow."""
    from jax.experimental import checkify

    from memvul_tpu.training.trainer import jit_step

    def raw(x, y):
        return jnp.log(x) + y.sum()  # log of a negative → nan

    checked = jit_step(raw, donate=(0, 1), debug_checks=True)
    x = jnp.asarray(-1.0)
    y = jnp.ones(4)
    with pytest.raises(checkify.JaxRuntimeError, match="nan"):
        checked(x, y)
    # debug mode must not donate: both inputs are still alive/readable
    assert float(x) == -1.0
    assert float(y.sum()) == 4.0
    # the same wiring WITHOUT debug_checks donates and runs clean
    donating = jit_step(raw, donate=(0,), debug_checks=False)
    assert float(donating(jnp.asarray(1.0), y)) == pytest.approx(4.0)


def test_metric_tracker_minimize_stores_raw_value():
    t = MetricTracker("-loss")
    t.update({"loss": 0.42}, 0)
    assert t.best == pytest.approx(0.42)  # raw, not negated


def test_total_steps_decay_wired_from_steps_per_epoch(ws, tmp_path):
    trainer = make_trainer(
        ws, tmp_path, num_epochs=2, steps_per_epoch=3, warmup_steps=1,
        serialization_dir=None,
    )
    # the trainer wires total_steps = num_epochs * steps_per_epoch
    assert trainer.total_steps == 6
    explicit = make_trainer(
        ws, tmp_path, num_epochs=2, steps_per_epoch=3, total_steps=11,
        serialization_dir=None,
    )
    assert explicit.total_steps == 11


def test_resume_restores_metrics_history(ws, tmp_path):
    t1 = make_trainer(ws, tmp_path, num_epochs=2, steps_per_epoch=2)
    r1 = t1.train()
    t2 = make_trainer(ws, tmp_path, num_epochs=2, steps_per_epoch=2)
    assert t2.maybe_restore()
    assert len(t2.metrics_history) == len(r1["history"])


def test_epoch_loop_runs_ahead_without_per_step_sync(ws, tmp_path, monkeypatch):
    """The hot loop must issue many consecutive steps with no blocking
    device→host transfer (the reference host-syncs every step,
    custom_trainer.py:398-435): all pulls route through _host_fetch, so
    counting its calls proves the loop runs ahead of the device."""
    from memvul_tpu.training import trainer as trainer_mod

    calls = []
    real = trainer_mod._host_fetch

    def counting(tree):
        calls.append(len(tree))
        return real(tree)

    monkeypatch.setattr(trainer_mod, "_host_fetch", counting)
    t = make_trainer(
        ws, tmp_path, num_epochs=1, steps_per_epoch=6, sync_every=100,
        serialization_dir=None,
    )
    metrics = t.train_epoch()
    assert metrics["num_steps"] == 6
    # one drain at epoch end covering all 6 steps — zero per-step syncs
    assert calls == [6]


def test_sync_every_preserves_metrics(ws, tmp_path):
    """Windowed draining is an execution detail: per-step sync and
    64-step windows must produce identical epoch metrics."""
    t1 = make_trainer(
        ws, tmp_path, num_epochs=1, steps_per_epoch=4, sync_every=1,
        serialization_dir=None,
    )
    t2 = make_trainer(
        ws, tmp_path, num_epochs=1, steps_per_epoch=4, sync_every=64,
        serialization_dir=None,
    )
    m1, m2 = t1.train_epoch(), t2.train_epoch()
    assert m1["loss"] == pytest.approx(m2["loss"])
    assert m1["accuracy"] == pytest.approx(m2["accuracy"])
    assert m1["f1-score"] == pytest.approx(m2["f1-score"])


def test_update_confusion_matches_update():
    from memvul_tpu.training.metrics import RunningClassification

    preds = np.array([0, 1, 1, 0, 1])
    labels = np.array([0, 1, 0, 0, 1])
    weights = np.array([1.0, 1.0, 0.0, 1.0, 1.0])
    r1 = RunningClassification(2, ["same", "diff"])
    r1.update(preds, labels, weights)
    cm = np.zeros((2, 2), np.int64)
    for p, l, w in zip(preds, labels, weights):
        if w > 0:
            cm[l, p] += 1
    r2 = RunningClassification(2, ["same", "diff"])
    r2.update_confusion(cm)
    assert r1.compute() == r2.compute()


def test_ema_folded_into_step_still_averages(ws, tmp_path):
    """EMA rides inside the jitted step now — the averaged params must
    still trail the live params after a few updates."""
    t = make_trainer(
        ws, tmp_path, num_epochs=1, steps_per_epoch=3, ema_decay=0.5,
        serialization_dir=None,
    )
    before = jax.device_get(jax.tree_util.tree_leaves(t.params)[0]).copy()
    t.train_epoch()
    live = jax.device_get(jax.tree_util.tree_leaves(t.params)[0])
    ema = jax.device_get(jax.tree_util.tree_leaves(t.ema_params)[0])
    assert not np.allclose(live, before)  # params moved
    assert not np.allclose(ema, live)  # ema lags the live params
    assert not np.allclose(ema, before)  # but it did move


def test_fold_tokens_does_not_mutate_inputs():
    from memvul_tpu.models.folding import fold_tokens

    ids = np.array([[2, 10, 11, 3, 0, 0]], dtype=np.int32)
    mask = (ids != 0).astype(np.int32)
    ids_before, mask_before = ids.copy(), mask.copy()
    fold_tokens(ids, mask, max_length=6, cls_id=2, sep_id=3, pad_id=0)
    np.testing.assert_array_equal(ids, ids_before)
    np.testing.assert_array_equal(mask, mask_before)
