"""Ragged serve path (docs/ragged_serving.md).

The acceptance contract this file pins:

* **kernel parity** — the segment-masked Pallas kernel (interpret mode
  on CPU) matches the masked jnp reference over random packs, block
  boundaries, batch > 1, and bf16;
* **packing** — ``pack_token_budget`` is a pure function of the input
  order (no row lost/duplicated, every pack within budget/row caps,
  sealed packs independent of what follows), and ``collate_ragged``'s
  real-row content is invariant to trailing dead rows — the hypothesis
  suite (optional tier, ``importorskip``);
* **model parity** — a request's packed embedding/scores match its
  padded-batch embedding/scores ≤1e-6;
* **single warm program** — a ragged predictor AOT-warms exactly ONE
  program and ``score_trace_count`` stays flat for ANY length mix,
  including a 200-concurrent mixed-length served load whose scores
  match the bucketed path ≤1e-6;
* **satellites** — ``serve.truncated`` counts clamped requests;
  shadow scoring routes through the active impl and its deltas are
  impl-invariant; the lint catches packer/ragged calls landing on
  handler/router classes; ``BENCH_MICRO=serve`` A/B emits the
  real-token ledger with ragged utilization above bucketed.
"""

import json
import sys
import threading
from pathlib import Path

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from memvul_tpu import telemetry
from memvul_tpu.data.batching import (
    collate_ragged,
    pack_token_budget,
)
from memvul_tpu.data.readers import MemoryReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.evaluate.predict_memory import SiamesePredictor
from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.ops.attention import _xla_attention
from memvul_tpu.ops.pallas.ragged_attention import (
    ragged_flash_attention,
    segment_bias,
)
from memvul_tpu.resilience import faults
from memvul_tpu.serving import InprocessClient, ScoringService, ServiceConfig

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("ragged"), seed=11)


@pytest.fixture(scope="module")
def setup(ws):
    """One tiny model + a bucketed and a ragged predictor SHARING its
    params — the parity pair every serving test scores against (their
    jit caches persist across tests, the warmed-program reuse the
    service relies on)."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    anchors = list(reader.read_anchors(ws["paths"]["anchors"]))
    bucketed = SiamesePredictor(
        model, params, ws["tokenizer"],
        batch_size=8, max_length=48, buckets=[16, 48],
    )
    bucketed.encode_anchors(anchors)
    ragged = SiamesePredictor(
        model, params, ws["tokenizer"],
        batch_size=8, max_length=48,
        score_impl="ragged", token_budget=96, max_rows_per_pack=8,
    )
    ragged.encode_anchors(anchors)
    texts = [
        inst["text1"]
        for inst in reader.read(ws["paths"]["test"], split="test")
    ]
    return {
        "model": model, "params": params, "reader": reader,
        "anchors": anchors, "texts": texts,
        "bucketed": bucketed, "ragged": ragged, "tokenizer": ws["tokenizer"],
    }


@pytest.fixture()
def tel(tmp_path):
    registry = telemetry.configure(run_dir=tmp_path / "run")
    yield registry
    telemetry.reset()
    faults.reset()


def _random_segments(rng, t, n_rows, batch=1):
    """A plausible pack layout: rows laid end-to-end, 0-padded tail."""
    seg = np.zeros((batch, t), np.int32)
    for b in range(batch):
        offset = 0
        for i in range(n_rows):
            n = int(rng.integers(1, max(2, t // n_rows)))
            if offset + n > t:
                break
            seg[b, offset : offset + n] = i + 1
            offset += n
    return seg


# -- ragged kernel parity (interpret mode) ------------------------------------

@pytest.mark.parametrize("t", [128, 160])  # 160: not a block multiple
def test_ragged_kernel_matches_masked_reference(t):
    rng = np.random.default_rng(t)
    b, h, d = 2, 4, 32
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5, jnp.float32)
    q, k, v = mk(), mk(), mk()
    seg = jnp.asarray(_random_segments(rng, t, n_rows=5, batch=b))
    out = ragged_flash_attention(q, k, v, seg, block_q=128, block_k=128,
                                 interpret=True)
    ref = _xla_attention(q, k, v, segment_bias(seg), None, 0.0, True)
    live = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(out)[live], np.asarray(ref)[live], atol=2e-5, rtol=2e-5
    )


def test_ragged_kernel_bf16_close_to_fp32_reference():
    rng = np.random.default_rng(3)
    b, t, h, d = 1, 128, 2, 32
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)) * 0.5, jnp.bfloat16)
    q, k, v = mk(), mk(), mk()
    seg = jnp.asarray(_random_segments(rng, t, n_rows=4))
    out = ragged_flash_attention(q, k, v, seg, interpret=True)
    ref = _xla_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        segment_bias(seg), None, 0.0, True,
    )
    assert out.dtype == jnp.bfloat16
    live = np.asarray(seg) > 0
    np.testing.assert_allclose(
        np.asarray(out, np.float32)[live], np.asarray(ref)[live],
        atol=3e-2, rtol=3e-2,
    )


def test_segment_bias_semantics():
    """Same non-zero segment attends; cross-segment and dead padding
    never do — the mask the kernel applies blockwise."""
    seg = jnp.asarray([[1, 1, 2, 0]], jnp.int32)
    bias = np.asarray(segment_bias(seg))[0, 0]  # [Tq, Tk]
    neg = np.finfo(np.float32).min
    assert bias[0, 1] == 0.0 and bias[1, 0] == 0.0  # within segment 1
    assert bias[2, 2] == 0.0                         # within segment 2
    assert bias[0, 2] == neg and bias[2, 0] == neg   # across segments
    assert (bias[:, 3] == neg).all()                 # dead key: never seen
    assert (bias[3, :] == neg).all()                 # dead query: sees nothing


def test_ragged_kernel_rejects_bad_segment_shape():
    rng = np.random.default_rng(0)
    q = jnp.asarray(rng.normal(size=(1, 64, 2, 16)), jnp.float32)
    with pytest.raises(ValueError, match="segment_ids"):
        ragged_flash_attention(
            q, q, q, jnp.zeros((1, 32), jnp.int32), interpret=True
        )


# -- token-budget packer -------------------------------------------------------

def test_pack_token_budget_order_budget_and_row_caps():
    # budget seals: 40+40 fits 96, +30 overflows -> new pack
    assert pack_token_budget([40, 40, 30], 96, 8) == [[0, 1], [2]]
    # row cap seals even when tokens fit
    assert pack_token_budget([1, 1, 1, 1, 1], 96, 2) == [[0, 1], [2, 3], [4]]
    # strictly in-order: a later short row never backfills an old pack
    assert pack_token_budget([90, 90, 2], 96, 8) == [[0], [1, 2]]
    # tail flush: the last partial pack is emitted
    assert pack_token_budget([5], 96, 8) == [[0]]
    assert pack_token_budget([], 96, 8) == []
    # over-budget rows clamp to one full pack instead of crashing
    assert pack_token_budget([500], 96, 8) == [[0]]


def test_pack_and_collate_validation():
    with pytest.raises(ValueError, match="token_budget"):
        pack_token_budget([1], 0, 8)
    with pytest.raises(ValueError, match="max_rows"):
        pack_token_budget([1], 96, 0)
    with pytest.raises(ValueError, match="max_rows"):
        collate_ragged([[1]] * 3, 96, 2, pad_id=0)
    with pytest.raises(ValueError, match="overflows token_budget"):
        collate_ragged([[1] * 50, [2] * 50], 96, 8, pad_id=0)


def test_collate_ragged_layout():
    seqs = [[7, 8, 9], [4, 5]]
    sample = collate_ragged(seqs, 12, 4, pad_id=0)
    ids, seg = sample["input_ids"][0], sample["segment_ids"][0]
    pos, mask = sample["position_ids"][0], sample["attention_mask"][0]
    assert ids.tolist() == [7, 8, 9, 4, 5, 0, 0, 0, 0, 0, 0, 0]
    assert seg.tolist() == [1, 1, 1, 2, 2, 0, 0, 0, 0, 0, 0, 0]
    assert pos.tolist() == [0, 1, 2, 0, 1, 0, 0, 0, 0, 0, 0, 0]
    assert mask.tolist() == [1, 1, 1, 1, 1, 0, 0, 0, 0, 0, 0, 0]
    assert sample["row_starts"].tolist() == [0, 3, 0, 0]
    for v in sample.values():
        assert v.dtype == np.int32


def test_packer_properties_hypothesis():
    """Property (hypothesis): any length multiset packs with no row
    lost/duplicated, every pack within the budget and row caps, sealed
    packs are a pure function of the prefix that produced them, and
    collation is invariant to trailing dead rows."""
    pytest.importorskip("hypothesis")  # property tier is optional (pyproject [test])
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=80, deadline=None)
    @given(
        st.lists(st.integers(min_value=1, max_value=64), max_size=40),
        st.integers(min_value=8, max_value=96),
        st.integers(min_value=1, max_value=12),
    )
    def check(lengths, budget, max_rows):
        packs = pack_token_budget(lengths, budget, max_rows)
        # partition: every row in exactly one pack, order preserved
        flat = [i for pack in packs for i in pack]
        assert flat == list(range(len(lengths)))
        for pack in packs:
            assert len(pack) <= max_rows
            assert sum(min(lengths[i], budget) for i in pack) <= budget
        # prefix purity: sealed packs never depend on later rows
        if len(packs) > 1:
            prefix = [i for pack in packs[:-1] for i in pack]
            again = pack_token_budget(
                [lengths[i] for i in prefix], budget, max_rows
            )
            assert again == packs[:-1]
        # trailing-dead-row invariance: growing max_rows (more dead
        # rows in the collated pack) changes nothing a real row sees
        if packs and len(packs[0]) < max_rows:
            seqs = [[1] * lengths[i] for i in packs[0]]
            a = collate_ragged(seqs, budget, max_rows, pad_id=0)
            b = collate_ragged(seqs, budget, max_rows + 3, pad_id=0)
            for key in ("input_ids", "attention_mask", "segment_ids",
                        "position_ids"):
                np.testing.assert_array_equal(a[key], b[key])
            np.testing.assert_array_equal(
                a["row_starts"][: len(seqs)], b["row_starts"][: len(seqs)]
            )

    check()


# -- model / predictor parity --------------------------------------------------

def test_encode_ragged_matches_padded_encode(setup):
    """Segment-aware pooling pulls each request's embedding out of the
    flat pack bit-for-bit equal to its padded-batch embedding (same
    positions, same masked softmax zeros, same pooler/header params)."""
    from memvul_tpu.data.batching import _pad_block

    model, params = setup["model"], setup["params"]
    enc = setup["bucketed"].encoder
    seqs = enc.encode_many(setup["texts"][:5])
    sample = collate_ragged(seqs, 128, 8, enc.pad_id)
    u_ragged = np.asarray(
        model.apply(params, sample, method=model.encode_ragged)
    )[: len(seqs)]
    u_padded = np.asarray(
        model.apply(
            params, _pad_block(seqs, len(seqs), enc.pad_id, 48),
            method=model.encode,
        )
    )
    np.testing.assert_allclose(u_ragged, u_padded, atol=1e-6, rtol=0)


def test_score_texts_parity_bucketed_vs_ragged(setup):
    """The tentpole parity gate: the SAME texts score ≤1e-6 identical
    through the bucketed grid and the single packed program."""
    texts = [setup["texts"][i % len(setup["texts"])] for i in range(60)]
    want = setup["bucketed"].score_texts(texts)
    got = setup["ragged"].score_texts(texts)
    np.testing.assert_allclose(got, want, atol=1e-6, rtol=0)


def test_ragged_warmup_is_single_program_and_traces_stay_flat(setup):
    """One AOT-warmed program serves ANY length mix with zero new
    traces — the single-warm-program contract replacing the bucket
    grid."""
    ragged = setup["ragged"]
    assert ragged.warmup_bank_shapes(ragged.anchor_bank) == 1
    traces = ragged.score_trace_count
    texts = setup["texts"]
    # adversarial mixes: singletons, short-only, long-only, shuffled
    mixes = [
        texts[:1],
        sorted(texts[:20], key=len)[:10],
        sorted(texts[:20], key=len)[-10:],
        [texts[(7 * i) % len(texts)] for i in range(33)],
    ]
    for mix in mixes:
        ragged.score_texts(mix)
    assert ragged.score_trace_count == traces


def test_predictor_ragged_validation(setup):
    model, params = setup["model"], setup["params"]
    tok = setup["tokenizer"]
    with pytest.raises(ValueError, match="score_impl"):
        SiamesePredictor(model, params, tok, score_impl="raggedy")
    with pytest.raises(ValueError, match="token_budget"):
        SiamesePredictor(
            model, params, tok, max_length=48,
            score_impl="ragged", token_budget=32,
        )
    with pytest.raises(ValueError, match="single-device"):
        SiamesePredictor(
            model, params, tok, mesh=object(), score_impl="ragged"
        )


# -- serving acceptance --------------------------------------------------------

def test_ragged_service_concurrent_mixed_load_parity_one_warm_program(
    setup, tel
):
    """200 concurrent mixed-length requests through a RAGGED service:
    every response matches the bucketed path ≤1e-6, zero mid-serve
    recompiles, and the padding ledger shows the packed shapes."""
    bucketed, ragged = setup["bucketed"], setup["ragged"]
    n = 200
    picks = [setup["texts"][i % len(setup["texts"])] for i in range(n)]
    expected = bucketed.score_texts(picks)
    traces_before = ragged.score_trace_count

    service = ScoringService(
        ragged,
        config=ServiceConfig(
            max_batch=8, max_wait_ms=3.0, max_queue=1000,
            default_deadline_ms=30000.0,
        ),
    )
    client = InprocessClient(service)
    results = {}
    lock = threading.Lock()

    def worker(indices):
        for i in indices:
            response = client.score(picks[i])
            with lock:
                results[i] = response

    threads = [
        threading.Thread(target=worker, args=(range(k, n, 16),))
        for k in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    service.drain()

    assert len(results) == n
    labels = ragged.anchor_labels
    for i in range(n):
        assert results[i]["status"] == "ok"
        got = np.array(
            [results[i]["predict"][label] for label in labels], np.float32
        )
        np.testing.assert_allclose(got, expected[i], atol=1e-6, rtol=0)
    # one warm program served the whole mixed-length load
    assert ragged.score_trace_count == traces_before
    counters = tel.snapshot()["counters"]
    assert counters["serve.served"] == n
    assert counters["serve.requests"] == n
    # padding ledger: every dispatch paid exactly token_budget slots
    assert counters["serve.tokens_padded"] % ragged.token_budget == 0
    assert 0 < counters["serve.tokens_real"] <= counters["serve.tokens_padded"]


def test_ragged_utilization_beats_bucketed_on_same_requests(setup, tel):
    """The padding win, measured: the same singleton dispatches cost
    token_budget slots ragged vs rows×bucket slots bucketed."""
    bucketed, ragged = setup["bucketed"], setup["ragged"]
    text = min(setup["texts"], key=len)

    def util_of(predictor):
        registry = telemetry.configure(run_dir=None)
        service = ScoringService(
            predictor,
            config=ServiceConfig(max_batch=4, max_wait_ms=1.0,
                                 default_deadline_ms=0.0),
        )
        for _ in range(4):
            InprocessClient(service).score(text)
        service.drain()
        counters = registry.snapshot()["counters"]
        return counters["serve.tokens_real"] / counters["serve.tokens_padded"]

    ragged_util = util_of(ragged)
    bucketed_util = util_of(bucketed)
    assert ragged_util > bucketed_util


def test_serve_truncated_counts_clamped_requests(setup, tel):
    """Over-long requests clamped into the largest bucket/budget are
    counted (serve.truncated) instead of silently truncated; short
    requests are not."""
    model, params, tok = setup["model"], setup["params"], setup["tokenizer"]
    predictor = SiamesePredictor(
        model, params, tok, batch_size=4, max_length=16,
        score_impl="ragged", token_budget=32, max_rows_per_pack=4,
    )
    predictor.encode_anchors(setup["anchors"])
    service = ScoringService(
        predictor,
        config=ServiceConfig(max_batch=4, max_wait_ms=1.0,
                             default_deadline_ms=0.0),
    )
    client = InprocessClient(service)
    long_text = " ".join(
        w for t in setup["texts"] for w in t.split()
    )[:4000]
    assert client.score(long_text)["status"] == "ok"
    assert client.score("short report")["status"] == "ok"
    service.drain()
    counters = tel.snapshot()["counters"]
    assert counters.get("serve.truncated", 0) == 1


def test_report_renders_utilization_and_truncated(tmp_path):
    """telemetry-report derives serve.real_token_utilization from the
    padding ledger and renders serve.truncated like any counter."""
    from memvul_tpu.telemetry.report import render_report

    registry = telemetry.configure(run_dir=tmp_path / "run")
    registry.counter("serve.tokens_real").inc(300)
    registry.counter("serve.tokens_padded").inc(400)
    registry.counter("serve.truncated").inc(2)
    registry.close()
    try:
        text = render_report(tmp_path / "run")
    finally:
        telemetry.reset()
    assert "serve.real_token_utilization = 0.750" in text
    assert "(300/400 token slots)" in text
    assert "serve.truncated = 2" in text


# -- shadow scoring rides the active impl (bankops satellite) ------------------

def test_shadow_scoring_is_impl_invariant(setup, tel):
    """bankops.score_texts routes through the predictor's ACTIVE impl,
    so a candidate bank's shadow deltas are the same whichever path is
    live (bucketed vs ragged active service)."""
    from memvul_tpu.bankops.shadow import ShadowScorer, score_texts

    bucketed, ragged = setup["bucketed"], setup["ragged"]
    candidate = [dict(a) for a in setup["anchors"]][: max(
        1, len(setup["anchors"]) - 1
    )]
    texts = setup["texts"][:24]
    # the scoring function the shadow worker runs, on both impls
    bank_b, _, n_b = bucketed.encode_bank(candidate)
    bank_r, _, n_r = ragged.encode_bank(candidate)
    ragged.warmup_bank_shapes(bank_r)
    rows_b = score_texts(bucketed, texts, bank_b, n_b)
    rows_r = score_texts(ragged, texts, bank_r, n_r)
    np.testing.assert_allclose(rows_r, rows_b, atol=1e-6, rtol=0)

    # end-to-end: a shadow attached to a RAGGED service samples served
    # traffic and scores it through the warmed ragged program with
    # score_trace_count flat
    service = ScoringService(
        ragged,
        config=ServiceConfig(max_batch=8, max_wait_ms=2.0,
                             default_deadline_ms=30000.0),
    )
    shadow = ShadowScorer(service, candidate)
    traces = ragged.score_trace_count
    client = InprocessClient(service)
    for text in texts:
        assert client.score(text)["status"] == "ok"
    deadline = 10.0
    import time as _time
    t0 = _time.monotonic()
    while (
        shadow.summary()["sampled"] < len(texts)
        and _time.monotonic() - t0 < deadline
    ):
        _time.sleep(0.02)
    summary = shadow.stop()
    service.drain()
    assert summary["sampled"] == len(texts)
    assert summary["errors"] == 0
    assert ragged.score_trace_count == traces


# -- lint: packing stays off handler/router classes ----------------------------

def test_lint_flags_ragged_dispatch_on_handler_and_router(tmp_path):
    from lint_no_blocking_in_handler import find_blocking_calls

    (tmp_path / "bad.py").write_text(
        "from http.server import BaseHTTPRequestHandler\n"
        "class H(BaseHTTPRequestHandler):\n"
        "    def do_POST(self):\n"
        "        packs = pack_token_budget([1], 8, 1)\n"
        "        sample = collate_ragged([[1]], 8, 1, 0)\n"
        "class MyRouter:\n"
        "    def _pick(self, request):\n"
        "        self.service.predictor._ragged_score_fn(None, None, None)\n"
        "        return self.service.predictor.score_texts([request])\n"
    )
    offenders = find_blocking_calls(tmp_path)
    names = sorted(o.rsplit(" ", 1)[-1] for o in offenders)
    assert names == [
        "_ragged_score_fn", "collate_ragged", "pack_token_budget",
        "score_texts",
    ]


def test_serve_from_archive_ragged_end_to_end(ws, tmp_path, tel):
    """Archive + serving.score_impl=ragged → a warmed ragged service:
    sized from the config section, one warm program, ok responses."""
    from memvul_tpu.archive import save_archive
    from memvul_tpu.build import build_model, init_params, serve_from_archive

    model_cfg = {
        "type": "model_memory",
        "encoder": {"preset": "tiny", "vocab_size": 4096},
        "header_dim": 32,
    }
    config = {
        "tokenizer": {
            "type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"],
        },
        "dataset_reader": {
            "type": "reader_memory",
            "anchor_path": ws["paths"]["anchors"],
            "cve_path": ws["paths"]["cve"],
        },
        "model": model_cfg,
        "serving": {
            "max_batch": 4, "max_length": 48,
            "score_impl": "ragged", "token_budget": 96,
        },
    }
    model = build_model(dict(model_cfg), 4096)
    params = init_params(model, seed=0)
    archive = save_archive(
        tmp_path / "model.tar.gz", config, params,
        tokenizer_file=ws["paths"]["tokenizer"],
    )
    service = serve_from_archive(archive, out_dir=tmp_path / "serve_run")
    try:
        assert service.predictor.score_impl == "ragged"
        assert service.predictor.ragged_shape() == (96, 4)  # max_batch rows
        traces = service.predictor.score_trace_count
        response = InprocessClient(service).score("a memory safety bug")
        assert response["status"] == "ok"
        assert service.predictor.score_trace_count == traces  # warmed
    finally:
        service.drain()
        telemetry.get_registry().close()

    # a junk impl is refused with a clear error
    with pytest.raises(ValueError, match="score_impl"):
        serve_from_archive(
            archive, overrides='{"serving": {"score_impl": "raggedy"}}'
        )


# -- bench A/B record ----------------------------------------------------------

def test_serve_microbench_ab_emits_token_ledger(monkeypatch, capsys):
    """BENCH_MICRO=serve BENCH_SERVE_IMPL=ab at tiny geometry: one
    parseable record with all four legs' real/padded token counts,
    ragged real_token_utilization above bucketed on the same seeded
    skewed schedule, the continuous leg's queue-wait ledger, and the
    cascade leg's tier-split ledger — the CPU-runnable shape of the
    owed on-hardware datapoint."""
    from memvul_tpu import bench

    monkeypatch.setenv("BENCH_MICRO", "serve")
    monkeypatch.setenv("BENCH_MODEL", "tiny")
    monkeypatch.setenv("BENCH_SERVE_IMPL", "ab")
    monkeypatch.setenv("BENCH_MICRO_REQUESTS", "48")
    monkeypatch.setenv("BENCH_MICRO_CLIENTS", "4")
    monkeypatch.setenv("BENCH_SERVE_MAX_BATCH", "4")
    monkeypatch.setenv("BENCH_SEQ_LEN", "32")
    monkeypatch.setenv("BENCH_SERVE_TOKEN_BUDGET", "32")
    monkeypatch.setenv("BENCH_PHASE_TIMEOUT", "0")
    bench._run_bench()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    record = json.loads(line)
    assert record["metric"] == "serve_microbench"
    assert record["config"]["impl_mode"] == "ab"
    legs = record["ab"]
    assert set(legs) == {"bucketed", "ragged", "continuous", "cascade"}
    for leg in legs.values():
        assert leg["errors"] == 0
        assert leg["real_tokens"] > 0
        assert leg["padded_tokens"] >= leg["real_tokens"]
        assert 0 < leg["real_token_utilization"] <= 1
        # ab mode turns tracing on so the admission-wait comparison has
        # data in every leg
        assert leg["queue_wait_ms"] is not None
        assert leg["queue_wait_ms"]["p50"] >= 0
    assert (
        legs["ragged"]["real_token_utilization"]
        > legs["bucketed"]["real_token_utilization"]
    )
    # the continuous leg's headline: p50 admission wait vs ragged on the
    # identical schedule (the ≥3× acceptance bar needs high offered load
    # and a slow device — at this tiny geometry only presence is pinned)
    assert record["queue_wait_gain"] > 0
    assert record["impl"] == "continuous"
    assert record["value"] > 0
    # the cascade leg's headline pair: how much traffic the band rescued
    # and the cascade-vs-bucketed throughput ratio (the ≥2× bar needs the
    # MXU int8 rate — on CPU only presence and consistency are pinned)
    casc = legs["cascade"]
    # every request exits exactly one tier (+1 for the warmup trickle,
    # which lands in the leg's registry like everything else)
    assert casc["cascade_rescored"] + casc["cascade_shortcircuit"] == 49
    assert record["cascade_rescore_rate"] == casc["cascade_rescore_rate"]
    assert record["cascade_throughput_gain"] > 0
