"""TrainCheckpointer best-swap durability.

The reference keeps a 'best' weights dir updated whenever validation
improves (custom_trainer.py:746-754).  Ours swaps it via rename-aside so
a crash at any instant leaves a committed best under ``best`` or
``best_old``; these tests pin the happy path and the crash-window
recovery.
"""

import json

import numpy as np

from memvul_tpu.training.checkpoint import MetricTracker, TrainCheckpointer


def _state(v: float):
    return {"w": np.full((4,), v, dtype=np.float32)}


def test_best_swap_roundtrip(tmp_path):
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(1.0), is_best=True)
    ck.save(1, _state(2.0), is_best=True)
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 2.0))
    # no stale aside/tmp dirs left behind
    assert not (tmp_path / "ck" / "best_old").exists()
    assert not (tmp_path / "ck" / "best_tmp").exists()


def test_best_swap_crash_window_recovers_from_aside(tmp_path):
    """Simulate a crash between 'move old best aside' and 'rename new into
    place': only ``best_old`` exists.  restore_best must recover it."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(3.0), is_best=True)
    ck.flush()
    best = tmp_path / "ck" / "best"
    best.rename(tmp_path / "ck" / "best_old")  # the crash left this state
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 3.0))
    assert best.exists()  # recovered into place


def test_best_swap_crash_window_prefers_committed_tmp(tmp_path):
    """Crash after ``best_old`` was moved aside AND the replacement
    committed under ``best_tmp`` (but before its rename): recovery must
    promote the NEWER best_tmp, not roll back to best_old — epoch
    metadata already records the newer epoch as best."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(1.0), is_best=True)
    ck.flush()
    base = tmp_path / "ck"
    (base / "best").rename(base / "best_old")  # older best, moved aside
    ck._best_ckptr.save(base / "best_tmp", _state(9.0))  # newer, committed
    ck._best_ckptr.wait_until_finished()
    restored = ck.restore_best(_state(0.0))
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 9.0))
    # a later save cleans up the leftover aside copy
    ck.save(1, _state(10.0), is_best=True)
    ck.flush()
    assert not (base / "best_old").exists()
    ck.close()


def test_best_swap_crash_window_tmp_beside_best_prefers_tmp(tmp_path):
    """Crash after ``best_tmp`` committed but BEFORE the old best was
    renamed aside: both ``best`` and ``best_tmp`` exist.  best_tmp is the
    newer committed copy (the swap writes it first), and the epoch
    checkpoint's MetricTracker already records the newer epoch as best —
    recovery must promote best_tmp over the stale best (round-4 advisor)."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(1.0), is_best=True)
    ck.flush()
    base = tmp_path / "ck"
    ck._best_ckptr.save(base / "best_tmp", _state(9.0))  # newer, committed
    ck._best_ckptr.wait_until_finished()
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 9.0))
    assert not (base / "best_tmp").exists()


def test_best_save_cleans_orbax_staging_litter(tmp_path):
    """A crash mid-write leaves orbax staging dirs beside the exact
    ``best_tmp`` name (``best_tmp.orbax-checkpoint-tmp-*``); the next
    best save must glob them away, not just the exact paths
    (round-4 advisor)."""
    ck = TrainCheckpointer(tmp_path / "ck")
    litter = tmp_path / "ck" / "best_tmp.orbax-checkpoint-tmp-1234"
    litter.mkdir(parents=True)
    (litter / "partial").write_text("half-written")
    ck.save(0, _state(2.0), is_best=True)
    ck.flush()
    assert not litter.exists()
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 2.0))


def test_first_best_save_crash_leaves_only_tmp(tmp_path):
    """Crash after the very first best save committed ``best_tmp`` but
    before any rename: restore_best must still find it."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck._best_ckptr.save(tmp_path / "ck" / "best_tmp", _state(5.0))
    ck._best_ckptr.wait_until_finished()
    restored = ck.restore_best(_state(0.0))
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 5.0))
    ck.close()


def test_restore_best_none_when_never_saved(tmp_path):
    ck = TrainCheckpointer(tmp_path / "ck")
    assert ck.restore_best(_state(0.0)) is None
    ck.close()


# -- checksum manifests + corrupt-fallback -----------------------------------


def _corrupt_one_payload(ckpt_dir):
    """Flip bytes in the first non-metadata payload file of an orbax
    checkpoint dir (what a torn disk write / bit rot looks like)."""
    for f in sorted(ckpt_dir.rglob("*")):
        if f.is_file() and f.stat().st_size > 8 and "METADATA" not in f.name:
            f.write_bytes(b"\xde\xad\xbe\xef" + f.read_bytes()[4:])
            return f
    raise AssertionError(f"no payload file found under {ckpt_dir}")


def test_manifest_written_and_verifies(tmp_path):
    ck = TrainCheckpointer(tmp_path / "ck", max_to_keep=2)
    ck.save(0, _state(1.0))
    ck.flush()
    manifest = json.loads((tmp_path / "ck" / "manifest_epochs_0.json").read_text())
    assert manifest["files"], "manifest recorded no files"
    assert ck.verify_manifest("epochs", 0)
    ck.close()


def test_restore_latest_falls_back_past_corrupt_newest(tmp_path):
    """The newest checkpoint fails its checksum manifest → restore_latest
    returns the previous good generation instead of poisoned state (this
    is why max_to_keep defaults to 2)."""
    ck = TrainCheckpointer(tmp_path / "ck", max_to_keep=2)
    ck.save(0, _state(1.0))
    ck.save(1, _state(2.0))
    ck.flush()
    _corrupt_one_payload(tmp_path / "ck" / "epochs" / "1")
    assert not ck.verify_manifest("epochs", 1)
    restored = ck.restore_latest(_state(0.0))
    ck.close()
    assert restored is not None
    step, state = restored
    assert step == 0
    np.testing.assert_array_equal(state["w"], np.full((4,), 1.0))


def test_step_checkpoint_roundtrip_with_metadata(tmp_path):
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save_step(7, _state(3.0), metadata={"epoch": 1, "stacks_done": 4})
    assert ck.latest_step_checkpoint() == 7
    assert ck.verify_manifest("steps", 7)
    assert ck.step_metadata(7) == {"epoch": 1, "stacks_done": 4}
    step, state = ck.restore_latest_step(_state(0.0))
    ck.close()
    assert step == 7
    np.testing.assert_array_equal(state["w"], np.full((4,), 3.0))


def test_step_restore_falls_back_past_corrupt_newest(tmp_path):
    ck = TrainCheckpointer(tmp_path / "ck", max_to_keep=2)
    ck.save_step(4, _state(4.0))
    ck.save_step(8, _state(8.0))
    _corrupt_one_payload(tmp_path / "ck" / "steps" / "8")
    step, state = ck.restore_latest_step(_state(0.0))
    ck.close()
    assert step == 4
    np.testing.assert_array_equal(state["w"], np.full((4,), 4.0))


def test_metadata_sidecar_written_atomically(tmp_path):
    """metrics_epoch_N.json goes through the tmp+os.replace helper: no
    torn halves, no tmp litter left beside it."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(1.0), metadata={"loss": 0.5})
    ck.flush()
    assert json.loads((tmp_path / "ck" / "metrics_epoch_0.json").read_text()) == {
        "loss": 0.5
    }
    assert list((tmp_path / "ck").glob("*.tmp.*")) == []
    ck.close()


def test_stale_manifests_pruned_with_gc(tmp_path):
    """max_to_keep GC deletes old checkpoint dirs; their manifests must
    not outlive them (a stale manifest could veto a fresh step number)."""
    ck = TrainCheckpointer(tmp_path / "ck", max_to_keep=2)
    for i in range(4):
        ck.save(i, _state(float(i)))
    ck.flush()
    live = {p.name for p in (tmp_path / "ck").glob("manifest_epochs_*.json")}
    assert live == {"manifest_epochs_2.json", "manifest_epochs_3.json"}
    ck.close()


# -- MetricTracker resume semantics ------------------------------------------


def test_metric_tracker_state_roundtrip_preserves_patience():
    """Early stopping must fire at the SAME epoch whether or not the
    tracker was serialized/restored mid-run — the trainer-resume
    contract for patience counting."""
    values = [0.5, 0.6, 0.55, 0.58, 0.59, 0.52]  # best at epoch 1
    uninterrupted = MetricTracker("+s_f1-score", patience=3)
    stop_epoch = None
    for epoch, v in enumerate(values):
        uninterrupted.update({"s_f1-score": v}, epoch)
        if uninterrupted.should_stop():
            stop_epoch = epoch
            break
    assert stop_epoch == 4  # 3 epochs without improvement after epoch 1

    resumed = MetricTracker("+s_f1-score", patience=3)
    for epoch, v in enumerate(values):
        resumed.update({"s_f1-score": v}, epoch)
        # checkpoint/restore between EVERY epoch
        fresh = MetricTracker("+s_f1-score", patience=3)
        fresh.load_state_dict(json.loads(json.dumps(resumed.state_dict())))
        resumed = fresh
        if resumed.should_stop():
            assert epoch == stop_epoch
            break
    else:
        raise AssertionError("restored tracker never fired early stopping")
    assert resumed.best_epoch == uninterrupted.best_epoch == 1
    assert resumed.best == uninterrupted.best


def test_metric_tracker_roundtrip_through_json_with_none_best():
    """A tracker checkpointed before its first validation (best=None)
    must survive the JSON round-trip the step-metadata sidecar uses."""
    t = MetricTracker("-loss", patience=2)
    restored = MetricTracker("-loss", patience=2)
    restored.load_state_dict(json.loads(json.dumps(t.state_dict())))
    assert restored.best is None and restored.epochs_without_improvement == 0
    assert restored.update({"loss": 1.0}, 0) is True
