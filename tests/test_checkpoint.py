"""TrainCheckpointer best-swap durability.

The reference keeps a 'best' weights dir updated whenever validation
improves (custom_trainer.py:746-754).  Ours swaps it via rename-aside so
a crash at any instant leaves a committed best under ``best`` or
``best_old``; these tests pin the happy path and the crash-window
recovery.
"""

import numpy as np

from memvul_tpu.training.checkpoint import TrainCheckpointer


def _state(v: float):
    return {"w": np.full((4,), v, dtype=np.float32)}


def test_best_swap_roundtrip(tmp_path):
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(1.0), is_best=True)
    ck.save(1, _state(2.0), is_best=True)
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 2.0))
    # no stale aside/tmp dirs left behind
    assert not (tmp_path / "ck" / "best_old").exists()
    assert not (tmp_path / "ck" / "best_tmp").exists()


def test_best_swap_crash_window_recovers_from_aside(tmp_path):
    """Simulate a crash between 'move old best aside' and 'rename new into
    place': only ``best_old`` exists.  restore_best must recover it."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(3.0), is_best=True)
    ck.flush()
    best = tmp_path / "ck" / "best"
    best.rename(tmp_path / "ck" / "best_old")  # the crash left this state
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 3.0))
    assert best.exists()  # recovered into place


def test_best_swap_crash_window_prefers_committed_tmp(tmp_path):
    """Crash after ``best_old`` was moved aside AND the replacement
    committed under ``best_tmp`` (but before its rename): recovery must
    promote the NEWER best_tmp, not roll back to best_old — epoch
    metadata already records the newer epoch as best."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(1.0), is_best=True)
    ck.flush()
    base = tmp_path / "ck"
    (base / "best").rename(base / "best_old")  # older best, moved aside
    ck._best_ckptr.save(base / "best_tmp", _state(9.0))  # newer, committed
    ck._best_ckptr.wait_until_finished()
    restored = ck.restore_best(_state(0.0))
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 9.0))
    # a later save cleans up the leftover aside copy
    ck.save(1, _state(10.0), is_best=True)
    ck.flush()
    assert not (base / "best_old").exists()
    ck.close()


def test_best_swap_crash_window_tmp_beside_best_prefers_tmp(tmp_path):
    """Crash after ``best_tmp`` committed but BEFORE the old best was
    renamed aside: both ``best`` and ``best_tmp`` exist.  best_tmp is the
    newer committed copy (the swap writes it first), and the epoch
    checkpoint's MetricTracker already records the newer epoch as best —
    recovery must promote best_tmp over the stale best (round-4 advisor)."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck.save(0, _state(1.0), is_best=True)
    ck.flush()
    base = tmp_path / "ck"
    ck._best_ckptr.save(base / "best_tmp", _state(9.0))  # newer, committed
    ck._best_ckptr.wait_until_finished()
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 9.0))
    assert not (base / "best_tmp").exists()


def test_best_save_cleans_orbax_staging_litter(tmp_path):
    """A crash mid-write leaves orbax staging dirs beside the exact
    ``best_tmp`` name (``best_tmp.orbax-checkpoint-tmp-*``); the next
    best save must glob them away, not just the exact paths
    (round-4 advisor)."""
    ck = TrainCheckpointer(tmp_path / "ck")
    litter = tmp_path / "ck" / "best_tmp.orbax-checkpoint-tmp-1234"
    litter.mkdir(parents=True)
    (litter / "partial").write_text("half-written")
    ck.save(0, _state(2.0), is_best=True)
    ck.flush()
    assert not litter.exists()
    restored = ck.restore_best(_state(0.0))
    ck.close()
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 2.0))


def test_first_best_save_crash_leaves_only_tmp(tmp_path):
    """Crash after the very first best save committed ``best_tmp`` but
    before any rename: restore_best must still find it."""
    ck = TrainCheckpointer(tmp_path / "ck")
    ck._best_ckptr.save(tmp_path / "ck" / "best_tmp", _state(5.0))
    ck._best_ckptr.wait_until_finished()
    restored = ck.restore_best(_state(0.0))
    assert restored is not None
    np.testing.assert_array_equal(restored["w"], np.full((4,), 5.0))
    ck.close()


def test_restore_best_none_when_never_saved(tmp_path):
    ck = TrainCheckpointer(tmp_path / "ck")
    assert ck.restore_best(_state(0.0)) is None
    ck.close()
