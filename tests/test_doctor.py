"""``python -m memvul_tpu doctor`` — environment/artifact diagnosis.

The reference has no operational tooling; the doctor front-loads the
failures its users hit hours into a run (missing vocab → silent fallback
tokenization, missing corpus files, wedged device).  These tests pin the
report contract on the virtual CPU mesh.
"""

import json

import pytest

from memvul_tpu.__main__ import main
from memvul_tpu.data.synthetic import build_workspace, selfcheck_config


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("doctor"), seed=7)


def _write_config(ws, path):
    path.write_text(json.dumps(selfcheck_config(ws)))
    return path


def test_doctor_ok_on_complete_workspace(ws, tmp_path, capsys):
    cfg = _write_config(ws, tmp_path / "config.json")
    rc = main(["doctor", "--config", str(cfg), "--device-timeout", "120"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0, report
    assert report["ok"] is True
    assert report["backend"]["devices"] >= 1
    assert report["mesh"]["ok"] is True
    assert report["vocabulary"]["ok"] is True
    assert report["data_artifacts"]["missing"] == []
    assert report["compile_cache"]["dir"]


def test_doctor_flags_missing_artifacts(ws, tmp_path, capsys):
    cfg_dict = selfcheck_config(ws)
    cfg_dict["train_data_path"] = str(tmp_path / "nope.json")
    cfg_dict["tokenizer"] = {"type": "wordpiece",
                             "vocab_path": str(tmp_path / "no_vocab.txt")}
    cfg = tmp_path / "config.json"
    cfg.write_text(json.dumps(cfg_dict))
    rc = main(["doctor", "--config", str(cfg), "--skip-device"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["ok"] is False
    assert "train_data_path" in report["data_artifacts"]["missing"]
    assert report["vocabulary"]["ok"] is False
    assert report["backend"] == {"ok": True, "skipped": True}
    assert report["mesh"] == {"ok": True, "skipped": True}  # no device op


def test_doctor_malformed_config_stays_a_report(tmp_path, capsys):
    """A syntax error in the config must land in the JSON report, never
    escape as a traceback (round-5 review)."""
    bad = tmp_path / "bad.json"
    bad.write_text('{"tokenizer": }')
    rc = main(["doctor", "--config", str(bad), "--skip-device"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["vocabulary"]["ok"] is False
    assert "Error" in report["vocabulary"]["error"]
    assert report["data_artifacts"]["error"] == report["vocabulary"]["error"]


def test_doctor_fallback_tokenizer_is_ok_with_note(ws, tmp_path, capsys):
    """Trained-tokenizer fallback: usable (ok) but the report must say
    reference parity needs the genuine vocab."""
    cfg_dict = selfcheck_config(ws)
    # selfcheck config names only tokenizer_path (the trained artifact)
    assert "vocab_path" not in (cfg_dict.get("tokenizer") or {})
    cfg = tmp_path / "config.json"
    cfg.write_text(json.dumps(cfg_dict))
    rc = main(["doctor", "--config", str(cfg), "--skip-device"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["vocabulary"]["ok"] is True
    assert "FALLBACK" in report["vocabulary"]["note"]


def test_doctor_missing_config_reports_cleanly(tmp_path, capsys):
    rc = main(["doctor", "--config", str(tmp_path / "absent.json"),
               "--skip-device"])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert report["vocabulary"]["ok"] is False
    assert "FileNotFoundError" in report["vocabulary"]["error"]
