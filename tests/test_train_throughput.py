"""The training-throughput subsystem (docs/training_throughput.md):
bucketed pair collation, in-batch anchor dedup, the double-buffered
device feed, and the train_step microbench record.

The contracts pinned here:

* pair routing is a partition over (len1, len2) grid cells and the
  dedup gather reconstructs every side-2 row exactly (property);
* the train step is padding-invariant — dead rows / growing to the
  next bucket leave loss and grad-norm unchanged (property);
* deduped vs undeduped whole-step loss parity ≤ 1e-5, and duplicate
  pairs share one embedding row bitwise;
* a short bucketed training run compiles exactly the stack-shape set
  the collator emits — no mid-epoch recompiles (train_trace_count);
* prefetch commits on the worker thread and reports queue occupancy;
* feed-depth / bucket-grid validation fails fast in config and at
  trainer construction;
* CachedEncoder hit/miss + truncation telemetry counters count.
"""

import json
import threading

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from memvul_tpu import telemetry
from memvul_tpu.data.batching import (
    CachedEncoder,
    bucketed_pair_batches_from_instances,
    dedup_capacities,
    pow2_buckets,
    prefetch,
    resolve_train_buckets,
)
from memvul_tpu.data.readers import MemoryReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.training.trainer import MemoryTrainer, TrainerConfig, make_train_step


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("tt"), seed=5)


@pytest.fixture(autouse=True)
def _clean_telemetry():
    yield
    telemetry.reset()


class StubEncoder:
    """Encodes a text like "7" or "7:3" as that many distinct-ish token
    ids — length (and identity) fully controlled by the text."""

    pad_id = 0
    max_length = 64

    def __call__(self, text):
        n = int(text.split(":")[0])
        salt = int(text.split(":")[1]) if ":" in text else 0
        return [1 + salt] * min(n, self.max_length)


def pair(n1, n2, label="same", url="u"):
    return {
        "text1": str(n1), "text2": str(n2), "label": label,
        "meta": {"Issue_Url": url},
    }


# -- collator unit behavior ----------------------------------------------------


def test_pair_collator_per_side_buckets_and_dedup():
    insts = [
        pair(5, 3, url=f"u{i}") for i in range(3)
    ] + [pair(5, "3:1", url="u3")]  # same lengths, one distinct side-2 text
    batches = list(
        bucketed_pair_batches_from_instances(
            iter(insts), StubEncoder(), batch_size=4, buckets=(8, 16, 64),
        )
    )
    assert len(batches) == 1
    b = batches[0]
    # per-side bucket lengths: both sides fit the 8 bucket independently
    assert b["sample1"]["input_ids"].shape == (4, 8)
    # dedup: 2 unique side-2 texts → capacity ladder floor (min(8, B)=4)
    assert b["sample2"]["input_ids"].shape == (4, 8)
    assert b["sample2_index"].tolist() == [0, 0, 0, 1]
    # unique rows beyond U are pad (dead) rows
    assert int(b["sample2"]["attention_mask"][2:].sum()) == 0


def test_pair_collator_routes_to_separate_cells_and_flushes_tails():
    insts = [pair(5, 3, url="a"), pair(30, 3, url="b"), pair(5, 3, url="c")]
    batches = list(
        bucketed_pair_batches_from_instances(
            iter(insts), StubEncoder(), batch_size=2, buckets=(8, 64),
        )
    )
    # cell (8, 8) fills with a+c; cell (64, 8) tail-flushes with b
    assert len(batches) == 2
    assert batches[0]["sample1"]["input_ids"].shape == (2, 8)
    assert [m["Issue_Url"] for m in batches[0]["meta"]] == ["a", "c"]
    assert batches[1]["sample1"]["input_ids"].shape == (2, 64)
    assert batches[1]["weight"].tolist() == [1.0, 0.0]


def test_pair_collator_per_bucket_batch_sizes():
    insts = [pair(5, 3, url=f"s{i}") for i in range(4)] + [
        pair(30, 3, url=f"l{i}") for i in range(2)
    ]
    batches = list(
        bucketed_pair_batches_from_instances(
            iter(insts), StubEncoder(), batch_size={8: 4, 64: 2},
            buckets=(8, 64),
        )
    )
    shapes = sorted(b["sample1"]["input_ids"].shape for b in batches)
    assert shapes == [(2, 64), (4, 8)]


def test_dedup_capacities_ladder():
    assert dedup_capacities(32) == (8, 16, 32)
    assert dedup_capacities(4) == (4,)
    assert dedup_capacities(12) == (8, 12)
    assert dedup_capacities(64, floor=16) == (16, 32, 64)


def test_pow2_and_resolve_train_buckets():
    assert pow2_buckets(256) == (64, 128, 256)
    assert pow2_buckets(32) == (32,)
    assert pow2_buckets(512) == (64, 128, 256, 512)
    assert resolve_train_buckets(None, 256) is None
    assert resolve_train_buckets("pow2", 256) == (64, 128, 256)
    assert resolve_train_buckets([16, 64], 64) == (16, 64)
    with pytest.raises(ValueError, match="largest bucket"):
        resolve_train_buckets([16, 32], 64)
    with pytest.raises(ValueError, match="not understood"):
        resolve_train_buckets("auto", 64)


def test_pair_collator_partition_property():
    """Property: every pair lands in exactly one batch row of its
    smallest covering (len1, len2) cell, and the dedup gather
    reconstructs every side-2 row exactly."""
    pytest.importorskip("hypothesis")  # property tier is optional
    from hypothesis import given, settings, strategies as st

    buckets = (8, 16, 64)

    @settings(max_examples=60, deadline=None)
    @given(
        st.lists(
            st.tuples(
                st.integers(min_value=1, max_value=64),   # len1
                st.integers(min_value=1, max_value=64),   # len2
                st.integers(min_value=0, max_value=3),    # side-2 salt
            ),
            max_size=30,
        ),
        st.integers(min_value=1, max_value=5),
    )
    def check(specs, batch_size):
        insts = [
            pair(n1, f"{n2}:{salt}", url=f"u{i}")
            for i, (n1, n2, salt) in enumerate(specs)
        ]
        enc = StubEncoder()
        seen = []
        for batch in bucketed_pair_batches_from_instances(
            iter(insts), enc, batch_size, buckets=buckets
        ):
            ids1 = batch["sample1"]["input_ids"]
            ids2 = batch["sample2"]["input_ids"]
            index = batch["sample2_index"]
            assert ids1.shape[0] == batch_size
            assert ids2.shape[0] in dedup_capacities(batch_size)
            for row, meta in enumerate(batch["meta"]):
                i = int(meta["Issue_Url"][1:])
                seen.append(i)
                n1, n2, salt = specs[i]
                # smallest covering cell, per side
                assert ids1.shape[1] == next(b for b in buckets if b >= n1)
                assert ids2.shape[1] == next(b for b in buckets if b >= n2)
                # the gather reconstructs the row's exact token sequence
                expect = enc(f"{n2}:{salt}")
                got = ids2[index[row]]
                assert got[: len(expect)].tolist() == expect
                assert int(got[len(expect):].sum()) == 0
        assert sorted(seen) == list(range(len(specs)))

    check()


# -- step math: padding invariance + dedup parity ------------------------------


@pytest.fixture(scope="module")
def tiny_model(ws):
    # dropout 0: the invariance claims are about padding/dedup, not about
    # reshaped dropout masks (docs/training_throughput.md notes the
    # dropout caveat; the e2e trainer tests cover dropout-on training)
    cfg = BertConfig.tiny(
        vocab_size=ws["tokenizer"].vocab_size,
        hidden_dropout=0.0,
        attention_dropout=0.0,
    )
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    import optax

    tx = optax.sgd(1e-3)
    opt_state = tx.init(params)
    # the RAW (unjitted) step: the parity tests below run it eagerly so
    # tier-1 pays no per-variant compiles; the property test jits it
    # itself (fixed shape set → each variant compiles once)
    step = make_train_step(model, tx)
    return model, params, tx, opt_state, step


def _stats_for(step, params, opt_state, stack):
    _, _, _, stats = step(params, opt_state, jax.random.PRNGKey(7), stack)
    return float(stats["loss"]), float(stats["grad_norm"])


def _block(rows, length, vocab=50):
    rng = np.random.default_rng(0)
    ids = np.zeros((len(rows), length), np.int32)
    mask = np.zeros((len(rows), length), np.int32)
    for i, n in enumerate(rows):
        ids[i, :n] = rng.integers(5, vocab, n)
        mask[i, :n] = 1
    return {"input_ids": ids, "attention_mask": mask}


def _grow(block, length):
    rows, old = block["input_ids"].shape
    out = {
        "input_ids": np.zeros((rows, length), np.int32),
        "attention_mask": np.zeros((rows, length), np.int32),
    }
    out["input_ids"][:, :old] = block["input_ids"]
    out["attention_mask"][:, :old] = block["attention_mask"]
    return out


def _dead_rows(block, extra):
    rows, length = block["input_ids"].shape
    return {
        k: np.concatenate([v, np.zeros((extra, length), np.int32)])
        for k, v in block.items()
    }


def test_padding_invariance_property(tiny_model):
    """Property: appending dead (zero-weight) rows or growing a batch to
    the next bucket length leaves the train step's loss and grad-norm
    unchanged — the guarantee that lets the bucketed collation replace
    pad-to-max without touching gradient math."""
    pytest.importorskip("hypothesis")
    from hypothesis import given, settings, strategies as st

    model, params, tx, opt_state, raw_step = tiny_model
    step = jax.jit(raw_step)  # no donation: params reused across variants

    # shapes drawn from a fixed set so jit caches across examples
    @settings(max_examples=10, deadline=None)
    @given(
        st.lists(st.integers(min_value=1, max_value=16), min_size=2, max_size=2),
        st.lists(st.integers(min_value=1, max_value=16), min_size=2, max_size=2),
        st.lists(st.integers(min_value=0, max_value=1), min_size=2, max_size=2),
    )
    def check(lens1, lens2, labels):
        base = {
            "sample1": _block(lens1, 16),
            "sample2": _block(lens2, 16),
            "label": np.asarray(labels, np.int32),
            "weight": np.ones(2, np.float32),
        }
        stack = lambda b: jax.tree_util.tree_map(lambda x: x[None], b)
        loss0, gn0 = _stats_for(step, params, opt_state, stack(base))

        dead = dict(base)
        dead["sample1"] = _dead_rows(base["sample1"], 2)
        dead["sample2"] = _dead_rows(base["sample2"], 2)
        dead["label"] = np.concatenate([base["label"], np.zeros(2, np.int32)])
        dead["weight"] = np.concatenate([base["weight"], np.zeros(2, np.float32)])
        loss1, gn1 = _stats_for(step, params, opt_state, stack(dead))

        grown = dict(base)
        grown["sample1"] = _grow(base["sample1"], 32)
        grown["sample2"] = _grow(base["sample2"], 32)
        loss2, gn2 = _stats_for(step, params, opt_state, stack(grown))

        assert loss1 == pytest.approx(loss0, abs=1e-5)
        assert gn1 == pytest.approx(gn0, rel=1e-5, abs=1e-6)
        assert loss2 == pytest.approx(loss0, abs=1e-5)
        assert gn2 == pytest.approx(gn0, rel=1e-5, abs=1e-6)

    check()


def test_dedup_step_parity_and_bitwise_gather(tiny_model):
    """Deduped batch (unique sample2 + gather) vs physically duplicated
    sample2: whole-step loss parity ≤ 1e-5, and duplicate pairs share
    one embedding row bitwise through the gather."""
    model, params, tx, opt_state, raw_step = tiny_model
    step = jax.jit(raw_step)  # two structures → two programs, no donation
    unique = _block([7, 4], 16, vocab=40)  # 2 unique side-2 texts
    index = np.asarray([0, 1, 0, 0], np.int32)  # heavy duplication
    full = {k: v[index] for k, v in unique.items()}  # undeduped twin
    sample1 = _block([9, 12, 5, 3], 16)
    label = np.asarray([0, 1, 0, 1], np.int32)
    weight = np.ones(4, np.float32)

    stack = lambda b: jax.tree_util.tree_map(lambda x: x[None], b)
    deduped = {
        "sample1": sample1, "sample2": unique, "sample2_index": index,
        "label": label, "weight": weight,
    }
    undeduped = {
        "sample1": sample1, "sample2": full, "label": label, "weight": weight,
    }
    loss_d, gn_d = _stats_for(step, params, opt_state, stack(deduped))
    loss_u, gn_u = _stats_for(step, params, opt_state, stack(undeduped))
    assert loss_d == pytest.approx(loss_u, abs=1e-5)
    assert gn_d == pytest.approx(gn_u, rel=1e-4, abs=1e-6)

    # the gather alone is bitwise: duplicate pairs see ONE embedding row
    v = model.apply(params, unique)  # encode → [U, D]
    gathered = jnp.take(v, index, axis=0)
    np.testing.assert_array_equal(
        np.asarray(gathered[0]), np.asarray(gathered[2])
    )
    np.testing.assert_array_equal(
        np.asarray(gathered[0]), np.asarray(v[0])
    )


# -- compile-count pinning -----------------------------------------------------


def make_trainer(ws, **cfg_kw):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"],
        anchor_path=ws["paths"]["anchors"],
        same_diff_ratio={"same": 2, "diff": 2},
        sample_neg=0.5,
        seed=2021,
    )
    defaults = dict(
        num_epochs=1, patience=None, batch_size=4, grad_accum=2,
        max_length=32, warmup_steps=2, base_lr=1e-3, serialization_dir=None,
    )
    defaults.update(cfg_kw)
    return MemoryTrainer(
        model, params, ws["tokenizer"], reader,
        train_path=ws["paths"]["train"],
        config=TrainerConfig(**defaults),
    )


STEP_CAP = 4  # the ws-seed-5 stream crosses two grid cells by stack 3
# (probed: cells (16,32) at stacks 0,1,3 and (16,16) at stack 2), so
# four stacks pin a multi-shape compile count at tier-1 cost


def test_bucketed_training_compile_count_pinned(ws):
    """A short bucketed run compiles exactly one step program per
    distinct stack shape the collator emits — and a second pass over the
    same epoch compiles NOTHING new (no mid-epoch/mid-run recompiles)."""
    trainer = make_trainer(ws, train_buckets=[16, 32], steps_per_epoch=STEP_CAP)
    # enumerate the epoch's first STEP_CAP stack shapes by dry-running
    # the collation (deterministic: the per-epoch reseed replays the
    # same stream, and train_epoch trains exactly these stacks)
    shapes = set()
    for n, (stack, _info) in enumerate(trainer._microbatch_stacks()):
        if n >= STEP_CAP:
            break
        shapes.add(str(jax.tree_util.tree_map(lambda x: x.shape, stack)))
    assert len(shapes) > 1  # the grid actually produced multiple shapes
    m = trainer.train_epoch()
    assert trainer.train_trace_count == len(shapes)
    trainer.train_epoch()  # same epoch stream again: fully cache-hit
    assert trainer.train_trace_count == len(shapes)
    # the same epoch also pins the token accounting: bucketing means the
    # device computed over fewer padded tokens than pad-to-max would,
    # and real (unpadded+deduped) work is what's left
    assert 0 < m["real_tokens"] < m["padded_tokens"]
    assert m["real_tokens_per_sec"] < m["tokens_per_sec"]
    assert m["num_steps"] > 0


# (the pad-to-max legacy path is exercised end-to-end — including its
# single-program compile count and exact padded-token accounting — by
# the BENCH_MICRO=train_step record test below)


# -- feed: prefetch commit + occupancy -----------------------------------------


class FakeGauge:
    def __init__(self):
        self.values = []

    def set(self, v):
        self.values.append(v)


def test_prefetch_commits_on_worker_and_reports_occupancy():
    gauge = FakeGauge()
    commit_threads = []

    def commit(x):
        commit_threads.append(threading.current_thread())
        return x * 10

    out = list(prefetch(iter(range(8)), depth=3, commit=commit, occupancy=gauge))
    assert out == [i * 10 for i in range(8)]
    assert commit_threads and all(
        t is not threading.main_thread() for t in commit_threads
    )
    assert gauge.values and all(0 <= v <= 3 for v in gauge.values)
    assert gauge.values[-1] == 0  # drained


def test_prefetch_depth_validated_everywhere(ws):
    from memvul_tpu.config import validate_training_config
    from memvul_tpu.training.single_trainer import ClassifierTrainerConfig

    with pytest.raises(ValueError, match="prefetch_depth"):
        make_trainer(ws, prefetch_depth=0)
    with pytest.raises(ValueError, match="prefetch_depth"):
        validate_training_config({"prefetch_depth": 0})
    with pytest.raises(ValueError, match="largest bucket"):
        validate_training_config({"train_buckets": [16], "max_length": 64})
    with pytest.raises(ValueError, match="dedup_anchors"):
        validate_training_config({"dedup_anchors": "false"})
    assert validate_training_config(None) == {}
    assert validate_training_config(
        {"prefetch_depth": 2, "train_buckets": "pow2"}
    )["prefetch_depth"] == 2
    # the dataclass default passes its own construction-time check
    assert ClassifierTrainerConfig().prefetch_depth >= 1


# -- telemetry counters --------------------------------------------------------


def test_encode_cache_hit_miss_counters(ws):
    tel = telemetry.configure(run_dir=None, enabled=True)
    enc = CachedEncoder(ws["tokenizer"], max_length=16)
    enc("alpha beta")
    enc("alpha beta")
    enc.encode_many(["alpha beta", "gamma", "gamma"])
    assert tel.counter("data.encode_cache_misses").value == 2  # alpha, gamma
    assert tel.counter("data.encode_cache_hits").value == 3


def test_truncation_past_largest_bucket_counted():
    from memvul_tpu.data.batching import _bucket_for

    tel = telemetry.configure(run_dir=None, enabled=True)
    assert _bucket_for(7, (8, 16)) == 8
    assert tel.counter("data.truncated_sequences").value == 0
    assert _bucket_for(40, (8, 16)) == 16  # explicit clamp, counted
    assert tel.counter("data.truncated_sequences").value == 1


def test_report_renders_cache_hit_rate(tmp_path):
    from memvul_tpu.telemetry.report import render_report

    tel = telemetry.configure(run_dir=tmp_path, enabled=True)
    tel.counter("data.encode_cache_hits").inc(30)
    tel.counter("data.encode_cache_misses").inc(10)
    tel.close()
    out = render_report(tmp_path)
    assert "data.encode_cache_hit_rate = 0.750 (30/40 lookups)" in out


# -- microbench record ---------------------------------------------------------


def test_train_step_microbench_emits_parseable_record(monkeypatch, capsys):
    """BENCH_MICRO=train_step at tiny geometry: the full A/B path runs on
    CPU and lands one parseable JSON record with both paths' padded- and
    real-token throughput (the acceptance record format)."""
    from memvul_tpu import bench

    monkeypatch.setenv("BENCH_MICRO", "train_step")
    monkeypatch.setenv("BENCH_MODEL", "tiny")
    monkeypatch.setenv("BENCH_TRAIN_STEPS", "1")
    monkeypatch.setenv("BENCH_TRAIN_BATCH", "2")
    monkeypatch.setenv("BENCH_TRAIN_ACCUM", "1")
    monkeypatch.setenv("BENCH_TRAIN_REPORTS", "24")
    monkeypatch.setenv("BENCH_SEQ_LEN", "32")  # single-bucket grid at tiny
    monkeypatch.setenv("BENCH_PHASE_TIMEOUT", "0")
    bench._run_bench()
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["metric"] == "train_step_microbench"
    assert rec["value"] > 0
    for path in ("pad_to_max", "bucketed_dedup"):
        stats = rec[path]
        assert stats["steps"] == 1
        assert stats["padded_tokens_per_s"] > 0
        assert stats["real_tokens_per_s"] > 0
        assert stats["real_tokens"] <= stats["padded_tokens"]
        assert stats["compiled_step_shapes"] >= 1
    # pad-to-max is by construction a single compiled step program
    assert rec["pad_to_max"]["compiled_step_shapes"] == 1
    # the bucketed path computed over fewer padded tokens for the same
    # stream of real work — the waste the collation exists to cut
    assert (
        rec["bucketed_dedup"]["padded_tokens"]
        <= rec["pad_to_max"]["padded_tokens"]
    )
