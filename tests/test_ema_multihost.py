"""EMA moving-average training + multi-host helpers.

Reference: the trainer's moving_average support
(custom_trainer.py:437-439,514-516) and the distributed backend
(custom_trainer.py:254-259) — here a jax.distributed wrapper.
"""

import jax
import numpy as np
import pytest

from memvul_tpu.build import build_model, build_reader, build_tokenizer, init_params
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.parallel import multihost
from memvul_tpu.training.trainer import MemoryTrainer, TrainerConfig


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("ema"), seed=31)


def make_trainer(ws, **cfg_kw):
    tokenizer = build_tokenizer({"tokenizer_path": ws["paths"]["tokenizer"]})
    reader = build_reader({
        "type": "reader_memory", "sample_neg": 1.0,
        "same_diff_ratio": {"same": 2, "diff": 2},
        "cve_path": ws["paths"]["cve"], "anchor_path": ws["paths"]["anchors"],
    })
    model = build_model(
        {"type": "model_memory", "encoder": {"preset": "tiny", "vocab_size": 4096},
         "header_dim": 16}, tokenizer.vocab_size,
    )
    cfg = dict(num_epochs=1, batch_size=4, grad_accum=2, max_length=32,
               steps_per_epoch=3, warmup_steps=2)
    cfg.update(cfg_kw)
    return MemoryTrainer(
        model, init_params(model), tokenizer, reader,
        train_path=ws["paths"]["train"], config=TrainerConfig(**cfg),
    )


def _leaf(params):
    return np.asarray(params["params"]["pair_kernel"], np.float32)


def test_ema_tracks_behind_live_params(ws):
    trainer = make_trainer(ws, ema_decay=0.9)
    init = _leaf(trainer.params).copy()
    trainer.train_epoch()
    live, ema = _leaf(trainer.params), _leaf(trainer.ema_params)
    # live params moved; EMA moved less (it lags the trajectory)
    assert np.abs(live - init).max() > 0
    assert 0 < np.abs(ema - init).max() < np.abs(live - init).max()
    # best_params surfaces the EMA weights
    np.testing.assert_array_equal(_leaf(trainer.best_params()), ema)


def test_resume_across_ema_toggle(ws, tmp_path):
    """A serialization dir written WITHOUT ema must restore into a trainer
    WITH ema_decay set (ema seeded from live params), and vice versa —
    toggling ema_decay on an existing dir degrades gracefully."""
    ser = str(tmp_path / "toggle")
    t1 = make_trainer(ws, serialization_dir=ser)
    t1.train()
    assert t1.ema_params is None

    # off -> on: ema seeded from the restored params
    t2 = make_trainer(ws, serialization_dir=ser, ema_decay=0.9, num_epochs=2)
    assert t2.maybe_restore()
    assert t2.ema_params is not None
    np.testing.assert_array_equal(_leaf(t2.ema_params), _leaf(t2.params))

    # on -> off: checkpoint with ema restores into a plain trainer
    ser2 = str(tmp_path / "toggle2")
    t3 = make_trainer(ws, serialization_dir=ser2, ema_decay=0.9)
    t3.train()
    t4 = make_trainer(ws, serialization_dir=ser2, num_epochs=2)
    assert t4.maybe_restore()
    assert t4.ema_params is None


def test_ema_disabled_by_default(ws):
    trainer = make_trainer(ws)
    assert trainer.ema_params is None
    trainer.train_epoch()
    np.testing.assert_array_equal(_leaf(trainer.best_params()), _leaf(trainer.params))


def test_ema_checkpoint_roundtrip(ws, tmp_path):
    trainer = make_trainer(
        ws, ema_decay=0.9, serialization_dir=str(tmp_path / "ser"), num_epochs=1
    )
    trainer.train()
    ema = _leaf(trainer.ema_params)
    resumed = make_trainer(
        ws, ema_decay=0.9, serialization_dir=str(tmp_path / "ser"), num_epochs=1
    )
    assert resumed.maybe_restore()
    np.testing.assert_array_equal(_leaf(resumed.ema_params), ema)


def test_multihost_single_process_noop():
    assert multihost.initialize() is False  # nothing to join
    assert multihost.is_primary()
    assert multihost.process_count() == 1


def test_local_batch_slice(monkeypatch):
    s = multihost.local_batch_slice(64)
    assert (s.start, s.stop) == (0, 64)
    # simulate a 4-host run: process 1 owns rows [16, 32)
    monkeypatch.setattr(jax, "process_count", lambda: 4)
    monkeypatch.setattr(jax, "process_index", lambda: 1)
    s = multihost.local_batch_slice(64)
    assert (s.start, s.stop) == (16, 32)
    with pytest.raises(ValueError):
        multihost.local_batch_slice(7)
