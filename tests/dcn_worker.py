"""Worker for the two-process DCN smoke test (run via subprocess).

Each process joins the jax.distributed runtime through
``memvul_tpu.parallel.multihost.initialize`` — the TPU-native equivalent
of the reference's torch.distributed/NCCL backend init
(custom_trainer.py:254-259) — then proves the cross-process contract:

- process_count / is_primary reflect the 2-process launch
- ``local_batch_slice`` tiles the global batch across hosts
- a data-sharded global array reduces across processes (XLA inserts the
  DCN collective; on CPU it rides Gloo, on pods it rides DCN)

Writes one JSON line to the path in argv[3]; the pytest side asserts it.

Usage: python dcn_worker.py <process_id> <coordinator_port> <out_path>
"""

import json
import os
import sys

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from memvul_tpu.utils.platform import honor_platform_env  # noqa: E402

honor_platform_env()


def main() -> None:
    process_id = int(sys.argv[1])
    port = int(sys.argv[2])
    out_path = sys.argv[3]

    from memvul_tpu.parallel import multihost

    joined = multihost.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=2,
        process_id=process_id,
    )

    import jax
    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from memvul_tpu.parallel.multihost import local_batch_slice

    sl = local_batch_slice(8)

    # each process contributes only ITS slice of the global batch (the
    # host-side input pipeline contract), then one jit reduces globally
    mesh = Mesh(np.array(jax.devices()).reshape(-1), ("data",))
    global_batch = np.arange(8, dtype=np.float32)
    local = global_batch[sl]
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, P("data")), local, global_shape=(8,)
    )
    total = jax.jit(
        lambda x: jnp.sum(x), out_shardings=NamedSharding(mesh, P())
    )(arr)

    result = {
        "joined": bool(joined),
        "process_id": process_id,
        "process_count": multihost.process_count(),
        "is_primary": multihost.is_primary(),
        "local_devices": jax.local_device_count(),
        "global_devices": jax.device_count(),
        "slice": [sl.start, sl.stop],
        "global_sum": float(total),
    }
    with open(out_path, "w") as f:
        json.dump(result, f)


if __name__ == "__main__":
    main()
