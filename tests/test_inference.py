import json
from pathlib import Path

import numpy as np
import pytest

import jax

from memvul_tpu.data.readers import MemoryReader, SingleReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.evaluate import cal_metrics
from memvul_tpu.evaluate import test_siamese as run_siamese_eval
from memvul_tpu.evaluate import test_single as run_single_eval
from memvul_tpu.evaluate.predict_memory import SiamesePredictor
from memvul_tpu.models import BertConfig, MemoryModel, SingleModel
from memvul_tpu.parallel import create_mesh


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("infer"), seed=3)


@pytest.fixture(scope="module")
def memory_setup(ws):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    return model, params, reader


def test_full_siamese_eval_pipeline(ws, memory_setup, tmp_path):
    model, params, reader = memory_setup
    out_results = tmp_path / "memvul_result.json"
    out_metrics = tmp_path / "memvul_metric_all.json"
    metrics = run_siamese_eval(
        model, params, ws["tokenizer"],
        test_file=ws["paths"]["test"],
        golden_file=ws["paths"]["anchors"],
        out_results=out_results,
        out_metrics=out_metrics,
        reader=reader,
        batch_size=16,
        max_length=64,
    )
    # result file: reference format — JSON lines of record lists
    lines = [json.loads(l) for l in out_results.read_text().splitlines()]
    records = [r for line in lines for r in line]
    test_corpus = json.loads(open(ws["paths"]["test"]).read())
    assert len(records) == len(test_corpus)
    first = records[0]
    assert set(first) == {"Issue_Url", "label", "predict"}
    assert set(first["predict"]) == set(ws["anchors"])  # one score per anchor
    assert all(0.0 <= p <= 1.0 for p in first["predict"].values())
    # metric file exists and has the reference keys
    saved = json.loads(out_metrics.read_text())
    for key in ["TP", "FN", "TN", "FP", "pd&recall", "prec", "f1", "ap", "auc", "thres"]:
        assert key in saved
    assert saved["TP"] + saved["FN"] + saved["TN"] + saved["FP"] == len(records)
    assert metrics["f1"] == saved["f1"]


def test_sharded_matches_unsharded(ws, memory_setup, tmp_path):
    model, params, reader = memory_setup
    mesh = create_mesh()
    r1 = tmp_path / "sharded_result.json"
    r2 = tmp_path / "unsharded_result.json"
    pred_mesh = SiamesePredictor(
        model, params, ws["tokenizer"], mesh=mesh, batch_size=16, max_length=64
    )
    pred_plain = SiamesePredictor(
        model, params, ws["tokenizer"], mesh=None, batch_size=16, max_length=64
    )
    for pred, path in [(pred_mesh, r1), (pred_plain, r2)]:
        pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
        pred.predict_file(reader, ws["paths"]["test"], path)
    recs1 = [r for l in r1.read_text().splitlines() for r in json.loads(l)]
    recs2 = [r for l in r2.read_text().splitlines() for r in json.loads(l)]
    assert len(recs1) == len(recs2)
    for a, b in zip(recs1, recs2):
        assert a["Issue_Url"] == b["Issue_Url"]
        for anchor in a["predict"]:
            np.testing.assert_allclose(
                a["predict"][anchor], b["predict"][anchor], rtol=1e-4, atol=1e-5
            )


def test_writer_thread_error_propagates(ws, memory_setup, tmp_path):
    """predict_file serializes on a writer thread; a failure there (e.g.
    unwritable output path) must surface to the caller, not hang or pass
    silently."""
    model, params, reader = memory_setup
    pred = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=16, max_length=64
    )
    pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    bad_path = tmp_path / "no_such_dir" / "result.json"
    with pytest.raises(OSError):
        pred.predict_file(reader, ws["paths"]["test"], bad_path)


def test_writer_death_mid_stream_does_not_deadlock(
    ws, memory_setup, tmp_path, monkeypatch
):
    """The harder failure window: the writer thread dies AFTER consuming
    some batches, while the producer may be blocked on the bounded queue.
    The failure-aware put/drain loops must surface the error promptly —
    this test completing at all (instead of hanging on q.put) is the
    assertion."""
    import memvul_tpu.evaluate.predict_memory as pm

    model, params, reader = memory_setup
    pred = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=2, max_length=64
    )
    pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    # the deadlock window only exists when the producer can outrun the
    # 16-deep writer queue: guarantee the corpus actually fills it past
    # the ~3 batches consumed before the synthetic death
    n_reports = len(json.loads(Path(ws["paths"]["test"]).read_text()))
    assert n_reports / 2 > 16 + 3, (
        "synthetic corpus shrank below the queue depth — this test no "
        "longer covers the blocked-producer window"
    )

    real_dumps = pm.json.dumps
    calls = {"n": 0}

    def dying_dumps(*a, **kw):
        calls["n"] += 1
        if calls["n"] >= 3:  # die mid-stream, after real progress
            raise RuntimeError("synthetic writer failure")
        return real_dumps(*a, **kw)

    monkeypatch.setattr(pm.json, "dumps", dying_dumps)
    with pytest.raises(RuntimeError, match="synthetic writer failure"):
        pred.predict_file(
            reader, ws["paths"]["test"], tmp_path / "result.json"
        )
    assert calls["n"] >= 3


def test_bucketed_scoring_matches_pad_to_max(ws, memory_setup, tmp_path):
    """Length-binned batching re-orders reports but must not change any
    per-report anchor probability (buckets cover max_length, so no extra
    truncation) — the throughput path is score-equivalent."""
    model, params, reader = memory_setup
    r_bucket = tmp_path / "bucket_result.json"
    r_flat = tmp_path / "flat_result.json"
    pred_bucket = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=8, max_length=64,
        buckets=(16, 32, 64),
    )
    pred_flat = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=8, max_length=64
    )
    for pred, path in [(pred_bucket, r_bucket), (pred_flat, r_flat)]:
        pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
        pred.predict_file(reader, ws["paths"]["test"], path)
    by_url = {}
    for line in r_flat.read_text().splitlines():
        for rec in json.loads(line):
            by_url[rec["Issue_Url"]] = rec
    n = 0
    for line in r_bucket.read_text().splitlines():
        for rec in json.loads(line):
            ref = by_url.pop(rec["Issue_Url"])
            assert rec["label"] == ref["label"]
            for anchor, p in rec["predict"].items():
                np.testing.assert_allclose(p, ref["predict"][anchor], rtol=1e-4, atol=1e-5)
            n += 1
    assert not by_url and n > 0  # same report set, nothing lost or duplicated


def test_bucketed_batch_shapes(ws):
    """Per-bucket token budget: short buckets run proportionally larger
    batches; every emitted batch has a bucket-sized sequence dim."""
    from memvul_tpu.data.batching import (
        CachedEncoder,
        bucket_batch_sizes,
        bucketed_batches_from_instances,
    )

    sizes = bucket_batch_sizes((16, 32, 64), tokens_per_batch=256)
    assert sizes == {16: 16, 32: 8, 64: 8}  # floor at multiple_of=8
    encoder = CachedEncoder(ws["tokenizer"], max_length=64)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    insts = list(reader.read(ws["paths"]["test"], split="test"))
    seen = set()
    total = 0
    for batch in bucketed_batches_from_instances(
        insts, encoder, batch_size=sizes, buckets=(16, 32, 64)
    ):
        b, length = batch["sample1"]["input_ids"].shape
        assert length in (16, 32, 64)
        assert b == sizes[length]
        assert batch["weight"].sum() == len(batch["meta"])
        total += len(batch["meta"])
        seen.add(length)
    assert total == len(insts)


def test_cal_metrics_perfect_and_inverted(tmp_path):
    # synthetic result file with known outcomes
    records = [
        {"Issue_Url": "u1", "label": "CWE-79", "predict": {"a": 0.9, "b": 0.2}},
        {"Issue_Url": "u2", "label": "neg", "predict": {"a": 0.1, "b": 0.3}},
        {"Issue_Url": "u3", "label": "neg", "predict": {"a": 0.6, "b": 0.1}},
    ]
    f = tmp_path / "m_result.json"
    f.write_text(json.dumps(records))
    m = cal_metrics(f, thres=0.5)
    assert (m["TP"], m["FN"], m["TN"], m["FP"]) == (1, 0, 1, 1)
    assert (tmp_path / "m_metric_all.json").exists()
    m2 = cal_metrics(f, thres=0.7)
    assert (m2["TP"], m2["FN"], m2["TN"], m2["FP"]) == (1, 0, 2, 0)
    assert m2["f1"] == 1.0


def test_single_model_eval_pipeline(ws, tmp_path):
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = SingleModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy)
    out = tmp_path / "single_result.json"
    metrics = run_single_eval(
        model, params, ws["tokenizer"],
        test_file=ws["paths"]["test"],
        out_results=out,
        out_metrics=tmp_path / "single_metric_all.json",
        reader=SingleReader(),
        batch_size=16,
        max_length=64,
    )
    records = [r for l in out.read_text().splitlines() for r in json.loads(l)]
    test_corpus = json.loads(open(ws["paths"]["test"]).read())
    assert len(records) == len(test_corpus)
    assert set(records[0]) == {"Issue_Url", "label", "predict", "prob"}
    assert metrics["TP"] + metrics["FN"] == sum(
        1 for r in records if r["label"] != "neg"
    )


def test_cal_metrics_empty_result_file(tmp_path):
    f = tmp_path / "empty_result.json"
    f.write_text("")
    m = cal_metrics(f, thres=0.5)
    assert m["f1"] == 0.0 and m["TP"] == 0


def test_buckets_must_cover_max_length(ws, memory_setup):
    """Buckets smaller than max_length would silently truncate long
    reports — constructor must reject the combination."""
    model, params, _ = memory_setup
    with pytest.raises(ValueError, match="truncated"):
        SiamesePredictor(
            model, params, ws["tokenizer"], max_length=64, buckets=(16, 32)
        )
    from memvul_tpu.evaluate.predict_single import SinglePredictor
    with pytest.raises(ValueError, match="truncated"):
        SinglePredictor(
            model, params, ws["tokenizer"], max_length=64, buckets=(16, 32)
        )


def test_aot_warmup_precompiles_every_bucket_shape(ws, memory_setup):
    """encode_anchors ends with the AOT shape warmup: one score-program
    compile per (bucket, batch-rows) shape, and STREAMING MUST NOT
    compile anything further — the probe counts jit cache misses, so a
    mid-stream compile would show as a count bump."""
    model, params, reader = memory_setup
    pred = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=8, max_length=64,
        buckets=(16, 32, 64), tokens_per_batch=256,
    )
    assert pred.score_trace_count == 0
    pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    shapes = pred.stream_shapes()
    assert len(shapes) == 3  # one per bucket, rows from the token budget
    assert pred.score_trace_count == len(shapes)
    n = 0
    for probs, metas in pred.score_instances(
        reader.read(ws["paths"]["test"], split="test")
    ):
        n += len(metas)
    assert n > 0
    assert pred.score_trace_count == len(shapes), (
        "streaming hit a shape outside the precompiled set"
    )


def test_aot_warmup_no_buckets_single_shape(ws, memory_setup):
    """Pad-to-max mode has exactly one stream shape to precompile."""
    model, params, reader = memory_setup
    pred = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=8, max_length=64
    )
    pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    assert pred.stream_shapes() == [(8, 64)]
    assert pred.score_trace_count == 1
    for _ in pred.score_instances(reader.read(ws["paths"]["test"], split="test")):
        pass
    assert pred.score_trace_count == 1


def test_aot_warmup_opt_out(ws, memory_setup):
    """aot_warmup=False restores compile-on-first-occurrence (the lazy
    behavior tiny interactive runs may prefer)."""
    model, params, reader = memory_setup
    pred = SiamesePredictor(
        model, params, ws["tokenizer"], batch_size=8, max_length=64,
        aot_warmup=False,
    )
    pred.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    assert pred.score_trace_count == 0
    for _ in pred.score_instances(reader.read(ws["paths"]["test"], split="test")):
        pass
    assert pred.score_trace_count == 1  # compiled lazily, mid-stream


def test_single_predictor_bucket_token_budget(ws):
    """tokens_per_batch drives per-bucket batch sizes on the single path
    too (the config field is honored end-to-end)."""
    from memvul_tpu.evaluate.predict_single import SinglePredictor
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = SingleModel(cfg)
    dummy = {"input_ids": np.zeros((2, 8), np.int32),
             "attention_mask": np.ones((2, 8), np.int32)}
    params = model.init(jax.random.PRNGKey(0), dummy)
    pred = SinglePredictor(
        model, params, ws["tokenizer"], max_length=64,
        buckets=(16, 32, 64), tokens_per_batch=512,
    )
    assert pred.bucket_sizes == {16: 32, 32: 16, 64: 8}


def test_single_predictor_shares_warmed_probs_program(ws):
    """predict_single's probs program is cached per model: a second
    predictor over an equal model (the one-off single-IR scoring path)
    adds ZERO traces — historically every call cold-compiled its own
    jitted lambda.  Counts are deltas off the shared program's history:
    earlier tests over an equal tiny model legitimately pre-warmed it
    (that reuse IS the feature)."""
    from memvul_tpu.evaluate.predict_single import SinglePredictor, probs_program

    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = SingleModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy)
    base = probs_program(model).trace_count
    # an odd geometry no other test uses → its warmup traces exactly once
    first = SinglePredictor(
        model, params, ws["tokenizer"], batch_size=3, max_length=24,
    )
    assert first.score_trace_count == base + 1
    reader = SingleReader()
    out = Path(ws["paths"]["test"]).parent / "single_cache_result.json"
    first.predict_file(reader, ws["paths"]["test"], out)
    assert first.score_trace_count == base + 1  # streaming reused the warmup

    # an EQUAL model (fresh object) and fresh params: same program, so
    # construction + scoring is compile-free after startup
    model2 = SingleModel(BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size))
    params2 = model2.init(jax.random.PRNGKey(1), dummy)
    second = SinglePredictor(
        model2, params2, ws["tokenizer"], batch_size=3, max_length=24,
    )
    assert second.score_trace_count == base + 1  # no new trace
    second.predict_file(reader, ws["paths"]["test"], out)
    assert second.score_trace_count == base + 1

    # adding a bucket set only compiles the genuinely NEW shape — the
    # (3, 24) bucket hits the shared program's existing executable
    other = SinglePredictor(
        model2, params2, ws["tokenizer"], batch_size=3, max_length=24,
        buckets=[16, 24],
    )
    assert other.score_trace_count == base + 2  # +1 for (3, 16) only
