import json

import numpy as np
import pytest

from memvul_tpu.data.batching import (
    LABELS_SIAMESE,
    CachedEncoder,
    batches_from_instances,
    prefetch,
)
from memvul_tpu.data.corpus import extract_project, preprocess, split_by_project
from memvul_tpu.data.cwe import (
    bfs_subtree,
    build_anchors,
    build_cwe_tree,
    cwe_distribution,
    describe_cwe,
)
from memvul_tpu.data.readers import MemoryReader, SingleReader, detect_split
from memvul_tpu.data.synthetic import (
    build_workspace,
    generate_corpus,
    research_view_records,
)
from memvul_tpu.data.tokenizer import WordPieceTokenizer


@pytest.fixture(scope="module")
def workspace(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("ws"), seed=7)


# -- tokenizer ---------------------------------------------------------------


def test_tokenizer_roundtrip(workspace):
    tok = workspace["tokenizer"]
    ids = tok.encode("buffer overflow in the parser")
    assert ids[0] == tok.cls_id and ids[-1] == tok.sep_id
    assert len(ids) > 2


def test_tokenizer_truncation(workspace):
    tok = workspace["tokenizer"]
    ids = tok.encode("word " * 300, max_length=16)
    assert len(ids) == 16 and ids[-1] == tok.sep_id


def test_tokenizer_batch_shapes(workspace):
    tok = workspace["tokenizer"]
    batch = tok.encode_batch(["short", "a much longer text " * 5], max_length=64, buckets=[16, 32, 64])
    assert batch["input_ids"].shape == batch["attention_mask"].shape
    assert batch["input_ids"].shape[1] in (16, 32, 64)


def test_tokenizer_save_load(workspace, tmp_path):
    tok = workspace["tokenizer"]
    p = tmp_path / "tok.json"
    tok.save(p)
    tok2 = WordPieceTokenizer(tokenizer_path=p)
    text = "sql injection in the login form"
    assert tok.encode(text) == tok2.encode(text)


def test_tag_tokens_atomic(workspace):
    tok = workspace["tokenizer"]
    ids = tok.encode("CVETAG")
    assert len(ids) == 3  # CLS + tag + SEP


def test_missing_named_vocab_warns_loudly(workspace, tmp_path, caplog):
    """A config naming a vocab_path that doesn't exist must WARN that the
    trained (non-parity) tokenizer is in use — reference tokenization is
    bert-base-uncased (MemVul/config_memory.json:16-20) and silently
    substituting a different vocab makes F1 parity impossible."""
    import logging

    p = tmp_path / "tok.json"
    workspace["tokenizer"].save(p)
    with caplog.at_level(logging.WARNING, logger="memvul_tpu.data.tokenizer"):
        WordPieceTokenizer(
            vocab_path=tmp_path / "does_not_exist_vocab.txt", tokenizer_path=p
        )
    assert any(
        "does NOT exist" in r.message and "parity" in r.message
        for r in caplog.records
    )
    # an existing vocab.txt must NOT warn
    caplog.clear()
    vocab = tmp_path / "vocab.txt"
    vocab.write_text(
        "\n".join(["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "the", "##s"])
    )
    with caplog.at_level(logging.WARNING, logger="memvul_tpu.data.tokenizer"):
        WordPieceTokenizer(vocab_path=vocab, tokenizer_path=p)
    assert not [r for r in caplog.records if r.levelno >= logging.WARNING]


# -- corpus pipeline ---------------------------------------------------------


def test_extract_project():
    assert extract_project("https://github.com/foo/bar/issues/12") == "foo/bar"
    assert extract_project("bogus") == "ERROR"


def test_preprocess_temporal_leak_guard():
    reports, _ = generate_corpus(num_projects=2, seed=1)
    # forge a CIR created after CVE disclosure
    leaked = dict(reports[0])
    leaked["Issue_Created_At"] = "2022-01-01T00:00:00Z"
    leaked["Issue_Url"] = "https://github.com/org0/repo0/issues/999"
    clean = preprocess(reports + [leaked])
    urls = {r["Issue_Url"] for r in clean}
    assert leaked["Issue_Url"] not in urls


def test_preprocess_drops_cirless_projects():
    reports = [
        {
            "Issue_Url": f"https://github.com/solo/neg/issues/{i}",
            "Issue_Title": "t",
            "Issue_Body": "b",
            "Security_Issue_Full": "0",
        }
        for i in range(3)
    ]
    assert preprocess(reports) == []


def test_split_by_project_is_project_level():
    reports, _ = generate_corpus(num_projects=8, seed=2)
    train, test = split_by_project(reports, held_out_frac=0.25, seed=3)
    train_projects = {extract_project(r["Issue_Url"]) for r in train}
    test_projects = {extract_project(r["Issue_Url"]) for r in test}
    assert train_projects.isdisjoint(test_projects)
    assert len(train) + len(test) == len(reports)


# -- CWE tree / anchors ------------------------------------------------------


def test_cwe_tree_edges():
    tree = build_cwe_tree(research_view_records())
    # every non-root is ChildOf the first id in the synthetic table
    root = research_view_records()[0]["CWE-ID"]
    assert all(root in tree[k]["father"] for k in tree if k != root)
    assert len(tree[root]["children"]) == len(tree) - 1


def test_bfs_subtree_levels():
    tree = build_cwe_tree(research_view_records())
    root = research_view_records()[0]["CWE-ID"]
    level0 = bfs_subtree(tree, root, level=0)
    level1 = bfs_subtree(tree, root, level=1)
    assert level0 == [root]
    assert set(level1) == set(tree.keys())


def test_describe_cwe_contains_fields():
    tree = build_cwe_tree(research_view_records())
    text = describe_cwe(tree, "89")
    assert "SQL Injection" in text
    assert "Execute Unauthorized Code or Commands" in text


def test_build_anchors_deterministic(workspace):
    reports, cve_dict = generate_corpus(seed=7)
    positives = [r for r in reports if r["Security_Issue_Full"] == "1"]
    for r in positives:
        r["CWE_ID"] = cve_dict[r["CVE_ID"]]["CWE_ID"]
    dist = cwe_distribution(positives, cve_dict)
    tree = build_cwe_tree(research_view_records())
    a1 = build_anchors(dist, tree, cve_dict, seed=5)
    a2 = build_anchors(dist, tree, cve_dict, seed=5)
    assert a1 == a2 and len(a1) > 0
    assert all(k.startswith("CWE-") for k in a1)


def test_build_full_view_anchors_covers_every_tree_node():
    """The CWE-1000-scale bank: one anchor per Research View node, CVE
    descriptions folded in where training data has them, deterministic."""
    from memvul_tpu.data.cwe import build_full_view_anchors

    reports, cve_dict = generate_corpus(seed=7)
    positives = [r for r in reports if r["Security_Issue_Full"] == "1"]
    for r in positives:
        r["CWE_ID"] = cve_dict[r["CVE_ID"]]["CWE_ID"]
    dist = cwe_distribution(positives, cve_dict)
    tree = build_cwe_tree(research_view_records())

    full = build_full_view_anchors(tree, cve_dict, dist, seed=5)
    assert {f"CWE-{i}" for i in tree} <= set(full)
    # the full bank is a strict superset of the train-seen bank's
    # categories — including out-of-view ones (NVD-CWE-noinfo etc.)
    train_bank = build_anchors(dist, tree, cve_dict, seed=5)
    assert set(train_bank) <= set(full)
    # determinism
    assert full == build_full_view_anchors(tree, cve_dict, dist, seed=5)
    # works with no distribution at all (pure-taxonomy bank over the view)
    bare = build_full_view_anchors(tree, cve_dict)
    assert set(bare) == {f"CWE-{i}" for i in tree}
    for text in bare.values():
        assert text  # every anchor has a real description


def test_anchor_for_unknown_cwe_uses_cve_descriptions():
    cve_dict = {
        f"CVE-1-{i}": {"CWE_ID": "NVD-CWE-noinfo", "CVE_Description": f"desc {i}"}
        for i in range(4)
    }
    positives = [
        {"CVE_ID": cve, "CWE_ID": "NVD-CWE-noinfo"} for cve in cve_dict
    ]
    dist = cwe_distribution(positives, cve_dict)
    anchors = build_anchors(dist, {}, cve_dict, seed=0)
    assert "NVD-CWE-noinfo" in anchors
    assert "desc" in anchors["NVD-CWE-noinfo"]


# -- readers -----------------------------------------------------------------


def test_detect_split():
    assert detect_split("a/train_project.json") == "train"
    assert detect_split("a/test_project.json") == "test"
    assert detect_split("a/validation_project.json") == "validation"
    assert detect_split("a/CWE_anchor_golden_project.json") == "golden"


def test_memory_reader_train_pairs(workspace):
    reader = MemoryReader(
        cve_path=workspace["paths"]["cve"],
        anchor_path=workspace["paths"]["anchors"],
        same_diff_ratio={"same": 4, "diff": 3},
        sample_neg=1.0,
        seed=11,
    )
    instances = list(reader.read(workspace["paths"]["train"]))
    assert instances, "no pairs generated"
    same = [i for i in instances if i["label"] == "same"]
    diff = [i for i in instances if i["label"] == "diff"]
    assert same and diff
    # every diff pair partners a negative report with an anchor description
    anchor_texts = set(workspace["anchors"].values())
    assert all(i["text2"] in anchor_texts for i in diff)
    # matched pairs are generated per positive: 4 each
    n_pos = len({i["meta"]["Issue_Url"] for i in same})
    assert len(same) == 4 * n_pos


def test_partner_text_mix_is_70_15_15(workspace):
    """The matched-pair partner text follows the reference's sampling mix
    (reader_memory.py:205-224): 70% partner's CVE description, 15% its
    CWE anchor, 15% its own report text — the fixed-seed distributional
    check SURVEY §4 calls for."""
    reader = MemoryReader(
        cve_path=workspace["paths"]["cve"],
        anchor_path=workspace["paths"]["anchors"],
        seed=0,
    )
    category = next(iter(workspace["anchors"]))
    cve_id = next(
        c for c, rec in reader._cve.items() if rec["CWE_ID"] == category
    )
    s = {"Issue_Url": "u1", "text": "SELF", "CVE_ID": cve_id, "CWE_ID": category}
    partner = {
        "Issue_Url": "u2",
        "text": "PARTNER-REPORT-TEXT",
        "CVE_ID": cve_id,
        "CWE_ID": category,
    }
    cve_text = reader._cve_description(cve_id)
    anchor_text = workspace["anchors"][category]
    assert len({cve_text, anchor_text, partner["text"]}) == 3

    n = 4000
    counts = {"cve": 0, "anchor": 0, "report": 0}
    for _ in range(n):
        text = reader._partner_text(s, partner)
        if text == cve_text:
            counts["cve"] += 1
        elif text == anchor_text:
            counts["anchor"] += 1
        else:
            counts["report"] += 1
    assert abs(counts["cve"] / n - 0.70) < 0.04, counts
    assert abs(counts["anchor"] / n - 0.15) < 0.04, counts
    assert abs(counts["report"] / n - 0.15) < 0.04, counts

    # a positive partnered with itself always uses its CVE description
    assert reader._partner_text(s, {**partner, "Issue_Url": "u1"}) == cve_text


def test_memory_reader_resampling_differs_between_epochs(workspace):
    reader = MemoryReader(
        cve_path=workspace["paths"]["cve"],
        anchor_path=workspace["paths"]["anchors"],
        same_diff_ratio={"same": 2, "diff": 2},
        sample_neg=0.5,
        seed=13,
    )
    epoch1 = [(i["text1"], i["text2"]) for i in reader.read(workspace["paths"]["train"])]
    epoch2 = [(i["text1"], i["text2"]) for i in reader.read(workspace["paths"]["train"])]
    assert epoch1 != epoch2  # online sampling re-rolls every epoch


def test_memory_reader_eval_instances(workspace):
    reader = MemoryReader(
        cve_path=workspace["paths"]["cve"],
        anchor_path=workspace["paths"]["anchors"],
    )
    instances = list(reader.read(workspace["paths"]["test"]))
    raw = json.loads(open(workspace["paths"]["test"]).read())
    assert len(instances) == len(
        [r for r in raw if r["Security_Issue_Full"] != "1" or "CVE_ID" in r]
    )
    assert all(i["meta"]["type"] == "unlabel" for i in instances)
    assert all("text2" not in i for i in instances)


def test_memory_reader_golden_instances(workspace):
    reader = MemoryReader(anchor_path=workspace["paths"]["anchors"])
    golden = list(reader.read(workspace["paths"]["anchors"], split="golden"))
    assert len(golden) == len(workspace["anchors"])
    assert all(g["meta"]["type"] == "golden" for g in golden)


def test_single_reader_subsamples_negatives(workspace):
    full = list(SingleReader(seed=3).read(workspace["paths"]["train"], split="validation"))
    sub = list(SingleReader(sample_neg=0.1, seed=3).read(workspace["paths"]["train"], split="train"))
    n_neg_full = sum(1 for i in full if i["label"] == "neg")
    n_neg_sub = sum(1 for i in sub if i["label"] == "neg")
    assert n_neg_sub < n_neg_full
    assert sum(1 for i in sub if i["label"] == "pos") == sum(
        1 for i in full if i["label"] == "pos"
    )


# -- batching ----------------------------------------------------------------


def test_batches_fixed_shape_and_weights(workspace):
    tok = workspace["tokenizer"]
    enc = CachedEncoder(tok, max_length=32)
    instances = [
        {"text1": "a b c", "text2": "d e", "label": "same", "meta": {}}
        for _ in range(5)
    ]
    batches = list(
        batches_from_instances(instances, enc, batch_size=4, buckets=[16, 32])
    )
    assert len(batches) == 2
    for b in batches:
        assert b["sample1"]["input_ids"].shape[0] == 4
        assert b["label"].shape == (4,)
    assert batches[1]["weight"].tolist() == [1.0, 0.0, 0.0, 0.0]
    assert batches[0]["sample2"]["input_ids"].shape[0] == 4


def test_batches_label_mapping(workspace):
    enc = CachedEncoder(workspace["tokenizer"], max_length=16)
    instances = [
        {"text1": "x", "label": "same", "meta": {}},
        {"text1": "y", "label": "diff", "meta": {}},
    ]
    (batch,) = batches_from_instances(instances, enc, batch_size=2)
    assert batch["label"].tolist() == [LABELS_SIAMESE["same"], LABELS_SIAMESE["diff"]]


def test_prefetch_preserves_order_and_propagates_errors():
    assert list(prefetch(iter(range(10)), depth=2)) == list(range(10))

    def boom():
        yield 1
        raise ValueError("boom")

    with pytest.raises(ValueError):
        list(prefetch(boom()))


def test_cached_encoder_caches(workspace):
    enc = CachedEncoder(workspace["tokenizer"], max_length=16)
    a = enc("same text here")
    b = enc("same text here")
    assert a is b


def test_collate_rejects_mismatched_label_map(workspace):
    enc = CachedEncoder(workspace["tokenizer"], max_length=16)
    instances = [{"text1": "x", "label": "pos", "meta": {}}]
    with pytest.raises(ValueError, match="label 'pos'"):
        list(batches_from_instances(instances, enc, batch_size=2))


def test_prefetch_early_exit_stops_worker():
    import threading

    before = threading.active_count()
    for _ in range(5):
        gen = prefetch(iter(range(1000)), depth=2)
        next(gen)
        gen.close()
    import time

    time.sleep(0.5)
    assert threading.active_count() <= before + 1


def test_explicit_unlabel_split_mode(workspace):
    reader = MemoryReader(
        cve_path=workspace["paths"]["cve"],
        anchor_path=workspace["paths"]["anchors"],
    )
    insts = list(reader.read(workspace["paths"]["test"], split="unlabel"))
    assert all(i["meta"]["type"] == "unlabel" for i in insts)
    insts_v = list(reader.read(workspace["paths"]["validation"], split="validation"))
    assert all(i["meta"]["type"] == "test" for i in insts_v)


def test_jsonl_corpus_streams_identically(workspace, tmp_path):
    """A .jsonl corpus (the streaming format for the 1.2M-report job)
    must yield exactly the same eval instances as the .json array."""
    import json as _json

    from memvul_tpu.data.readers import MemoryReader, SingleReader

    src = workspace["paths"]["test"]
    samples = _json.loads(open(src).read())
    jsonl = tmp_path / "test_stream.jsonl"
    jsonl.write_text("\n".join(_json.dumps(s) for s in samples))

    reader = MemoryReader(
        cve_path=workspace["paths"]["cve"],
        anchor_path=workspace["paths"]["anchors"],
    )
    from_json = list(reader.read(src, split="test"))
    # fresh reader: no grouped cache for the jsonl path
    reader2 = MemoryReader(
        cve_path=workspace["paths"]["cve"],
        anchor_path=workspace["paths"]["anchors"],
    )
    from_jsonl = list(reader2.read(str(jsonl), split="test"))
    assert from_json == from_jsonl
    assert not reader2._grouped_cache  # streaming never built the dict

    single = SingleReader()
    assert list(single.read(src, split="test")) == list(
        single.read(str(jsonl), split="test")
    )


# -- auto bucketing -----------------------------------------------------------


def test_auto_buckets_beats_powers_of_two_on_skewed_sample():
    from memvul_tpu.data.batching import auto_buckets

    rng = np.random.default_rng(0)
    # long-tailed mix: most reports short, a capped heavy tail
    lengths = np.concatenate([
        rng.integers(20, 60, 800),
        rng.integers(90, 130, 150),
        np.full(50, 512),
    ])
    buckets = auto_buckets(lengths, max_length=512, n_buckets=4)
    assert buckets[-1] == 512
    assert len(buckets) <= 4

    def padded(bounds):
        total = 0
        for l in np.minimum(lengths, 512):
            total += next(b for b in bounds if b >= l)
        return total

    assert padded(buckets) <= padded((64, 128, 256, 512))


def test_auto_buckets_properties():
    from memvul_tpu.data.batching import auto_buckets, validate_buckets

    assert auto_buckets([], 512) == (512,)
    # every sampled length fits some bucket; final bound is max_length
    lengths = [5, 9, 17, 200, 600]
    b = auto_buckets(lengths, max_length=256, n_buckets=3, align=8)
    assert b[-1] == 256
    assert all(any(x >= min(l, 256) for x in b) for l in lengths)
    # output always satisfies the coverage contract
    assert validate_buckets(b, 256) == b
    # boundaries are ascending and unique
    assert list(b) == sorted(set(b))


def test_auto_buckets_exact_on_two_clusters():
    """Two tight clusters + the free cap boundary: with a 3-bucket budget
    the DP lands interior boundaries at the aligned cluster maxima."""
    from memvul_tpu.data.batching import auto_buckets

    lengths = [30, 31, 32, 120, 121, 122]
    b = auto_buckets(lengths, max_length=512, n_buckets=3, align=8)
    assert b == (32, 128, 512)


def test_inflight_pipeline_invariants():
    """The shared async-dispatch core (both predictors + bench): batches
    are yielded exactly once in order; the dispatch-ahead depth never
    exceeds ``inflight`` + 1; ``inflight=0`` degrades to strict
    dispatch-then-sync alternation; and the input is consumed lazily
    (never drained ahead of the dispatch window)."""
    from memvul_tpu.data.batching import inflight_pipeline

    for inflight in (0, 1, 2, 5):
        events = []
        consumed = 0

        def batches():
            nonlocal consumed
            for i in range(12):
                consumed += 1
                yield {"i": i}

        def dispatch(b):
            events.append(("d", b["i"]))
            return b["i"] * 10

        yielded = []
        for result, batch in inflight_pipeline(batches(), dispatch, inflight=inflight):
            events.append(("y", batch["i"]))
            yielded.append((result, batch["i"]))
            # dispatch-ahead bound: dispatched − yielded ≤ inflight (+1
            # for the batch appended just before this yield fired)
            d = sum(1 for k, _ in events if k == "d")
            y = sum(1 for k, _ in events if k == "y")
            assert d - y <= inflight + 1
            # laziness: the generator is never drained ahead of dispatch
            assert consumed == d
        assert yielded == [(i * 10, i) for i in range(12)]
        if inflight == 0:
            # strict alternation after the first dispatch
            kinds = "".join(k for k, _ in events)
            assert kinds == "d" + "yd" * 11 + "y"


def test_split_by_project_partition_property():
    """Property (hypothesis): for arbitrary report→project assignments,
    the project-level split is a PARTITION of the reports, no project
    ever straddles the boundary (the leak-guard invariant, reference:
    utils.py:115-152), and a fixed seed is reproducible."""
    pytest.importorskip("hypothesis")  # property tier is optional (pyproject [test])
    from hypothesis import given, settings, strategies as st

    @settings(max_examples=60, deadline=None)
    @given(
        st.lists(st.integers(min_value=0, max_value=9), min_size=1, max_size=40),
        st.floats(min_value=0.0, max_value=0.9),
        st.integers(min_value=0, max_value=999),
    )
    def check(project_ids, frac, seed):
        reports = [
            {"Issue_Url": f"https://github.com/org{p}/repo{p}/issues/{i}",
             "idx": i}
            for i, p in enumerate(project_ids)
        ]
        train, test = split_by_project(reports, held_out_frac=frac, seed=seed)
        # partition: every report lands on exactly one side
        assert sorted(r["idx"] for r in train + test) == list(
            range(len(reports))
        )
        # corpus order is preserved WITHIN each side (no group-by reshuffle)
        assert [r["idx"] for r in train] == sorted(r["idx"] for r in train)
        assert [r["idx"] for r in test] == sorted(r["idx"] for r in test)
        # leak guard: no project appears on both sides
        proj = lambda r: extract_project(r["Issue_Url"])
        assert not ({proj(r) for r in train} & {proj(r) for r in test})
        # determinism
        train2, test2 = split_by_project(reports, held_out_frac=frac, seed=seed)
        assert train == train2 and test == test2

    check()


def test_auto_buckets_is_exactly_optimal_vs_brute_force():
    """Property (hypothesis): the interval-partition DP's padded-token
    total equals the brute-force optimum over ALL aligned boundary
    subsets within the bucket budget.  This is the policy that now picks
    the shipped eval/bench bucketing (auto-8 default), so 'minimize' must
    mean minimize, not approximately."""
    from itertools import combinations

    pytest.importorskip("hypothesis")  # property tier is optional (pyproject [test])
    from hypothesis import given, settings, strategies as st

    from memvul_tpu.data.batching import auto_buckets

    ALIGN, CAP = 8, 128

    def padded_total(lengths, bounds):
        return sum(
            next(b for b in sorted(bounds) if b >= min(l, CAP)) - min(l, CAP)
            for l in lengths
        )

    @settings(max_examples=60, deadline=None)
    @given(
        st.lists(st.integers(min_value=1, max_value=160), min_size=1, max_size=24),
        st.integers(min_value=1, max_value=4),
    )
    def check(lengths, n_buckets):
        got = auto_buckets(lengths, CAP, n_buckets=n_buckets, align=ALIGN)
        assert got[-1] == CAP and len(got) <= n_buckets or got == (CAP,)
        # brute force over aligned candidate boundaries (cap always in)
        cands = sorted(
            {min(CAP, -(-min(l, CAP) // ALIGN) * ALIGN) for l in lengths} - {CAP}
        )
        best = padded_total(lengths, (CAP,))
        for k in range(1, n_buckets):
            for combo in combinations(cands, min(k, len(cands))):
                best = min(best, padded_total(lengths, combo + (CAP,)))
        assert padded_total(lengths, got) == best

    check()


def test_auto_buckets_respects_bucket_budget_including_cap():
    """The forced max_length boundary must count against n_buckets when
    the sample never reaches the cap — never n_buckets+1 programs."""
    from memvul_tpu.data.batching import auto_buckets

    lengths = [20] * 100 + [60] * 50 + [100] * 20 + [200] * 5
    b = auto_buckets(lengths, max_length=512, n_buckets=4)
    assert len(b) <= 4
    assert b[-1] == 512
    # sample reaching the cap: all four buckets available to the DP
    b2 = auto_buckets(lengths + [512] * 10, max_length=512, n_buckets=4)
    assert len(b2) <= 4 and b2[-1] == 512


def test_bucketed_batches_partition_property():
    """Property (hypothesis): for arbitrary token-length streams, bucketed
    batching is a PARTITION — every instance appears in exactly one batch
    row, each row sits in the smallest covering bucket, and every batch
    has its bucket's fixed shape (the static-shape contract XLA needs)."""
    pytest.importorskip("hypothesis")  # property tier is optional (pyproject [test])
    from hypothesis import given, settings, strategies as st

    from memvul_tpu.data.batching import bucketed_batches_from_instances

    class StubEncoder:
        pad_id = 0
        max_length = 64

        def __call__(self, text):
            return [1] * int(text)  # text encodes its own token length

    @settings(max_examples=60, deadline=None)
    @given(
        st.lists(st.integers(min_value=1, max_value=64), max_size=40),
        st.integers(min_value=1, max_value=5),
    )
    def check(lengths, batch_size):
        instances = [
            {"text1": str(n), "label": "same",
             "meta": {"Issue_Url": f"u{i}"}}
            for i, n in enumerate(lengths)
        ]
        buckets = (8, 16, 64)
        seen = []
        for batch in bucketed_batches_from_instances(
            iter(instances), StubEncoder(), batch_size, buckets=buckets
        ):
            ids = batch["sample1"]["input_ids"]
            mask = batch["sample1"]["attention_mask"]
            width = ids.shape[1]
            assert width in buckets
            assert ids.shape[0] == batch_size  # fixed rows (dead-row padded)
            for row, meta in enumerate(batch["meta"]):
                n = int(meta["Issue_Url"][1:])
                seen.append(n)
                true_len = min(lengths[n], 64)
                # smallest covering bucket
                assert width == next(b for b in buckets if b >= true_len)
                assert int(mask[row].sum()) == true_len
        assert sorted(seen) == list(range(len(lengths)))

    check()
