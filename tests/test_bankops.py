"""Anchor-bank lifecycle subsystem (memvul_tpu/bankops/,
docs/anchor_bank.md).

The acceptance contract this file pins:

* **store** — versions are immutable, digest-verified, and lineage-
  complete: every non-root version records its parent and the exact
  diff ops; a tampered artifact raises, a crash remnant is invisible;
* **shadow** — with a shadow scorer attached, active responses are
  BITWISE-identical to a no-shadow run, ``score_trace_count`` stays
  flat under load, and ``shadow_deltas.jsonl`` carries exactly one row
  per sampled request; a crashing shadow worker (the ``bank.shadow``
  fault point) lands in ``bank.shadow_errors`` and clients never see
  it — the serve counter invariant is untouched;
* **promotion** — the gate refuses a bad candidate with a
  machine-readable reason and promotes a good one through the PR 6
  ``rolling_swap`` (every response stamped with exactly one bank
  version; provenance recorded store→manifest→/healthz); ``demote``
  restores the parent;
* **observability** — per-anchor win/drift telemetry renders as a
  table in ``telemetry-report``;
* **lint** — bankops/ writes artifacts only through
  ``atomic_write_text``/``JsonlSink`` (tools/lint_bank_artifact_writes).
"""

import json
import sys
import threading
import time
from pathlib import Path

import numpy as np
import pytest

import jax

from memvul_tpu import telemetry
from memvul_tpu.bankops import (
    BankDiff,
    BankIntegrityError,
    BankStore,
    BankStoreError,
    GateThresholds,
    PromotionRefused,
    ShadowConfig,
    ShadowScorer,
    demote,
    evaluate_cascade,
    evaluate_gate,
    golden_metrics,
    pin_baseline,
    promote,
    replay_results,
    total_variation,
    update_drift_gauge,
    win_shares,
)
from memvul_tpu.bankops.promote import (
    REASON_AUC,
    REASON_FLIP_RATE,
    REASON_SHADOW_MISSING,
    REASON_SHADOW_SAMPLES,
    PromotionDecision,
)
from memvul_tpu.bankops.shadow import SHADOW_DELTAS_NAME
from memvul_tpu.data.cwe import load_anchors
from memvul_tpu.data.readers import MemoryReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.evaluate.predict_memory import SiamesePredictor
from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.resilience import faults
from memvul_tpu.serving import (
    MANIFEST_NAME,
    Replica,
    ReplicaRouter,
    RouterConfig,
    ScoringService,
    ServiceConfig,
)
from memvul_tpu.telemetry.report import render_report
from memvul_tpu.telemetry.sinks import read_jsonl

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "tools"))


@pytest.fixture(autouse=True)
def _clean():
    yield
    faults.reset()
    telemetry.reset()


# -- store ---------------------------------------------------------------------

ANCHORS_V1 = {
    "CWE-79": "cross site scripting description",
    "CWE-89": "sql injection description",
    "CWE-22": "path traversal description",
}


def test_store_create_derive_lineage(tmp_path):
    store = BankStore(tmp_path / "banks")
    m1 = store.create(ANCHORS_V1, source="build", note="seed bank")
    assert m1["version"] == "v1" and m1["parent"] is None
    assert m1["n_anchors"] == 3 and m1["diff"] == []
    diff = BankDiff.from_json([
        {"op": "add", "category": "CWE-502",
         "description": "deserialization of untrusted data"},
        {"op": "retire", "category": "CWE-89"},
        {"op": "reweight", "category": "CWE-79", "weight": 2.0},
    ])
    m2 = store.derive("v1", diff, note="rotate")
    assert m2["version"] == "v2" and m2["parent"] == "v1"
    anchors = store.anchors("v2")
    assert "CWE-502" in anchors and "CWE-89" not in anchors
    assert m2["weights"] == {"CWE-79": 2.0}
    assert m2["diff"] == diff.to_json()
    # lineage is root-first and complete
    assert [m["version"] for m in store.log("v2")] == ["v1", "v2"]
    assert store.versions() == ["v1", "v2"]
    assert store.latest() == "v2"
    # instances feed encode_anchors directly, weights ride in meta
    instances = store.instances("v2")
    by_label = {inst["meta"]["label"]: inst for inst in instances}
    assert by_label["CWE-79"]["meta"]["weight"] == 2.0
    assert by_label["CWE-502"]["meta"]["weight"] == 1.0
    assert by_label["CWE-502"]["text1"].startswith("deserialization")


def test_store_diff_validation():
    store_diff = BankDiff.from_json
    with pytest.raises(BankStoreError):
        store_diff([{"op": "nuke", "category": "CWE-79"}])
    with pytest.raises(BankStoreError):
        store_diff([{"op": "add", "category": "CWE-1", "typo": 1}])
    anchors = dict(ANCHORS_V1)
    for bad in (
        [{"op": "add", "category": "CWE-79", "description": "dup"}],
        [{"op": "retire", "category": "CWE-404"}],
        [{"op": "edit", "category": "CWE-404", "description": "x"}],
        [{"op": "reweight", "category": "CWE-79"}],
        [{"op": "add", "category": "CWE-1"}],
    ):
        with pytest.raises(BankStoreError):
            store_diff(bad).apply(anchors, {})


def test_store_integrity_and_crash_remnants(tmp_path):
    store = BankStore(tmp_path)
    store.create(ANCHORS_V1)
    # tampering with the committed artifact is detected on read
    anchors_path = tmp_path / "v1" / "anchors.json"
    anchors_path.write_text(anchors_path.read_text().replace("sql", "SQL"))
    with pytest.raises(BankIntegrityError):
        store.anchors("v1")
    with pytest.raises(BankIntegrityError):
        store.verify("v1")
    # a manifest-less dir (crash between anchor write and commit) is
    # invisible to readers and its id is never reused
    (tmp_path / "v2").mkdir()
    assert store.versions() == ["v1"]
    m3 = store.create(ANCHORS_V1)
    assert m3["version"] == "v3"
    # unknown versions and empty banks are refused loudly
    with pytest.raises(BankStoreError):
        store.manifest("v9")
    with pytest.raises(BankStoreError):
        store.create({})
    with pytest.raises(BankStoreError):
        store.derive("v3", BankDiff([]))


def test_store_active_pointer_and_promotions(tmp_path):
    store = BankStore(tmp_path)
    store.create(ANCHORS_V1)
    assert store.active() is None
    with pytest.raises(BankStoreError):
        store.set_active("v7")  # must point at a committed version
    record = store.set_active("v1", source="promotion")
    assert store.active()["version"] == "v1"
    assert record["source"] == "promotion"
    store.record_promotion(kind="promotion", candidate="v1")
    store.record_promotion(kind="demotion", restored="v1")
    kinds = [r["kind"] for r in store.promotions()]
    assert kinds == ["promotion", "demotion"]


# -- gate (pure logic) ---------------------------------------------------------

GOOD = {"auc": 0.91, "f1": 0.80}
SHADOW_OK = {"sampled": 500, "flip_rate": 0.004}


def _codes(decision):
    return [r["code"] for r in decision.reasons]


def test_gate_approves_within_tolerances():
    decision = evaluate_gate(
        GOOD, {"auc": 0.905, "f1": 0.795}, SHADOW_OK,
        GateThresholds(), candidate="v2", parent="v1",
    )
    assert decision.approved and decision.reasons == []
    assert decision.to_json()["candidate"] == "v2"


def test_gate_refusals_are_machine_readable():
    thresholds = GateThresholds(
        max_auc_drop=0.01, max_f1_drop=0.01,
        max_flip_rate=0.02, min_shadow_samples=100,
    )
    worse = {"auc": 0.80, "f1": 0.80}
    decision = evaluate_gate(GOOD, worse, SHADOW_OK, thresholds)
    assert not decision.approved
    assert _codes(decision) == [REASON_AUC]
    assert decision.reasons[0]["observed"] == pytest.approx(0.11)
    assert decision.reasons[0]["limit"] == 0.01
    # flip-rate + sample-count gates
    decision = evaluate_gate(
        GOOD, GOOD, {"sampled": 10, "flip_rate": 0.5}, thresholds
    )
    assert set(_codes(decision)) == {REASON_SHADOW_SAMPLES, REASON_FLIP_RATE}
    # shadow evidence is mandatory unless explicitly waived
    decision = evaluate_gate(GOOD, GOOD, None, thresholds)
    assert _codes(decision) == [REASON_SHADOW_MISSING]
    waived = GateThresholds(require_shadow=False)
    assert evaluate_gate(GOOD, GOOD, None, waived).approved


def test_promote_refuses_unapproved_decision(tmp_path):
    store = BankStore(tmp_path)
    store.create(ANCHORS_V1)
    decision = evaluate_gate(
        GOOD, GOOD, None, GateThresholds(), candidate="v1",
    )
    with pytest.raises(PromotionRefused) as excinfo:
        promote(object(), store, decision)
    refused = excinfo.value.decision
    assert _codes(refused) == [REASON_SHADOW_MISSING]
    # the refusal itself is audited, machine-readable
    audit = store.promotions()
    assert audit[-1]["kind"] == "promotion_refused"
    assert audit[-1]["reasons"][0]["code"] == REASON_SHADOW_MISSING


# -- drift ---------------------------------------------------------------------

def test_drift_math_and_baseline_roundtrip(tmp_path):
    assert total_variation({"a": 1.0}, {"a": 1.0}) == 0.0
    assert total_variation({"a": 1.0}, {"b": 1.0}) == 1.0
    assert total_variation(
        {"a": 0.5, "b": 0.5}, {"a": 1.0}
    ) == pytest.approx(0.5)
    assert win_shares({}) == {}
    registry = telemetry.configure(run_dir=tmp_path / "run")
    registry.counter("bank.anchor_wins.CWE-79").inc(3)
    registry.counter("bank.anchor_wins.CWE-89").inc(1)
    baseline = pin_baseline(registry, tmp_path / "anchor_baseline.json")
    assert baseline == {"CWE-79": 0.75, "CWE-89": 0.25}
    from memvul_tpu.bankops import load_baseline

    assert load_baseline(tmp_path / "anchor_baseline.json") == baseline
    assert load_baseline(tmp_path / "missing.json") is None
    # identical distribution → zero drift, published as the gauge
    assert update_drift_gauge(registry, baseline) == 0.0
    registry.counter("bank.anchor_wins.CWE-22").inc(96)
    drift = update_drift_gauge(registry, baseline)
    assert drift == pytest.approx(0.96)
    assert registry.snapshot()["gauges"]["bank.anchor_drift"] == drift


def test_report_renders_anchor_table_and_shadow_line(tmp_path):
    registry = telemetry.configure(run_dir=tmp_path / "run")
    registry.counter("bank.anchor_wins.CWE-79").inc(30)
    registry.counter("bank.anchor_wins.CWE-89").inc(10)
    registry.histogram("bank.anchor_score.CWE-79").observe(0.9)
    registry.counter("bank.shadow_sampled").inc(40)
    registry.counter("bank.shadow_flips").inc(2)
    pin_baseline(registry, tmp_path / "run" / "anchor_baseline.json")
    update_drift_gauge(registry, {"CWE-79": 0.5, "CWE-89": 0.5})
    registry.write_summary()
    report = render_report(tmp_path / "run")
    assert "ANCHOR BANK" in report
    assert "CWE-79" in report and "75.0%" in report
    assert "drift(gauge)" in report and "drift(vs baseline): 0.000" in report
    assert "shadow: sampled=40 flips=2 flip_rate=0.0500" in report


# -- lint ----------------------------------------------------------------------

def test_bankops_writes_only_through_helpers():
    from lint_bank_artifact_writes import find_bare_writes

    offenders = find_bare_writes(REPO / "memvul_tpu" / "bankops")
    assert offenders == [], (
        "bankops/ must write artifacts via atomic_write_text / JsonlSink "
        f"(docs/anchor_bank.md): {offenders}"
    )


def test_bank_write_lint_flags_offenders(tmp_path, capsys):
    from lint_bank_artifact_writes import find_bare_writes, main

    (tmp_path / "bad.py").write_text(
        "open('x', 'w')\n"
        "open('y', mode='ab')\n"
        "from pathlib import Path\n"
        "Path('z').write_text('t')\n"
        "open('ok')\n"
        "open('ok2', 'r')\n"
    )
    offenders = find_bare_writes(tmp_path)
    assert {o.rsplit(":", 1)[1] for o in offenders} == {"1", "2", "4"}
    assert main([str(tmp_path)]) == 1
    assert "bad.py:1" in capsys.readouterr().out
    (tmp_path / "bad.py").write_text("x = open('ok')\n")
    assert main([str(tmp_path)]) == 0
    assert main([str(tmp_path / "missing")]) == 2


# -- real-model fixtures -------------------------------------------------------

@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("bankops"), seed=13)


@pytest.fixture(scope="module")
def setup(ws):
    """One warmed tiny predictor + a v1/v2 bank store: v2 = v1 with one
    anchor retired and two added (a GEOMETRY-changing diff, so shadow
    attach exercises the off-path re-warm)."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    predictor = SiamesePredictor(
        model, params, ws["tokenizer"],
        batch_size=8, max_length=48, buckets=[16, 48],
    )
    predictor.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    texts = [
        inst["text1"]
        for inst in reader.read(ws["paths"]["test"], split="test")
    ]
    return predictor, reader, texts


@pytest.fixture()
def store_v2(ws, tmp_path):
    """A store whose v1 is the workspace's golden bank and whose v2
    retires one anchor and adds two new ones."""
    store = BankStore(tmp_path / "banks")
    anchors = load_anchors(ws["paths"]["anchors"])
    store.create(anchors, source="build")
    first = sorted(anchors)[0]
    store.derive("v1", BankDiff.from_json([
        {"op": "retire", "category": first},
        {"op": "add", "category": "CWE-NEW-1",
         "description": "a brand new weakness class about parsing"},
        {"op": "add", "category": "CWE-NEW-2",
         "description": "another new weakness class about memory"},
    ]))
    return store


def make_service(predictor, tel_dir=None, **overrides):
    defaults = dict(
        max_batch=8, max_wait_ms=3.0, max_queue=1000,
        default_deadline_ms=30000.0,
    )
    defaults.update(overrides)
    return ScoringService(
        predictor, config=ServiceConfig(**defaults), manifest_dir=tel_dir
    )


def _score_all(service, texts, timeout=60.0):
    futures = [service.submit(t) for t in texts]
    return [f.result(timeout) for f in futures]


def _wait_counter(registry, name, target, timeout=20.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if registry.counter(name).value >= target:
            return registry.counter(name).value
        time.sleep(0.01)
    return registry.counter(name).value


# -- the end-to-end lifecycle (acceptance criteria) ----------------------------

def test_lifecycle_shadow_promote_demote(setup, store_v2, tmp_path):
    """build v1 → diff v2 → shadow v2 under live load (active bitwise
    unchanged, traces flat, delta rows exact) → gate refuses then
    promotes → demote restores the parent."""
    predictor, reader, texts = setup
    store = store_v2
    registry = telemetry.configure(run_dir=tmp_path / "run")
    service = make_service(predictor, tel_dir=tmp_path / "run")
    texts = texts[:24]
    try:
        # -- baseline run, no shadow
        baseline = _score_all(service, texts)
        assert all(r["status"] == "ok" for r in baseline)
        assert all(r["bank_version"] == 1 for r in baseline)

        # -- shadow v2 against live load
        scorer = ShadowScorer(
            service,
            store.instances("v2"),
            out_dir=tmp_path / "run",
            config=ShadowConfig(sample_stride=1, max_queue=10_000),
            candidate_version="v2",
        )
        traces_after_attach = predictor.score_trace_count
        shadowed = _score_all(service, texts)
        # active responses BITWISE-unchanged with the shadow on
        for a, b in zip(baseline, shadowed):
            assert a["predict"] == b["predict"]
            assert a["anchor"] == b["anchor"]
        # no mid-serve compile on account of the shadow
        assert predictor.score_trace_count == traces_after_attach
        sampled = _wait_counter(registry, "bank.shadow_sampled", len(texts))
        assert sampled == len(texts)
        summary = scorer.stop()
        # one delta row per sampled request, exactly
        rows, torn = read_jsonl(tmp_path / "run" / SHADOW_DELTAS_NAME)
        assert torn == 0
        assert len(rows) == summary["sampled"] == len(texts)
        assert all(r["candidate_version"] == "v2" for r in rows)
        assert all(r["active_version"] == 1 for r in rows)
        for row in rows:
            assert row["delta"] == pytest.approx(
                row["shadow_score"] - row["active_score"]
            )

        # -- gate refuses a candidate without enough shadow evidence,
        # with a machine-readable reason
        strict = GateThresholds(min_shadow_samples=10 ** 6)
        refused = evaluate_gate(
            {"auc": 0.9, "f1": 0.8}, {"auc": 0.9, "f1": 0.8},
            summary, strict, candidate="v2", parent="v1",
        )
        assert not refused.approved
        assert refused.reasons[0]["code"] == REASON_SHADOW_SAMPLES
        assert refused.reasons[0]["observed"] == len(texts)
        with pytest.raises(PromotionRefused):
            promote(service, store, refused)
        assert service.bank_version == 1  # nothing was installed

        # -- and promotes a good one
        lenient = GateThresholds(
            max_auc_drop=1.0, max_f1_drop=1.0,
            max_flip_rate=1.0, min_shadow_samples=1,
        )
        approved = evaluate_gate(
            {"auc": 0.9, "f1": 0.8}, {"auc": 0.9, "f1": 0.8},
            summary, lenient, candidate="v2", parent="v1",
        )
        serving_version = promote(service, store, approved)
        assert serving_version == 2 and service.bank_version == 2
        snapshot = service.bank_snapshot()
        assert snapshot.source == "promotion"
        assert snapshot.store_version == "v2"
        assert snapshot.parent_version == 1
        assert store.active()["version"] == "v2"
        v2_labels = set(store.anchors("v2"))
        assert set(service.bank_labels) == v2_labels
        promoted = _score_all(service, texts[:8])
        assert all(r["bank_version"] == 2 for r in promoted)
        manifest = json.loads(
            (tmp_path / "run" / MANIFEST_NAME).read_text()
        )
        assert manifest["source"] == "promotion"
        assert manifest["store_version"] == "v2"
        assert manifest["parent_version"] == 1

        # -- demote restores the parent
        result = demote(service, store)
        assert result["version"] == "v1"
        assert service.bank_version == result["serving_version"] == 3
        assert set(service.bank_labels) == set(store.anchors("v1"))
        assert service.bank_snapshot().source == "demotion"
        assert store.active()["version"] == "v1"
        kinds = [r["kind"] for r in store.promotions()]
        assert kinds == ["promotion_refused", "promotion", "demotion"]

        # -- per-anchor win/drift table renders
        registry.write_summary()
        report = render_report(tmp_path / "run")
        assert "ANCHOR BANK" in report
        assert "shadow: sampled=" in report
    finally:
        service.drain()


def test_shadow_fault_never_touches_active_path(setup, tmp_path):
    """Chaos: the ``bank.shadow`` fault point crashes the shadow worker
    — errors land in ``bank.shadow_errors``, every client still gets an
    ``ok`` response, and the serve counter invariant holds exactly."""
    predictor, reader, texts = setup
    registry = telemetry.configure(run_dir=tmp_path / "run")
    service = make_service(predictor)
    texts = texts[:12]
    faults.configure("bank.shadow=raise:RuntimeError:shadow boom")
    try:
        scorer = ShadowScorer(
            service, predictor_bank_instances(reader, predictor),
            out_dir=tmp_path / "run",
            config=ShadowConfig(sample_stride=1, max_queue=10_000),
        )
        responses = _score_all(service, texts)
        assert all(r["status"] == "ok" for r in responses)
        # wait for the worker to account every tapped sample (scored or
        # errored) before detaching — the tap fires after resolution
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            counters = registry.snapshot()["counters"]
            done = (
                counters.get("bank.shadow_sampled", 0)
                + counters.get("bank.shadow_errors", 0)
            )
            if done >= len(texts):
                break
            time.sleep(0.01)
        errors = registry.counter("bank.shadow_errors").value
        assert errors >= 1
        summary = scorer.stop()
        assert summary["errors"] >= 1
        # the active path never counted an error, and the invariant sums
        counters = registry.snapshot()["counters"]
        assert counters.get("serve.errors", 0) == 0
        assert counters["serve.served"] == len(texts)
        assert (
            counters["serve.served"]
            + counters.get("serve.shed", 0)
            + counters.get("serve.errors", 0)
            == counters["serve.requests"]
        )
        # shadowed rows = sampled - errored; every written row is intact
        rows, torn = read_jsonl(tmp_path / "run" / SHADOW_DELTAS_NAME)
        assert torn == 0
        assert len(rows) == summary["sampled"] == len(texts) - errors
    finally:
        service.drain()


def predictor_bank_instances(reader, predictor):
    """The predictor's own anchors as instances (an identity candidate)."""
    return [
        {"text1": "anchor text for " + label, "label": "same",
         "meta": {"type": "golden", "label": label}}
        for label in predictor.anchor_labels
    ]


def test_offline_replay_matches_recorded_run(setup, store_v2, ws, tmp_path):
    """Offline shadow: replaying a predict_file output against the SAME
    bank yields zero delta and zero flips, one row per recorded report."""
    predictor, reader, texts = setup
    store = store_v2
    out = tmp_path / "replay"
    out.mkdir()
    results = out / "memory_result.json"
    metrics = predictor.predict_file(
        reader, ws["paths"]["test"], results, split="test"
    )
    summary = replay_results(
        predictor,
        store.instances("v1"),
        reader,
        corpus_path=ws["paths"]["test"],
        results_path=results,
        out_dir=out,
        split="test",
        candidate_version="v1",
    )
    assert summary["sampled"] == int(metrics["num_samples"])
    assert summary["flips"] == 0
    assert summary["mean_abs_delta"] == pytest.approx(0.0, abs=1e-6)
    rows, torn = read_jsonl(out / SHADOW_DELTAS_NAME)
    assert torn == 0 and len(rows) == summary["sampled"]
    assert all(r["shadow_anchor"] == r["active_anchor"] for r in rows)


def test_golden_metrics_smoke(setup, store_v2, ws):
    predictor, reader, _texts = setup
    metrics = golden_metrics(
        predictor,
        store_v2.instances("v1"),
        list(reader.read(ws["paths"]["test"], split="test"))[:16],
    )
    for key in ("auc", "f1", "precision", "recall"):
        assert key in metrics
    assert metrics["n_eval"] == 16


# -- cascade parity gate (docs/quantized_serving.md) ---------------------------

@pytest.fixture(scope="module")
def cascade_gate_setup(ws):
    """One tiny model + params shared by the cascade-gate tests; the band
    varies per test, so the fixture returns a builder."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    anchors = list(reader.read_anchors(ws["paths"]["anchors"]))

    def make(low, high):
        predictor = SiamesePredictor(
            model, params, ws["tokenizer"],
            batch_size=8, max_length=48, buckets=[48],
            encoder_precision="int8", score_impl="cascade",
            cascade_low=low, cascade_high=high,
        )
        predictor.encode_anchors(anchors)
        return predictor

    instances = list(reader.read(ws["paths"]["test"], split="test"))
    return {"make": make, "instances": instances}


def test_evaluate_cascade_requires_int8_predictor(setup):
    predictor, _reader, _texts = setup
    with pytest.raises(ValueError, match="int8"):
        evaluate_cascade(predictor, [])


def test_evaluate_cascade_approves_and_prefers_live_shadow(cascade_gate_setup):
    """A sane rescue band over the golden set approves: fp32-vs-cascade
    deltas are quantization noise, zero decision flips — and a live
    ShadowScorer summary, when supplied, is used verbatim instead of the
    synthesized offline one."""
    instances = cascade_gate_setup["instances"]
    predictor = cascade_gate_setup["make"](0.3, 0.7)
    decision = evaluate_cascade(
        predictor, instances,
        thresholds=GateThresholds(min_shadow_samples=10),
    )
    assert decision.approved and decision.reasons == []
    assert decision.candidate == "cascade" and decision.parent == "fp32"
    shadow = decision.metrics["shadow"]
    assert shadow["sampled"] == len(instances)
    assert shadow["flips"] == 0
    assert shadow["max_abs_delta"] < 0.01
    assert decision.metrics["candidate"]["n_eval"] == float(len(instances))

    live = {"sampled": 500, "flips": 1, "flip_rate": 0.002}
    with_live = evaluate_cascade(predictor, instances, shadow_summary=live)
    assert with_live.approved
    assert with_live.metrics["shadow"] == live


def test_evaluate_cascade_refuses_lossy_band_machine_readably(
    cascade_gate_setup,
):
    """A band that lets every row short-circuit on int8 (low == high == 0:
    nothing is ever rescued) must refuse once the decision threshold sits
    inside the quantization gap — with the standard machine-readable
    ``{code, observed, limit}`` reason, not a vague failure."""
    instances = cascade_gate_setup["instances"]
    predictor = cascade_gate_setup["make"](0.0, 0.0)
    texts = [inst["text1"] for inst in instances]
    fp32 = predictor.score_texts(texts, impl="bucketed").max(axis=1)
    int8 = predictor.score_texts(texts, impl="int8").max(axis=1)
    deltas = np.abs(fp32 - int8)
    row = int(deltas.argmax())
    assert deltas[row] > 0  # quantization moves at least one best score
    cut = float((fp32[row] + int8[row]) / 2.0)  # a flip by construction
    decision = evaluate_cascade(
        predictor, instances, threshold=cut,
        thresholds=GateThresholds(max_flip_rate=0.0, min_shadow_samples=1),
    )
    assert not decision.approved
    assert [r["code"] for r in decision.reasons] == [REASON_FLIP_RATE]
    (reason,) = decision.reasons
    assert set(reason) == {"code", "observed", "limit"}
    assert reason["observed"] >= 1 / len(instances)
    assert reason["limit"] == 0.0


# -- offline attribution satellites --------------------------------------------

def test_score_instances_anchor_attribution_flag(setup, ws):
    predictor, reader, _texts = setup
    instances = list(reader.read(ws["paths"]["test"], split="test"))[:8]
    # default: metas untouched
    for probs, metas in predictor.score_instances(iter(instances)):
        assert all("_anchor" not in m for m in metas)
    for probs, metas in predictor.score_instances(
        iter(instances), with_anchors=True
    ):
        for row, meta in zip(probs, metas):
            assert meta["_anchor_index"] == int(np.argmax(row))
            assert (
                meta["_anchor"]
                == predictor.anchor_labels[meta["_anchor_index"]]
            )


def test_predict_file_attribute_anchors_flag(setup, ws, tmp_path):
    predictor, reader, _texts = setup
    default_out = tmp_path / "default.json"
    predictor.predict_file(
        reader, ws["paths"]["test"], default_out, split="test"
    )
    records = [
        rec
        for line in default_out.read_text().splitlines()
        for rec in json.loads(line)
    ]
    assert records and all("anchor" not in r for r in records)
    attributed_out = tmp_path / "attributed.json"
    predictor.predict_file(
        reader, ws["paths"]["test"], attributed_out, split="test",
        attribute_anchors=True,
    )
    attributed = [
        rec
        for line in attributed_out.read_text().splitlines()
        for rec in json.loads(line)
    ]
    assert len(attributed) == len(records)
    for rec in attributed:
        assert rec["anchor"] == max(rec["predict"], key=rec["predict"].get)
        assert rec["anchor_index"] == predictor.anchor_labels.index(
            rec["anchor"]
        )
        # the probability payload itself is unchanged by the flag
    assert [r["predict"] for r in attributed] == [
        r["predict"] for r in records
    ]


def test_predict_single_returns_attribution(setup):
    predictor, _reader, texts = setup
    traces = predictor.score_trace_count
    result = predictor.predict_single(texts[0])
    assert predictor.score_trace_count == traces  # warmed shape, no trace
    assert set(result) == {"predict", "score", "anchor", "anchor_index"}
    assert result["anchor"] == max(
        result["predict"], key=result["predict"].get
    )
    assert result["score"] == result["predict"][result["anchor"]]
    assert (
        predictor.anchor_labels[result["anchor_index"]] == result["anchor"]
    )


# -- serving provenance satellite ----------------------------------------------

def test_swap_bank_manifest_and_health_record_provenance(setup, tmp_path):
    predictor, reader, _texts = setup
    telemetry.configure(run_dir=tmp_path / "run")
    service = make_service(predictor, tel_dir=tmp_path / "run")
    try:
        manifest = json.loads((tmp_path / "run" / MANIFEST_NAME).read_text())
        assert manifest["source"] == "startup"
        assert manifest["parent_version"] is None
        service.swap_bank(
            predictor_bank_instances(reader, predictor), source="manual"
        )
        manifest = json.loads((tmp_path / "run" / MANIFEST_NAME).read_text())
        assert manifest["version"] == 2
        assert manifest["parent_version"] == 1
        assert manifest["source"] == "manual"
        assert manifest["store_version"] is None
        health = service.health_summary()
        assert health["bank"] == {
            "version": 2, "source": "manual",
            "parent_version": 1, "store_version": None,
        }
    finally:
        service.drain()


# -- fleet promotion via rolling_swap (fake predictors, fast) ------------------

class _FakeEncoder:
    pad_id = 0

    def __init__(self, max_length=8):
        self.max_length = max_length

    def encode_many(self, texts):
        return [[1] * min(max(len(t), 1), self.max_length) for t in texts]


class _FakePredictor:
    def __init__(self, n_anchors=3, rows=4, length=8):
        self.encoder = _FakeEncoder(length)
        self.mesh = None
        self.params = None
        self.n_anchors = n_anchors
        self.anchor_labels = [f"A{i}" for i in range(n_anchors)]
        self.anchor_bank = np.zeros((n_anchors, 2), np.float32)
        self.score_trace_count = 0
        self._shapes = [(rows, length)]

    def stream_shapes(self):
        return list(self._shapes)

    def encode_bank(self, instances):
        instances = list(instances)
        labels = [inst["meta"]["label"] for inst in instances]
        return np.zeros((len(labels), 2), np.float32), labels, len(labels)

    def warmup_bank_shapes(self, bank):
        return len(self._shapes)

    def _score_fn(self, params, sample, bank):
        rows = sample["input_ids"].shape[0]
        return np.tile(
            np.linspace(0.1, 0.9, bank.shape[0], dtype=np.float32), (rows, 1)
        )


def _fake_fleet(n=2):
    def make_factory(i):
        def factory(registry):
            return ScoringService(
                _FakePredictor(),
                config=ServiceConfig(
                    max_batch=4, max_wait_ms=1.0, max_queue=1000,
                    default_deadline_ms=30000.0,
                ),
                registry=registry,
            )
        return factory

    replicas = [
        Replica(i, make_factory(i), telemetry_enabled=True) for i in range(n)
    ]
    router = ReplicaRouter(
        replicas, config=RouterConfig(monitor_interval_s=0.05)
    )
    return router, replicas


def test_fleet_promotion_rolls_and_demotes(tmp_path):
    """promote() on a router goes through rolling_swap: the fleet
    advances one version, every response under load carries exactly one
    version, provenance lands in every replica's health row, and
    demote() rolls the parent back out."""
    store = BankStore(tmp_path / "banks")
    store.create({"A0": "zero", "A1": "one", "A2": "two"})
    store.derive("v1", BankDiff.from_json([
        {"op": "add", "category": "A3", "description": "three"},
    ]))
    router, replicas = _fake_fleet(2)
    try:
        stop = threading.Event()
        versions_seen = set()
        failures = []

        def client():
            while not stop.is_set():
                try:
                    response = router.submit("report text").result(10)
                except Exception as e:  # pragma: no cover - fail the test
                    failures.append(repr(e))
                    return
                if response["status"] == "ok":
                    versions_seen.add(response["bank_version"])

        thread = threading.Thread(target=client)
        thread.start()
        decision = PromotionDecision(
            approved=True, candidate="v2", parent="v1",
            reasons=[], metrics={},
        )
        serving_version = promote(router, store, decision)
        stop.set()
        thread.join(10)
        assert not failures, failures
        assert serving_version == 2 and router.bank_version == 2
        # every response carried exactly one of the two rollout versions
        assert versions_seen <= {1, 2}
        for replica in replicas:
            row = replica.summary()
            assert row["bank_version"] == 2
            assert row["bank_source"] == "promotion"
            assert row["bank_store_version"] == "v2"
        assert store.active()["version"] == "v2"
        # demote: the parent rolls back out fleet-wide
        result = demote(router, store)
        assert result["version"] == "v1"
        assert router.bank_version == result["serving_version"] == 3
        for replica in replicas:
            row = replica.summary()
            assert row["bank_source"] == "demotion"
            assert row["bank_store_version"] == "v1"
            assert set(replica.service.bank_labels) == {"A0", "A1", "A2"}
        assert store.active()["version"] == "v1"
    finally:
        router.drain()


def test_router_shadow_tap_fans_out_and_survives_restart():
    """The router installs one tap on every replica, and a replica
    restart re-attaches it (a death must not silently end a shadow
    evaluation)."""
    router, replicas = _fake_fleet(2)
    try:
        seen = []
        router.set_shadow_tap(lambda texts, probs, bank: seen.append(1))
        for replica in replicas:
            assert replica.service._shadow_tap is not None
        replicas[0].restart()
        assert replicas[0].service._shadow_tap is not None
        router.clear_shadow_tap()
        for replica in replicas:
            assert replica.service._shadow_tap is None
    finally:
        router.drain()


# -- CLI -----------------------------------------------------------------------

def test_bank_cli_build_diff_log_roundtrip(tmp_path, capsys):
    from memvul_tpu.__main__ import main

    anchors_path = tmp_path / "anchors.json"
    anchors_path.write_text(json.dumps(ANCHORS_V1))
    store_dir = tmp_path / "banks"
    assert main([
        "bank", "build", "--store", str(store_dir),
        "--anchors", str(anchors_path), "--note", "seed",
    ]) == 0
    built = json.loads(capsys.readouterr().out)
    assert built["version"] == "v1" and built["n_anchors"] == 3
    ops = [
        {"op": "add", "category": "CWE-502", "description": "deser"},
    ]
    assert main([
        "bank", "diff", "--store", str(store_dir),
        "--ops", json.dumps(ops),
        "--retire", "CWE-89", "--reweight", "CWE-79=2.5",
    ]) == 0
    derived = json.loads(capsys.readouterr().out)
    assert derived["version"] == "v2" and derived["parent"] == "v1"
    assert derived["weights"] == {"CWE-79": 2.5}
    assert main(["bank", "log", "--store", str(store_dir)]) == 0
    log = json.loads(capsys.readouterr().out)
    assert log["versions"] == ["v1", "v2"]
    assert [m["version"] for m in log["lineage"]] == ["v1", "v2"]
    # a conflicting diff exits 2 with a usage message, not a traceback
    assert main([
        "bank", "diff", "--store", str(store_dir), "--retire", "CWE-404",
    ]) == 2
