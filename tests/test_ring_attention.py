"""Sequence-parallel ring attention vs the single-device XLA kernel.

The reference has no sequence parallelism (long inputs are folded,
custom_PTM_embedder.py:244-381); ring attention is the TPU build's
long-context capability, so it must match exact attention bit-for-bit
(to fp32 tolerance) on an 8-way sharded sequence axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from memvul_tpu.ops.attention import dot_product_attention, mask_to_bias
from memvul_tpu.parallel import create_mesh, make_ring_attention


def _qkv(b=2, t=64, h=4, d=16, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    mk = lambda: jnp.asarray(rng.normal(size=(b, t, h, d)), dtype)
    return mk(), mk(), mk()


@pytest.fixture(scope="module")
def seq_mesh():
    return create_mesh({"seq": 8})


def test_ring_matches_xla_full_mask(seq_mesh):
    q, k, v = _qkv()
    mask = jnp.ones(q.shape[:2], jnp.int32)
    ring_fn = make_ring_attention(seq_mesh)
    out_ring = ring_fn(q, k, v, mask)
    out_ref = dot_product_attention(q, k, v, bias=mask_to_bias(mask))
    np.testing.assert_allclose(
        np.asarray(out_ring), np.asarray(out_ref), atol=1e-5, rtol=1e-5
    )


def test_ring_matches_xla_ragged_mask(seq_mesh):
    q, k, v = _qkv(seed=1)
    # ragged: some sequences end mid-shard, exercising travelling key masks
    lengths = [64, 37, ]
    mask = np.zeros(q.shape[:2], np.int32)
    for i, L in enumerate(lengths):
        mask[i, :L] = 1
    mask = jnp.asarray(mask)
    out_ring = make_ring_attention(seq_mesh)(q, k, v, mask)
    out_ref = dot_product_attention(q, k, v, bias=mask_to_bias(mask))
    # compare only real query rows; padded-query rows attend uniformly and
    # are dropped by downstream pooling either way
    m = np.asarray(mask).astype(bool)
    np.testing.assert_allclose(
        np.asarray(out_ring)[m], np.asarray(out_ref)[m], atol=1e-5, rtol=1e-5
    )


def test_ring_all_masked_sequence_returns_zeros(seq_mesh):
    """A sequence whose keys are ALL padding must produce zero outputs for
    every query row, not a uniform average over masked keys (the documented
    public-API contract for all-masked rows)."""
    q, k, v = _qkv(seed=3)
    mask = np.ones(q.shape[:2], np.int32)
    mask[1, :] = 0  # second sequence entirely padding
    out = make_ring_attention(seq_mesh)(q, k, v, jnp.asarray(mask))
    out = np.asarray(out)
    np.testing.assert_array_equal(out[1], np.zeros_like(out[1]))
    # real sequence is untouched
    ref = dot_product_attention(q, k, v, bias=mask_to_bias(jnp.asarray(mask)))
    np.testing.assert_allclose(out[0], np.asarray(ref)[0], atol=1e-5, rtol=1e-5)


def test_ring_matches_xla_for_arbitrary_length_mixes(seq_mesh):
    """Property (hypothesis): ring == XLA attention for ARBITRARY valid
    length mixes across the batch — lengths landing exactly on shard
    boundaries (multiples of T/8), mid-shard, full, and zero (the
    all-masked-zeros contract) in one batch.  Generalizes the
    hand-picked ragged cases; the travelling-key-mask arithmetic must
    hold for every boundary alignment."""
    pytest.importorskip("hypothesis")  # property tier is optional (pyproject [test])
    from hypothesis import given, settings, strategies as st

    T = 64
    ring_fn = make_ring_attention(seq_mesh)

    # batch fixed at 4: a varying batch dim would force one JIT compile
    # per distinct size inside the hypothesis loop for no coverage gain
    @settings(max_examples=20, deadline=None)
    @given(st.lists(st.integers(min_value=0, max_value=T), min_size=4, max_size=4),
           st.integers(min_value=0, max_value=2**31 - 1))
    def check(lengths, seed):
        q, k, v = _qkv(b=len(lengths), t=T, seed=seed)
        mask = np.zeros((len(lengths), T), np.int32)
        for i, L in enumerate(lengths):
            mask[i, :L] = 1
        mask = jnp.asarray(mask)
        out_ring = np.asarray(ring_fn(q, k, v, mask))
        out_ref = np.asarray(
            dot_product_attention(q, k, v, bias=mask_to_bias(mask))
        )
        m = np.asarray(mask).astype(bool)
        np.testing.assert_allclose(out_ring[m], out_ref[m], atol=1e-5, rtol=1e-5)
        for i, L in enumerate(lengths):
            if L == 0:  # all-masked rows: exact zeros, not uniform average
                np.testing.assert_array_equal(
                    out_ring[i], np.zeros_like(out_ring[i])
                )

    check()


def test_ring_bf16_close_to_fp32(seq_mesh):
    q, k, v = _qkv(seed=2, dtype=jnp.bfloat16)
    mask = jnp.ones(q.shape[:2], jnp.int32)
    out_ring = make_ring_attention(seq_mesh)(q, k, v, mask)
    assert out_ring.dtype == jnp.bfloat16
    out_ref = dot_product_attention(
        q.astype(jnp.float32), k.astype(jnp.float32), v.astype(jnp.float32),
        bias=mask_to_bias(mask),
    )
    np.testing.assert_allclose(
        np.asarray(out_ring, np.float32), np.asarray(out_ref), atol=3e-2, rtol=3e-2
    )


def test_sequence_parallel_encoder_matches_dense(seq_mesh):
    """Full BertEncoder with attention_impl='ring', sequence 8-way sharded,
    vs the same params run dense with XLA attention."""
    from memvul_tpu.models import BertConfig, BertEncoder
    from memvul_tpu.parallel import encode_sequence_parallel

    cfg = BertConfig.tiny(vocab_size=512)
    dense = BertEncoder(cfg)
    ring = BertEncoder(cfg.replace(attention_impl="ring"))

    rng = np.random.default_rng(4)
    b, t = 2, 64
    ids = jnp.asarray(rng.integers(0, 500, (b, t)), jnp.int32)
    mask = np.ones((b, t), np.int32)
    mask[1, 40:] = 0  # ragged: second sequence ends inside shard 5
    mask = jnp.asarray(mask)

    params = dense.init(jax.random.PRNGKey(0), ids, mask)
    out_dense = dense.apply(params, ids, mask, deterministic=True)
    out_ring = encode_sequence_parallel(ring, params, ids, mask, seq_mesh)
    m = np.asarray(mask).astype(bool)
    np.testing.assert_allclose(
        np.asarray(out_ring)[m], np.asarray(out_dense)[m], atol=1e-5, rtol=1e-5
    )


def test_sequence_parallel_rejects_wrong_impl(seq_mesh):
    from memvul_tpu.models import BertConfig, BertEncoder
    from memvul_tpu.parallel import encode_sequence_parallel

    enc = BertEncoder(BertConfig.tiny(vocab_size=64))
    with pytest.raises(ValueError, match="ring"):
        encode_sequence_parallel(
            enc, {}, jnp.zeros((1, 64), jnp.int32),
            jnp.ones((1, 64), jnp.int32), seq_mesh,
        )


def test_ring_jits_and_grads(seq_mesh):
    """The op must be differentiable for sequence-parallel training."""
    q, k, v = _qkv(seed=3, t=32, h=2, d=8)
    mask = jnp.ones(q.shape[:2], jnp.int32)
    ring_fn = make_ring_attention(seq_mesh)

    def loss(q):
        return (ring_fn(q, k, v, mask) ** 2).sum()

    g = jax.jit(jax.grad(loss))(q)
    assert g.shape == q.shape
    assert bool(jnp.isfinite(g).all())

    def loss_ref(q):
        return (dot_product_attention(q, k, v, bias=mask_to_bias(mask)) ** 2).sum()

    g_ref = jax.grad(loss_ref)(q)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), atol=1e-4, rtol=1e-4)
