"""CLI + config-driven construction + archive round-trip.

Covers the reference's L5 contract: ``allennlp train <config> -s <dir>``
(→ ``python -m memvul_tpu train``), the archived-config override merge
used by the eval scripts (reference: predict_memory.py:60-67), and the
model.tar.gz round-trip.
"""

import json
from pathlib import Path

import jax.numpy as jnp
import numpy as np
import pytest

from memvul_tpu.__main__ import main
from memvul_tpu.archive import load_archive, save_archive
from memvul_tpu.build import build_model, encoder_config, init_params
from memvul_tpu.config import loads_config
from memvul_tpu.data.synthetic import build_workspace, selfcheck_config

CONFIGS_DIR = Path(__file__).resolve().parent.parent / "configs"


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("cli"), seed=11)


@pytest.fixture(scope="module")
def trained_ser_dir(ws, tmp_path_factory):
    """One CLI-trained tiny archive shared by every evaluate-flag test
    below (each used to re-train an identical model — ~40% of this
    file's tier-1 wall clock).  Evaluation is read-only on the archive;
    each test still writes to its own output dir.  The train path
    itself stays covered by test_cli_train_then_evaluate_memory, which
    asserts on the training artifacts."""
    base = tmp_path_factory.mktemp("cli_shared_train")
    config = tiny_memory_config(ws)
    cfg_path = base / "config.json"
    cfg_path.write_text(json.dumps(config))
    ser_dir = base / "out"
    assert main(["train", str(cfg_path), "-s", str(ser_dir)]) == 0
    return ser_dir


def tiny_memory_config(ws, **trainer_kw):
    # the shared selfcheck geometry (memvul_tpu/data/synthetic.py) —
    # the CLI `selfcheck` command trains exactly this
    return selfcheck_config(ws, **trainer_kw)


# -- config parsing / model construction --------------------------------------

def test_shipped_configs_parse():
    files = sorted(CONFIGS_DIR.glob("*.json"))
    assert len(files) >= 8
    for f in files:
        cfg = loads_config(f.read_text())
        assert isinstance(cfg, dict) and cfg


def test_shipped_trainer_blocks_construct_their_dataclasses():
    """Every shipped train config's trainer block must construct its
    trainer dataclass — catches config/dataclass drift (an unknown key
    in JSON raises TypeError here instead of at training time)."""
    from memvul_tpu.pretrain.mlm import MLMTrainerConfig
    from memvul_tpu.training.single_trainer import ClassifierTrainerConfig
    from memvul_tpu.training.trainer import TrainerConfig

    checked = 0
    for f in sorted(CONFIGS_DIR.glob("*.json")):
        cfg = loads_config(f.read_text())
        trainer = dict(cfg.get("trainer") or {})
        if not trainer:
            continue  # test-time override fragments have no trainer block
        # mirror build.py's dispatch exactly: further_pretrain → MLM,
        # model.type defaults to model_memory, everything else classifier
        model_type = (cfg.get("model") or {}).get("type", "model_memory")
        if f.name.startswith("further"):
            MLMTrainerConfig(**trainer)
        elif model_type == "model_memory":
            TrainerConfig(**trainer)
        else:
            ClassifierTrainerConfig(**trainer)
        checked += 1
    assert checked >= 8


def test_encoder_config_dtype_and_preset():
    cfg = encoder_config({"preset": "tiny", "dtype": "bfloat16"}, vocab_size=777)
    assert cfg.dtype == jnp.bfloat16
    assert cfg.vocab_size == 777
    assert cfg.num_layers == 2


def test_build_model_types():
    from memvul_tpu.models import MemoryModel, SingleModel
    from memvul_tpu.models.textcnn import TextCNN

    mem = build_model(
        {"type": "model_memory", "encoder": {"preset": "tiny"}}, vocab_size=100
    )
    single = build_model(
        {"type": "model_single", "encoder": {"preset": "tiny"}}, vocab_size=100
    )
    cnn = build_model({"type": "model_cnn", "embed_dim": 16}, vocab_size=100)
    assert isinstance(mem, MemoryModel)
    assert isinstance(single, SingleModel)
    assert isinstance(cnn, TextCNN)
    with pytest.raises(ValueError):
        build_model({"type": "nope"}, vocab_size=10)


# -- archive round-trip --------------------------------------------------------

def test_archive_roundtrip_with_overrides(ws, tmp_path):
    model_cfg = {"type": "model_memory", "encoder": {"preset": "tiny", "vocab_size": 4096}, "header_dim": 32}
    config = {
        "tokenizer": {"type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"]},
        "model": model_cfg,
        "evaluation": {"batch_size": 512, "max_length": 512},
    }
    model = build_model(model_cfg, 4096)
    params = init_params(model, seed=0)
    path = save_archive(
        tmp_path / "model.tar.gz", config, params,
        tokenizer_file=ws["paths"]["tokenizer"],
    )
    arch = load_archive(path, overrides={"evaluation": {"batch_size": 8}})
    assert arch.config["evaluation"]["batch_size"] == 8
    assert arch.config["evaluation"]["max_length"] == 512  # deep merge keeps rest
    # params survive serialization bit-exactly
    orig = np.asarray(params["params"]["pair_kernel"])
    back = np.asarray(arch.params["params"]["pair_kernel"])
    np.testing.assert_array_equal(orig, back)
    # the archived tokenizer is self-contained (loaded from inside the tar)
    assert arch.tokenizer.vocab_size == ws["tokenizer"].vocab_size


def test_archive_roundtrip_with_bert_vocab_txt(tmp_path):
    """An archive built from a bert-style ``vocab.txt`` stays self-contained:
    the vocab file keeps its name in the tar and wins over any (possibly
    nonexistent) path mentioned in the stored config."""
    words = ["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]", "hello", "world", "##s"]
    vocab_path = tmp_path / "vocab.txt"
    vocab_path.write_text("\n".join(words) + "\n")
    model_cfg = {"type": "model_memory", "encoder": {"preset": "tiny", "vocab_size": len(words)}, "header_dim": 32}
    config = {
        # deliberately points at a path that will NOT exist at load time
        "tokenizer": {"type": "wordpiece", "vocab_path": "data/vocab.txt"},
        "model": model_cfg,
    }
    model = build_model(model_cfg, len(words))
    params = init_params(model, seed=0)
    path = save_archive(
        tmp_path / "model.tar.gz", config, params, tokenizer_file=vocab_path
    )
    arch = load_archive(path)
    assert arch.tokenizer.vocab_size == len(words)
    assert arch.tokenizer.encode("hello worlds") == [2, 5, 6, 7, 3]


# -- end-to-end CLI ------------------------------------------------------------

def test_cli_selfcheck(tmp_path, capsys):
    """The one-command acceptance run: builds its own corpus, trains,
    archives, evaluates, and reports the metric contract."""
    rc = main(["selfcheck", "--dir", str(tmp_path / "sc"), "--reports", "12"])
    out = capsys.readouterr().out.strip().splitlines()[-1]
    report = json.loads(out)
    assert rc == 0
    assert report["selfcheck"] == "ok"
    assert report["missing_metric_keys"] == []
    assert all(report["splits"].values()), report["splits"]
    assert Path(report["archive"]).exists()


def test_cli_train_then_evaluate_memory(ws, tmp_path):
    config = tiny_memory_config(ws)
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(config))
    ser_dir = tmp_path / "out"

    rc = main(["train", str(cfg_path), "-s", str(ser_dir)])
    assert rc == 0
    assert (ser_dir / "model.tar.gz").exists()
    assert (ser_dir / "metrics.json").exists()

    eval_dir = tmp_path / "eval"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(eval_dir), "--name", "memvul", "--no-mesh",
        "--overrides", json.dumps({"evaluation": {"batch_size": 8, "max_length": 48}}),
    ])
    assert rc == 0
    result_file = eval_dir / "memvul_result.json"
    metric_file = eval_dir / "memvul_metric_all.json"
    assert result_file.exists() and metric_file.exists()
    metrics = json.loads(metric_file.read_text())
    for key in ("TP", "FN", "TN", "FP", "prec", "f1", "auc"):
        assert key in metrics

    # "auto" buckets (padding-minimizing DP from a corpus length sample)
    # must reproduce the pad-to-max metrics exactly
    auto_dir = tmp_path / "eval_auto"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(auto_dir), "--name", "memvul", "--no-mesh",
        "--overrides", json.dumps({"evaluation": {
            "batch_size": 8, "max_length": 48,
            "buckets": "auto", "n_buckets": 3, "tokens_per_batch": 256,
        }}),
    ])
    assert rc == 0
    auto_metrics = json.loads((auto_dir / "memvul_metric_all.json").read_text())
    for key in ("TP", "FN", "TN", "FP", "f1", "auc"):
        assert auto_metrics[key] == pytest.approx(metrics[key], abs=1e-6), key

    # the SHIPPED override file, verbatim (// comments and all), against a
    # tiny-position archive: the Jsonnet-tolerant override parse plus the
    # max_length→max_position_embeddings clamp must make this just work
    # instead of crashing in the encoder (the override names 512, the
    # tiny model has 128 positions)
    shipped_dir = tmp_path / "eval_shipped"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(shipped_dir), "--name", "memvul", "--no-mesh",
        "--overrides",
        (CONFIGS_DIR / "test_config_memory.json").read_text(),
    ])
    assert rc == 0
    shipped_metrics = json.loads(
        (shipped_dir / "memvul_metric_all.json").read_text()
    )
    for key in ("TP", "FN", "TN", "FP", "f1", "auc"):
        assert key in shipped_metrics


def test_parse_mesh_flag():
    """--mesh parsing: axis specs build the right mesh, malformed specs
    fail with the usage hint BEFORE any training starts (the fast-tier
    stand-in for the end-to-end mesh run below)."""
    from memvul_tpu.__main__ import _parse_mesh

    assert _parse_mesh(None) is None
    mesh = _parse_mesh("data=8")
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {"data": 8}
    mesh = _parse_mesh("data=4,model=2")
    assert dict(zip(mesh.axis_names, mesh.devices.shape)) == {
        "data": 4, "model": 2,
    }
    for bad in ("data=7", "bogus=8", "data", "data=x"):
        with pytest.raises(SystemExit):
            _parse_mesh(bad)


@pytest.mark.slow  # two full train+evaluate CLI runs over the 8-device mesh
def test_cli_mesh_flag_end_to_end(ws, tmp_path):
    """--mesh through the CLI: dp training over all 8 virtual devices,
    then evaluation on a dp×tp mesh (model axis → TP param split + the
    model-sharded anchor-bank path) — the full flag-to-collective chain
    the library-level mesh tests can't see."""
    config = tiny_memory_config(ws, batch_size=8)
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(config))
    ser_dir = tmp_path / "out"
    rc = main(["train", str(cfg_path), "-s", str(ser_dir),
               "--mesh", "data=8"])
    assert rc == 0
    assert (ser_dir / "model.tar.gz").exists()

    eval_dir = tmp_path / "eval_mesh"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(eval_dir), "--name", "memvul",
        "--mesh", "data=4,model=2",
        "--overrides", json.dumps(
            {"evaluation": {"batch_size": 16, "max_length": 48}}
        ),
    ])
    assert rc == 0
    metrics = json.loads((eval_dir / "memvul_metric_all.json").read_text())
    for key in ("TP", "FN", "TN", "FP", "f1", "auc"):
        assert key in metrics

    # the shipped default POLICY (auto buckets + token budget) under the
    # same dp×tp mesh: metrics must match the pad-to-max mesh run
    # exactly (batching never changes scores).  The row-divisibility
    # invariant the mesh path relies on (multiple_of = 8×n_data,
    # predict_memory.py:67) is asserted on the helper with the exact
    # multiple this mesh passes:
    from memvul_tpu.data.batching import bucket_batch_sizes

    sizes = bucket_batch_sizes((16, 32, 48), 1024, multiple_of=8 * 4)
    assert sizes and all(v % 32 == 0 for v in sizes.values())

    auto_dir = tmp_path / "eval_mesh_auto"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(auto_dir), "--name", "memvul",
        "--mesh", "data=4,model=2",
        "--overrides", json.dumps({"evaluation": {
            "batch_size": 16, "max_length": 48,
            "buckets": "auto", "n_buckets": 3, "tokens_per_batch": 1024,
        }}),
    ])
    assert rc == 0
    auto_metrics = json.loads(
        (auto_dir / "memvul_metric_all.json").read_text()
    )
    for key in ("TP", "FN", "TN", "FP", "f1", "auc"):
        assert auto_metrics[key] == pytest.approx(metrics[key], abs=1e-5), key

    # malformed specs are USAGE errors: exit 2 (not 1 = run failed),
    # message on stderr, no traceback
    for bad in ("data=", "data=3", "date=8"):
        with pytest.raises(SystemExit) as exc:
            main(["train", str(cfg_path), "-s", str(tmp_path / "x"),
                  "--mesh", bad])
        assert exc.value.code == 2, bad


def test_cli_evaluate_threshold_flag_reaches_metrics(ws, trained_ser_dir, tmp_path):
    """--threshold carries the validation-chosen decision threshold into
    cal_metrics (reference: predict_memory.py thres argument); the
    metric file must record it and the confusion counts must respond."""
    ser_dir = trained_ser_dir
    overrides = json.dumps({"evaluation": {"batch_size": 8, "max_length": 48}})
    for thres in ("0.1", "0.9"):
        out = tmp_path / f"ev_{thres}"
        rc = main(["evaluate", str(ser_dir), ws["paths"]["test"],
                   "-o", str(out), "--name", "memvul", "--no-mesh",
                   "--threshold", thres, "--overrides", overrides])
        assert rc == 0
        m = json.loads((out / "memvul_metric_all.json").read_text())
        assert m["thres"] == float(thres)
        # falsifiable: TP+FP must equal the number of reports whose
        # max-over-anchors score clears THIS threshold, recomputed
        # independently from the result records — a vote decoupled from
        # the recorded threshold fails here
        expected_pos = 0
        for line in (out / "memvul_result.json").read_text().splitlines():
            for rec in json.loads(line):
                expected_pos += max(rec["predict"].values()) >= float(thres)
        assert m["TP"] + m["FP"] == expected_pos, thres


def test_cli_evaluate_jsonl_stream_matches_json(ws, trained_ser_dir, tmp_path):
    """The docs/full_corpus.md recipe: evaluating a ``.jsonl`` stream
    (the 1.2M-report format) through the CLI must produce the same
    metrics as the equivalent ``.json`` corpus."""
    ser_dir = trained_ser_dir
    samples = json.loads(Path(ws["paths"]["test"]).read_text())
    stream = tmp_path / "test_stream.jsonl"
    stream.write_text("\n".join(json.dumps(s) for s in samples))

    overrides = json.dumps({"evaluation": {"batch_size": 8, "max_length": 48}})
    rc = main(["evaluate", str(ser_dir), ws["paths"]["test"],
               "-o", str(tmp_path / "ev_json"), "--name", "memvul",
               "--no-mesh", "--overrides", overrides])
    assert rc == 0
    rc = main(["evaluate", str(ser_dir), str(stream),
               "-o", str(tmp_path / "ev_jsonl"), "--name", "memvul",
               "--no-mesh", "--overrides", overrides])
    assert rc == 0
    m_json = json.loads(
        (tmp_path / "ev_json" / "memvul_metric_all.json").read_text()
    )
    m_jsonl = json.loads(
        (tmp_path / "ev_jsonl" / "memvul_metric_all.json").read_text()
    )
    for key in ("TP", "FN", "TN", "FP", "f1", "auc"):
        assert m_jsonl[key] == pytest.approx(m_json[key], abs=1e-6), key


def test_cli_evaluate_golden_file_swaps_anchor_bank(ws, trained_ser_dir, tmp_path):
    """--golden-file replaces the archive config's anchor bank at eval
    time (reference: predict_memory.py's golden file argument) — the
    entry point of the CWE-1000 full-view flow.  Result records must
    score against the ALTERNATE bank's labels."""
    ser_dir = trained_ser_dir
    anchors = json.loads(Path(ws["paths"]["anchors"]).read_text())
    extra_label = "CWE-TEST-ONLY"
    anchors[extra_label] = "A synthetic anchor describing a test weakness."
    alt = tmp_path / "alt_anchors.json"
    alt.write_text(json.dumps(anchors))

    eval_dir = tmp_path / "eval_alt"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(eval_dir), "--name", "memvul", "--no-mesh",
        "--golden-file", str(alt),
        "--overrides", json.dumps(
            {"evaluation": {"batch_size": 8, "max_length": 48}}
        ),
    ])
    assert rc == 0
    first_line = (eval_dir / "memvul_result.json").read_text().splitlines()[0]
    record = json.loads(first_line)[0]
    assert extra_label in record["predict"]
    assert len(record["predict"]) == len(anchors)


@pytest.mark.slow  # two full CLI runs just to watch trace dirs appear;
# trace_context itself is covered fast in tests/test_profiling.py
def test_cli_profile_flags_write_traces(ws, tmp_path):
    """--profile on train AND pretrain wraps the run in a jax.profiler
    trace scope; each trace dir must materialize (evaluate shares the
    same wrapper; bench has BENCH_PROFILE)."""
    from memvul_tpu.data.synthetic import corpus_texts, generate_corpus

    config = tiny_memory_config(ws, num_epochs=1)
    cfg_path = tmp_path / "config.json"
    cfg_path.write_text(json.dumps(config))
    train_trace = tmp_path / "trace_train"
    rc = main([
        "train", str(cfg_path), "-s", str(tmp_path / "out"),
        "--profile", str(train_trace),
    ])
    assert rc == 0
    assert train_trace.exists() and any(train_trace.rglob("*"))

    reports, _ = generate_corpus(seed=4)
    train_txt = tmp_path / "mlm.txt"
    train_txt.write_text("\n".join(corpus_texts(reports)[:24]))
    mlm_cfg = tmp_path / "pretrain.json"
    mlm_cfg.write_text(json.dumps({
        "tokenizer": {"type": "wordpiece",
                      "tokenizer_path": ws["paths"]["tokenizer"]},
        "encoder": {"preset": "tiny"},
        "train_data_path": str(train_txt),
        "output_dir": str(tmp_path / "out_wwm"),
        "trainer": {"batch_size": 4, "grad_accum": 1, "max_length": 32,
                    "num_epochs": 1, "steps_per_epoch": 1,
                    "warmup_steps": 1},
    }))
    mlm_trace = tmp_path / "trace_mlm"
    rc = main(["pretrain", str(mlm_cfg), "--profile", str(mlm_trace)])
    assert rc == 0
    assert mlm_trace.exists() and any(mlm_trace.rglob("*"))


def test_eval_config_inflight_reaches_dispatch(ws, trained_ser_dir, tmp_path, monkeypatch):
    """``evaluation.inflight`` (async device dispatch depth) must reach
    score_instances — it is a first-class sweep knob on chip."""
    from memvul_tpu.build import evaluate_from_archive
    from memvul_tpu.evaluate import predict_memory as pm

    ser_dir = trained_ser_dir
    seen = {}
    real = pm.SiamesePredictor.score_instances

    def spy(self, instances, inflight=2, **kw):
        seen["inflight"] = inflight
        return real(self, instances, inflight=inflight, **kw)

    monkeypatch.setattr(pm.SiamesePredictor, "score_instances", spy)
    evaluate_from_archive(
        str(ser_dir), ws["paths"]["test"], str(tmp_path / "eval_if"),
        overrides={"evaluation": {"batch_size": 8, "max_length": 48,
                                  "inflight": 3}},
        name="memvul", use_mesh=False,
    )
    assert seen["inflight"] == 3


def test_cli_pretrain_with_eval_and_hf_export(ws, tmp_path, capsys):
    """cmd_pretrain end-to-end: tiny MLM run + held-out eval
    (validation_data_path → eval_loss/perplexity in the report) + HF
    export dir with model, config, and vocab.txt."""
    from memvul_tpu.data.synthetic import corpus_texts, generate_corpus

    reports, _ = generate_corpus(seed=4)
    texts = corpus_texts(reports)
    train_txt = tmp_path / "mlm.txt"
    train_txt.write_text("\n".join(texts[:48]))
    val_txt = tmp_path / "mlm_val.txt"
    val_txt.write_text("\n".join(texts[48:64]))
    config = {
        "tokenizer": {"type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"]},
        "encoder": {"preset": "tiny"},
        "train_data_path": str(train_txt),
        "validation_data_path": str(val_txt),
        "output_dir": str(tmp_path / "out_wwm"),
        "trainer": {
            "batch_size": 4, "grad_accum": 1, "max_length": 32,
            "num_epochs": 1, "steps_per_epoch": 2, "warmup_steps": 1,
        },
    }
    cfg_path = tmp_path / "pretrain.json"
    cfg_path.write_text(json.dumps(config))
    rc = main(["pretrain", str(cfg_path), "--export-hf"])
    report = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert rc == 0
    assert np.isfinite(report["final_loss"])
    assert report["eval_loss"] > 0 and report["masked_tokens"] > 0
    hf = Path(report["hf_checkpoint"])
    for name in ("pytorch_model.bin", "config.json", "vocab.txt"):
        assert (hf / name).exists(), name
    # a missing validation file fails fast (before training)
    bad = dict(config, validation_data_path=str(tmp_path / "nope.txt"))
    cfg_path.write_text(json.dumps(bad))
    assert main(["pretrain", str(cfg_path)]) == 2


def test_cli_analyze(ws, tmp_path):
    """The paper-analysis suite as one CLI command (the reference edits
    utils.py __main__ to run these)."""
    out_path = tmp_path / "analysis.json"
    rc = main([
        "analyze", ws["paths"]["train"],
        "--cve-dict", ws["paths"]["cve"], "-o", str(out_path),
    ])
    assert rc == 0
    report = json.loads(out_path.read_text())
    km = report["keyword_match"]
    assert report["num_samples"] == sum(km.values()) > 0
    assert report["attack_steps"]["total"] >= report["attack_steps"]["with_attack_steps"]
    # the histogram actually matched records (not just static labels)
    assert report["delta_days"]["total"] > 0
    assert sum(report["delta_days"]["counts"]) == report["delta_days"]["total"]
    # ECDF ends at fraction 1.0
    assert report["cwe_cumulative"][-1][1] == pytest.approx(1.0)


def test_cli_train_single_classifier(ws, tmp_path):
    config = {
        "random_seed": 2021,
        "tokenizer": {"type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"]},
        "dataset_reader": {"type": "reader_single", "sample_neg": 1.0},
        "train_data_path": ws["paths"]["train"],
        "validation_data_path": ws["paths"]["validation"],
        "model": {
            "type": "model_single",
            "encoder": {"preset": "tiny", "vocab_size": 4096},
            "header_dim": 32,
        },
        "trainer": {
            "num_epochs": 1, "batch_size": 4, "max_length": 48,
            "eval_batch_size": 8, "eval_max_length": 48,
            # exercise the length-binned validation wiring end-to-end
            "eval_buckets": [16, 48], "eval_tokens_per_batch": 256,
            "steps_per_epoch": 3,
        },
        "evaluation": {"batch_size": 8, "max_length": 48},
    }
    cfg_path = tmp_path / "config_single.json"
    cfg_path.write_text(json.dumps(config))
    ser_dir = tmp_path / "out_single"
    assert main(["train", str(cfg_path), "-s", str(ser_dir)]) == 0

    eval_dir = tmp_path / "eval_single"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(eval_dir), "--no-mesh",
    ])
    assert rc == 0
    metrics = json.loads((eval_dir / "model_single_metric_all.json").read_text())
    assert "f1" in metrics


def test_cli_train_textcnn(ws, tmp_path):
    from memvul_tpu.data.synthetic import corpus_texts, generate_corpus
    from memvul_tpu.data.tokenizer import WordTokenizer

    reports, _ = generate_corpus(seed=3)
    vocab_path = tmp_path / "word_vocab.json"
    WordTokenizer.train_from_corpus(
        corpus_texts(reports), max_vocab=500, save_path=vocab_path
    )
    config = {
        "random_seed": 2021,
        "tokenizer": {"type": "word", "vocab_path": str(vocab_path)},
        "dataset_reader": {"type": "reader_single", "sample_neg": 1.0},
        "train_data_path": ws["paths"]["train"],
        "validation_data_path": ws["paths"]["validation"],
        "model": {
            "type": "model_cnn", "embed_dim": 16, "num_filters": 8,
            "header_dim": 16,
        },
        "trainer": {
            "num_epochs": 1, "batch_size": 4, "max_length": 48,
            "eval_batch_size": 8, "eval_max_length": 48,
            "base_lr": 1e-3, "steps_per_epoch": 3,
        },
    }
    cfg_path = tmp_path / "config_cnn.json"
    cfg_path.write_text(json.dumps(config))
    assert main(["train", str(cfg_path), "-s", str(tmp_path / "out_cnn")]) == 0
    assert (tmp_path / "out_cnn" / "model.tar.gz").exists()


def test_cli_build_data(tmp_path):
    import csv

    from memvul_tpu.data.synthetic import generate_corpus, research_view_records

    reports, cve_dict = generate_corpus(seed=9)
    csv_path = tmp_path / "all_samples.csv"
    with open(csv_path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=list(reports[0].keys()))
        writer.writeheader()
        writer.writerows(reports)
    cve_path = tmp_path / "CVE_dict.json"
    cve_path.write_text(json.dumps(cve_dict))
    cwe_path = tmp_path / "1000.csv"
    records = research_view_records()
    with open(cwe_path, "w", newline="") as f:
        writer = csv.DictWriter(f, fieldnames=list(records[0].keys()))
        writer.writeheader()
        writer.writerows(records)

    out = tmp_path / "data"
    rc = main([
        "build-data", "--csv", str(csv_path), "--cve-dict", str(cve_path),
        "--cwe-csv", str(cwe_path), "--out", str(out),
    ])
    assert rc == 0
    for name in (
        "train_project.json", "validation_project.json", "test_project.json",
        "train_project_mlm.txt", "CWE_anchor_golden_project.json",
    ):
        assert (out / name).exists(), name


def test_online_resample_off_freezes_pairs(ws, tmp_path):
    """MemVul-o: with online_resample false the epoch stream is identical
    across epochs (the reference comments out reset_dataloader)."""
    from memvul_tpu.build import build_model, build_reader, build_tokenizer, init_params
    from memvul_tpu.training.trainer import MemoryTrainer, TrainerConfig

    config = tiny_memory_config(ws, online_resample=False)
    tokenizer = build_tokenizer(config["tokenizer"])
    reader = build_reader(config["dataset_reader"])
    model = build_model(config["model"], tokenizer.vocab_size)
    params = init_params(model)
    trainer = MemoryTrainer(
        model, params, tokenizer, reader,
        train_path=config["train_data_path"],
        config=TrainerConfig(**{**config["trainer"], "online_resample": False}),
    )
    # _microbatch_stacks yields (host_stack, token-count info) pairs
    first = [
        np.asarray(s["sample1"]["input_ids"])
        for s, _ in trainer._microbatch_stacks()
    ]
    second = [
        np.asarray(s["sample1"]["input_ids"])
        for s, _ in trainer._microbatch_stacks()
    ]
    assert len(first) == len(second)
    for a, b in zip(first, second):
        np.testing.assert_array_equal(a, b)


def test_cli_evaluate_with_int8_quant_override(ws, trained_ser_dir, tmp_path):
    """The shipped int8 eval config drives the quantized scoring path on
    an archived full-precision model: same checkpoint, metric files come
    out, quant flag actually reaches the rebuilt model."""
    ser_dir = trained_ser_dir
    shipped = loads_config(
        (CONFIGS_DIR / "test_config_memory_int8.json").read_text()
    )
    assert shipped["model"]["encoder"]["quant"] == "int8_dynamic"
    overrides = {
        "model": {"encoder": {"quant": "int8_dynamic"}},  # dtype: keep tiny default
        "evaluation": {"batch_size": 8, "max_length": 48},
    }
    eval_dir = tmp_path / "eval_int8"
    rc = main([
        "evaluate", str(ser_dir), ws["paths"]["test"],
        "-o", str(eval_dir), "--name", "memvul", "--no-mesh",
        "--overrides", json.dumps(overrides),
    ])
    assert rc == 0
    metrics = json.loads((eval_dir / "memvul_metric_all.json").read_text())
    for key in ("TP", "FN", "TN", "FP", "prec", "f1", "auc"):
        assert key in metrics

    arch = load_archive(ser_dir, overrides=overrides)
    model = build_model(dict(arch.config["model"]), arch.tokenizer.vocab_size)
    assert model.config.quant == "int8_dynamic"


def test_cli_help_names_every_registered_subcommand(capsys):
    """The top-level --help is the CLI's table of contents: every
    registered subcommand (including serve and telemetry-report) must
    appear there with a one-line description — a new command cannot
    ship invisible."""
    import argparse

    from memvul_tpu.__main__ import build_parser

    parser = build_parser()
    sub = next(
        a for a in parser._actions
        if isinstance(a, argparse._SubParsersAction)
    )
    names = set(sub.choices)
    # the full current command surface; growing it here is deliberate
    assert {
        "train", "evaluate", "serve", "pretrain", "baseline", "build-data",
        "analyze", "bench", "bank", "telemetry-report", "doctor", "parity",
        "selfcheck", "lint", "score-corpus", "tune",
    } <= names
    # every subcommand carries a non-empty one-line help
    helps = {ca.dest: ca.help for ca in sub._choices_actions}
    for name in names:
        assert helps.get(name), f"subcommand {name!r} has no help text"
    # and the rendered --help output names each of them
    with pytest.raises(SystemExit) as exc:
        main(["--help"])
    assert exc.value.code == 0
    out = capsys.readouterr().out
    for name in names:
        assert name in out, f"--help does not mention {name!r}"
    # the serve subcommand's flag surface is pinned too: the scale-out
    # tier's --replicas (docs/serving.md "Replica tier") must stay
    # registered alongside the PR 4 flags
    serve_flags = {
        flag
        for action in sub.choices["serve"]._actions
        for flag in action.option_strings
    }
    assert {
        "--replicas", "--out-dir", "--overrides", "--port", "--tsdb-cadence",
        "--tenants",
    } <= serve_flags
    # the lint subcommand's flag surface is pinned too: the engine's
    # select/json/baseline workflow (docs/static_analysis.md) must stay
    # registered
    lint_flags = {
        flag
        for action in sub.choices["lint"]._actions
        for flag in action.option_strings
    }
    assert {
        "--select", "--json", "--baseline", "--no-baseline",
        "--write-baseline", "--list-codes",
    } <= lint_flags
    # the tune subcommand's flag surface is pinned (docs/tuning.md):
    # the sweep controls, the report path, and the unknown-device
    # escape hatch are all part of the offline-autotuner contract
    tune_flags = {
        flag
        for action in sub.choices["tune"]._actions
        for flag in action.option_strings
    }
    assert {
        "--mode", "--out", "--cascade", "--target-rescore-rate",
        "--report", "--splice", "--device-class", "--allow-unknown-device",
        "--max-programs", "--hbm-fraction", "--full-space",
    } <= tune_flags
    # telemetry-report's machine-readable output flag (PR 10) is pinned
    # the same way: bench/CI consume it, so it cannot silently vanish
    report_flags = {
        flag
        for action in sub.choices["telemetry-report"]._actions
        for flag in action.option_strings
    }
    assert "--json" in report_flags
    # score-corpus's flag surface is pinned the same way: the sharding
    # contract (docs/full_corpus.md "Sharded corpus scoring") rides on
    # these knobs
    corpus_flags = {
        flag
        for action in sub.choices["score-corpus"]._actions
        for flag in action.option_strings
    }
    assert {
        "--shards", "--out-dir", "--overrides", "--golden-file",
        "--threshold", "--split",
    } <= corpus_flags


def test_cli_bank_help_names_every_lifecycle_subcommand(capsys):
    """The ``bank`` group's --help must name the full lifecycle surface
    (docs/anchor_bank.md): build → diff → log → shadow → promote."""
    import argparse

    from memvul_tpu.__main__ import build_parser

    parser = build_parser()
    sub = next(
        a for a in parser._actions
        if isinstance(a, argparse._SubParsersAction)
    )
    bank_sub = next(
        a for a in sub.choices["bank"]._actions
        if isinstance(a, argparse._SubParsersAction)
    )
    expected = {"build", "diff", "log", "shadow", "promote"}
    assert expected <= set(bank_sub.choices)
    helps = {ca.dest: ca.help for ca in bank_sub._choices_actions}
    for name in expected:
        assert helps.get(name), f"bank subcommand {name!r} has no help text"
    # every lifecycle step takes --tenant: one <store>/<tenant> root per
    # org, the layout serve --tenants points at (docs/multitenancy.md)
    for name in expected:
        flags = {
            flag
            for action in bank_sub.choices[name]._actions
            for flag in action.option_strings
        }
        assert "--tenant" in flags, f"bank {name} lost --tenant"
    with pytest.raises(SystemExit) as exc:
        main(["bank", "--help"])
    assert exc.value.code == 0
    out = capsys.readouterr().out
    for name in expected:
        assert name in out
