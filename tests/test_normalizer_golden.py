"""Golden-output oracle for the text normalizer.

``tests/golden/normalizer_golden.json`` was produced by executing the
REFERENCE normalizer (reference: MemVul/util.py:39-142,
``replace_tokens_simple``) over a 219-document adversarial battery via
``tools/gen_normalizer_golden.py``.  This test asserts byte-equality of
``normalize_text`` against those reference outputs — the root of the
F1-parity chain: identical tag streams in ⇒ identical tokens in.

There are currently ZERO intentional divergences; any future divergence
must be added to ``KNOWN_DIVERGENCES`` with a written justification.
"""

import json
from pathlib import Path

import pytest

from memvul_tpu.data.normalize import normalize_text

GOLDEN = Path(__file__).parent / "golden" / "normalizer_golden.json"

# input -> reason strings for any documented, intentional divergence.
KNOWN_DIVERGENCES: dict = {}


def _cases():
    return json.loads(GOLDEN.read_text())


def test_battery_is_large_enough():
    assert len(_cases()) >= 200


@pytest.mark.parametrize(
    "case", _cases(), ids=lambda c: repr(c["input"][:40])
)
def test_normalize_matches_reference_golden(case):
    if case["input"] in KNOWN_DIVERGENCES:
        pytest.skip(KNOWN_DIVERGENCES[case["input"]])
    assert normalize_text(case["input"]) == case["expected"]
