"""Two-process DCN smoke: the multi-host path with real process
boundaries.

The single-process tests (test_ema_multihost.py) cover the helpers'
logic; this one actually launches TWO processes that join one
jax.distributed runtime and reduce across the process boundary — the
contract the reference's NCCL backend provides (custom_trainer.py:
254-259, 379-396), here carried by the jax coordination service + XLA
collectives (Gloo on CPU, DCN on pods).
"""

import json
import socket
import subprocess
import sys
from pathlib import Path

import pytest

_WORKER = Path(__file__).parent / "dcn_worker.py"


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


@pytest.mark.slow
def test_two_process_reduction(tmp_path):
    port = _free_port()
    outs = [tmp_path / f"proc{i}.json" for i in range(2)]
    # worker output goes to files, not pipes: a worker blocked on a full
    # pipe buffer would stall the OTHER worker at the distributed barrier
    logs = [open(tmp_path / f"proc{i}.log", "wb") for i in range(2)]
    procs = [
        subprocess.Popen(
            [sys.executable, str(_WORKER), str(i), str(port), str(outs[i])],
            stdout=log,
            stderr=subprocess.STDOUT,
        )
        for i, log in enumerate(logs)
    ]
    try:
        for i, p in enumerate(procs):
            try:
                p.wait(timeout=180)
            except subprocess.TimeoutExpired:
                for q in procs:
                    q.kill()
                pytest.fail("DCN worker timed out")
            assert p.returncode == 0, (
                tmp_path / f"proc{i}.log"
            ).read_text()[-2000:]
    finally:
        for log in logs:
            log.close()

    results = [json.loads(o.read_text()) for o in outs]
    for i, r in enumerate(results):
        assert r["joined"] is True
        assert r["process_count"] == 2
        assert r["is_primary"] is (i == 0)
        assert r["local_devices"] == 2
        assert r["global_devices"] == 4
        # both processes agree on the cross-process reduction: sum(0..7)
        assert r["global_sum"] == 28.0
    # the two local_batch_slice results tile the global batch exactly
    assert results[0]["slice"] == [0, 4]
    assert results[1]["slice"] == [4, 8]
