import numpy as np
import pytest

import jax
import jax.numpy as jnp

from memvul_tpu.models import (
    BertConfig,
    BertEncoder,
    MemoryModel,
    SingleModel,
    anchor_probs,
    best_anchor_score,
    classification_loss,
    pair_loss,
)
from memvul_tpu.parallel import create_mesh, replicate, shard_batch

B, T, A = 4, 16, 6
CFG = BertConfig.tiny(vocab_size=512)


def token_batch(rng, batch=B, seq=T):
    ids = rng.integers(4, 500, size=(batch, seq)).astype(np.int32)
    mask = np.ones((batch, seq), dtype=np.int32)
    mask[:, seq - 3 :] = 0
    return {"input_ids": jnp.asarray(ids), "attention_mask": jnp.asarray(mask)}


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(0)


@pytest.fixture(scope="module")
def pair_setup(rng):
    model = MemoryModel(CFG)
    s1, s2 = token_batch(rng), token_batch(rng)
    params = model.init(jax.random.PRNGKey(0), s1, s2)
    return model, params, s1, s2


def test_encoder_output_shape(rng):
    enc = BertEncoder(CFG)
    batch = token_batch(rng)
    params = enc.init(jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"])
    out = enc.apply(params, batch["input_ids"], batch["attention_mask"])
    assert out.shape == (B, T, CFG.hidden_size)
    assert jnp.isfinite(out).all()


def test_mask_actually_masks(rng):
    enc = BertEncoder(CFG)
    batch = token_batch(rng)
    params = enc.init(jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"])
    out1 = enc.apply(params, batch["input_ids"], batch["attention_mask"])
    # perturb tokens under the mask: visible positions must not change
    ids2 = batch["input_ids"].at[:, T - 1].set(7)
    out2 = enc.apply(params, ids2, batch["attention_mask"])
    np.testing.assert_allclose(
        out1[:, : T - 3], out2[:, : T - 3], rtol=1e-5, atol=1e-5
    )


def test_memory_model_pair_path(pair_setup):
    model, params, s1, s2 = pair_setup
    logits = model.apply(params, s1, s2)
    assert logits.shape == (B, 2)


def test_memory_model_encode_path(pair_setup):
    model, params, s1, _ = pair_setup
    u = model.apply(params, s1)
    assert u.shape == (B, 512)  # header output


def test_anchor_match_equals_concat_formulation(pair_setup):
    model, params, s1, _ = pair_setup
    u = model.apply(params, s1)
    anchors = jax.random.normal(jax.random.PRNGKey(1), (A, u.shape[-1]))
    logits = model.apply(params, s1, anchors=anchors)
    assert logits.shape == (B, A, 2)
    # explicit concat formulation, one anchor at a time
    kernel = params["params"]["pair_kernel"]
    for a in range(A):
        feats = jnp.concatenate(
            [u, jnp.broadcast_to(anchors[a], u.shape), jnp.abs(u - anchors[a])],
            axis=-1,
        )
        np.testing.assert_allclose(
            np.asarray(feats @ kernel), np.asarray(logits[:, a]), rtol=2e-4, atol=2e-4
        )


def test_best_anchor_score_picks_max():
    logits = jnp.asarray(
        [[[5.0, 0.0], [1.0, 0.0]], [[0.0, 5.0], [3.0, 0.0]]]
    )  # [2, 2 anchors, 2]
    p = anchor_probs(logits)
    score, idx = best_anchor_score(logits)
    assert idx.tolist() == [0, 1]
    np.testing.assert_allclose(score, p.max(axis=-1))


def test_pair_loss_ignores_padding_rows():
    logits = jnp.asarray([[2.0, 0.0], [0.0, 2.0], [9.0, -9.0]])
    labels = jnp.asarray([0, 1, 1])  # last row is padding and totally wrong
    full = pair_loss(logits, labels, jnp.asarray([1.0, 1.0, 1.0]), 1.0)
    masked = pair_loss(logits, labels, jnp.asarray([1.0, 1.0, 0.0]), 1.0)
    assert masked < full


def test_temperature_scales_loss():
    logits = jnp.asarray([[1.0, 0.0]])
    labels = jnp.asarray([0])
    w = jnp.asarray([1.0])
    sharp = pair_loss(logits, labels, w, 0.1)
    soft = pair_loss(logits, labels, w, 1.0)
    assert sharp < soft  # temperature sharpens correct predictions


def test_single_model(rng):
    model = SingleModel(CFG)
    batch = token_batch(rng)
    params = model.init(jax.random.PRNGKey(0), batch)
    logits = model.apply(params, batch)
    assert logits.shape == (B, 2)
    loss = classification_loss(logits, jnp.zeros(B, dtype=jnp.int32), jnp.ones(B))
    assert jnp.isfinite(loss)


def test_dropout_rng_changes_training_output(pair_setup):
    model, params, s1, s2 = pair_setup
    out1 = model.apply(
        params, s1, s2, deterministic=False, rngs={"dropout": jax.random.PRNGKey(1)}
    )
    out2 = model.apply(
        params, s1, s2, deterministic=False, rngs={"dropout": jax.random.PRNGKey(2)}
    )
    assert not np.allclose(out1, out2)


def test_jit_compiles_and_matches_eager(pair_setup):
    model, params, s1, s2 = pair_setup
    eager = model.apply(params, s1, s2)
    jitted = jax.jit(lambda p, a, b: model.apply(p, a, b))(params, s1, s2)
    np.testing.assert_allclose(np.asarray(eager), np.asarray(jitted), rtol=1e-5, atol=1e-5)


def test_scan_and_remat_variants_run(rng):
    batch = token_batch(rng)
    for cfg in [CFG.replace(scan_layers=True), CFG.replace(remat=True),
                CFG.replace(scan_layers=True, remat=True)]:
        enc = BertEncoder(cfg)
        params = enc.init(
            jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"]
        )
        out = enc.apply(params, batch["input_ids"], batch["attention_mask"])
        assert out.shape == (B, T, cfg.hidden_size)
    # scan stacks layer params: [L, ...]
    scanned = BertEncoder(CFG.replace(scan_layers=True)).init(
        jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"]
    )
    stack = scanned["params"]["encoder"]["layers"]["layer"]
    leaf = jax.tree_util.tree_leaves(stack)[0]
    assert leaf.shape[0] == CFG.num_layers


def test_bf16_forward_finite(rng):
    cfg = CFG.replace(dtype=jnp.bfloat16)
    model = MemoryModel(cfg)
    s1, s2 = token_batch(rng), token_batch(rng)
    params = model.init(jax.random.PRNGKey(0), s1, s2)
    logits = model.apply(params, s1, s2)
    assert logits.dtype == jnp.bfloat16
    assert jnp.isfinite(logits.astype(jnp.float32)).all()


# -- sharded execution over the virtual 8-device mesh ------------------------


def test_sharded_anchor_scoring(pair_setup):
    model, params, _, _ = pair_setup
    mesh = create_mesh()
    assert mesh.devices.size == 8
    rng = np.random.default_rng(3)
    batch = token_batch(rng, batch=16)
    batch = shard_batch(batch, mesh)
    params_r = replicate(params, mesh)
    anchors = replicate(
        jnp.asarray(np.random.default_rng(4).normal(size=(A, 512)), dtype=jnp.float32),
        mesh,
    )

    @jax.jit
    def score(p, b, anc):
        logits = model.apply(p, b, anchors=anc)
        return best_anchor_score(logits)[0]

    scores = score(params_r, batch, anchors)
    assert scores.shape == (16,)
    # compare against unsharded run
    ref = score(params, jax.device_get(batch), jax.device_get(anchors))
    np.testing.assert_allclose(np.asarray(scores), np.asarray(ref), rtol=1e-5, atol=1e-5)


def test_model_loss_method_uses_temperature(pair_setup):
    model, params, s1, s2 = pair_setup
    logits = model.apply(params, s1, s2)
    labels = jnp.zeros(B, dtype=jnp.int32)
    w = jnp.ones(B)
    via_model = model.apply(params, logits, labels, w, method=model.loss)
    direct = pair_loss(logits, labels, w, model.temperature)
    np.testing.assert_allclose(np.asarray(via_model), np.asarray(direct))


def test_shard_batch_handles_modelonly_mesh_and_scalars(pair_setup):
    mesh = create_mesh({"model": 8})
    out = shard_batch({"x": np.ones((16, 4)), "s": np.float32(3.0), "meta": ["a"]}, mesh)
    assert out["x"].shape == (16, 4)
    assert out["meta"] == ["a"]


def test_flash_impl_falls_back_on_cpu(rng):
    from memvul_tpu.ops import dot_product_attention

    q = jnp.asarray(rng.normal(size=(2, 8, 4, 16)), jnp.float32)
    ref = dot_product_attention(q, q, q, impl="xla")
    out = dot_product_attention(q, q, q, impl="flash")
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), rtol=1e-6)


def test_ring_impl_requires_bound_axis():
    """impl='ring' dispatches to sequence-parallel attention, which only
    works inside shard_map with the "seq" axis bound — outside, jax
    reports the unbound axis (full coverage in test_ring_attention.py)."""
    from memvul_tpu.ops import dot_product_attention

    q = jnp.zeros((1, 4, 2, 8))
    with pytest.raises(NameError, match="seq"):
        dot_product_attention(q, q, q, impl="ring")


def test_ring_impl_rejects_dropout():
    from memvul_tpu.ops import dot_product_attention

    q = jnp.zeros((1, 4, 2, 8))
    with pytest.raises(ValueError, match="dropout"):
        dot_product_attention(
            q, q, q, impl="ring", deterministic=False, dropout_rate=0.1,
            dropout_rng=jax.random.PRNGKey(0),
        )


def test_pooler_dropout_active_in_training(rng):
    model = MemoryModel(CFG, use_header=False)
    s1 = token_batch(rng)
    params = model.init(jax.random.PRNGKey(0), s1)
    det = model.apply(params, s1)
    stoch = model.apply(
        params, s1, deterministic=False, rngs={"dropout": jax.random.PRNGKey(9)}
    )
    assert not np.allclose(det, stoch)  # pooled path is regularized


def test_overlong_sequence_raises(rng):
    enc = BertEncoder(CFG)  # tiny: max_position_embeddings=128
    ids = jnp.zeros((2, 200), jnp.int32)
    mask = jnp.ones((2, 200), jnp.int32)
    with pytest.raises(ValueError, match="max_position_embeddings"):
        enc.init(jax.random.PRNGKey(0), ids, mask)


# -- ScalarMix (reference custom_PTM_embedder.py:107-118) --------------------


def _mix_batch(rng, cfg):
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (2, 12)), jnp.int32)
    return {"input_ids": ids, "attention_mask": jnp.ones_like(ids)}


def test_scalar_mix_output_shape_and_params(rng):
    cfg = BertConfig.tiny(vocab_size=512, last_layer_only=False)
    enc = BertEncoder(cfg)
    batch = _mix_batch(rng, cfg)
    params = enc.init(jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"])
    out = enc.apply(params, batch["input_ids"], batch["attention_mask"])
    assert out.shape == (2, 12, cfg.hidden_size)
    mix = params["params"]["scalar_mix"]
    assert mix["scalar_weights"].shape == (cfg.num_layers,)
    assert mix["gamma"].shape == ()


def test_scalar_mix_equal_weights_is_layer_mean(rng):
    """Zero-init weights softmax to uniform and gamma is 1, so the mixed
    output at init equals the mean of the per-layer outputs."""
    cfg = BertConfig.tiny(vocab_size=512, last_layer_only=False)
    enc = BertEncoder(cfg)
    batch = _mix_batch(rng, cfg)
    params = enc.init(jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"])
    mixed = enc.apply(params, batch["input_ids"], batch["attention_mask"])

    from memvul_tpu.models.bert import BertEmbeddings, BertEncoderStack
    from memvul_tpu.ops.attention import mask_to_bias

    # recompute the stacked per-layer outputs with the same params by
    # driving the sub-modules standalone on their param subtrees
    emb = BertEmbeddings(cfg).apply(
        {"params": params["params"]["embeddings"]},
        batch["input_ids"], jnp.zeros_like(batch["input_ids"]), True,
    )
    stack_out = BertEncoderStack(cfg).apply(
        {"params": params["params"]["encoder"]},
        emb, mask_to_bias(batch["attention_mask"], dtype=cfg.dtype), True,
    )
    np.testing.assert_allclose(
        np.asarray(mixed), np.asarray(stack_out.mean(axis=0)), rtol=1e-5, atol=1e-5
    )


def test_scalar_mix_scan_and_loop_agree(rng):
    """The scan path's stacked ys and the python-loop path's stacked list
    feed ScalarMix identically."""
    cfg_loop = BertConfig.tiny(vocab_size=512, last_layer_only=False)
    cfg_scan = BertConfig.tiny(
        vocab_size=512, last_layer_only=False, scan_layers=True
    )
    batch = _mix_batch(rng, cfg_loop)
    enc_loop, enc_scan = BertEncoder(cfg_loop), BertEncoder(cfg_scan)
    p_loop = enc_loop.init(jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"])

    layers = [
        p_loop["params"]["encoder"][f"layer_{i}"]
        for i in range(cfg_loop.num_layers)
    ]
    stacked = jax.tree_util.tree_map(lambda *xs: jnp.stack(xs, 0), *layers)
    p_scan = {
        "params": {
            **p_loop["params"],
            "encoder": {"layers": {"layer": stacked}},
        }
    }
    out_loop = enc_loop.apply(p_loop, batch["input_ids"], batch["attention_mask"])
    out_scan = enc_scan.apply(p_scan, batch["input_ids"], batch["attention_mask"])
    np.testing.assert_allclose(
        np.asarray(out_loop), np.asarray(out_scan), rtol=1e-5, atol=1e-5
    )


def test_scalar_mix_weights_and_gamma_steer_output(rng):
    """The learned parameters actually influence the mix: pushing the
    softmax toward layer 0 vs layer 1 changes the output, and gamma
    scales it (and receives gradient)."""
    cfg = BertConfig.tiny(vocab_size=512, last_layer_only=False)
    enc = BertEncoder(cfg)
    batch = _mix_batch(rng, cfg)
    params = enc.init(jax.random.PRNGKey(0), batch["input_ids"], batch["attention_mask"])

    def with_mix(w, gamma=1.0):
        p = jax.tree_util.tree_map(lambda x: x, params)  # shallow copy
        p["params"]["scalar_mix"] = {
            "scalar_weights": jnp.asarray(w, jnp.float32),
            "gamma": jnp.asarray(gamma, jnp.float32),
        }
        return enc.apply(p, batch["input_ids"], batch["attention_mask"])

    lo = with_mix([8.0, -8.0])   # ~ layer 0
    hi = with_mix([-8.0, 8.0])   # ~ layer 1
    assert float(np.abs(np.asarray(lo - hi)).max()) > 1e-3
    np.testing.assert_allclose(
        np.asarray(with_mix([0.0, 0.0], gamma=2.0)),
        2.0 * np.asarray(with_mix([0.0, 0.0], gamma=1.0)),
        rtol=1e-5, atol=1e-5,
    )

    def loss(p):
        out = enc.apply(p, batch["input_ids"], batch["attention_mask"])
        return (out.astype(jnp.float32) ** 2).mean()

    g = jax.grad(loss)(params)["params"]["scalar_mix"]
    assert float(np.abs(np.asarray(g["gamma"])).max()) > 0
