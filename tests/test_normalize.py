from memvul_tpu.data.normalize import normalize_text, replace_tokens_simple


def test_non_string_input_returns_empty():
    assert normalize_text(None) == ""
    assert normalize_text(123) == ""


def test_alias_is_same_function():
    assert replace_tokens_simple is normalize_text


def test_whitespace_collapsed():
    assert normalize_text("a   b\t c") == "a b c"


def test_fenced_code_with_error_becomes_errortag():
    out = normalize_text("see ```Traceback error: boom``` here")
    assert "ERRORTAG" in out
    assert "Traceback" not in out


def test_fenced_prose_is_kept():
    out = normalize_text("x ```simple words here``` y")
    assert "simple words here" in out


def test_fenced_single_token_becomes_apitag():
    out = normalize_text("call ```do_stuff``` now")
    assert "APITAG" in out


def test_fenced_long_code_becomes_codetag():
    code = "import os\nfor x in y:\n    foo(x, bar=1) qq\n" * 6
    out = normalize_text(f"repro: ```{code}```")
    assert "CODETAG" in out


def test_empty_fence_removed():
    out = normalize_text("a `````` b")
    assert out == "a b"


def test_inline_code_apitag():
    out = normalize_text("use `do_stuff` ok")
    assert "APITAG" in out


def test_markdown_file_link_becomes_filetag():
    out = normalize_text("see [report.pdf](http://x.org/report.pdf) ok")
    assert "FILETAG" in out


def test_markdown_plain_link_unwrapped():
    out = normalize_text("see [here](http://github.com/a/issues/5) ok")
    assert "here" in out
    # target URL then hits the URL pass (no file-ish tail)
    assert "URLTAG" in out or "PATHTAG" in out


def test_mitre_links_are_leak_guarded():
    out = normalize_text("ref https://cwe.mitre.org/data/definitions/79")
    assert "CVETAG" in out
    assert "mitre" not in out


def test_plain_url_tagged():
    out = normalize_text("go to http://github.com/octo today")
    assert "URLTAG" in out


def test_cve_and_cwe_ids_are_leak_guarded():
    out = normalize_text("this fixes CVE-2021-44228 and CWE-79 . ok")
    assert out.count("CVETAG") == 2


def test_email_tagged():
    out = normalize_text("mail me at bob@gmail.com please")
    assert "EMAILTAG" in out


def test_mention_tagged():
    out = normalize_text("thanks @octocat for the report")
    assert "MENTIONTAG" in out


def test_exception_name_tagged():
    out = normalize_text("throws NullPointerException in prod")
    assert "ERRORTAG" in out


def test_path_tagged():
    out = normalize_text("edit /usr/local/bin/thing to fix")
    assert "PATHTAG" in out


def test_filename_tagged():
    out = normalize_text("open the config.yml file")
    assert "FILETAG" in out


def test_camelcase_identifier_tagged():
    out = normalize_text("the parseHeader thing broke")
    assert "APITAG" in out


def test_call_site_tagged():
    out = normalize_text("invoke setup() first")
    assert "APITAG" in out


def test_version_number_tagged():
    out = normalize_text("upgrade from 1.2.3 please")
    assert "NUMBERTAG" in out


def test_very_long_token_tagged():
    out = normalize_text("blob " + "q" * 40 + " end")
    assert "APITAG" in out


def test_hyphens_split():
    assert normalize_text("well-known fact") == "well known fact"


def test_plain_prose_untouched():
    text = "the server crashes when a user logs in"
    assert normalize_text(text) == text


def test_heading_and_emphasis_markers_removed():
    out = normalize_text("## Title with **bold** text")
    assert "#" not in out and "*" not in out


def test_html_comment_removed():
    out = normalize_text("a <!--- hidden ---> b")
    assert "hidden" not in out


def test_leak_guard_property_random_contexts():
    """The normalizer's security property: no CVE/CWE identifier or
    mitre/bugzilla reference survives normalization, wherever it appears
    (reference leak guard: MemVul/util.py:85-90,102-104).  Randomized
    contexts — headings, code fences, links, sentences, paths — seeded
    for determinism."""
    import random
    import re

    rng = random.Random(2021)
    contexts = [
        "see {} for details",
        "# {} fixed\nbody text",
        "`{}`",
        "```\n{}\n```",
        "[link]({})",
        "reported in {} and elsewhere",
        "a/b/{}/c.txt",
        "{}: heap overflow",
        "prefix{}suffix",
        "*{}*",
        "> quoted {} here",
    ]
    idents = [
        lambda: f"CVE-{rng.randint(1999, 2030)}-{rng.randint(1, 99999)}",
        lambda: f"CWE-{rng.randint(1, 1400)}",
        lambda: (
            "https://cve.mitre.org/cgi-bin/cvename.cgi?name="
            f"CVE-{rng.randint(1999, 2030)}-{rng.randint(1, 99999)}"
        ),
        lambda: f"https://bugzilla.redhat.com/show_bug.cgi?id={rng.randint(1, 9_999_999)}",
    ]
    leak = re.compile(r"CVE-[0-9]|CWE-[0-9]|mitre\.org|bugzilla")
    for _ in range(300):
        text = rng.choice(contexts).format(rng.choice(idents)())
        out = normalize_text(text)
        assert not leak.search(out), (text, out)
