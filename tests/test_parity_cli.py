"""The one-command parity runner (evaluate/parity.py + ``parity`` CLI).

True numeric parity vs torch is pinned by tests/test_convert_parity.py
and tests/test_reference_archive_parity.py; these tests pin the
PACKAGING — that a single command drives convert-check → archive
scoring → metric diff end-to-end on a synthetic HF dir + reference
archive, so the real-weights run on a networked machine is pure
execution (round-4 verdict #4)."""

import json
from pathlib import Path

import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import test_reference_archive_parity as refarc

from memvul_tpu.__main__ import main as cli_main
from memvul_tpu.data.synthetic import build_workspace, corpus_texts, generate_corpus
from memvul_tpu.data.tokenizer import WordPieceTokenizer
from memvul_tpu.evaluate.parity import convert_logit_parity, hf_geometry


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("paritycli"), seed=33)


@pytest.fixture(scope="module")
def torch_model_and_hf_dir(tmp_path_factory):
    """A reference-shaped torch model plus an HF checkpoint dir saved from
    its OWN transformer, so stage (a) compares those exact weights and
    stage (b) reads matching geometry + vocabulary."""
    reports, _ = generate_corpus(seed=33)
    tok = WordPieceTokenizer.train_from_corpus(
        corpus_texts(reports), vocab_size=1024
    )
    torch.manual_seed(7)
    model = refarc.TorchMemoryModel(vocab_size=tok.vocab_size)
    model.eval()

    hf_dir = tmp_path_factory.mktemp("hf") / "tiny-bert"
    bert = model._text_field_embedder.token_embedder_tokens.transformer_model
    bert.save_pretrained(str(hf_dir))
    vocab = sorted(tok._tok.get_vocab().items(), key=lambda kv: kv[1])
    (hf_dir / "vocab.txt").write_text("\n".join(w for w, _ in vocab) + "\n")
    return model, hf_dir


def test_hf_geometry_reads_checkpoint_dims(torch_model_and_hf_dir):
    _, hf_dir = torch_model_and_hf_dir
    cfg = hf_geometry(hf_dir)
    assert cfg.hidden_size == refarc.HIDDEN
    assert cfg.num_layers == refarc.LAYERS
    assert cfg.num_heads == refarc.HEADS
    assert cfg.intermediate_size == refarc.INTER


def test_convert_logit_parity_stage(torch_model_and_hf_dir):
    _, hf_dir = torch_model_and_hf_dir
    report = convert_logit_parity(hf_dir, batch=2, seq_len=24, atol=1e-3)
    assert report["ok"], report
    assert report["max_abs_err"] < 1e-3
    assert report["geometry"]["num_layers"] == refarc.LAYERS


def test_parity_cli_full_chain(torch_model_and_hf_dir, ws, tmp_path, capsys):
    model, hf_dir = torch_model_and_hf_dir
    archive = refarc._save_reference_archive(model, tmp_path / "model.tar.gz")
    out = tmp_path / "parity_out"

    base_args = [
        "parity", "--hf-dir", str(hf_dir),
        "--archive", str(archive),
        "--corpus", ws["paths"]["test"],
        "--anchors", ws["paths"]["anchors"],
        "-o", str(out),
        "--max-length", "64", "--batch-size", "16",
        "--seq-len", "24", "--atol", "1e-3",
    ]
    rc = cli_main(base_args)
    report = json.loads(capsys.readouterr().out)
    assert rc == 0, report
    assert report["convert_parity"]["ok"]
    assert "f1" in report["archive_scoring"]["metrics"]
    assert Path(report["archive_scoring"]["result_file"]).exists()
    metric_file = Path(report["archive_scoring"]["metric_file"])
    assert metric_file.exists()
    assert report["metric_diff"]["skipped"] is True

    # a matching reference metric file diffs clean …
    rc = cli_main(base_args + ["--ref-metrics", str(metric_file)])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0, report
    assert report["metric_diff"]["ok"]
    assert report["metric_diff"]["deltas"]["f1"]["delta"] == 0.0

    # … and one outside the ±0.5-F1 band fails the run
    drifted = json.loads(metric_file.read_text())
    drifted["f1"] = float(drifted["f1"]) + 0.1
    bad = tmp_path / "ref_metric_drifted.json"
    bad.write_text(json.dumps(drifted))
    rc = cli_main(base_args + ["--ref-metrics", str(bad)])
    report = json.loads(capsys.readouterr().out)
    assert rc == 1
    assert not report["metric_diff"]["ok"]


def test_parity_without_archive_reports_skip(torch_model_and_hf_dir, capsys):
    _, hf_dir = torch_model_and_hf_dir
    rc = cli_main([
        "parity", "--hf-dir", str(hf_dir),
        "--seq-len", "24", "--atol", "1e-3",
    ])
    report = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert report["archive_scoring"]["skipped"] is True
    assert report["metric_diff"]["skipped"] is True


def test_parity_partial_scoring_inputs_error(torch_model_and_hf_dir, capsys):
    """Forgetting one of --archive/--corpus/--anchors (or passing
    --ref-metrics without them) must be a hard error naming the missing
    flags, never a silent skip that reads as a pass."""
    _, hf_dir = torch_model_and_hf_dir
    rc = cli_main([
        "parity", "--hf-dir", str(hf_dir),
        "--archive", "whatever.tar.gz",
        "--corpus", "test.json",
        "--seq-len", "24",
    ])
    assert rc == 2
    assert "--anchors" in capsys.readouterr().err

    rc = cli_main([
        "parity", "--hf-dir", str(hf_dir),
        "--ref-metrics", "ref_metric.json",
        "--seq-len", "24",
    ])
    assert rc == 2
    assert "--ref-metrics" in capsys.readouterr().err
