"""Online scoring service (memvul_tpu/serving/, docs/serving.md).

The acceptance contract this file pins:

* **correctness** — ≥200 concurrent mixed-length requests return
  probabilities bitwise-equal to direct ``SiamesePredictor`` scoring of
  the same texts, with zero mid-serve recompiles (``score_trace_count``
  flat after warmup);
* **shutdown** — SIGTERM mid-load finishes the in-flight micro-batch,
  sheds everything queued with the ``"drain"`` status, and leaves a
  parseable ``telemetry.json`` whose served+shed counters sum to the
  submitted count;
* **admission control** — a full queue sheds the *oldest* requests with
  ``"shed"``, expired requests resolve ``"deadline"``, and the
  telemetry sub-counters match the per-status response counts exactly
  (driven by a slow fake predictor — no real model, no timing races);
* **chaos** — a transient ``serve.batch`` fault retries through
  ``RetryPolicy`` and still returns correct scores; a persistent one
  dead-letters with a reason instead of hanging clients;
* **hot swap** — swapping to a sentinel bank mid-stream never yields a
  torn mix of old and new labels within one response, and the versioned
  manifest commits atomically.
"""

import json
import os
import signal
import threading
import time
import urllib.error
import urllib.request
from pathlib import Path

import numpy as np
import pytest

import jax

from memvul_tpu import telemetry
from memvul_tpu.data.readers import MemoryReader
from memvul_tpu.data.synthetic import build_workspace
from memvul_tpu.evaluate.predict_memory import SiamesePredictor
from memvul_tpu.models import BertConfig, MemoryModel
from memvul_tpu.resilience import faults
from memvul_tpu.resilience.retry import RetryPolicy
from memvul_tpu.serving import (
    MANIFEST_NAME,
    STATUS_DEADLINE,
    STATUS_DRAIN,
    STATUS_OK,
    STATUS_SHED,
    HTTPClient,
    InprocessClient,
    ScoringService,
    ServiceConfig,
)
from memvul_tpu.serving.frontend import run_http_server


@pytest.fixture(scope="module")
def ws(tmp_path_factory):
    return build_workspace(tmp_path_factory.mktemp("serving"), seed=7)


@pytest.fixture(scope="module")
def setup(ws):
    """One warmed tiny predictor shared by the real-model tests (its
    jit caches persist across tests — exactly the warmed-program reuse
    the service relies on)."""
    cfg = BertConfig.tiny(vocab_size=ws["tokenizer"].vocab_size)
    model = MemoryModel(cfg)
    dummy = {
        "input_ids": np.zeros((2, 8), np.int32),
        "attention_mask": np.ones((2, 8), np.int32),
    }
    params = model.init(jax.random.PRNGKey(0), dummy, dummy)
    reader = MemoryReader(
        cve_path=ws["paths"]["cve"], anchor_path=ws["paths"]["anchors"]
    )
    predictor = SiamesePredictor(
        model, params, ws["tokenizer"],
        batch_size=8, max_length=48, buckets=[16, 48],
    )
    predictor.encode_anchors(reader.read_anchors(ws["paths"]["anchors"]))
    texts = [
        inst["text1"]
        for inst in reader.read(ws["paths"]["test"], split="test")
    ]
    return predictor, reader, texts


@pytest.fixture()
def tel(tmp_path):
    registry = telemetry.configure(run_dir=tmp_path / "run")
    yield registry
    telemetry.reset()
    faults.reset()


def make_service(predictor, tel_dir=None, **overrides):
    defaults = dict(
        max_batch=8, max_wait_ms=3.0, max_queue=1000,
        default_deadline_ms=30000.0,
    )
    defaults.update(overrides)
    return ScoringService(
        predictor, config=ServiceConfig(**defaults), manifest_dir=tel_dir
    )


# -- end-to-end correctness ----------------------------------------------------

def test_concurrent_mixed_length_requests_bitwise_match_direct(setup, tel):
    """≥200 concurrent requests, all bitwise-equal to offline scoring,
    zero mid-serve recompiles."""
    predictor, _, texts = setup
    n = 200
    picks = [texts[i % len(texts)] for i in range(n)]
    # direct scoring of the same texts through the SAME bucket policy
    instances = [
        {"text1": t, "label": "same", "meta": {"i": i}}
        for i, t in enumerate(picks)
    ]
    expected = {}
    for probs, metas in predictor.score_instances(iter(instances)):
        for row, meta in zip(probs, metas):
            expected[meta["i"]] = row.copy()
    traces_before = predictor.score_trace_count

    service = make_service(predictor)
    client = InprocessClient(service)
    results = {}
    lock = threading.Lock()

    def worker(indices):
        for i in indices:
            response = client.score(picks[i])
            with lock:
                results[i] = response

    threads = [
        threading.Thread(target=worker, args=(range(k, n, 16),))
        for k in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    service.drain()

    assert len(results) == n
    for i in range(n):
        assert results[i]["status"] == STATUS_OK
        got = np.array(
            [results[i]["predict"][label] for label in predictor.anchor_labels],
            dtype=np.float32,
        )
        want = np.asarray(expected[i], dtype=np.float32)
        np.testing.assert_array_equal(got, want)  # bitwise, not approx
        assert results[i]["bank_version"] == 1
    # the whole load ran on the AOT-warmed programs
    assert predictor.score_trace_count == traces_before
    counters = tel.snapshot()["counters"]
    assert counters["serve.served"] == n
    assert counters["serve.requests"] == n


def test_sigterm_mid_load_drains_and_telemetry_sums(setup, tel, tmp_path):
    """SIGTERM finishes in-flight work, sheds the queue with "drain",
    and telemetry.json parses with served+shed == submitted."""
    predictor, _, texts = setup
    service = make_service(predictor, max_batch=4)
    previous = service.install_signal_handlers()
    n = 200
    try:
        futures = [service.submit(texts[i % len(texts)]) for i in range(n)]
        os.kill(os.getpid(), signal.SIGTERM)  # the preemption notice
        service.drain()
    finally:
        service.restore_signal_handlers(previous)
    statuses = {}
    for future in futures:
        status = future.result(timeout=10)["status"]
        statuses[status] = statuses.get(status, 0) + 1
    assert set(statuses) <= {STATUS_OK, STATUS_DRAIN}
    assert statuses.get(STATUS_DRAIN, 0) > 0  # the kill landed mid-load
    run_dir = tel.run_dir
    tel.close()
    rollup = json.loads((run_dir / "telemetry.json").read_text())
    counters = rollup["counters"]
    assert counters["serve.served"] + counters["serve.shed"] == n
    assert counters["serve.served"] == statuses.get(STATUS_OK, 0)
    assert counters["serve.shed_drain"] == statuses.get(STATUS_DRAIN, 0)


# -- admission control (slow fake predictor — no model, no races) --------------

class _FakeEncoder:
    pad_id = 0

    def __init__(self, max_length=8):
        self.max_length = max_length

    def encode_many(self, texts):
        return [[1] * min(len(t), self.max_length) for t in texts]


class _SlowFakePredictor:
    """Minimal predictor surface; scoring blocks until released, so the
    tests control exactly when the batcher is busy."""

    def __init__(self, n_anchors=3, rows=4, length=8):
        self.encoder = _FakeEncoder(length)
        self.mesh = None
        self.params = None
        self.n_anchors = n_anchors
        self.anchor_labels = [f"A{i}" for i in range(n_anchors)]
        self.anchor_bank = np.zeros((n_anchors, 2), np.float32)
        self.score_trace_count = 0
        self._shapes = [(rows, length)]
        self.started = threading.Event()  # set when a batch enters scoring
        self.hold = threading.Event()     # scoring blocks until set

    def stream_shapes(self):
        return list(self._shapes)

    def _score_fn(self, params, sample, bank):
        self.started.set()
        assert self.hold.wait(timeout=10), "test forgot to release hold"
        rows = sample["input_ids"].shape[0]
        return np.tile(
            np.linspace(0.1, 0.9, self.n_anchors, dtype=np.float32), (rows, 1)
        )


def test_queue_overflow_sheds_oldest_and_deadline_expires(tel):
    """Queue fills → oldest shed with "shed"; waiting past the deadline
    → "deadline"; sub-counters match the response counts exactly."""
    fake = _SlowFakePredictor()
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=4, max_wait_ms=1.0, max_queue=4,
            default_deadline_ms=50.0,
        ),
    )
    # occupy the batcher: first request is pulled and blocks in scoring
    first = service.submit("r0", deadline_ms=0)  # no deadline
    assert fake.started.wait(timeout=5)
    # burst 8 while busy: queue cap 4 → the 4 oldest of the burst shed
    burst = [service.submit(f"r{i+1}", deadline_ms=50.0) for i in range(8)]
    shed = [f for f in burst[:4]]
    queued = [f for f in burst[4:]]
    for future in shed:
        assert future.result(timeout=5)["status"] == STATUS_SHED
    # let the queued ones expire, then release the batcher
    time.sleep(0.1)
    fake.hold.set()
    assert first.result(timeout=10)["status"] == STATUS_OK
    for future in queued:
        assert future.result(timeout=10)["status"] == STATUS_DEADLINE
    service.drain()
    counters = tel.snapshot()["counters"]
    assert counters["serve.shed_overflow"] == 4   # exactly the shed set
    assert counters["serve.shed_deadline"] == 4   # exactly the expired set
    assert counters["serve.shed"] == 8
    assert counters["serve.served"] == 1
    assert counters["serve.requests"] == 9


def test_submit_after_drain_resolves_drain_status(tel):
    fake = _SlowFakePredictor()
    fake.hold.set()
    service = ScoringService(fake, config=ServiceConfig(max_wait_ms=1.0))
    service.drain()
    response = service.submit("late").result(timeout=5)
    assert response["status"] == STATUS_DRAIN
    assert tel.snapshot()["counters"]["serve.shed_drain"] == 1


# -- chaos ---------------------------------------------------------------------

@pytest.mark.chaos
def test_transient_serve_batch_fault_retries_to_correct_scores(setup, tel):
    predictor, _, texts = setup
    # direct expectation before arming the fault
    instances = [{"text1": texts[0], "label": "same", "meta": {"i": 0}}]
    (expected, _), = predictor.score_instances(iter(instances))
    faults.configure("serve.batch=raise:RuntimeError:UNAVAILABLE injected")
    service = ScoringService(
        predictor,
        config=ServiceConfig(max_batch=8, max_wait_ms=3.0),
        retry_policy=RetryPolicy(attempts=3, sleep=lambda s: None),
    )
    response = InprocessClient(service).score(texts[0])
    service.drain()
    assert response["status"] == STATUS_OK
    got = np.array(
        [response["predict"][label] for label in predictor.anchor_labels],
        dtype=np.float32,
    )
    np.testing.assert_array_equal(got, np.asarray(expected[0], np.float32))
    assert tel.snapshot()["counters"]["resilience.retries"] >= 1


@pytest.mark.chaos
def test_persistent_serve_batch_fault_dead_letters_with_reason(setup, tel):
    predictor, _, texts = setup
    # three one-shot clauses = every attempt of a 3-try policy fails
    faults.configure(
        "serve.batch=raise:RuntimeError:UNAVAILABLE a;"
        "serve.batch=raise:RuntimeError:UNAVAILABLE b;"
        "serve.batch=raise:RuntimeError:UNAVAILABLE c"
    )
    service = ScoringService(
        predictor,
        config=ServiceConfig(max_batch=8, max_wait_ms=3.0),
        retry_policy=RetryPolicy(attempts=3, sleep=lambda s: None),
    )
    client = InprocessClient(service)
    response = client.score(texts[0], timeout_s=30)  # must not hang
    assert response["status"] == "error"
    assert "UNAVAILABLE" in response["reason"]
    counters = tel.snapshot()["counters"]
    assert counters["serve.dead_letters"] == 1
    assert counters["serve.errors"] == 1
    # the fault set is spent — the service recovers without a restart
    faults.reset()
    assert client.score(texts[0])["status"] == STATUS_OK
    service.drain()


@pytest.mark.chaos
def test_non_transient_fault_dead_letters_without_burning_retries(setup, tel):
    predictor, _, texts = setup
    faults.configure("serve.batch=raise:ValueError:genuine bug")
    service = ScoringService(
        predictor,
        config=ServiceConfig(max_batch=8, max_wait_ms=3.0),
        retry_policy=RetryPolicy(attempts=3, sleep=lambda s: None),
    )
    response = InprocessClient(service).score(texts[0])
    service.drain()
    assert response["status"] == "error"
    assert "genuine bug" in response["reason"]
    assert tel.snapshot()["counters"].get("resilience.retries", 0) == 0


# -- hot anchor-bank swap ------------------------------------------------------

def sentinel_instances(n):
    return [
        {
            "text1": f"sentinel weakness number {i} with deliberately new text",
            "meta": {"label": f"SENTINEL#{i}", "type": "golden"},
        }
        for i in range(n)
    ]


def test_hot_bank_swap_under_load_never_tears(setup, tel, tmp_path):
    """Mid-stream swap to a sentinel bank: every response is all-old or
    all-new labels, the manifest commits the new version, and a
    same-shape swap costs zero recompiles."""
    predictor, _, texts = setup
    run_dir = tmp_path / "swaprun"
    service = make_service(predictor, tel_dir=run_dir)
    client = InprocessClient(service)
    manifest = json.loads((run_dir / MANIFEST_NAME).read_text())
    assert manifest["version"] == 1
    assert manifest["labels"] == list(predictor.anchor_labels)
    manifest_v1_labels = manifest["labels"]

    old_labels = set(predictor.anchor_labels)
    new_labels = {f"SENTINEL#{i}" for i in range(len(old_labels))}
    counts = {"old": 0, "new": 0, "torn": 0}
    lock = threading.Lock()
    stop = threading.Event()

    def load():
        i = 0
        while not stop.is_set():
            response = client.score(texts[i % len(texts)])
            if response["status"] == STATUS_OK:
                keys = set(response["predict"])
                if keys == old_labels and response["bank_version"] == 1:
                    kind = "old"
                elif keys == new_labels and response["bank_version"] == 2:
                    kind = "new"
                else:
                    # a label set that matches neither bank, or labels
                    # from one bank stamped with the other's version —
                    # both are torn snapshots
                    kind = "torn"
                with lock:
                    counts[kind] += 1
            i += 1

    loaders = [threading.Thread(target=load) for _ in range(4)]
    for t in loaders:
        t.start()
    time.sleep(0.3)
    traces_before = predictor.score_trace_count
    version = service.swap_bank(sentinel_instances(len(old_labels)))
    time.sleep(0.3)
    stop.set()
    for t in loaders:
        t.join()
    service.drain()

    assert version == 2
    assert counts["torn"] == 0
    assert counts["old"] > 0 and counts["new"] > 0  # swap landed mid-stream
    # same bank geometry → the warmed programs keep serving untraced
    assert predictor.score_trace_count == traces_before
    manifest = json.loads((run_dir / MANIFEST_NAME).read_text())
    assert manifest["version"] == 2
    assert set(manifest["labels"]) == new_labels
    assert tel.snapshot()["counters"]["serve.bank_swaps"] == 1
    # the swap lived in the service's snapshot only — the predictor's
    # own installed bank is untouched, so later services start from v1
    assert list(predictor.anchor_labels) == manifest_v1_labels


def test_bank_swap_to_new_geometry_prewarms(setup, tel):
    """A swap that changes the bank's row count compiles the new
    programs BEFORE install (trace count moves at swap time, then stays
    flat while serving the new bank)."""
    predictor, _, texts = setup
    service = make_service(predictor)
    client = InprocessClient(service)
    n_old = predictor.n_anchors
    traces_before = predictor.score_trace_count
    version = service.swap_bank(sentinel_instances(n_old + 3))
    traces_after_swap = predictor.score_trace_count
    assert traces_after_swap > traces_before  # pre-warm happened...
    response = client.score(texts[0])
    assert response["status"] == STATUS_OK
    assert len(response["predict"]) == n_old + 3
    assert response["bank_version"] == version
    # ...and serving the new geometry added no further traces
    assert predictor.score_trace_count == traces_after_swap
    service.drain()


# -- HTTP front end ------------------------------------------------------------

def test_http_front_end_roundtrip(setup, tel):
    predictor, _, texts = setup
    service = make_service(predictor)
    server = run_http_server(service, port=0)
    try:
        client = HTTPClient(
            "http://127.0.0.1:%d" % server.server_address[1]
        )
        health = client.health()
        assert health["status"] == "ok"
        assert health["bank_version"] >= 1
        response = client.score(texts[0])
        assert response["status"] == STATUS_OK
        assert response["predict"] and response["anchor"] in response["predict"]
        # bad requests are 400s with a reason, not hangs
        bad = client._request(urllib.request.Request(
            client.base_url + "/score",
            data=b'{"no_text": 1}',
            headers={"Content-Type": "application/json"},
            method="POST",
        ))
        assert bad["status"] == "error" and "bad request" in bad["reason"]
        missing = client._request(urllib.request.Request(
            client.base_url + "/nope", method="GET"
        ))
        assert missing["status"] == "error"
    finally:
        server.shutdown()
        service.drain()


# -- config + archive entry point ----------------------------------------------

def test_serving_config_section_defaults_and_overrides():
    from memvul_tpu.config import SERVING_DEFAULTS, serving_config

    cfg = serving_config(None)
    assert cfg == SERVING_DEFAULTS
    cfg = serving_config({"serving": {"max_batch": 32, "max_queue": None}})
    assert cfg["max_batch"] == 32
    assert cfg["max_queue"] == SERVING_DEFAULTS["max_queue"]  # null → default


def test_serve_from_archive_end_to_end(ws, tmp_path, tel):
    """Archive → warmed service, sized by the ``serving`` config
    section, manifest + telemetry in the out dir."""
    from memvul_tpu.archive import save_archive
    from memvul_tpu.build import build_model, init_params, serve_from_archive

    model_cfg = {
        "type": "model_memory",
        "encoder": {"preset": "tiny", "vocab_size": 4096},
        "header_dim": 32,
    }
    config = {
        "tokenizer": {
            "type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"],
        },
        "dataset_reader": {
            "type": "reader_memory",
            "anchor_path": ws["paths"]["anchors"],
            "cve_path": ws["paths"]["cve"],
        },
        "model": model_cfg,
        "serving": {"max_batch": 4, "buckets": [16, 48], "max_length": 48},
    }
    model = build_model(dict(model_cfg), 4096)
    params = init_params(model, seed=0)
    archive = save_archive(
        tmp_path / "model.tar.gz", config, params,
        tokenizer_file=ws["paths"]["tokenizer"],
    )
    out_dir = tmp_path / "serve_run"
    service = serve_from_archive(archive, out_dir=out_dir)
    try:
        assert service.config.max_batch == 4
        assert service.predictor.buckets == (16, 48)
        assert (out_dir / MANIFEST_NAME).exists()
        traces = service.predictor.score_trace_count
        response = InprocessClient(service).score("a memory safety bug")
        assert response["status"] == STATUS_OK
        assert set(response["predict"]) == set(service.predictor.anchor_labels)
        assert service.predictor.score_trace_count == traces  # warmed
    finally:
        service.drain()
        telemetry.get_registry().close()

    # a single-model archive is refused with a clear error
    single_cfg = dict(config, model={
        "type": "model_single",
        "encoder": {"preset": "tiny", "vocab_size": 4096},
        "header_dim": 32,
    })
    single_model = build_model(dict(single_cfg["model"]), 4096)
    bad = save_archive(
        tmp_path / "single.tar.gz", single_cfg,
        init_params(single_model, seed=0),
        tokenizer_file=ws["paths"]["tokenizer"],
    )
    with pytest.raises(ValueError, match="Siamese"):
        serve_from_archive(bad)


# -- request-journey tracing (PR 10, docs/observability.md) --------------------

_WAYPOINT_ORDER = (
    "received", "enqueued", "coalesced", "dispatched", "device_done",
    "resolved",
)
_STAGE_NAMES = ("queue_wait_s", "pack_s", "device_s", "resolve_s")


def _assert_complete_monotonic(record):
    """One served trace: every waypoint present, in order, and the four
    stage durations sum to the end-to-end latency (≤5 ms slack)."""
    waypoints = record["waypoints"]
    assert set(waypoints) == set(_WAYPOINT_ORDER), record
    values = [waypoints[name] for name in _WAYPOINT_ORDER]
    assert values == sorted(values), record  # monotonic chain
    stages = record["stages"]
    assert set(stages) == set(_STAGE_NAMES), record
    assert all(v >= 0 for v in stages.values()), record
    assert abs(sum(stages.values()) - record["total_s"]) < 5e-3, record


def test_tracing_full_sample_200_concurrent_chains_and_parity(setup, tel):
    """The tentpole gate: sampling at 1.0 under the 200-concurrent
    mixed-length load — every resolved request has a complete monotonic
    waypoint chain whose stage durations sum to end-to-end latency,
    scores stay bitwise-equal to direct scoring, zero mid-serve
    recompiles, and exactly one rtrace event lands per request."""
    predictor, _, texts = setup
    n = 200
    picks = [texts[i % len(texts)] for i in range(n)]
    instances = [
        {"text1": t, "label": "same", "meta": {"i": i}}
        for i, t in enumerate(picks)
    ]
    expected = {}
    for probs, metas in predictor.score_instances(iter(instances)):
        for row, meta in zip(probs, metas):
            expected[meta["i"]] = row.copy()
    traces_before = predictor.score_trace_count

    service = make_service(predictor, trace_sample_rate=1.0)
    client = InprocessClient(service)
    results = {}
    lock = threading.Lock()

    def worker(indices):
        for i in indices:
            response = client.score(picks[i])
            with lock:
                results[i] = response

    threads = [
        threading.Thread(target=worker, args=(range(k, n, 16),))
        for k in range(16)
    ]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    ring = service.recent_traces()
    service.drain()

    # tracing changed nothing about the scores or the compiled set
    assert len(results) == n
    for i in range(n):
        assert results[i]["status"] == STATUS_OK
        got = np.array(
            [results[i]["predict"][label] for label in predictor.anchor_labels],
            dtype=np.float32,
        )
        np.testing.assert_array_equal(got, np.asarray(expected[i], np.float32))
    assert predictor.score_trace_count == traces_before

    # every request produced one complete, monotonic, summing trace
    assert len(ring) == n
    assert all(r["cause"] == STATUS_OK for r in ring)
    assert all(r["hops"] == 0 for r in ring)
    for record in ring:
        _assert_complete_monotonic(record)
        assert record["batch"] >= 1
        assert record["shape"].startswith("bucket:")
    # newest-first ordering
    resolved = [r["waypoints"]["resolved"] for r in ring]
    assert resolved == sorted(resolved, reverse=True)
    assert len(set(r["trace_id"] for r in ring)) == n

    counters = tel.snapshot()["counters"]
    assert counters["serve.traces_sampled"] == n
    hists = tel.snapshot()["histograms"]
    for stage in _STAGE_NAMES:
        assert hists[f"serve.{stage}"]["count"] == n
    run_dir = tel.run_dir
    tel.close()
    events, skipped = telemetry.read_jsonl(run_dir / "events.jsonl")
    assert skipped == 0
    rtraces = [ev for ev in events if ev.get("kind") == "rtrace"]
    assert len(rtraces) == n
    assert {ev["trace_id"] for ev in rtraces} == {r["trace_id"] for r in ring}


def test_tracing_off_zero_overhead_metric_and_event_pin(tel):
    """The zero-overhead pin: with tracing off (the default), a served
    load emits EXACTLY the PR 9 metric-name set — no stage histograms,
    no trace counter, no rtrace events, an empty /tracez ring."""
    fake = _SlowFakePredictor()
    fake.hold.set()  # score immediately
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=4, max_wait_ms=1.0, max_queue=100,
            default_deadline_ms=30000.0, anchor_stats=False,
        ),
    )
    futures = [service.submit(f"r {i}") for i in range(40)]
    for future in futures:
        assert future.result(timeout=10)["status"] == STATUS_OK
    assert service.recent_traces() == []
    service.drain()
    snapshot = tel.snapshot()
    # the exact emitted-metric set of the pre-tracing serving tier
    assert set(snapshot["counters"]) == {
        "serve.requests", "serve.served", "serve.batches",
        "serve.tokens_real", "serve.tokens_padded",
    }
    assert set(snapshot["gauges"]) == {"serve.queue_depth"}
    assert set(snapshot["histograms"]) == {
        "serve.latency_s", "serve.batch_latency_s", "serve.batch_occupancy",
    }
    run_dir = tel.run_dir
    tel.close()
    events, _ = telemetry.read_jsonl(run_dir / "events.jsonl")
    kinds = {ev.get("kind") for ev in events}
    assert "rtrace" not in kinds
    assert kinds <= {"run_start", "serve_drained", "run_end"}


def test_non_served_outcomes_always_traced_with_cause(tel):
    """Shed / deadline / drain requests carry their cause even at a
    near-zero sample rate: non-ok rtrace emission is always-on."""
    fake = _SlowFakePredictor()
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=4, max_wait_ms=1.0, max_queue=4,
            default_deadline_ms=50.0, trace_sample_rate=1e-9,
        ),
    )
    first = service.submit("r0", deadline_ms=0)  # no deadline; blocks
    assert fake.started.wait(timeout=5)
    burst = [service.submit(f"r{i+1}", deadline_ms=50.0) for i in range(8)]
    for future in burst[:4]:
        assert future.result(timeout=5)["status"] == STATUS_SHED
    time.sleep(0.1)
    fake.hold.set()
    assert first.result(timeout=10)["status"] == STATUS_OK
    for future in burst[4:]:
        assert future.result(timeout=10)["status"] == STATUS_DEADLINE
    service.drain()
    causes = {}
    for record in service.recent_traces():
        causes[record["cause"]] = causes.get(record["cause"], 0) + 1
        assert "hops" in record
    assert causes[STATUS_SHED] == 4
    assert causes[STATUS_DEADLINE] == 4
    assert causes.get(STATUS_OK, 0) == 1  # ringed even when not sampled
    # a shed request's trace never reached dispatch
    shed_traces = [
        r for r in service.recent_traces() if r["cause"] == STATUS_SHED
    ]
    assert all("dispatched" not in r["waypoints"] for r in shed_traces)
    run_dir = tel.run_dir
    tel.close()
    events, _ = telemetry.read_jsonl(run_dir / "events.jsonl")
    rtraces = [ev for ev in events if ev.get("kind") == "rtrace"]
    # at a ~0 sample rate only the 8 non-ok outcomes emit events
    assert len(rtraces) == 8
    assert {ev["cause"] for ev in rtraces} == {STATUS_SHED, STATUS_DEADLINE}
    counters = tel.snapshot()["counters"]
    assert counters["serve.traces_sampled"] == 8


# -- live exposition endpoints (GET /metrics, /tracez) -------------------------

def _http_get(base, path):
    with urllib.request.urlopen(base + path, timeout=10) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


def test_metrics_endpoint_parses_and_agrees_with_snapshot(tel):
    """GET /metrics parses as Prometheus text format and agrees exactly
    with TelemetryRegistry.snapshot() at scrape time."""
    from memvul_tpu.telemetry.exposition import (
        parse_exposition, sanitize_metric_name,
    )

    fake = _SlowFakePredictor()
    fake.hold.set()
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=4, max_wait_ms=1.0, default_deadline_ms=30000.0,
        ),
    )
    server = run_http_server(service, port=0)
    try:
        base = "http://%s:%d" % server.server_address[:2]
        for i in range(9):
            assert service.submit(f"r {i}").result(timeout=10)[
                "status"
            ] == STATUS_OK
        snapshot = tel.snapshot()
        status, ctype, body = _http_get(base, "/metrics")
        assert status == 200
        assert "text/plain" in ctype
        parsed = parse_exposition(body.decode("utf-8"))  # raises if malformed
        for name, value in snapshot["counters"].items():
            assert parsed[sanitize_metric_name(name)][""] == value, name
        for name, value in snapshot["gauges"].items():
            assert parsed[sanitize_metric_name(name)][""] == value, name
        for name, summary in snapshot["histograms"].items():
            metric = sanitize_metric_name(name)
            assert parsed[f"{metric}_count"][""] == summary["count"], name
            assert abs(
                parsed[f"{metric}_sum"][""] - summary["total"]
            ) < 1e-9, name
    finally:
        server.shutdown()
        service.drain()


def test_tracez_endpoint_newest_first_and_limit(tel):
    fake = _SlowFakePredictor()
    fake.hold.set()
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=2, max_wait_ms=1.0, default_deadline_ms=30000.0,
            trace_sample_rate=1.0, trace_ring=16,
        ),
    )
    server = run_http_server(service, port=0)
    try:
        base = "http://%s:%d" % server.server_address[:2]
        for i in range(10):
            assert service.submit(f"r {i}").result(timeout=10)[
                "status"
            ] == STATUS_OK
        status, _, body = _http_get(base, "/tracez")
        assert status == 200
        payload = json.loads(body)
        assert payload["count"] == 10
        resolved = [
            t["waypoints"]["resolved"] for t in payload["traces"]
        ]
        assert resolved == sorted(resolved, reverse=True)
        status, _, body = _http_get(base, "/tracez?limit=3")
        assert json.loads(body)["count"] == 3
        # a bounded ring: flooding past trace_ring keeps the newest 16
        for i in range(20):
            service.submit(f"flood {i}").result(timeout=10)
        status, _, body = _http_get(base, "/tracez")
        assert json.loads(body)["count"] == 16
    finally:
        server.shutdown()
        service.drain()


def test_healthz_carries_slo_block_when_monitor_attached(tel):
    from memvul_tpu.serving.slo import SLOConfig, SLOMonitor

    fake = _SlowFakePredictor()
    fake.hold.set()
    service = ScoringService(
        fake, config=ServiceConfig(max_wait_ms=1.0),
    )
    service.slo_monitor = SLOMonitor(
        service, registry=tel, config=SLOConfig(interval_s=0.0), start=False,
    )
    server = run_http_server(service, port=0)
    try:
        base = "http://%s:%d" % server.server_address[:2]
        assert service.submit("hello").result(timeout=10)["status"] == STATUS_OK
        service.slo_monitor.tick()
        status, _, body = _http_get(base, "/healthz")
        assert status == 200
        slo = json.loads(body)["slo"]
        assert slo["scale_hint"] in ("up", "hold", "down")
        assert slo["objectives"]["availability"] == 0.999
        assert 0.0 <= slo["availability"] <= 1.0
        gauges = tel.snapshot()["gauges"]
        assert "slo.availability" in gauges and "slo.scale_hint" in gauges
    finally:
        server.shutdown()
        service.drain()


def test_profilez_capture_conflict_and_disabled(tel, tmp_path):
    """POST /profilez starts one capture at a time: 200 with the trace
    dir, 409 while running, 400 on junk, 503 without a run dir."""
    fake = _SlowFakePredictor()
    fake.hold.set()
    service = ScoringService(fake, config=ServiceConfig(max_wait_ms=1.0))
    prof_dir = tmp_path / "prof"
    server = run_http_server(service, port=0, profile_dir=prof_dir)
    no_prof = run_http_server(service, port=0)  # no run dir: disabled

    def post(srv, payload):
        base = "http://%s:%d" % srv.server_address[:2]
        req = urllib.request.Request(
            base + "/profilez",
            data=json.dumps(payload).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        try:
            with urllib.request.urlopen(req, timeout=10) as resp:
                return resp.status, json.loads(resp.read())
        except urllib.error.HTTPError as e:
            return e.code, json.loads(e.read())

    # hold the capture open on an event so the in-flight window is
    # under test control — a timed sleep races the HTTP round-trips
    # on a loaded machine
    release = threading.Event()
    server.profiler._wait = lambda seconds: release.wait(timeout=10)
    try:
        status, payload = post(server, {"seconds": 0.4})
        assert status == 200 and payload["status"] == "ok"
        assert payload["seconds"] == 0.4
        # capture in flight: a second request conflicts
        status, payload = post(server, {"seconds": 0.1})
        assert status == 409 and "already running" in payload["reason"]
        # serving continues during the capture
        assert service.submit("live").result(timeout=10)["status"] == STATUS_OK
        release.set()
        deadline = time.monotonic() + 10
        while server.profiler.busy and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not server.profiler.busy
        assert (prof_dir / "profile-001").is_dir()
        assert tel.snapshot()["counters"]["serve.profile_captures"] == 1
        # bad/missing duration → 400; no run dir → 503
        assert post(server, {"seconds": "soon"})[0] == 400
        assert post(server, {})[0] == 400
        assert post(server, {"seconds": -1})[0] == 400
        assert post(no_prof, {"seconds": 0.1})[0] == 503
    finally:
        server.shutdown()
        no_prof.shutdown()
        service.drain()


def test_hbm_gauges_sampled_at_heartbeat_cadence(tel, monkeypatch):
    """The batcher samples device_memory_stats into serve.hbm_* gauges
    at heartbeat cadence — per replica, the way trainers already report
    it (monkeypatched stats: CPU exposes none)."""
    from memvul_tpu.utils import profiling

    seen_devices = []

    def fake_stats(device=None, all_devices=False):
        seen_devices.append(device)
        return {"bytes_in_use": 123.0, "peak_bytes_in_use": 456.0}

    monkeypatch.setattr(profiling, "device_memory_stats", fake_stats)
    fake = _SlowFakePredictor()
    fake.hold.set()
    sentinel = object()
    service = ScoringService(
        fake, config=ServiceConfig(max_wait_ms=1.0), device=sentinel,
    )
    try:
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline and not seen_devices:
            time.sleep(0.02)
        gauges = tel.snapshot()["gauges"]
        assert gauges["serve.hbm_in_use_bytes"] == 123.0
        assert gauges["serve.hbm_peak_bytes"] == 456.0
        assert seen_devices[0] is sentinel  # THIS replica's device
    finally:
        service.drain()
    # the gate: hbm_gauges=False never probes the device
    seen_devices.clear()
    telemetry.configure(run_dir=tel.run_dir)
    off = ScoringService(
        fake, config=ServiceConfig(max_wait_ms=1.0, hbm_gauges=False),
    )
    time.sleep(0.2)
    off.drain()
    assert seen_devices == []


def test_profilez_via_serve_cli_subprocess(ws, tmp_path):
    """The satellite gate: a real `serve` process captures an on-demand
    jax.profiler trace into its run dir while serving live traffic —
    409 while one is running — and still drains cleanly on SIGTERM."""
    import subprocess
    import sys as _sys

    from memvul_tpu.archive import save_archive
    from memvul_tpu.build import build_model, init_params

    model_cfg = {
        "type": "model_memory",
        "encoder": {"preset": "tiny", "vocab_size": 4096},
        "header_dim": 32,
    }
    config = {
        "tokenizer": {
            "type": "wordpiece", "tokenizer_path": ws["paths"]["tokenizer"],
        },
        "dataset_reader": {
            "type": "reader_memory",
            "anchor_path": ws["paths"]["anchors"],
            "cve_path": ws["paths"]["cve"],
        },
        "model": model_cfg,
        "serving": {"max_batch": 4, "buckets": [16], "max_length": 16},
    }
    model = build_model(dict(model_cfg), 4096)
    archive = save_archive(
        tmp_path / "model.tar.gz", config, init_params(model, seed=0),
        tokenizer_file=ws["paths"]["tokenizer"],
    )
    out_dir = tmp_path / "serve_run"
    proc = subprocess.Popen(
        [_sys.executable, "-m", "memvul_tpu", "serve", str(archive),
         "-o", str(out_dir), "--port", "0", "--no-mesh"],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
    )
    try:
        line = proc.stdout.readline()
        if not line.strip():
            proc.kill()
            _, err = proc.communicate(timeout=30)
            raise AssertionError(f"serve never became ready: {err[-3000:]}")
        ready = json.loads(line)
        base = ready["serving"]

        def post_profilez(payload):
            req = urllib.request.Request(
                base + "/profilez",
                data=json.dumps(payload).encode(),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            try:
                with urllib.request.urlopen(req, timeout=10) as resp:
                    return resp.status, json.loads(resp.read())
            except urllib.error.HTTPError as e:
                return e.code, json.loads(e.read())

        status, payload = post_profilez({"seconds": 1.0})
        assert status == 200, payload
        assert payload["trace_dir"].startswith(str(out_dir))
        # conflict while the capture runs
        status, conflict = post_profilez({"seconds": 0.1})
        assert status == 409, conflict
        # live traffic keeps flowing during the capture
        score_req = urllib.request.Request(
            base + "/score",
            data=json.dumps({"text": "a memory safety bug"}).encode(),
            headers={"Content-Type": "application/json"},
            method="POST",
        )
        with urllib.request.urlopen(score_req, timeout=30) as resp:
            assert json.loads(resp.read())["status"] == STATUS_OK
        # the capture finishes and leaves a trace dir in the run dir
        deadline = time.monotonic() + 15
        profile_dir = Path(payload["trace_dir"])
        while time.monotonic() < deadline and not profile_dir.is_dir():
            time.sleep(0.1)
        assert profile_dir.is_dir()
        proc.send_signal(signal.SIGTERM)
        assert proc.wait(timeout=60) == 0
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait(timeout=10)


def test_ragged_dispatch_traces_pack_fill(tel):
    """Ragged mode's trace shape records the token-budget fill
    (pack:real/budget) instead of a bucket, with the same complete
    stage chain."""
    fake = _SlowFakePredictor()
    fake.hold.set()
    fake.score_impl = "ragged"
    fake.ragged_shape = lambda: (32, 4)
    fake._ragged_score_fn = lambda params, sample, bank: np.tile(
        np.linspace(0.1, 0.9, fake.n_anchors, dtype=np.float32), (4, 1)
    )
    service = ScoringService(
        fake,
        config=ServiceConfig(
            max_batch=4, max_wait_ms=1.0, default_deadline_ms=30000.0,
            trace_sample_rate=1.0,
        ),
    )
    futures = [service.submit(f"req {i}") for i in range(6)]
    for future in futures:
        assert future.result(timeout=10)["status"] == STATUS_OK
    ring = service.recent_traces()
    service.drain()
    assert len(ring) == 6
    for record in ring:
        _assert_complete_monotonic(record)
        real, budget = record["shape"].split(":", 1)[1].split("/")
        assert real.isdigit() and int(budget) == 32
    hists = tel.snapshot()["histograms"]
    assert hists["serve.pack_s"]["count"] == 6
